"""HLO static budget gates — the compile-time half of ``dptpu check``.

Compiles the representative step configs on the CPU backend (the fake
8-device pod, tests/conftest.py's trick) and statically asserts the
committed budget table ``HLO_BUDGETS.json``:

* per-link collective instruction counts and per-chip ring-send bytes
  EXACTLY as committed, and within 2% of the analytic formulas locked
  in tests/test_hierarchy.py (flat DDP: ``2(n-1)/n × (G + P)`` of pure
  all-reduce; ZeRO-1: same total volume as DDP; accum: identical
  collectives to DDP — ONE reduction per update; hierarchical:
  RS+AG on ICI at ``2(I-1)/I·G``, the shard-sized AR crossing DCN at
  ``2(S-1)/S·G/I`` plus the world pmean);
* donation honored — the compiled module's ``input_output_alias`` map
  covers at least every parameter leaf, so the update never
  materializes a full-parameter copy;
* zero f64 shapes anywhere (no accidental double promotion);
* overlap evidence (the ``*_overlap`` configs, ISSUE 13): the bucketed
  engine (``DPTPU_OVERLAP=1``, dptpu/parallel/overlap.py) emits >= 2
  independent per-bucket reductions INTERLEAVED with backward compute
  in the compiled schedule (``hlo_accounting.overlap_evidence``), at
  total collective bytes within 0.1% of the unbucketed program;
* the rules-engine configs (ISSUE 16): ``zero3`` reproduces the DDP
  collective volume as AG+RS+AR (the r06 equivalence, stage-3 form),
  ``gspmd_hier`` keeps DCN bytes under half of flat GSPMD's all-DCN
  volume on the ``{slice, data}``-factored mesh, and ``gspmd_overlap``
  holds the partitioner's reduction volume at the DDP analytic with
  the same interleaving evidence as the shard_map overlap configs;
* the serve-quant config (ISSUE 18): the int8 serve forward's
  REQUESTED matmul dtypes, from pre-optimization HLO (this backend's
  float normalization hides them post-optimization) — s8 parameters
  present, >= 1 bf16 dot/convolution, ZERO f32/f64 dots — so a silent
  fp32 fallback in the quantized fast path fails statically.

A comms/sharding regression therefore fails ``dptpu check`` BEFORE any
bench runs. After an INTENDED change, re-commit the table with
``dptpu check --update-hlo-budgets``.

All jax/flax imports are lazy: importing this module (and the lint
half of dptpu.analysis) stays stdlib-only.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Tuple

BUDGETS_FILENAME = "HLO_BUDGETS.json"

# the representative geometries: 4 fake devices, 2 slices × 2 chips for
# the hierarchical arm (the tests/test_hierarchy.py geometry)
_N = 4
_SLICES = 2

REPRESENTATIVE_CONFIGS = ("ddp", "zero1", "accum", "slices",
                          "ddp_overlap", "zero1_overlap", "slices_overlap",
                          "zero3", "gspmd_hier", "gspmd_overlap",
                          "serve_quant")

# bucket bound for the overlap configs: small enough that the probe
# model's ~7 KB of gradients split into >= 2 buckets (the evidence
# gates need at least two independent per-bucket reductions)
_OVERLAP_BUCKET_BYTES = 2048

# |parsed − analytic| / analytic tolerance: the formulas count gradient
# + pmean payload; the compiled program adds a handful of scalar-sized
# control collectives (same 2% bound tests/test_hierarchy.py locks)
_ANALYTIC_RTOL = 0.02


@dataclasses.dataclass(frozen=True)
class BudgetViolation:
    """One failed budget gate — formats to an actionable message."""

    config: str
    field: str
    message: str

    def format(self) -> str:
        return (
            f"hlo-budget: {BUDGETS_FILENAME}: [{self.config}] "
            f"{self.field}: {self.message} (if this comms/sharding "
            f"change is INTENDED, re-commit the table with "
            f"`dptpu check --update-hlo-budgets` and say why in the PR)"
        )


def _budget_model():
    """The budget probe model — dense-heavy so every leaf scatters at
    the 2/4-way geometries (the tests/test_hierarchy.py TinyDense
    pattern), with BN for the replicated batch_stats pmean."""
    from flax import linen as nn

    class BudgetNet(nn.Module):
        num_classes: int = 10

        @nn.compact
        def __call__(self, x, train: bool = False):
            x = nn.Conv(16, (3, 3), use_bias=False)(x)
            x = nn.BatchNorm(use_running_average=not train,
                             momentum=0.9)(x)
            x = nn.relu(x)
            x = x.mean(axis=(1, 2))
            x = nn.Dense(32)(x)
            x = nn.relu(x)
            return nn.Dense(self.num_classes)(x)

    return BudgetNet()


def _state():
    import jax

    from dptpu.train import create_train_state, make_optimizer

    tx = make_optimizer(momentum=0.9, weight_decay=1e-4)
    return create_train_state(
        jax.random.PRNGKey(0), _budget_model(), tx,
        input_shape=(1, 8, 8, 3),
    )


def _batch():
    import numpy as np

    rng = np.random.RandomState(0)
    return {
        "images": rng.randint(0, 256, (16, 8, 8, 3)).astype(np.uint8),
        "labels": rng.randint(0, 10, (16,)).astype(np.int32),
    }


def _leaf_counts(state) -> dict:
    import jax
    import numpy as np

    def total(tree):
        return 4 * sum(
            int(np.prod(l.shape)) if l.shape else 1
            for l in jax.tree_util.tree_leaves(tree)
        )

    return {
        "param_leaves": len(jax.tree_util.tree_leaves(state.params)),
        "state_leaves": len(jax.tree_util.tree_leaves(state)),
        # analytic payloads (fp32): gradient bytes, and the BN-stat +
        # 3-scalar-metric pmean payload — tests/test_hierarchy.py's
        # _grad_bytes/_pmean_bytes
        "grad_bytes": total(state.params),
        "pmean_bytes": total(state.batch_stats) + 4 * 3,
    }


def _compile_config(name: str) -> Tuple[str, dict]:
    """Compiled HLO text + model facts for one representative config."""
    import jax

    from dptpu.parallel import (
        make_hierarchical_mesh,
        make_mesh,
        make_zero1_train_step,
        make_zero3_train_step,
        replicated_sharding,
        shard_host_batch,
        shard_zero1_state,
        shard_zero3_state,
        zero3_param_specs,
    )
    from dptpu.train import make_train_step

    devices = jax.devices()[:_N]
    if len(devices) < _N:
        raise RuntimeError(
            f"HLO budget gates need {_N} devices, got {len(devices)} — "
            f"run under XLA_FLAGS=--xla_force_host_platform_device_"
            f"count=8 (tests/conftest.py does this automatically)"
        )
    st = _state()
    facts = _leaf_counts(st)
    if name == "slices":
        mesh = make_hierarchical_mesh(_SLICES, devices)
        step = make_train_step(mesh)
    elif name == "slices_overlap":
        mesh = make_hierarchical_mesh(_SLICES, devices)
        step = make_train_step(mesh, overlap=True,
                               bucket_bytes=_OVERLAP_BUCKET_BYTES)
    elif name == "accum":
        mesh = make_mesh(devices, {"data": _N})
        step = make_train_step(mesh, accum_steps=2)
    elif name == "zero1":
        mesh = make_mesh(devices, {"data": _N})
        step = make_zero1_train_step(mesh, st)
    elif name == "zero1_overlap":
        mesh = make_mesh(devices, {"data": _N})
        step = make_zero1_train_step(mesh, st, overlap=True,
                                     bucket_bytes=_OVERLAP_BUCKET_BYTES)
    elif name == "ddp":
        mesh = make_mesh(devices, {"data": _N})
        step = make_train_step(mesh)
    elif name == "ddp_overlap":
        mesh = make_mesh(devices, {"data": _N})
        step = make_train_step(mesh, overlap=True,
                               bucket_bytes=_OVERLAP_BUCKET_BYTES)
    elif name == "zero3":
        # ZeRO-3/FSDP: rules-table placement over the data axis; the
        # probe model is not a registry family, so the GENERIC table's
        # AUTO_FSDP row drives it (same as any CNN)
        mesh = make_mesh(devices, {"data": _N})
        z3_specs = zero3_param_specs("budgetnet", st.params, mesh)
        step = make_zero3_train_step(mesh, st, z3_specs)
    elif name in ("gspmd_hier", "gspmd_overlap"):
        from dptpu.parallel.gspmd import (
            dp_specs,
            gspmd_specs_for_arch,
            make_gspmd_train_step,
            shard_gspmd_state,
        )

        if name == "gspmd_hier":
            # the {slice, data}-factored mesh + rules-table FSDP
            # placement: the partitioner derives the DCN-aware
            # decomposition itself (the by_link gate below)
            mesh = make_hierarchical_mesh(_SLICES, devices)
            specs = gspmd_specs_for_arch("budgetnet", st.params, mesh,
                                         fsdp=True)
            step = make_gspmd_train_step(mesh, st, specs)
        else:
            mesh = make_mesh(devices, {"data": _N})
            specs = dp_specs(st.params)
            step = make_gspmd_train_step(
                mesh, st, specs, overlap=True,
                bucket_bytes=_OVERLAP_BUCKET_BYTES,
            )
        st = shard_gspmd_state(st, mesh, specs)
        batch = shard_host_batch(_batch(), mesh)
        return step.lower(st, batch).compile().as_text(), facts
    else:
        raise ValueError(
            f"unknown budget config {name!r} "
            f"(representative set: {', '.join(REPRESENTATIVE_CONFIGS)})"
        )
    if name == "zero3":
        st = shard_zero3_state(st, mesh, z3_specs)
    elif name.startswith("zero1"):
        st = shard_zero1_state(st, mesh)
    else:
        st = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, replicated_sharding(mesh)), st
        )
    batch = shard_host_batch(_batch(), mesh)
    return step.lower(st, batch).compile().as_text(), facts


def _serve_quant_hlo() -> str:
    """Pre-optimization HLO of the serve engine's REAL int8 forward
    (``ServeEngine._forward_int8`` on a quantized resnet18@32 tree) —
    lowered, not compiled: the requested dot dtypes are the gate, and
    they exist before XLA's backend-specific rewrites (this container's
    CPU backend promotes bf16 gemms to f32 in the optimized text)."""
    import jax
    import jax.numpy as jnp

    from dptpu.ops.quant import quantize_tree
    from dptpu.serve.engine import ServeEngine

    engine = ServeEngine("resnet18", buckets=(1,), num_classes=8,
                         image_size=32, placement="replicated")
    qvars = {
        "params": quantize_tree(engine._host_variables["params"]),
        "batch_stats": engine._host_variables["batch_stats"],
    }
    structs = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), qvars
    )
    img = jax.ShapeDtypeStruct((1, 32, 32, 3), jnp.uint8)
    return jax.jit(engine._forward_int8).lower(
        structs, img
    ).compiler_ir(dialect="hlo").as_hlo_text()


def extract_budget(name: str) -> Tuple[dict, Optional[dict]]:
    """Parse one config's compiled program into its budget row."""
    from dptpu.parallel.hlo_accounting import (
        collective_bytes_by_link,
        collective_bytes_per_chip,
        donated_alias_count,
        dot_dtype_census,
        op_census,
        overlap_evidence,
        parse_collectives,
    )

    if name == "serve_quant":
        txt = _serve_quant_hlo()
        row = dot_dtype_census(txt)
        row["f64_shapes"] = op_census(txt)["f64_shapes"]
        return row, None

    txt, facts = _compile_config(name)
    inner = _N // _SLICES
    counts = {"all-gather": 0, "reduce-scatter": 0, "all-reduce": 0}
    for inst in parse_collectives(txt):
        counts[inst["op"]] += 1
    row = {
        "collective_instructions": counts,
        "per_chip": collective_bytes_per_chip(txt, _N),
        "alias_entries": donated_alias_count(txt),
        "f64_shapes": op_census(txt)["f64_shapes"],
    }
    if name in ("slices", "slices_overlap", "gspmd_hier"):
        row["by_link"] = collective_bytes_by_link(
            txt, lambda p: p // inner, _N
        )
    if name.endswith("_overlap"):
        # the overlap-evidence block: per-bucket reductions interleaved
        # with backward compute in the compiled schedule. Only the
        # GATED properties are committed — entry_instructions /
        # compute_between shift on any compute-only fusion change, and
        # locking them exactly would turn every XLA upgrade into a
        # phantom comms regression.
        ev = overlap_evidence(txt)
        row["overlap"] = {k: ev[k] for k in (
            "reductions", "interleaved_gaps", "contiguous_tail_block",
        )}
    return row, facts


def compute_budgets() -> dict:
    """The full budget table (what ``--update-hlo-budgets`` commits)."""
    configs = {}
    facts = None
    for name in REPRESENTATIVE_CONFIGS:
        configs[name], f = extract_budget(name)
        if f is not None:
            facts = f
    return {
        "version": 1,
        "geometry": {"devices": _N, "slices": _SLICES,
                     "inner": _N // _SLICES},
        "model": facts,
        "configs": configs,
    }


def load_budgets(root: str) -> Optional[dict]:
    path = os.path.join(root, BUDGETS_FILENAME)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def write_budgets(root: str, budgets: dict) -> str:
    path = os.path.join(root, BUDGETS_FILENAME)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(budgets, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def _analytic_violations(computed: dict) -> List[BudgetViolation]:
    """The committed-table-independent half: the compiled programs must
    reproduce the analytic formulas (so even a stale committed table
    cannot bless a regression)."""
    out = []
    n, s = _N, _SLICES
    inner = n // s
    g = computed["model"]["grad_bytes"]
    p = computed["model"]["pmean_bytes"]
    cfg = computed["configs"]

    def close(got, want):
        return want > 0 and abs(got - want) / want < _ANALYTIC_RTOL

    ddp = cfg["ddp"]["per_chip"]
    if ddp["reduce-scatter"] or ddp["all-gather"]:
        out.append(BudgetViolation(
            "ddp", "per_chip",
            f"flat DDP must emit ONLY all-reduce, got RS="
            f"{ddp['reduce-scatter']} AG={ddp['all-gather']} bytes",
        ))
    want = 2 * (n - 1) / n * (g + p)
    if not close(ddp["all-reduce"], want):
        out.append(BudgetViolation(
            "ddp", "per_chip.all-reduce",
            f"{ddp['all-reduce']} bytes vs analytic 2(n-1)/n·(G+P) = "
            f"{want:.0f} (r06 lock, tests/test_hierarchy.py)",
        ))
    z = cfg["zero1"]["per_chip"]["total"]
    if not (ddp["total"] > 0
            and abs(z - ddp["total"]) / ddp["total"] < 0.001):
        out.append(BudgetViolation(
            "zero1", "per_chip.total",
            f"{z} bytes vs DDP's {ddp['total']} — ZeRO-1's AG+RS volume "
            f"must equal the DDP all-reduce (the r06 equivalence)",
        ))
    # ZeRO-3: gather-on-use + scatter-on-grad is the SAME volume as the
    # DDP all-reduce (AG (n-1)/n·G forward + RS (n-1)/n·G backward +
    # the pmean AR — the r06 equivalence extended to stage 3), and the
    # program must actually show the gather/scatter shape
    z3 = cfg["zero3"]["per_chip"]
    if not (z3["all-gather"] > 0 and z3["reduce-scatter"] > 0):
        out.append(BudgetViolation(
            "zero3", "per_chip",
            f"AG={z3['all-gather']} RS={z3['reduce-scatter']} bytes — "
            f"ZeRO-3 must all-gather params at use and reduce-scatter "
            f"the grads (did the placement collapse to replicated?)",
        ))
    if not (ddp["total"] > 0
            and abs(z3["total"] - ddp["total"]) / ddp["total"] < 0.001):
        out.append(BudgetViolation(
            "zero3", "per_chip.total",
            f"{z3['total']} bytes vs DDP's {ddp['total']} — ZeRO-3's "
            f"AG+RS+AR volume must equal the DDP all-reduce (the r06 "
            f"equivalence, stage-3 form)",
        ))
    if (cfg["accum"]["collective_instructions"]
            != cfg["ddp"]["collective_instructions"]):
        out.append(BudgetViolation(
            "accum", "collective_instructions",
            f"{cfg['accum']['collective_instructions']} vs DDP's "
            f"{cfg['ddp']['collective_instructions']} — accumulation "
            f"must keep ONE reduction per update, never per microbatch",
        ))
    want_ici = 2 * (inner - 1) / inner * g
    want_dcn = (2 * (s - 1) / s * g / inner
                + 2 * (n - 1) / n * p)
    for cname in ("slices", "slices_overlap"):
        link = cfg[cname]["by_link"]
        structural = (link["ici"]["all-reduce"] == 0
                      and link["dcn"]["reduce-scatter"] == 0
                      and link["dcn"]["all-gather"] == 0)
        if not structural:
            out.append(BudgetViolation(
                cname, "by_link",
                "the hierarchical decomposition leaked: ICI must carry "
                "only RS+AG and DCN only the shard-sized AR "
                f"(got ici.AR={link['ici']['all-reduce']} "
                f"dcn.RS={link['dcn']['reduce-scatter']} "
                f"dcn.AG={link['dcn']['all-gather']})",
            ))
        if not close(link["ici"]["total"], want_ici):
            out.append(BudgetViolation(
                cname, "by_link.ici.total",
                f"{link['ici']['total']} bytes vs analytic 2(I-1)/I·G = "
                f"{want_ici:.0f}",
            ))
        if not close(link["dcn"]["total"], want_dcn):
            out.append(BudgetViolation(
                cname, "by_link.dcn.total",
                f"{link['dcn']['total']} bytes vs analytic "
                f"2(S-1)/S·G/I + 2(n-1)/n·P = {want_dcn:.0f}",
            ))
    # overlap gates (ISSUE 13 acceptance): the bucketed engine's bytes
    # are a pure regrouping — totals within 0.1% of the unbucketed
    # program — and the compiled schedule shows >= 2 independent
    # per-bucket reductions interleaved with backward compute
    for cname, base in (("ddp_overlap", "ddp"),
                        ("zero1_overlap", "zero1")):
        got = cfg[cname]["per_chip"]["total"]
        want = cfg[base]["per_chip"]["total"]
        if not (want > 0 and abs(got - want) / want < 0.001):
            out.append(BudgetViolation(
                cname, "per_chip.total",
                f"{got} bytes vs the unbucketed {base} program's {want} "
                f"— bucketing must be a pure regrouping of the same "
                f"reduction bytes (0.1% gate)",
            ))
    # GSPMD gates. The partitioner derives its own collectives, so the
    # honest assertions differ from the shard_map ones:
    # * gspmd_overlap — the bucket boundaries are sharding-constraint
    #   annotations on logically-pre-reduced grads; the partitioner's
    #   per-leaf reductions ALREADY interleave with backward compute,
    #   and bucketing must stay a pure regrouping of the same volume
    #   (in practice the compiled program is identical to unbucketed —
    #   the gate is that the volume matches the DDP analytic, plus the
    #   overlap evidence thresholds in the *_overlap loop below).
    go = cfg["gspmd_overlap"]["per_chip"]
    if not close(go["all-reduce"], want):
        out.append(BudgetViolation(
            "gspmd_overlap", "per_chip.all-reduce",
            f"{go['all-reduce']} bytes vs the DDP analytic "
            f"2(n-1)/n·(G+P) = {want:.0f} — the partitioner's gradient "
            f"reduction volume drifted",
        ))
    # * gspmd_hier — the partitioner picks its own decomposition (AG+AR
    #   mixes, not the shard_map RS/AR/AG ladder), so the gate is the
    #   CLAIM that matters: the {slice, data} factoring + FSDP placement
    #   moves traffic off DCN. Flat GSPMD on this topology map crosses
    #   its whole volume over DCN (every group spans the world), so
    #   hier DCN bytes must stay under half of that, with ICI carrying
    #   the majority.
    gh = cfg["gspmd_hier"]["by_link"]
    flat_total = cfg["gspmd_overlap"]["per_chip"]["total"]
    if not (gh["dcn"]["total"] * 2 < flat_total):
        out.append(BudgetViolation(
            "gspmd_hier", "by_link.dcn.total",
            f"{gh['dcn']['total']} DCN bytes vs flat GSPMD's "
            f"{flat_total} all-DCN bytes — the hierarchical mesh no "
            f"longer moves the reduction off the slow link",
        ))
    if not (gh["ici"]["total"] > gh["dcn"]["total"]):
        out.append(BudgetViolation(
            "gspmd_hier", "by_link",
            f"ici={gh['ici']['total']} <= dcn={gh['dcn']['total']} "
            f"bytes — ICI must carry the majority of the collective "
            f"traffic on a {_SLICES}-slice mesh",
        ))
    for cname in ("ddp_overlap", "zero1_overlap", "slices_overlap",
                  "gspmd_overlap"):
        ev = cfg[cname]["overlap"]
        if ev["reductions"] < 2:
            out.append(BudgetViolation(
                cname, "overlap.reductions",
                f"{ev['reductions']} gradient-scale reduction "
                f"collectives in the compiled schedule — the bucketed "
                f"engine must emit >= 2 independent per-bucket "
                f"reductions (did the partition collapse to one "
                f"bucket, or did a combiner fuse them?)",
            ))
        if ev["interleaved_gaps"] < 1 or ev["contiguous_tail_block"]:
            out.append(BudgetViolation(
                cname, "overlap.interleaved_gaps",
                f"per-bucket reductions form one contiguous block "
                f"(interleaved_gaps={ev['interleaved_gaps']}) — the "
                f"schedule no longer overlaps the reductions with "
                f"backward computation",
            ))
    # serve-quant (ISSUE 18): the int8 serve forward's REQUESTED matmul
    # dtypes, asserted statically — a refactor that lets the fp32 model
    # dtype promote the dequantized weights back to f32 (the silent
    # fallback that keeps the residency win but loses the compute win)
    # fails here before any bench runs
    sq = cfg["serve_quant"]
    if sq["s8_params"] < 1:
        out.append(BudgetViolation(
            "serve_quant", "s8_params",
            "no s8 parameters in the int8 forward — the quantized "
            "weights no longer travel int8 (did stage_quantized start "
            "dequantizing on the host?)",
        ))
    if sq["dots"].get("bf16", 0) < 1:
        out.append(BudgetViolation(
            "serve_quant", "dots.bf16",
            f"{sq['dots']} — the int8 forward requests no bf16 "
            f"dot/convolution at all",
        ))
    fp_dots = sq["dots"].get("f32", 0) + sq["dots"].get("f64", 0)
    if fp_dots:
        out.append(BudgetViolation(
            "serve_quant", "dots.f32",
            f"{fp_dots} f32/f64 dot/convolution instructions in the "
            f"int8 forward ({sq['dots']}) — a silent fp32 fallback: "
            f"some layer's inputs or weights promoted past bf16 "
            f"(check the model's dtype attribute survives "
            f"ServeEngine._bf16_model)",
        ))
    for name, row in cfg.items():
        if row["f64_shapes"]:
            out.append(BudgetViolation(
                name, "f64_shapes",
                f"{row['f64_shapes']} f64 shapes in the compiled "
                f"program — an accidental double-precision promotion",
            ))
        if name == "serve_quant":
            continue  # an inference forward: donates nothing
        if row["alias_entries"] < computed["model"]["param_leaves"]:
            out.append(BudgetViolation(
                name, "alias_entries",
                f"input_output_alias covers {row['alias_entries']} "
                f"buffers < {computed['model']['param_leaves']} param "
                f"leaves — donation broke and the update now "
                f"materializes a full-parameter copy",
            ))
    return out


def check_hlo_budgets(
    root: str, budgets: Optional[dict] = None,
    computed: Optional[dict] = None,
) -> Tuple[List[BudgetViolation], dict]:
    """Run the gates. Returns (violations, computed_table). ``budgets``
    overrides the committed table and ``computed`` a fresh compile —
    the seeded-regression tests inject tampered tables through these
    without paying four compiles per case."""
    if computed is None:
        computed = compute_budgets()
    violations = _analytic_violations(computed)
    committed = budgets if budgets is not None else load_budgets(root)
    if committed is None:
        violations.append(BudgetViolation(
            "*", BUDGETS_FILENAME,
            "no committed budget table — generate one with "
            "`dptpu check --update-hlo-budgets`",
        ))
        return violations, computed
    for name in REPRESENTATIVE_CONFIGS:
        want = committed.get("configs", {}).get(name)
        got = computed["configs"][name]
        if want is None:
            violations.append(BudgetViolation(
                name, "configs",
                "config missing from the committed table",
            ))
            continue
        for field in ("collective_instructions", "per_chip", "by_link",
                      "alias_entries", "f64_shapes", "overlap",
                      "dots", "s8_params"):
            if field not in got and field not in want:
                continue
            if got.get(field) != want.get(field):
                violations.append(BudgetViolation(
                    name, field,
                    f"compiled program changed: committed "
                    f"{json.dumps(want.get(field), sort_keys=True)} "
                    f"vs compiled "
                    f"{json.dumps(got.get(field), sort_keys=True)}",
                ))
    return violations, computed


def budget_summary(violations: List[BudgetViolation],
                   computed: dict) -> Dict:
    """The ANALYSIS.json block for the HLO half."""
    return {
        "ok": not violations,
        "violations": [v.format() for v in violations],
        "configs": computed["configs"],
        "model": computed["model"],
        "geometry": computed["geometry"],
    }
