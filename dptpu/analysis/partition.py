"""``partition-rules`` — the rules-table half of ``dptpu check``.

Statically validates the per-family partition-rules tables
(dptpu/models/registry.py) against the registry models they claim to
place, so a stale table fails ``dptpu check`` BEFORE any bench or
training run picks up a wrong placement:

* every table is well-formed (``validate_rules``: ordered regexes, a
  mandatory ``.*`` fallback, PartitionSpec/AUTO_FSDP values only);
* every axis name a spec mentions is a mesh axis (``slice``/``data``/
  ``model``) — a typo'd axis would only surface at jit time otherwise;
* no dead rules: each non-fallback rule matches at least one leaf in
  at least one of the family's structural representatives (Swin needs
  BOTH v1 and v2 — ``logit_scale``/``cpb_mlp`` exist only in v2, and a
  per-model census would flag those rows as dead on v1);
* no fallback-only sharded families: a family that declares
  model-axis (TP) rules must actually place leaves through them — a
  module rename that silently demotes every kernel to the AUTO_FSDP
  fallback is THE regression this rule exists to catch.

Param trees come from ``jax.eval_shape`` over ``model.init`` — shapes
only, nothing allocated — so the check stays cheap enough to run with
the HLO budget gates (the jax half of ``dptpu check``; the stdlib-only
``--no-hlo`` run skips it for the same reason it skips the budgets).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

# the mesh vocabulary every spec must stay inside
_MESH_AXES = ("slice", "data", "model")

# structural representatives: the smallest registry model(s) covering
# each family's module vocabulary. One per structure is enough — every
# vit_* shares in_proj/out_proj/mlp_* names, every swin_v2_* carries
# the v2-only leaves — and 4-variant coverage keeps the check seconds-
# cheap where all 79 registry models would take minutes.
FAMILY_REPRESENTATIVES: Dict[str, Tuple[str, ...]] = {
    "vit": ("vit_b_32",),
    "swin": ("swin_t", "swin_v2_t"),
    "convnext": ("convnext_tiny",),
    "generic": ("resnet18",),
}


@dataclasses.dataclass(frozen=True)
class PartitionViolation:
    """One failed partition-rules gate — formats to an actionable line."""

    family: str
    rule: str
    message: str

    def format(self) -> str:
        return (
            f"partition-rules: [{self.family}] {self.rule}: "
            f"{self.message} (fix the family's table in "
            f"dptpu/models/registry.py — every placement consumer "
            f"projects it)"
        )


def _family_params(arch: str):
    """Shape-only param tree for a registry arch (nothing allocated)."""
    import jax
    import jax.numpy as jnp

    from dptpu.models import create_model

    model = create_model(arch)
    shaped = jax.eval_shape(
        lambda r, x: model.init(r, x, train=False),
        jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3), jnp.float32),
    )
    return shaped["params"]


def check_partition_rules() -> List[PartitionViolation]:
    """Run every gate; [] means the tables and the zoo agree."""
    from dptpu.models.registry import FAMILY_RULES
    from dptpu.parallel.rules import (
        AutoFsdp,
        _entry_axes,
        rule_match_counts,
        validate_rules,
    )

    out: List[PartitionViolation] = []
    for family, rules in sorted(FAMILY_RULES.items()):
        try:
            validate_rules(rules)
        except ValueError as e:
            out.append(PartitionViolation(family, "well-formed", str(e)))
            continue
        for pattern, spec in rules:
            if isinstance(spec, AutoFsdp):
                continue
            bad = [a for entry in spec for a in _entry_axes(entry)
                   if a not in _MESH_AXES]
            if bad:
                out.append(PartitionViolation(
                    family, pattern,
                    f"spec {spec} names non-mesh axes {bad} — the mesh "
                    f"vocabulary is {'/'.join(_MESH_AXES)}",
                ))
        reps = FAMILY_REPRESENTATIVES[family]
        # first-match-wins census, aggregated across the family's
        # structural representatives (the dead-rule contract)
        totals = [0] * len(rules)
        for arch in reps:
            counts = rule_match_counts(rules, _family_params(arch))
            totals = [t + c for t, c in zip(totals, counts)]
        non_fallback_leaves = sum(totals[:-1])
        for i, (pattern, _) in enumerate(rules[:-1]):
            if totals[i] == 0:
                out.append(PartitionViolation(
                    family, pattern,
                    f"dead rule: matches zero leaves across "
                    f"{'/'.join(reps)} — a module rename orphaned it",
                ))
        if len(rules) > 1 and non_fallback_leaves == 0:
            out.append(PartitionViolation(
                family, "*",
                f"fallback-only family: every leaf of "
                f"{'/'.join(reps)} fell through to the .* row — the "
                f"declared sharding rules place nothing",
            ))
    return out


def partition_summary(violations: List[PartitionViolation]) -> dict:
    """The ANALYSIS.json block for the partition-rules half."""
    from dptpu.models.registry import FAMILY_RULES
    from dptpu.parallel.rules import rules_fingerprint

    return {
        "ok": not violations,
        "violations": [v.format() for v in violations],
        # the per-family table hashes — the same fingerprints the
        # checkpoint sharding stamp carries, so a placement drift is
        # diffable from the committed report alone
        "fingerprints": {
            family: rules_fingerprint(rules)
            for family, rules in sorted(FAMILY_RULES.items())
        },
        "representatives": {
            family: list(reps)
            for family, reps in sorted(FAMILY_REPRESENTATIVES.items())
        },
    }
