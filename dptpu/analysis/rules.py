"""The repo-invariant lint rules (registered into dptpu.analysis.lint).

Each rule machine-checks one contract the repo previously enforced only
by convention and by whichever test happened to exercise it:

* ``knob-contract`` — every ``DPTPU_*`` read flows through
  dptpu/envknob.py (fail-fast: a typo'd value raises, never silently
  falls back) or names a declared registry entry
  (dptpu/analysis/knobs.py), and every non-internal registry knob is
  documented in README.
* ``determinism`` — no wall-clock, unseeded RNG, ``os.urandom`` or
  set-iteration-ordering hazards inside the ``(seed, epoch, index)``
  bit-identity surfaces (dptpu/data/, dptpu/resilience/).
* ``host-sync`` — no device→host syncs (``.item()``, ``float(arr)``,
  ``np.asarray``/``np.array``, ``jax.device_get``,
  ``block_until_ready``) inside the hot-loop files' step bodies and the
  DevicePrefetcher.
* ``shm-hygiene`` — every /dev/shm segment creation goes through
  ``create_named_segment`` with a prefix the conftest leak-guard census
  knows, so an abandoned segment is attributable and policed.
* ``shard-map`` — step bodies go through ``shard_map_nocheck``
  (collectives placed EXPLICITLY under ``check_rep=False``) and thread
  ``axis_names`` through ``train_step_body`` so the hierarchical mesh
  cannot be silently dropped.

Stdlib-only, like the engine.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional, Tuple

from dptpu.analysis.lint import FileContext, register

_KNOB_RE = re.compile(r"^DPTPU_[A-Z0-9_]+$")

# the /dev/shm attribution prefixes the tests/conftest.py leak-guard
# census polices (dptpu_{kind}_{pid}_{hex}) — a new kind must be added
# BOTH there and here, which is the point: the census can't drift
SHM_CENSUS_PREFIXES = ("dptpu_ring", "dptpu_cache", "dptpu_serve",
                      "dptpu_shard")

# the bit-identity surfaces: everything the (seed, epoch, index) replay
# contract flows through
_DETERMINISM_DIRS = ("dptpu/data/", "dptpu/resilience/")

# the hot-path files the host-sync rule guards
_HOT_FILES = ("dptpu/train/loop.py", "dptpu/train/step.py",
              "dptpu/data/loader.py")


def _qualname(node: ast.AST) -> Optional[str]:
    """Dotted name for a Name/Attribute chain (``np.random.randint``)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _qualname(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _in_package(relpath: str) -> bool:
    return relpath.startswith(("dptpu/", "scripts/"))


# ------------------------------------------------------------ knob-contract


def _knob_scope(relpath: str) -> bool:
    # envknob.py IS the sanctioned read point
    return _in_package(relpath) and relpath != "dptpu/envknob.py"


@register(
    "knob-contract", _knob_scope,
    "DPTPU_* knobs: reads go through dptpu/envknob helpers (fail-fast, "
    "no silent fallback), names are declared in the registry "
    "(dptpu/analysis/knobs.py), and non-internal knobs are documented "
    "in README",
)
def knob_contract(ctx: FileContext) -> Iterator[Tuple[int, str]]:
    reg = ctx.repo.knobs
    if reg is None:
        from dptpu.analysis.knobs import KNOB_REGISTRY as reg  # noqa: N811
    for node in ast.walk(ctx.tree):
        # raw read with silent fallback: environ.get("DPTPU_X"[, default]),
        # os.getenv("DPTPU_X"[, default]), environ.setdefault(...)
        if isinstance(node, ast.Call):
            f = node.func
            q = _qualname(f) or ""
            raw_read = False
            if (isinstance(f, ast.Attribute)
                    and f.attr in ("get", "setdefault") and node.args):
                recv = _qualname(f.value) or ""
                raw_read = (recv.endswith("environ")
                            or recv in ("env", "environ"))
            elif q in ("os.getenv", "getenv") and node.args:
                raw_read = True
            if raw_read:
                knob = ctx.resolve_str(node.args[0])
                if knob and _KNOB_RE.match(knob):
                    yield node.lineno, (
                        f"raw environ read of {knob} bypasses the "
                        f"fail-fast knob contract — use the "
                        f"dptpu.envknob helper for its kind "
                        f"(env_int/env_float/env_bool/env_choice/"
                        f"env_str)"
                    )
        # raw subscript read: environ["DPTPU_X"] (writes/pops are the
        # bench drivers legitimately SETTING knobs for children)
        if (isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)):
            recv = _qualname(node.value) or ""
            knob = ctx.resolve_str(node.slice)
            if (knob and _KNOB_RE.match(knob)
                    and (recv.endswith("environ")
                         or recv in ("env", "environ"))):
                yield node.lineno, (
                    f"raw environ[{knob!r}] read bypasses the fail-fast "
                    f"knob contract — use a dptpu.envknob helper"
                )
        # every DPTPU_* literal must be declared (or be a declared-knob
        # prefix scan, e.g. "DPTPU_OBS_")
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            lit = node.value
            if not _KNOB_RE.match(lit):
                continue
            if lit.endswith("_"):
                if not any(k.startswith(lit) for k in reg):
                    yield node.lineno, (
                        f"knob prefix {lit!r} matches no declared "
                        f"registry knob (dptpu/analysis/knobs.py)"
                    )
            elif lit not in reg:
                yield node.lineno, (
                    f"undeclared knob {lit} — add a registry entry in "
                    f"dptpu/analysis/knobs.py (and README docs unless "
                    f"internal)"
                )
    # registry ↔ README cross-check, anchored at each entry's line in
    # the registry file itself
    if (ctx.relpath == "dptpu/analysis/knobs.py"
            and ctx.repo.readme_text is not None):
        lines = ctx.source.splitlines()
        for name, meta in sorted(reg.items()):
            if meta.get("internal"):
                continue
            # boundary match: DPTPU_SP documented must mean DPTPU_SP
            # itself, not a substring hit inside DPTPU_SP_MODE
            if not re.search(rf"{name}(?![A-Z0-9_])",
                             ctx.repo.readme_text):
                lineno = next(
                    (i for i, text in enumerate(lines, start=1)
                     if name in text), 1,
                )
                yield lineno, (
                    f"declared knob {name} is not documented in "
                    f"README's knob docs — document it (or mark the "
                    f"registry entry internal=True if it is a "
                    f"child-process sentinel)"
                )


# ------------------------------------------------------------- determinism


_SEEDED_NP_CTORS = {"RandomState", "default_rng", "Generator",
                    "SeedSequence", "PCG64", "Philox", "MT19937"}


def _determinism_scope(relpath: str) -> bool:
    return relpath.startswith(_DETERMINISM_DIRS)


@register(
    "determinism", _determinism_scope,
    "no wall-clock (time.time), unseeded random/np.random, os.urandom, "
    "or set-iteration-ordering hazards inside the (seed, epoch, index) "
    "bit-identity surfaces (dptpu/data/, dptpu/resilience/)",
)
def determinism(ctx: FileContext) -> Iterator[Tuple[int, str]]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            q = _qualname(node.func) or ""
            if q in ("time.time", "time.time_ns"):
                yield node.lineno, (
                    "wall-clock read in a bit-identity surface — replay "
                    "must not depend on when it runs (derive from "
                    "(seed, epoch, index), or use time.monotonic for "
                    "pure deadlines)"
                )
            elif q == "os.urandom":
                yield node.lineno, (
                    "os.urandom in a bit-identity surface — draw from a "
                    "seeded generator keyed by (seed, epoch, index)"
                )
            elif q in ("random.Random", "random.SystemRandom"):
                if q.endswith("SystemRandom") or not (
                        node.args or node.keywords):
                    yield node.lineno, (
                        f"{q}() without a seed in a bit-identity "
                        f"surface — seed it from (seed, epoch, index)"
                    )
            elif q.startswith("random.") and q[7:8].islower():
                yield node.lineno, (
                    f"{q}() draws from the process-global unseeded RNG "
                    f"— use a random.Random(seed) instance keyed by "
                    f"(seed, epoch, index)"
                )
            elif (q.startswith(("np.random.", "numpy.random."))
                  and q.rsplit(".", 1)[-1] not in _SEEDED_NP_CTORS):
                yield node.lineno, (
                    f"{q}() uses numpy's global RNG — use an explicit "
                    f"np.random.Generator/RandomState seeded from "
                    f"(seed, epoch, index)"
                )
            elif (q.startswith(("np.random.", "numpy.random."))
                  and q.rsplit(".", 1)[-1] in _SEEDED_NP_CTORS
                  and not (node.args or node.keywords)):
                yield node.lineno, (
                    f"{q}() without a seed is entropy-seeded — pass a "
                    f"seed derived from (seed, epoch, index)"
                )
        iters = []
        if isinstance(node, ast.For):
            iters = [node.iter]
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            iters = [g.iter for g in node.generators]
        for it in iters:
            is_set = isinstance(it, (ast.Set, ast.SetComp)) or (
                isinstance(it, ast.Call)
                and isinstance(it.func, ast.Name)
                and it.func.id in ("set", "frozenset")
            )
            if is_set:
                yield it.lineno, (
                    "iterating a set in a bit-identity surface — set "
                    "order depends on PYTHONHASHSEED across processes; "
                    "iterate sorted(...) instead"
                )


# --------------------------------------------------------------- host-sync


def _host_sync_scope(relpath: str) -> bool:
    return relpath in _HOT_FILES


@register(
    "host-sync", _host_sync_scope,
    "no device→host syncs (.item(), float(arr), np.asarray/np.array, "
    "jax.device_get, block_until_ready) in the hot-loop files' step "
    "bodies and DevicePrefetcher — a sync drains the dispatch queue "
    "and stalls the chip",
)
def host_sync(ctx: FileContext) -> Iterator[Tuple[int, str]]:
    # loader.py is scanned only inside DevicePrefetcher (the loader's
    # worker plumbing is host-side by definition); float()/np.*array
    # are additionally skipped in loop.py, whose floats convert
    # already-fetched host scalars — there the device_get sites ARE the
    # sync points this rule polices.
    in_loader = ctx.relpath == "dptpu/data/loader.py"
    flag_float = ctx.relpath != "dptpu/train/loop.py"
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if in_loader and "DevicePrefetcher" not in ctx.enclosing_functions(
                node):
            continue
        q = _qualname(node.func) or ""
        attr = node.func.attr if isinstance(node.func, ast.Attribute) \
            else None
        if q == "jax.device_get":
            yield node.lineno, (
                "jax.device_get blocks the host on the device stream — "
                "buffer device values and fetch once per interval (the "
                "loop.py lagged-fetch pattern)"
            )
        elif attr == "block_until_ready" or q == "jax.block_until_ready":
            yield node.lineno, (
                "block_until_ready drains the dispatch queue — only the "
                "measured bench harnesses may sync the stream"
            )
        elif attr == "item" and not node.args:
            yield node.lineno, (
                ".item() is a per-value device sync (the reference's "
                "per-batch stall, imagenet_ddp.py:267) — keep values on "
                "device and batch the fetch"
            )
        elif flag_float and q in ("np.asarray", "numpy.asarray",
                                  "np.array", "numpy.array"):
            yield node.lineno, (
                f"{q} on a device value copies through the host — keep "
                f"the math in jnp inside compiled code"
            )
        elif flag_float and q == "float" and node.args and not isinstance(
                node.args[0], ast.Constant):
            yield node.lineno, (
                "float(x) forces a device→host sync when x is a device "
                "array — keep scalars on device until the batched fetch"
            )


# ------------------------------------------------------------- shm-hygiene


def _dptpu_only(relpath: str) -> bool:
    return relpath.startswith("dptpu/")


@register(
    "shm-hygiene", _dptpu_only,
    "every /dev/shm segment creation goes through create_named_segment "
    "with a prefix in the conftest leak-guard census "
    f"({', '.join(SHM_CENSUS_PREFIXES)})",
)
def shm_hygiene(ctx: FileContext) -> Iterator[Tuple[int, str]]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        q = _qualname(node.func) or ""
        if q.rsplit(".", 1)[-1] == "SharedMemory":
            creating = any(
                kw.arg == "create"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in node.keywords
            )
            blessed = "create_named_segment" in ctx.enclosing_functions(
                node)
            if creating and not blessed:
                yield node.lineno, (
                    "direct SharedMemory(create=True) — allocate through "
                    "dptpu.data.shm_cache.create_named_segment so the "
                    "segment gets a census-attributable dptpu_* name "
                    "the conftest leak guard can police"
                )
        elif q.rsplit(".", 1)[-1] == "create_named_segment":
            prefix_node = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords
                 if kw.arg == "prefix"), None,
            )
            prefix = ctx.resolve_str(prefix_node) \
                if prefix_node is not None else None
            if prefix is None:
                yield node.lineno, (
                    "create_named_segment prefix is not statically "
                    "resolvable — the leak-guard census cannot "
                    "attribute the segment kind"
                )
            elif not prefix.startswith(SHM_CENSUS_PREFIXES):
                yield node.lineno, (
                    f"segment prefix {prefix!r} is outside the conftest "
                    f"leak-guard census ({', '.join(SHM_CENSUS_PREFIXES)}"
                    f") — add the kind to BOTH the census and "
                    f"dptpu/analysis/rules.py"
                )


# --------------------------------------------------------------- shard-map


@register(
    "shard-map", _dptpu_only,
    "step bodies go through shard_map_nocheck (explicit collectives "
    "under check_rep=False) and thread axis_names through "
    "train_step_body",
)
def shard_map_discipline(ctx: FileContext) -> Iterator[Tuple[int, str]]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        q = (_qualname(node.func) or "").rsplit(".", 1)[-1]
        if q == "shard_map":
            if "shard_map_nocheck" not in ctx.enclosing_functions(node):
                yield node.lineno, (
                    "raw shard_map call — go through "
                    "dptpu.train.step.shard_map_nocheck: this "
                    "container's rep-checker cannot infer the steps' "
                    "replicated outputs, so collectives are placed "
                    "explicitly under check_rep=False"
                )
        elif q == "train_step_body":
            if not any(kw.arg == "axis_names" for kw in node.keywords):
                yield node.lineno, (
                    "train_step_body called without axis_names — the "
                    "hierarchical {slice, data} mesh depends on the "
                    "axes being threaded through the step body"
                )
