"""Repo-invariant static analysis — ``dptpu check``.

Three parts (ISSUE 12 + the ISSUE 14 concurrency analyzer):

* **Concurrency rules** (:mod:`dptpu.analysis.concurrency`):
  ``guarded-by`` (shared mutable attributes of thread-spawning /
  lock-owning classes must be annotated and lock-held on every access),
  ``lock-order`` (acquisition-graph ABBA/cycle detection + the declared
  ``LOCK_RANKS`` order), and ``thread-hygiene`` (joinable non-daemon
  threads, census-attributable names, predicate-looped
  ``Condition.wait``, no join-under-lock). The runtime mirror is
  ``DPTPU_SYNC_CHECK=1`` (dptpu/utils/sync.py).

* **AST lint engine** (:mod:`dptpu.analysis.lint`, rules in
  :mod:`dptpu.analysis.rules`): stdlib-``ast`` lints for the contracts
  the repo otherwise enforces only by convention — the fail-fast
  ``DPTPU_*`` knob rule, determinism inside the ``(seed, epoch, index)``
  bit-identity surfaces, no host syncs in the hot path, ``/dev/shm``
  segment hygiene, and the explicit-collectives shard_map discipline.
  Findings are suppressible per line with
  ``# dptpu: allow-<rule>(<reason>)`` — a reason is MANDATORY.

* **HLO budget gates** (:mod:`dptpu.analysis.hlo_budget`): compile the
  representative step configs (DDP, ZeRO-1, accum, ``--slices``) on the
  CPU backend and assert the committed ``HLO_BUDGETS.json`` — per-link
  collective ops/bytes matching the analytic formulas locked in
  tests/test_hierarchy.py, donation honored, no f64 ops — so a
  comms/sharding regression fails ``dptpu check`` before any bench runs.

This module and the lint half import NOTHING heavy (no jax/numpy at
module scope) so the check can run inside spawned data workers and in
jax-free CI shards; only the HLO half touches jax, lazily.
"""

from dptpu.analysis.lint import (  # noqa: F401
    Finding,
    iter_rules,
    lint_paths,
    lint_repo,
    lint_source,
)
from dptpu.analysis.knobs import KNOB_REGISTRY  # noqa: F401
