"""Fail-fast environment-knob parsing, shared by every layer.

The locked knob contract (SURVEY §7 / PR 1): an UNSET or empty knob means
"use the default", but every EXPLICIT value must parse or raise an
actionable error — a typo'd knob must never silently fall back. One
implementation serves the trainer (``dptpu/train/fit.py``), the data
pipeline's supervision knobs (``dptpu/data/shm.py``) and the fault
harness (``dptpu/resilience/faults.py``); this module is imported inside
spawned data workers, so it stays stdlib-only — never JAX.
"""

from __future__ import annotations

import os
from typing import Optional


def env_int(name: str, default: Optional[int] = None,
            environ=None) -> Optional[int]:
    """Integer env knob; unset/empty → ``default`` (pass None so callers
    can tell an explicit 0 from absence), junk → actionable error."""
    raw = (environ if environ is not None else os.environ).get(
        name, "").strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not an integer (expected e.g. {name}=2)"
        ) from None


def env_float(name: str, default: Optional[float] = None,
              environ=None) -> Optional[float]:
    """Float env knob; unset/empty → ``default``, junk → actionable error."""
    raw = (environ if environ is not None else os.environ).get(
        name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not a number (expected e.g. {name}=2.5)"
        ) from None


def env_choice(name: str, choices, default: Optional[str] = None,
               environ=None) -> Optional[str]:
    """Enumerated env knob; unset/empty → ``default``, any explicit value
    must be one of ``choices`` or the knob raises with the accepted set."""
    raw = (environ if environ is not None else os.environ).get(
        name, "").strip()
    if not raw:
        return default
    if raw not in choices:
        raise ValueError(
            f"{name}={raw!r} must be one of "
            + "/".join(repr(c) for c in choices)
        )
    return raw


def env_str(name: str, default: Optional[str] = None,
            environ=None) -> Optional[str]:
    """String env knob (paths, specs, sentinels); unset/empty →
    ``default``. Any explicit value is legal — the helper exists so
    free-form knobs still flow through ONE read point (the knob-contract
    lint, dptpu/analysis, flags raw ``os.environ`` reads) and so their
    names land in the declared registry + README like every other knob."""
    raw = (environ if environ is not None else os.environ).get(
        name, "").strip()
    return raw if raw else default


_TRUE = ("1", "true", "yes", "on")
_FALSE = ("0", "false", "no", "off")


def env_bool(name: str, default: Optional[bool] = None,
             environ=None) -> Optional[bool]:
    """Boolean env knob; unset/empty → ``default``, anything outside the
    1/0/true/false/yes/no/on/off vocabulary → actionable error (the same
    fail-fast contract as the numeric knobs — a typo'd 'flase' must not
    silently mean anything)."""
    raw = (environ if environ is not None else os.environ).get(
        name, "").strip().lower()
    if not raw:
        return default
    if raw in _TRUE:
        return True
    if raw in _FALSE:
        return False
    raise ValueError(
        f"{name}={raw!r} is not a boolean (expected e.g. {name}=1 or "
        f"{name}=0)"
    )
