"""Leased shared-memory staging ring for request batches.

The serving twin of the feed's zero-copy batch-slot ring
(dptpu/data/shm.py): preprocessed request pixels are written ONCE, into
a row of a preallocated /dev/shm slot, and the device reads from that
same memory — no per-request copy-out, no per-batch assemble. The
handoff protocol is literally the feed's: a dispatched slot is held by a
:class:`dptpu.data.shm.SlotLease` (the same class — generation-checked,
double-release-safe) and recycles only on ``release()``, which the
engine performs after the batch's logits have materialized (by then the
device has consumed the input bytes on every backend, including the
CPU PJRT whose ``device_put`` zero-copy-aliases host buffers — the
DevicePrefetcher's aliasing hazard, defended here by ordering rather
than copying).

Segments are named ``dptpu_serve_{pid}_{hex}`` so the conftest /dev/shm
leak guard polices them exactly like ``dptpu_ring_*``/``dptpu_cache_*``;
``live_segment_names()`` is its allowlist and ``leaked_lease_count()``
its close-with-lease-outstanding counter, mirroring dptpu/data/shm.

Slot lifecycle: FREE -> FILLING (the batcher's one open slot, rows
claimed per request) -> LEASED (dispatched to the device) -> FREE
(lease released). /dev/shm rather than plain numpy so a future
process-pool preprocessor (the feed's worker model) can decode straight
into the ring without a byte of plumbing changing.

jax-free by design: the conftest guard and the CLI's fail-fast path
import this module before any backend exists.
"""

from __future__ import annotations

import atexit
import weakref
from typing import Optional, Tuple

import numpy as np

from dptpu.data.shm import SlotLease
from dptpu.data.shm_cache import close_segment, create_named_segment

SEGMENT_PREFIX = "dptpu_serve"

_LIVE_RINGS: "weakref.WeakSet" = weakref.WeakSet()
_ATEXIT_REGISTERED = False

# slots still leased when their ring closed — a serve-side protocol bug
# (the engine must release after logits materialize); the conftest
# session fixture fails the suite when this moves
_LEASE_LEAKS = 0

_FREE, _FILLING, _LEASED = 0, 1, 2


def leaked_lease_count() -> int:
    """Staging slots still leased when their ring closed, summed over
    every ring this process has closed (same contract as
    ``dptpu.data.shm.leaked_lease_count``)."""
    return _LEASE_LEAKS


def live_segment_names():
    """Segment names owned by still-open rings in THIS process (the
    conftest /dev/shm leak guard's allowlist)."""
    return {
        ring._shm.name.lstrip("/")
        for ring in list(_LIVE_RINGS)
        if not ring._closed
    }


def _atexit_close_all():
    for ring in list(_LIVE_RINGS):
        try:
            ring.close()
        except Exception:
            pass


def _register(ring):
    global _ATEXIT_REGISTERED
    _LIVE_RINGS.add(ring)
    if not _ATEXIT_REGISTERED:
        atexit.register(_atexit_close_all)
        _ATEXIT_REGISTERED = True


class StagingRing:
    """``slots`` request-batch buffers of ``bucket_max`` rows each, in one
    named /dev/shm segment."""

    def __init__(self, slots: int, bucket_max: int,
                 item_shape: Tuple[int, int, int]):
        if slots < 2:
            raise ValueError(
                f"staging ring needs >= 2 slots (one filling + one "
                f"leased), got {slots}"
            )
        self.slots = slots
        self.bucket_max = bucket_max
        self.item_shape = tuple(item_shape)
        nbytes = int(np.prod((slots, bucket_max) + self.item_shape))
        self._shm = create_named_segment(SEGMENT_PREFIX, nbytes)
        self._imgs = np.ndarray(
            (slots, bucket_max) + self.item_shape, np.uint8,
            buffer=self._shm.buf,
        )
        # Generation-fenced slot state machine (CONCURRENCY.md): forward
        # transitions (FREE->FILLING->LEASED) run on the batcher
        # dispatcher thread only; the backward LEASED->FREE transition
        # runs on whichever thread releases the lease, fenced by the
        # per-slot generation counter so a late release against a
        # recycled slot is a no-op. Every write is one GIL-atomic list
        # element store — the protocol, not a lock, is the owner.
        self._state = [_FREE] * slots  # owned-by: slot-protocol
        self._gen = [0] * slots  # owned-by: slot-protocol
        self._closed = False  # owned-by: slot-protocol
        _register(self)

    def acquire(self) -> Optional[int]:
        """Claim a FREE slot for filling; None when every slot is either
        the open one or still leased to an in-flight batch (the
        batcher's backpressure moment)."""
        for s in range(self.slots):
            if self._state[s] == _FREE:
                self._state[s] = _FILLING
                return s
        return None

    def rows(self, slot: int) -> np.ndarray:
        """The slot's ``[bucket_max, H, W, C]`` view — the batcher hands
        out one row per request for in-place preprocessing."""
        return self._imgs[slot]

    def lease(self, slot: int) -> SlotLease:
        """Dispatch the FILLING slot: it stays byte-stable until the
        returned lease is released (the engine does, after the batch's
        logits are on the host)."""
        if self._state[slot] != _FILLING:
            raise RuntimeError(
                f"staging slot {slot} leased while "
                f"{'FREE' if self._state[slot] == _FREE else 'already leased'}"
            )
        self._state[slot] = _LEASED
        return SlotLease(self, slot, self._gen[slot])

    def abandon(self, slot: int) -> None:
        """Return a FILLING slot unleased (batcher shutdown with
        requests still queued — their futures fail, the slot frees)."""
        if self._state[slot] == _FILLING:
            self._state[slot] = _FREE
            self._gen[slot] += 1

    def _release_slot(self, slot: int, gen: int) -> None:
        # SlotLease's callback — generation check makes a late release
        # against a closed/recycled ring a no-op (shared contract with
        # the feed ring)
        if self._closed or self._gen[slot] != gen \
                or self._state[slot] != _LEASED:
            return
        self._state[slot] = _FREE
        self._gen[slot] += 1

    def leased_count(self) -> int:
        return sum(1 for s in self._state if s == _LEASED)

    def free_count(self) -> int:
        return sum(1 for s in self._state if s == _FREE)

    def close(self) -> None:
        global _LEASE_LEAKS
        if self._closed:
            return
        self._closed = True
        _LEASE_LEAKS += self.leased_count()
        self._imgs = None
        close_segment(self._shm, unlink=True)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
