"""The inference engine: AOT bucket compilation, placement, hot-swap.

Design (ISSUE 7 tentpole):

* **AOT bucket ladder** — the forward pass is lowered + compiled at
  construction for every batch-size bucket in the ladder
  (``DPTPU_SERVE_BUCKETS``), so no request ever hits a compile stall:
  the first request is as fast as the thousandth. Weights are a call
  ARGUMENT, not a captured constant, so a hot-swap never recompiles.

* **Batch-invariant numerics** — the = 0 logit-parity contract between
  buckets needs per-row results that do not depend on the executable's
  batch size. Two measured sources of batch-dependence on this
  toolchain's CPU backend, each with its own counter (locked by the
  parity test):

  - XLA's M=1 matmul lowers to a gemv whose reduction order differs
    from the M>=2 gemm path (max|Δlogit| ~ 3e-6 on a 512x1000 head) —
    countered by the **execution floor**: every bucket executes at
    ``max(bucket, 2)`` rows, so the single-request path rides the SAME
    gemm lowering as every padded bucket. Exactness costs one duplicate
    row through the trunk at bucket 1 (noise on an accelerator, the
    honest price of = 0 on CPU).
  - Eigen's MULTI-THREADED gemm splits the K reduction shape-dependently
    (resnet18's 1x1 downsample conv diverged 5e-7 between exec 4 and
    exec 8 on a 2-core host) — countered by compiling serve executables
    with ``xla_cpu_multi_thread_eigen=false`` (``compiler_options``,
    scoped to THESE executables only — training jits in the same
    process keep threaded gemm). Measured cost on the 2-core bench box:
    none (82.5 vs 87.8 ms for a bucket-16 resnet18@32 — thread handoff
    outweighed the parallel win at serving shapes). TPU backends have
    no Eigen and take no flag; the MXU's tiling is batch-invariant.

* **Padded-batch execution** — a bucket runs with ``n_valid`` real rows
  and ``exec - n_valid`` pad rows (row-0 repeats, the loader's padding
  convention); eval-mode forwards are row-independent (BN uses running
  stats), so pad content cannot perturb real rows, and the result is
  sliced to ``n_valid``.

* **Placement per family** (``resolve_placement``) — ``replicated``
  runs the single-program forward; ``tp`` opens a ``model``-axis mesh
  and shards params by the family's Megatron rule
  (dptpu/parallel/gspmd.py ``tp_specs_for_arch``; activations
  replicated, the partitioner inserts the per-block all-reduces).
  ``auto`` picks TP for the three families with a real rule when more
  than one device is visible, replicated otherwise.

* **Generation-tagged weights** — ``swap_weights`` installs a new
  weight generation without dropping in-flight requests: a dispatched
  batch pins the generation it was assigned (``acquire_generation``),
  every batch is served by exactly ONE generation (mixed-generation
  serving is structurally impossible — one pytree per call), and a
  superseded generation's buffers are dropped the moment its last
  in-flight batch releases (``old generation drains``).

* **Precision axis** (ISSUE 18) — the bucket ladder is compiled per
  PRECISION: ``_compiled[(precision, nexec)]``. ``fp32`` is the base
  ladder (today's path); ``bf16`` stores matmul weights bf16; ``int8``
  stores them int8 with per-channel scales (dptpu/ops/quant.py) and
  dequantizes in-graph to bf16 — the compiled HLO carries ``s8``
  params and ``bf16`` dots (statically asserted by the serve-quant
  budget config in ``dptpu check``). Each weight GENERATION carries
  its precision, so a quantized rollout is just a staged generation:
  it rides the canary machinery (shadow eval, top-1 agreement +
  max|Δlogit| gate, auto-rollback) and is NEVER silently promoted —
  ``stage_quantized`` also refuses to run without a verified
  calibration artifact (CRC + arch + weights-fingerprint match).

* **Per-shard TP loading** — under ``tp`` placement, weights are
  constructed shard-by-shard from the unified partition-rules
  projection (``jax.make_array_from_callback``: each device's shard is
  sliced from the host array on demand) instead of gathering the full
  array onto every device and resharding — the serve twin of the
  rules-table unification, locked at max|Δlogit| = 0 against the
  gathered path.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from dptpu import obs
from dptpu.serve.knobs import parse_buckets
from dptpu.utils.sync import OrderedLock

# the measured gemv/gemm divergence floor (module docstring): every
# executable's leading dim is >= 2 so all buckets share one lowering
EXEC_FLOOR = 2


def serve_compiler_options():
    """Per-executable options for batch-invariant numerics (module
    docstring): on the CPU backend, single-thread Eigen's gemm so
    reduction order cannot depend on the batch dimension; elsewhere no
    flag (and an unknown option would be rejected by the plugin)."""
    if jax.default_backend() == "cpu":
        return {"xla_cpu_multi_thread_eigen": False}
    return None


def resolve_placement(arch: str, placement: str,
                      device_count: Optional[int] = None) -> str:
    """``auto``/``replicated``/``tp`` -> the concrete placement, failing
    fast on impossible requests (explicit ``tp`` for a family with no TP
    rule, or on a single device) instead of silently degrading."""
    from dptpu.parallel.gspmd import tp_rule_for_arch

    if device_count is None:
        device_count = jax.device_count()
    rule = tp_rule_for_arch(arch)
    if placement == "tp":
        if rule == "dp_specs":
            raise ValueError(
                f"--placement=tp: no tensor-parallel sharding rule for "
                f"{arch!r} (TP families: vit_*, swin*, convnext_* — see "
                f"dptpu/parallel/gspmd.py tp_rule_for_arch); use "
                f"--placement=replicated"
            )
        if device_count < 2:
            raise ValueError(
                f"--placement=tp needs >= 2 devices to open a model "
                f"axis, found {device_count}"
            )
        return "tp"
    if placement == "replicated":
        return "replicated"
    # auto: TP where a family rule exists and there is a mesh to use it
    return "tp" if (rule != "dp_specs" and device_count >= 2) \
        else "replicated"


class ServeEngine:
    """AOT bucket-compiled, hot-swappable eval forward for one registry
    arch. ``variables`` takes explicit weights (tests/benches);
    ``pretrained=True`` loads the converted-torchvision ``<arch>.npz``
    (``DPTPU_PRETRAINED_DIR``); neither = random init (load-testing)."""

    def __init__(self, arch: str, *, buckets: Sequence[int] = (1, 4, 16, 64),
                 placement: str = "auto", num_classes: int = 1000,
                 image_size: int = 224, variables: Optional[dict] = None,
                 pretrained: bool = False,
                 compute_dtype=jnp.float32, verbose: bool = False):
        from dptpu.models import create_model

        self.arch = arch
        # immutable tuple, republished whole by add_bucket (one
        # GIL-atomic store, every named exec size compiled first) from
        # the single thread that ticks the serve-ladder actuator; all
        # other readers take lock-free snapshots
        self.buckets = parse_buckets(buckets, source="buckets")  # owned-by: tick-thread
        self.num_classes = num_classes
        self.image_size = image_size
        self.compute_dtype = compute_dtype
        self.model = create_model(
            arch, pretrained=pretrained, num_classes=num_classes
        )
        # built lazily at first sub-fp32 stage; duplicate off-lock
        # builds produce identical clones, so last-write-wins is benign
        self._bf16_model_cache = None  # dptpu: allow-guarded-by(idempotent lazy clone; racing stagers rebuild an identical module)
        self.placement = resolve_placement(arch, placement)
        input_shape = (1, image_size, image_size, 3)
        if variables is None:
            if pretrained:
                from dptpu.models.pretrained import load_pretrained_variables

                variables = load_pretrained_variables(
                    arch, self.model, input_shape=input_shape
                )
            else:
                init = self.model.init(
                    jax.random.PRNGKey(0),
                    np.zeros(input_shape, np.float32), train=False,
                )
                variables = {"params": init["params"],
                             "batch_stats": init.get("batch_stats", {})}
        variables = {"params": variables["params"],
                     "batch_stats": variables.get("batch_stats", {})}
        # host-side fp32 copy: the quantization source (stage_quantized
        # fingerprints + quantizes THESE exact weights) — one host copy,
        # never on device
        self._host_variables = jax.tree_util.tree_map(
            np.asarray, variables
        )

        self._mesh = None
        self._var_shardings = None
        self.tp_rule = "dp_specs"
        if self.placement == "tp":
            from jax.sharding import NamedSharding, PartitionSpec as P

            from dptpu.parallel.gspmd import tp_specs_for_arch
            from dptpu.parallel.mesh import MODEL_AXIS, make_mesh

            self._mesh = make_mesh(
                mesh_shape={MODEL_AXIS: jax.device_count()}
            )
            self.tp_rule, specs = tp_specs_for_arch(
                arch, variables["params"]
            )
            rep = NamedSharding(self._mesh, P())
            self._var_shardings = {
                "params": jax.tree_util.tree_map(
                    lambda s: NamedSharding(self._mesh, s), specs
                ),
                "batch_stats": jax.tree_util.tree_map(
                    lambda _: rep, variables["batch_stats"]
                ),
            }
            self._img_sharding = rep
            self._out_sharding = rep

        # generation store: {gen: device-placed variables}; a dispatched
        # batch pins its generation until its logits materialize.
        # _gen is the CURRENT (default-served) generation; _latest is the
        # id counter — they diverge while a canary generation is staged
        # (resident + pinned by its controller, but not current)
        self._lock = OrderedLock("serve.engine")
        self._gen = 1  # guarded-by: _lock
        self._latest = 1  # guarded-by: _lock
        self._weights: Dict[int, dict] = {1: self._place(variables)}  # guarded-by: _lock
        self._inflight: Dict[int, int] = {1: 0}  # guarded-by: _lock
        self._precision: Dict[int, str] = {1: "fp32"}  # guarded-by: _lock
        self._verbose = verbose

        # AOT compile the base ladder (dedup buckets that share an exec
        # size: 1 and 2 both execute at the floor); further precision
        # ladders compile lazily at first stage of that precision
        self._compiled = {}  # {(precision, nexec): executable}  # dptpu: allow-guarded-by(idempotent compile cache mutated off-lock by design; concurrent stagers race to identical executables and dict stores are atomic)
        self._compile_ladder("fp32", self._weights[1])

    # -- compilation ----------------------------------------------------

    def _forward(self, variables, images):
        from dptpu.train.step import normalize_images

        x = normalize_images(images, self.compute_dtype)
        out = self.model.apply(variables, x, train=False)
        return out.astype(jnp.float32)

    def _forward_int8(self, qvariables, images):
        from dptpu.ops.quant import dequantize_tree
        from dptpu.train.step import normalize_images

        # in-graph dequantize: weights STAY int8 in device memory (the
        # residency win); the convert+scale fuses into the consumer and
        # every dot runs bf16
        variables = {
            "params": dequantize_tree(qvariables["params"], jnp.bfloat16),
            "batch_stats": qvariables["batch_stats"],
        }
        x = normalize_images(images, jnp.bfloat16)
        out = self._bf16_model().apply(variables, x, train=False)
        return out.astype(jnp.float32)

    def _forward_bf16(self, variables, images):
        from dptpu.train.step import normalize_images

        x = normalize_images(images, jnp.bfloat16)
        out = self._bf16_model().apply(variables, x, train=False)
        return out.astype(jnp.float32)

    def _bf16_model(self):
        """The model at compute dtype bf16 — the sub-fp32 forwards MUST
        apply this twin, not ``self.model``: every registry module casts
        activations to its own ``dtype`` attribute (fp32 here), so
        applying the fp32 module would silently promote every dot back
        to f32 and keep only the residency win. The serve-quant HLO
        budget gate (`dptpu check`) asserts the requested dot dtypes
        statically, so that regression fails before any bench."""
        if self._bf16_model_cache is None:
            self._bf16_model_cache = self.model.clone(dtype=jnp.bfloat16)
        return self._bf16_model_cache

    def _forward_for(self, precision: str):
        return {"fp32": self._forward, "bf16": self._forward_bf16,
                "int8": self._forward_int8}[precision]

    def _compile_ladder(self, precision: str, placed_variables) -> None:
        """AOT-compile every bucket of the ladder at ``precision`` from
        a placed variables tree (idempotent; races between concurrent
        stagers install identical executables)."""
        var_structs = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            placed_variables,
        )
        for b in self.buckets:
            nexec = self.exec_batch(b)
            if (precision, nexec) in self._compiled:
                continue
            with obs.get_tracer().span("serve_compile"):
                exe = self._compile_at(nexec, var_structs, precision)
            self._compiled[(precision, nexec)] = exe
            if self._verbose:
                print(f"=> serve: AOT-compiled {self.arch} bucket {b} "
                      f"(exec batch {nexec}, {self.placement}, "
                      f"{precision})")

    def _compile_at(self, nexec: int, var_structs, precision: str = "fp32"):
        img = jax.ShapeDtypeStruct(
            (nexec, self.image_size, self.image_size, 3), jnp.uint8
        )
        forward = self._forward_for(precision)
        if self.placement == "tp":
            fn = jax.jit(
                forward,
                in_shardings=(self._var_shardings, self._img_sharding),
                out_shardings=self._out_sharding,
                compiler_options=serve_compiler_options(),
            )
        else:
            fn = jax.jit(
                forward, compiler_options=serve_compiler_options()
            )
        return fn.lower(var_structs, img).compile()

    def exec_batch(self, bucket: int) -> int:
        """The executable's leading dim for ``bucket`` (the >= 2 floor)."""
        return max(int(bucket), EXEC_FLOOR)

    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= ``n`` (the batcher's coalescing target)."""
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(
            f"{n} requests exceed the largest bucket "
            f"{self.buckets[-1]} — the batcher must split first"
        )

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    def add_bucket(self, bucket: int) -> Optional[int]:
        """Insert an INTERIOR bucket into the ladder at runtime (the
        tune controller's serve-ladder actuator, ISSUE 19): AOT-compile
        the new exec size for every resident precision FIRST, then
        publish the new ladder — no request ever hits a compile stall,
        and admission (``max_bucket``) never moves. Returns the bucket,
        or None when it already exists or falls outside
        ``(0, max_bucket)`` — the actuator reads None as "no headroom"
        and disarms cleanly."""
        bucket = int(bucket)
        if bucket < 1 or bucket >= self.max_bucket \
                or bucket in self.buckets:
            return None
        nexec = self.exec_batch(bucket)
        with self._lock:
            by_precision = {
                self._precision[g]: self._weights[g]
                for g in sorted(self._weights)
            }
        for precision, placed in by_precision.items():
            if (precision, nexec) in self._compiled:
                continue
            var_structs = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                placed,
            )
            with obs.get_tracer().span("serve_compile"):
                exe = self._compile_at(nexec, var_structs, precision)
            self._compiled[(precision, nexec)] = exe
        # one GIL-atomic tuple store publishes the grown ladder to the
        # dispatch thread's bucket_for/max_bucket reads; every exec size
        # it names is compiled above, before the store
        self.buckets = tuple(sorted(self.buckets + (bucket,)))
        if self._verbose:
            print(f"=> serve: ladder grew to {self.buckets} "
                  f"(tune controller inserted bucket {bucket})")
        return bucket

    # -- weight generations ---------------------------------------------

    def _place(self, variables):
        if self.placement == "tp":
            # per-shard construction from the rules projection: each
            # device's addressable shard is SLICED from the host array
            # by the callback — the full array is never gathered onto
            # any device and then resharded (the old device_put path).
            # Bit-identical to the gathered path (same host values,
            # same final layout) — locked at max|Δlogit| = 0 by
            # tests/test_serve.py.
            def put(x, s):
                a = np.asarray(x)
                return jax.make_array_from_callback(
                    a.shape, s, lambda idx, _a=a: _a[idx]
                )

            return jax.tree_util.tree_map(
                put, variables, self._var_shardings,
            )
        return jax.device_put(variables)

    def _place_gathered(self, variables):
        """The pre-rules-projection placement (gather the full array to
        every device, let the sharding reshard) — kept ONLY as the = 0
        parity reference for the per-shard path."""
        if self.placement == "tp":
            return jax.tree_util.tree_map(
                lambda x, s: jax.device_put(np.asarray(x), s),
                variables, self._var_shardings,
            )
        return jax.device_put(variables)

    def swap_weights(self, variables) -> int:
        """Install a new weight generation (same tree/shapes — validated
        against the compiled signature by construction: a mismatched
        tree fails the compiled call loudly, not silently). In-flight
        batches keep serving their pinned generation; the old one is
        dropped when its last batch releases. Returns the new id."""
        variables = {"params": variables["params"],
                     "batch_stats": variables.get("batch_stats", {})}
        placed = self._place(variables)  # off-lock: device transfer
        with self._lock:
            self._latest += 1
            self._gen = self._latest
            self._weights[self._gen] = placed
            self._inflight[self._gen] = 0
            self._precision[self._gen] = "fp32"
            self._drop_drained_locked()
            return self._gen

    def stage_weights(self, variables, precision: str = "fp32") -> int:
        """Install a new generation WITHOUT making it current (the
        canary rollout's first half): the generation is resident and
        pinnable via ``acquire_generation(gen=...)``, but default
        traffic keeps serving the current one. The staged generation
        starts with ONE in-flight pin — the stager's — so draining
        cannot drop it before ``promote`` or ``discard_staged`` decides
        its fate. ``precision`` != fp32 expects an ALREADY-converted
        tree (``stage_quantized`` is the artifact-verified front door)
        and lazily compiles that precision's ladder. Returns the staged
        id."""
        variables = {"params": variables["params"],
                     "batch_stats": variables.get("batch_stats", {})}
        if precision != "fp32" and self.placement == "tp":
            raise ValueError(
                f"precision {precision!r} is not supported under tp "
                f"placement (quantized marker leaves have no sharding "
                f"rule projection yet) — serve quantized models "
                f"replicated"
            )
        placed = self._place(variables)  # off-lock: device transfer
        self._compile_ladder(precision, placed)  # off-lock: idempotent
        with self._lock:
            self._latest += 1
            gen = self._latest
            self._weights[gen] = placed
            self._inflight[gen] = 1  # the stager's pin
            self._precision[gen] = precision
            return gen

    def stage_quantized(self, calibration: str, precision: str = "int8"):
        """The quantized rollout's front door: verify the calibration
        artifact against THIS engine's arch and live weights (CRC +
        arch + weights fingerprint — dptpu/serve/quant.py names the
        recalibration command on any mismatch), quantize the host-side
        fp32 weights with the artifact's scales, and stage the result
        as a new generation. Returns ``(gen, meta)`` — ``meta`` carries
        the gate bounds the canary controller must enforce
        (``meta["bounds"]``: min top-1 agreement, max|Δlogit|). bf16
        precision needs no scales; the artifact is still required so
        every sub-fp32 deployment has a provenance record."""
        from dptpu.serve.quant import load_calibration, quantize_variables

        payload = load_calibration(
            calibration, arch=self.arch,
            params=self._host_variables["params"],
        )
        qvars = quantize_variables(
            self._host_variables, precision,
            scales=payload.get("scales") if precision == "int8" else None,
        )
        gen = self.stage_weights(qvars, precision=precision)
        return gen, payload["meta"]

    def promote(self, gen: int) -> None:
        """Make a staged generation CURRENT (the canary rollout's happy
        ending) and release the stager's pin; the superseded generation
        drains away exactly like a ``swap_weights`` predecessor."""
        with self._lock:
            if gen not in self._weights:
                raise KeyError(f"generation {gen} is not resident")
            if gen == self._gen:
                return
            self._gen = gen
            self._inflight[gen] -= 1
            self._drop_drained_locked()

    def discard_staged(self, gen: int) -> None:
        """Release the stager's pin WITHOUT promoting (canary rollback):
        the staged generation's buffers drop the moment its last
        in-flight canary batch releases."""
        with self._lock:
            if gen not in self._weights or gen == self._gen:
                return  # already dropped, or promoted out from under us
            self._inflight[gen] -= 1
            self._drop_drained_locked()

    def acquire_generation(self, gen: Optional[int] = None) -> int:
        """Pin a generation for one batch (default: the CURRENT one;
        a canary controller pins its staged id explicitly); the batch is
        served with this generation's weights no matter what swaps land
        while it is in flight."""
        with self._lock:
            if gen is None:
                gen = self._gen
            elif gen not in self._weights:
                raise KeyError(
                    f"generation {gen} is not resident (live: "
                    f"{sorted(self._weights)})"
                )
            self._inflight[gen] += 1
            return gen

    def release_generation(self, gen: int) -> None:
        with self._lock:
            self._inflight[gen] -= 1
            self._drop_drained_locked()

    def _drop_drained_locked(self):
        for g in [g for g in self._weights
                  if g != self._gen and self._inflight[g] == 0]:
            del self._weights[g]
            del self._inflight[g]
            del self._precision[g]

    def generations(self) -> Tuple[int, ...]:
        """Live (resident) generation ids — newest is current; older
        ones are draining."""
        with self._lock:
            return tuple(sorted(self._weights))

    @property
    def current_generation(self) -> int:
        with self._lock:
            return self._gen

    def generation_precision(self, gen: Optional[int] = None) -> str:
        """The precision axis of a resident generation (default: the
        current one)."""
        with self._lock:
            return self._precision[self._gen if gen is None else gen]

    def resident_bytes(self) -> Dict[int, int]:
        """Per-generation resident weight bytes — the HBM-residency
        meter SERVEBENCH's quantized arm reports (int8 matmul weights
        are 4x smaller than their fp32 generation)."""
        from dptpu.ops.quant import tree_nbytes

        with self._lock:
            return {g: tree_nbytes(w) for g, w in self._weights.items()}

    # -- execution ------------------------------------------------------

    def run_bucket(self, bucket: int, images_exec: np.ndarray,
                   n_valid: int, gen: Optional[int] = None) -> np.ndarray:
        """Run one padded bucket: ``images_exec`` is the FULL
        ``exec_batch(bucket)``-row array (pad rows already filled — the
        batcher repeats row 0), ``n_valid`` of which are real. Blocks
        until the logits are on the host (which is also the moment the
        input buffer is provably no longer read — the staging lease may
        release after this returns, CPU-PJRT aliasing included). Returns
        float32 ``[n_valid, num_classes]``."""
        nexec = self.exec_batch(bucket)
        if images_exec.shape[0] != nexec:
            raise ValueError(
                f"bucket {bucket} executes at {nexec} rows, got "
                f"{images_exec.shape[0]}"
            )
        owns_gen = gen is None
        if owns_gen:
            gen = self.acquire_generation()
        try:
            with self._lock:
                weights = self._weights[gen]
                precision = self._precision[gen]
            with obs.get_tracer().span("serve_device"):
                out = self._compiled[(precision, nexec)](
                    weights, images_exec
                )
                logits = np.asarray(out)  # blocks: device done with input
        finally:
            if owns_gen:
                self.release_generation(gen)
        return logits[:n_valid]

    def infer(self, images: np.ndarray) -> np.ndarray:
        """Convenience single-shot path (tests, the CLI self-test): pick
        the bucket for ``len(images)``, pad with row-0 repeats, run,
        slice. The batcher's zero-copy path calls ``run_bucket`` on a
        staging-slot view instead."""
        images = np.ascontiguousarray(images, dtype=np.uint8)
        n = images.shape[0]
        nexec = self.exec_batch(self.bucket_for(n))
        if n < nexec:
            pad = np.broadcast_to(
                images[0], (nexec - n,) + images.shape[1:]
            )
            images = np.concatenate([images, pad], axis=0)
        return self.run_bucket(self.bucket_for(n), images, n)
