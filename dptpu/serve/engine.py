"""The inference engine: AOT bucket compilation, placement, hot-swap.

Design (ISSUE 7 tentpole):

* **AOT bucket ladder** — the forward pass is lowered + compiled at
  construction for every batch-size bucket in the ladder
  (``DPTPU_SERVE_BUCKETS``), so no request ever hits a compile stall:
  the first request is as fast as the thousandth. Weights are a call
  ARGUMENT, not a captured constant, so a hot-swap never recompiles.

* **Batch-invariant numerics** — the = 0 logit-parity contract between
  buckets needs per-row results that do not depend on the executable's
  batch size. Two measured sources of batch-dependence on this
  toolchain's CPU backend, each with its own counter (locked by the
  parity test):

  - XLA's M=1 matmul lowers to a gemv whose reduction order differs
    from the M>=2 gemm path (max|Δlogit| ~ 3e-6 on a 512x1000 head) —
    countered by the **execution floor**: every bucket executes at
    ``max(bucket, 2)`` rows, so the single-request path rides the SAME
    gemm lowering as every padded bucket. Exactness costs one duplicate
    row through the trunk at bucket 1 (noise on an accelerator, the
    honest price of = 0 on CPU).
  - Eigen's MULTI-THREADED gemm splits the K reduction shape-dependently
    (resnet18's 1x1 downsample conv diverged 5e-7 between exec 4 and
    exec 8 on a 2-core host) — countered by compiling serve executables
    with ``xla_cpu_multi_thread_eigen=false`` (``compiler_options``,
    scoped to THESE executables only — training jits in the same
    process keep threaded gemm). Measured cost on the 2-core bench box:
    none (82.5 vs 87.8 ms for a bucket-16 resnet18@32 — thread handoff
    outweighed the parallel win at serving shapes). TPU backends have
    no Eigen and take no flag; the MXU's tiling is batch-invariant.

* **Padded-batch execution** — a bucket runs with ``n_valid`` real rows
  and ``exec - n_valid`` pad rows (row-0 repeats, the loader's padding
  convention); eval-mode forwards are row-independent (BN uses running
  stats), so pad content cannot perturb real rows, and the result is
  sliced to ``n_valid``.

* **Placement per family** (``resolve_placement``) — ``replicated``
  runs the single-program forward; ``tp`` opens a ``model``-axis mesh
  and shards params by the family's Megatron rule
  (dptpu/parallel/gspmd.py ``tp_specs_for_arch``; activations
  replicated, the partitioner inserts the per-block all-reduces).
  ``auto`` picks TP for the three families with a real rule when more
  than one device is visible, replicated otherwise.

* **Generation-tagged weights** — ``swap_weights`` installs a new
  weight generation without dropping in-flight requests: a dispatched
  batch pins the generation it was assigned (``acquire_generation``),
  every batch is served by exactly ONE generation (mixed-generation
  serving is structurally impossible — one pytree per call), and a
  superseded generation's buffers are dropped the moment its last
  in-flight batch releases (``old generation drains``).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from dptpu import obs
from dptpu.serve.knobs import parse_buckets
from dptpu.utils.sync import OrderedLock

# the measured gemv/gemm divergence floor (module docstring): every
# executable's leading dim is >= 2 so all buckets share one lowering
EXEC_FLOOR = 2


def serve_compiler_options():
    """Per-executable options for batch-invariant numerics (module
    docstring): on the CPU backend, single-thread Eigen's gemm so
    reduction order cannot depend on the batch dimension; elsewhere no
    flag (and an unknown option would be rejected by the plugin)."""
    if jax.default_backend() == "cpu":
        return {"xla_cpu_multi_thread_eigen": False}
    return None


def resolve_placement(arch: str, placement: str,
                      device_count: Optional[int] = None) -> str:
    """``auto``/``replicated``/``tp`` -> the concrete placement, failing
    fast on impossible requests (explicit ``tp`` for a family with no TP
    rule, or on a single device) instead of silently degrading."""
    from dptpu.parallel.gspmd import tp_rule_for_arch

    if device_count is None:
        device_count = jax.device_count()
    rule = tp_rule_for_arch(arch)
    if placement == "tp":
        if rule == "dp_specs":
            raise ValueError(
                f"--placement=tp: no tensor-parallel sharding rule for "
                f"{arch!r} (TP families: vit_*, swin*, convnext_* — see "
                f"dptpu/parallel/gspmd.py tp_rule_for_arch); use "
                f"--placement=replicated"
            )
        if device_count < 2:
            raise ValueError(
                f"--placement=tp needs >= 2 devices to open a model "
                f"axis, found {device_count}"
            )
        return "tp"
    if placement == "replicated":
        return "replicated"
    # auto: TP where a family rule exists and there is a mesh to use it
    return "tp" if (rule != "dp_specs" and device_count >= 2) \
        else "replicated"


class ServeEngine:
    """AOT bucket-compiled, hot-swappable eval forward for one registry
    arch. ``variables`` takes explicit weights (tests/benches);
    ``pretrained=True`` loads the converted-torchvision ``<arch>.npz``
    (``DPTPU_PRETRAINED_DIR``); neither = random init (load-testing)."""

    def __init__(self, arch: str, *, buckets: Sequence[int] = (1, 4, 16, 64),
                 placement: str = "auto", num_classes: int = 1000,
                 image_size: int = 224, variables: Optional[dict] = None,
                 pretrained: bool = False,
                 compute_dtype=jnp.float32, verbose: bool = False):
        from dptpu.models import create_model

        self.arch = arch
        self.buckets = parse_buckets(buckets, source="buckets")
        self.num_classes = num_classes
        self.image_size = image_size
        self.compute_dtype = compute_dtype
        self.model = create_model(
            arch, pretrained=pretrained, num_classes=num_classes
        )
        self.placement = resolve_placement(arch, placement)
        input_shape = (1, image_size, image_size, 3)
        if variables is None:
            if pretrained:
                from dptpu.models.pretrained import load_pretrained_variables

                variables = load_pretrained_variables(
                    arch, self.model, input_shape=input_shape
                )
            else:
                init = self.model.init(
                    jax.random.PRNGKey(0),
                    np.zeros(input_shape, np.float32), train=False,
                )
                variables = {"params": init["params"],
                             "batch_stats": init.get("batch_stats", {})}
        variables = {"params": variables["params"],
                     "batch_stats": variables.get("batch_stats", {})}

        self._mesh = None
        self._var_shardings = None
        self.tp_rule = "dp_specs"
        if self.placement == "tp":
            from jax.sharding import NamedSharding, PartitionSpec as P

            from dptpu.parallel.gspmd import tp_specs_for_arch
            from dptpu.parallel.mesh import MODEL_AXIS, make_mesh

            self._mesh = make_mesh(
                mesh_shape={MODEL_AXIS: jax.device_count()}
            )
            self.tp_rule, specs = tp_specs_for_arch(
                arch, variables["params"]
            )
            rep = NamedSharding(self._mesh, P())
            self._var_shardings = {
                "params": jax.tree_util.tree_map(
                    lambda s: NamedSharding(self._mesh, s), specs
                ),
                "batch_stats": jax.tree_util.tree_map(
                    lambda _: rep, variables["batch_stats"]
                ),
            }
            self._img_sharding = rep
            self._out_sharding = rep

        # generation store: {gen: device-placed variables}; a dispatched
        # batch pins its generation until its logits materialize.
        # _gen is the CURRENT (default-served) generation; _latest is the
        # id counter — they diverge while a canary generation is staged
        # (resident + pinned by its controller, but not current)
        self._lock = OrderedLock("serve.engine")
        self._gen = 1  # guarded-by: _lock
        self._latest = 1  # guarded-by: _lock
        self._weights: Dict[int, dict] = {1: self._place(variables)}  # guarded-by: _lock
        self._inflight: Dict[int, int] = {1: 0}  # guarded-by: _lock

        # AOT compile the ladder (dedup buckets that share an exec size:
        # 1 and 2 both execute at the floor)
        self._compiled = {}
        var_structs = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            self._weights[1],
        )
        for b in self.buckets:
            nexec = self.exec_batch(b)
            if nexec in self._compiled:
                continue
            with obs.get_tracer().span("serve_compile"):
                self._compiled[nexec] = self._compile_at(nexec, var_structs)
            if verbose:
                print(f"=> serve: AOT-compiled {arch} bucket {b} "
                      f"(exec batch {nexec}, {self.placement})")

    # -- compilation ----------------------------------------------------

    def _forward(self, variables, images):
        from dptpu.train.step import normalize_images

        x = normalize_images(images, self.compute_dtype)
        out = self.model.apply(variables, x, train=False)
        return out.astype(jnp.float32)

    def _compile_at(self, nexec: int, var_structs):
        img = jax.ShapeDtypeStruct(
            (nexec, self.image_size, self.image_size, 3), jnp.uint8
        )
        if self.placement == "tp":
            fn = jax.jit(
                self._forward,
                in_shardings=(self._var_shardings, self._img_sharding),
                out_shardings=self._out_sharding,
                compiler_options=serve_compiler_options(),
            )
        else:
            fn = jax.jit(
                self._forward, compiler_options=serve_compiler_options()
            )
        return fn.lower(var_structs, img).compile()

    def exec_batch(self, bucket: int) -> int:
        """The executable's leading dim for ``bucket`` (the >= 2 floor)."""
        return max(int(bucket), EXEC_FLOOR)

    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= ``n`` (the batcher's coalescing target)."""
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(
            f"{n} requests exceed the largest bucket "
            f"{self.buckets[-1]} — the batcher must split first"
        )

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    # -- weight generations ---------------------------------------------

    def _place(self, variables):
        if self.placement == "tp":
            return jax.tree_util.tree_map(
                lambda x, s: jax.device_put(np.asarray(x), s),
                variables, self._var_shardings,
            )
        return jax.device_put(variables)

    def swap_weights(self, variables) -> int:
        """Install a new weight generation (same tree/shapes — validated
        against the compiled signature by construction: a mismatched
        tree fails the compiled call loudly, not silently). In-flight
        batches keep serving their pinned generation; the old one is
        dropped when its last batch releases. Returns the new id."""
        variables = {"params": variables["params"],
                     "batch_stats": variables.get("batch_stats", {})}
        placed = self._place(variables)  # off-lock: device transfer
        with self._lock:
            self._latest += 1
            self._gen = self._latest
            self._weights[self._gen] = placed
            self._inflight[self._gen] = 0
            self._drop_drained_locked()
            return self._gen

    def stage_weights(self, variables) -> int:
        """Install a new generation WITHOUT making it current (the
        canary rollout's first half): the generation is resident and
        pinnable via ``acquire_generation(gen=...)``, but default
        traffic keeps serving the current one. The staged generation
        starts with ONE in-flight pin — the stager's — so draining
        cannot drop it before ``promote`` or ``discard_staged`` decides
        its fate. Returns the staged id."""
        variables = {"params": variables["params"],
                     "batch_stats": variables.get("batch_stats", {})}
        placed = self._place(variables)  # off-lock: device transfer
        with self._lock:
            self._latest += 1
            gen = self._latest
            self._weights[gen] = placed
            self._inflight[gen] = 1  # the stager's pin
            return gen

    def promote(self, gen: int) -> None:
        """Make a staged generation CURRENT (the canary rollout's happy
        ending) and release the stager's pin; the superseded generation
        drains away exactly like a ``swap_weights`` predecessor."""
        with self._lock:
            if gen not in self._weights:
                raise KeyError(f"generation {gen} is not resident")
            if gen == self._gen:
                return
            self._gen = gen
            self._inflight[gen] -= 1
            self._drop_drained_locked()

    def discard_staged(self, gen: int) -> None:
        """Release the stager's pin WITHOUT promoting (canary rollback):
        the staged generation's buffers drop the moment its last
        in-flight canary batch releases."""
        with self._lock:
            if gen not in self._weights or gen == self._gen:
                return  # already dropped, or promoted out from under us
            self._inflight[gen] -= 1
            self._drop_drained_locked()

    def acquire_generation(self, gen: Optional[int] = None) -> int:
        """Pin a generation for one batch (default: the CURRENT one;
        a canary controller pins its staged id explicitly); the batch is
        served with this generation's weights no matter what swaps land
        while it is in flight."""
        with self._lock:
            if gen is None:
                gen = self._gen
            elif gen not in self._weights:
                raise KeyError(
                    f"generation {gen} is not resident (live: "
                    f"{sorted(self._weights)})"
                )
            self._inflight[gen] += 1
            return gen

    def release_generation(self, gen: int) -> None:
        with self._lock:
            self._inflight[gen] -= 1
            self._drop_drained_locked()

    def _drop_drained_locked(self):
        for g in [g for g in self._weights
                  if g != self._gen and self._inflight[g] == 0]:
            del self._weights[g]
            del self._inflight[g]

    def generations(self) -> Tuple[int, ...]:
        """Live (resident) generation ids — newest is current; older
        ones are draining."""
        with self._lock:
            return tuple(sorted(self._weights))

    @property
    def current_generation(self) -> int:
        with self._lock:
            return self._gen

    # -- execution ------------------------------------------------------

    def run_bucket(self, bucket: int, images_exec: np.ndarray,
                   n_valid: int, gen: Optional[int] = None) -> np.ndarray:
        """Run one padded bucket: ``images_exec`` is the FULL
        ``exec_batch(bucket)``-row array (pad rows already filled — the
        batcher repeats row 0), ``n_valid`` of which are real. Blocks
        until the logits are on the host (which is also the moment the
        input buffer is provably no longer read — the staging lease may
        release after this returns, CPU-PJRT aliasing included). Returns
        float32 ``[n_valid, num_classes]``."""
        nexec = self.exec_batch(bucket)
        if images_exec.shape[0] != nexec:
            raise ValueError(
                f"bucket {bucket} executes at {nexec} rows, got "
                f"{images_exec.shape[0]}"
            )
        owns_gen = gen is None
        if owns_gen:
            gen = self.acquire_generation()
        try:
            with self._lock:
                weights = self._weights[gen]
            with obs.get_tracer().span("serve_device"):
                out = self._compiled[nexec](weights, images_exec)
                logits = np.asarray(out)  # blocks: device done with input
        finally:
            if owns_gen:
                self.release_generation(gen)
        return logits[:n_valid]

    def infer(self, images: np.ndarray) -> np.ndarray:
        """Convenience single-shot path (tests, the CLI self-test): pick
        the bucket for ``len(images)``, pad with row-0 repeats, run,
        slice. The batcher's zero-copy path calls ``run_bucket`` on a
        staging-slot view instead."""
        images = np.ascontiguousarray(images, dtype=np.uint8)
        n = images.shape[0]
        nexec = self.exec_batch(self.bucket_for(n))
        if n < nexec:
            pad = np.broadcast_to(
                images[0], (nexec - n,) + images.shape[1:]
            )
            images = np.concatenate([images, pad], axis=0)
        return self.run_bucket(self.bucket_for(n), images, n)
