"""Serve-side quantization: the calibration artifact and its loader.

``dptpu quantize`` runs OFFLINE: it builds the fp32 model, computes
per-channel absmax scales (dptpu/ops/quant.py), replays a shard sample
through both the fp32 and the quantized forward, and commits the
result as a **calibration artifact** — the provenance record a
quantized deployment must present before it is allowed to serve:

* CRC-sealed with the checkpoint footer discipline
  (``dptpu.train.checkpoint.seal_payload``) — bit rot and truncated
  writes fail the load, never parse;
* stamped with the arch AND a content fingerprint of the exact weights
  it was calibrated against — quantizing *different* weights with
  stale scales is the silent-drift path, so the loader refuses it by
  name;
* carrying the drift gate's bounds (min top-1 agreement, max|Δlogit|)
  **measured on the calibration sample** — the canary controller
  enforces the same bounds online, so the artifact states exactly what
  "no drift" means for this deployment.

Every load failure names the recalibration command — the operator
never has to reverse-engineer what went stale.
"""

from __future__ import annotations

import os
import tempfile
import zlib
from typing import Optional, Tuple

import numpy as np

from dptpu.ops.quant import cast_tree, quantize_tree, scales_tree
from dptpu.serve.knobs import PRECISIONS

# Artifact format version: bump on any change to the scales scheme or
# the meta layout (the loader refuses newer schemes by name).
CALIB_SCHEME = "absmax-int8-perchannel-v1"

# Conservative defaults when the operator does not override: bounds are
# stamped from the MEASURED calibration-sample stats with this margin
# (drift grows ~sqrt(depth) off-sample; 2x headroom keeps the gate
# honest without tripping on sampling noise).
DRIFT_MARGIN = 2.0


class CalibrationError(ValueError):
    """Calibration artifact missing/corrupt/mismatched — message always
    names the ``dptpu quantize`` recalibration command."""


def _recalib_cmd(arch: str, path: str) -> str:
    return f"dptpu quantize --arch {arch} --out {path}"


def weights_fingerprint(params) -> str:
    """Content fingerprint of a param tree: crc32 over (path, shape,
    dtype, raw bytes) of every leaf in canonical flatten order. Ties an
    artifact to the EXACT weights it was calibrated from — a resumed
    checkpoint, a different seed, or a new pretrained drop all change
    the fingerprint and force recalibration."""
    import jax

    crc = 0
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    for path, leaf in flat:
        a = np.asarray(leaf)
        header = f"{jax.tree_util.keystr(path)}|{a.shape}|{a.dtype}"
        crc = zlib.crc32(header.encode(), crc)
        crc = zlib.crc32(np.ascontiguousarray(a).tobytes(), crc)
    return f"{crc & 0xFFFFFFFF:08x}"


def measure_drift(base_logits, q_logits) -> Tuple[float, float]:
    """``(top1_agreement, max_abs_dlogit)`` between two logit batches —
    the SERVEBENCH parity-style pair the quantized gate is built on."""
    b = np.asarray(base_logits, np.float32)
    q = np.asarray(q_logits, np.float32)
    if b.shape != q.shape:
        raise ValueError(f"logit shape mismatch {b.shape} vs {q.shape}")
    agree = float(np.mean(b.argmax(-1) == q.argmax(-1)))
    drift = float(np.max(np.abs(b - q))) if b.size else 0.0
    return agree, drift


def quantize_variables(variables: dict, precision: str,
                       scales: Optional[dict] = None) -> dict:
    """A serve variables dict (``{"params", "batch_stats"}``) at the
    requested precision. ``batch_stats`` always stays fp32 (BN moving
    stats are normalization state, same rule as norm params)."""
    if precision not in PRECISIONS:
        raise ValueError(
            f"precision {precision!r} not in {PRECISIONS}"
        )
    bs = variables.get("batch_stats", {})
    if precision == "fp32":
        return {"params": variables["params"], "batch_stats": bs}
    if precision == "bf16":
        import jax.numpy as jnp

        return {"params": cast_tree(variables["params"], jnp.bfloat16),
                "batch_stats": bs}
    return {"params": quantize_tree(variables["params"], scales),
            "batch_stats": bs}


def save_calibration(path: str, *, arch: str, params, stats: dict,
                     bounds: dict, num_classes: int,
                     image_size: int, sample_n: int,
                     extra_meta: Optional[dict] = None) -> dict:
    """Seal + atomically write the calibration artifact. Returns the
    restored-form payload (what :func:`load_calibration` will answer)."""
    from flax import serialization

    from dptpu.train.checkpoint import seal_payload
    from dptpu.utils.provenance import host_provenance

    payload = {
        "meta": {
            "scheme": CALIB_SCHEME,
            "arch": arch,
            "weights_fingerprint": weights_fingerprint(params),
            "num_classes": int(num_classes),
            "image_size": int(image_size),
            "sample_n": int(sample_n),
            "stats": {k: float(v) for k, v in stats.items()},
            "bounds": {k: float(v) for k, v in bounds.items()},
            "host": host_provenance(),
        },
        "scales": scales_tree(params),
    }
    raw = seal_payload(serialization.msgpack_serialize(payload))
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".calib-")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(raw)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return payload


def load_calibration(path: str, *, arch: Optional[str] = None,
                     params=None) -> dict:
    """Load + verify a calibration artifact; every failure is a
    :class:`CalibrationError` naming the recalibration command.

    Checks, in order: file present and non-empty → CRC footer present
    AND valid (an unfooted file is not a calibration artifact) → scheme
    known → arch matches (when given) → weights fingerprint matches the
    live params (when given) — the arch/generation match the ISSUE
    locks."""
    from flax import serialization

    from dptpu.train.checkpoint import CorruptCheckpointError, split_payload

    cmd = _recalib_cmd(arch or "<arch>", path)
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as e:
        raise CalibrationError(
            f"calibration artifact {path}: {e.strerror or e} — "
            f"produce one with: {cmd}"
        ) from e
    if not raw:
        raise CalibrationError(
            f"calibration artifact {path} is empty (crashed write?) — "
            f"recalibrate with: {cmd}"
        )
    try:
        payload_bytes, verified = split_payload(raw, path)
    except CorruptCheckpointError as e:
        raise CalibrationError(
            f"{e} — recalibrate with: {cmd}"
        ) from e
    if not verified:
        raise CalibrationError(
            f"calibration artifact {path} has no CRC footer — not a "
            f"dptpu calibration artifact (or truncated past the "
            f"footer); recalibrate with: {cmd}"
        )
    try:
        payload = serialization.msgpack_restore(payload_bytes)
    except Exception as e:
        raise CalibrationError(
            f"calibration artifact {path} failed to parse after a "
            f"clean CRC ({e}) — recalibrate with: {cmd}"
        ) from e
    meta = payload.get("meta", {})
    if meta.get("scheme") != CALIB_SCHEME:
        raise CalibrationError(
            f"calibration artifact {path}: scheme "
            f"{meta.get('scheme')!r} != {CALIB_SCHEME!r} (artifact from "
            f"a different dptpu version) — recalibrate with: {cmd}"
        )
    if arch is not None and meta.get("arch") != arch:
        raise CalibrationError(
            f"calibration artifact {path} was calibrated for arch "
            f"{meta.get('arch')!r}, not {arch!r} — recalibrate with: "
            f"{_recalib_cmd(arch, path)}"
        )
    if params is not None:
        live = weights_fingerprint(params)
        want = meta.get("weights_fingerprint")
        if live != want:
            raise CalibrationError(
                f"calibration artifact {path} was calibrated against "
                f"weights {want} but the engine is serving weights "
                f"{live} (new checkpoint / different generation) — "
                f"stale scales drift silently, so this refuses to "
                f"load; recalibrate with: {cmd}"
            )
    return payload
