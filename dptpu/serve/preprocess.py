"""Request preprocessing: image bytes -> model-ready uint8 HWC tensor.

ONE implementation of "what pixels does a request become", shared by the
serving engine, ``scripts/check_tv_parity.py`` and any offline caller:
the pixel-exact validation stack (``ValTransform`` — Resize(256) →
CenterCrop(224) as one fractional-box resample, dptpu/data/transforms.py)
applied to a PIL RGB decode of the bytes.

Bit-identity contract (locked by tests/test_serve.py): for a given image
file, ``preprocess_bytes(open(f,'rb').read())`` equals the row the
training/eval pipeline produces for that file —
``ImageFolderDataset(transform=ValTransform()).get(i)`` — byte for byte.
That holds because this IS the same code path: ``ValTransform`` sets
``native_ok = False``, so the val pipeline always decodes via PIL
(reproducing torchvision's published-accuracy pixels; the native fast
path's scaled decode + 2-tap lerp is augmentation-grade — see the
ValTransform docstring), and so does this function. A model served here
sees exactly the pixels its reported validation accuracy was measured
on.

Output stays uint8 HWC: like the training feed, normalization happens
on device inside the compiled forward (``normalize_images``) — x4 less
staging-buffer traffic and one fewer host-side float pass per request.
"""

from __future__ import annotations

import io
import sys
from typing import Optional

import numpy as np

from dptpu.data.transforms import ValTransform

# fused native serve-ingest (dptpu_serve_ingest in image_ops.cpp): JPEG
# bytes -> val pixels straight into the staging row, one native call, no
# PIL round trip. It is only ever used after PROVING bit-identity against
# the PIL path on this host's libjpeg (tri-state: None = not yet probed).
_NATIVE_INGEST_OK: Optional[bool] = None

_JPEG_MAGIC = b"\xff\xd8\xff"


def _pil_val_pixels(data: bytes, size: int, resize: int) -> np.ndarray:
    """The reference PIL path, non-recursively (what the probe compares
    the native kernel against)."""
    from PIL import Image

    tf = ValTransform(size, resize)
    with Image.open(io.BytesIO(data)) as img:
        return tf(img.convert("RGB"))


def _probe_native_ingest() -> bool:
    """Prove ``dptpu_serve_ingest`` bit-identical to the PIL path on THIS
    host before it may serve a single request. The probe JPEGs cover the
    geometries that exercise every branch of the resample (odd dims,
    portrait/landscape, grayscale->RGB replication, box-enlarge,
    progressive scan); any mismatching byte disables the kernel for the
    process, LOUDLY — served pixels silently diverging from the pixels
    accuracy was measured on is the one failure this path must not have.
    """
    from dptpu.native.build import load_library

    lib = load_library()
    if lib is None or not hasattr(lib, "dptpu_serve_ingest"):
        return False
    from PIL import Image

    rng = np.random.RandomState(0)
    cases = []
    for (w, h, mode, kw) in [
        (277, 179, "RGB", {"quality": 85}),
        (160, 240, "RGB", {"quality": 92}),
        (200, 200, "L", {"quality": 85}),
        (96, 80, "RGB", {"quality": 90}),   # resize=256 ENLARGES this one
        (230, 310, "RGB", {"quality": 85, "progressive": True}),
    ]:
        shape = (h, w, 3) if mode == "RGB" else (h, w)
        buf = io.BytesIO()
        Image.fromarray(rng.randint(0, 256, shape, np.uint8), mode).save(
            buf, "JPEG", **kw
        )
        cases.append(buf.getvalue())
    for size, resize in ((224, 256), (64, 73)):
        for data in cases:
            native = np.empty((size, size, 3), np.uint8)
            rc = lib.dptpu_serve_ingest(data, len(data), size, resize,
                                        native.ctypes.data)
            if rc != 0 or not np.array_equal(
                native, _pil_val_pixels(data, size, resize)
            ):
                print(
                    "=> dptpu serve-ingest native kernel FAILED the "
                    f"bit-identity probe (rc={rc}, size={size}) — this "
                    "host's libjpeg does not reproduce PIL's pixels; "
                    "serving stays on the PIL path (slower, identical "
                    "output)", file=sys.stderr, flush=True,
                )
                return False
    return True


def _native_ingest(data: bytes, size: int, resize: int,
                   out: Optional[np.ndarray]) -> Optional[np.ndarray]:
    """The fused path, or None when the caller must use PIL (probe
    failed, non-JPEG bytes, or a per-image bail like CMYK color)."""
    global _NATIVE_INGEST_OK
    if not data.startswith(_JPEG_MAGIC):
        return None
    if _NATIVE_INGEST_OK is None:
        _NATIVE_INGEST_OK = _probe_native_ingest()
    if not _NATIVE_INGEST_OK:
        return None
    from dptpu.native.build import load_library

    lib = load_library()
    if out is not None and (out.shape != (size, size, 3)
                            or out.dtype != np.uint8):
        raise ValueError(
            f"preprocess out buffer is {out.dtype}{out.shape}, "
            f"expected uint8{(size, size, 3)}"
        )
    dst = out if (out is not None and out.flags.c_contiguous) else \
        np.empty((size, size, 3), np.uint8)
    rc = lib.dptpu_serve_ingest(data, len(data), size, resize,
                                dst.ctypes.data)
    if rc != 0:
        return None  # corrupt/CMYK/etc: PIL decides (and 400s cleanly)
    if out is not None and dst is not out:
        np.copyto(out, dst)
        return out
    return dst


def val_resize_for(size: int) -> int:
    """The val pipeline's resize edge for a crop of ``size``: the
    reference 256-resize-then-224-crop ratio, scaled (fit.py builds the
    val dataset with exactly this formula — 256 at the standard 224).
    Serving MUST use the same formula or a non-224 engine would crop a
    different fraction of the image than the accuracy was measured on."""
    return int(size * 256 / 224)


def preprocess_bytes(data: bytes, size: int = 224,
                     resize: Optional[int] = None,
                     out: Optional[np.ndarray] = None,
                     _transform: Optional[ValTransform] = None
                     ) -> np.ndarray:
    """Decode + val-transform one request's image bytes.

    ``resize`` defaults to ``val_resize_for(size)`` — the val
    pipeline's own edge, at EVERY size, not just 224.

    ``out`` (uint8 ``(size, size, 3)``) lets the batcher write the pixels
    straight into a staging-ring row — the request-side analog of the
    loader's decode-into-slot path; anything else allocates. JPEG, PNG
    and every other PIL-decodable container are accepted (requests are
    not guaranteed to be JPEG); undecodable bytes raise ``ValueError``
    naming the cause, so a bad request 400s instead of crashing a batch.

    ``_transform`` lets a hot caller reuse one ``ValTransform`` (it is
    stateless; the default constructs per call for the one-shot case).

    JPEG requests take the fused native serve-ingest kernel
    (``dptpu_serve_ingest``) when — and only when — it has PROVED
    bit-identity with the PIL path on this host (probe at first use,
    loud stderr fallback): one native call decodes and box-resamples
    straight into ``out``, so the identical pixels arrive without the
    PIL round trip or any intermediate fp32 buffer. Every other
    container, and every native bail (CMYK, corrupt bytes), lands on
    the PIL path below — same pixels either way, that is the contract.
    """
    from PIL import Image, UnidentifiedImageError

    if resize is None:
        resize = val_resize_for(size)
    fast = _native_ingest(data, size, resize, out)
    if fast is not None:
        return fast
    tf = _transform if _transform is not None else ValTransform(size, resize)
    try:
        with Image.open(io.BytesIO(data)) as img:
            arr = tf(img.convert("RGB"))
    except (UnidentifiedImageError, OSError) as e:
        raise ValueError(f"undecodable image bytes: {e}") from None
    if out is not None:
        if out.shape != arr.shape or out.dtype != np.uint8:
            raise ValueError(
                f"preprocess out buffer is {out.dtype}{out.shape}, "
                f"expected uint8{arr.shape}"
            )
        np.copyto(out, arr)
        return out
    return arr


def preprocess_array(img: np.ndarray, size: int = 224,
                     resize: Optional[int] = None,
                     out: Optional[np.ndarray] = None) -> np.ndarray:
    """Same val stack over an already-decoded uint8 HWC array (the
    bench's synthetic-request path — no container round trip)."""
    from PIL import Image

    tf = ValTransform(size, resize if resize is not None
                      else val_resize_for(size))
    arr = tf(Image.fromarray(img))
    if out is not None:
        np.copyto(out, arr)
        return out
    return arr
