"""Request preprocessing: image bytes -> model-ready uint8 HWC tensor.

ONE implementation of "what pixels does a request become", shared by the
serving engine, ``scripts/check_tv_parity.py`` and any offline caller:
the pixel-exact validation stack (``ValTransform`` — Resize(256) →
CenterCrop(224) as one fractional-box resample, dptpu/data/transforms.py)
applied to a PIL RGB decode of the bytes.

Bit-identity contract (locked by tests/test_serve.py): for a given image
file, ``preprocess_bytes(open(f,'rb').read())`` equals the row the
training/eval pipeline produces for that file —
``ImageFolderDataset(transform=ValTransform()).get(i)`` — byte for byte.
That holds because this IS the same code path: ``ValTransform`` sets
``native_ok = False``, so the val pipeline always decodes via PIL
(reproducing torchvision's published-accuracy pixels; the native fast
path's scaled decode + 2-tap lerp is augmentation-grade — see the
ValTransform docstring), and so does this function. A model served here
sees exactly the pixels its reported validation accuracy was measured
on.

Output stays uint8 HWC: like the training feed, normalization happens
on device inside the compiled forward (``normalize_images``) — x4 less
staging-buffer traffic and one fewer host-side float pass per request.
"""

from __future__ import annotations

import io
from typing import Optional

import numpy as np

from dptpu.data.transforms import ValTransform


def val_resize_for(size: int) -> int:
    """The val pipeline's resize edge for a crop of ``size``: the
    reference 256-resize-then-224-crop ratio, scaled (fit.py builds the
    val dataset with exactly this formula — 256 at the standard 224).
    Serving MUST use the same formula or a non-224 engine would crop a
    different fraction of the image than the accuracy was measured on."""
    return int(size * 256 / 224)


def preprocess_bytes(data: bytes, size: int = 224,
                     resize: Optional[int] = None,
                     out: Optional[np.ndarray] = None,
                     _transform: Optional[ValTransform] = None
                     ) -> np.ndarray:
    """Decode + val-transform one request's image bytes.

    ``resize`` defaults to ``val_resize_for(size)`` — the val
    pipeline's own edge, at EVERY size, not just 224.

    ``out`` (uint8 ``(size, size, 3)``) lets the batcher write the pixels
    straight into a staging-ring row — the request-side analog of the
    loader's decode-into-slot path; anything else allocates. JPEG, PNG
    and every other PIL-decodable container are accepted (requests are
    not guaranteed to be JPEG); undecodable bytes raise ``ValueError``
    naming the cause, so a bad request 400s instead of crashing a batch.

    ``_transform`` lets a hot caller reuse one ``ValTransform`` (it is
    stateless; the default constructs per call for the one-shot case).
    """
    from PIL import Image, UnidentifiedImageError

    if resize is None:
        resize = val_resize_for(size)
    tf = _transform if _transform is not None else ValTransform(size, resize)
    try:
        with Image.open(io.BytesIO(data)) as img:
            arr = tf(img.convert("RGB"))
    except (UnidentifiedImageError, OSError) as e:
        raise ValueError(f"undecodable image bytes: {e}") from None
    if out is not None:
        if out.shape != arr.shape or out.dtype != np.uint8:
            raise ValueError(
                f"preprocess out buffer is {out.dtype}{out.shape}, "
                f"expected uint8{arr.shape}"
            )
        np.copyto(out, arr)
        return out
    return arr


def preprocess_array(img: np.ndarray, size: int = 224,
                     resize: Optional[int] = None,
                     out: Optional[np.ndarray] = None) -> np.ndarray:
    """Same val stack over an already-decoded uint8 HWC array (the
    bench's synthetic-request path — no container round trip)."""
    from PIL import Image

    tf = ValTransform(size, resize if resize is not None
                      else val_resize_for(size))
    arr = tf(Image.fromarray(img))
    if out is not None:
        np.copyto(out, arr)
        return out
    return arr
