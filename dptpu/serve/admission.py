"""Admission control: bounded queues, priorities, deadline feasibility.

ISSUE 17 tentpole (a). The staging ring already bounds MEMORY (submit
blocks when every slot is leased), but blocking is the WRONG overload
response for a latency-bounded tier: a request that will wait longer
than its deadline should be rejected in microseconds, not queued into
a p99 explosion. This layer sits in FRONT of the batcher and answers
one question per request — *can this request plausibly be served within
its deadline, and is there room for its priority class?* — without
touching the device path:

* **Bounded occupancy** — at most ``DPTPU_SERVE_QUEUE_DEPTH`` requests
  may be admitted-but-unanswered per model. Occupancy is taken at
  ``try_admit`` and released by a :class:`ServeFuture` done-callback,
  so it counts the WHOLE lifecycle (queue + preprocess + coalesce +
  device), not just a queue length.

* **Priority water marks** (``DPTPU_SERVE_PRIORITIES``, fractions of
  the depth, non-increasing high→normal→low): a priority class is shed
  with **503** once occupancy crosses its mark, so low-priority traffic
  drains first and high-priority traffic still lands at full depth.
  503 = "the server is saturated, back off" and carries ``Retry-After``
  derived from the service-time EWMA.

* **Deadline feasibility** — a request whose deadline budget is below
  the observed service-time EWMA cannot succeed; it is rejected
  immediately with **429** (the client asked for the impossible —
  retrying the same deadline will fail again, so no ``Retry-After``).

Shedding happens entirely under one mutex with no allocation or device
work, so the rejection fast-path stays orders of magnitude below a
service time — SERVEBENCH's overload arm gates on exactly that.

Lock order: ``serve.admission`` (rank 15) sits ABOVE the batcher lock
(rank 10) because releases run inside future done-callbacks fired under
the batcher's condition, and BELOW the engine lock (rank 20).
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence, Tuple

from dptpu.serve.knobs import (
    DEFAULT_DEADLINE_MS,
    DEFAULT_PRIORITIES,
    DEFAULT_QUEUE_DEPTH,
    PRIORITY_NAMES,
)
from dptpu.utils.sync import OrderedLock


class AdmissionError(RuntimeError):
    """Request shed at the admission boundary; carries the HTTP status
    (429 infeasible deadline / 503 saturated) and an optional
    ``Retry-After`` hint in seconds."""

    def __init__(self, msg: str, status: int,
                 retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.status = status
        self.retry_after_s = retry_after_s


class AdmissionTicket:
    """One admitted request's occupancy claim. ``deadline`` is the
    absolute ``time.perf_counter()`` second the batcher must beat (None
    = unbounded); release is idempotent (disconnect paths may race the
    done-callback)."""

    __slots__ = ("priority", "deadline", "t_admit", "released")

    def __init__(self, priority: str, deadline: Optional[float],
                 t_admit: float):
        self.priority = priority
        self.deadline = deadline
        self.t_admit = t_admit
        self.released = False  # flipped under the controller's _lock


class AdmissionController:
    """Per-model admission gate; see the module docstring for policy."""

    def __init__(self, depth: int = DEFAULT_QUEUE_DEPTH,
                 priorities: Sequence[float] = DEFAULT_PRIORITIES,
                 deadline_ms: float = DEFAULT_DEADLINE_MS,
                 service_hint_ms: float = 50.0,
                 name: str = "default"):
        if depth < 1:
            raise ValueError(f"queue depth {depth} must be >= 1")
        self.name = name
        self.depth = depth
        self.default_deadline_ms = deadline_ms
        # water mark per class: occupancy at/above it sheds the class
        self.thresholds: Dict[str, int] = {
            cls: max(1, round(depth * frac))
            for cls, frac in zip(PRIORITY_NAMES, priorities)
        }
        self._lock = OrderedLock("serve.admission")
        self._occupancy = 0  # guarded-by: _lock
        self._admitted = 0  # guarded-by: _lock
        self._shed_queue = 0  # guarded-by: _lock
        self._shed_deadline = 0  # guarded-by: _lock
        # EWMA of observed end-to-end service time; seeded with a hint
        # so feasibility works before the first completion
        self._service_ewma_ms = service_hint_ms  # guarded-by: _lock

    # -- the gate -------------------------------------------------------

    def try_admit(self, priority: str = "normal",
                  deadline_ms: Optional[float] = None) -> AdmissionTicket:
        """Admit one request or raise :class:`AdmissionError` (fast, no
        allocation, no device work). ``deadline_ms`` is the request's
        RELATIVE budget; None falls back to the model's default
        (``DPTPU_SERVE_DEADLINE_MS``); 0/None-default = no deadline."""
        if priority not in self.thresholds:
            raise ValueError(
                f"priority {priority!r} is not one of {PRIORITY_NAMES}"
            )
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        now = time.perf_counter()
        with self._lock:
            est = self._service_ewma_ms
            if deadline_ms and deadline_ms < est:
                self._shed_deadline += 1
                raise AdmissionError(
                    f"deadline {deadline_ms:.0f} ms is below the "
                    f"observed service time (~{est:.0f} ms): infeasible",
                    status=429,
                )
            mark = self.thresholds[priority]
            if self._occupancy >= mark:
                self._shed_queue += 1
                excess = self._occupancy - mark + 1
                retry = max(0.05, excess * est / 1e3)
                raise AdmissionError(
                    f"{self.name}: {self._occupancy} in flight >= "
                    f"{priority} water mark {mark} (depth {self.depth})",
                    status=503, retry_after_s=retry,
                )
            self._occupancy += 1
            self._admitted += 1
        deadline = now + deadline_ms / 1e3 if deadline_ms else None
        return AdmissionTicket(priority, deadline, now)

    def release(self, ticket: AdmissionTicket,
                service_ms: Optional[float] = None) -> None:
        """Return ``ticket``'s occupancy claim; idempotent. Successful
        completions pass their end-to-end latency to keep the
        feasibility EWMA honest."""
        with self._lock:
            if ticket.released:
                return
            ticket.released = True
            self._occupancy -= 1
            if service_ms is not None:
                self._service_ewma_ms += \
                    0.2 * (service_ms - self._service_ewma_ms)

    # -- introspection --------------------------------------------------

    def shedding_hard(self) -> bool:
        """True while even NORMAL-priority traffic is being shed — the
        readiness signal: a fleet router should stop sending here."""
        with self._lock:
            return self._occupancy >= self.thresholds["normal"]

    def stats(self) -> dict:
        with self._lock:
            return {
                "occupancy": self._occupancy,
                "depth": self.depth,
                "admitted": self._admitted,
                "shed_queue": self._shed_queue,
                "shed_deadline": self._shed_deadline,
                "service_ewma_ms": self._service_ewma_ms,
                "thresholds": dict(self.thresholds),
            }
