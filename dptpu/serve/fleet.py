"""Multi-host serve fleet: membership, heartbeat verdicts, failover.

ISSUE 18 tentpole (c). One serving host is one failure domain; the
fleet tier turns N of them into one front:

* **Membership over the quorum KV transport** — each ``dptpu serve``
  host with a fleet dir configured registers a ``serve-host-<id>`` key
  (endpoint + pid, written once) in a :class:`~dptpu.resilience.quorum
  .FileKVStore` directory and then heartbeats ``serve-beat-<id>``
  (timestamp + a load snapshot read from the host's own metrics
  registry) on a dedicated thread — the elastic-training membership
  recipe (``dptpu/resilience/quorum.py``) reused verbatim: atomic
  single-file writes, wall-clock staleness verdicts, no coordinator.

* **Auto-drain on the heartbeat verdict** — the fleet router's poll
  thread re-scans membership every beat period; a member whose last
  beat is older than ``DPTPU_FLEET_DEADLINE_S`` (or who wrote a
  ``draining`` tombstone on clean shutdown) is REMOVED from the route
  table, loudly (stderr + ``Fleet/drains`` counter). A host that
  resumes beating re-enters the table on the next poll — drain is a
  routing verdict, not an expulsion.

* **Zero failed in-flight requests** — a forwarded request whose
  member connection dies (the host was killed mid-request) is retried
  on another healthy member up to ``DPTPU_FLEET_RETRIES`` times; the
  inference POST is idempotent, so failover is safe by construction.
  Together with the drain verdict this is the acceptance property:
  killing a host mid-load costs latency on the requests it was
  holding, never an error surfaced to a client.

* **Admission fronts the whole fleet** — the PR-17
  :class:`~dptpu.serve.admission.AdmissionController` runs in the
  front with fleet-wide water marks: saturation sheds with 503 +
  Retry-After at the door instead of queueing on a dying member.

Lock order: ``serve.fleet`` (rank 12) guards only the route table and
per-member in-flight counts; it never nests with the admission (15) or
engine (20) locks — forwarding happens entirely off-lock.
"""

from __future__ import annotations

import http.client
import json
import os
import socket
import sys
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

from dptpu import obs
from dptpu.serve.admission import AdmissionController, AdmissionError
from dptpu.utils.sync import OrderedLock, StopToken

MEMBER_PREFIX = "serve-host-"
BEAT_PREFIX = "serve-beat-"

# metrics-registry scalars summarized into each beat (the router reads
# load from the member's OWN registry, not from probing it)
_LOAD_KEYS = ("Serve/completed", "Admission/admitted", "Admission/shed")


class FleetUnavailable(AdmissionError):
    """No healthy member can take this request right now."""

    def __init__(self, msg: str):
        super().__init__(msg, status=503, retry_after_s=1.0)


class FleetMember:
    """One serving host's fleet presence: a registration record plus a
    heartbeat thread (``dptpu-serve-fleet-beat``) stamping liveness and
    a load snapshot from this process's metrics registry."""

    def __init__(self, directory: str, *, host: str, port: int,
                 member_id: Optional[str] = None,
                 heartbeat_s: float = 1.0, load_fn=None):
        from dptpu.resilience.quorum import FileKVStore

        self.store = FileKVStore(directory)
        self.member_id = member_id or (
            f"{socket.gethostname()}-{os.getpid()}-{uuid.uuid4().hex[:6]}"
        )
        self.endpoint = (host, int(port))
        self.heartbeat_s = float(heartbeat_s)
        self._load_fn = load_fn or self._registry_load
        self.store.put(MEMBER_PREFIX + self.member_id, json.dumps({
            "host": host, "port": int(port), "pid": os.getpid(),
            "registered_ts": time.time(),
        }))
        self._stop = StopToken()
        self.beat()  # first beat lands BEFORE the router can see us
        self._thread = threading.Thread(
            target=self._beat_loop, name="dptpu-serve-fleet-beat",
            daemon=True,
        )
        self._thread.start()

    @staticmethod
    def _registry_load() -> dict:
        scalars = obs.get_registry().scalars()
        return {k: scalars[k] for k in _LOAD_KEYS if k in scalars}

    def beat(self) -> None:
        payload = {"ts": time.time()}
        try:
            payload["load"] = self._load_fn()
        except Exception:
            payload["load"] = {}  # a broken meter must not stop beats
        self.store.put(BEAT_PREFIX + self.member_id, json.dumps(payload))

    def _beat_loop(self):
        while not self._stop.wait(self.heartbeat_s):
            try:
                self.beat()
            except OSError as e:
                # the KV dir vanishing mid-run: keep trying (the router
                # will drain us on staleness either way) but say so
                print(f"=> fleet member {self.member_id}: heartbeat "
                      f"write failed: {e}", file=sys.stderr, flush=True)

    def close(self, timeout: float = 5.0) -> None:
        """Clean shutdown: stop beating and write the ``draining``
        tombstone so the router drains us on its NEXT poll instead of
        waiting out the staleness deadline."""
        self._stop.stop()
        self._thread.join(timeout)
        try:
            self.store.put(BEAT_PREFIX + self.member_id, json.dumps({
                "ts": time.time(), "draining": True,
            }))
        except OSError:
            pass  # staleness catches what the tombstone cannot


class FleetRouter:
    """The routing tier over the registered members (no local engine).

    Route table maintenance runs on one poll thread
    (``dptpu-serve-fleet``); request forwarding runs on the callers'
    threads, picking the healthy member with the fewest in-flight
    forwards (joined-shortest-queue) and failing over on connection
    death."""

    def __init__(self, directory: str, *, deadline_s: float = 3.0,
                 poll_s: float = 1.0, retries: int = 2,
                 queue_depth: int = 64,
                 priorities=(1.0, 0.85, 0.6), deadline_ms: float = 0.0,
                 http_timeout_s: float = 60.0):
        from dptpu.resilience.quorum import FileKVStore

        self.store = FileKVStore(directory)
        self.deadline_s = float(deadline_s)
        self.retries = int(retries)
        self.http_timeout_s = float(http_timeout_s)
        self.admission = AdmissionController(
            depth=queue_depth, priorities=priorities,
            deadline_ms=deadline_ms, name="fleet",
        )
        self._lock = OrderedLock("serve.fleet")
        self._members: Dict[str, dict] = {}  # guarded-by: _lock
        self._inflight: Dict[str, int] = {}  # guarded-by: _lock
        self._drains = 0  # guarded-by: _lock
        self._stop = StopToken()
        self.poll_s = float(poll_s)
        self._poll_once()  # populate before the first request
        self._thread = threading.Thread(
            target=self._poll_loop, name="dptpu-serve-fleet",
            daemon=True,
        )
        self._thread.start()

    # -- membership -----------------------------------------------------

    def _poll_loop(self):
        while not self._stop.wait(self.poll_s):
            try:
                self._poll_once()
            except Exception as e:
                print(f"=> fleet router: membership poll failed: {e}",
                      file=sys.stderr, flush=True)

    def _poll_once(self):
        regs = self.store.scan(MEMBER_PREFIX)
        beats = self.store.scan(BEAT_PREFIX)
        now = time.time()
        alive: Dict[str, dict] = {}
        for key, raw in regs.items():
            member_id = key[len(MEMBER_PREFIX):]
            try:
                reg = json.loads(raw)
                beat = json.loads(beats.get(BEAT_PREFIX + member_id, "{}"))
            except ValueError:
                continue  # torn JSON cannot happen (atomic put); skip
            if beat.get("draining"):
                continue  # clean-shutdown tombstone
            age = now - float(beat.get("ts", 0.0))
            if age > self.deadline_s:
                continue  # the heartbeat verdict: stale = dead
            alive[member_id] = {
                "host": reg["host"], "port": int(reg["port"]),
                "beat_age_s": age, "load": beat.get("load", {}),
            }
        with self._lock:
            drained = set(self._members) - set(alive)
            joined = set(alive) - set(self._members)
            self._members = alive
            for m in joined:
                self._inflight.setdefault(m, 0)
            self._drains += len(drained)
        reg_counters = obs.get_registry()
        reg_counters.gauge("Fleet/members").set(len(alive))
        for m in drained:
            reg_counters.counter("Fleet/drains").inc()
            print(f"=> fleet DRAINED member {m} (stale heartbeat or "
                  f"tombstone)", file=sys.stderr, flush=True)
        for m in joined:
            print(f"=> fleet joined member {m}", file=sys.stderr,
                  flush=True)

    def members(self) -> Dict[str, dict]:
        with self._lock:
            return {m: dict(v) for m, v in self._members.items()}

    def _pick(self, exclude) -> Optional[Tuple[str, str, int]]:
        """Healthy member with the fewest in-flight forwards, skipping
        ``exclude``; increments its in-flight count (caller releases)."""
        with self._lock:
            candidates = [
                (self._inflight.get(m, 0), m)
                for m in self._members if m not in exclude
            ]
            if not candidates:
                return None
            _, member_id = min(candidates)
            self._inflight[member_id] = \
                self._inflight.get(member_id, 0) + 1
            info = self._members[member_id]
            return member_id, info["host"], info["port"]

    def _release(self, member_id: str) -> None:
        with self._lock:
            if member_id in self._inflight:
                self._inflight[member_id] -= 1

    # -- request path ---------------------------------------------------

    def forward(self, path: str, body: bytes,
                headers: Optional[dict] = None) -> Tuple[int, bytes]:
        """POST ``body`` to a healthy member; fail over on connection
        death up to ``retries`` times. Returns ``(status, body)`` —
        an HTTP-level error status from a member (4xx/5xx) is a real
        ANSWER and is returned, not retried (only transport death is,
        because only transport death is generation-ambiguous for the
        member and idempotent for us)."""
        tried = set()
        last_err: Optional[Exception] = None
        for _ in range(self.retries + 1):
            picked = self._pick(tried)
            if picked is None:
                break
            member_id, host, port = picked
            try:
                conn = http.client.HTTPConnection(
                    host, port, timeout=self.http_timeout_s
                )
                try:
                    conn.request("POST", path, body=body,
                                 headers=headers or {})
                    resp = conn.getresponse()
                    data = resp.read()
                    return resp.status, data
                finally:
                    conn.close()
            except (OSError, http.client.HTTPException) as e:
                last_err = e
                tried.add(member_id)
                obs.get_registry().counter("Fleet/failovers").inc()
                print(f"=> fleet: member {member_id} connection died "
                      f"({e.__class__.__name__}: {e}); failing over",
                      file=sys.stderr, flush=True)
            finally:
                self._release(member_id)
        if last_err is not None:
            raise FleetUnavailable(
                f"no healthy member answered after "
                f"{len(tried)} failover(s): {last_err}"
            )
        raise FleetUnavailable("fleet has no healthy members")

    def submit(self, path: str, body: bytes,
               headers: Optional[dict] = None,
               priority: str = "normal",
               deadline_ms: Optional[float] = None) -> Tuple[int, bytes]:
        """The admitted path: fleet-wide admission gate, then forward.
        Raises :class:`~dptpu.serve.admission.AdmissionError` on shed."""
        ticket = self.admission.try_admit(priority, deadline_ms)
        t0 = time.perf_counter()
        try:
            status, data = self.forward(path, body, headers)
        except BaseException:
            self.admission.release(ticket)
            raise
        self.admission.release(
            ticket,
            service_ms=(time.perf_counter() - t0) * 1e3
            if status == 200 else None,
        )
        return status, data

    # -- health / lifecycle ---------------------------------------------

    def readiness(self) -> Tuple[bool, List[str]]:
        reasons: List[str] = []
        with self._lock:
            n = len(self._members)
        if n == 0:
            reasons.append("fleet: no healthy members")
        if self.admission.shedding_hard():
            reasons.append("fleet: shedding")
        return not reasons, reasons

    def stats(self) -> dict:
        with self._lock:
            return {
                "members": {m: dict(v) for m, v in self._members.items()},
                "inflight": dict(self._inflight),
                "drains": self._drains,
                "admission": self.admission.stats(),
            }

    def close(self, timeout: float = 5.0) -> None:
        self._stop.stop()
        self._thread.join(timeout)


def make_fleet_handler(fleet: FleetRouter):
    """Stdlib handler for the fleet front — the member front's endpoint
    surface (dptpu/serve/http.py) minus per-model detail: /predict
    forwards, /healthz is liveness, /readyz is the fleet verdict,
    /metrics is the front's registry + route table."""
    from http.server import BaseHTTPRequestHandler

    from dptpu.serve.http import DEADLINE_HEADER, PRIORITY_HEADER

    class Handler(BaseHTTPRequestHandler):
        server_version = "dptpu-serve-fleet/1"

        def _send(self, code: int, payload: dict, headers=()):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):
            pass

        def do_GET(self):
            if self.path == "/healthz":
                self._send(200, {"ok": True, "fleet": True,
                                 "members": sorted(fleet.members())})
            elif self.path == "/readyz":
                ready, reasons = fleet.readiness()
                self._send(200 if ready else 503,
                           {"ready": ready, "reasons": reasons})
            elif self.path == "/metrics":
                self._send(200, {
                    "registry": obs.get_registry().scalars(),
                    "fleet": fleet.stats(),
                })
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if not (self.path == "/predict"
                    or self.path.startswith("/predict/")):
                self._send(404, {"error": f"no route {self.path}"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
            except ValueError:
                length = -1
            if not 0 < length <= 64 << 20:
                self._send(400, {"error": "missing or oversized body"})
                return
            headers = {"Content-Type": "application/octet-stream"}
            for h in (PRIORITY_HEADER, DEADLINE_HEADER):
                if self.headers.get(h):
                    headers[h] = self.headers[h]
            try:
                status, data = fleet.submit(
                    self.path, self.rfile.read(length), headers,
                    priority=self.headers.get(PRIORITY_HEADER, "normal"),
                )
            except AdmissionError as e:
                hs = []
                if e.retry_after_s:
                    hs.append(("Retry-After", f"{e.retry_after_s:.3f}"))
                self._send(e.status, {"error": str(e)}, hs)
                return
            except (BrokenPipeError, ConnectionResetError):
                raise
            except Exception as e:
                self._send(500, {"error": str(e)})
                return
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            try:
                self.wfile.write(data)
            except (BrokenPipeError, ConnectionResetError):
                pass

    return Handler


def serve_fleet_forever(fleet: FleetRouter, host: str = "127.0.0.1",
                        port: int = 8000):
    """Blocking fleet-front listener (the ``dptpu serve --fleet``
    loop); Ctrl-C returns, leaving router lifecycle to the caller."""
    from http.server import ThreadingHTTPServer

    httpd = ThreadingHTTPServer((host, port), make_fleet_handler(fleet))
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
    return httpd
