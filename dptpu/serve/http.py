"""Minimal stdlib HTTP front end over the model router (the ``dptpu
serve`` subcommand's listener — no web framework in this environment,
and none needed: the threading server's one-thread-per-connection model
is exactly the batcher's submission model, where the caller's thread
does the request's preprocessing).

Endpoints:

* ``POST /predict`` (default model) / ``POST /predict/<model>`` — body
  = image bytes (any PIL-decodable container); response = JSON
  ``{"top5": [[class_index, logit], ...], "model": m, "generation": g,
  "timings": {...}}``. Undecodable bytes → 400; unknown model → 404.
  Optional headers: ``X-DPTPU-Priority: high|normal|low`` and
  ``X-DPTPU-Deadline-Ms: <float>`` (relative budget). Admission sheds
  with **503** + ``Retry-After`` (saturated) or **429** (infeasible
  deadline); an expired deadline answers **504**.
* ``GET /healthz`` — LIVENESS only: the process is up and the engines
  exist. Always 200 while the server can answer at all.
* ``GET /readyz`` — READINESS: 200 only when every model can take
  normal-priority traffic; 503 with the reasons (draining / shedding /
  mid-rollback) so a fleet router pulls the host without killing
  in-flight work.
* ``GET /metrics`` — the obs registry's flat scalar snapshot plus
  per-model batcher/admission/canary stats.

Client-disconnect hygiene: if the peer drops mid-request the handler
CANCELS the future — a still-coalescing request is evicted, its staging
row is compacted away, and the admission ticket releases via the
done-callback, so a dropped connection can never strand a leased row
(the conftest lease-leak guard polices exactly that).
"""

from __future__ import annotations

import json
import select
import socket
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from dptpu.serve.admission import AdmissionError
from dptpu.serve.batcher import DeadlineExceeded

PRIORITY_HEADER = "X-DPTPU-Priority"
DEADLINE_HEADER = "X-DPTPU-Deadline-Ms"


def make_handler(router):
    class Handler(BaseHTTPRequestHandler):
        server_version = "dptpu-serve/2"

        def _send(self, code: int, payload: dict, headers=()):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # quiet: obs carries telemetry
            pass

        def _peer_gone(self) -> bool:
            """True when the client hung up: the socket is readable and
            a peek returns EOF (pipelined request bytes are NOT EOF)."""
            try:
                r, _, _ = select.select([self.connection], [], [], 0)
                if not r:
                    return False
                return self.connection.recv(1, socket.MSG_PEEK) == b""
            except OSError:
                return True

        def _await(self, fut, timeout: float = 60.0):
            """Wait for the future while WATCHING the socket: a client
            that hangs up mid-wait gets its request CANCELLED — the
            still-coalescing row is evicted instead of riding the batch
            for a reader that no longer exists."""
            t0 = time.monotonic()
            while True:
                try:
                    return fut.result(timeout=0.25)
                except TimeoutError:
                    if time.monotonic() - t0 >= timeout:
                        raise
                    if self._peer_gone():
                        fut.cancel()
                        raise ConnectionResetError(
                            "client disconnected mid-request"
                        )

        def do_GET(self):
            if self.path == "/healthz":
                # liveness: the process is up; per-model identity only
                self._send(200, {
                    "ok": True,
                    "models": {
                        name: {
                            "arch": m.engine.arch,
                            "buckets": list(m.engine.buckets),
                            "placement": m.engine.placement,
                            "generation": m.engine.current_generation,
                        }
                        for name, m in router.models.items()
                    },
                })
            elif self.path == "/readyz":
                ready, reasons = router.readiness()
                self._send(200 if ready else 503,
                           {"ready": ready, "reasons": reasons})
            elif self.path == "/metrics":
                from dptpu import obs

                self._send(200, {
                    "registry": obs.get_registry().scalars(),
                    "models": router.stats(),
                })
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path == "/predict":
                model = None
            elif self.path.startswith("/predict/"):
                model = self.path[len("/predict/"):]
            else:
                self._send(404, {"error": f"no route {self.path}"})
                return
            priority = self.headers.get(PRIORITY_HEADER, "normal")
            raw_deadline = self.headers.get(DEADLINE_HEADER)
            deadline_ms = None
            if raw_deadline is not None:
                try:
                    deadline_ms = float(raw_deadline)
                    if deadline_ms <= 0:
                        raise ValueError
                except ValueError:
                    self._send(400, {
                        "error": f"{DEADLINE_HEADER}={raw_deadline!r} "
                                 f"is not a positive millisecond budget"
                    })
                    return
            try:
                length = int(self.headers.get("Content-Length", 0))
            except ValueError:
                length = -1  # malformed header = a bad request, not a
                #              handler traceback + dropped connection
            if not 0 < length <= 64 << 20:
                self._send(400, {"error": "missing or oversized body"})
                return
            fut = None
            try:
                data = self.rfile.read(length)
                fut = router.submit(
                    data=data, model=model, priority=priority,
                    deadline_ms=deadline_ms,
                )
                logits = self._await(fut)
            except AdmissionError as e:
                headers = []
                if e.retry_after_s:
                    headers.append(
                        ("Retry-After", f"{e.retry_after_s:.3f}")
                    )
                self._send(e.status, {"error": str(e)}, headers)
                return
            except DeadlineExceeded as e:
                self._send(504, {"error": str(e)})
                return
            except KeyError as e:
                self._send(404, {"error": str(e)})
                return
            except ValueError as e:
                self._send(400, {"error": str(e)})
                return
            except TimeoutError as e:
                # backstop: never leave a leased row pinned by a future
                # nobody will wait on again
                fut.cancel()
                self._send(504, {"error": str(e)})
                return
            except (BrokenPipeError, ConnectionResetError):
                # client vanished while we read its body: withdraw the
                # request so its row never reaches a bucket
                if fut is not None:
                    fut.cancel()
                raise  # BaseHTTPRequestHandler closes the connection
            except Exception as e:
                self._send(500, {"error": str(e)})
                return
            top = logits.argsort()[::-1][:5]
            try:
                self._send(200, {
                    "top5": [[int(i), float(logits[i])] for i in top],
                    "model": model if model is not None else router.default,
                    "generation": fut.generation,
                    "timings": {
                        k: round(v, 3) if isinstance(v, float) else v
                        for k, v in fut.timings.items()
                    },
                })
            except (BrokenPipeError, ConnectionResetError):
                pass  # answered into a closed socket; work already done

    return Handler


def serve_forever(router, host: str = "127.0.0.1", port: int = 8000):
    """Blocking listener; Ctrl-C (or ``shutdown()`` from another thread)
    returns, leaving router lifecycle to the caller."""
    httpd = ThreadingHTTPServer((host, port), make_handler(router))
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
    return httpd
