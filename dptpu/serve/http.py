"""Minimal stdlib HTTP front end over the batcher (the ``dptpu serve``
subcommand's listener — no web framework in this environment, and none
needed: the threading server's one-thread-per-connection model is
exactly the batcher's submission model, where the caller's thread does
the request's preprocessing).

Endpoints:

* ``POST /predict`` — body = image bytes (any PIL-decodable container);
  response = JSON ``{"top5": [[class_index, logit], ...],
  "generation": g, "timings": {...}}``. Undecodable bytes → 400.
* ``GET /healthz`` — liveness + the engine's arch/bucket ladder.
* ``GET /metrics`` — the obs registry's flat scalar snapshot plus the
  batcher's aggregate stats (``Serve/*`` group included).
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def make_handler(batcher):
    engine = batcher.engine

    class Handler(BaseHTTPRequestHandler):
        server_version = "dptpu-serve/1"

        def _send(self, code: int, payload: dict):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # quiet: obs carries telemetry
            pass

        def do_GET(self):
            if self.path == "/healthz":
                self._send(200, {
                    "ok": True, "arch": engine.arch,
                    "buckets": list(engine.buckets),
                    "placement": engine.placement,
                    "generation": engine.current_generation,
                })
            elif self.path == "/metrics":
                from dptpu import obs

                self._send(200, {
                    "registry": obs.get_registry().scalars(),
                    "serve": batcher.stats(reset_window=False),
                })
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path != "/predict":
                self._send(404, {"error": f"no route {self.path}"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
            except ValueError:
                length = -1  # malformed header = a bad request, not a
                #              handler traceback + dropped connection
            if not 0 < length <= 64 << 20:
                self._send(400, {"error": "missing or oversized body"})
                return
            data = self.rfile.read(length)
            try:
                fut = batcher.submit_bytes(data)
                logits = fut.result(timeout=60.0)
            except ValueError as e:
                self._send(400, {"error": str(e)})
                return
            except Exception as e:
                self._send(500, {"error": str(e)})
                return
            top = logits.argsort()[::-1][:5]
            self._send(200, {
                "top5": [[int(i), float(logits[i])] for i in top],
                "generation": fut.generation,
                "timings": {k: round(v, 3) if isinstance(v, float) else v
                            for k, v in fut.timings.items()},
            })

    return Handler


def serve_forever(batcher, host: str = "127.0.0.1", port: int = 8000):
    """Blocking listener; Ctrl-C (or ``shutdown()`` from another thread)
    returns, leaving batcher lifecycle to the caller."""
    httpd = ThreadingHTTPServer((host, port), make_handler(batcher))
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
    return httpd
