"""Canary rollout with drift/latency gating and loud auto-rollback.

ISSUE 17 tentpole (d). A weight push to a serving fleet is the moment
most likely to break it, and the failure mode is silent: the new
checkpoint loads fine, serves fine, and returns confidently wrong
logits. This controller turns the engine's generation machinery into a
gated rollout:

* ``start(variables)`` STAGES generation N+1 (:meth:`stage_weights` —
  resident and pinnable, but NOT current; default traffic keeps hitting
  gen N untouched).

* The batcher asks :meth:`pick_generation` per batch; a
  ``DPTPU_SERVE_CANARY_FRACTION`` slice of batches pins the canary
  generation. The pin is taken INSIDE the canary lock so a concurrent
  rollback can never hand out a generation it just discarded.

* Every canary batch is SHADOW-EVALUATED: the batcher snapshots the
  input rows before the staging lease recycles them, and the evaluator
  thread (``dptpu-serve-canary``) replays them through the BASELINE
  generation. ``max|Δlogit|`` above ``DPTPU_SERVE_CANARY_DRIFT`` means
  the new weights disagree with the old beyond numerical noise —
  **auto-rollback**. A canary batch-latency EWMA above
  ``DPTPU_SERVE_CANARY_LAT_FACTOR`` × baseline rolls back too.

* **Quantized rollouts** (ISSUE 18): ``start_quantized`` stages an
  int8/bf16 generation through the engine's artifact-verified front
  door and ARMS the gate with the artifact's own bounds — per-rollout
  ``max|Δlogit|`` AND a cumulative **top-1 agreement** floor over the
  shadow-evaluated rows (quantization error that flips argmax is a
  serving regression even when every |Δlogit| is individually small).
  Both verdicts are loud; a drifting quantized generation rolls back
  exactly like a bad weight push, never silently.

* Rollback is LOUD (stderr + ``Serve/canary_rollbacks`` counter) and
  clean: :meth:`discard_staged` drops the stager's pin, in-flight
  canary batches drain on their pinned generation (the mixed-generation
  -impossible property ``swap_weights`` already guarantees), and no
  response is ever computed from a half-installed state.

* After ``min_batches`` clean shadow evals the canary PROMOTES
  (:meth:`promote` makes it current; gen N drains away).

The injected ``canary_drift`` fault (``DPTPU_FAULT=canary_drift``)
perturbs the staged weights at ``start`` so SERVEBENCH can prove the
gate fires; the perturbation lives HERE (jax-side) to keep
``dptpu.resilience.faults`` stdlib-only.

Lock order: ``serve.canary`` (rank 18) sits between admission (15) and
the engine (20) — pick/rollback/promote call into the engine while
holding the canary lock.
"""

from __future__ import annotations

import sys
import time
import queue
import threading
from typing import Optional

import numpy as np

from dptpu import obs
from dptpu.utils.sync import OrderedLock


class CanaryController:
    """Gated rollout of one staged generation on one engine."""

    def __init__(self, engine, *, fraction: float = 0.1,
                 drift_limit: float = 50.0, lat_factor: float = 5.0,
                 min_batches: int = 8, min_top1_agreement: float = 0.0,
                 fault_plan=None):
        if not 0.0 < fraction < 1.0:
            raise ValueError(
                f"canary fraction {fraction} must be in (0, 1)"
            )
        if not 0.0 <= min_top1_agreement <= 1.0:
            raise ValueError(
                f"min_top1_agreement {min_top1_agreement} must be in "
                f"[0, 1]"
            )
        self.engine = engine
        self.fraction = fraction
        self.drift_limit = drift_limit
        self.lat_factor = lat_factor
        self.min_batches = min_batches
        self.min_top1_agreement = min_top1_agreement
        self._plan = fault_plan
        self._lock = OrderedLock("serve.canary")
        self._state = "idle"  # guarded-by: _lock
        self._canary_gen: Optional[int] = None  # guarded-by: _lock
        self._base_gen: Optional[int] = None  # guarded-by: _lock
        self._accum = 0.0  # guarded-by: _lock
        self._canary_ms = 0.0  # guarded-by: _lock
        self._base_ms = 0.0  # guarded-by: _lock
        self._canary_batches = 0  # guarded-by: _lock
        self._base_batches = 0  # guarded-by: _lock
        self._clean_evals = 0  # guarded-by: _lock
        self._max_drift = 0.0  # guarded-by: _lock
        self._rollbacks = 0  # guarded-by: _lock
        self._rollback_reason = ""  # guarded-by: _lock
        # per-rollout gate bounds (quantized rollouts arm these from
        # the calibration artifact; start() uses the constructor's)
        self._active_drift = drift_limit  # guarded-by: _lock
        self._active_top1 = min_top1_agreement  # guarded-by: _lock
        self._agree_rows = 0  # guarded-by: _lock
        self._total_rows = 0  # guarded-by: _lock
        self._q: queue.Queue = queue.Queue()
        self._eval_thread = threading.Thread(
            target=self._eval_loop, name="dptpu-serve-canary",
            daemon=True,
        )
        self._eval_thread.start()

    # -- rollout lifecycle ----------------------------------------------

    def start(self, variables) -> int:
        """Stage ``variables`` as the canary generation and begin
        routing a traffic fraction at it. Returns the staged id."""
        if self._plan is not None and self._plan.canary_drift_armed():
            # injected drift: shift every parameter so the shadow eval
            # MUST trip the gate (the fault-injection proof)
            import jax
            variables = dict(variables)
            variables["params"] = jax.tree_util.tree_map(
                lambda p: p + 3.0, variables["params"]
            )
        base = self.engine.current_generation
        gen = self.engine.stage_weights(variables)
        self._begin(gen, base, self.drift_limit, self.min_top1_agreement)
        return gen

    def start_quantized(self, calibration: str, precision: str = "int8",
                        drift_limit: Optional[float] = None,
                        top1_min: Optional[float] = None) -> int:
        """Stage a QUANTIZED canary through the engine's
        artifact-verified front door and arm the gate with the
        artifact's bounds (``meta["bounds"]``: ``max_abs_dlogit``,
        ``min_top1_agreement`` — stated at calibration time, enforced
        online here). Explicit ``drift_limit``/``top1_min`` (the
        ``DPTPU_QUANT_DRIFT``/``DPTPU_QUANT_TOP1_MIN`` operator
        overrides) win over the artifact. Returns the staged id."""
        gen, meta = self.engine.stage_quantized(
            calibration, precision=precision
        )
        bounds = meta.get("bounds", {})
        if drift_limit is None:
            drift_limit = float(
                bounds.get("max_abs_dlogit", self.drift_limit)
            )
        if top1_min is None:
            top1_min = float(
                bounds.get("min_top1_agreement",
                           self.min_top1_agreement)
            )
        self._begin(gen, self.engine.current_generation,
                    float(drift_limit), float(top1_min))
        return gen

    def _begin(self, gen: int, base: int, drift_limit: float,
               top1_min: float) -> None:
        with self._lock:
            if self._state == "canary":
                # a rollout is already live: discard the new stage
                self.engine.discard_staged(gen)
                raise RuntimeError(
                    "a canary rollout is already in progress"
                )
            self._state = "canary"
            self._canary_gen = gen
            self._base_gen = base
            self._accum = 0.0
            self._canary_ms = 0.0
            self._base_ms = 0.0
            self._canary_batches = 0
            self._base_batches = 0
            self._clean_evals = 0
            self._max_drift = 0.0
            self._rollback_reason = ""
            self._active_drift = drift_limit
            self._active_top1 = top1_min
            self._agree_rows = 0
            self._total_rows = 0

    def pick_generation(self) -> int:
        """Choose + PIN the generation for one batch (the batcher calls
        this instead of ``engine.acquire_generation()``). The engine pin
        happens inside the canary lock so the chosen generation cannot
        be discarded between the decision and the pin."""
        with self._lock:
            if self._state != "canary":
                return self.engine.acquire_generation()
            self._accum += self.fraction
            if self._accum >= 1.0:
                self._accum -= 1.0
                return self.engine.acquire_generation(self._canary_gen)
            return self.engine.acquire_generation(self._base_gen)

    def wants_shadow(self, gen: int) -> bool:
        """True when a batch pinned to ``gen`` must snapshot its input
        rows for baseline replay (canary batches only)."""
        with self._lock:
            return self._state == "canary" and gen == self._canary_gen

    def observe(self, gen: int, bucket: int, n: int, device_ms: float,
                shadow, logits) -> None:
        """Batcher callback after every completed batch: feeds the
        latency gate and enqueues canary batches for shadow eval."""
        with self._lock:
            if self._state != "canary":
                return
            if gen == self._base_gen:
                self._base_batches += 1
                self._base_ms += 0.3 * (device_ms - self._base_ms) \
                    if self._base_batches > 1 else device_ms
                return
            if gen != self._canary_gen:
                return
            self._canary_batches += 1
            self._canary_ms += 0.3 * (device_ms - self._canary_ms) \
                if self._canary_batches > 1 else device_ms
            if (self._canary_batches >= 3 and self._base_batches >= 3
                    and self._canary_ms >
                    self.lat_factor * self._base_ms):
                self._rollback_locked(
                    f"canary batch latency {self._canary_ms:.1f} ms > "
                    f"{self.lat_factor}x baseline {self._base_ms:.1f} ms"
                )
                return
            if shadow is not None:
                self._q.put((gen, bucket, n, shadow, np.array(logits)))

    # -- shadow evaluation ----------------------------------------------

    def _eval_loop(self):
        while True:
            job = self._q.get()
            try:
                if job is None:
                    return
                self._eval_one(*job)
            except Exception as e:
                # the evaluator must survive a bad job: a dead evaluator
                # silently disables the drift gate
                print(f"=> serve canary shadow eval failed: {e}",
                      file=sys.stderr, flush=True)
            finally:
                self._q.task_done()

    def _eval_one(self, gen, bucket, n, shadow, canary_logits):
        with self._lock:
            if self._state != "canary" or gen != self._canary_gen:
                return
            base_gen = self._base_gen
        try:
            pin = self.engine.acquire_generation(base_gen)
        except KeyError:
            return  # baseline drained (promotion landed)
        try:
            base_logits = self.engine.run_bucket(
                bucket, shadow, n, gen=pin
            )
        finally:
            self.engine.release_generation(pin)
        drift = float(np.max(np.abs(
            base_logits[:n] - canary_logits[:n]
        )))
        agree = int(np.sum(
            base_logits[:n].argmax(-1) == canary_logits[:n].argmax(-1)
        ))
        with self._lock:
            if self._state != "canary" or gen != self._canary_gen:
                return
            if drift > self._max_drift:
                self._max_drift = drift
            self._agree_rows += agree
            self._total_rows += n
            if drift > self._active_drift:
                self._rollback_locked(
                    f"logit drift {drift:.3g} > limit "
                    f"{self._active_drift:.3g}"
                )
                return
            # top-1 agreement is CUMULATIVE over shadow-evaled rows (a
            # single flipped row in a tiny batch is sampling noise; a
            # persistent deficit is drift) — judged once enough rows
            # accumulated, and again at promotion time
            if (self._active_top1 > 0.0
                    and self._total_rows >= self.min_batches
                    and self._agree_rows
                    < self._active_top1 * self._total_rows):
                self._rollback_locked(
                    f"top-1 agreement "
                    f"{self._agree_rows / self._total_rows:.3f} "
                    f"({self._agree_rows}/{self._total_rows} rows) < "
                    f"floor {self._active_top1:.3f}"
                )
                return
            self._clean_evals += 1
            self._maybe_promote_locked()

    # -- verdicts (call with _lock held) --------------------------------

    def _rollback_locked(self, reason: str):
        gen = self._canary_gen
        self._state = "rolled_back"
        self._rollbacks += 1
        self._rollback_reason = reason
        print(
            f"=> serve canary ROLLED BACK (gen {gen}): {reason}",
            file=sys.stderr, flush=True,
        )
        obs.get_registry().counter("Serve/canary_rollbacks").inc()
        # drop the stager's pin: in-flight canary batches drain on their
        # own pins, then the generation's buffers free (18 -> 20 nests)
        self.engine.discard_staged(gen)

    def _maybe_promote_locked(self):
        if (self._active_top1 > 0.0 and self._total_rows > 0
                and self._agree_rows
                < self._active_top1 * self._total_rows):
            return  # agreement deficit: never promote past the floor
        if (self._clean_evals >= self.min_batches
                and self._canary_batches >= self.min_batches):
            self.engine.promote(self._canary_gen)
            self._state = "promoted"
            print(
                f"=> serve canary PROMOTED (gen {self._canary_gen}): "
                f"{self._clean_evals} clean shadow evals, max drift "
                f"{self._max_drift:.3g}",
                file=sys.stderr, flush=True,
            )

    # -- introspection / lifecycle --------------------------------------

    @property
    def rolling_back(self) -> bool:
        """True during the rollback WINDOW: the verdict landed but
        canary-pinned batches are still draining (the staged generation
        is still resident). Readiness goes false here — a fleet router
        must not route to a host mid-rollback."""
        with self._lock:
            if self._state != "rolled_back":
                return False
            return self._canary_gen in self.engine.generations()

    def status(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "canary_gen": self._canary_gen,
                "base_gen": self._base_gen,
                "fraction": self.fraction,
                "canary_batches": self._canary_batches,
                "base_batches": self._base_batches,
                "clean_evals": self._clean_evals,
                "max_drift": self._max_drift,
                "drift_limit": self._active_drift,
                "top1_floor": self._active_top1,
                "top1_agreement": (
                    self._agree_rows / self._total_rows
                    if self._total_rows else None
                ),
                "shadow_rows": self._total_rows,
                "canary_ms": self._canary_ms,
                "base_ms": self._base_ms,
                "rollbacks": self._rollbacks,
                "rollback_reason": self._rollback_reason,
                "pending_evals": self._q.qsize(),
            }

    def drain_evals(self, timeout: float = 10.0) -> None:
        """Block until every enqueued shadow eval has been PROCESSED
        (tests and the bench use this to make verdicts deterministic)."""
        t0 = time.perf_counter()
        while self._q.unfinished_tasks:
            if time.perf_counter() - t0 > timeout:
                raise TimeoutError("shadow evals still pending")
            time.sleep(0.005)

    def close(self, timeout: float = 10.0) -> None:
        self._q.put(None)  # sentinel: wakes the evaluator to exit
        self._eval_thread.join(timeout)
