"""Serve-side env knobs — the locked fail-fast contract, serving edition.

Same discipline as the feed/opt/obs knobs (dptpu/envknob.py): an unset
or empty knob means "use the default / the CLI value", every EXPLICIT
value must parse and validate or raise an actionable error, and the env
twin WINS over the CLI/config value when both are set (the precedence
every ``DPTPU_*`` knob in this repo follows — benches and tests drive
fit()/serve() through env without forking argv plumbing).

Knobs:

* ``DPTPU_SERVE_BUCKETS`` — comma list of AOT-compiled batch-size
  buckets (default ``1,4,16,64``); each positive, strictly increasing;
* ``DPTPU_SERVE_MAX_DELAY_MS`` — the batcher's coalescing latency
  budget (default 5.0; ``0`` = dispatch immediately, never wait);
* ``DPTPU_SERVE_PLACEMENT`` — ``auto`` / ``replicated`` / ``tp``
  (auto: TP for the families with a real TP rule when >1 device,
  replicated otherwise — dptpu/parallel/gspmd.py ``tp_rule_for_arch``);
* ``DPTPU_SERVE_SLOTS`` — staging-ring depth in leased batch slots
  (default 4, >= 2: one filling + one in flight).

Stdlib-only: the CLI validates pre-jax (a typo'd knob must fail before
any compile), and the conftest leak guard imports the serve package.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple

from dptpu.envknob import env_choice, env_float, env_int, env_str

DEFAULT_BUCKETS: Tuple[int, ...] = (1, 4, 16, 64)
DEFAULT_MAX_DELAY_MS = 5.0
DEFAULT_SLOTS = 4

PLACEMENTS = ("auto", "replicated", "tp")


class ServeKnobs(NamedTuple):
    buckets: Tuple[int, ...]
    max_delay_ms: float
    placement: str
    slots: int


def parse_buckets(raw, source: str = "DPTPU_SERVE_BUCKETS"
                  ) -> Tuple[int, ...]:
    """Validate a bucket ladder (comma string or int sequence): every
    bucket a positive int, strictly increasing — an unsorted or
    duplicated ladder would make "smallest bucket >= n" ambiguous, so it
    raises instead of silently sorting."""
    if isinstance(raw, str):
        parts = [p.strip() for p in raw.split(",") if p.strip()]
        if not parts:
            raise ValueError(
                f"{source}={raw!r} names no buckets (expected e.g. "
                f"{source}=1,4,16,64)"
            )
        try:
            buckets = tuple(int(p) for p in parts)
        except ValueError:
            raise ValueError(
                f"{source}={raw!r} is not a comma list of integers "
                f"(expected e.g. {source}=1,4,16,64)"
            ) from None
    else:
        buckets = tuple(int(b) for b in raw)
        if not buckets:
            raise ValueError(f"{source}: empty bucket ladder")
    if any(b < 1 for b in buckets):
        raise ValueError(
            f"{source}={','.join(map(str, buckets))}: every bucket must "
            f"be a positive batch size"
        )
    if any(a >= b for a, b in zip(buckets, buckets[1:])):
        raise ValueError(
            f"{source}={','.join(map(str, buckets))}: buckets must be "
            f"strictly increasing (the batcher picks the smallest bucket "
            f">= the coalesced request count)"
        )
    return buckets


def serve_knobs(buckets: Optional[Sequence[int]] = None,
                max_delay_ms: Optional[float] = None,
                placement: Optional[str] = None,
                slots: Optional[int] = None,
                environ=None) -> ServeKnobs:
    """Resolve + validate the serve knobs. Arguments are the CLI/config
    values (None = not given); the env twins override them when set; the
    IDENTICAL validation applies either way (a programmatic caller's bad
    ladder fails exactly like a typo'd env)."""
    import os

    env = environ if environ is not None else os.environ
    raw_buckets = env_str("DPTPU_SERVE_BUCKETS", "", environ=env)
    if raw_buckets:
        out_buckets = parse_buckets(raw_buckets)
    elif buckets is not None:
        out_buckets = parse_buckets(buckets, source="--buckets")
    else:
        out_buckets = DEFAULT_BUCKETS

    delay = env_float("DPTPU_SERVE_MAX_DELAY_MS", None, environ=env)
    source = "DPTPU_SERVE_MAX_DELAY_MS"
    if delay is None:
        delay, source = max_delay_ms, "--max-delay-ms"
    if delay is None:
        delay = DEFAULT_MAX_DELAY_MS
    if delay < 0:
        raise ValueError(
            f"{source}={delay} must be >= 0 ms (0 dispatches every "
            f"request immediately, never coalescing)"
        )

    place = env_choice("DPTPU_SERVE_PLACEMENT", PLACEMENTS, None,
                       environ=env)
    if place is None:
        place = placement if placement is not None else "auto"
    if place not in PLACEMENTS:
        raise ValueError(
            f"--placement={place!r} must be one of "
            + "/".join(repr(p) for p in PLACEMENTS)
        )

    n_slots = env_int("DPTPU_SERVE_SLOTS", None, environ=env)
    source = "DPTPU_SERVE_SLOTS"
    if n_slots is None:
        n_slots, source = slots, "--slots"
    if n_slots is None:
        n_slots = DEFAULT_SLOTS
    if n_slots < 2:
        raise ValueError(
            f"{source}={n_slots} must be >= 2 staging slots (one "
            f"filling while one is leased to the device)"
        )
    return ServeKnobs(out_buckets, float(delay), place, int(n_slots))
