"""Serve-side env knobs — the locked fail-fast contract, serving edition.

Same discipline as the feed/opt/obs knobs (dptpu/envknob.py): an unset
or empty knob means "use the default / the CLI value", every EXPLICIT
value must parse and validate or raise an actionable error, and the env
twin WINS over the CLI/config value when both are set (the precedence
every ``DPTPU_*`` knob in this repo follows — benches and tests drive
fit()/serve() through env without forking argv plumbing).

Knobs:

* ``DPTPU_SERVE_BUCKETS`` — comma list of AOT-compiled batch-size
  buckets (default ``1,4,16,64``); each positive, strictly increasing;
* ``DPTPU_SERVE_MAX_DELAY_MS`` — the batcher's coalescing latency
  budget (default 5.0; ``0`` = dispatch immediately, never wait);
* ``DPTPU_SERVE_PLACEMENT`` — ``auto`` / ``replicated`` / ``tp``
  (auto: TP for the families with a real TP rule when >1 device,
  replicated otherwise — dptpu/parallel/gspmd.py ``tp_rule_for_arch``);
* ``DPTPU_SERVE_SLOTS`` — staging-ring depth in leased batch slots
  (default 4, >= 2: one filling + one in flight).

Admission / robustness knobs (ISSUE 17):

* ``DPTPU_SERVE_QUEUE_DEPTH`` — per-model admission bound: requests
  admitted-but-unanswered beyond this are SHED with a fast 503 +
  Retry-After instead of queueing (default 64, >= 1);
* ``DPTPU_SERVE_PRIORITIES`` — shed thresholds for the three priority
  classes (high,normal,low) as fractions of the queue depth, comma
  list, each in (0, 1], non-increasing (high sheds LAST; default
  ``1.0,0.85,0.6``);
* ``DPTPU_SERVE_DEADLINE_MS`` — default per-request deadline applied
  when a request names none (default 0 = no server-imposed deadline;
  an expired request is evicted pre-dispatch and answered 504);
* ``DPTPU_SERVE_CANARY_FRACTION`` — fraction of BATCHES routed to a
  staged canary generation while a rollout is active (default 0.1,
  in (0, 1) — batch-granular so one-generation-per-batch holds);
* ``DPTPU_SERVE_CANARY_DRIFT`` — canary logit-drift gate: max|Δlogit|
  vs the baseline generation on the same inputs above this triggers
  auto-rollback (default 50.0, > 0 — catastrophic-weights scale, not
  a retraining-noise scale);
* ``DPTPU_SERVE_CANARY_LAT_FACTOR`` — canary latency gate: canary
  batch device time above ``factor x`` the baseline's triggers
  auto-rollback (default 5.0, > 1).

Quantized-serving knobs (ISSUE 18, ``DPTPU_QUANT_*``):

* ``DPTPU_QUANT_PRECISION`` — ``fp32`` / ``bf16`` / ``int8``: the
  precision a quantized generation is deployed at (default fp32 = no
  quantized rollout). Anything below fp32 REQUIRES a calibration
  artifact and rides the canary gate — never a silent cutover;
* ``DPTPU_QUANT_CALIB`` — path to the CRC-sealed calibration artifact
  (``dptpu quantize`` output). Required when the precision knob is
  below fp32; verified (CRC + arch + weights fingerprint) at load;
* ``DPTPU_QUANT_DRIFT`` — operator override of the quantized rollout's
  max|Δlogit| gate (default 0 = use the bound stated in the artifact);
* ``DPTPU_QUANT_TOP1_MIN`` — operator override of the quantized
  rollout's cumulative top-1 agreement floor (default 0 = use the
  artifact's bound), in (0, 1].

Fleet knobs (ISSUE 18, ``DPTPU_FLEET_*``):

* ``DPTPU_FLEET_DIR`` — the shared quorum-KV directory fleet members
  register in and the fleet router scans (required for ``--fleet`` /
  member registration; empty = fleet disabled);
* ``DPTPU_FLEET_HEARTBEAT_S`` — member heartbeat period (default 1.0,
  > 0);
* ``DPTPU_FLEET_DEADLINE_S`` — the staleness verdict: a member whose
  last beat is older than this is auto-DRAINED from routing (default
  3.0; must exceed the heartbeat period or every member flaps);
* ``DPTPU_FLEET_RETRIES`` — per-request failover budget: a request
  whose member connection dies is retried on another healthy member
  this many times before the client sees an error (default 2, >= 0 —
  the zero-failed-in-flight-requests lever during a drain).

Stdlib-only: the CLI validates pre-jax (a typo'd knob must fail before
any compile), and the conftest leak guard imports the serve package.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple

from dptpu.envknob import env_choice, env_float, env_int, env_str

DEFAULT_BUCKETS: Tuple[int, ...] = (1, 4, 16, 64)
DEFAULT_MAX_DELAY_MS = 5.0
DEFAULT_SLOTS = 4
DEFAULT_QUEUE_DEPTH = 64
DEFAULT_PRIORITIES: Tuple[float, ...] = (1.0, 0.85, 0.6)
DEFAULT_DEADLINE_MS = 0.0  # 0 = no server-imposed default deadline
DEFAULT_CANARY_FRACTION = 0.1
DEFAULT_CANARY_DRIFT = 50.0
DEFAULT_CANARY_LAT_FACTOR = 5.0
DEFAULT_PRECISION = "fp32"
DEFAULT_QUANT_DRIFT = 0.0  # 0 = the calibration artifact's bound
DEFAULT_QUANT_TOP1_MIN = 0.0  # 0 = the calibration artifact's bound
DEFAULT_FLEET_HEARTBEAT_S = 1.0
DEFAULT_FLEET_DEADLINE_S = 3.0
DEFAULT_FLEET_RETRIES = 2

PLACEMENTS = ("auto", "replicated", "tp")
PRIORITY_NAMES = ("high", "normal", "low")
PRECISIONS = ("fp32", "bf16", "int8")


class ServeKnobs(NamedTuple):
    buckets: Tuple[int, ...]
    max_delay_ms: float
    placement: str
    slots: int
    queue_depth: int
    priorities: Tuple[float, ...]
    deadline_ms: float
    canary_fraction: float
    canary_drift: float
    canary_lat_factor: float
    precision: str = DEFAULT_PRECISION
    calib: str = ""
    quant_drift: float = DEFAULT_QUANT_DRIFT
    quant_top1_min: float = DEFAULT_QUANT_TOP1_MIN
    fleet_dir: str = ""
    fleet_heartbeat_s: float = DEFAULT_FLEET_HEARTBEAT_S
    fleet_deadline_s: float = DEFAULT_FLEET_DEADLINE_S
    fleet_retries: int = DEFAULT_FLEET_RETRIES


def parse_buckets(raw, source: str = "DPTPU_SERVE_BUCKETS"
                  ) -> Tuple[int, ...]:
    """Validate a bucket ladder (comma string or int sequence): every
    bucket a positive int, strictly increasing — an unsorted or
    duplicated ladder would make "smallest bucket >= n" ambiguous, so it
    raises instead of silently sorting."""
    if isinstance(raw, str):
        parts = [p.strip() for p in raw.split(",") if p.strip()]
        if not parts:
            raise ValueError(
                f"{source}={raw!r} names no buckets (expected e.g. "
                f"{source}=1,4,16,64)"
            )
        try:
            buckets = tuple(int(p) for p in parts)
        except ValueError:
            raise ValueError(
                f"{source}={raw!r} is not a comma list of integers "
                f"(expected e.g. {source}=1,4,16,64)"
            ) from None
    else:
        buckets = tuple(int(b) for b in raw)
        if not buckets:
            raise ValueError(f"{source}: empty bucket ladder")
    if any(b < 1 for b in buckets):
        raise ValueError(
            f"{source}={','.join(map(str, buckets))}: every bucket must "
            f"be a positive batch size"
        )
    if any(a >= b for a, b in zip(buckets, buckets[1:])):
        raise ValueError(
            f"{source}={','.join(map(str, buckets))}: buckets must be "
            f"strictly increasing (the batcher picks the smallest bucket "
            f">= the coalesced request count)"
        )
    return buckets


def parse_priorities(raw, source: str = "DPTPU_SERVE_PRIORITIES"
                     ) -> Tuple[float, ...]:
    """Validate the priority shed thresholds (comma string or float
    sequence): one fraction of the queue depth per class
    (high, normal, low), each in (0, 1], non-increasing — high priority
    must shed LAST, so an increasing ladder is a config bug, not a
    creative policy."""
    if isinstance(raw, str):
        parts = [p.strip() for p in raw.split(",") if p.strip()]
        try:
            fracs = tuple(float(p) for p in parts)
        except ValueError:
            raise ValueError(
                f"{source}={raw!r} is not a comma list of fractions "
                f"(expected e.g. {source}=1.0,0.85,0.6)"
            ) from None
    else:
        fracs = tuple(float(f) for f in raw)
    if len(fracs) != len(PRIORITY_NAMES):
        raise ValueError(
            f"{source}={raw!r} needs exactly {len(PRIORITY_NAMES)} "
            f"thresholds, one per priority class "
            f"({','.join(PRIORITY_NAMES)})"
        )
    if any(not 0.0 < f <= 1.0 for f in fracs):
        raise ValueError(
            f"{source}={','.join(map(str, fracs))}: every threshold "
            f"must be a fraction of the queue depth in (0, 1]"
        )
    if any(a < b for a, b in zip(fracs, fracs[1:])):
        raise ValueError(
            f"{source}={','.join(map(str, fracs))}: thresholds must be "
            f"non-increasing from high to low (high priority sheds "
            f"last, so its threshold is the largest)"
        )
    return fracs


def serve_knobs(buckets: Optional[Sequence[int]] = None,
                max_delay_ms: Optional[float] = None,
                placement: Optional[str] = None,
                slots: Optional[int] = None,
                queue_depth: Optional[int] = None,
                priorities: Optional[Sequence[float]] = None,
                deadline_ms: Optional[float] = None,
                canary_fraction: Optional[float] = None,
                canary_drift: Optional[float] = None,
                canary_lat_factor: Optional[float] = None,
                precision: Optional[str] = None,
                calib: Optional[str] = None,
                quant_drift: Optional[float] = None,
                quant_top1_min: Optional[float] = None,
                fleet_dir: Optional[str] = None,
                fleet_heartbeat_s: Optional[float] = None,
                fleet_deadline_s: Optional[float] = None,
                fleet_retries: Optional[int] = None,
                environ=None) -> ServeKnobs:
    """Resolve + validate the serve knobs. Arguments are the CLI/config
    values (None = not given); the env twins override them when set; the
    IDENTICAL validation applies either way (a programmatic caller's bad
    ladder fails exactly like a typo'd env)."""
    import os

    env = environ if environ is not None else os.environ
    raw_buckets = env_str("DPTPU_SERVE_BUCKETS", "", environ=env)
    if raw_buckets:
        out_buckets = parse_buckets(raw_buckets)
    elif buckets is not None:
        out_buckets = parse_buckets(buckets, source="--buckets")
    else:
        out_buckets = DEFAULT_BUCKETS

    delay = env_float("DPTPU_SERVE_MAX_DELAY_MS", None, environ=env)
    source = "DPTPU_SERVE_MAX_DELAY_MS"
    if delay is None:
        delay, source = max_delay_ms, "--max-delay-ms"
    if delay is None:
        delay = DEFAULT_MAX_DELAY_MS
    if delay < 0:
        raise ValueError(
            f"{source}={delay} must be >= 0 ms (0 dispatches every "
            f"request immediately, never coalescing)"
        )

    place = env_choice("DPTPU_SERVE_PLACEMENT", PLACEMENTS, None,
                       environ=env)
    if place is None:
        place = placement if placement is not None else "auto"
    if place not in PLACEMENTS:
        raise ValueError(
            f"--placement={place!r} must be one of "
            + "/".join(repr(p) for p in PLACEMENTS)
        )

    n_slots = env_int("DPTPU_SERVE_SLOTS", None, environ=env)
    source = "DPTPU_SERVE_SLOTS"
    if n_slots is None:
        n_slots, source = slots, "--slots"
    if n_slots is None:
        n_slots = DEFAULT_SLOTS
    if n_slots < 2:
        raise ValueError(
            f"{source}={n_slots} must be >= 2 staging slots (one "
            f"filling while one is leased to the device)"
        )

    depth = env_int("DPTPU_SERVE_QUEUE_DEPTH", None, environ=env)
    source = "DPTPU_SERVE_QUEUE_DEPTH"
    if depth is None:
        depth, source = queue_depth, "--queue-depth"
    if depth is None:
        depth = DEFAULT_QUEUE_DEPTH
    if depth < 1:
        raise ValueError(
            f"{source}={depth} must be >= 1 admitted-but-unanswered "
            f"request (the bound past which admission sheds with "
            f"503 + Retry-After instead of queueing)"
        )

    raw_prios = env_str("DPTPU_SERVE_PRIORITIES", "", environ=env)
    if raw_prios:
        out_prios = parse_priorities(raw_prios)
    elif priorities is not None:
        out_prios = parse_priorities(priorities, source="--priorities")
    else:
        out_prios = DEFAULT_PRIORITIES

    dl = env_float("DPTPU_SERVE_DEADLINE_MS", None, environ=env)
    source = "DPTPU_SERVE_DEADLINE_MS"
    if dl is None:
        dl, source = deadline_ms, "--deadline-ms"
    if dl is None:
        dl = DEFAULT_DEADLINE_MS
    if dl < 0:
        raise ValueError(
            f"{source}={dl} must be >= 0 ms (0 = no server-imposed "
            f"default deadline; requests may still name their own)"
        )

    frac = env_float("DPTPU_SERVE_CANARY_FRACTION", None, environ=env)
    source = "DPTPU_SERVE_CANARY_FRACTION"
    if frac is None:
        frac, source = canary_fraction, "--canary-fraction"
    if frac is None:
        frac = DEFAULT_CANARY_FRACTION
    if not 0.0 < frac < 1.0:
        raise ValueError(
            f"{source}={frac} must be a fraction in (0, 1) — the share "
            f"of batches routed to a staged canary generation (1.0 "
            f"would be a full cutover, which is swap_weights, not a "
            f"canary)"
        )

    drift = env_float("DPTPU_SERVE_CANARY_DRIFT", None, environ=env)
    source = "DPTPU_SERVE_CANARY_DRIFT"
    if drift is None:
        drift, source = canary_drift, "--canary-drift"
    if drift is None:
        drift = DEFAULT_CANARY_DRIFT
    if drift <= 0:
        raise ValueError(
            f"{source}={drift} must be > 0 (max|Δlogit| vs the baseline "
            f"generation tolerated before auto-rollback; 0 would "
            f"roll back every real weight change)"
        )

    lat = env_float("DPTPU_SERVE_CANARY_LAT_FACTOR", None, environ=env)
    source = "DPTPU_SERVE_CANARY_LAT_FACTOR"
    if lat is None:
        lat, source = canary_lat_factor, "--canary-lat-factor"
    if lat is None:
        lat = DEFAULT_CANARY_LAT_FACTOR
    if lat <= 1.0:
        raise ValueError(
            f"{source}={lat} must be > 1 (canary batch latency above "
            f"factor x the baseline's triggers auto-rollback; <= 1 "
            f"would roll back on measurement noise)"
        )

    prec = env_choice("DPTPU_QUANT_PRECISION", PRECISIONS, None,
                      environ=env)
    if prec is None:
        prec = precision if precision is not None else DEFAULT_PRECISION
    if prec not in PRECISIONS:
        raise ValueError(
            f"--precision={prec!r} must be one of "
            + "/".join(repr(p) for p in PRECISIONS)
        )

    calib_path = env_str("DPTPU_QUANT_CALIB", "", environ=env)
    if not calib_path:
        calib_path = calib if calib is not None else ""
    if prec != "fp32" and not calib_path:
        raise ValueError(
            f"precision {prec!r} needs a calibration artifact: set "
            f"DPTPU_QUANT_CALIB/--calib to a `dptpu quantize` output "
            f"(sub-fp32 serving without a provenance-stamped artifact "
            f"is the silent-drift path this refuses)"
        )

    qdrift = env_float("DPTPU_QUANT_DRIFT", None, environ=env)
    source = "DPTPU_QUANT_DRIFT"
    if qdrift is None:
        qdrift, source = quant_drift, "--quant-drift"
    if qdrift is None:
        qdrift = DEFAULT_QUANT_DRIFT
    if qdrift < 0:
        raise ValueError(
            f"{source}={qdrift} must be >= 0 (0 = enforce the "
            f"max|Δlogit| bound stated in the calibration artifact; "
            f"> 0 overrides it)"
        )

    top1 = env_float("DPTPU_QUANT_TOP1_MIN", None, environ=env)
    source = "DPTPU_QUANT_TOP1_MIN"
    if top1 is None:
        top1, source = quant_top1_min, "--quant-top1-min"
    if top1 is None:
        top1 = DEFAULT_QUANT_TOP1_MIN
    if not 0.0 <= top1 <= 1.0:
        raise ValueError(
            f"{source}={top1} must be a fraction in [0, 1] (0 = enforce "
            f"the top-1 agreement floor stated in the calibration "
            f"artifact; > 0 overrides it)"
        )

    fdir = env_str("DPTPU_FLEET_DIR", "", environ=env)
    if not fdir:
        fdir = fleet_dir if fleet_dir is not None else ""

    beat = env_float("DPTPU_FLEET_HEARTBEAT_S", None, environ=env)
    source = "DPTPU_FLEET_HEARTBEAT_S"
    if beat is None:
        beat, source = fleet_heartbeat_s, "--fleet-heartbeat-s"
    if beat is None:
        beat = DEFAULT_FLEET_HEARTBEAT_S
    if beat <= 0:
        raise ValueError(
            f"{source}={beat} must be > 0 seconds (the fleet member "
            f"heartbeat period)"
        )

    fdl = env_float("DPTPU_FLEET_DEADLINE_S", None, environ=env)
    source = "DPTPU_FLEET_DEADLINE_S"
    if fdl is None:
        fdl, source = fleet_deadline_s, "--fleet-deadline-s"
    if fdl is None:
        fdl = DEFAULT_FLEET_DEADLINE_S
    if fdl <= beat:
        raise ValueError(
            f"{source}={fdl} must exceed the heartbeat period ({beat}s) "
            f"— a deadline at or under one beat drains every healthy "
            f"member on scheduler jitter"
        )

    retries = env_int("DPTPU_FLEET_RETRIES", None, environ=env)
    source = "DPTPU_FLEET_RETRIES"
    if retries is None:
        retries, source = fleet_retries, "--fleet-retries"
    if retries is None:
        retries = DEFAULT_FLEET_RETRIES
    if retries < 0:
        raise ValueError(
            f"{source}={retries} must be >= 0 failover retries (0 "
            f"disables failover: a member dying mid-request surfaces "
            f"to the client)"
        )

    return ServeKnobs(out_buckets, float(delay), place, int(n_slots),
                      int(depth), out_prios, float(dl), float(frac),
                      float(drift), float(lat), prec, str(calib_path),
                      float(qdrift), float(top1), str(fdir),
                      float(beat), float(fdl), int(retries))
