"""dptpu.serve — batched inference under heavy traffic.

The production half of the repo (ROADMAP open item 1): everything the
training stack built — the 20+ model registry, the logit-exact
torchvision weight converter, the TP sharding rules, the zero-copy
leased-slot protocol and ``dptpu/obs`` — consumed by one serving
vertical:

* :class:`ServeEngine` (engine.py) — AOT-compiles the eval forward at a
  fixed ladder of batch-size buckets (``DPTPU_SERVE_BUCKETS``) so no
  request ever hits a compile stall; padded-bucket execution is
  logit-IDENTICAL to the single-request path (the >= 2 execution floor,
  see engine.py); weights are generation-tagged and hot-swappable
  without dropping in-flight requests; placement per family is
  replicated or Megatron-TP (``DPTPU_SERVE_PLACEMENT``).
* :class:`DynamicBatcher` (batcher.py) — continuous dynamic batching:
  queued requests coalesce into the largest ready bucket under a
  latency budget (``DPTPU_SERVE_MAX_DELAY_MS``), staged zero-copy in a
  leased /dev/shm slot ring (staging.py — the feed's ``SlotLease``
  handoff, serving edition).
* :func:`preprocess_bytes` (preprocess.py) — request bytes -> the
  pixel-exact validation pixels (``ValTransform``), bit-identical to
  the training/eval pipeline's val path.
* :class:`AdmissionController` (admission.py) — bounded per-model
  queues with priority water marks and deadline-feasibility shedding
  (fast 429/503 + ``Retry-After``), so overload p99 stays bounded at
  the admission boundary, not just by ring backpressure.
* :class:`CanaryController` (canary.py) — gated rollout of a staged
  generation: a traffic fraction pins gen N+1, shadow evals replay its
  inputs through gen N, drift/latency breaches auto-rollback LOUDLY.
* :class:`ModelRouter` (router.py) — N co-resident engines (different
  archs and/or generations) behind one submit/readiness surface, each
  with its own queue, ladder and admission gate.
* quantized fast path (quant.py + dptpu/ops/quant.py) — weight-only
  int8 (per-channel absmax) and bf16 serve precisions behind a
  CRC-sealed, provenance-stamped calibration artifact (``dptpu
  quantize``); the engine's bucket ladder gains a precision axis and
  the canary gate enforces the artifact's logit-drift bounds online —
  a drifting quantized rollout auto-rolls-back, never serves silently.
* :class:`FleetRouter` / :class:`FleetMember` (fleet.py) — the
  multi-host tier (``dptpu serve --fleet``): membership + heartbeats
  over the quorum KV transport, auto-drain of dead hosts on the
  heartbeat verdict, least-loaded routing with connection-death
  failover (zero failed in-flight requests when a host dies), fleet-
  wide admission at the front door.
* knob contract (knobs.py) + stdlib HTTP listener (http.py — liveness
  ``/healthz``, readiness ``/readyz``, ``/predict[/<model>]`` with
  priority/deadline headers) behind the ``dptpu serve`` CLI subcommand
  (dptpu/cli.py).

Benchmarked by ``scripts/run_servebench.py`` (SERVEBENCH.json: p50/p99
latency x offered-load curves closed- and open-loop, saturation
throughput, bucket utilization, a tail-latency gate), smoked in tier 1
by tests/test_servebench_smoke.py.

This package root is import-light: engine/batcher (and jax with them)
load lazily so the CLI can validate knobs — and the conftest leak guard
can police staging segments — without touching a backend.
"""

from dptpu.serve.admission import (
    AdmissionController,
    AdmissionError,
    AdmissionTicket,
)
from dptpu.serve.knobs import (
    DEFAULT_BUCKETS,
    DEFAULT_CANARY_DRIFT,
    DEFAULT_CANARY_FRACTION,
    DEFAULT_CANARY_LAT_FACTOR,
    DEFAULT_DEADLINE_MS,
    DEFAULT_MAX_DELAY_MS,
    DEFAULT_PRIORITIES,
    DEFAULT_QUEUE_DEPTH,
    DEFAULT_SLOTS,
    PLACEMENTS,
    PRIORITY_NAMES,
    ServeKnobs,
    parse_buckets,
    parse_priorities,
    serve_knobs,
)
from dptpu.serve.preprocess import preprocess_array, preprocess_bytes

__all__ = [
    "DEFAULT_BUCKETS", "DEFAULT_MAX_DELAY_MS", "DEFAULT_SLOTS",
    "DEFAULT_QUEUE_DEPTH", "DEFAULT_PRIORITIES", "DEFAULT_DEADLINE_MS",
    "DEFAULT_CANARY_FRACTION", "DEFAULT_CANARY_DRIFT",
    "DEFAULT_CANARY_LAT_FACTOR", "PRIORITY_NAMES",
    "PLACEMENTS", "ServeKnobs", "parse_buckets", "parse_priorities",
    "serve_knobs", "preprocess_bytes", "preprocess_array",
    "AdmissionController", "AdmissionError", "AdmissionTicket",
    "ServeEngine", "DynamicBatcher", "ServeFuture", "ServeError",
    "ServeCancelled", "DeadlineExceeded", "CanaryController",
    "ModelRouter", "ServedModel", "build_served_model",
    "resolve_placement",
    "CalibrationError", "load_calibration", "save_calibration",
    "quantize_variables", "measure_drift", "weights_fingerprint",
    "FleetMember", "FleetRouter", "FleetUnavailable",
    "serve_fleet_forever",
]


def __getattr__(name):
    # lazy jax-side surface: ServeEngine/DynamicBatcher/router import
    # the backend; the knob/preprocess/admission surface above stays
    # import-light
    if name in ("ServeEngine", "resolve_placement"):
        from dptpu.serve import engine

        return getattr(engine, name)
    if name in ("DynamicBatcher", "ServeFuture", "ServeError",
                "ServeCancelled", "DeadlineExceeded"):
        from dptpu.serve import batcher

        return getattr(batcher, name)
    if name == "CanaryController":
        from dptpu.serve.canary import CanaryController

        return CanaryController
    if name in ("ModelRouter", "ServedModel", "build_served_model"):
        from dptpu.serve import router

        return getattr(router, name)
    if name in ("CalibrationError", "load_calibration",
                "save_calibration", "quantize_variables",
                "measure_drift", "weights_fingerprint"):
        from dptpu.serve import quant

        return getattr(quant, name)
    if name in ("FleetMember", "FleetRouter", "FleetUnavailable",
                "serve_fleet_forever"):
        from dptpu.serve import fleet

        return getattr(fleet, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
