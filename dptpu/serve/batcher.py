"""Continuous dynamic batching over the leased staging ring.

Protocol (ISSUE 7 tentpole (b)): requests preprocess IN PLACE into rows
of the one OPEN staging slot; a dispatcher thread closes the slot —
coalescing everything queued into the smallest bucket that holds it —
the moment either (a) the largest bucket fills, or (b) the oldest
request has waited ``max_delay_ms`` (the latency budget; ``0`` =
dispatch every ready request immediately). While the engine runs one
batch, new arrivals fill the NEXT slot — batching is continuous, the
device never waits on a fixed batch boundary, and a full ring (every
slot leased to an in-flight batch) is the backpressure signal that
blocks ``submit`` rather than growing an unbounded queue.

Per-request phase spans land on the ``dptpu/obs`` tracer
(``serve_queue`` — waiting for a staging row; ``serve_preprocess`` —
bytes -> pixels; ``serve_batch_wait`` — coalescing delay;
``serve_device`` — the engine records the compiled call;
``serve_postprocess`` — logit slicing/top-k) and the serve metrics
group on the registry (``Serve/qps``, ``Serve/p99_ms``,
``Serve/bucket_occupancy``, ``Serve/padding_waste``).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import numpy as np

from dptpu import obs
from dptpu.data.transforms import ValTransform
from dptpu.serve.preprocess import preprocess_bytes, val_resize_for
from dptpu.serve.staging import StagingRing
from dptpu.utils.sync import OrderedLock


class ServeError(RuntimeError):
    pass


class ServeFuture:
    """One request's pending result; ``result()`` blocks for the logits
    (float32 ``[num_classes]``) or re-raises the request's failure."""

    __slots__ = ("_event", "_logits", "_error", "generation", "timings")

    def __init__(self):
        self._event = threading.Event()
        self._logits = None  # owned-by: dispatcher
        self._error = None  # owned-by: dispatcher
        self.generation = None  # owned-by: dispatcher
        self.timings: Dict[str, float] = {}  # owned-by: dispatcher
        # all four are written once by the fulfilling thread BEFORE
        # _event.set() and read only after _event.wait() returns — the
        # Event is the publication barrier (single-writer handoff)

    def _fulfill(self, logits, generation, timings):
        self._logits = logits
        self.generation = generation
        self.timings = timings
        self._event.set()

    def _fail(self, exc):
        self._error = exc
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError("serve request still pending")
        if self._error is not None:
            raise self._error
        return self._logits


class _Request:
    __slots__ = ("future", "row", "t_arrive", "t_ready", "ready", "failed")

    def __init__(self, row: int, t_arrive: float):
        self.future = ServeFuture()
        self.row = row
        self.t_arrive = t_arrive
        self.t_ready = 0.0
        self.ready = False
        self.failed = False


class DynamicBatcher:
    """Continuous batcher over one :class:`ServeEngine`."""

    def __init__(self, engine, max_delay_ms: float = 5.0, slots: int = 4):
        if max_delay_ms < 0:
            raise ValueError(
                f"max_delay_ms={max_delay_ms} must be >= 0"
            )
        self.engine = engine
        self.max_delay_s = max_delay_ms / 1e3
        item = (engine.image_size, engine.image_size, 3)
        # rows per slot = the LARGEST bucket's executable size, so pad
        # rows live in the same leased memory the device reads — but
        # ADMISSION is capped at the largest bucket itself: the floor
        # rows beyond it (a 1-only ladder executes at 2) are pad-only
        # and must never be claimed by a request bucket_for() can't place
        self._ring = StagingRing(
            slots, engine.exec_batch(engine.max_bucket), item
        )
        self._admit_max = engine.max_bucket
        self._tf = ValTransform(
            engine.image_size, val_resize_for(engine.image_size)
        )
        self._lock = OrderedLock("serve.batcher")
        self._cond = threading.Condition(self._lock)
        self._open: Optional[int] = None  # guarded-by: _lock
        self._open_reqs: list = []  # guarded-by: _lock
        self._closing = False  # guarded-by: _lock
        # telemetry
        self._completed = 0  # guarded-by: _lock
        self._failed = 0  # guarded-by: _lock
        self._batches = 0  # guarded-by: _lock
        self._batch_seq = 0  # guarded-by: _lock
        self._bucket_counts: Dict[int, int] = {}  # guarded-by: _lock
        self._occupancy_sum = 0.0  # guarded-by: _lock
        self._pad_rows = 0  # guarded-by: _lock
        self._exec_rows = 0  # guarded-by: _lock
        self._latency = obs.get_registry().histogram("Serve/latency_ms")
        self._qps_t0 = time.perf_counter()  # guarded-by: _lock
        self._qps_n0 = 0  # guarded-by: _lock
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="dptpu-serve-dispatch",
            daemon=True,
        )
        self._dispatcher.start()

    # -- submission -----------------------------------------------------

    def submit_bytes(self, data: bytes) -> ServeFuture:
        """Enqueue one request from image bytes (any PIL-decodable
        container); decoding runs on the CALLER's thread — submission
        concurrency is the preprocessing parallelism."""
        return self._submit(data, None)

    def submit_array(self, img: np.ndarray) -> ServeFuture:
        """Enqueue an already-preprocessed uint8 HWC tensor (the bench's
        decode-free path; shape must match the engine's image size)."""
        return self._submit(None, img)

    def _submit(self, data, img) -> ServeFuture:
        tracer = obs.get_tracer()
        t_arrive = time.perf_counter()
        with self._cond:
            while True:
                if self._closing:
                    raise ServeError("batcher is shut down")
                if self._open is None:
                    slot = self._ring.acquire()
                    if slot is not None:
                        self._open = slot
                        self._open_reqs = []
                if self._open is not None and \
                        len(self._open_reqs) < self._admit_max:
                    break
                # every slot leased or the open one is full mid-decode:
                # backpressure (bounded ring), not an unbounded queue
                self._cond.wait(0.05)
            req = _Request(len(self._open_reqs), t_arrive)
            self._open_reqs.append(req)
            slot = self._open
            row_view = self._ring.rows(slot)[req.row]
        t_row = time.perf_counter()
        if t_row - t_arrive > 1e-4:
            tracer.record("serve_queue", t_arrive, t_row - t_arrive)
        try:
            if img is not None:
                if img.shape != row_view.shape:
                    raise ValueError(
                        f"request tensor {img.shape} != engine item "
                        f"shape {row_view.shape} (preprocess first?)"
                    )
                np.copyto(row_view, img)
            else:
                preprocess_bytes(
                    data, size=self.engine.image_size, out=row_view,
                    _transform=self._tf,
                )
        except Exception as e:
            with self._cond:
                req.failed = True
                req.ready = True
                req.t_ready = time.perf_counter()
                self._failed += 1
                self._cond.notify_all()
            req.future._fail(
                e if isinstance(e, ValueError) else ServeError(str(e))
            )
            return req.future
        t_done = time.perf_counter()
        tracer.record("serve_preprocess", t_row, t_done - t_row)
        with self._cond:
            req.ready = True
            req.t_ready = t_done
            self._cond.notify_all()
        return req.future

    # -- dispatch -------------------------------------------------------

    def _dispatchable_locked(self):
        """(slot, reqs) when the open slot should dispatch NOW, else
        (None, deadline): all claimed rows decoded AND (bucket_max full
        OR oldest ready request older than the budget OR closing)."""
        reqs = self._open_reqs
        if self._open is None or not reqs:
            return None, None
        if not all(r.ready for r in reqs):
            return None, None  # a decode is mid-flight; it will notify
        oldest = min(r.t_ready for r in reqs if not r.failed) \
            if any(not r.failed for r in reqs) else 0.0
        full = len(reqs) == self._admit_max
        deadline = oldest + self.max_delay_s
        if full or self._closing or time.perf_counter() >= deadline \
                or all(r.failed for r in reqs):
            slot = self._open
            self._open = None
            self._open_reqs = []
            return (slot, reqs), None
        return None, deadline

    def _dispatch_loop(self):
        while True:
            with self._cond:
                while True:
                    batch, deadline = self._dispatchable_locked()
                    if batch is not None:
                        break
                    if self._closing and self._open is None:
                        return
                    timeout = None if deadline is None else \
                        max(0.0, deadline - time.perf_counter())
                    self._cond.wait(timeout)
            slot, reqs = batch
            try:
                self._run_batch(slot, reqs)
            except Exception as e:
                # the dispatcher thread must survive ANY batch failure:
                # a dead dispatcher strands the open slot and blocks
                # every future submit on backpressure forever.
                # _run_batch already fails futures + releases the lease
                # on engine errors; this guard covers the pre-lease
                # paths (the slot is still FILLING there, so abandon
                # frees it; post-lease it is a checked no-op)
                err = ServeError(f"dispatch failed: {e}")
                for r in reqs:
                    if not r.future.done():
                        r.future._fail(err)
                self._ring.abandon(slot)
                with self._lock:
                    self._failed += sum(1 for r in reqs if not r.failed)
            finally:
                with self._cond:
                    self._cond.notify_all()

    def _run_batch(self, slot: int, reqs):
        tracer = obs.get_tracer()
        live = [r for r in reqs if not r.failed]
        if not live:
            self._ring.abandon(slot)
            return
        n = len(reqs)  # failed rows still occupy their claimed rows
        engine = self.engine
        bucket = engine.bucket_for(n)
        nexec = engine.exec_batch(bucket)
        rows = self._ring.rows(slot)
        for pad in range(n, nexec):
            np.copyto(rows[pad], rows[live[0].row])
        lease = self._ring.lease(slot)
        gen = engine.acquire_generation()
        with self._lock:
            self._batch_seq += 1
            batch_index = self._batch_seq
        t_disp = time.perf_counter()
        try:
            logits = engine.run_bucket(bucket, rows[:nexec], n, gen=gen)
        except Exception as e:
            lease.release()
            engine.release_generation(gen)
            err = ServeError(f"bucket {bucket} execution failed: {e}")
            for r in live:
                r.future._fail(err)
            with self._lock:
                self._failed += len(live)
            return
        # logits are materialized on the host => the device is done
        # reading the slot: the lease may recycle it under new requests
        lease.release()
        engine.release_generation(gen)
        t_post = time.perf_counter()
        for r in live:
            tracer.record("serve_batch_wait", r.t_ready,
                          t_disp - r.t_ready)
            out = np.array(logits[r.row])
            r.future._fulfill(out, gen, {
                "queue_ms": (r.t_ready - r.t_arrive) * 1e3,
                "batch_wait_ms": (t_disp - r.t_ready) * 1e3,
                "device_ms": (t_post - t_disp) * 1e3,
                "total_ms": (t_post - r.t_arrive) * 1e3,
                "bucket": bucket,
                "batch_index": batch_index,
            })
            self._latency.observe((t_post - r.t_arrive) * 1e3)
        tracer.record("serve_postprocess", t_post,
                      time.perf_counter() - t_post)
        reg = obs.get_registry()
        occupancy = n / bucket
        waste = (nexec - n) / nexec
        reg.gauge("Serve/bucket_occupancy").set(occupancy)
        reg.gauge("Serve/padding_waste").set(waste)
        with self._lock:
            self._completed += len(live)
            self._batches += 1
            self._bucket_counts[bucket] = \
                self._bucket_counts.get(bucket, 0) + 1
            self._occupancy_sum += occupancy
            self._pad_rows += nexec - n
            self._exec_rows += nexec

    # -- telemetry / lifecycle ------------------------------------------

    def stats(self, reset_window: bool = True) -> dict:
        """Aggregate serve telemetry; also refreshes the ``Serve/qps``
        and ``Serve/p99_ms`` gauges. ``reset_window`` makes qps AND the
        latency percentiles cover the interval since the previous
        resetting call — and bounds the histogram's memory, which would
        otherwise grow one float per request forever on a long-lived
        server; pass False for a pure peek (the /metrics endpoint)."""
        with self._lock:
            now = time.perf_counter()
            interval = max(now - self._qps_t0, 1e-9)
            qps = (self._completed - self._qps_n0) / interval
            if reset_window:
                self._qps_t0, self._qps_n0 = now, self._completed
            lat = self._latency.snapshot(reset=reset_window)
            out = {
                "completed": self._completed,
                "failed": self._failed,
                "batches": self._batches,
                "qps": qps,
                "bucket_counts": dict(self._bucket_counts),
                "mean_bucket_occupancy": (
                    self._occupancy_sum / self._batches
                    if self._batches else 0.0
                ),
                "padding_waste": (
                    self._pad_rows / self._exec_rows
                    if self._exec_rows else 0.0
                ),
                "latency_ms": lat,
            }
        reg = obs.get_registry()
        reg.gauge("Serve/qps").set(qps)
        if lat.get("count"):
            reg.gauge("Serve/p99_ms").set(lat["p99"])
        return out

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop accepting requests; by default DRAIN what is queued
        (every accepted future resolves), then stop the dispatcher and
        unlink the staging ring."""
        with self._cond:
            if self._closing:
                return
            self._closing = True
            if not drain and self._open is not None:
                for r in self._open_reqs:
                    if not r.future.done():
                        r.future._fail(ServeError("batcher shut down"))
                self._ring.abandon(self._open)
                self._open = None
                self._open_reqs = []
            self._cond.notify_all()
        self._dispatcher.join(timeout)
        self._ring.close()

    def __del__(self):
        try:
            if not self._closing:
                self.close(drain=False, timeout=1.0)
        except Exception:
            pass
