"""Continuous dynamic batching over the leased staging ring.

Protocol (ISSUE 7 tentpole (b)): requests preprocess IN PLACE into rows
of the one OPEN staging slot; a dispatcher thread closes the slot —
coalescing everything queued into the smallest bucket that holds it —
the moment either (a) the largest bucket fills, or (b) the oldest
request has waited ``max_delay_ms`` (the latency budget; ``0`` =
dispatch every ready request immediately). While the engine runs one
batch, new arrivals fill the NEXT slot — batching is continuous, the
device never waits on a fixed batch boundary, and a full ring (every
slot leased to an in-flight batch) is the backpressure signal that
blocks ``submit`` rather than growing an unbounded queue.

Per-request phase spans land on the ``dptpu/obs`` tracer
(``serve_queue`` — waiting for a staging row; ``serve_preprocess`` —
bytes -> pixels; ``serve_batch_wait`` — coalescing delay;
``serve_device`` — the engine records the compiled call;
``serve_postprocess`` — logit slicing/top-k) and the serve metrics
group on the registry (``Serve/qps``, ``Serve/p99_ms``,
``Serve/bucket_occupancy``, ``Serve/padding_waste``).

Request lifecycle (ISSUE 17 tentpole (b)): every request may carry an
absolute DEADLINE (``time.perf_counter()`` seconds); an expired or
client-cancelled request is evicted while it coalesces — it fails fast
with :class:`DeadlineExceeded`/:class:`ServeCancelled`, its row is
COMPACTED away before execution (a dead request occupies zero bucket
rows, proven by the padding-waste accounting), and the ``max_delay_ms``
coalescing timer re-anchors onto the oldest LIVE request so a corpse
never drives dispatch cadence. Serve-side ``DPTPU_FAULT`` hooks
(``serve_exception`` / ``preprocess_crash`` / ``slow_model``) inject at
the submit, preprocess, and execute boundaries.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import numpy as np

from dptpu import obs
from dptpu.data.transforms import ValTransform
from dptpu.resilience.faults import FaultPlan
from dptpu.serve.preprocess import preprocess_bytes, val_resize_for
from dptpu.serve.staging import StagingRing
from dptpu.utils.sync import OrderedLock


class ServeError(RuntimeError):
    pass


class ServeCancelled(ServeError):
    """The request was withdrawn (client disconnect / explicit cancel)
    before its batch dispatched."""


class DeadlineExceeded(ServeError):
    """The request's deadline expired before its logits materialized."""


class ServeFuture:
    """One request's pending result; ``result()`` blocks for the logits
    (float32 ``[num_classes]``) or re-raises the request's failure.
    ``cancel()`` withdraws a still-coalescing request (the HTTP layer's
    client-disconnect path); ``add_done_callback`` runs exactly once on
    completion (the admission layer's occupancy release)."""

    __slots__ = ("_event", "_cb_lock", "_done_cbs", "_cancel_cb",
                 "_logits", "_error", "generation", "timings")

    def __init__(self, cancel_cb=None):
        self._event = threading.Event()
        # raw leaf Lock (no rank): held only for list/flag flips, never
        # while acquiring a ranked lock — callbacks run AFTER release
        self._cb_lock = threading.Lock()
        self._done_cbs: list = []  # guarded-by: _cb_lock
        self._cancel_cb = cancel_cb
        self._logits = None  # owned-by: completer
        self._error = None  # owned-by: completer
        self.generation = None  # owned-by: completer
        self.timings: Dict[str, float] = {}  # owned-by: completer
        # the payload attrs are written once by the COMPLETING thread
        # before _event.set() and read only after _event.wait() returns
        # — the Event is the publication barrier; _cb_lock arbitrates
        # WHICH thread completes (dispatcher fulfil vs cancel/deadline
        # failure race first-wins, losers are dropped)

    def _complete(self, error, logits=None, generation=None,
                  timings=None) -> bool:
        with self._cb_lock:
            if self._event.is_set():
                return False  # first completion wins
            self._error = error
            self._logits = logits
            if generation is not None:
                self.generation = generation
            if timings is not None:
                self.timings = timings
            self._event.set()
            cbs, self._done_cbs = self._done_cbs, []
        for cb in cbs:  # off-lock: callbacks may take ranked locks
            try:
                cb(self)
            except Exception:
                pass
        return True

    def _fulfill(self, logits, generation, timings) -> bool:
        return self._complete(None, logits, generation, timings)

    def _fail(self, exc) -> bool:
        return self._complete(exc)

    def add_done_callback(self, fn) -> None:
        """Arrange ``fn(self)`` to run when the request completes; an
        already-done future runs it immediately on the caller's thread.
        Callback exceptions are swallowed (they must not kill the
        dispatcher)."""
        with self._cb_lock:
            if not self._event.is_set():
                self._done_cbs.append(fn)
                return
        try:
            fn(self)
        except Exception:
            pass

    def cancel(self) -> bool:
        """Withdraw the request if its batch has not dispatched; True
        when the cancellation took (``result()`` raises
        :class:`ServeCancelled`, the staged row is compacted away)."""
        if self._cancel_cb is None:
            return False
        return self._cancel_cb()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError("serve request still pending")
        if self._error is not None:
            raise self._error
        return self._logits


class _Request:
    __slots__ = ("future", "row", "t_arrive", "t_ready", "deadline",
                 "ready", "failed", "cancelled", "dispatched")

    def __init__(self, row: int, t_arrive: float,
                 deadline: Optional[float], canceller):
        self.future = ServeFuture(
            cancel_cb=(lambda: canceller(self)) if canceller else None
        )
        self.row = row
        self.t_arrive = t_arrive
        self.t_ready = 0.0
        self.deadline = deadline  # absolute perf_counter s, or None
        self.ready = False
        self.failed = False
        self.cancelled = False
        self.dispatched = False


class DynamicBatcher:
    """Continuous batcher over one :class:`ServeEngine`."""

    def __init__(self, engine, max_delay_ms: float = 5.0, slots: int = 4,
                 canary=None, fault_plan: Optional[FaultPlan] = None):
        if max_delay_ms < 0:
            raise ValueError(
                f"max_delay_ms={max_delay_ms} must be >= 0"
            )
        self.engine = engine
        # generation picker + drift observer for canary rollout; None =
        # every batch pins the engine's current generation
        self._canary = canary
        self._plan = fault_plan if fault_plan is not None \
            else FaultPlan.from_env()
        self.max_delay_s = max_delay_ms / 1e3
        item = (engine.image_size, engine.image_size, 3)
        # rows per slot = the LARGEST bucket's executable size, so pad
        # rows live in the same leased memory the device reads — but
        # ADMISSION is capped at the largest bucket itself: the floor
        # rows beyond it (a 1-only ladder executes at 2) are pad-only
        # and must never be claimed by a request bucket_for() can't place
        self._ring = StagingRing(
            slots, engine.exec_batch(engine.max_bucket), item
        )
        self._admit_max = engine.max_bucket
        self._tf = ValTransform(
            engine.image_size, val_resize_for(engine.image_size)
        )
        self._lock = OrderedLock("serve.batcher")
        self._cond = threading.Condition(self._lock)
        self._open: Optional[int] = None  # guarded-by: _lock
        self._open_reqs: list = []  # guarded-by: _lock
        self._closing = False  # guarded-by: _lock
        # telemetry
        self._completed = 0  # guarded-by: _lock
        self._failed = 0  # guarded-by: _lock
        self._cancelled = 0  # guarded-by: _lock
        self._expired = 0  # guarded-by: _lock
        self._dead_rows = 0  # guarded-by: _lock
        self._submit_seq = 0  # guarded-by: _lock
        self._batches = 0  # guarded-by: _lock
        self._batch_seq = 0  # guarded-by: _lock
        self._bucket_counts: Dict[int, int] = {}  # guarded-by: _lock
        self._occupancy_sum = 0.0  # guarded-by: _lock
        self._pad_rows = 0  # guarded-by: _lock
        self._exec_rows = 0  # guarded-by: _lock
        # tune controller (ISSUE 19): attached before traffic, ticked on
        # the dispatch thread between batches while holding no lock
        self._controller = None  # owned-by: caller
        self._latency = obs.get_registry().histogram("Serve/latency_ms")
        self._qps_t0 = time.perf_counter()  # guarded-by: _lock
        self._qps_n0 = 0  # guarded-by: _lock
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="dptpu-serve-dispatch",
            daemon=True,
        )
        self._dispatcher.start()

    # -- submission -----------------------------------------------------

    def submit_bytes(self, data: bytes,
                     deadline: Optional[float] = None) -> ServeFuture:
        """Enqueue one request from image bytes (any PIL-decodable
        container); decoding runs on the CALLER's thread — submission
        concurrency is the preprocessing parallelism. ``deadline`` is an
        absolute ``time.perf_counter()`` second past which the request
        is evicted instead of served."""
        return self._submit(data, None, deadline)

    def submit_array(self, img: np.ndarray,
                     deadline: Optional[float] = None) -> ServeFuture:
        """Enqueue an already-preprocessed uint8 HWC tensor (the bench's
        decode-free path; shape must match the engine's image size)."""
        return self._submit(None, img, deadline)

    def _submit(self, data, img, deadline) -> ServeFuture:
        tracer = obs.get_tracer()
        t_arrive = time.perf_counter()
        with self._cond:
            self._submit_seq += 1
            seq = self._submit_seq
        if self._plan is not None:
            try:
                self._plan.on_serve_submit(seq)  # fault hook
            except Exception as e:
                raise ServeError(f"request rejected: {e}")
        with self._cond:
            while True:
                if self._closing:
                    raise ServeError("batcher is shut down")
                if deadline is not None and \
                        time.perf_counter() >= deadline:
                    # expired while blocked on ring backpressure: fail
                    # fast WITHOUT claiming a row
                    raise DeadlineExceeded(
                        "request deadline expired before a staging row "
                        "freed"
                    )
                if self._open is None:
                    slot = self._ring.acquire()
                    if slot is not None:
                        self._open = slot
                        self._open_reqs = []
                if self._open is not None and \
                        len(self._open_reqs) < self._admit_max:
                    break
                # every slot leased or the open one is full mid-decode:
                # backpressure (bounded ring), not an unbounded queue
                self._cond.wait(0.05)
            req = _Request(len(self._open_reqs), t_arrive, deadline,
                           self._cancel)
            self._open_reqs.append(req)
            slot = self._open
            row_view = self._ring.rows(slot)[req.row]
        t_row = time.perf_counter()
        if t_row - t_arrive > 1e-4:
            tracer.record("serve_queue", t_arrive, t_row - t_arrive)
        try:
            if self._plan is not None:
                self._plan.on_serve_preprocess(seq)  # fault hook
            if img is not None:
                if img.shape != row_view.shape:
                    raise ValueError(
                        f"request tensor {img.shape} != engine item "
                        f"shape {row_view.shape} (preprocess first?)"
                    )
                np.copyto(row_view, img)
            else:
                preprocess_bytes(
                    data, size=self.engine.image_size, out=row_view,
                    _transform=self._tf,
                )
        except Exception as e:
            with self._cond:
                req.failed = True
                req.ready = True
                req.t_ready = time.perf_counter()
                self._failed += 1
                self._cond.notify_all()
            req.future._fail(
                e if isinstance(e, ValueError) else ServeError(str(e))
            )
            return req.future
        t_done = time.perf_counter()
        tracer.record("serve_preprocess", t_row, t_done - t_row)
        with self._cond:
            req.ready = True
            req.t_ready = t_done
            self._cond.notify_all()
        return req.future

    def _cancel(self, req: _Request) -> bool:
        """Withdraw ``req`` while it is still coalescing: its row is
        marked dead (compacted away at dispatch), the ``max_delay_ms``
        timer re-anchors onto the next-oldest LIVE request, and its
        future fails with :class:`ServeCancelled`. False once the batch
        has dispatched — device work cannot be unclaimed."""
        with self._cond:
            if req.dispatched or req.future.done():
                return False
            req.cancelled = True
            self._cancelled += 1
            self._cond.notify_all()
        return req.future._fail(ServeCancelled("request cancelled"))

    # -- dispatch -------------------------------------------------------

    def _dispatchable_locked(self):
        """(slot, reqs) when the open slot should dispatch NOW, else
        (None, wake): all claimed rows decoded AND (bucket_max full OR
        oldest LIVE ready request older than the budget OR closing OR
        every claimed row dead). Deadline-expired requests are evicted
        here: they fail fast, stop anchoring the coalescing timer, and
        their rows are compacted away before execution. ``wake`` is the
        next instant a time-based condition can flip (coalesce budget or
        the earliest live deadline)."""
        reqs = self._open_reqs
        if self._open is None or not reqs:
            return None, None
        now = time.perf_counter()
        for r in reqs:
            if not r.failed and not r.cancelled and \
                    r.deadline is not None and now >= r.deadline:
                r.cancelled = True
                self._expired += 1
                # done-callbacks run under the batcher lock (rank 10);
                # admission release (rank 15) nests legally above it
                r.future._fail(DeadlineExceeded(
                    "request deadline expired while coalescing"
                ))
        if not all(r.ready for r in reqs):
            # a decode is mid-flight (it will notify); dead rows also
            # wait here — compaction must never copy over a row a
            # preprocess thread is still writing
            return None, None
        live = [r for r in reqs if not r.failed and not r.cancelled]
        full = len(reqs) == self._admit_max
        if not live:
            slot = self._open
            self._open = None
            self._open_reqs = []
            for r in reqs:
                r.dispatched = True
            return (slot, reqs), None
        # timer re-anchor: only LIVE requests drive dispatch cadence
        oldest = min(r.t_ready for r in live)
        coalesce = oldest + self.max_delay_s
        if full or self._closing or now >= coalesce:
            slot = self._open
            self._open = None
            self._open_reqs = []
            for r in reqs:
                r.dispatched = True
            return (slot, reqs), None
        wake = coalesce
        for r in live:
            if r.deadline is not None and r.deadline < wake:
                wake = r.deadline
        return None, wake

    def _dispatch_loop(self):
        while True:
            with self._cond:
                while True:
                    batch, deadline = self._dispatchable_locked()
                    if batch is not None:
                        break
                    if self._closing and self._open is None:
                        return
                    timeout = None if deadline is None else \
                        max(0.0, deadline - time.perf_counter())
                    self._cond.wait(timeout)
            slot, reqs = batch
            try:
                self._run_batch(slot, reqs)
                if self._controller is not None:
                    # rate-limited inside; actuator seams take their own
                    # locks in rank order (batcher 10 -> engine 20) and
                    # never raise into the dispatch loop
                    self._controller.tick()
            except Exception as e:
                # the dispatcher thread must survive ANY batch failure:
                # a dead dispatcher strands the open slot and blocks
                # every future submit on backpressure forever.
                # _run_batch already fails futures + releases the lease
                # on engine errors; this guard covers the pre-lease
                # paths (the slot is still FILLING there, so abandon
                # frees it; post-lease it is a checked no-op)
                err = ServeError(f"dispatch failed: {e}")
                for r in reqs:
                    if not r.future.done():
                        r.future._fail(err)
                self._ring.abandon(slot)
                with self._lock:
                    self._failed += sum(1 for r in reqs if not r.failed)
            finally:
                with self._cond:
                    self._cond.notify_all()

    def _run_batch(self, slot: int, reqs):
        tracer = obs.get_tracer()
        live = [r for r in reqs if not r.failed and not r.cancelled]
        dead = len(reqs) - len(live)
        if dead:
            with self._lock:
                self._dead_rows += dead
        if not live:
            self._ring.abandon(slot)
            return
        rows = self._ring.rows(slot)
        # dead-request hygiene: compact live rows to the front so a
        # failed/cancelled/expired request occupies ZERO bucket rows —
        # the batch executes at the LIVE count's bucket, not the claimed
        # count's. Rows were claimed in submission order, so r.row is
        # strictly increasing and the forward copy never clobbers an
        # unread source row.
        for i, r in enumerate(live):
            if r.row != i:
                np.copyto(rows[i], rows[r.row])
                r.row = i
        n = len(live)
        engine = self.engine
        bucket = engine.bucket_for(n)
        nexec = engine.exec_batch(bucket)
        for pad in range(n, nexec):
            np.copyto(rows[pad], rows[0])
        lease = self._ring.lease(slot)
        if self._canary is not None:
            gen = self._canary.pick_generation()
        else:
            gen = engine.acquire_generation()
        shadow = None
        if self._canary is not None and self._canary.wants_shadow(gen):
            # snapshot BEFORE the lease recycles the slot under new
            # requests: the baseline drift replay needs these pixels
            shadow = np.array(rows[:nexec])
        with self._lock:
            self._batch_seq += 1
            batch_index = self._batch_seq
        t_disp = time.perf_counter()
        if self._plan is not None:
            delay = self._plan.serve_model_delay_s()
            if delay:
                time.sleep(delay)  # injected slow_model fault
        try:
            logits = engine.run_bucket(bucket, rows[:nexec], n, gen=gen)
        except Exception as e:
            lease.release()
            engine.release_generation(gen)
            err = ServeError(f"bucket {bucket} execution failed: {e}")
            for r in live:
                r.future._fail(err)
            with self._lock:
                self._failed += len(live)
            return
        # logits are materialized on the host => the device is done
        # reading the slot: the lease may recycle it under new requests
        lease.release()
        engine.release_generation(gen)
        t_post = time.perf_counter()
        for r in live:
            tracer.record("serve_batch_wait", r.t_ready,
                          t_disp - r.t_ready)
            out = np.array(logits[r.row])
            r.future._fulfill(out, gen, {
                "queue_ms": (r.t_ready - r.t_arrive) * 1e3,
                "batch_wait_ms": (t_disp - r.t_ready) * 1e3,
                "device_ms": (t_post - t_disp) * 1e3,
                "total_ms": (t_post - r.t_arrive) * 1e3,
                "bucket": bucket,
                "batch_index": batch_index,
            })
            self._latency.observe((t_post - r.t_arrive) * 1e3)
        tracer.record("serve_postprocess", t_post,
                      time.perf_counter() - t_post)
        if self._canary is not None:
            self._canary.observe(gen, bucket, n, (t_post - t_disp) * 1e3,
                                 shadow, logits)
        reg = obs.get_registry()
        occupancy = n / bucket
        waste = (nexec - n) / nexec
        reg.gauge("Serve/bucket_occupancy").set(occupancy)
        reg.gauge("Serve/padding_waste").set(waste)
        with self._lock:
            self._completed += len(live)
            self._batches += 1
            self._bucket_counts[bucket] = \
                self._bucket_counts.get(bucket, 0) + 1
            self._occupancy_sum += occupancy
            self._pad_rows += nexec - n
            self._exec_rows += nexec

    # -- telemetry / lifecycle ------------------------------------------

    def attach_controller(self, controller) -> None:
        """Arm a tune controller (ISSUE 19): ticked on the dispatch
        thread after every batch. Attach before traffic."""
        self._controller = controller

    def padding_counts(self):
        """Cumulative ``(pad_rows, exec_rows)`` — the serve-ladder
        actuator's raw feed (it computes interval ratios itself, so the
        ``stats()`` qps/latency windows stay untouched)."""
        with self._lock:
            return self._pad_rows, self._exec_rows

    def stats(self, reset_window: bool = True) -> dict:
        """Aggregate serve telemetry; also refreshes the ``Serve/qps``
        and ``Serve/p99_ms`` gauges. ``reset_window`` makes qps AND the
        latency percentiles cover the interval since the previous
        resetting call — and bounds the histogram's memory, which would
        otherwise grow one float per request forever on a long-lived
        server; pass False for a pure peek (the /metrics endpoint)."""
        with self._lock:
            now = time.perf_counter()
            interval = max(now - self._qps_t0, 1e-9)
            qps = (self._completed - self._qps_n0) / interval
            if reset_window:
                self._qps_t0, self._qps_n0 = now, self._completed
            lat = self._latency.snapshot(reset=reset_window)
            out = {
                "completed": self._completed,
                "failed": self._failed,
                "cancelled": self._cancelled,
                "expired": self._expired,
                "dead_rows": self._dead_rows,
                "batches": self._batches,
                "qps": qps,
                "bucket_counts": dict(self._bucket_counts),
                "mean_bucket_occupancy": (
                    self._occupancy_sum / self._batches
                    if self._batches else 0.0
                ),
                "padding_waste": (
                    self._pad_rows / self._exec_rows
                    if self._exec_rows else 0.0
                ),
                "latency_ms": lat,
            }
        reg = obs.get_registry()
        reg.gauge("Serve/qps").set(qps)
        if lat.get("count"):
            reg.gauge("Serve/p99_ms").set(lat["p99"])
        return out

    @property
    def draining(self) -> bool:
        """True once ``close`` has begun: accepted requests still
        resolve, new submissions are refused (the readiness signal)."""
        with self._lock:
            return self._closing

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop accepting requests; by default DRAIN what is queued
        (every accepted future resolves), then stop the dispatcher and
        unlink the staging ring."""
        with self._cond:
            if self._closing:
                return
            self._closing = True
            if not drain and self._open is not None:
                for r in self._open_reqs:
                    if not r.future.done():
                        r.future._fail(ServeError("batcher shut down"))
                self._ring.abandon(self._open)
                self._open = None
                self._open_reqs = []
            self._cond.notify_all()
        self._dispatcher.join(timeout)
        self._ring.close()

    def __del__(self):
        try:
            if not self._closing:
                self.close(drain=False, timeout=1.0)
        except Exception:
            pass
