"""Multi-model multiplexing: N engines, one host, one request front.

ISSUE 17 tentpole (c). One serving host rarely hosts one model: A/B
archs, per-tenant heads, and N+1-generation canaries all share the same
device budget. The router composes the per-model stacks —

    AdmissionController -> DynamicBatcher -> ServeEngine (+ canary)

— behind one submit/readiness surface. Each model keeps its OWN bounded
queue, bucket ladder, staging ring and admission water marks, so a
saturated model sheds ITS traffic while its neighbours keep serving
(SERVEBENCH's multi-model arm records exactly that: per-model p99s with
two co-resident engines under concurrent load).

The model table is built once and then IMMUTABLE — routing is a dict
lookup, no lock, no contention on the hot path. Lifecycle (``close``)
tears the stacks down model by model: admission first refuses new work,
the batcher drains, the canary evaluator joins.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from dptpu.serve.admission import AdmissionController, AdmissionTicket
from dptpu.serve.batcher import DynamicBatcher, ServeFuture
from dptpu.serve.canary import CanaryController


class ServedModel:
    """One model's full serving stack (a plain immutable record)."""

    __slots__ = ("name", "engine", "batcher", "admission", "canary")

    def __init__(self, name: str, engine, batcher: DynamicBatcher,
                 admission: AdmissionController,
                 canary: CanaryController):
        self.name = name
        self.engine = engine
        self.batcher = batcher
        self.admission = admission
        self.canary = canary


def build_served_model(name: str, arch: str, knobs, *,
                       num_classes: int = 1000, image_size: int = 224,
                       variables: Optional[dict] = None,
                       pretrained: bool = False, verbose: bool = False,
                       fault_plan=None) -> ServedModel:
    """Assemble one model's stack from validated :class:`ServeKnobs`.
    Construction order matters: the canary controller must exist before
    the batcher so the batcher's generation picker is wired at
    construction (never mutated after)."""
    from dptpu.serve.engine import ServeEngine

    engine = ServeEngine(
        arch, buckets=knobs.buckets, placement=knobs.placement,
        num_classes=num_classes, image_size=image_size,
        variables=variables, pretrained=pretrained, verbose=verbose,
    )
    canary = CanaryController(
        engine, fraction=knobs.canary_fraction,
        drift_limit=knobs.canary_drift,
        lat_factor=knobs.canary_lat_factor,
        min_top1_agreement=knobs.quant_top1_min, fault_plan=fault_plan,
    )
    batcher = DynamicBatcher(
        engine, max_delay_ms=knobs.max_delay_ms, slots=knobs.slots,
        canary=canary, fault_plan=fault_plan,
    )
    admission = AdmissionController(
        depth=knobs.queue_depth, priorities=knobs.priorities,
        deadline_ms=knobs.deadline_ms, name=name,
    )
    return ServedModel(name, engine, batcher, admission, canary)


class ModelRouter:
    """Immutable name -> :class:`ServedModel` table; the first model is
    the default route (bare ``/predict``)."""

    def __init__(self, models: List[ServedModel]):
        if not models:
            raise ValueError("a router needs at least one model")
        self.models: Dict[str, ServedModel] = {}
        for m in models:
            if m.name in self.models:
                raise ValueError(f"duplicate model name {m.name!r}")
            self.models[m.name] = m
        self.default = models[0].name

    def model(self, name: Optional[str] = None) -> ServedModel:
        key = name if name is not None else self.default
        try:
            return self.models[key]
        except KeyError:
            raise KeyError(
                f"no model {key!r} (serving: {sorted(self.models)})"
            )

    # -- request path ---------------------------------------------------

    def submit(self, data: Optional[bytes] = None,
               img: Optional[np.ndarray] = None,
               model: Optional[str] = None, priority: str = "normal",
               deadline_ms: Optional[float] = None) -> ServeFuture:
        """The admitted request path: admission gate -> batcher submit
        with the ticket's absolute deadline -> occupancy released by the
        future's done-callback (covers the WHOLE lifecycle). Raises
        :class:`~dptpu.serve.admission.AdmissionError` on shed,
        :class:`~dptpu.serve.batcher.DeadlineExceeded` when the deadline
        expires during submit backpressure."""
        m = self.model(model)
        ticket = m.admission.try_admit(priority, deadline_ms)
        try:
            if img is not None:
                fut = m.batcher.submit_array(img, deadline=ticket.deadline)
            else:
                fut = m.batcher.submit_bytes(data, deadline=ticket.deadline)
        except Exception:
            m.admission.release(ticket)
            raise

        def _release(f, _adm=m.admission, _t=ticket):
            # only SERVED requests feed the feasibility EWMA — a failed
            # or cancelled future has empty timings and passes None
            _adm.release(_t, service_ms=f.timings.get("total_ms"))

        fut.add_done_callback(_release)
        return fut

    # -- health ---------------------------------------------------------

    def readiness(self) -> Tuple[bool, List[str]]:
        """(ready, reasons). Ready = EVERY model can take normal-priority
        traffic right now; reasons name the models that cannot and why
        (draining / shedding hard / mid-rollback)."""
        reasons: List[str] = []
        for name, m in self.models.items():
            if m.batcher.draining:
                reasons.append(f"{name}: draining")
            if m.admission.shedding_hard():
                reasons.append(f"{name}: shedding")
            if m.canary.rolling_back:
                reasons.append(f"{name}: rolling back")
        return not reasons, reasons

    def start_canary(self, variables, model: Optional[str] = None) -> int:
        """Stage a canary generation on one model (see
        :class:`~dptpu.serve.canary.CanaryController`)."""
        return self.model(model).canary.start(variables)

    def start_quantized(self, knobs, model: Optional[str] = None) -> int:
        """Deploy a quantized generation on one model per the validated
        :class:`~dptpu.serve.knobs.ServeKnobs`: the engine verifies the
        calibration artifact, the canary gate enforces the artifact's
        bounds (operator knobs > 0 override), and a drifting rollout
        auto-rolls-back — the ONLY path to sub-fp32 serving."""
        return self.model(model).canary.start_quantized(
            knobs.calib, precision=knobs.precision,
            drift_limit=knobs.quant_drift or None,
            top1_min=knobs.quant_top1_min or None,
        )

    def stats(self) -> dict:
        return {
            name: {
                "serve": m.batcher.stats(reset_window=False),
                "admission": m.admission.stats(),
                "canary": m.canary.status(),
            }
            for name, m in self.models.items()
        }

    def close(self, drain: bool = True) -> None:
        for m in self.models.values():
            m.batcher.close(drain=drain)
            m.canary.close()
