"""Packed sequential shard format: ImageNet as N big files, not 1.3M tiny ones.

Every ImageNet-in-minutes system feeds from packed sequential containers
(arXiv:1711.04325, arXiv:1903.12650): a directory of a million small
JPEGs costs an open+stat+small-random-read per sample — syscall churn and
seek traffic that cap the cold feed once decode is parallel — and cannot
be range-fetched from an object store at all. A ``.dpts`` shard packs the
raw encoded bytes of a contiguous slice of the (deterministic,
sorted-walk) ImageFolder sample order into one file:

``[ header 96 B | meta JSON | index u64[n,5] | pad to 4 KiB | data ]``

* **Header** — magic/version/geometry plus CRC32s of the meta, the
  index, and the header itself (the checkpoint layer's CRC-seal
  discipline: a truncated or bit-rotted shard is detected before any
  byte of it is trusted).
* **Meta** — JSON: class names, the shard's global start index. No
  timestamps anywhere: packing the same tree twice yields BYTE-IDENTICAL
  shards (locked by tests), so shards are content-addressable and
  rsync/object-store friendly.
* **Index** — per sample ``(offset, length, label, crc32, flags)`` as
  little-endian u64 rows: the extent map that lets a streaming reader
  (or an HTTP range fetch) pull exactly one sample — and verify it —
  without touching the rest of the shard. ``flags`` bit 0 marks JPEG
  payloads (the native-decoder gate that ImageFolder derives from the
  file extension).
* **Data** — the files' bytes, concatenated unmodified (so pixels are
  bit-identical to the ImageFolder path by construction), starting at a
  4 KiB-aligned offset (the O_DIRECT reader's natural block).

``write_shards`` converts one ImageFolder split; the ``dptpu pack`` CLI
wraps it for ``train/``+``val/`` trees. ``ShardSet`` is the reader-side
map: manifest + lazily range-fetched per-shard indexes, global index →
``(shard, extent)``. :class:`ShardLocalitySampler` builds the epoch
permutation as a seeded SHARD-level shuffle + in-shard shuffle — the
streaming-friendly visit order (one shard's extents drain before the
next shard is touched) that remains a pure function of ``(seed,
epoch)``, so mid-epoch ``--resume`` replays it exactly like the default
sampler; per-``(seed, epoch, index)`` pixels are unchanged either way.

Worker-safe: stdlib + numpy only, never JAX (spawned decode workers
import this).
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import List, Optional, Tuple

import numpy as np

from dptpu.data.store import Store, open_store

MAGIC = b"DPTPUSH1"
VERSION = 1
HEADER_LEN = 96
_HEADER_FMT = "<8sIIIIQQQQQIII"  # + pad to HEADER_LEN
_HEADER_STRUCT = struct.Struct(_HEADER_FMT)
DATA_ALIGN = 4096  # data region starts block-aligned (O_DIRECT's unit)
MANIFEST_NAME = "dptpu_shards.json"
SHARD_SUFFIX = ".dpts"

# index row fields (u64 each)
IDX_OFF, IDX_LEN, IDX_LABEL, IDX_CRC, IDX_FLAGS = range(5)
IDX_FIELDS = 5
FLAG_JPEG = 1

_JPEG_EXT = (".jpg", ".jpeg")


class ShardFormatError(ValueError):
    """Shard bytes fail their structural parse or a sealed CRC."""


class ShardCorruptError(ShardFormatError):
    """A sample extent's content CRC mismatched — the shard is damaged
    at that extent (bit rot, truncation, or a torn remote fetch)."""


def shard_name(index: int) -> str:
    return f"shard-{index:05d}{SHARD_SUFFIX}"


def _pack_header(shard_index: int, num_shards: int, num_samples: int,
                 meta: bytes, index_bytes: bytes, data_len: int) -> bytes:
    meta_off = HEADER_LEN
    index_off = meta_off + len(meta)
    data_off = -(-(index_off + len(index_bytes)) // DATA_ALIGN) * DATA_ALIGN
    body = _HEADER_STRUCT.pack(
        MAGIC, VERSION, shard_index, num_shards, num_samples,
        meta_off, len(meta), index_off, data_off, data_len,
        zlib.crc32(meta) & 0xFFFFFFFF,
        zlib.crc32(index_bytes) & 0xFFFFFFFF,
        0,  # header_crc placeholder
    )
    crc = zlib.crc32(body[:-4]) & 0xFFFFFFFF
    body = body[:-4] + struct.pack("<I", crc)
    return body + b"\x00" * (HEADER_LEN - len(body))


def parse_header(raw: bytes, name: str = "<shard>") -> dict:
    """Parse + CRC-verify the 96-byte shard header; raises
    :class:`ShardFormatError` on anything not a healthy v1 shard."""
    if len(raw) < HEADER_LEN:
        raise ShardFormatError(
            f"{name}: {len(raw)} bytes is shorter than the {HEADER_LEN}-"
            f"byte shard header — truncated or not a .dpts shard"
        )
    fields = _HEADER_STRUCT.unpack(raw[:_HEADER_STRUCT.size])
    (magic, version, shard_index, num_shards, num_samples,
     meta_off, meta_len, index_off, data_off, data_len,
     meta_crc, index_crc, header_crc) = fields
    if magic != MAGIC:
        raise ShardFormatError(
            f"{name}: bad magic {magic!r} — not a dptpu packed shard"
        )
    if version != VERSION:
        raise ShardFormatError(
            f"{name}: shard format version {version} != supported "
            f"{VERSION}"
        )
    if zlib.crc32(raw[:_HEADER_STRUCT.size - 4]) & 0xFFFFFFFF != header_crc:
        raise ShardFormatError(
            f"{name}: shard header CRC mismatch — the header is corrupt"
        )
    return {
        "shard_index": shard_index, "num_shards": num_shards,
        "num_samples": num_samples, "meta_off": meta_off,
        "meta_len": meta_len, "index_off": index_off,
        "data_off": data_off, "data_len": data_len,
        "meta_crc": meta_crc, "index_crc": index_crc,
    }


def parse_index(raw: bytes, expected_crc: int, num_samples: int,
                name: str = "<shard>") -> np.ndarray:
    """The ``(n, 5)`` u64 extent table from its on-disk bytes, CRC-
    verified against the sealed header."""
    if zlib.crc32(raw) & 0xFFFFFFFF != expected_crc:
        raise ShardFormatError(
            f"{name}: shard index CRC mismatch — the extent table is "
            f"corrupt; re-pack or re-fetch the shard"
        )
    idx = np.frombuffer(raw, dtype="<u8")
    if idx.size != num_samples * IDX_FIELDS:
        raise ShardFormatError(
            f"{name}: index holds {idx.size} words, expected "
            f"{num_samples * IDX_FIELDS} ({num_samples} samples x "
            f"{IDX_FIELDS} fields)"
        )
    return idx.reshape(num_samples, IDX_FIELDS)


def verify_sample(data: bytes, crc: int, shard: str, pos: int) -> bytes:
    """CRC-check one fetched extent; raises :class:`ShardCorruptError`
    naming the shard and in-shard position on mismatch."""
    if zlib.crc32(data) & 0xFFFFFFFF != (crc & 0xFFFFFFFF):
        raise ShardCorruptError(
            f"{shard}: sample {pos} content CRC mismatch "
            f"({len(data)} bytes) — the shard is corrupt at this extent "
            f"(bit rot, truncation, or a torn fetch); re-pack or "
            f"re-fetch the shard"
        )
    return data


def shard_split(num_samples: int, num_shards: int) -> List[int]:
    """Deterministic contiguous split: shard ``s`` holds
    ``base + (1 if s < rem else 0)`` samples. Returns per-shard counts."""
    if num_shards < 1:
        raise ValueError(f"num_shards={num_shards} must be >= 1")
    if num_samples < num_shards:
        raise ValueError(
            f"cannot pack {num_samples} samples into {num_shards} shards "
            f"(at least one sample per shard)"
        )
    base, rem = divmod(num_samples, num_shards)
    return [base + (1 if s < rem else 0) for s in range(num_shards)]


def write_shards(root: str, dest: str, num_shards: int,
                 verbose: bool = False) -> dict:
    """Pack ONE ImageFolder split (``root``) into ``num_shards`` packed
    shards under ``dest`` + a manifest. Deterministic: the sample order
    is the ImageFolder sorted-walk order, the split is contiguous, and
    no timestamp or hostname enters any byte — the same tree always
    yields byte-identical shards (locked by tests). Returns the
    manifest dict."""
    from dptpu.data.dataset import ImageFolderDataset

    ds = ImageFolderDataset(root)
    counts = shard_split(len(ds.samples), num_shards)
    os.makedirs(dest, exist_ok=True)
    shards = []
    g = 0
    for s, count in enumerate(counts):
        samples = ds.samples[g:g + count]
        name = shard_name(s)
        path = os.path.join(dest, name)
        index = np.zeros((count, IDX_FIELDS), dtype="<u8")
        meta = json.dumps(
            {"classes": ds.classes, "global_start": g, "format": VERSION},
            sort_keys=True, separators=(",", ":"),
        ).encode("utf-8")
        # sizes first (one stat pass) so header/index offsets are known
        # before the single streaming data pass
        sizes = [os.path.getsize(p) for p, _ in samples]
        data_len = sum(sizes)
        off = 0
        for i, ((p, label), n) in enumerate(zip(samples, sizes)):
            index[i, IDX_OFF] = off
            index[i, IDX_LEN] = n
            index[i, IDX_LABEL] = label
            index[i, IDX_FLAGS] = (
                FLAG_JPEG if p.lower().endswith(_JPEG_EXT) else 0
            )
            off += n
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            # data pass: stream each file through, CRC-ing as we go;
            # the index (with the CRCs) and header are written after
            hdr_probe = _pack_header(s, num_shards, count, meta,
                                     index.tobytes(), data_len)
            data_off = parse_header(hdr_probe, name)["data_off"]
            f.write(b"\x00" * data_off)
            for i, (p, _label) in enumerate(samples):
                with open(p, "rb") as src:
                    data = src.read()
                if len(data) != sizes[i]:
                    raise ShardFormatError(
                        f"{p}: size changed while packing "
                        f"({sizes[i]} -> {len(data)} bytes) — the source "
                        f"tree must be immutable during dptpu pack"
                    )
                index[i, IDX_CRC] = zlib.crc32(data) & 0xFFFFFFFF
                f.write(data)
            index_bytes = index.tobytes()
            header = _pack_header(s, num_shards, count, meta, index_bytes,
                                  data_len)
            f.seek(0)
            f.write(header)
            f.write(meta)
            f.write(index_bytes)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        shards.append({
            "name": name, "samples": count, "start": g,
            "bytes": data_off + data_len,
        })
        if verbose:
            print(f"  {name}: {count} samples, "
                  f"{(data_off + data_len) / 1e6:.1f} MB")
        g += count
    manifest = {
        "format": VERSION,
        "num_samples": len(ds.samples),
        "num_shards": num_shards,
        "classes": ds.classes,
        "shards": shards,
    }
    with open(os.path.join(dest, MANIFEST_NAME), "w") as f:
        json.dump(manifest, f, sort_keys=True, indent=1)
        f.write("\n")
    return manifest


def verify_shard(path: str, deep: bool = False) -> Tuple[bool, str]:
    """Integrity triage for one shard file: header CRC, meta CRC, index
    CRC; ``deep=True`` additionally CRCs every sample extent. Returns
    ``(ok, reason)`` — the checkpoint scanner's calling convention."""
    try:
        with open(path, "rb") as f:
            raw_hdr = f.read(HEADER_LEN)
            try:
                hdr = parse_header(raw_hdr, path)
            except ShardFormatError as e:
                return False, str(e)
            f.seek(hdr["meta_off"])
            meta = f.read(hdr["meta_len"])
            if zlib.crc32(meta) & 0xFFFFFFFF != hdr["meta_crc"]:
                return False, f"{path}: meta CRC mismatch"
            f.seek(hdr["index_off"])
            raw_idx = f.read(hdr["num_samples"] * IDX_FIELDS * 8)
            try:
                idx = parse_index(raw_idx, hdr["index_crc"],
                                  hdr["num_samples"], path)
            except ShardFormatError as e:
                return False, str(e)
            if deep:
                for i in range(hdr["num_samples"]):
                    f.seek(hdr["data_off"] + int(idx[i, IDX_OFF]))
                    data = f.read(int(idx[i, IDX_LEN]))
                    try:
                        verify_sample(data, int(idx[i, IDX_CRC]), path, i)
                    except ShardCorruptError as e:
                        return False, str(e)
    except OSError as e:
        return False, f"{path}: unreadable: {e}"
    return True, "ok"


class ShardSet:
    """Reader-side view of one packed split: the manifest plus lazily
    fetched per-shard extent tables, resolving a GLOBAL sample index to
    a ``(shard, extent)`` pair. Works over any :class:`Store` — local
    directory or HTTP prefix — fetching each shard's 96-byte header and
    index exactly once, by range, on first touch (an object-store-sized
    dataset never requires reading a whole shard just to look one
    sample up)."""

    def __init__(self, store_or_location, verify: bool = True):
        self.store: Store = (
            store_or_location if isinstance(store_or_location, Store)
            else open_store(store_or_location)
        )
        self.verify = verify
        manifest = json.loads(
            self.store.get_bytes(MANIFEST_NAME).decode("utf-8")
        )
        if manifest.get("format") != VERSION:
            raise ShardFormatError(
                f"{MANIFEST_NAME}: manifest format "
                f"{manifest.get('format')!r} != supported {VERSION}"
            )
        self.manifest = manifest
        self.classes: List[str] = list(manifest["classes"])
        self.num_samples: int = int(manifest["num_samples"])
        self.num_shards: int = int(manifest["num_shards"])
        self.shard_names = [s["name"] for s in manifest["shards"]]
        self.shard_counts = np.array(
            [int(s["samples"]) for s in manifest["shards"]], np.int64
        )
        self.shard_starts = np.concatenate(
            [[0], np.cumsum(self.shard_counts)[:-1]]
        )
        if int(self.shard_counts.sum()) != self.num_samples:
            raise ShardFormatError(
                f"{MANIFEST_NAME}: shard sample counts sum to "
                f"{int(self.shard_counts.sum())} != num_samples "
                f"{self.num_samples}"
            )
        self._headers: dict = {}  # shard_id -> parsed header
        self._indexes: dict = {}  # shard_id -> (n, 5) u64 extent table

    def __len__(self) -> int:
        return self.num_samples

    def locate(self, gidx: int) -> Tuple[int, int]:
        """Global index -> ``(shard_id, in-shard position)`` — the
        in-shard index map (contiguous split, so one searchsorted)."""
        if not 0 <= gidx < self.num_samples:
            raise IndexError(
                f"sample index {gidx} outside [0, {self.num_samples})"
            )
        s = int(np.searchsorted(self.shard_starts, gidx, side="right")) - 1
        return s, gidx - int(self.shard_starts[s])

    def shard_table(self, shard_id: int) -> Tuple[dict, np.ndarray]:
        """``(header, index)`` for one shard, range-fetched + CRC-
        verified on first touch and cached for the process lifetime."""
        cached = self._indexes.get(shard_id)
        if cached is not None:
            return self._headers[shard_id], cached
        name = self.shard_names[shard_id]
        hdr = parse_header(
            self.store.get_range(name, 0, HEADER_LEN), name
        )
        if hdr["num_samples"] != int(self.shard_counts[shard_id]):
            raise ShardFormatError(
                f"{name}: header says {hdr['num_samples']} samples, "
                f"manifest says {int(self.shard_counts[shard_id])} — "
                f"manifest and shard disagree"
            )
        raw = self.store.get_range(
            name, hdr["index_off"], hdr["num_samples"] * IDX_FIELDS * 8
        )
        idx = parse_index(raw, hdr["index_crc"], hdr["num_samples"], name)
        self._headers[shard_id] = hdr
        self._indexes[shard_id] = idx
        return hdr, idx

    def extent(self, gidx: int) -> dict:
        """The byte extent for global sample ``gidx``: shard name,
        ABSOLUTE file offset, length, label, content CRC, jpeg flag."""
        shard_id, pos = self.locate(gidx)
        hdr, idx = self.shard_table(shard_id)
        row = idx[pos]
        return {
            "shard_id": shard_id,
            "shard": self.shard_names[shard_id],
            "pos": pos,
            "offset": hdr["data_off"] + int(row[IDX_OFF]),
            "length": int(row[IDX_LEN]),
            "label": int(row[IDX_LABEL]),
            "crc": int(row[IDX_CRC]),
            "is_jpeg": bool(int(row[IDX_FLAGS]) & FLAG_JPEG),
        }


from dptpu.data.sampler import ShardedSampler  # noqa: E402  (leaf import)


class ShardLocalitySampler(ShardedSampler):
    """The seeded SHARD-LEVEL shuffle + in-shard shuffle epoch order:
    visit shards in a ``(seed, epoch)``-seeded permutation, and within
    each shard visit its samples in a second seeded permutation — so a
    streaming reader drains one shard's extents before touching the
    next (sequential I/O, one shard resident at a time) instead of
    striding the whole dataset.

    Still a PURE function of ``(seed, epoch)`` — the resilience replay
    contract is untouched, so mid-epoch ``--resume`` replays exactly;
    and per-``(seed, epoch, index)`` pixels are identical to any other
    sampler (the dataset's index space is unchanged — only the visit
    ORDER differs from the default global permutation)."""

    def __init__(self, shard_set: ShardSet, num_shards: int = 1,
                 shard_index: int = 0, shuffle: bool = True, seed: int = 0,
                 drop_last: bool = False):
        super().__init__(
            len(shard_set), num_shards=num_shards, shard_index=shard_index,
            shuffle=shuffle, seed=seed, drop_last=drop_last,
        )
        self._starts = shard_set.shard_starts.copy()
        self._counts = shard_set.shard_counts.copy()

    def _epoch_order(self, epoch: int) -> np.ndarray:
        if not self.shuffle:
            return np.arange(self.num_examples)
        rs = np.random.RandomState(self.seed + epoch)
        parts = []
        for s in rs.permutation(len(self._counts)):
            parts.append(
                int(self._starts[s]) + rs.permutation(int(self._counts[s]))
            )
        return np.concatenate(parts)
