"""Multi-process shared-memory batch ring for the input pipeline.

The thread-pool loader flatlines on multi-core hosts: PIL/libjpeg release
the GIL for the pixel work, but header parsing, RNG, numpy bookkeeping and
the futures machinery all serialize on it (HOSTBENCH r5: 542.8 img/s at 8
threads vs 516.6 at 1 — the pool buys ~5%). Worker PROCESSES sidestep the
GIL entirely; the classic cost of torch-style workers — pickling every
decoded batch through a pipe — is removed by giving the workers the
loader's preallocated batch memory itself:

* a ring of ``slots`` batch buffers lives in ONE
  ``multiprocessing.shared_memory`` segment per array (images uint8
  ``[slots, B, H, W, C]``, labels int32 ``[slots, B]``), named
  ``dptpu_ring_*`` so /dev/shm hygiene checks can attribute them;
* workers run the SAME span-decode path as thread mode
  (``dataset.get_into`` → the native decoder's caller-supplied output
  row), writing JPEG decodes directly into their slot's rows — pixels
  never cross a pipe, only tiny ``(slot, task, offsets, indices,
  epoch)`` tasks and ``(done, ...)`` acks do;
* per-item augmentation RNG is derived from ``(seed, epoch, index)``
  exactly as in thread mode, so process and thread loaders yield
  BIT-IDENTICAL batches for the same seed (tests/test_shm_loader.py);
* CACHE-AFFINITY SPAN ROUTING: each worker owns a task queue, and
  ``submit`` routes every sample index to the worker picked by a stable
  hash of the index — so when the decode cache is per-worker sharded
  (``DPTPU_CACHE_SCOPE=sharded``) the same worker re-decodes the same
  images every epoch and its shard stays warm across reshuffles
  (previously ~1/N of hits landed on the wrong shard and re-decoded).
  Groups are rebalanced down to ``ceil(B/N)`` items so one hot worker
  cannot serialize a batch — the moved items decode cold in sharded
  scope and hit anyway in pooled scope;
* ZERO-COPY HANDOFF: ``collect(leased=True)`` returns numpy VIEWS into
  the slot plus a :class:`SlotLease`; the slot re-enters the free ring
  only when the lease is released (``DevicePrefetcher`` releases it
  after the device transfer of that batch completes), eliminating the
  parent's per-batch copy-out entirely — ``feed_stats`` reports
  ``bytes_copied_per_batch = 0``. The legacy copy-out path remains the
  default for consumers that retain batches (``leased=False``);
* DECODE-AHEAD PIPELINING: the ring depth is decoupled from the lease
  depth (``DPTPU_RING_DEPTH``) and the DataLoader pre-issues spans for
  up to ``DPTPU_DECODE_AHEAD`` batches the moment slots free, so the
  per-worker queues always hold the NEXT batches' spans — workers roll
  straight across batch boundaries instead of draining while the
  parent collects, and per-slot completion counters absorb spans
  finishing out of batch order. ``collect`` still consumes in batch
  order (the epoch contract is unchanged);
* SPECULATIVE STRAGGLER RE-ISSUE (``DPTPU_SPECULATE``, default on):
  when a collect has waited ``speculate_after_s`` on a slot whose last
  spans sit on a stalled worker, the parent re-issues those spans to
  IDLE workers. First-writer-wins is safe under the ``(seed, epoch,
  index)`` bit-identity contract — both copies write the SAME bytes
  into the SAME disjoint rows, so even racing writes cannot tear. The
  late twin's ack is recognized as a GHOST (its task is no longer
  pending) and, until it arrives, the slot is QUARANTINED rather than
  recycled: a ghost still writing its (old, identical) bytes must
  never overlap a NEW batch decoded into a reused slot;
* COLD-EPOCH BYTE READAHEAD (``DPTPU_READAHEAD``, default on): at span
  pre-issue time the parent advises the kernel
  (``posix_fadvise(WILLNEED)`` via the native ``dptpu_file_readahead``
  or the ``os`` fallback) to start pulling the JPEG bytes of the
  pre-issued batches into the page cache — the workers' reads land
  warm ``DPTPU_DECODE_AHEAD`` batches later, hiding cold-epoch I/O
  latency under decode of the current batches.

SUPERVISION (dptpu.resilience): the pool is watched, not trusted. Every
result wait runs under a deadline (``DPTPU_WORKER_TIMEOUT_S``); a dead
worker (OOM-kill, native crash, SIGKILL) or a silent hang triggers a pool
restart — workers are killed, queues rebuilt, and every UNACKED span
re-enqueued to its assigned worker, which is safe because spans are
deterministic pure writes into disjoint rows (re-decoding produces the
same bytes). A span that ERRORS is retried ``DPTPU_SPAN_RETRIES`` times
(covers transient I/O) before the worker's traceback is re-raised in the
parent. After ``DPTPU_POOL_RESTARTS`` CONSECUTIVE restarts without
progress the pool raises :class:`WorkerPoolBroken`, and the DataLoader
degrades to thread mode with a loud warning instead of killing a
multi-hour job. An ``atexit`` hook unlinks the SharedMemory segments of
any pipeline the parent abandons without ``close()`` (an aborted run
must not leak ``/dev/shm`` until reboot).

Workers are spawned (not forked) by default: the parent holds JAX/XLA
runtime threads whose locks must not be forked mid-flight. Spawn pickles
the dataset once per worker; a sharded ``DecodeCache`` crosses that
boundary as budget-only (each worker warms its own shard, budget divided
by the pool size — see ``dptpu/data/cache.py``), while a pooled
``ShmDecodeCache`` crosses as an attach spec to the one shared slab that
also SURVIVES pool restarts warm (``dptpu/data/shm_cache.py``).
"""

from __future__ import annotations

import atexit
import queue as _queue
import sys
import time
import traceback
import weakref
from typing import Optional, Tuple

import numpy as np

from dptpu.data.dataset import _copy_checked
from dptpu.data.shm_cache import close_segment, create_named_segment
from dptpu.envknob import env_float, env_int
from dptpu.resilience.faults import FaultPlan

SEGMENT_PREFIX = "dptpu_ring"

_LIVE_PIPELINES: "weakref.WeakSet" = weakref.WeakSet()
_ATEXIT_REGISTERED = False

# slots still leased (never released by the consumer, never revoked by a
# reset) when their pipeline closed — a consumer-side protocol bug; the
# conftest session fixture fails the suite when this moves
_LEASE_LEAKS = 0


def leaked_lease_count() -> int:
    """Slots that were still leased when their pipeline closed, summed
    over every pipeline this process has closed. A lease the consumer
    released (or a ``reset`` revoked — the abandoned-epoch path) never
    counts; only close-with-lease-outstanding does."""
    return _LEASE_LEAKS


def _atexit_close_all():
    """Unlink shared-memory segments of pipelines the parent never closed
    (otherwise an aborted run leaks /dev/shm until reboot)."""
    for pipe in list(_LIVE_PIPELINES):
        try:
            pipe.close()
        except Exception:
            pass


def _register_pipeline(pipe):
    global _ATEXIT_REGISTERED
    _LIVE_PIPELINES.add(pipe)
    if not _ATEXIT_REGISTERED:
        atexit.register(_atexit_close_all)
        _ATEXIT_REGISTERED = True


def live_segment_names():
    """Ring segment names owned by still-open pipelines in THIS process
    (the conftest /dev/shm leak guard's allowlist)."""
    out = set()
    for pipe in list(_LIVE_PIPELINES):
        if not pipe._closed:
            out.add(pipe._shm_imgs.name.lstrip("/"))
            out.add(pipe._shm_labels.name.lstrip("/"))
    return out


class WorkerPoolBroken(RuntimeError):
    """The pool failed ``max_restarts`` consecutive times — the caller
    should degrade to thread mode rather than keep flogging it."""


def _affinity_of(index: int, num_workers: int) -> int:
    """Stable index → worker hash (Fibonacci multiplicative): identical
    across epochs, runs and pool restarts, so a worker's sharded cache
    keeps seeing the same images no matter how the sampler reshuffles."""
    return ((index * 2654435761) >> 7) % num_workers


def routing_of(dataset, span_affinity: bool) -> str:
    """Which affinity key routes spans to workers: ``"shard"`` (a
    packed-shard dataset exposing ``shard_of`` — whole-shard-per-worker
    routing on a stable hash of the shard id), ``"index"`` (per-sample
    hash) or ``"contiguous"`` (affinity off). The ONE derivation —
    ``ShmBatchPipeline`` routes by it and ``DataLoader.feed_stats``
    reports it (also before the lazy pipeline exists), so the reported
    mode can never diverge from the mode actually used."""
    if not span_affinity:
        return "contiguous"
    return "shard" if getattr(dataset, "shard_of", None) is not None \
        else "index"


def _affinity_spans(batch_indices, num_workers: int, affinity_key=None):
    """Split one batch into per-worker spans by affinity, then
    rebalance any group above ``ceil(B/N)`` down to the least-loaded
    workers (the idle-worker fallback: utilization beats affinity for
    the overflow items). Returns ``[(wid, offsets, indices), ...]``.

    ``affinity_key`` maps a sample index to the value that is hashed
    (default: the index itself). Packed-shard datasets pass their
    ``shard_of`` so a WHOLE shard's extents land on one worker — the
    shard-level decode-cache affinity (ROADMAP data-plane follow-on):
    the hash is stable in the SHARD id, so a shard's samples stay
    together no matter how the sampler interleaves shards, instead of
    scattering one shard's extent stream across every worker."""
    n = len(batch_indices)
    if num_workers <= 1:
        return [(0, tuple(range(n)),
                 tuple(int(i) for i in batch_indices))]
    groups = [([], []) for _ in range(num_workers)]
    for o, raw in enumerate(batch_indices):
        idx = int(raw)
        key = idx if affinity_key is None else affinity_key(idx)
        g = groups[_affinity_of(int(key), num_workers)]
        g[0].append(o)
        g[1].append(idx)
    cap = -(-n // num_workers)
    sizes = [len(g[0]) for g in groups]
    for w in range(num_workers):
        while sizes[w] > cap:
            t = min(range(num_workers), key=lambda k: sizes[k])
            if sizes[t] >= cap:
                break
            groups[t][0].append(groups[w][0].pop())
            groups[t][1].append(groups[w][1].pop())
            sizes[w] -= 1
            sizes[t] += 1
    return [
        (w, tuple(offs), tuple(idxs))
        for w, (offs, idxs) in enumerate(groups)
        if offs
    ]


def _contiguous_spans(batch_indices, num_workers: int):
    """Legacy span split (affinity off): contiguous ceil(B/N) chunks,
    chunk k → worker k."""
    n = len(batch_indices)
    span = -(-n // num_workers)
    out = []
    for k, o in enumerate(range(0, n, span)):
        idxs = tuple(int(i) for i in batch_indices[o:o + span])
        out.append((k % num_workers, tuple(range(o, o + len(idxs))), idxs))
    return out


def _worker_main(worker_id, dataset, imgs_name, labels_name, slots,
                 batch_size, item_shape, seed, num_workers, task_q, res_q):
    """Decode-worker loop: pull ``(slot, task, offsets, indices, epoch)``
    spans from THIS worker's queue, write pixels/labels straight into the
    shared ring, ack on ``res_q``.

    Runs in a spawned child — keep imports local and never touch JAX
    (``_copy_checked`` comes from the module import: dataset.py is
    numpy/stdlib-only, so hoisting it out of the hot loop is safe).
    """
    from multiprocessing import shared_memory

    # NOTE: attaching re-registers the names with the resource tracker the
    # children inherit from the parent — an idempotent set-add, so the
    # parent's close()+unlink() still cleans up exactly once. Do NOT
    # unregister here: that would strip the parent's registration and leak
    # the segments if the parent dies uncleanly.
    shm_imgs = shared_memory.SharedMemory(name=imgs_name)
    shm_labels = shared_memory.SharedMemory(name=labels_name)
    imgs = np.ndarray((slots, batch_size) + tuple(item_shape), np.uint8,
                      buffer=shm_imgs.buf)
    labels = np.ndarray((slots, batch_size), np.int32,
                        buffer=shm_labels.buf)
    cache = getattr(dataset, "decode_cache", None)
    if cache is not None and num_workers > 1:
        # keep the configured cache_bytes a TOTAL budget across the pool
        # (a pooled ShmDecodeCache makes this a documented no-op: its
        # slab is already one shared budget)
        cache.scale_budget(num_workers)
    get_into = getattr(dataset, "get_into", None)
    get = getattr(dataset, "get", None)
    # worker-side fault injection (io_error / worker_hang) re-parses the
    # inherited DPTPU_FAULT env — nothing fault-related crosses the pickle
    try:
        fault_plan = FaultPlan.from_env()
    except ValueError:
        fault_plan = None  # the parent raises the parse error loudly
    try:
        while True:
            task = task_q.get()
            if task is None:
                break
            slot, task_id, offsets, idxs, epoch = task
            try:
                t_span = time.monotonic()
                for off, index in zip(offsets, idxs):
                    if fault_plan is not None:
                        fault_plan.worker_decode_hook(worker_id, index)
                    rng = np.random.default_rng([seed, epoch, index])
                    row = imgs[slot, off]
                    if get_into is not None:
                        labels[slot, off] = get_into(index, rng, row)
                    else:
                        if get is not None:
                            img, lab = get(index, rng)
                        else:
                            img, lab = dataset[index]
                        _copy_checked(row, img, index)
                        labels[slot, off] = lab
                hits, misses = (cache.hits, cache.misses) if cache else (0, 0)
                # the span's own decode wall time rides the ack — the
                # straggler controller's per-worker speed signal,
                # unpolluted by queue wait or the parent's drain cadence
                res_q.put(("done", worker_id, slot, task_id, hits, misses,
                           time.monotonic() - t_span))
            except BaseException:
                res_q.put(
                    ("error", worker_id, slot, task_id,
                     traceback.format_exc())
                )
    except (EOFError, OSError, KeyboardInterrupt):
        pass  # parent went away / interrupt: exit quietly
    finally:
        imgs = labels = None
        shm_imgs.close()
        shm_labels.close()


class SlotLease:
    """Consumer-held claim on one ring slot: the views ``collect``
    returned stay byte-stable until ``release()``. Releasing twice (or
    after the ring reset/retired the slot underneath — the generation
    check) is a no-op, so the DataLoader's after-yield backstop and the
    DevicePrefetcher's after-transfer release compose safely."""

    __slots__ = ("_pipe", "slot", "_gen", "released")

    def __init__(self, pipe, slot: int, gen: int):
        self._pipe = pipe
        self.slot = slot
        self._gen = gen
        # single-writer handoff: only the consumer that holds the lease
        # flips it (idempotence guard); the ring side never writes it —
        # revocation happens through the generation counter instead
        self.released = False  # owned-by: consumer

    def release(self):
        if self.released:
            return
        self.released = True
        self._pipe._release_slot(self.slot, self._gen)


class ShmBatchPipeline:
    """The process-mode backend of ``DataLoader``: shared-memory slot ring
    + supervised persistent worker pool + per-worker task queues (span
    affinity) + one shared ack queue.

    Protocol (driven by ``DataLoader._epoch_process``): ``submit`` fans a
    batch's indices out as one span task per worker into a free slot;
    ``collect`` blocks until that slot's spans are acked, then either
    copies the rows out and recycles the slot immediately (legacy), or —
    ``leased=True`` — hands back zero-copy views plus a
    :class:`SlotLease` and recycles only on release. ``reset`` drains an
    abandoned epoch's in-flight work, revokes outstanding leases (their
    late ``release()`` calls no-op via the generation check) and marks
    every slot free.

    Supervision bookkeeping: ``_pending[slot][task_id] = task`` holds
    every unacked span — exactly what a pool restart must re-enqueue; it
    is the single source of truth for "work the consumer is still owed".
    """

    def __init__(self, dataset, batch_size: int, item_shape: Tuple[int, ...],
                 num_workers: int, seed: int, slots: int,
                 mp_start: str = "spawn",
                 timeout_s: Optional[float] = None,
                 max_restarts: Optional[int] = None,
                 span_retries: Optional[int] = None,
                 span_affinity: bool = True,
                 speculate: bool = True,
                 speculate_after_s: float = 0.5,
                 readahead: bool = True):
        import multiprocessing as mp

        self.batch_size = batch_size
        self.item_shape = tuple(int(d) for d in item_shape)
        self.num_workers = max(1, num_workers)
        self.slots = max(2, slots)
        self.span_affinity = span_affinity
        # shard-level cache affinity: a packed-shard dataset exposes
        # shard_of, and hashing THAT (not the sample index) routes a
        # whole shard's extents to one worker (see _affinity_spans)
        self.routing = routing_of(dataset, span_affinity)
        self._affinity_key = (
            dataset.shard_of if self.routing == "shard" else None
        )
        self._dataset = dataset
        self._seed = seed
        self._has_cache = getattr(dataset, "decode_cache", None) is not None
        # supervision knobs (ctor beats env beats default)
        self.timeout_s = (
            timeout_s if timeout_s is not None
            else env_float("DPTPU_WORKER_TIMEOUT_S", 120.0)
        )
        self.max_restarts = (
            max_restarts if max_restarts is not None
            else env_int("DPTPU_POOL_RESTARTS", 3)
        )
        self.span_retries = (
            span_retries if span_retries is not None
            else env_int("DPTPU_SPAN_RETRIES", 2)
        )
        if self.timeout_s <= 0:
            raise ValueError(
                f"DPTPU_WORKER_TIMEOUT_S={self.timeout_s} must be > 0 "
                f"seconds"
            )
        if self.max_restarts < 0 or self.span_retries < 0:
            raise ValueError(
                "DPTPU_POOL_RESTARTS and DPTPU_SPAN_RETRIES must be >= 0"
            )
        item_bytes = int(np.prod(self.item_shape))
        self._ctx = mp.get_context(mp_start)
        self._shm_imgs = create_named_segment(
            SEGMENT_PREFIX,
            max(1, self.slots * batch_size * item_bytes),
        )
        self._shm_labels = create_named_segment(
            SEGMENT_PREFIX, self.slots * batch_size * 4
        )
        self._imgs = np.ndarray(
            (self.slots, batch_size) + self.item_shape, np.uint8,
            buffer=self._shm_imgs.buf,
        )
        self._labels = np.ndarray(
            (self.slots, batch_size), np.int32, buffer=self._shm_labels.buf
        )
        self._outstanding = [0] * self.slots  # span acks still in flight
        self._pending = {s: {} for s in range(self.slots)}  # task_id -> task
        self._retries = {}  # (slot, task_id) -> attempts so far
        self._free = list(range(self.slots))
        self._leased = set()  # slots held by unreleased SlotLeases
        self._slot_gen = [0] * self.slots  # stale-lease guard
        self._worker_cache = {}  # worker_id -> latest (hits, misses)
        self._cache_base = [0, 0]  # counts folded in from killed pools
        self._restarts_total = 0
        self._span_retries_total = 0
        self._consec_failures = 0
        self._bytes_copied = 0  # parent-side copy-out bytes (legacy path)
        self._collects = 0
        # decode-ahead / speculation bookkeeping
        self.speculate = speculate and self.num_workers > 1
        self.speculate_after_s = speculate_after_s
        self._worker_load = [0] * self.num_workers  # unacked issues per q
        self._extra_issues = [0] * self.slots  # unacked DUPLICATE issues
        self._quarantine = set()  # freed slots awaiting ghost acks
        self._speculated = set()  # (slot, task_id) already re-issued
        self._straggler_reissues_total = 0
        # straggler-control seam (dptpu/resilience/elastic.py): every
        # done ack carries the span's worker-side decode duration —
        # charged to the worker that DID the decode — drained by the
        # controller each tick; a re-split routes future affinity AWAY
        # from a slow worker and the eviction hook feeds the
        # supervisor's restart policy
        self._latency_obs = []  # [(acking_worker, span_decode_s), ...]
        self._routed_away = set()  # workers the affinity router avoids
        self._resplits_total = 0
        self._evictions_total = 0
        self._io_wait_s = 0.0  # parent time blocked in collect waits
        self._occ_sum = 0  # ring-occupancy accumulator (sampled at collect)
        self._occ_n = 0
        # cold-epoch byte readahead: fadvise the pre-issued spans' JPEG
        # files so worker reads land in a warm page cache (file-backed
        # datasets only — synthetic ones have no paths to advise).
        # Advised-once dedup is a per-index BITMAP, not a set of path
        # strings: at ImageNet scale the strings would pin hundreds of
        # MB of parent RSS for the pipeline's lifetime
        self._readahead = readahead
        self._sample_paths = getattr(dataset, "samples", None)
        # a STREAMING shard dataset owns its own I/O engine (O_DIRECT
        # ring / store range fetch into the /dev/shm byte slab,
        # dptpu/data/stream.py): pre-issue routes to its
        # ``prefetch_extents`` INSTEAD of fadvise — WILLNEED would
        # repopulate the page cache the O_DIRECT ring just bypassed.
        # Such datasets expose no ``samples`` path list, so the two
        # paths are mutually exclusive by construction (and asserted in
        # DataLoader.feed_stats).
        self._prefetch_extents = getattr(dataset, "prefetch_extents", None)
        self._readahead_done = (
            bytearray(len(self._sample_paths))
            if self._sample_paths is not None
            and self._prefetch_extents is None else None
        )
        self._closed = False
        self._start_workers()
        _register_pipeline(self)

    def _start_workers(self):
        """(Re)create the task/ack queues and spawn the worker pool —
        queues are rebuilt with the pool because a SIGKILLed worker can
        leave a queue's internal pipe in a torn state."""
        # straggler detection baseline: a worker is SUSPECT once it has
        # gone speculate_after_s without acking (reset with the pool)
        self._worker_last_ack = [time.monotonic()] * self.num_workers
        self._task_qs = [self._ctx.Queue() for _ in range(self.num_workers)]
        self._res_q = self._ctx.Queue()
        self._procs = [
            self._ctx.Process(
                target=_worker_main,
                args=(wid, self._dataset, self._shm_imgs.name,
                      self._shm_labels.name, self.slots, self.batch_size,
                      self.item_shape, self._seed, self.num_workers,
                      self._task_qs[wid], self._res_q),
                daemon=True,
                name=f"dptpu-data-{wid}",
            )
            for wid in range(self.num_workers)
        ]
        for p in self._procs:
            p.start()

    # -- submission / collection -------------------------------------------

    def free_slot_count(self) -> int:
        """Slots available to ``submit`` right now (the DataLoader's
        pre-issue pump gates on this instead of racing the exception)."""
        return len(self._free)

    def ghost_issues_in_flight(self) -> bool:
        """True while any speculated duplicate issue is still unacked —
        quarantined slots can only re-enter the free ring once these
        drain (or a pool restart vaporizes them)."""
        return any(self._extra_issues)

    def drain_one_ack(self):
        """Process ONE worker ack under the watchdog — the pump's
        escape hatch when every free slot is ghost-quarantined: the ack
        (or the watchdog's restart) is what frees a slot."""
        self._handle(self._next_result(), mode="normal")

    def submit(self, batch_indices, epoch: int) -> Tuple[int, int]:
        """Fan one batch out as affinity-routed span tasks into a free
        slot; returns ``(slot, n_valid)``. The caller's issue-ahead
        window plus its unreleased leases must not exceed ``slots``
        (DataLoader sizes the ring accordingly)."""
        if not self._free:
            raise RuntimeError(
                f"no free batch slot (ring of {self.slots}, "
                f"{len(self._leased)} leased, {len(self._quarantine)} "
                f"ghost-quarantined, rest in flight) — issue-ahead depth "
                f"plus unreleased leases exceeded the ring size"
            )
        slot = self._free.pop()
        # drop the previous tenant's speculation records: (slot, task_id)
        # pairs recur when slots are reused, and a stale entry would
        # silently veto re-issue for the NEW batch's spans (safe to drop
        # here — a slot re-enters the free ring only once its ghost
        # issues have fully drained)
        self._speculated = {k for k in self._speculated if k[0] != slot}
        spans = (
            _affinity_spans(batch_indices, self.num_workers,
                            self._affinity_key)
            if self.span_affinity
            else _contiguous_spans(batch_indices, self.num_workers)
        )
        if self._routed_away:
            # straggler route-away (the affinity seam): spans headed for
            # a worker the controller re-split divert to the least-
            # loaded healthy workers — planned loads tracked per span,
            # so a batch's diverted spans SPREAD instead of all landing
            # on whoever was idlest at remap time. Affinity resumes
            # when the controller restores the worker (recovered) or a
            # pool restart installs a fresh one.
            healthy = [w for w in range(self.num_workers)
                       if w not in self._routed_away]
            if healthy:
                planned = dict.fromkeys(healthy, 0)
                remapped = []
                for wid, offs, idxs in spans:
                    if wid in self._routed_away:
                        t = min(healthy, key=lambda k:
                                self._worker_load[k] + planned[k])
                        planned[t] += 1
                        wid = t
                    remapped.append((wid, offs, idxs))
                spans = remapped
        for task_id, (wid, offsets, idxs) in enumerate(spans):
            task = (slot, task_id, offsets, idxs, epoch, wid)
            self._pending[slot][task_id] = task
            self._task_qs[wid].put(task[:5])
            self._worker_load[wid] += 1
        self._outstanding[slot] = len(self._pending[slot])
        if self._readahead:
            self._issue_readahead(batch_indices)
        return slot, len(batch_indices)

    def _issue_readahead(self, batch_indices):
        """Parent-side cold-epoch byte prefetch: advise the kernel to
        start reading this (pre-issued) batch's JPEG files NOW, so the
        worker that decodes them ``decode_ahead`` batches from now finds
        the bytes already in the page cache. Each path is advised once
        per pipeline — after the first epoch the cache is as warm as it
        will get and repeated advice is pure syscall overhead.

        Shard-streaming datasets take the OTHER branch: their extents
        are staged into the /dev/shm byte slab by their own engine
        (every pre-issue, not once — the slab evicts), and fadvise
        never runs."""
        if self._prefetch_extents is not None:
            self._prefetch_extents(batch_indices)
            return
        samples = self._sample_paths
        if samples is None:
            return
        from dptpu.data.native_image import file_readahead

        done = self._readahead_done
        for raw in batch_indices:
            i = int(raw)
            if done[i]:
                continue
            done[i] = 1
            file_readahead(samples[i][0])

    def collect(self, slot: int, out_rows: int, leased: bool = False):
        """Wait for ``slot``'s spans, then hand the rows to the consumer:
        ``leased=False`` copies them out (consumer owns the copies, slot
        recycles immediately); ``leased=True`` returns zero-copy VIEWS
        plus a :class:`SlotLease` — the slot recycles only on
        ``lease.release()``. Raises the worker's decode error, with its
        traceback, once its retry budget is spent.

        Acks are processed for WHATEVER slot they belong to while
        waiting (out-of-order span completion); and once the wait has
        lasted ``speculate_after_s``, the remaining spans of THIS slot
        are re-issued to idle workers (straggler speculation)."""
        t0 = time.monotonic()

        def _tick():
            # re-checked every poll (a no-op pass is a few comparisons):
            # the first attempt may find no healthy target yet — e.g.
            # every worker still busy or warming up — and a straggler is
            # only recognizable once its peers pull ahead
            if self.speculate and time.monotonic() - t0 \
                    >= self.speculate_after_s:
                self._speculate_slot(slot)

        while self._outstanding[slot] > 0:
            self._handle(self._next_result(tick=_tick), mode="normal")
        self._io_wait_s += time.monotonic() - t0
        self._occ_sum += self.slots - len(self._free)
        self._occ_n += 1
        self._collects += 1
        if leased:
            self._leased.add(slot)
            return (self._imgs[slot, :out_rows],
                    self._labels[slot, :out_rows],
                    SlotLease(self, slot, self._slot_gen[slot]))
        imgs = np.array(self._imgs[slot, :out_rows])
        labels = np.array(self._labels[slot, :out_rows])
        self._bytes_copied += imgs.nbytes + labels.nbytes
        self._recycle_slot(slot)
        return imgs, labels, None

    def _recycle_slot(self, slot: int):
        """Return a consumed slot to the free ring — unless a speculated
        ghost write may still be in flight for it, in which case it is
        QUARANTINED until the ghost acks (``_ghost_ack``): the ghost's
        bytes are identical to what the slot held, but would corrupt a
        NEW batch decoded into the reused slot."""
        if self._extra_issues[slot] > 0:
            self._quarantine.add(slot)
        else:
            self._free.append(slot)

    def _ghost_ack(self, slot: int):
        """Account one DUPLICATE ack (speculated twin, or the late ack
        of a span a retry/salvage already satisfied) and release the
        slot from quarantine once no ghost writer remains."""
        if self._extra_issues[slot] > 0:
            self._extra_issues[slot] -= 1
        if slot in self._quarantine and self._extra_issues[slot] == 0:
            self._quarantine.discard(slot)
            self._free.append(slot)

    def _speculate_slot(self, slot: int):
        """Re-issue ``slot``'s still-pending spans when their assigned
        worker looks STALLED — no ack from it within the speculation
        window — to the least-loaded HEALTHY worker (one duplicate per
        span, ever). The assigned worker keeps its copy — whichever
        finishes first completes the span (identical bytes, so even a
        racing write is benign) and the loser's ack is absorbed as a
        ghost. Healthy-target gating is what keeps this safe on a
        uniformly slow cold batch: when every worker is busy-but-acking
        there is no suspect, and when every worker is suspect there is
        no target — either way no decode work is doubled."""
        now = time.monotonic()
        # suspect = OWES work and has not acked within the window; a
        # worker with nothing queued is idle-HEALTHY (a drained queue
        # also goes quiet, and it is exactly the re-issue target)
        suspect = [
            self._worker_load[w] > 0
            and now - self._worker_last_ack[w] >= self.speculate_after_s
            for w in range(self.num_workers)
        ]
        healthy = [w for w in range(self.num_workers) if not suspect[w]]
        if not healthy:
            return
        for task_id, task in list(self._pending[slot].items()):
            if (slot, task_id) in self._speculated:
                continue
            assigned = task[5]
            if not suspect[assigned]:
                continue  # its worker is alive and acking: just slow us
            targets = [w for w in healthy if w != assigned]
            if not targets:
                continue
            w = min(targets, key=lambda k: self._worker_load[k])
            self._speculated.add((slot, task_id))
            self._extra_issues[slot] += 1
            self._worker_load[w] += 1
            self._straggler_reissues_total += 1
            self._task_qs[w].put(task[:5])

    def _release_slot(self, slot: int, gen: int):
        """SlotLease callback: recycle a leased slot. Generation-checked
        so a lease that outlived a ``reset``/``close`` (abandoned epoch,
        degrade-to-thread) is silently void instead of double-freeing."""
        if self._closed or gen != self._slot_gen[slot] \
                or slot not in self._leased:
            return
        self._leased.discard(slot)
        self._slot_gen[slot] += 1
        self._recycle_slot(slot)

    def reset(self):
        """Reclaim the ring after an abandoned epoch: wait out (or, on a
        restart, simply drop) in-flight work — INCLUDING ghost acks from
        speculated twins, which must drain before a slot may be reused —
        revoke outstanding leases, and mark every slot free. Errors for
        batches nobody will consume are discarded."""
        while any(self._outstanding) or any(self._extra_issues):
            self._handle(self._next_result(requeue=False), mode="discard")
        self._free = list(range(self.slots))
        self._quarantine.clear()
        self._leased.clear()
        self._slot_gen = [g + 1 for g in self._slot_gen]
        for spans in self._pending.values():
            spans.clear()
        self._retries.clear()
        self._speculated.clear()

    def kill_worker(self, index: int = 0) -> Optional[int]:
        """Fault-injection/debug hook: SIGKILL one live worker process
        (the supervisor must then restart the pool and re-enqueue its
        span). Returns the killed pid, or None if nothing was alive.

        Synchronous by design: the join guarantees the death is visible
        to the very next liveness check, so a chaos run deterministically
        exercises the restart path instead of racing a fast epoch."""
        alive = [p for p in self._procs if p.is_alive()]
        if not alive:
            return None
        p = alive[index % len(alive)]
        pid = p.pid
        p.kill()
        p.join(timeout=5.0)
        return pid

    # -- straggler control seam (dptpu/resilience/elastic.py) ---------------

    def drain_latency_observations(self):
        """``[(worker_id, span decode seconds), ...]`` since the last
        drain — the straggler controller's input. Durations are
        measured INSIDE the worker (stamped on the ack), so the signal
        reads pure per-worker decode speed, never the parent's drain
        cadence or queue depth."""
        obs, self._latency_obs = self._latency_obs, []
        return obs

    def resplit_worker(self, worker_id: int) -> int:
        """Controller escalation 1: re-issue worker ``worker_id``'s
        entire pending span tail to the least-loaded healthy workers NOW
        (the speculation machinery without its time gate — duplicate
        acks absorb as ghosts, first-writer-wins keeps bit-identity)
        and steer future affinity away from it until it is evicted or
        recovers. Returns the number of spans re-issued."""
        if not 0 <= worker_id < self.num_workers:
            raise ValueError(
                f"resplit_worker({worker_id}): pool has "
                f"{self.num_workers} workers"
            )
        targets = [w for w in range(self.num_workers)
                   if w != worker_id and w not in self._routed_away]
        if not targets:
            return 0  # nobody healthy to take the tail
        n = 0
        for slot, spans in self._pending.items():
            for task_id, task in list(spans.items()):
                if task[5] != worker_id \
                        or (slot, task_id) in self._speculated:
                    continue
                t = min(targets, key=lambda k: self._worker_load[k])
                self._speculated.add((slot, task_id))
                self._extra_issues[slot] += 1
                self._worker_load[t] += 1
                self._straggler_reissues_total += 1
                self._task_qs[t].put(task[:5])
                n += 1
        self._routed_away.add(worker_id)
        self._resplits_total += 1
        return n

    def restore_worker(self, worker_id: int):
        """Controller de-escalation: a re-split worker whose fresh
        observations read healthy again rejoins the affinity router."""
        self._routed_away.discard(worker_id)

    def evict_worker(self, worker_id: int) -> Optional[int]:
        """Controller escalation 2 — the supervisor's eviction policy:
        SIGKILL the worker; the watchdog's pool restart re-enqueues its
        unacked spans (the proven worker_kill recovery path). The dead
        worker stays routed-away until the restart actually installs
        its replacement (``_restart_pool`` clears the set) — routing
        spans at a corpse's queue would stall every batch behind the
        speculation window."""
        if not 0 <= worker_id < len(self._procs):
            return None
        p = self._procs[worker_id]
        pid = p.pid if p.is_alive() else None
        if pid is not None:
            p.kill()
            p.join(timeout=5.0)
        self._evictions_total += 1
        return pid

    # -- supervision --------------------------------------------------------

    def _next_result(self, requeue: bool = True, tick=None):
        """Wait for one worker ack under the watchdog: a dead worker or a
        deadline with zero progress restarts the pool (re-enqueueing the
        unacked spans unless ``requeue`` is off — the reset path drops
        them instead). Liveness is checked BEFORE every wait, not only on
        timeout: a worker that dies idle would otherwise silently shrink
        the pool forever. ``tick`` (optional) is called once per poll
        interval — the straggler-speculation trigger rides it, since a
        stalled span means no result arrives to return control."""
        deadline = time.monotonic() + self.timeout_s
        while True:
            if tick is not None:
                tick()
            dead = [p for p in self._procs if not p.is_alive()]
            if dead:
                p = dead[0]
                self._restart_pool(
                    f"worker {p.name} (pid {p.pid}) died with exit "
                    f"code {p.exitcode} — killed, OOM-reaped, or a "
                    f"native crash in the decoder",
                    requeue=requeue,
                )
            elif time.monotonic() > deadline:
                self._restart_pool(
                    f"no worker progress for {self.timeout_s:.1f}s "
                    f"with {sum(self._outstanding)} span(s) in flight "
                    f"— worker hang suspected",
                    requeue=requeue,
                )
            else:
                try:
                    return self._res_q.get(timeout=min(0.2, self.timeout_s))
                except _queue.Empty:
                    continue
            if not any(self._outstanding) and not any(self._extra_issues):
                # a restart dropped all pending work AND vaporized every
                # ghost issue (the queues died with the pool); nothing
                # will ever ack, so hand back a sentinel the _handle
                # modes understand as "no-op"
                return ("none",)
            deadline = time.monotonic() + self.timeout_s

    def _restart_pool(self, reason: str, requeue: bool = True):
        """Kill + respawn the pool; re-enqueue every unacked span to its
        assigned worker (safe: spans are deterministic pure writes into
        disjoint rows — and a pooled decode cache slab survives the
        restart warm, since it belongs to the parent's dataset)."""
        self._consec_failures += 1
        if self._consec_failures > self.max_restarts:
            raise WorkerPoolBroken(
                f"data-worker pool failed {self._consec_failures} "
                f"consecutive times (budget {self.max_restarts}); last "
                f"failure: {reason}"
            )
        self._restarts_total += 1
        print(
            f"WARNING: dptpu data-worker pool restart "
            f"{self._consec_failures}/{self.max_restarts}: {reason}",
            file=sys.stderr,
        )
        for p in self._procs:
            if p.is_alive():
                p.kill()
        for p in self._procs:
            p.join(timeout=2.0)
        # salvage acks already delivered before the failure, then drop
        # the torn queues (a SIGKILL mid-put can wedge them)
        while True:
            try:
                msg = self._res_q.get_nowait()
            except Exception:
                # Empty, or a torn message from the killed worker's
                # feeder thread (UnpicklingError & friends) — either way
                # the queue is done yielding salvage; the restart's span
                # re-enqueue covers whatever was lost
                break
            if msg[0] == "done":
                self._handle(msg, mode="normal")
            # drained error acks stay pending: the restart re-enqueues
            # them, which is exactly a retry
        for q in self._task_qs + [self._res_q]:
            try:
                q.close()
                q.cancel_join_thread()
            except Exception:
                pass
        # respawned workers count hits/misses from zero: fold the dead
        # pool's last-known counts into a base so the cumulative numbers
        # feed_stats differences stay MONOTONIC across restarts (else a
        # warm post-restart epoch reads a bogus 0.0 interval hit rate)
        self._cache_base[0] += sum(
            h for h, _ in self._worker_cache.values())
        self._cache_base[1] += sum(
            m for _, m in self._worker_cache.values())
        self._worker_cache.clear()
        self._start_workers()
        # the old queues died with the pool: every in-flight issue —
        # including speculated twins — is gone, so ghost accounting
        # resets and quarantined slots are safe to reuse immediately
        self._speculated.clear()
        self._extra_issues = [0] * self.slots
        if self._quarantine:
            self._free.extend(sorted(self._quarantine))
            self._quarantine.clear()
        self._worker_load = [0] * self.num_workers
        # the whole pool is fresh: straggler verdicts start over
        self._routed_away.clear()
        if requeue:
            for spans in self._pending.values():
                for task in spans.values():
                    self._task_qs[task[5]].put(task[:5])
                    self._worker_load[task[5]] += 1
        else:
            for spans in self._pending.values():
                spans.clear()
            self._outstanding = [0] * self.slots
            self._retries.clear()

    def _handle(self, msg, mode: str = "normal"):
        """Apply one worker ack. Modes: ``normal`` (collect path — retry
        errored spans up to the budget, then raise with the worker's
        traceback), ``discard`` (reset path — drop errored spans).

        An ack for a task NO LONGER PENDING is a GHOST — the speculated
        twin (or a retry the twin beat) finishing late. Ghosts never
        touch the completion counters (a second decrement would send
        ``_outstanding`` negative and wedge ``reset``); they only settle
        the slot's quarantine accounting."""
        kind = msg[0]
        if kind == "none":  # restart-with-drop sentinel from _next_result
            return
        worker_id, slot, task_id = msg[1], msg[2], msg[3]
        if worker_id < len(self._worker_load):
            if self._worker_load[worker_id] > 0:
                self._worker_load[worker_id] -= 1
            self._worker_last_ack[worker_id] = time.monotonic()
        if kind == "done":
            self._consec_failures = 0  # the pool is making progress
            self._worker_cache[worker_id] = (msg[4], msg[5])
            if len(msg) > 6:
                # the span's worker-side decode duration, charged to
                # whichever worker actually decoded it (ghost twins
                # included — their decode speed is real signal too)
                self._latency_obs.append((worker_id, float(msg[6])))
                if len(self._latency_obs) > 4096:
                    del self._latency_obs[:2048]
            if self._pending[slot].pop(task_id, None) is None:
                self._ghost_ack(slot)
                return
            self._outstanding[slot] -= 1
            self._retries.pop((slot, task_id), None)
            return
        # kind == "error"
        task = self._pending[slot].get(task_id)
        if task is None:  # ghost twin errored after the span completed
            self._ghost_ack(slot)
            return
        if mode == "discard":
            self._outstanding[slot] -= 1
            self._pending[slot].pop(task_id, None)
            self._retries.pop((slot, task_id), None)
            return
        attempts = self._retries.get((slot, task_id), 0)
        if attempts < self.span_retries:
            self._retries[(slot, task_id)] = attempts + 1
            self._span_retries_total += 1
            print(
                f"WARNING: dptpu data worker {worker_id} errored on batch "
                f"slot {slot} span {task_id}; retrying span "
                f"({attempts + 1}/{self.span_retries})",
                file=sys.stderr,
            )
            self._task_qs[task[5]].put(task[:5])
            self._worker_load[task[5]] += 1
            # the errored copy may have been the speculated twin while
            # the assigned worker is STILL stalled: forget the
            # speculation record so a later tick may re-issue — without
            # this, the retry sits behind the stall and the span can
            # only complete via watchdog pool restart
            self._speculated.discard((slot, task_id))
            return
        raise RuntimeError(
            f"data worker {worker_id} failed while decoding (batch "
            f"slot {slot}, span {task_id}"
            + (f", after {attempts} retries" if attempts else "")
            + f"); worker traceback:\n{msg[4]}"
        )

    # -- telemetry ----------------------------------------------------------

    def cache_stats(self) -> dict:
        """Pool-wide decode-cache counters, aggregated from the latest
        per-worker ack (workers piggyback cumulative counts on every
        ``done`` message — no extra round trip)."""
        if not self._has_cache:
            return {}
        hits = self._cache_base[0] + sum(
            h for h, _ in self._worker_cache.values())
        misses = self._cache_base[1] + sum(
            m for _, m in self._worker_cache.values())
        total = hits + misses
        stats = {
            "cache_hits": hits,
            "cache_misses": misses,
            "cache_hit_rate": (hits / total) if total else 0.0,
        }
        scope = getattr(self._dataset.decode_cache, "scope", "sharded")
        stats["cache_scope"] = scope
        return stats

    def supervision_stats(self) -> dict:
        """Watchdog + straggler-control counters for feed telemetry."""
        return {
            "pool_restarts": self._restarts_total,
            "span_retries": self._span_retries_total,
            "straggler_resplits": self._resplits_total,
            "worker_evictions": self._evictions_total,
        }

    def copy_stats(self) -> dict:
        """Parent-side copy-out accounting: ``bytes_copied`` stays 0 when
        every collect was leased (the zero-copy contract the feed_stats
        ``bytes_copied_per_batch`` field reports)."""
        return {
            "bytes_copied": self._bytes_copied,
            "collects": self._collects,
        }

    def ring_stats(self) -> dict:
        """Decode-ahead telemetry, cumulative since pipeline start (the
        DataLoader folds closed pipelines' totals and turns ``io_wait_s``
        into a per-feed_stats-call interval): occupancy is sampled at
        every collect (slots in flight + leased + quarantined, out of
        ``slots``), ``io_wait_s`` is parent wall time blocked waiting
        for a slot's spans, and ``straggler_reissues`` counts
        speculative re-issues to idle workers."""
        return {
            "ring_depth": self.slots,
            "occupancy_sum": self._occ_sum,
            "occupancy_samples": self._occ_n,
            "io_wait_s": self._io_wait_s,
            "straggler_reissues": self._straggler_reissues_total,
        }

    # -- lifecycle ----------------------------------------------------------

    def close(self):
        if self._closed:
            return
        self._closed = True
        # lease-leak bookkeeping for the conftest session guard: a slot
        # still leased HERE was neither released by its consumer nor
        # revoked by a reset — a protocol bug worth failing CI over
        # (the segments themselves are still unlinked below regardless)
        global _LEASE_LEAKS
        _LEASE_LEAKS += len(self._leased)
        for q in self._task_qs:
            try:
                q.put(None)
            except Exception:
                pass
        for p in self._procs:
            p.join(timeout=1.0)
            if p.is_alive():
                p.terminate()
                p.join(timeout=2.0)
            if p.is_alive():  # hung in non-interruptible state: no mercy
                p.kill()
                p.join(timeout=2.0)
        for q in self._task_qs + [self._res_q]:
            try:
                q.close()
                q.cancel_join_thread()
            except Exception:
                pass
        self._imgs = self._labels = None  # release buffer exports first
        for shm in (self._shm_imgs, self._shm_labels):
            # an unreleased lease view makes mmap.close() raise
            # BufferError; the NAME is unlinked regardless, so nothing
            # outlives the process (see shm_cache.close_segment)
            close_segment(shm, unlink=True)
        _LIVE_PIPELINES.discard(self)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
