"""Multi-process shared-memory batch ring for the input pipeline.

The thread-pool loader flatlines on multi-core hosts: PIL/libjpeg release
the GIL for the pixel work, but header parsing, RNG, numpy bookkeeping and
the futures machinery all serialize on it (HOSTBENCH r5: 542.8 img/s at 8
threads vs 516.6 at 1 — the pool buys ~5%). Worker PROCESSES sidestep the
GIL entirely; the classic cost of torch-style workers — pickling every
decoded batch through a pipe — is removed by giving the workers the
loader's preallocated batch memory itself:

* a ring of ``slots`` batch buffers lives in ONE
  ``multiprocessing.shared_memory`` segment per array (images uint8
  ``[slots, B, H, W, C]``, labels int32 ``[slots, B]``);
* workers run the SAME span-decode path as thread mode
  (``dataset.get_into`` → the native decoder's caller-supplied output
  row), writing JPEG decodes directly into their slot's rows — pixels
  never cross a pipe, only tiny ``(slot, offset, indices, epoch)`` tasks
  and ``(done, ...)`` acks do;
* per-item augmentation RNG is derived from ``(seed, epoch, index)``
  exactly as in thread mode, so process and thread loaders yield
  BIT-IDENTICAL batches for the same seed (tests/test_shm_loader.py);
* the parent copies a completed slot out once (so consumers own their
  batches and the slot recycles immediately); that single memcpy is
  ~1-2 ms against a >100 ms decode per batch.

SUPERVISION (dptpu.resilience): the pool is watched, not trusted. Every
result wait runs under a deadline (``DPTPU_WORKER_TIMEOUT_S``); a dead
worker (OOM-kill, native crash, SIGKILL) or a silent hang triggers a pool
restart — workers are killed, queues rebuilt, and every UNACKED span
re-enqueued, which is safe because spans are deterministic pure writes
into disjoint rows (re-decoding produces the same bytes). A span that
ERRORS is retried ``DPTPU_SPAN_RETRIES`` times (covers transient I/O)
before the worker's traceback is re-raised in the parent. After
``DPTPU_POOL_RESTARTS`` CONSECUTIVE restarts without progress the pool
raises :class:`WorkerPoolBroken`, and the DataLoader degrades to thread
mode with a loud warning instead of killing a multi-hour job. An
``atexit`` hook unlinks the SharedMemory segments of any pipeline the
parent abandons without ``close()`` (an aborted run must not leak
``/dev/shm`` until reboot).

Workers are spawned (not forked) by default: the parent holds JAX/XLA
runtime threads whose locks must not be forked mid-flight. Spawn pickles
the dataset once per worker; a ``DecodeCache`` crosses that boundary as
budget-only (each worker warms its own shard, budget divided by the pool
size — see ``dptpu/data/cache.py``).
"""

from __future__ import annotations

import atexit
import queue as _queue
import sys
import time
import traceback
import weakref
from typing import Optional, Tuple

import numpy as np

from dptpu.envknob import env_float, env_int
from dptpu.resilience.faults import FaultPlan

_LIVE_PIPELINES: "weakref.WeakSet" = weakref.WeakSet()
_ATEXIT_REGISTERED = False


def _atexit_close_all():
    """Unlink shared-memory segments of pipelines the parent never closed
    (otherwise an aborted run leaks /dev/shm until reboot)."""
    for pipe in list(_LIVE_PIPELINES):
        try:
            pipe.close()
        except Exception:
            pass


def _register_pipeline(pipe):
    global _ATEXIT_REGISTERED
    _LIVE_PIPELINES.add(pipe)
    if not _ATEXIT_REGISTERED:
        atexit.register(_atexit_close_all)
        _ATEXIT_REGISTERED = True


class WorkerPoolBroken(RuntimeError):
    """The pool failed ``max_restarts`` consecutive times — the caller
    should degrade to thread mode rather than keep flogging it."""


def _worker_main(worker_id, dataset, imgs_name, labels_name, slots,
                 batch_size, item_shape, seed, num_workers, task_q, res_q):
    """Decode-worker loop: pull ``(slot, offset, indices, epoch)`` spans,
    write pixels/labels straight into the shared ring, ack on ``res_q``.

    Runs in a spawned child — keep imports local and never touch JAX.
    """
    from multiprocessing import shared_memory

    # NOTE: attaching re-registers the names with the resource tracker the
    # children inherit from the parent — an idempotent set-add, so the
    # parent's close()+unlink() still cleans up exactly once. Do NOT
    # unregister here: that would strip the parent's registration and leak
    # the segments if the parent dies uncleanly.
    shm_imgs = shared_memory.SharedMemory(name=imgs_name)
    shm_labels = shared_memory.SharedMemory(name=labels_name)
    imgs = np.ndarray((slots, batch_size) + tuple(item_shape), np.uint8,
                      buffer=shm_imgs.buf)
    labels = np.ndarray((slots, batch_size), np.int32,
                        buffer=shm_labels.buf)
    cache = getattr(dataset, "decode_cache", None)
    if cache is not None and num_workers > 1:
        # keep the configured cache_bytes a TOTAL budget across the pool
        cache.scale_budget(num_workers)
    get_into = getattr(dataset, "get_into", None)
    get = getattr(dataset, "get", None)
    # worker-side fault injection (io_error / worker_hang) re-parses the
    # inherited DPTPU_FAULT env — nothing fault-related crosses the pickle
    try:
        fault_plan = FaultPlan.from_env()
    except ValueError:
        fault_plan = None  # the parent raises the parse error loudly
    try:
        while True:
            task = task_q.get()
            if task is None:
                break
            slot, offset, idxs, epoch = task
            try:
                for j, index in enumerate(idxs):
                    if fault_plan is not None:
                        fault_plan.worker_decode_hook(worker_id, index)
                    rng = np.random.default_rng([seed, epoch, index])
                    row = imgs[slot, offset + j]
                    if get_into is not None:
                        labels[slot, offset + j] = get_into(index, rng, row)
                    else:
                        from dptpu.data.dataset import _copy_checked

                        if get is not None:
                            img, lab = get(index, rng)
                        else:
                            img, lab = dataset[index]
                        _copy_checked(row, img, index)
                        labels[slot, offset + j] = lab
                hits, misses = (cache.hits, cache.misses) if cache else (0, 0)
                res_q.put(("done", worker_id, slot, offset, hits, misses))
            except BaseException:
                res_q.put(
                    ("error", worker_id, slot, offset, traceback.format_exc())
                )
    except (EOFError, OSError, KeyboardInterrupt):
        pass  # parent went away / interrupt: exit quietly
    finally:
        imgs = labels = None
        shm_imgs.close()
        shm_labels.close()


class ShmBatchPipeline:
    """The process-mode backend of ``DataLoader``: shared-memory slot ring
    + supervised persistent worker pool + span task/ack queues.

    Protocol (driven by ``DataLoader._epoch_process``): ``submit`` fans a
    batch's indices out as one span task per worker into a free slot;
    ``collect`` blocks until that slot's spans are acked, copies the rows
    out, and recycles the slot. ``reset`` drains an abandoned epoch's
    in-flight work so the ring starts an epoch fully free.

    Supervision bookkeeping: ``_pending[slot][offset] = task`` holds every
    unacked span — exactly what a pool restart must re-enqueue; it is the
    single source of truth for "work the consumer is still owed".
    """

    def __init__(self, dataset, batch_size: int, item_shape: Tuple[int, ...],
                 num_workers: int, seed: int, slots: int,
                 mp_start: str = "spawn",
                 timeout_s: Optional[float] = None,
                 max_restarts: Optional[int] = None,
                 span_retries: Optional[int] = None):
        import multiprocessing as mp
        from multiprocessing import shared_memory

        self.batch_size = batch_size
        self.item_shape = tuple(int(d) for d in item_shape)
        self.num_workers = max(1, num_workers)
        self.slots = max(2, slots)
        self._dataset = dataset
        self._seed = seed
        self._has_cache = getattr(dataset, "decode_cache", None) is not None
        # supervision knobs (ctor beats env beats default)
        self.timeout_s = (
            timeout_s if timeout_s is not None
            else env_float("DPTPU_WORKER_TIMEOUT_S", 120.0)
        )
        self.max_restarts = (
            max_restarts if max_restarts is not None
            else env_int("DPTPU_POOL_RESTARTS", 3)
        )
        self.span_retries = (
            span_retries if span_retries is not None
            else env_int("DPTPU_SPAN_RETRIES", 2)
        )
        if self.timeout_s <= 0:
            raise ValueError(
                f"DPTPU_WORKER_TIMEOUT_S={self.timeout_s} must be > 0 "
                f"seconds"
            )
        if self.max_restarts < 0 or self.span_retries < 0:
            raise ValueError(
                "DPTPU_POOL_RESTARTS and DPTPU_SPAN_RETRIES must be >= 0"
            )
        item_bytes = int(np.prod(self.item_shape))
        self._ctx = mp.get_context(mp_start)
        self._shm_imgs = shared_memory.SharedMemory(
            create=True, size=max(1, self.slots * batch_size * item_bytes)
        )
        self._shm_labels = shared_memory.SharedMemory(
            create=True, size=self.slots * batch_size * 4
        )
        self._imgs = np.ndarray(
            (self.slots, batch_size) + self.item_shape, np.uint8,
            buffer=self._shm_imgs.buf,
        )
        self._labels = np.ndarray(
            (self.slots, batch_size), np.int32, buffer=self._shm_labels.buf
        )
        self._outstanding = [0] * self.slots  # span acks still in flight
        self._pending = {s: {} for s in range(self.slots)}  # offset -> task
        self._retries = {}  # (slot, offset) -> attempts so far
        self._free = list(range(self.slots))
        self._worker_cache = {}  # worker_id -> latest (hits, misses)
        self._restarts_total = 0
        self._span_retries_total = 0
        self._consec_failures = 0
        self._closed = False
        self._start_workers()
        _register_pipeline(self)

    def _start_workers(self):
        """(Re)create the task/ack queues and spawn the worker pool —
        queues are rebuilt with the pool because a SIGKILLed worker can
        leave a queue's internal pipe in a torn state."""
        self._task_q = self._ctx.Queue()
        self._res_q = self._ctx.Queue()
        self._procs = [
            self._ctx.Process(
                target=_worker_main,
                args=(wid, self._dataset, self._shm_imgs.name,
                      self._shm_labels.name, self.slots, self.batch_size,
                      self.item_shape, self._seed, self.num_workers,
                      self._task_q, self._res_q),
                daemon=True,
                name=f"dptpu-data-{wid}",
            )
            for wid in range(self.num_workers)
        ]
        for p in self._procs:
            p.start()

    # -- submission / collection -------------------------------------------

    def submit(self, batch_indices, epoch: int) -> Tuple[int, int]:
        """Fan one batch out as span tasks into a free slot; returns
        ``(slot, n_valid)``. The caller's prefetch depth must not exceed
        ``slots`` (DataLoader sizes the ring accordingly)."""
        if not self._free:
            raise RuntimeError(
                f"no free batch slot (ring of {self.slots}, all in "
                f"flight) — prefetch depth exceeded the ring size"
            )
        slot = self._free.pop()
        n = len(batch_indices)
        span = -(-n // self.num_workers)
        for o in range(0, n, span):
            task = (slot, o,
                    tuple(int(i) for i in batch_indices[o:o + span]), epoch)
            self._pending[slot][o] = task
            self._task_q.put(task)
        self._outstanding[slot] = len(self._pending[slot])
        return slot, n

    def collect(self, slot: int, out_rows: int):
        """Wait for ``slot``'s spans, copy ``out_rows`` rows out (consumer
        owns the copies), recycle the slot. Raises the worker's decode
        error, with its traceback, once its retry budget is spent."""
        while self._outstanding[slot] > 0:
            self._handle(self._next_result(), mode="normal")
        imgs = np.array(self._imgs[slot, :out_rows])
        labels = np.array(self._labels[slot, :out_rows])
        self._free.append(slot)
        return imgs, labels

    def reset(self):
        """Reclaim the ring after an abandoned epoch: wait out (or, on a
        restart, simply drop) in-flight work and mark every slot free.
        Errors for batches nobody will consume are discarded."""
        while any(self._outstanding):
            self._handle(self._next_result(requeue=False), mode="discard")
        self._free = list(range(self.slots))
        for spans in self._pending.values():
            spans.clear()
        self._retries.clear()

    def kill_worker(self, index: int = 0) -> Optional[int]:
        """Fault-injection/debug hook: SIGKILL one live worker process
        (the supervisor must then restart the pool and re-enqueue its
        span). Returns the killed pid, or None if nothing was alive.

        Synchronous by design: the join guarantees the death is visible
        to the very next liveness check, so a chaos run deterministically
        exercises the restart path instead of racing a fast epoch."""
        alive = [p for p in self._procs if p.is_alive()]
        if not alive:
            return None
        p = alive[index % len(alive)]
        pid = p.pid
        p.kill()
        p.join(timeout=5.0)
        return pid

    # -- supervision --------------------------------------------------------

    def _next_result(self, requeue: bool = True):
        """Wait for one worker ack under the watchdog: a dead worker or a
        deadline with zero progress restarts the pool (re-enqueueing the
        unacked spans unless ``requeue`` is off — the reset path drops
        them instead). Liveness is checked BEFORE every wait, not only on
        timeout: a worker that dies idle (its spans picked up by the
        survivors) would otherwise silently shrink the pool forever."""
        deadline = time.monotonic() + self.timeout_s
        while True:
            dead = [p for p in self._procs if not p.is_alive()]
            if dead:
                p = dead[0]
                self._restart_pool(
                    f"worker {p.name} (pid {p.pid}) died with exit "
                    f"code {p.exitcode} — killed, OOM-reaped, or a "
                    f"native crash in the decoder",
                    requeue=requeue,
                )
            elif time.monotonic() > deadline:
                self._restart_pool(
                    f"no worker progress for {self.timeout_s:.1f}s "
                    f"with {sum(self._outstanding)} span(s) in flight "
                    f"— worker hang suspected",
                    requeue=requeue,
                )
            else:
                try:
                    return self._res_q.get(timeout=min(0.2, self.timeout_s))
                except _queue.Empty:
                    continue
            if not any(self._outstanding):
                # a reset-path restart dropped all pending work; nothing
                # will ever ack, so hand back a sentinel the _handle
                # modes understand as "no-op"
                return ("none",)
            deadline = time.monotonic() + self.timeout_s

    def _restart_pool(self, reason: str, requeue: bool = True):
        """Kill + respawn the pool; re-enqueue every unacked span (safe:
        spans are deterministic pure writes into disjoint rows)."""
        self._consec_failures += 1
        if self._consec_failures > self.max_restarts:
            raise WorkerPoolBroken(
                f"data-worker pool failed {self._consec_failures} "
                f"consecutive times (budget {self.max_restarts}); last "
                f"failure: {reason}"
            )
        self._restarts_total += 1
        print(
            f"WARNING: dptpu data-worker pool restart "
            f"{self._consec_failures}/{self.max_restarts}: {reason}",
            file=sys.stderr,
        )
        for p in self._procs:
            if p.is_alive():
                p.kill()
        for p in self._procs:
            p.join(timeout=2.0)
        # salvage acks already delivered before the failure, then drop
        # the torn queues (a SIGKILL mid-put can wedge them)
        while True:
            try:
                msg = self._res_q.get_nowait()
            except Exception:
                # Empty, or a torn message from the killed worker's
                # feeder thread (UnpicklingError & friends) — either way
                # the queue is done yielding salvage; the restart's span
                # re-enqueue covers whatever was lost
                break
            if msg[0] == "done":
                self._handle(msg, mode="normal")
            # drained error acks stay pending: the restart re-enqueues
            # them, which is exactly a retry
        for q in (self._task_q, self._res_q):
            try:
                q.close()
                q.cancel_join_thread()
            except Exception:
                pass
        self._start_workers()
        if requeue:
            for spans in self._pending.values():
                for task in spans.values():
                    self._task_q.put(task)
        else:
            for spans in self._pending.values():
                spans.clear()
            self._outstanding = [0] * self.slots
            self._retries.clear()

    def _handle(self, msg, mode: str = "normal"):
        """Apply one worker ack. Modes: ``normal`` (collect path — retry
        errored spans up to the budget, then raise with the worker's
        traceback), ``discard`` (reset path — drop errored spans)."""
        kind = msg[0]
        if kind == "none":  # restart-with-drop sentinel from _next_result
            return
        worker_id, slot, offset = msg[1], msg[2], msg[3]
        if kind == "done":
            self._consec_failures = 0  # the pool is making progress
            self._outstanding[slot] -= 1
            self._pending[slot].pop(offset, None)
            self._retries.pop((slot, offset), None)
            self._worker_cache[worker_id] = (msg[4], msg[5])
            return
        # kind == "error"
        if mode == "discard":
            self._outstanding[slot] -= 1
            self._pending[slot].pop(offset, None)
            self._retries.pop((slot, offset), None)
            return
        attempts = self._retries.get((slot, offset), 0)
        task = self._pending[slot].get(offset)
        if attempts < self.span_retries and task is not None:
            self._retries[(slot, offset)] = attempts + 1
            self._span_retries_total += 1
            print(
                f"WARNING: dptpu data worker {worker_id} errored on batch "
                f"slot {slot} offset {offset}; retrying span "
                f"({attempts + 1}/{self.span_retries})",
                file=sys.stderr,
            )
            self._task_q.put(task)
            return
        raise RuntimeError(
            f"data worker {worker_id} failed while decoding (batch "
            f"slot {slot}, offset {offset}"
            + (f", after {attempts} retries" if attempts else "")
            + f"); worker traceback:\n{msg[4]}"
        )

    # -- telemetry ----------------------------------------------------------

    def cache_stats(self) -> dict:
        """Pool-wide decode-cache counters, aggregated from the latest
        per-worker ack (workers piggyback cumulative counts on every
        ``done`` message — no extra round trip)."""
        if not self._has_cache:
            return {}
        hits = sum(h for h, _ in self._worker_cache.values())
        misses = sum(m for _, m in self._worker_cache.values())
        total = hits + misses
        return {
            "cache_hits": hits,
            "cache_misses": misses,
            "cache_hit_rate": (hits / total) if total else 0.0,
        }

    def supervision_stats(self) -> dict:
        """Watchdog counters for feed telemetry."""
        return {
            "pool_restarts": self._restarts_total,
            "span_retries": self._span_retries_total,
        }

    # -- lifecycle ----------------------------------------------------------

    def close(self):
        if self._closed:
            return
        self._closed = True
        for p in self._procs:
            if p.is_alive():
                try:
                    self._task_q.put(None)
                except Exception:
                    pass
        for p in self._procs:
            p.join(timeout=1.0)
            if p.is_alive():
                p.terminate()
                p.join(timeout=2.0)
            if p.is_alive():  # hung in non-interruptible state: no mercy
                p.kill()
                p.join(timeout=2.0)
        for q in (self._task_q, self._res_q):
            try:
                q.close()
                q.cancel_join_thread()
            except Exception:
                pass
        self._imgs = self._labels = None  # release buffer exports first
        for shm in (self._shm_imgs, self._shm_labels):
            try:
                shm.close()
                shm.unlink()
            except FileNotFoundError:
                pass
        _LIVE_PIPELINES.discard(self)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
