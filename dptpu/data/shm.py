"""Multi-process shared-memory batch ring for the input pipeline.

The thread-pool loader flatlines on multi-core hosts: PIL/libjpeg release
the GIL for the pixel work, but header parsing, RNG, numpy bookkeeping and
the futures machinery all serialize on it (HOSTBENCH r5: 542.8 img/s at 8
threads vs 516.6 at 1 — the pool buys ~5%). Worker PROCESSES sidestep the
GIL entirely; the classic cost of torch-style workers — pickling every
decoded batch through a pipe — is removed by giving the workers the
loader's preallocated batch memory itself:

* a ring of ``slots`` batch buffers lives in ONE
  ``multiprocessing.shared_memory`` segment per array (images uint8
  ``[slots, B, H, W, C]``, labels int32 ``[slots, B]``);
* workers run the SAME span-decode path as thread mode
  (``dataset.get_into`` → the native decoder's caller-supplied output
  row), writing JPEG decodes directly into their slot's rows — pixels
  never cross a pipe, only tiny ``(slot, offset, indices, epoch)`` tasks
  and ``(done, ...)`` acks do;
* per-item augmentation RNG is derived from ``(seed, epoch, index)``
  exactly as in thread mode, so process and thread loaders yield
  BIT-IDENTICAL batches for the same seed (tests/test_shm_loader.py);
* a decode error in a worker is caught, carried back as a traceback
  string, and re-raised in the parent with context — never a hang;
* the parent copies a completed slot out once (so consumers own their
  batches and the slot recycles immediately); that single memcpy is
  ~1-2 ms against a >100 ms decode per batch.

Workers are spawned (not forked) by default: the parent holds JAX/XLA
runtime threads whose locks must not be forked mid-flight. Spawn pickles
the dataset once per worker; a ``DecodeCache`` crosses that boundary as
budget-only (each worker warms its own shard, budget divided by the pool
size — see ``dptpu/data/cache.py``).
"""

from __future__ import annotations

import queue as _queue
import traceback
from typing import Optional, Tuple

import numpy as np


def _worker_main(worker_id, dataset, imgs_name, labels_name, slots,
                 batch_size, item_shape, seed, num_workers, task_q, res_q):
    """Decode-worker loop: pull ``(slot, offset, indices, epoch)`` spans,
    write pixels/labels straight into the shared ring, ack on ``res_q``.

    Runs in a spawned child — keep imports local and never touch JAX.
    """
    from multiprocessing import shared_memory

    # NOTE: attaching re-registers the names with the resource tracker the
    # children inherit from the parent — an idempotent set-add, so the
    # parent's close()+unlink() still cleans up exactly once. Do NOT
    # unregister here: that would strip the parent's registration and leak
    # the segments if the parent dies uncleanly.
    shm_imgs = shared_memory.SharedMemory(name=imgs_name)
    shm_labels = shared_memory.SharedMemory(name=labels_name)
    imgs = np.ndarray((slots, batch_size) + tuple(item_shape), np.uint8,
                      buffer=shm_imgs.buf)
    labels = np.ndarray((slots, batch_size), np.int32,
                        buffer=shm_labels.buf)
    cache = getattr(dataset, "decode_cache", None)
    if cache is not None and num_workers > 1:
        # keep the configured cache_bytes a TOTAL budget across the pool
        cache.scale_budget(num_workers)
    get_into = getattr(dataset, "get_into", None)
    get = getattr(dataset, "get", None)
    try:
        while True:
            task = task_q.get()
            if task is None:
                break
            slot, offset, idxs, epoch = task
            try:
                for j, index in enumerate(idxs):
                    rng = np.random.default_rng([seed, epoch, index])
                    row = imgs[slot, offset + j]
                    if get_into is not None:
                        labels[slot, offset + j] = get_into(index, rng, row)
                    else:
                        from dptpu.data.dataset import _copy_checked

                        if get is not None:
                            img, lab = get(index, rng)
                        else:
                            img, lab = dataset[index]
                        _copy_checked(row, img, index)
                        labels[slot, offset + j] = lab
                hits, misses = (cache.hits, cache.misses) if cache else (0, 0)
                res_q.put(("done", worker_id, slot, hits, misses))
            except BaseException:
                res_q.put(("error", worker_id, slot, traceback.format_exc()))
    except (EOFError, OSError, KeyboardInterrupt):
        pass  # parent went away / interrupt: exit quietly
    finally:
        imgs = labels = None
        shm_imgs.close()
        shm_labels.close()


class ShmBatchPipeline:
    """The process-mode backend of ``DataLoader``: shared-memory slot ring
    + persistent worker pool + span task/ack queues.

    Protocol (driven by ``DataLoader._epoch_process``): ``submit`` fans a
    batch's indices out as one span task per worker into a free slot;
    ``collect`` blocks until that slot's spans are acked, copies the rows
    out, and recycles the slot. ``reset`` drains an abandoned epoch's
    in-flight work so the ring starts an epoch fully free.
    """

    def __init__(self, dataset, batch_size: int, item_shape: Tuple[int, ...],
                 num_workers: int, seed: int, slots: int,
                 mp_start: str = "spawn"):
        import multiprocessing as mp
        from multiprocessing import shared_memory

        self.batch_size = batch_size
        self.item_shape = tuple(int(d) for d in item_shape)
        self.num_workers = max(1, num_workers)
        self.slots = max(2, slots)
        self._has_cache = getattr(dataset, "decode_cache", None) is not None
        item_bytes = int(np.prod(self.item_shape))
        ctx = mp.get_context(mp_start)
        self._shm_imgs = shared_memory.SharedMemory(
            create=True, size=max(1, self.slots * batch_size * item_bytes)
        )
        self._shm_labels = shared_memory.SharedMemory(
            create=True, size=self.slots * batch_size * 4
        )
        self._imgs = np.ndarray(
            (self.slots, batch_size) + self.item_shape, np.uint8,
            buffer=self._shm_imgs.buf,
        )
        self._labels = np.ndarray(
            (self.slots, batch_size), np.int32, buffer=self._shm_labels.buf
        )
        self._task_q = ctx.Queue()
        self._res_q = ctx.Queue()
        self._outstanding = [0] * self.slots  # span acks still in flight
        self._free = list(range(self.slots))
        self._worker_cache = {}  # worker_id -> latest (hits, misses)
        self._closed = False
        self._procs = [
            ctx.Process(
                target=_worker_main,
                args=(wid, dataset, self._shm_imgs.name,
                      self._shm_labels.name, self.slots, batch_size,
                      self.item_shape, seed, self.num_workers,
                      self._task_q, self._res_q),
                daemon=True,
                name=f"dptpu-data-{wid}",
            )
            for wid in range(self.num_workers)
        ]
        for p in self._procs:
            p.start()

    # -- submission / collection -------------------------------------------

    def submit(self, batch_indices, epoch: int) -> Tuple[int, int]:
        """Fan one batch out as span tasks into a free slot; returns
        ``(slot, n_valid)``. The caller's prefetch depth must not exceed
        ``slots`` (DataLoader sizes the ring accordingly)."""
        if not self._free:
            raise RuntimeError(
                f"no free batch slot (ring of {self.slots}, all in "
                f"flight) — prefetch depth exceeded the ring size"
            )
        slot = self._free.pop()
        n = len(batch_indices)
        span = -(-n // self.num_workers)
        nspans = 0
        for o in range(0, n, span):
            self._task_q.put(
                (slot, o,
                 tuple(int(i) for i in batch_indices[o:o + span]), epoch)
            )
            nspans += 1
        self._outstanding[slot] = nspans
        return slot, n

    def collect(self, slot: int, out_rows: int):
        """Wait for ``slot``'s spans, copy ``out_rows`` rows out (consumer
        owns the copies), recycle the slot. Raises the worker's decode
        error, with its traceback, if any span failed."""
        while self._outstanding[slot] > 0:
            self._handle(self._next_result(), raise_errors=True)
        imgs = np.array(self._imgs[slot, :out_rows])
        labels = np.array(self._labels[slot, :out_rows])
        self._free.append(slot)
        return imgs, labels

    def reset(self):
        """Drain in-flight work from an abandoned epoch (workers always
        finish or error their span) and mark every slot free. Errors for
        batches nobody will consume are discarded."""
        while any(self._outstanding):
            self._handle(self._next_result(), raise_errors=False)
        self._free = list(range(self.slots))

    def _next_result(self):
        while True:
            try:
                return self._res_q.get(timeout=1.0)
            except _queue.Empty:
                for p in self._procs:
                    if not p.is_alive():
                        raise RuntimeError(
                            f"data worker {p.name} (pid {p.pid}) died with "
                            f"exit code {p.exitcode} without reporting an "
                            f"error — likely OOM-killed or a native crash "
                            f"in the decoder"
                        ) from None

    def _handle(self, msg, raise_errors: bool):
        kind, worker_id, slot = msg[0], msg[1], msg[2]
        self._outstanding[slot] -= 1
        if kind == "done":
            self._worker_cache[worker_id] = (msg[3], msg[4])
        elif kind == "error" and raise_errors:
            raise RuntimeError(
                f"data worker {worker_id} failed while decoding (batch "
                f"slot {slot}); worker traceback:\n{msg[3]}"
            )

    # -- telemetry ----------------------------------------------------------

    def cache_stats(self) -> dict:
        """Pool-wide decode-cache counters, aggregated from the latest
        per-worker ack (workers piggyback cumulative counts on every
        ``done`` message — no extra round trip)."""
        if not self._has_cache:
            return {}
        hits = sum(h for h, _ in self._worker_cache.values())
        misses = sum(m for _, m in self._worker_cache.values())
        total = hits + misses
        return {
            "cache_hits": hits,
            "cache_misses": misses,
            "cache_hit_rate": (hits / total) if total else 0.0,
        }

    # -- lifecycle ----------------------------------------------------------

    def close(self):
        if self._closed:
            return
        self._closed = True
        for p in self._procs:
            if p.is_alive():
                self._task_q.put(None)
        for p in self._procs:
            p.join(timeout=5.0)
            if p.is_alive():
                p.terminate()
                p.join(timeout=5.0)
        for q in (self._task_q, self._res_q):
            q.close()
            q.cancel_join_thread()
        self._imgs = self._labels = None  # release buffer exports first
        for shm in (self._shm_imgs, self._shm_labels):
            try:
                shm.close()
                shm.unlink()
            except FileNotFoundError:
                pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
