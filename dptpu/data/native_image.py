"""ctypes bindings for the native image ops (dptpu/native/src/image_ops.cpp).

``decode_crop_resize`` fuses JPEG decode (at the lowest sufficient libjpeg
scale), crop, bilinear resize, and flip into one C call that releases the
GIL — the data pipeline's per-item hot path. ``available()`` gates use;
non-JPEG inputs and missing-toolchain environments fall back to PIL.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np

from dptpu.native import load_library


def available() -> bool:
    return load_library() is not None


def file_readahead(path: str) -> bool:
    """Advise the kernel to pull ``path``'s bytes into the page cache
    (``posix_fadvise(WILLNEED)``) — the decode-ahead pipeline's
    cold-epoch byte prefetch, issued by the PARENT when a span is
    pre-issued so the worker's read (``decode_ahead`` batches later)
    services from memory. The native call releases the GIL; without the
    native lib, ``os.posix_fadvise`` covers Linux. Returns True when
    advice was delivered (best-effort — False never blocks decode)."""
    lib = load_library()
    if lib is not None:
        return lib.dptpu_file_readahead(path.encode()) >= 0
    import os

    if not hasattr(os, "posix_fadvise"):
        return False
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_WILLNEED)
        finally:
            os.close(fd)
        return True
    except OSError:
        return False


def jpeg_dims(data: bytes) -> Optional[Tuple[int, int]]:
    """(width, height) from the JPEG header, or None if not decodable."""
    lib = load_library()
    if lib is None:
        return None
    w = ctypes.c_int()
    h = ctypes.c_int()
    if lib.dptpu_jpeg_dims(data, len(data), ctypes.byref(w), ctypes.byref(h)):
        return None
    return w.value, h.value


def decode_crop_resize(data: bytes, box, out_size: int, flip: bool,
                       out: Optional[np.ndarray] = None
                       ) -> Optional[np.ndarray]:
    """Decode + crop ``box`` (left, top, w, h in full-res coords) + resize to
    ``out_size``² RGB (+flip). Returns uint8 HWC array or None on failure.

    ``out`` lets the caller supply the destination (e.g. one row of the
    loader's preallocated batch) so the decoder writes the pixels in
    place — no per-image intermediate + memcpy. It must be a C-contiguous
    uint8 (out_size, out_size, 3) array; anything else falls back to a
    fresh allocation (the caller can detect that by identity)."""
    lib = load_library()
    if lib is None:
        return None
    if (out is None or out.dtype != np.uint8
            or out.shape != (out_size, out_size, 3)
            or not out.flags["C_CONTIGUOUS"]):
        out = np.empty((out_size, out_size, 3), np.uint8)
    left, top, cw, ch = (float(v) for v in box)
    rc = lib.dptpu_jpeg_decode_crop_resize(
        data, len(data), left, top, cw, ch, out_size, int(flip),
        out.ctypes.data_as(ctypes.c_void_p),
    )
    return out if rc == 0 else None


def decode_into_cache(data: bytes, out: np.ndarray) -> bool:
    """Full-resolution RGB decode into ``out`` (H, W, 3 uint8, C-contiguous,
    sized from ``jpeg_dims``) — the decode-cache FILL path.

    Uses the same libjpeg settings as ``decode_crop_resize`` at scale 8/8
    (JCS_RGB, IFAST DCT), so a subsequent ``crop_resize`` from this buffer
    reproduces the fused path bit-for-bit whenever the fused path's scale
    picker stays at full resolution. Returns False on failure (caller falls
    back to the uncached path)."""
    lib = load_library()
    if lib is None:
        return False
    if (out.dtype != np.uint8 or out.ndim != 3 or out.shape[2] != 3
            or not out.flags["C_CONTIGUOUS"]):
        return False
    h, w = out.shape[:2]
    rc = lib.dptpu_jpeg_decode_rgb(
        data, len(data), w, h, out.ctypes.data_as(ctypes.c_void_p)
    )
    return rc == 0


def crop_resize(src: np.ndarray, box, out_size: int, flip: bool,
                out: Optional[np.ndarray] = None) -> Optional[np.ndarray]:
    """Crop ``box`` (left, top, w, h in ``src`` coords) + bilinear resize to
    ``out_size``² (+flip) from a decoded RGB buffer — the decode-cache HIT
    path, skipping JPEG decode entirely. Same fixed-point kernel as
    ``decode_crop_resize``; ``out`` semantics match it too."""
    lib = load_library()
    if lib is None:
        return None
    if (src.dtype != np.uint8 or src.ndim != 3 or src.shape[2] != 3
            or not src.flags["C_CONTIGUOUS"]):
        return None
    if (out is None or out.dtype != np.uint8
            or out.shape != (out_size, out_size, 3)
            or not out.flags["C_CONTIGUOUS"]):
        out = np.empty((out_size, out_size, 3), np.uint8)
    h, w = src.shape[:2]
    left, top, cw, ch = (float(v) for v in box)
    rc = lib.dptpu_crop_resize_rgb(
        src.ctypes.data_as(ctypes.c_void_p), w, h, left, top, cw, ch,
        out_size, int(flip), out.ctypes.data_as(ctypes.c_void_p),
    )
    return out if rc == 0 else None
