"""Input pipeline: ImageFolder reader, transforms, sharded sampling, prefetch.

TPU-native L4 (SURVEY.md §1): torchvision's ImageFolder + transform stacks +
DistributedSampler + the Apex fast_collate/DataPrefetcher become an in-tree
host pipeline — per-host disjoint shards, thread-pool JPEG decode, uint8
NHWC collation (normalization stays on-device, fused into the train step),
and a double-buffered device prefetcher that overlaps host decode + H2D with
the running step.
"""

from dptpu.data.cache import DecodeCache
from dptpu.data.dataset import ImageFolderDataset, SyntheticDataset
from dptpu.data.loader import DataLoader, DevicePrefetcher
from dptpu.data.sampler import ShardedSampler
from dptpu.data.shards import (
    ShardLocalitySampler,
    ShardSet,
    verify_shard,
    write_shards,
)
from dptpu.data.shm_cache import ShmDecodeCache
from dptpu.data.store import (
    HTTPStore,
    LocalStore,
    ShardByteCache,
    Store,
    is_store_url,
    open_store,
)
from dptpu.data.stream import ShardStreamDataset
from dptpu.data.transforms import (
    center_crop,
    random_horizontal_flip,
    random_resized_crop,
    resize_shorter,
    train_transform,
    val_transform,
)

__all__ = [
    "DataLoader",
    "DecodeCache",
    "DevicePrefetcher",
    "HTTPStore",
    "ImageFolderDataset",
    "LocalStore",
    "ShardByteCache",
    "ShardLocalitySampler",
    "ShardSet",
    "ShardStreamDataset",
    "ShardedSampler",
    "ShmDecodeCache",
    "Store",
    "SyntheticDataset",
    "is_store_url",
    "open_store",
    "verify_shard",
    "write_shards",
    "center_crop",
    "random_horizontal_flip",
    "random_resized_crop",
    "resize_shorter",
    "train_transform",
    "val_transform",
]
