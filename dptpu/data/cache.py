"""RAM-budgeted decoded-sample cache for the input pipeline.

JPEG Huffman decode + IDCT is the dominant share of per-item host cost
(HOSTBENCH: the native fused path spends most of its time inside libjpeg,
not the crop-resize). Across epochs the pipeline decodes the SAME files
again and again, varying only the sampled crop/flip — so ``DecodeCache``
keeps the decoded full-resolution RGB pixels and epoch 1+ re-applies only
the per-epoch augmentation (crop/resize/flip), skipping the decode
entirely on a hit. The same idea drives every fast-ImageNet input
pipeline (DALI's decoder cache, tf.data's ``.cache()``); here it is
byte-budgeted and in-process.

Semantics:

* **Byte budget, LRU eviction.** ``put`` accounts ``arr.nbytes``; least-
  recently-used entries are evicted until the new entry fits. Entries
  larger than the whole budget are rejected (never cached), so one huge
  image cannot flush the working set.
* **Bit-stable hit path.** The dataset's cache-aware decode fills the
  cache with the SAME decoded pixels the miss path then resamples from
  (``native_image.decode_into_cache`` / PIL full decode), so a hit and a
  miss produce identical output for identical augmentation RNG — cache
  warmth never changes what a seeded run trains on.
* **Process-pool friendly.** Pickling transfers the budget but NOT the
  contents (workers warm their own), and ``scale_budget`` divides the
  budget across a worker pool so ``cache_bytes`` stays the TOTAL RAM
  spend no matter the worker count.

Thread-safe; stats (hits/misses/evictions/bytes) feed the loader's
``feed_stats`` telemetry.
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

from dptpu.utils.sync import OrderedLock


class DecodeCache:
    """LRU byte-budgeted map of hashable keys → decoded uint8 arrays.

    This is the SHARDED scope (``DPTPU_CACHE_SCOPE=sharded``): in-process
    and private, so a worker-process pool divides the budget N ways and
    each worker reaches only its own shard. The POOLED alternative — one
    cross-process /dev/shm slab every worker shares, surviving pool
    restarts — is :class:`dptpu.data.shm_cache.ShmDecodeCache`; both
    serve the same bytes for the same key, so the scopes are
    bit-interchangeable.
    """

    scope = "sharded"

    def __init__(self, budget_bytes: int):
        if budget_bytes <= 0:
            raise ValueError(
                f"cache budget must be positive, got {budget_bytes} "
                f"(omit the cache instead of zero-sizing it)"
            )
        self.budget_bytes = int(budget_bytes)  # guarded-by: _lock
        self._entries: OrderedDict = OrderedDict()  # guarded-by: _lock
        self._bytes = 0  # guarded-by: _lock
        self._lock = OrderedLock("data.decode_cache")
        self.hits = 0  # guarded-by: _lock
        self.misses = 0  # guarded-by: _lock
        self.evictions = 0  # guarded-by: _lock

    # -- core ---------------------------------------------------------------

    def get(self, key):
        """The cached array for ``key`` (marked most-recently-used), or
        None. Callers must treat the result as READ-ONLY: it is the
        shared decoded buffer every future hit resamples from."""
        with self._lock:
            arr = self._entries.get(key)
            if arr is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return arr

    def with_entry(self, key, fn):
        """Uniform hit-path API with :class:`ShmDecodeCache.with_entry`:
        ``(True, fn(cached))`` on a hit, ``(False, None)`` on a miss.
        In-process the cached buffer is already zero-copy (read-only,
        GC-protected), so no lock needs to be held across ``fn``."""
        arr = self.get(key)
        if arr is None:
            return False, None
        return True, fn(arr)

    def put(self, key, arr: np.ndarray) -> bool:
        """Insert ``arr`` under ``key``, evicting LRU entries to fit the
        byte budget. Returns False (not cached) when ``arr`` alone
        exceeds the budget."""
        nbytes = int(arr.nbytes)
        # the stored buffer is shared by every future hit: freeze it so
        # an aliasing caller fails loudly instead of corrupting the cache
        arr.flags.writeable = False
        with self._lock:
            if nbytes > self.budget_bytes:
                return False
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            while self._bytes + nbytes > self.budget_bytes and self._entries:
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= evicted.nbytes
                self.evictions += 1
            self._entries[key] = arr
            self._bytes += nbytes
            return True

    # -- introspection ------------------------------------------------------

    @property
    def bytes_in_use(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "cache_hits": self.hits,
                "cache_misses": self.misses,
                "cache_evictions": self.evictions,
                "cache_entries": len(self._entries),
                "cache_bytes_in_use": self._bytes,
                "cache_budget_bytes": self.budget_bytes,
                "cache_hit_rate": (self.hits / total) if total else 0.0,
            }

    # -- pooling ------------------------------------------------------------

    def scale_budget(self, divisor: int):
        """Divide the budget by ``divisor`` (process-pool split: each of N
        workers keeps 1/N of the configured TOTAL budget). Existing
        entries are evicted down to the new budget."""
        if divisor <= 0:
            raise ValueError(f"divisor must be positive, got {divisor}")
        with self._lock:
            self.budget_bytes = max(1, self.budget_bytes // divisor)
            while self._bytes > self.budget_bytes and self._entries:
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= evicted.nbytes
                self.evictions += 1

    def __getstate__(self):
        # budget crosses the pickle boundary; contents do not (each
        # process-pool worker warms its own working set)
        return {"budget_bytes": self.budget_bytes}

    def __setstate__(self, state):
        self.__init__(state["budget_bytes"])
