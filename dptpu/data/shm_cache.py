"""Cross-process POOLED decode cache: one /dev/shm slab, every worker.

The per-worker ``DecodeCache`` shards a ``DPTPU_CACHE_BYTES`` budget N
ways across a process pool (each spawned worker warms its own private
dict), which costs twice: only 1/N of the budget is reachable from any
one worker, and a pool restart (the PR 2 supervisor's recovery path)
throws every shard away. ``ShmDecodeCache`` keeps the decoded full-res
pixels in ONE fixed-budget shared-memory slab instead:

* **Pooled budget.** The arena is ``budget_bytes`` of ``/dev/shm``
  shared by every attached process — any worker hits any cached image,
  so the effective working set is the full budget, not 1/N of it
  (``scale_budget`` is therefore a documented no-op here).
* **Hit ≡ miss, bit-identical.** A hit copies the stored full-res
  decode out of the arena; the caller resamples it exactly as the miss
  path resamples its freshly decoded buffer — same source pixels, same
  RNG, same output. Cache warmth never changes what a seeded run sees
  (the ``DecodeCache`` contract, unchanged).
* **Byte budget, insertion-order eviction.** ``put`` allocates from a
  ring arena; when full, the OLDEST entries are evicted until the new
  one fits, and an entry larger than the whole arena is rejected. Under
  the training access pattern — every epoch touches each image exactly
  once, in a fresh permutation — insertion order IS recency order, so
  ring/FIFO eviction and LRU evict the same entries; the byte-budget
  contract (``bytes_in_use <= budget``, oversized rejected) matches
  ``DecodeCache`` exactly.
* **Lock-striped index.** Keys digest to 128 bits (blake2b — collisions
  are ~2^-64 territory) and hash into ``n_stripes`` independent bucket
  ranges, each guarded by its own ``multiprocessing.Lock``; allocation
  takes one global arena lock. Lock order is always arena → stripe, one
  stripe at a time, so the scheme cannot deadlock against itself.
* **Survives worker death.** The slab belongs to the PARENT (the
  dataset that created it); killed/restarted pool workers merely
  re-attach, so a supervisor pool restart keeps the cache warm — unlike
  the sharded design, which restarts cold. A worker SIGKILLed while
  HOLDING a lock is recovered: every acquisition runs under a deadline,
  and on timeout the recorded owner pid is liveness-checked — a dead
  owner's semaphore is released (serialized through a dedicated
  recovery lock so two survivors cannot double-release) and its
  half-written entries are invalidated by the seqlock-style
  ``(seq, state)`` commit protocol. If recovery itself is ever torn,
  the cache degrades to miss-only (timeouts) — slower, never wrong.
* **Cleanup discipline.** Segments are named ``dptpu_cache_*`` so leak
  checks can find them; the creator unlinks on ``close()``/``__del__``
  and an ``atexit`` sweep covers abandoned instances, mirroring
  ``dptpu/data/shm.py`` (tests/conftest.py fails the suite on leaked
  ``dptpu_*`` segments).

Pickling transfers an ATTACH spec (segment name + geometry + the lock
handles), not contents — this only works across a ``multiprocessing``
spawn boundary (the locks refuse plain pickling by design), which is
exactly how the loader ships datasets to its workers.
"""

from __future__ import annotations

import atexit
import os
import secrets
import time
import weakref
from hashlib import blake2b

import numpy as np

from dptpu.utils.sync import ordered_mp_lock

SEGMENT_PREFIX = "dptpu_cache"

_LIVE_CACHES: "weakref.WeakSet" = weakref.WeakSet()
_ATEXIT_REGISTERED = False


def _atexit_close_all():
    for cache in list(_LIVE_CACHES):
        try:
            cache.close()
        except Exception:
            pass


def _register_cache(cache):
    global _ATEXIT_REGISTERED
    _LIVE_CACHES.add(cache)
    if not _ATEXIT_REGISTERED:
        atexit.register(_atexit_close_all)
        _ATEXIT_REGISTERED = True


def live_segment_names():
    """Segment names owned by still-referenced caches in THIS process —
    the set the conftest leak guard treats as legitimately present."""
    out = set()
    for cache in list(_LIVE_CACHES):
        name = getattr(cache, "segment_name", None)
        if name and not cache.closed:
            out.add(name)
    return out


def create_named_segment(prefix: str, size: int):
    """A SharedMemory segment with a ``dptpu_*`` name (collision-retried)
    so /dev/shm hygiene checks can attribute it; shared with the batch
    ring in dptpu/data/shm.py."""
    from multiprocessing import shared_memory

    for _ in range(16):
        name = f"{prefix}_{os.getpid()}_{secrets.token_hex(4)}"
        try:
            return shared_memory.SharedMemory(
                name=name, create=True, size=size
            )
        except FileExistsError:
            continue
    raise RuntimeError(f"could not allocate a unique {prefix} segment name")


def close_segment(shm, unlink: bool):
    """close()+unlink() tolerant of exported views: a consumer still
    holding a numpy view (e.g. a leased batch) makes ``mmap.close()``
    raise BufferError — the mapping then lives until that view dies, but
    the /dev/shm NAME is removed either way, so nothing leaks past the
    process."""
    try:
        shm.close()
    except BufferError:
        pass
    if unlink:
        try:
            shm.unlink()
        except FileNotFoundError:
            pass


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def _signed64(u: int) -> np.int64:
    """Reinterpret an unsigned 64-bit int as the int64 the entry table
    stores (numpy int64 cannot hold values >= 2^63 directly)."""
    return np.int64(u - (1 << 64) if u >= (1 << 63) else u)


def _digest128(key) -> tuple:
    """Stable 128-bit digest of a cache key → (lo, hi) uint64 pair,
    never (0, 0) — that pattern marks an empty bucket."""
    d = blake2b(repr(key).encode("utf-8"), digest_size=16).digest()
    lo = int.from_bytes(d[:8], "little")
    hi = int.from_bytes(d[8:], "little")
    if lo == 0 and hi == 0:  # astronomically unlikely; keep the invariant
        lo = 1
    return lo, hi


# ---- slab layout ----------------------------------------------------------
# [ header int64[16] | owners int64[2 + n_stripes] | entries int64[E, 11]
#   | fifo int64[E] | arena bytes ]
_H_MAGIC, _H_ARENA, _H_ENTRIES, _H_STRIPES, _H_HEAD, _H_TAIL, \
    _H_QHEAD, _H_QTAIL = range(8)
_HDR_LEN = 16
_MAGIC = 0x44505443  # 'DPTC'

# per-entry int64 fields
_E_KEY_LO, _E_KEY_HI, _E_OFF, _E_NBYTES, _E_AEND, _E_H, _E_W, _E_C, \
    _E_STATE, _E_OWNER, _E_SEQ = range(11)
_E_LEN = 11

_EMPTY, _WRITING, _READY = 0, 1, 2

_ALIGN = 64


class ShmDecodeCache:
    """Pooled cross-process decoded-pixel cache (see module docstring).

    Drop-in for :class:`dptpu.data.cache.DecodeCache` at the dataset
    call sites: ``get(key) -> uint8 HWC array | None``, ``put(key, arr)
    -> bool``, plus the hits/misses/evictions counters the loader's
    telemetry aggregates (counters are PER-PROCESS — in process mode the
    ring's done-acks piggyback and sum them, exactly as before).
    """

    scope = "pooled"

    def __init__(self, budget_bytes: int, n_stripes: int = 64,
                 max_entries: int = 0, lock_timeout_s: float = 2.0,
                 segment_prefix: str = SEGMENT_PREFIX):
        if budget_bytes <= 0:
            raise ValueError(
                f"cache budget must be positive, got {budget_bytes} "
                f"(omit the cache instead of zero-sizing it)"
            )
        import multiprocessing as mp

        self.budget_bytes = int(budget_bytes)
        self.n_stripes = int(n_stripes)
        if max_entries <= 0:
            # one entry slot per 32 KB of arena, floored at 256 so small
            # test budgets never starve the index and capped at 64Ki
            # (ImageNet decodes run ~600 KB, so the cap only binds for
            # pathologically tiny images; index overhead ≤ ~0.3%)
            max_entries = max(256, min(self.budget_bytes // (32 << 10),
                                       1 << 16))
        # stripes own equal contiguous bucket ranges
        max_entries = -(-max_entries // self.n_stripes) * self.n_stripes
        self.max_entries = max_entries
        self.lock_timeout_s = float(lock_timeout_s)
        self._creator = True
        self._closed = False  # owned-by: closing-caller
        # per-process telemetry counters, bumped from every decode
        # thread OUTSIDE the cross-process locks (the mp stripe locks
        # guard the SLAB, not this process's counters): a racy += can
        # only undercount a stat, never corrupt data — the censused
        # waiver below records that deliberately
        self.hits = 0  # dptpu: allow-guarded-by(per-process telemetry counter bumped lock-free by design; a torn += only undercounts a stat — the slab itself is guarded by the seqlock commit protocol and the mp stripe locks)
        self.misses = 0  # dptpu: allow-guarded-by(per-process telemetry counter bumped lock-free by design; a torn += only undercounts a stat — the slab itself is guarded by the seqlock commit protocol and the mp stripe locks)
        self.evictions = 0  # dptpu: allow-guarded-by(per-process telemetry counter bumped lock-free by design; a torn += only undercounts a stat — the slab itself is guarded by the seqlock commit protocol and the mp stripe locks)

        ctx = mp.get_context("spawn")
        # the declared arena -> recovery -> stripe order
        # (dptpu/utils/sync.py LOCK_RANKS; every acquisition in this
        # protocol is deadline-bounded, so it cannot deadlock — it
        # times out and degrades to a miss)
        self._alloc_lock = ordered_mp_lock("shm.alloc", ctx)
        self._recovery_lock = ordered_mp_lock("shm.recovery", ctx)
        self._stripe_locks = [ordered_mp_lock("shm.stripe", ctx)
                              for _ in range(self.n_stripes)]

        meta_bytes = (_HDR_LEN + 2 + self.n_stripes
                      + max_entries * _E_LEN + max_entries) * 8
        meta_bytes = -(-meta_bytes // _ALIGN) * _ALIGN
        self._arena_off = meta_bytes
        # the prefix is the /dev/shm attribution tag: decoded-pixel slabs
        # keep "dptpu_cache", the shard BYTE cache (dptpu/data/store.py)
        # passes "dptpu_shard" so the conftest leak guard can tell them
        # apart
        self._shm = create_named_segment(  # dptpu: allow-shm-hygiene(prefix is caller-supplied: the decode cache passes dptpu_cache, the shard byte cache dptpu_shard — both census kinds; a new caller with a new prefix trips the census assert in tests/conftest.py)
            segment_prefix, meta_bytes + self.budget_bytes
        )
        self.segment_name = self._shm.name
        self._map_views()
        self._hdr[:] = 0
        self._hdr[_H_MAGIC] = _MAGIC
        self._hdr[_H_ARENA] = self.budget_bytes
        self._hdr[_H_ENTRIES] = max_entries
        self._hdr[_H_STRIPES] = self.n_stripes
        self._owners[:] = 0
        self._entries[:] = 0
        self._fifo[:] = 0
        _register_cache(self)

    # -- mapping / pickling -------------------------------------------------

    def _map_views(self):
        buf = self._shm.buf
        off = 0
        self._hdr = np.ndarray((_HDR_LEN,), np.int64, buffer=buf, offset=off)
        off += _HDR_LEN * 8
        # owners[0] = alloc lock, owners[1] = recovery lock, then stripes
        self._owners = np.ndarray((2 + self.n_stripes,), np.int64,
                                  buffer=buf, offset=off)
        off += (2 + self.n_stripes) * 8
        self._entries = np.ndarray((self.max_entries, _E_LEN), np.int64,
                                   buffer=buf, offset=off)
        off += self.max_entries * _E_LEN * 8
        self._fifo = np.ndarray((self.max_entries,), np.int64,
                                buffer=buf, offset=off)
        self._arena = np.ndarray((self.budget_bytes,), np.uint8,
                                 buffer=buf, offset=self._arena_off)

    def __getstate__(self):
        # attach spec: name + geometry + lock handles. Lock handles only
        # pickle across a multiprocessing spawn (they raise elsewhere,
        # on purpose) — the loader's worker-spawn path is that boundary.
        return {
            "segment_name": self.segment_name,
            "budget_bytes": self.budget_bytes,
            "n_stripes": self.n_stripes,
            "max_entries": self.max_entries,
            "lock_timeout_s": self.lock_timeout_s,
            "alloc_lock": self._alloc_lock,
            "recovery_lock": self._recovery_lock,
            "stripe_locks": self._stripe_locks,
        }

    def __setstate__(self, state):
        from multiprocessing import shared_memory

        self.segment_name = state["segment_name"]
        self.budget_bytes = state["budget_bytes"]
        self.n_stripes = state["n_stripes"]
        self.max_entries = state["max_entries"]
        self.lock_timeout_s = state["lock_timeout_s"]
        self._alloc_lock = state["alloc_lock"]
        self._recovery_lock = state["recovery_lock"]
        self._stripe_locks = state["stripe_locks"]
        self._creator = False
        self._closed = False
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        meta_bytes = (_HDR_LEN + 2 + self.n_stripes
                      + self.max_entries * _E_LEN + self.max_entries) * 8
        self._arena_off = -(-meta_bytes // _ALIGN) * _ALIGN
        self._shm = shared_memory.SharedMemory(name=self.segment_name)
        self._map_views()
        _register_cache(self)

    # -- locking with orphan recovery ---------------------------------------

    def _acquire(self, lock, owner_idx: int) -> bool:
        """Deadline-bounded acquire. On timeout, a recorded owner that is
        DEAD had its semaphore recovered (released once, serialized by
        the recovery lock); an alive owner means real contention — give
        up and let the caller treat the op as a miss/skip."""
        deadline = time.monotonic() + self.lock_timeout_s
        while True:
            if lock.acquire(timeout=0.05):
                self._owners[owner_idx] = os.getpid()
                return True
            owner = int(self._owners[owner_idx])
            if owner and not _pid_alive(owner):
                if self._recovery_lock.acquire(timeout=0.2):
                    try:
                        # re-check under the recovery lock: exactly one
                        # survivor performs the release
                        if (int(self._owners[owner_idx]) == owner
                                and not _pid_alive(owner)
                                and not lock.acquire(timeout=0.01)):
                            self._owners[owner_idx] = 0
                            try:
                                lock.release()
                            except ValueError:
                                pass
                        elif int(self._owners[owner_idx]) == owner:
                            # the re-acquire succeeded: we now hold it
                            self._owners[owner_idx] = os.getpid()
                            return True
                    finally:
                        self._recovery_lock.release()
                continue
            if time.monotonic() > deadline:
                return False

    def _release(self, lock, owner_idx: int):
        self._owners[owner_idx] = 0
        lock.release()

    def _stripe_of(self, key_lo: int) -> int:
        return key_lo % self.n_stripes

    def _stripe_range(self, stripe: int) -> tuple:
        per = self.max_entries // self.n_stripes
        return stripe * per, (stripe + 1) * per

    @staticmethod
    def _scan(ent, lo_s, hi_s, ready_only: bool) -> int:
        """Find ``key`` in a stripe's bucket slice: one vectorized pass
        on key_lo, then verify the (almost always single) candidate —
        2-3× cheaper than the naive three-mask scan on the hit path.
        Returns the bucket row within ``ent``, or -1."""
        cand = np.nonzero(ent[:, _E_KEY_LO] == lo_s)[0]
        for j in cand:
            e = ent[int(j)]
            if int(e[_E_KEY_HI]) != int(hi_s):
                continue
            state = int(e[_E_STATE])
            if state == _READY or (not ready_only and state != _EMPTY):
                return int(j)
        return -1

    # -- core ---------------------------------------------------------------

    def get(self, key):
        """The cached decoded array for ``key`` (a private copy — safe to
        hand to any transform), or None. Lock-timeout degrades to a miss:
        identical pixels either way, only slower."""
        if self._closed:
            return None
        lo, hi = _digest128(key)
        lo_s, hi_s = _signed64(lo), _signed64(hi)
        stripe = self._stripe_of(lo)
        lock = self._stripe_locks[stripe]
        if not self._acquire(lock, 2 + stripe):
            self.misses += 1
            return None
        try:
            a, b = self._stripe_range(stripe)
            ent = self._entries[a:b]
            j = self._scan(ent, lo_s, hi_s, ready_only=True)
            if j < 0:
                self.misses += 1
                return None
            e = ent[j]
            off, nbytes = int(e[_E_OFF]), int(e[_E_NBYTES])
            shape = (int(e[_E_H]), int(e[_E_W]), int(e[_E_C]))
            # copy out UNDER the stripe lock: eviction must take this
            # same lock before recycling the region, so the bytes are
            # stable for the duration of the copy
            arr = np.array(self._arena[off:off + nbytes]).reshape(shape)
            self.hits += 1
            return arr
        finally:
            self._release(lock, 2 + stripe)

    def contains(self, key) -> bool:
        """READY-entry existence check without the copy-out (the shard
        prefetcher's already-staged test — a get() would memcpy the
        whole payload just to throw it away). Lock-free like
        ``with_entry``'s scan: a torn race reads as absent, which only
        costs a redundant re-stage."""
        if self._closed:
            return False
        lo, hi = _digest128(key)
        a, b = self._stripe_range(self._stripe_of(lo))
        return self._scan(self._entries[a:b], _signed64(lo), _signed64(hi),
                          ready_only=True) >= 0

    def with_entry(self, key, fn):
        """ZERO-COPY LOCK-FREE hit path: run ``fn(view)`` on the cached
        pixels in place — no slab→heap copy (the ``get`` copy measured
        ~280 µs per 600 KB decode on the bench host, most of a warm
        hit's cost) and no reader-side lock (a reader never blocks a
        writer, and a killed reader can never orphan a lock).

        Readers are SEQLOCK-validated instead: snapshot the entry's
        ``(seq, state)`` before building the view, bounds-check the
        snapshot (a torn multi-field read cannot escape the arena), run
        ``fn``, then re-check ``(seq, state)`` — eviction and overwrite
        both bump ``seq`` under the writer locks, so any mid-read
        recycling is detected and the call reports a MISS. ``fn`` may
        therefore run on torn bytes before the miss is reported: it must
        be IDEMPOTENT (safe to re-run on the miss path's freshly decoded
        buffer — restore any RNG state it consumes) and must not let
        ``view`` escape.

        Returns ``(True, result)`` on a validated hit, ``(False, None)``
        on a miss."""
        if self._closed:
            return False, None
        lo, hi = _digest128(key)
        lo_s, hi_s = _signed64(lo), _signed64(hi)
        a, b = self._stripe_range(self._stripe_of(lo))
        ent = self._entries[a:b]
        for _attempt in range(2):
            j = self._scan(ent, lo_s, hi_s, ready_only=True)
            if j < 0:
                break
            e = ent[j]
            seq1 = int(e[_E_SEQ])
            off, nbytes = int(e[_E_OFF]), int(e[_E_NBYTES])
            shape = (int(e[_E_H]), int(e[_E_W]), int(e[_E_C]))
            if int(e[_E_STATE]) != _READY or int(e[_E_SEQ]) != seq1:
                continue  # recycled between scan and snapshot: rescan
            if (shape[0] * shape[1] * shape[2] != nbytes or off < 0
                    or off + nbytes > self.budget_bytes):
                continue  # torn snapshot caught by the invariants
            view = self._arena[off:off + nbytes].reshape(shape)
            view.flags.writeable = False
            result = fn(view)
            if int(e[_E_SEQ]) == seq1 and int(e[_E_STATE]) == _READY:
                self.hits += 1
                return True, result
            # evicted/overwritten mid-read: the result may be garbage —
            # rescan once, else fall through to the miss path
        self.misses += 1
        return False, None

    def put(self, key, arr: np.ndarray) -> bool:
        """Insert a decoded uint8 HWC array, evicting oldest entries to
        fit; returns False when not cached (oversized, index full, lock
        contention/orphan, or a concurrent WRITING entry at the ring
        tail). Never blocks the decode path beyond the lock deadline."""
        if self._closed:
            return False
        arr = np.ascontiguousarray(arr)
        if arr.dtype != np.uint8 or arr.ndim != 3:
            return False  # the slab stores decoded uint8 HWC pixels only
        nbytes = int(arr.nbytes)
        need = -(-max(nbytes, 1) // _ALIGN) * _ALIGN
        arena = self.budget_bytes
        if need > arena:
            return False
        lo, hi = _digest128(key)
        lo_s, hi_s = _signed64(lo), _signed64(hi)
        stripe = self._stripe_of(lo)
        if not self._acquire(self._alloc_lock, 0):
            return False
        claimed = None
        try:
            # ring allocation: evict oldest (FIFO ≡ LRU under per-epoch
            # permutation access) until the request fits contiguously
            while True:
                head, tail = int(self._hdr[_H_HEAD]), int(self._hdr[_H_TAIL])
                pos = head % arena
                gap = arena - pos if arena - pos < need else 0
                if arena - (head - tail) >= gap + need:
                    break
                if not self._evict_oldest():
                    return False
            # claim a bucket in the key's stripe (arena → stripe order)
            lock = self._stripe_locks[stripe]
            if not self._acquire(lock, 2 + stripe):
                return False
            try:
                a, b = self._stripe_range(stripe)
                ent = self._entries[a:b]
                if self._scan(ent, lo_s, hi_s, ready_only=False) >= 0:
                    return True  # a concurrent decoder of this image won
                free = np.nonzero(ent[:, _E_STATE] == _EMPTY)[0]
                if free.size == 0:
                    return False  # stripe's index is full: skip caching
                idx = a + int(free[0])
                e = self._entries[idx]
                seq = int(e[_E_SEQ]) + 1
                e[_E_KEY_LO] = lo_s
                e[_E_KEY_HI] = hi_s
                e[_E_OFF] = 0 if gap else pos  # a wrap restarts at the base
                e[_E_NBYTES] = nbytes
                e[_E_AEND] = head + gap + need
                e[_E_H], e[_E_W], e[_E_C] = arr.shape
                e[_E_OWNER] = os.getpid()
                e[_E_SEQ] = seq
                e[_E_STATE] = _WRITING
                claimed = (idx, seq, int(e[_E_OFF]))
            finally:
                self._release(lock, 2 + stripe)
            # commit the reservation (fifo + head) last, so a failed
            # bucket claim leaves the arena untouched
            self._fifo[int(self._hdr[_H_QHEAD]) % self.max_entries] = claimed[0]
            self._hdr[_H_QHEAD] += 1
            self._hdr[_H_HEAD] = head + gap + need
        finally:
            self._release(self._alloc_lock, 0)

        # pixel copy OUTSIDE the locks: the region is reserved (eviction
        # refuses live WRITING entries) and invisible until READY
        off = claimed[2]
        self._arena[off:off + nbytes] = arr.reshape(-1).view(np.uint8)
        lock = self._stripe_locks[stripe]
        if self._acquire(lock, 2 + stripe):
            try:
                e = self._entries[claimed[0]]
                if int(e[_E_SEQ]) == claimed[1] \
                        and int(e[_E_STATE]) == _WRITING:
                    e[_E_OWNER] = 0
                    e[_E_STATE] = _READY
                    return True
            finally:
                self._release(lock, 2 + stripe)
        # commit failed (stripe-lock timeout, or the entry was reclaimed
        # under us): abandon the claim. Zeroing the owner lets eviction
        # treat OUR still-WRITING entry like a dead writer's — otherwise
        # a live-owner WRITING entry at the ring tail would refuse
        # eviction forever and wedge every future allocation. A single
        # int64 store is safe without the lock: only the owner (us) or a
        # dead-owner reclaim ever touches a WRITING entry's fields.
        e = self._entries[claimed[0]]
        if int(e[_E_SEQ]) == claimed[1] and int(e[_E_STATE]) == _WRITING:
            e[_E_OWNER] = 0
        return False

    def _evict_oldest(self) -> bool:
        """Pop the ring-oldest entry (caller holds the alloc lock).
        A WRITING victim whose owner is still alive aborts the eviction
        (its bytes are in flight); a dead owner's half-write is
        reclaimed."""
        qhead, qtail = int(self._hdr[_H_QHEAD]), int(self._hdr[_H_QTAIL])
        if qtail >= qhead:
            # no live entries but the arena math says full — only
            # possible via a wrap gap with an empty ring: hard reset
            self._hdr[_H_HEAD] = self._hdr[_H_TAIL] = 0
            return True
        idx = int(self._fifo[qtail % self.max_entries])
        e = self._entries[idx]
        if int(e[_E_STATE]) == _WRITING and _pid_alive(int(e[_E_OWNER])):
            return False
        key_lo = int(e[_E_KEY_LO])
        stripe = self._stripe_of(key_lo & ((1 << 64) - 1))
        lock = self._stripe_locks[stripe]
        if not self._acquire(lock, 2 + stripe):
            return False
        try:
            self._hdr[_H_TAIL] = int(e[_E_AEND])
            e[_E_SEQ] = int(e[_E_SEQ]) + 1  # invalidate in-flight commits
            e[_E_STATE] = _EMPTY
            e[_E_KEY_LO] = e[_E_KEY_HI] = 0
            self._hdr[_H_QTAIL] = qtail + 1
            self.evictions += 1
            return True
        finally:
            self._release(lock, 2 + stripe)

    # -- introspection ------------------------------------------------------

    @property
    def bytes_in_use(self) -> int:
        """Arena bytes between ring tail and head (includes alignment
        padding and wrap gaps — the honest /dev/shm working set)."""
        return int(self._hdr[_H_HEAD]) - int(self._hdr[_H_TAIL])

    def __len__(self) -> int:
        return int(np.count_nonzero(self._entries[:, _E_STATE] == _READY))

    @property
    def closed(self) -> bool:
        return self._closed

    def stats(self) -> dict:
        total = self.hits + self.misses
        return {
            "cache_hits": self.hits,
            "cache_misses": self.misses,
            "cache_evictions": self.evictions,
            "cache_entries": len(self),
            "cache_bytes_in_use": self.bytes_in_use,
            "cache_budget_bytes": self.budget_bytes,
            "cache_scope": self.scope,
            "cache_hit_rate": (self.hits / total) if total else 0.0,
        }

    # -- pooling ------------------------------------------------------------

    def scale_budget(self, divisor: int):
        """No-op BY DESIGN: the slab is one pooled budget shared by every
        attached process — there is nothing to divide (the sharded
        ``DecodeCache`` splits its budget here instead)."""
        if divisor <= 0:
            raise ValueError(f"divisor must be positive, got {divisor}")

    # -- lifecycle ----------------------------------------------------------

    def close(self):
        if self._closed:
            return
        self._closed = True
        # the mapped views are set once at attach (_map_views, from
        # __init__/__setstate__) and dropped once here: any worker
        # racing a close sees either the live views or the _closed
        # flag's miss-only path
        self._hdr = self._owners = self._entries = None  # owned-by: closing-caller
        self._fifo = self._arena = None  # owned-by: closing-caller
        close_segment(self._shm, unlink=self._creator)
        _LIVE_CACHES.discard(self)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
