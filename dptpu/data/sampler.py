"""Per-host disjoint sharding with per-epoch reshuffle.

``ShardedSampler`` is the DistributedSampler contract (reference
imagenet_ddp.py:175-183; README.md:61): each shard (here: each *host* — chips
on a host share one process, SURVEY.md §1 L1) sees a disjoint 1/N slice,
padded by wrap-around so every shard draws the same number of samples, and
the permutation is reseeded from ``(seed, epoch)`` — the
``train_sampler.set_epoch(epoch)`` analog (imagenet_ddp.py:202) made
explicit: ``epoch`` is an argument, not mutable sampler state.

This purity is also the RESILIENCE contract (dptpu/resilience): because
the whole epoch permutation is a function of ``(seed, epoch)`` alone —
no consumed-iterator state — any mid-epoch position is replayable after a
preemption. A checkpoint only needs ``(epoch, step_in_epoch)``; the
resumed ``DataLoader.epoch(epoch, start_batch=step_in_epoch)`` rebuilds
the identical permutation and skips forward, so the batches (and with
them the loss trajectory) match the uninterrupted run bit for bit.
"""

from __future__ import annotations

import numpy as np


class ShardedSampler:
    def __init__(self, num_examples: int, num_shards: int = 1,
                 shard_index: int = 0, shuffle: bool = True, seed: int = 0,
                 drop_last: bool = False):
        if not 0 <= shard_index < num_shards:
            raise ValueError(f"shard_index {shard_index} not in [0, {num_shards})")
        self.num_examples = num_examples
        self.num_shards = num_shards
        self.shard_index = shard_index
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        if drop_last:
            self.samples_per_shard = num_examples // num_shards
        else:
            self.samples_per_shard = -(-num_examples // num_shards)  # ceil

    def __len__(self) -> int:
        return self.samples_per_shard

    def indices(self, epoch: int = 0) -> np.ndarray:
        """This shard's index slice for ``epoch`` (set_epoch analog)."""
        return self.indices_and_validity(epoch)[0]

    def _epoch_order(self, epoch: int) -> np.ndarray:
        """The full-dataset visit order for ``epoch`` — a pure function
        of ``(seed, epoch)`` (the resilience replay contract). Subclasses
        may reorder (e.g. the packed-shard locality sampler,
        dptpu/data/shards.py) but must stay pure in the same inputs."""
        if self.shuffle:
            return np.random.RandomState(self.seed + epoch).permutation(
                self.num_examples
            )
        return np.arange(self.num_examples)

    def indices_and_validity(self, epoch: int = 0):
        """``(indices, valid)`` for this shard and ``epoch``.

        ``valid`` is a bool array flagging which positions are real samples
        vs wrap-around padding. DistributedSampler pads by wrap-around so
        every shard draws the same count (imagenet_ddp.py:175-183) — fine
        for training, but an *exact* psum-aggregated validation
        (imagenet_ddp_apex.py:457-460) must not count the duplicated
        samples twice, so the loader zeroes their mask entries.
        """
        order = self._epoch_order(epoch)
        total = self.samples_per_shard * self.num_shards
        valid = np.ones(max(total, order.size), np.bool_)
        if total > order.size:  # pad by wrap-around (DistributedSampler)
            valid[order.size:] = False
            order = np.concatenate([order, order[: total - order.size]])
        else:
            order = order[:total]
            valid = valid[:total]
        # interleaved assignment: shard i takes order[i::num_shards],
        # so shards stay disjoint for any epoch
        sl = slice(self.shard_index, None, self.num_shards)
        return order[sl], valid[sl]
