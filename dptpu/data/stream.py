"""Streaming shard I/O: O_DIRECT byte ring, extent prefetch, ShardStreamDataset.

The local engine is an O_DIRECT double-buffered aligned byte ring:
shard extents are read with the page cache BYPASSED, so cold-epoch
throughput no longer depends on the dataset fitting in RAM — the exact
production case the shipped ``posix_fadvise`` readahead cannot help
(it only warms a cache the dataset immediately evicts). Reads go
through 4 KiB-aligned buffers at aligned offsets; the prefetcher keeps
TWO of them in flight (read extent k+1 while extent k is being copied
into the pooled staging slab) so the disk never idles behind the copy.
Filesystems that refuse O_DIRECT (tmpfs, some overlayfs) are detected
at open/first-read time and fall back to plain ``pread`` — recorded in
``io_stats`` (``odirect_active`` / ``odirect_why``), never silent.

The remote engine is the :class:`~dptpu.data.store.Store` range
fetcher: the same prefetcher pulls coalesced extent ranges (or whole
shards, ``DPTPU_STORE_FETCH=shard``) over HTTP with retry/backoff.

Both engines stage bytes into the POOLED ``/dev/shm`` slab
(:class:`~dptpu.data.store.ShardByteCache` — the PR 3 decode-cache
machinery reused byte-for-byte): the PARENT's prefetcher writes extents
in at span pre-issue time (the decode-ahead pump's moment), and every
DECODE WORKER reads them out — O_DIRECT bypasses the page cache, so the
slab IS the hand-off between the process that reads and the processes
that decode. A worker that misses (cold start, eviction) reads its own
extent directly; every fetched extent is CRC-verified against the
shard index before a single byte is decoded.

:class:`ShardStreamDataset` is the ImageFolder drop-in over a packed
split (local dir, ``file://`` or ``http(s)://``): same
``get``/``get_into`` surface, same transforms, same decode-cache knobs
— and the same ``(seed, epoch, index)`` bit-identity contract, because
the extents hold the source files' exact bytes and decode goes through
the SAME code paths (dptpu/data/dataset.py's bytes-level helpers).
It deliberately exposes NO ``samples`` path list — the shm pipeline's
``posix_fadvise`` readahead therefore never arms — and instead exposes
``prefetch_extents``, which the loader calls at the same pre-issue
moment; the two I/O paths are mutually exclusive by construction (and
asserted in ``feed_stats``).

Env knobs (fail-fast, the locked contract):

* ``DPTPU_SHARD_CACHE_BYTES`` — staging slab budget (default 128 MiB;
  0 disables staging: every read is direct);
* ``DPTPU_ODIRECT`` — use O_DIRECT for local shard reads when the
  filesystem supports it (default on; off forces plain ``pread``);
* ``DPTPU_STORE_FETCH`` — remote prefetch granularity: ``extent``
  (coalesced ranges, default) or ``shard`` (whole data region on first
  touch).

Worker-safe: stdlib + numpy only, never JAX.
"""

from __future__ import annotations

import os
import queue as _queue
import threading
import weakref
from typing import List, Optional, Tuple

import numpy as np

from dptpu.data.shards import (
    IDX_CRC,
    IDX_FLAGS,
    IDX_LABEL,
    IDX_LEN,
    IDX_OFF,
    FLAG_JPEG,
    ShardSet,
    verify_sample,
)
from dptpu.data.store import (
    LocalStore,
    ShardByteCache,
    Store,
    open_store,
)
from dptpu.envknob import env_bool, env_choice, env_int
from dptpu.utils.sync import OrderedLock, StopToken

ALIGN = 4096
_COALESCE_GAP = 64 << 10  # merge extents closer than this into one read
_MAX_RANGE = 8 << 20  # cap one coalesced read (bounds buffer + latency)

# open shard fds in THIS process — the conftest leak guard's census
_OPEN_READERS: "weakref.WeakSet" = weakref.WeakSet()


def open_fd_count() -> int:
    """Shard-file descriptors still open in this process (the conftest
    session guard fails the suite when a dataset leaks them past
    ``close()``)."""
    return sum(1 for r in list(_OPEN_READERS) if r._fd is not None)


def _aligned_buffer(nbytes: int):
    """``(keepalive, view)`` where ``view`` is an ALIGN-aligned uint8
    array of ``nbytes`` — the O_DIRECT user-buffer requirement."""
    raw = np.empty(nbytes + ALIGN, np.uint8)
    off = (-raw.ctypes.data) % ALIGN
    return raw, raw[off:off + nbytes]


class ShardFileReader:
    """One shard file, read via O_DIRECT when the filesystem allows it
    (probed at open AND first read — some filesystems accept the open
    flag and fail the read) with a plain-``pread`` fallback. Lazy open,
    per process; never pickled (the engine recreates readers post-
    spawn)."""

    def __init__(self, path: str, want_odirect: bool = True):
        self.path = path
        self.want_odirect = want_odirect and hasattr(os, "O_DIRECT")
        self._fd: Optional[int] = None  # guarded-by: _lock
        self.odirect = False  # guarded-by: _lock
        self.odirect_why = ""  # guarded-by: _lock
        self._lock = OrderedLock("data.shard_reader")
        _OPEN_READERS.add(self)

    def _ensure_open_locked(self):
        if self._fd is not None:
            return
        if self.want_odirect:
            try:
                self._fd = os.open(self.path, os.O_RDONLY | os.O_DIRECT)
                self.odirect = True
                return
            except OSError as e:
                self.odirect_why = (
                    f"O_DIRECT open refused by the filesystem ({e}); "
                    f"plain read() fallback"
                )
        elif not hasattr(os, "O_DIRECT"):
            self.odirect_why = "platform has no O_DIRECT"
        elif not self.want_odirect:
            self.odirect_why = "disabled (DPTPU_ODIRECT=0)"
        self._fd = os.open(self.path, os.O_RDONLY)
        self.odirect = False

    def _fall_back_locked(self, why: str):
        if self._fd is not None:
            os.close(self._fd)
        self._fd = os.open(self.path, os.O_RDONLY)
        self.odirect = False
        self.odirect_why = why

    def read_range(self, offset: int, length: int,
                   buf: Optional[np.ndarray] = None) -> bytes:
        """``length`` bytes at ``offset`` — via an aligned enclosing
        O_DIRECT read (into ``buf`` when provided and big enough: the
        prefetcher's double-buffer) or a plain pread."""
        with self._lock:
            self._ensure_open_locked()
            if self.odirect:
                a0 = (offset // ALIGN) * ALIGN
                need = -(-(offset + length - a0) // ALIGN) * ALIGN
                if buf is None or buf.size < need:
                    _keep, view = _aligned_buffer(need)
                else:
                    view = buf[:need]
                got = 0
                try:
                    while got < need:
                        n = os.preadv(self._fd, [view[got:need]], a0 + got)
                        if n <= 0:
                            break  # EOF
                        got += n
                except OSError as e:
                    # the open accepted O_DIRECT but the read refused it
                    # (overlayfs quirk): fall back for the file's lifetime
                    self._fall_back_locked(
                        f"O_DIRECT read failed ({e}); plain read() "
                        f"fallback"
                    )
                    return self._plain_read_locked(offset, length)
                if got < (offset - a0) + length:
                    raise OSError(
                        f"{self.path}: short read — wanted "
                        f"[{offset}:{offset + length}) but the aligned "
                        f"read ended {got} bytes after {a0} (truncated "
                        f"shard?)"
                    )
                lo = offset - a0
                return view[lo:lo + length].tobytes()
            return self._plain_read_locked(offset, length)

    def _plain_read_locked(self, offset: int, length: int) -> bytes:
        out = bytearray()
        while len(out) < length:
            chunk = os.pread(self._fd, length - len(out),
                             offset + len(out))
            if not chunk:
                raise OSError(
                    f"{self.path}: short read at {offset + len(out)} "
                    f"(wanted {length} bytes; truncated shard?)"
                )
            out.extend(chunk)
        return bytes(out)

    def close(self):
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _coalesce(extents: List[Tuple[int, int, int]],
              max_range: int = _MAX_RANGE,
              gap: int = _COALESCE_GAP):
    """Merge per-sample extents ``(offset, length, tag)`` (sorted by
    offset) into read ranges ``(range_off, range_len, [members])`` —
    sequential I/O instead of one syscall/request per sample."""
    out = []
    cur_off = cur_end = None
    members: list = []
    for off, length, tag in sorted(extents):
        if cur_off is not None and off - cur_end <= gap \
                and (off + length) - cur_off <= max_range:
            cur_end = max(cur_end, off + length)
            members.append((off, length, tag))
            continue
        if cur_off is not None:
            out.append((cur_off, cur_end - cur_off, members))
        cur_off, cur_end = off, off + length
        members = [(off, length, tag)]
    if cur_off is not None:
        out.append((cur_off, cur_end - cur_off, members))
    return out


class ShardIOEngine:
    """Per-process byte source for a packed split: resolves a global
    sample index to its shard extent and fetches the bytes — staging
    slab first, then the local O_DIRECT/pread reader or the remote
    store range fetch. The PARENT additionally runs the prefetcher
    (:meth:`prefetch`) that fills the slab ahead of the decode
    workers."""

    def __init__(self, shard_set: ShardSet, byte_cache: Optional[
                 ShardByteCache], cache_tag: str, odirect: bool = True,
                 fetch_mode: str = "extent"):
        self.shard_set = shard_set
        self.byte_cache = byte_cache
        self.cache_tag = cache_tag
        self.odirect_wanted = odirect
        self.fetch_mode = fetch_mode
        self.store = shard_set.store
        self._local = isinstance(self.store, LocalStore)
        # the reader table is reached from the prefetcher thread AND
        # the consumer decode path — creation races would leak an fd
        self._readers: dict = {}  # guarded-by: _lock
        self._whole_fetched: set = set()  # owned-by: prefetch-thread
        self._prefetcher: Optional[_ExtentPrefetcher] = None  # owned-by: caller
        self._lock = OrderedLock("data.shard_engine")
        # telemetry (this process)
        self.bytes_read = 0  # guarded-by: _lock
        self.extents_read = 0  # guarded-by: _lock
        self.cache_hits = 0  # guarded-by: _lock
        self.cache_misses = 0  # guarded-by: _lock

    # -- byte sources -------------------------------------------------------

    def _reader(self, shard_id: int) -> ShardFileReader:
        with self._lock:
            r = self._readers.get(shard_id)
            if r is None:
                path = self.store.path_for(
                    self.shard_set.shard_names[shard_id])
                r = ShardFileReader(path, want_odirect=self.odirect_wanted)
                self._readers[shard_id] = r
            return r

    def _fetch_range(self, shard_id: int, offset: int, length: int,
                     buf: Optional[np.ndarray] = None) -> bytes:
        if self._local:
            data = self._reader(shard_id).read_range(offset, length, buf)
        else:
            data = self.store.get_range(
                self.shard_set.shard_names[shard_id], offset, length
            )
            if len(data) < length:
                raise OSError(
                    f"{self.shard_set.shard_names[shard_id]}: range "
                    f"fetch returned {len(data)} of {length} bytes"
                )
        with self._lock:
            self.bytes_read += length
            self.extents_read += 1
        return data

    def _cache_key(self, shard_id: int, pos: int):
        return ("dpts", self.cache_tag, shard_id, pos)

    def read_sample(self, gidx: int) -> Tuple[bytes, int, bool]:
        """``(encoded bytes, label, is_jpeg)`` for global index ``gidx``
        — slab hit, or a direct (CRC-verified) read."""
        shard_id, pos = self.shard_set.locate(gidx)
        _hdr, idx = self.shard_set.shard_table(shard_id)
        row = idx[pos]
        return (self.read_row(shard_id, pos),
                int(row[IDX_LABEL]),
                bool(int(row[IDX_FLAGS]) & FLAG_JPEG))

    def read_row(self, shard_id: int, pos: int) -> bytes:
        """The encoded bytes for one ALREADY-RESOLVED extent — callers
        that looked the extent up for its metadata (the dataset's
        decode path) fetch through here so the locate/row resolution
        never runs twice per sample."""
        hdr, idx = self.shard_set.shard_table(shard_id)
        row = idx[pos]
        length = int(row[IDX_LEN])
        key = self._cache_key(shard_id, pos)
        if self.byte_cache is not None and not self.byte_cache.closed:
            data = self.byte_cache.get(key, length)
            if data is not None:
                with self._lock:
                    self.cache_hits += 1
                # slab entries were CRC-verified on fill; verify again
                # anyway — the check is cheap and a torn slab read
                # must never reach the decoder
                return verify_sample(
                    data, int(row[IDX_CRC]),
                    self.shard_set.shard_names[shard_id], pos,
                )
            with self._lock:
                self.cache_misses += 1
        data = self._fetch_range(
            shard_id, hdr["data_off"] + int(row[IDX_OFF]), length
        )
        # deliberately NO put-on-miss: each sample is consumed once per
        # epoch, so staging a consumer's own miss helps nobody — only
        # the parent prefetcher (which stages AHEAD of consumption)
        # writes the slab
        return verify_sample(data, int(row[IDX_CRC]),
                             self.shard_set.shard_names[shard_id], pos)

    # -- prefetch (parent side) ---------------------------------------------

    def prefetch(self, indices) -> None:
        """Queue upcoming samples' extents for background staging into
        the slab — the loader calls this at span pre-issue time, so the
        bytes land ``decode_ahead`` batches before a worker asks. No-op
        without a staging slab (nowhere to put the bytes)."""
        if self.byte_cache is None:
            return
        if self._prefetcher is None:
            self._prefetcher = _ExtentPrefetcher(self)
        self._prefetcher.enqueue([int(i) for i in indices])

    def _stage_batch(self, indices: List[int]):
        """Resolve indices to extents, coalesce per shard, fetch each
        range (double-buffered on the local O_DIRECT path), slice the
        member extents out and put them into the slab. Runs on the
        prefetcher thread."""
        from dptpu import obs

        by_shard: dict = {}
        for g in indices:
            shard_id, pos = self.shard_set.locate(g)
            hdr, idx = self.shard_set.shard_table(shard_id)
            row = idx[pos]
            if self.byte_cache.contains(self._cache_key(shard_id, pos)):
                continue  # already staged
            by_shard.setdefault(shard_id, []).append((
                hdr["data_off"] + int(row[IDX_OFF]), int(row[IDX_LEN]),
                (pos, int(row[IDX_CRC])),
            ))
        for shard_id, extents in by_shard.items():
            if self.fetch_mode == "shard" and not self._local:
                self._stage_whole_shard(shard_id)
                continue
            ranges = _coalesce(extents)
            with obs.get_tracer().span("shard_fetch"):
                self._stage_ranges(shard_id, ranges)

    def _stage_ranges(self, shard_id: int, ranges):
        """The double-buffered byte ring: while range k is being sliced
        and copied into the slab, range k+1 is already being read into
        the OTHER aligned buffer. The two buffers are PERSISTENT (grown
        to the largest range seen, capped by the coalescer) — one
        prefetch thread, strictly alternating, so reuse across calls
        cannot race."""
        need = max(length for _, length, _m in ranges) + 2 * ALIGN
        bufs = getattr(self, "_ring_bufs", None)
        if bufs is None or bufs[0][1].size < need:
            bufs = self._ring_bufs = [  # owned-by: prefetch-thread
                _aligned_buffer(need), _aligned_buffer(need),
            ]
        ex = self._range_executor()
        nxt = None
        for k, (off, length, members) in enumerate(ranges):
            buf = bufs[k % 2][1]
            fut = ex.submit(self._fetch_range, shard_id, off, length, buf)
            if nxt is not None:
                self._stage_members(shard_id, *nxt)
            nxt = (fut, off, members)
        if nxt is not None:
            self._stage_members(shard_id, *nxt)

    def _stage_members(self, shard_id: int, fut, range_off: int, members):
        data = fut.result()
        for off, length, (pos, crc) in members:
            lo = off - range_off
            payload = data[lo:lo + length]
            try:
                verify_sample(payload, crc,
                              self.shard_set.shard_names[shard_id], pos)
            except Exception:
                continue  # the consumer's direct read surfaces it loudly
            self.byte_cache.put(self._cache_key(shard_id, pos), payload)

    def _range_executor(self):
        from concurrent.futures import ThreadPoolExecutor

        if not hasattr(self, "_range_pool"):
            self._range_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="dptpu-shard-read"
            )
        return self._range_pool

    def _stage_whole_shard(self, shard_id: int):
        """Remote whole-shard mode: pull the full data region once and
        populate every extent of the shard into the slab (skipped when
        the shard exceeds half the slab budget — it would evict itself)."""
        if shard_id in self._whole_fetched:
            return
        hdr, idx = self.shard_set.shard_table(shard_id)
        budget = self.byte_cache._cache.budget_bytes
        if hdr["data_len"] > budget // 2:
            ranges = _coalesce([
                (hdr["data_off"] + int(r[IDX_OFF]), int(r[IDX_LEN]),
                 (int(p), int(r[IDX_CRC])))
                for p, r in enumerate(idx)
            ])
            self._stage_ranges(shard_id, ranges)
            return
        data = self._fetch_range(shard_id, hdr["data_off"],
                                 hdr["data_len"])
        # mark AFTER the fetch succeeded: a failed first touch (remote
        # flake past the retry budget) must stay retryable on the next
        # prefetch, not silently demote the shard to per-extent direct
        # reads for the rest of the run
        self._whole_fetched.add(shard_id)
        for pos in range(hdr["num_samples"]):
            off, length = int(idx[pos, IDX_OFF]), int(idx[pos, IDX_LEN])
            payload = data[off:off + length]
            try:
                verify_sample(payload, int(idx[pos, IDX_CRC]),
                              self.shard_set.shard_names[shard_id], pos)
            except Exception:
                continue
            self.byte_cache.put(self._cache_key(shard_id, pos), payload)

    # -- telemetry / lifecycle ----------------------------------------------

    def io_stats(self) -> dict:
        with self._lock:
            stats = {
                "shard_streaming": True,
                "shard_bytes_read": self.bytes_read,
                "shard_extents_read": self.extents_read,
                "shard_cache_hits": self.cache_hits,
                "shard_cache_misses": self.cache_misses,
            }
            probe = next(iter(self._readers.values()), None)
        if self._local:
            stats["odirect_active"] = bool(probe and probe.odirect)
            if probe is not None and not probe.odirect:
                stats["odirect_why"] = probe.odirect_why
        else:
            stats["odirect_active"] = False
            stats["odirect_why"] = "remote store (range fetch)"
        stats.update(self.store.stats())
        if self.byte_cache is not None and not self.byte_cache.closed:
            stats.update(self.byte_cache.stats())
        return stats

    def close(self):
        if self._prefetcher is not None:
            self._prefetcher.close()
            self._prefetcher = None
        if hasattr(self, "_range_pool"):
            self._range_pool.shutdown(wait=True)
            del self._range_pool
        with self._lock:
            readers = list(self._readers.values())
            self._readers.clear()
        for r in readers:
            r.close()


class _ExtentPrefetcher:
    """One background thread draining index batches into
    :meth:`ShardIOEngine._stage_batch`. The queue is SHALLOW and lossy
    (prefetch is advisory — a dropped batch just means the worker's own
    direct read pays the latency instead).

    Teardown rides the shared :class:`dptpu.utils.sync.StopToken`
    idiom: ``close()`` trips the token and nudges the queue with a
    sentinel, so the drain loop wakes IMMEDIATELY whether it was parked
    in ``get()`` or mid-stage — and ``close()`` itself never blocks on
    a full queue (the old ``put(None)`` could)."""

    def __init__(self, engine: ShardIOEngine, depth: int = 8):
        self._engine = engine
        self._q: "_queue.Queue" = _queue.Queue(maxsize=depth)
        self._stop = StopToken()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="dptpu-shard-prefetch"
        )
        self._thread.start()

    def enqueue(self, indices: List[int]):
        if self._stop.stopped:
            return  # closing: new work would race the teardown
        try:
            self._q.put_nowait(indices)
        except _queue.Full:
            pass  # advisory: the consumer is ahead of the disk already

    def _run(self):
        while not self._stop.stopped:
            item = self._q.get()
            if item is None or self._stop.stopped:
                return
            try:
                self._engine._stage_batch(item)
            except Exception:
                # prefetch must never kill the run: the consumer-side
                # direct read will surface any real error with context
                pass

    def close(self):
        self._stop.stop()
        try:
            # wake a get()-parked drain loop; a FULL queue needs no
            # nudge (the pending item wakes it and the token exits)
            self._q.put_nowait(None)
        except _queue.Full:
            pass
        self._thread.join(timeout=5.0)


def _shard_knobs(byte_cache_bytes, odirect, fetch_mode):
    """The streaming knobs under the locked fail-fast contract."""
    if byte_cache_bytes is None:
        byte_cache_bytes = env_int("DPTPU_SHARD_CACHE_BYTES", 128 << 20)
    if byte_cache_bytes < 0:
        raise ValueError(
            f"DPTPU_SHARD_CACHE_BYTES={byte_cache_bytes} must be >= 0 "
            f"bytes (0 disables the staging slab)"
        )
    if odirect is None:
        odirect = env_bool("DPTPU_ODIRECT", True)
    if fetch_mode is None:
        fetch_mode = env_choice(
            "DPTPU_STORE_FETCH", ("extent", "shard"), default="extent"
        )
    elif fetch_mode not in ("extent", "shard"):
        raise ValueError(
            f"fetch_mode={fetch_mode!r} must be 'extent' or 'shard'"
        )
    return byte_cache_bytes, odirect, fetch_mode


class ShardStreamDataset:
    """ImageFolder-semantics dataset over a PACKED split (local path,
    ``file://`` or ``http(s)://`` store URL): same classes/labels, same
    transforms, same per-``(seed, epoch, index)`` pixels — streaming vs
    ImageFolder batches are bit-identical by construction (locked by
    tests and the DATABENCH gate). See the module docstring for the
    I/O engine underneath.

    ``cache_bytes``/``cache_scope`` attach the DECODED-pixel cache
    exactly as on :class:`ImageFolderDataset`; ``byte_cache_bytes``
    budgets the ENCODED-byte staging slab (``DPTPU_SHARD_CACHE_BYTES``).
    """

    def __init__(self, location: str, transform=None, cache_bytes: int = 0,
                 cache_scope: str = "sharded",
                 byte_cache_bytes: Optional[int] = None,
                 odirect: Optional[bool] = None,
                 fetch_mode: Optional[str] = None,
                 store: Optional[Store] = None):
        self.location = location
        self.transform = transform
        if cache_scope not in ("sharded", "pooled"):
            raise ValueError(
                f"cache_scope={cache_scope!r} must be 'sharded' or "
                f"'pooled'"
            )
        if cache_bytes and cache_scope == "pooled":
            from dptpu.data.shm_cache import ShmDecodeCache

            self.decode_cache = ShmDecodeCache(cache_bytes)
        elif cache_bytes:
            from dptpu.data.cache import DecodeCache

            self.decode_cache = DecodeCache(cache_bytes)
        else:
            self.decode_cache = None
        byte_cache_bytes, odirect, fetch_mode = _shard_knobs(
            byte_cache_bytes, odirect, fetch_mode
        )
        self._odirect = odirect
        self._fetch_mode = fetch_mode
        self.shard_set = ShardSet(store if store is not None
                                  else open_store(location))
        self.classes = self.shard_set.classes
        self.class_to_idx = {c: i for i, c in enumerate(self.classes)}
        self.byte_cache = (
            ShardByteCache(byte_cache_bytes) if byte_cache_bytes else None
        )
        self._engine: Optional[ShardIOEngine] = None
        self._closed = False

    # NOTE deliberately NO ``samples`` attribute: the shm pipeline keys
    # its posix_fadvise readahead off it, and the shard engine owns the
    # I/O here (fadvise would repopulate the page cache O_DIRECT just
    # bypassed). The loader routes ``prefetch_extents`` instead.

    def __len__(self) -> int:
        return self.shard_set.num_samples

    def shard_of(self, index: int) -> int:
        """The packed shard holding global sample ``index`` — the
        shm pipeline's shard-level cache-affinity key (dptpu/data/
        shm.py): routing a whole shard's extents to one worker by a
        stable hash of THIS id (not the sample index) keeps that
        shard's decoded pixels hot in the worker's reach and its byte
        extents coalesced in one engine stream."""
        return self.shard_set.locate(index)[0]

    def __getstate__(self):
        # spawn boundary: workers rebuild their own engine (fds, HTTP
        # connections and threads never cross); per-shard index tables
        # re-fetch lazily so the pickle stays manifest-sized
        state = dict(self.__dict__)
        state["_engine"] = None
        shard_set = state["shard_set"]
        clone = ShardSet.__new__(ShardSet)
        clone.__dict__ = dict(shard_set.__dict__)
        clone._headers = {}
        clone._indexes = {}
        state["shard_set"] = clone
        return state

    def engine(self) -> ShardIOEngine:
        if self._engine is None:
            self._engine = ShardIOEngine(
                self.shard_set, self.byte_cache, cache_tag=self.location,
                odirect=self._odirect, fetch_mode=self._fetch_mode,
            )
        return self._engine

    # -- the ImageFolder surface --------------------------------------------

    def _decode(self, index: int, rng, out=None):
        from dptpu.data.dataset import (
            native_decode_sample,
            pil_decode_sample,
        )

        engine = self.engine()
        holder = {}
        # extent metadata (label, jpeg flag) WITHOUT fetching bytes —
        # the decode-cache hit path must not touch the store at all —
        # and the resolved (shard, pos) rides into the byte thunk so
        # the locate/row lookup never runs twice per sample
        shard_id, pos = self.shard_set.locate(index)
        _hdr, idx = self.shard_set.shard_table(shard_id)
        ext_row = idx[pos]
        label = int(ext_row[IDX_LABEL])
        is_jpeg = bool(int(ext_row[IDX_FLAGS]) & FLAG_JPEG)

        def read_bytes():
            return engine.read_row(shard_id, pos)

        key = ("dpts", self.location, int(index))
        arr = native_decode_sample(
            read_bytes, is_jpeg, self.transform, rng,
            decode_cache=self.decode_cache, cache_key=("native",) + key,
            out=out,
        )
        if arr is None:
            arr = pil_decode_sample(
                read_bytes, self.transform, rng,
                decode_cache=self.decode_cache, cache_key=("pil",) + key,
            )
            holder["pil"] = True
        return arr, label, holder

    def get(self, index: int, rng: Optional[np.random.Generator] = None):
        """Load + transform one sample; mirrors
        :meth:`ImageFolderDataset.get` (same rng convention, same decode
        paths, bit-identical pixels for the same source image)."""
        if rng is None:
            rng = np.random.default_rng(index)
        arr, label, _ = self._decode(index, rng)
        return arr, label

    def get_into(self, index: int, rng, out: np.ndarray) -> int:
        """Decode + transform DIRECTLY into ``out`` (one row of the
        loader's preallocated batch); returns the label."""
        from dptpu.data.dataset import _copy_checked

        arr, label, holder = self._decode(index, rng, out=out)
        if holder.get("pil") or arr is not out:
            _copy_checked(out, arr, index)
        return label

    def __getitem__(self, index: int):
        return self.get(index)

    # -- loader hooks --------------------------------------------------------

    def prefetch_extents(self, indices) -> None:
        """Pre-issue hook (the fadvise slot's replacement): stage these
        samples' extents into the slab ahead of the decode workers."""
        if not self._closed:
            self.engine().prefetch(indices)

    def io_stats(self) -> dict:
        if self._closed:
            return {"shard_streaming": True}
        stats = self.engine().io_stats()
        stats["shard_fetch_mode"] = self._fetch_mode
        return stats

    def close(self):
        if self._closed:
            return
        self._closed = True
        if self._engine is not None:
            self._engine.close()
            self._engine = None
        if self.byte_cache is not None:
            self.byte_cache.close()
        cache = self.decode_cache
        if cache is not None and hasattr(cache, "close"):
            cache.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
