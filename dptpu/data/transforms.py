"""Host-side image transforms with torchvision-exact sampling math.

Train stack = RandomResizedCrop(224) → RandomHorizontalFlip; val stack =
Resize(256) → CenterCrop(224) (reference imagenet_ddp.py:163-194). ToTensor +
Normalize are deliberately ABSENT: like the Apex fast path ("Too slow" on
CPU, imagenet_ddp_apex.py:215-226), output stays uint8 HWC and normalization
happens on-device inside the compiled step (dptpu.train.step.normalize_images).

Crop-geometry *sampling* (the randomness) is separated from *application*
(the pixels): ``TrainTransform.sample`` draws the torchvision
RandomResizedCrop box + flip from an explicit ``numpy.random.Generator``, and
either the PIL path here or the native C++ decoder
(dptpu/native, libjpeg decode + fused bilinear crop-resize) applies it.
Both appliers consume identical boxes, so a seeded run selects identical
crops regardless of which backend decodes (the ``--seed`` contract,
nd_imagenet.py:68-69,84-92).
"""

from __future__ import annotations

import math

import numpy as np

_BILINEAR = 2  # PIL.Image.BILINEAR


def sample_rrc_box(width, height, rng, scale=(0.08, 1.0),
                   ratio=(3.0 / 4.0, 4.0 / 3.0)):
    """torchvision RandomResizedCrop geometry: area ~ U(scale)·A, log-uniform
    aspect, 10 attempts, then the aspect-clamped center-crop fallback.
    Returns ``(left, top, crop_w, crop_h)`` in original-image coordinates."""
    area = width * height
    log_ratio = (math.log(ratio[0]), math.log(ratio[1]))
    for _ in range(10):
        target_area = area * rng.uniform(scale[0], scale[1])
        aspect = math.exp(rng.uniform(log_ratio[0], log_ratio[1]))
        cw = int(round(math.sqrt(target_area * aspect)))
        ch = int(round(math.sqrt(target_area / aspect)))
        if 0 < cw <= width and 0 < ch <= height:
            left = int(rng.integers(0, width - cw + 1))
            top = int(rng.integers(0, height - ch + 1))
            return left, top, cw, ch
    # fallback: clamp aspect, center crop
    in_ratio = width / height
    if in_ratio < ratio[0]:
        cw, ch = width, int(round(width / ratio[0]))
    elif in_ratio > ratio[1]:
        ch, cw = height, int(round(height * ratio[1]))
    else:
        cw, ch = width, height
    return (width - cw) // 2, (height - ch) // 2, cw, ch


def center_fit_box(width, height, size=224, resize=256):
    """Resize(resize)+CenterCrop(size) as ONE (fractional) crop box
    matching torchvision's two-step pipeline to within ±1 LSB of uint8
    rounding (the enforced bound — see below).

    torchvision's Resize scales the short edge to ``resize`` and the long
    edge to ``int(resize * long / short)`` (truncation), then CenterCrop
    cuts ``size``² at integer offsets of THAT grid — a plain crop, no
    second resample. A single box-resize reproduces it when the box is
    the crop rectangle mapped back through each axis's own scale: output
    coord x spans intermediate [left, left+size), i.e. source
    [left·W/nw, (left+size)·W/nw) — fractional in general (the long-edge
    int() makes sx ≠ sy by a hair, and odd margins make left·s
    fractional). Round 5's A/B (scripts/check_tv_parity.py) measured the
    previous integer-box approximation at mean |Δpx| up to ~10 on
    non-integer-scale geometries — a sub-pixel phase shift. The exact
    box removes that shift; what remains is the two-step pipeline's
    intermediate uint8 quantization (it rounds the Resize(256) grid to
    bytes before cropping, the one-box path doesn't), so the agreement
    bound — asserted by tests/test_data.py and recorded in
    TV_PARITY.json — is max |Δpx| ≤ 1 on < 2% of pixels, not literal 0."""
    if width <= height:
        nw, nh = resize, int(resize * height / width)
    else:
        nh, nw = resize, int(resize * width / height)
    sx, sy = width / float(nw), height / float(nh)
    left, top = (nw - size) // 2, (nh - size) // 2
    return left * sx, top * sy, size * sx, size * sy


class TrainTransform:
    """RandomResizedCrop(size) → flip → uint8 HWC array (PIL applier)."""

    def __init__(self, size=224, scale=(0.08, 1.0),
                 ratio=(3.0 / 4.0, 4.0 / 3.0), flip_prob=0.5):
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.flip_prob = flip_prob

    def sample(self, width, height, rng):
        """Draw (box, flip) for one item — shared by PIL and native paths."""
        box = sample_rrc_box(width, height, rng, self.scale, self.ratio)
        flip = bool(rng.random() < self.flip_prob)
        return box, flip

    def __call__(self, img, rng):
        from PIL import Image

        (left, top, cw, ch), flip = self.sample(*img.size, rng)
        out = img.resize(
            (self.size, self.size), _BILINEAR,
            box=(left, top, left + cw, top + ch),
        )
        if flip:
            out = out.transpose(Image.FLIP_LEFT_RIGHT)
        return np.asarray(out, dtype=np.uint8)


class ValTransform:
    """Resize(resize) → CenterCrop(size) → uint8 HWC array (PIL applier;
    accepts and ignores ``rng``).

    ``native_ok = False``: the val pipeline ALWAYS decodes via PIL. The
    native C fast path trades exactness for speed (libjpeg scaled
    decode, IFAST DCT, 2-tap fixed-point lerp vs PIL's anti-aliased
    reduction filter — measured mean |Δpx| ≈ 1.5 on q85 JPEGs), which is
    fine under training augmentation but not for validation, where the
    whole point is reproducing torchvision's published-accuracy pixels
    (the fractional-box math above makes the PIL path two-step-exact).
    Val is ~4% of an ImageNet epoch's decode volume, so correctness
    wins."""

    native_ok = False

    def __init__(self, size=224, resize=256):
        self.size = size
        self.resize = resize

    def sample(self, width, height, rng=None):
        return center_fit_box(width, height, self.size, self.resize), False

    def __call__(self, img, rng=None):
        (left, top, cw, ch), _ = self.sample(*img.size)
        out = img.resize(
            (self.size, self.size), _BILINEAR,
            box=(left, top, left + cw, top + ch),
        )
        return np.asarray(out, dtype=np.uint8)


# legacy functional forms (kept for tests / direct use) -----------------------


def random_resized_crop(img, rng, size=224, scale=(0.08, 1.0),
                        ratio=(3.0 / 4.0, 4.0 / 3.0)):
    left, top, cw, ch = sample_rrc_box(*img.size, rng, scale, ratio)
    return img.resize((size, size), _BILINEAR,
                      box=(left, top, left + cw, top + ch))


def random_horizontal_flip(img, rng, p=0.5):
    from PIL import Image

    if rng.random() < p:
        return img.transpose(Image.FLIP_LEFT_RIGHT)
    return img


def resize_shorter(img, size=256):
    """Resize so the shorter side == size, keeping aspect (tv Resize(int))."""
    w, h = img.size
    if w <= h:
        nw, nh = size, max(1, int(round(h * size / w)))
    else:
        nh, nw = size, max(1, int(round(w * size / h)))
    return img.resize((nw, nh), _BILINEAR)


def center_crop(img, size=224):
    w, h = img.size
    left, top = (w - size) // 2, (h - size) // 2
    return img.crop((left, top, left + size, top + size))


def train_transform(size=224):
    """Factory kept for API stability: returns a TrainTransform."""
    return TrainTransform(size)


def val_transform(size=224, resize=256):
    """Factory kept for API stability: returns a ValTransform."""
    return ValTransform(size, resize)
