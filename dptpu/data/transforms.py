"""Host-side image transforms with torchvision-exact sampling math.

Train stack = RandomResizedCrop(224) → RandomHorizontalFlip; val stack =
Resize(256) → CenterCrop(224) (reference imagenet_ddp.py:163-194). ToTensor +
Normalize are deliberately ABSENT: like the Apex fast path ("Too slow" on
CPU, imagenet_ddp_apex.py:215-226), output stays uint8 HWC and normalization
happens on-device inside the compiled step (dptpu.train.step.normalize_images).

All randomness flows through an explicit ``numpy.random.Generator`` so a
seeded run is reproducible end-to-end (the ``--seed`` contract,
nd_imagenet.py:68-69,84-92) without any process-global RNG state.
"""

from __future__ import annotations

import math

import numpy as np

_BILINEAR = 2  # PIL.Image.BILINEAR


def random_resized_crop(img, rng, size=224, scale=(0.08, 1.0),
                        ratio=(3.0 / 4.0, 4.0 / 3.0)):
    """torchvision RandomResizedCrop: area ~ U(scale)·A, log-uniform aspect,
    10 attempts, then the aspect-clamped center-crop fallback."""
    w, h = img.size
    area = w * h
    log_ratio = (math.log(ratio[0]), math.log(ratio[1]))
    for _ in range(10):
        target_area = area * rng.uniform(scale[0], scale[1])
        aspect = math.exp(rng.uniform(log_ratio[0], log_ratio[1]))
        cw = int(round(math.sqrt(target_area * aspect)))
        ch = int(round(math.sqrt(target_area / aspect)))
        if 0 < cw <= w and 0 < ch <= h:
            left = int(rng.integers(0, w - cw + 1))
            top = int(rng.integers(0, h - ch + 1))
            return img.resize(
                (size, size), _BILINEAR, box=(left, top, left + cw, top + ch)
            )
    # fallback: clamp aspect, center crop
    in_ratio = w / h
    if in_ratio < ratio[0]:
        cw, ch = w, int(round(w / ratio[0]))
    elif in_ratio > ratio[1]:
        ch, cw = h, int(round(h * ratio[1]))
    else:
        cw, ch = w, h
    left, top = (w - cw) // 2, (h - ch) // 2
    return img.resize((size, size), _BILINEAR, box=(left, top, left + cw, top + ch))


def random_horizontal_flip(img, rng, p=0.5):
    from PIL import Image

    if rng.random() < p:
        return img.transpose(Image.FLIP_LEFT_RIGHT)
    return img


def resize_shorter(img, size=256):
    """Resize so the shorter side == size, keeping aspect (tv Resize(int))."""
    w, h = img.size
    if w <= h:
        nw, nh = size, max(1, int(round(h * size / w)))
    else:
        nh, nw = size, max(1, int(round(w * size / h)))
    return img.resize((nw, nh), _BILINEAR)


def center_crop(img, size=224):
    w, h = img.size
    left, top = (w - size) // 2, (h - size) // 2
    return img.crop((left, top, left + size, top + size))


def train_transform(size=224):
    """RandomResizedCrop(size) → flip → uint8 HWC array.

    The returned callable takes ``(img, rng)`` — the loader derives ``rng``
    per (seed, epoch, sample-index), so augmentations are reproducible no
    matter how the decode threads are scheduled.
    """

    def apply(img, rng):
        img = random_resized_crop(img, rng, size)
        img = random_horizontal_flip(img, rng)
        return np.asarray(img, dtype=np.uint8)

    return apply


def val_transform(size=224, resize=256):
    """Resize(resize) → CenterCrop(size) → uint8 HWC array (deterministic;
    accepts and ignores ``rng`` for signature uniformity)."""

    def apply(img, rng=None):
        return np.asarray(center_crop(resize_shorter(img, resize), size),
                          dtype=np.uint8)

    return apply
