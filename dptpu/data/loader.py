"""Threaded batch loader + double-buffered device prefetcher.

The torch ``DataLoader(num_workers=j)`` + Apex ``fast_collate`` +
``DataPrefetcher`` trio (reference imagenet_ddp.py:178-194;
imagenet_ddp_apex.py:26-39,304-351), rebuilt for the TPU host model:

* decode/transform on a thread pool (PIL/libjpeg release the GIL for the
  heavy work — no process fork needed, unlike torch workers);
* CHUNKED submission, decoded in place: each batch submits one future per
  worker (not per image), and each worker decodes its span of samples
  DIRECTLY into the preallocated uint8 NHWC batch (``dataset.get_into`` →
  the native decoder's caller-supplied output buffer) — fast_collate's
  "no float conversion on CPU" insight (×4 less H2D traffic) without the
  per-image future dispatch + intermediate-array memcpy that round 4's
  HOSTBENCH measured as ~19% of a decode core;
* keep ``prefetch_batches`` batches in flight so decode overlaps step time;
* per-item augmentation RNG derived from ``(seed, epoch, sample_index)`` —
  reproducible regardless of thread scheduling (the ``--seed`` contract,
  nd_imagenet.py:68-69, without torch's worker_init_fn caveats);
* ``DevicePrefetcher`` stays one batch ahead on-device: ``device_put`` /
  ``make_array_from_process_local_data`` dispatch is async in JAX, so the
  H2D copy of batch N+1 rides under the compute of batch N — the CUDA
  side-stream trick (imagenet_ddp_apex.py:310,329-340) without streams, and
  normalization already lives inside the compiled step.
"""

from __future__ import annotations

from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Optional

import numpy as np

import jax

from dptpu.data.sampler import ShardedSampler


class DataLoader:
    """Batches of ``{"images": uint8 [B,H,W,C], "labels": int32 [B]}``.

    Final-batch policy when the shard doesn't divide evenly:
      * ``drop_last=True`` — drop the remainder (train default in fit).
      * ``pad_final=True`` — pad by repeating sample 0 and attach a ``mask``
        (1.0 = real): static shapes for jit, exact masked eval.
      * ``pad_final=False`` — yield the short batch as-is (costs one extra
        jit specialization for the tail shape).
    """

    def __init__(self, dataset, batch_size: int,
                 sampler: Optional[ShardedSampler] = None,
                 num_workers: int = 4, drop_last: bool = False,
                 pad_final: bool = True, seed: int = 0):
        self.dataset = dataset
        self.batch_size = batch_size
        self.sampler = sampler or ShardedSampler(len(dataset), shuffle=False)
        self.num_workers = max(1, num_workers)
        self.drop_last = drop_last
        self.pad_final = pad_final
        self.seed = seed
        self._get = getattr(dataset, "get", None)
        self._get_into = getattr(dataset, "get_into", None)
        self._item_shape = None  # probed from the first sample
        self._pool = ThreadPoolExecutor(
            max_workers=self.num_workers, thread_name_prefix="dptpu-data"
        )

    def __len__(self) -> int:
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def _load_one(self, index: int, epoch: int):
        if self._get is None:
            return self.dataset[index]
        rng = np.random.default_rng([self.seed, epoch, index])
        return self._get(index, rng)

    def _load_span(self, idxs, epoch, imgs, labels, offset):
        """Decode a span of samples directly into rows
        ``offset..offset+len(idxs)`` of the shared batch arrays — the
        per-worker unit of a chunked submission (disjoint rows, so
        concurrent spans never race)."""
        get_into = self._get_into
        for j, index in enumerate(idxs):
            index = int(index)
            if get_into is not None:
                rng = np.random.default_rng([self.seed, epoch, index])
                labels[offset + j] = get_into(index, rng, imgs[offset + j])
            else:
                img, label = self._load_one(index, epoch)
                imgs[offset + j] = img
                labels[offset + j] = label

    def _submit_batch(self, batch_indices, epoch):
        """Preallocate one batch and fan its samples out as ONE future
        per worker (each decoding in place via ``_load_span``) — not one
        per image: HOSTBENCH r4 measured the per-image dispatch +
        intermediate memcpy at ~19% of a decode core."""
        n_valid = len(batch_indices)
        out_size = self.batch_size if self.pad_final else n_valid
        imgs = np.empty((out_size,) + self._item_shape, np.uint8)
        labels = np.zeros((out_size,), np.int32)
        span = -(-n_valid // self.num_workers)
        futs = [
            self._pool.submit(
                self._load_span, batch_indices[o:o + span], epoch,
                imgs, labels, o,
            )
            for o in range(0, n_valid, span)
        ]
        return futs, imgs, labels, n_valid

    def _finalize(self, futs, imgs, labels, n_valid, valid=None):
        for f in futs:
            f.result()  # wait + propagate decode errors
        batch = {"images": imgs, "labels": labels}
        out_size = imgs.shape[0]
        # the eval mask flags positions an exact aggregation must skip:
        # batch-tail padding AND the sampler's wrap-around duplicates
        # (samplers pad shards to equal length, imagenet_ddp.py:175-183).
        # Wrap-dup masking rides the pad_final (exact-eval) mode only:
        # train batches keep DistributedSampler's duplicate-sample
        # semantics and a stable pytree (no mid-epoch mask key).
        need_mask = n_valid < out_size or (
            self.pad_final and valid is not None and not valid.all()
        )
        if n_valid < out_size:  # pad tail by repeating sample 0
            imgs[n_valid:] = imgs[0]
            labels[n_valid:] = labels[0]
        if need_mask:
            mask = np.zeros((out_size,), np.float32)
            mask[:n_valid] = (
                1.0 if valid is None else valid.astype(np.float32)
            )
            batch["mask"] = mask
        return batch

    def epoch(self, epoch: int = 0, prefetch_batches: int = 2) -> Iterator[dict]:
        """Iterate one epoch's batches (``epoch`` reseeds the shuffle —
        the set_epoch analog, imagenet_ddp.py:202)."""
        indices, valid = self.sampler.indices_and_validity(epoch)
        nb = len(self)
        sl = lambda b: slice(b * self.batch_size, (b + 1) * self.batch_size)  # noqa: E731
        chunks = [(indices[sl(b)], valid[sl(b)]) for b in range(nb)]
        if self._item_shape is None and nb:
            # one probe decode fixes the item shape for preallocation
            # (cached on the loader; only the first epoch() call pays)
            img, _ = self._load_one(int(chunks[0][0][0]), epoch)
            self._item_shape = np.asarray(img).shape

        pending = deque()
        ahead = 1 + max(0, prefetch_batches)
        for chunk, _ in chunks[:ahead]:
            pending.append(self._submit_batch(chunk, epoch))
        next_idx = ahead
        for b in range(nb):
            item = pending.popleft()
            if next_idx < nb:
                pending.append(self._submit_batch(chunks[next_idx][0], epoch))
                next_idx += 1
            yield self._finalize(*item, valid=chunks[b][1])

    def close(self):
        self._pool.shutdown(wait=False, cancel_futures=True)


class DevicePrefetcher:
    """Keep one batch resident on device ahead of the consumer.

    ``put`` is either ``jax.device_put`` (single host) or
    ``dptpu.parallel.shard_host_batch`` partially applied with the mesh.
    JAX dispatches the transfer asynchronously, so the copy of batch N+1
    overlaps the compiled step running on batch N — the DataPrefetcher's
    double-buffering (imagenet_ddp_apex.py:304-351) with zero custom
    stream code.
    """

    def __init__(self, batches: Iterator[dict], put=jax.device_put):
        self._it = iter(batches)
        self._put = put
        self._next = self._advance()

    def _advance(self):
        try:
            return self._put(next(self._it))
        except StopIteration:
            return None

    def __iter__(self):
        return self

    def __next__(self):
        if self._next is None:
            raise StopIteration
        current, self._next = self._next, self._advance()
        return current
