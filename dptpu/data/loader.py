"""Batch loader (thread- or process-backed) + double-buffered prefetcher.

The torch ``DataLoader(num_workers=j)`` + Apex ``fast_collate`` +
``DataPrefetcher`` trio (reference imagenet_ddp.py:178-194;
imagenet_ddp_apex.py:26-39,304-351), rebuilt for the TPU host model:

* decode/transform on a worker pool. ``workers_mode="thread"`` uses a
  thread pool (PIL/libjpeg release the GIL for the pixel work);
  ``workers_mode="process"`` uses spawned worker processes writing into
  a shared-memory batch ring (``dptpu/data/shm.py``) — the GIL caps the
  thread pool at ~1 core of useful decode on real hosts (HOSTBENCH r5:
  542.8 img/s at 8 threads vs 516.6 at 1), while processes scale with
  host cores and pixels still never get pickled;
* CHUNKED submission, decoded in place: each batch submits one span per
  worker (not one task per image), and each worker decodes its span of
  samples DIRECTLY into the preallocated uint8 NHWC batch
  (``dataset.get_into`` → the native decoder's caller-supplied output
  buffer) — fast_collate's "no float conversion on CPU" insight (×4 less
  H2D traffic) without the per-image dispatch + intermediate-array
  memcpy that round 4's HOSTBENCH measured as ~19% of a decode core;
* keep ``prefetch_batches`` batches in flight so decode overlaps step time;
* per-item augmentation RNG derived from ``(seed, epoch, sample_index)`` —
  reproducible regardless of worker scheduling OR workers_mode: thread
  and process loaders yield bit-identical batches for the same seed (the
  ``--seed`` contract, nd_imagenet.py:68-69, without torch's
  worker_init_fn caveats; locked in tests/test_shm_loader.py);
* FIXED-SHAPE contract: the first sample's transformed shape is probed
  once and every batch is preallocated to it — all samples must share
  one shape (use a sizing transform). A mismatched sample raises a
  ``ValueError`` naming the offending index, not a broadcast error.
* ``DevicePrefetcher`` stays one batch ahead on-device: ``device_put`` /
  ``make_array_from_process_local_data`` dispatch is async in JAX, so the
  H2D copy of batch N+1 rides under the compute of batch N — the CUDA
  side-stream trick (imagenet_ddp_apex.py:310,329-340) without streams, and
  normalization already lives inside the compiled step.
* ZERO-COPY LEASED FEED (process mode, ``leased=True`` /
  ``DPTPU_LEASE``): batches are numpy VIEWS into the shared-memory ring
  plus a ``"_lease"`` token; ``DevicePrefetcher`` releases the lease
  after the device transfer of that batch completes, and the ring
  recycles only released slots — the parent's per-batch copy-out is
  gone (``feed_stats``: ``bytes_copied_per_batch = 0``). Consumers that
  RETAIN batches (``list(loader.epoch(0))``) must keep the default
  ``leased=False`` copy path: a leased batch's bytes are only stable
  until the iterator advances past it (the after-yield backstop then
  reclaims the slot).
* DECODE-AHEAD PIPELINED FEED (process mode): the ring depth is its
  own knob (``DPTPU_RING_DEPTH``, decoupled from prefetch and lease
  depth) and a pre-issue pump keeps spans for up to
  ``DPTPU_DECODE_AHEAD`` batches queued on the workers the moment
  slots free — workers never drain at batch boundaries, a straggler
  span delays only its own batch's collect (and ``DPTPU_SPECULATE``
  re-issues it to an idle worker after ``speculate_after_s``), and the
  pre-issue moment doubles as the cold-epoch JPEG readahead hook
  (``DPTPU_READAHEAD`` — ``posix_fadvise(WILLNEED)`` so worker reads
  land in a warm page cache). All of it preserves the bit-identity,
  lease and restart/resume contracts (dptpu/data/shm.py docstring).
"""

from __future__ import annotations

from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Optional

import numpy as np

import jax

from dptpu import obs
from dptpu.data.sampler import ShardedSampler


class DataLoader:
    """Batches of ``{"images": uint8 [B,H,W,C], "labels": int32 [B]}``.

    Final-batch policy when the shard doesn't divide evenly:
      * ``drop_last=True`` — drop the remainder (train default in fit).
      * ``pad_final=True`` — pad by repeating sample 0 and attach a ``mask``
        (1.0 = real): static shapes for jit, exact masked eval.
      * ``pad_final=False`` — yield the short batch as-is (costs one extra
        jit specialization for the tail shape).
    """

    def __init__(self, dataset, batch_size: int,
                 sampler: Optional[ShardedSampler] = None,
                 num_workers: int = 4, drop_last: bool = False,
                 pad_final: bool = True, seed: int = 0,
                 workers_mode: str = "thread", mp_start: str = "spawn",
                 leased: bool = False, lease_depth: Optional[int] = None,
                 span_affinity: Optional[bool] = None,
                 ring_depth: Optional[int] = None,
                 decode_ahead: Optional[int] = None,
                 speculate: Optional[bool] = None,
                 speculate_after_s: float = 0.5,
                 readahead: Optional[bool] = None):
        from dptpu.envknob import env_bool, env_int

        if workers_mode not in ("thread", "process"):
            raise ValueError(
                f"workers_mode={workers_mode!r} must be 'thread' or "
                f"'process'"
            )
        self.dataset = dataset
        self.batch_size = batch_size
        self.sampler = sampler or ShardedSampler(len(dataset), shuffle=False)
        self.num_workers = max(1, num_workers)
        self.drop_last = drop_last
        self.pad_final = pad_final
        self.seed = seed
        self.workers_mode = workers_mode
        self.mp_start = mp_start
        # zero-copy leased handoff (process mode): opt-in — the consumer
        # must release (DevicePrefetcher does) or advance promptly
        self.leased = leased
        self.lease_depth = (
            lease_depth if lease_depth is not None
            else env_int("DPTPU_LEASE_DEPTH", 2)
        )
        if self.lease_depth < 1:
            raise ValueError(
                f"DPTPU_LEASE_DEPTH={self.lease_depth} must be >= 1 "
                f"extra ring slot"
            )
        self.span_affinity = (
            span_affinity if span_affinity is not None
            else env_bool("DPTPU_SPAN_AFFINITY", True)
        )
        # decode-ahead pipelining knobs (process mode; locked fail-fast
        # contract — every explicit-but-invalid value raises):
        # * ring_depth — TOTAL batch slots in the shared-memory ring;
        #   None derives it from the issue window + lease depth;
        # * decode_ahead — batches whose spans may be pre-issued ahead
        #   of the consume point. Explicit values are EXACT (=1 is the
        #   batch-serial baseline the benches A/B against); None keeps
        #   at least the legacy prefetch window, deepened to >= 4.
        self.ring_depth = (
            ring_depth if ring_depth is not None
            else env_int("DPTPU_RING_DEPTH", None)
        )
        if self.ring_depth is not None and self.ring_depth < 2:
            raise ValueError(
                f"DPTPU_RING_DEPTH={self.ring_depth} must be >= 2 batch "
                f"slots (one collecting + one in flight)"
            )
        self.decode_ahead = (
            decode_ahead if decode_ahead is not None
            else env_int("DPTPU_DECODE_AHEAD", None)
        )
        if self.decode_ahead is not None and self.decode_ahead < 1:
            raise ValueError(
                f"DPTPU_DECODE_AHEAD={self.decode_ahead} must be >= 1 "
                f"batch in flight (1 = batch-serial issue, no lookahead)"
            )
        self.speculate = (
            speculate if speculate is not None
            else env_bool("DPTPU_SPECULATE", True)
        )
        self.speculate_after_s = speculate_after_s
        self.readahead = (
            readahead if readahead is not None
            else env_bool("DPTPU_READAHEAD", True)
        )
        self._get = getattr(dataset, "get", None)
        self._get_into = getattr(dataset, "get_into", None)
        # shard-streaming hook (dptpu/data/stream.py): the dataset owns
        # its I/O engine; pre-issue stages extents into the byte slab.
        # Thread mode calls it at submit time; process mode routes it
        # through the shm pipeline's pre-issue pump.
        self._prefetch_extents = getattr(dataset, "prefetch_extents", None)
        self._item_shape = None  # probed from the first sample
        self._probe = None  # owned-by: caller — (index, epoch, img, label) probe, consumed at submit time
        self._pipeline = None  # lazy shm ring (process mode)
        self._prev_cache_counts = (0, 0)  # feed_stats interval baseline
        self._degraded = False  # process pool gave up → thread fallback
        self._supervision = {"pool_restarts": 0, "span_retries": 0,
                             "straggler_resplits": 0,
                             "worker_evictions": 0}
        self._copy_totals = {"bytes_copied": 0, "collects": 0}
        # ring telemetry folded across pipeline rebuilds (same
        # survive-rebuilds discipline as _supervision/_copy_totals)
        self._ring_totals = {"occupancy_sum": 0, "occupancy_samples": 0,
                             "io_wait_s": 0.0, "straggler_reissues": 0}
        self._prev_io_wait = 0.0  # feed_stats interval baseline
        self._issue_ahead_sum = 0  # pre-issued batches, sampled per batch
        self._issue_ahead_n = 0
        self._pool = (
            ThreadPoolExecutor(
                max_workers=self.num_workers, thread_name_prefix="dptpu-data"
            )
            if workers_mode == "thread"
            else None
        )

    def __len__(self) -> int:
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def _load_one(self, index: int, epoch: int):
        if self._get is None:
            return self.dataset[index]
        rng = np.random.default_rng([self.seed, epoch, index])
        return self._get(index, rng)

    def _load_span(self, idxs, epoch, imgs, labels, offset, skip=()):
        """Decode a span of samples directly into rows
        ``offset..offset+len(idxs)`` of the shared batch arrays — the
        per-worker unit of a chunked submission (disjoint rows, so
        concurrent spans never race). ``skip`` rows were already filled
        by the caller (the shape probe's reused decode)."""
        from dptpu.data.dataset import _copy_checked

        get_into = self._get_into
        for j, index in enumerate(idxs):
            index = int(index)
            if offset + j in skip:
                continue
            if get_into is not None:
                rng = np.random.default_rng([self.seed, epoch, index])
                labels[offset + j] = get_into(index, rng, imgs[offset + j])
            else:
                img, label = self._load_one(index, epoch)
                _copy_checked(imgs[offset + j], img, index)
                labels[offset + j] = label

    def _submit_batch(self, batch_indices, epoch):
        """Preallocate one batch and fan its samples out as ONE future
        per worker (each decoding in place via ``_load_span``) — not one
        per image: HOSTBENCH r4 measured the per-image dispatch +
        intermediate memcpy at ~19% of a decode core."""
        n_valid = len(batch_indices)
        if self._prefetch_extents is not None and self.readahead:
            # stage this batch's shard extents now — it decodes
            # ``prefetch_batches`` from now, so the bytes land first
            self._prefetch_extents(batch_indices)
        out_size = self.batch_size if self.pad_final else n_valid
        imgs = np.empty((out_size,) + self._item_shape, np.uint8)
        labels = np.zeros((out_size,), np.int32)
        # the shape probe already decoded one sample of this epoch with
        # its exact rng: reuse it HERE, on the caller thread, so _probe
        # stays single-writer caller state (the decode spans run on the
        # pool — guarded-by discipline, dptpu check)
        skip = ()
        probe = self._probe
        if probe is not None and probe[1] == epoch:
            for j, index in enumerate(batch_indices):
                if int(index) == probe[0]:
                    self._probe = None
                    imgs[j] = probe[2]
                    labels[j] = probe[3]
                    skip = (j,)
                    break
        span = -(-n_valid // self.num_workers)
        futs = [
            self._pool.submit(
                self._load_span, batch_indices[o:o + span], epoch,
                imgs, labels, o, skip,
            )
            for o in range(0, n_valid, span)
        ]
        return futs, imgs, labels, n_valid

    def _finalize(self, futs, imgs, labels, n_valid, valid=None):
        # the parent-blocked-on-decode moment, thread edition (the
        # process path's equivalent wait is spanned around collect)
        with obs.get_tracer().span("collect"):
            for f in futs:
                f.result()  # wait + propagate decode errors
        return self._assemble(imgs, labels, n_valid, valid)

    def _assemble(self, imgs, labels, n_valid, valid=None):
        """Pad/mask policy shared by the thread and process backends."""
        batch = {"images": imgs, "labels": labels}
        out_size = imgs.shape[0]
        # the eval mask flags positions an exact aggregation must skip:
        # batch-tail padding AND the sampler's wrap-around duplicates
        # (samplers pad shards to equal length, imagenet_ddp.py:175-183).
        # Wrap-dup masking rides the pad_final (exact-eval) mode only:
        # train batches keep DistributedSampler's duplicate-sample
        # semantics and a stable pytree (no mid-epoch mask key).
        need_mask = n_valid < out_size or (
            self.pad_final and valid is not None and not valid.all()
        )
        if n_valid < out_size:  # pad tail by repeating sample 0
            imgs[n_valid:] = imgs[0]
            labels[n_valid:] = labels[0]
        if need_mask:
            mask = np.zeros((out_size,), np.float32)
            mask[:n_valid] = (
                1.0 if valid is None else valid.astype(np.float32)
            )
            batch["mask"] = mask
        return batch

    def epoch(self, epoch: int = 0, prefetch_batches: int = 2,
              start_batch: int = 0) -> Iterator[dict]:
        """Iterate one epoch's batches (``epoch`` reseeds the shuffle —
        the set_epoch analog, imagenet_ddp.py:202).

        ``start_batch`` replays the sampler to a mid-epoch resume point
        (dptpu.resilience): the FULL epoch permutation is rebuilt from
        ``(seed, epoch)`` exactly as an uninterrupted run would, then the
        first ``start_batch`` batches are skipped WITHOUT decoding — the
        remaining batches are bit-identical to what the uninterrupted
        epoch would have yielded from that position.
        """
        indices, valid = self.sampler.indices_and_validity(epoch)
        nb = len(self)
        sl = lambda b: slice(b * self.batch_size, (b + 1) * self.batch_size)  # noqa: E731
        chunks = [(indices[sl(b)], valid[sl(b)]) for b in range(nb)]
        if start_batch:
            if not 0 <= start_batch <= nb:
                raise ValueError(
                    f"start_batch={start_batch} outside this epoch's "
                    f"[0, {nb}] batches — checkpoint from a different "
                    f"batch size or dataset?"
                )
            chunks = chunks[start_batch:]
        if self._item_shape is None and chunks:
            # one probe decode fixes the item shape for preallocation
            # (cached on the loader; only the first epoch() call pays —
            # and thread mode reuses the decode for the sample's row)
            probe_idx = int(chunks[0][0][0])
            img, label = self._load_one(probe_idx, epoch)
            img = np.asarray(img)
            self._item_shape = img.shape
            self._probe = (probe_idx, epoch, img, label)

        ahead = 1 + max(0, prefetch_batches)
        if self.workers_mode == "process":
            yield from self._epoch_process(chunks, epoch, ahead)
            return
        yield from self._epoch_thread(chunks, epoch, ahead)

    def _epoch_thread(self, chunks, epoch, ahead):
        """Thread-pool epoch over an explicit chunk list (also the landing
        path when a broken process pool degrades mid-epoch)."""
        nb = len(chunks)
        pending = deque()
        for chunk, _ in chunks[:ahead]:
            pending.append(self._submit_batch(chunk, epoch))
        next_idx = ahead
        for b in range(nb):
            item = pending.popleft()
            if next_idx < nb:
                pending.append(self._submit_batch(chunks[next_idx][0], epoch))
                next_idx += 1
            yield self._finalize(*item, valid=chunks[b][1])

    def _epoch_process(self, chunks, epoch, ahead):
        """Process-mode epoch: drive the shared-memory slot ring
        (dptpu/data/shm.py) as a DECODE-AHEAD pipeline — a pump keeps up
        to ``issue window`` batches' spans pre-issued into the per-worker
        queues, refilling the moment slots free, so workers roll straight
        across batch boundaries while ``collect`` consumes in batch
        order (spans complete out of order against per-slot counters).
        ``leased=True`` yields zero-copy slot views carrying a
        ``"_lease"`` token; an after-yield backstop reclaims any lease
        the consumer didn't release, so the ring keeps flowing even for
        consumers unaware of the protocol (their batch bytes are then
        only stable until they advance — retaining consumers must use
        the copy path). If the supervised pool exhausts its restart
        budget (``WorkerPoolBroken``), degrade to thread mode for the
        rest of the run instead of killing the job — batches are
        bit-identical between modes, so the hand-off is seamless."""
        from dptpu.data.shm import WorkerPoolBroken

        if not chunks:
            return
        self._probe = None  # workers decode row 0 themselves
        nb = len(chunks)
        b = 0
        try:
            # issue window: explicit decode_ahead is exact (=1 is the
            # batch-serial baseline); default keeps at least the legacy
            # prefetch window, deepened to 4 for multi-batch lookahead
            window = (
                self.decode_ahead if self.decode_ahead is not None
                else max(ahead, 4)
            )
            slots = (
                self.ring_depth if self.ring_depth is not None
                else window + 1 + (self.lease_depth if self.leased else 0)
            )
            pipe = self._ensure_pipeline(slots=slots)
            pipe.reset()  # reclaim slots from an abandoned prior epoch
            pending = deque()
            next_idx = 0
            for b in range(nb):
                # the pre-issue pump: fill every free slot up to the
                # issue window before blocking on the in-order collect
                while True:
                    while next_idx < nb and len(pending) < window \
                            and pipe.free_slot_count() > 0:
                        pending.append(
                            pipe.submit(chunks[next_idx][0], epoch))
                        next_idx += 1
                    if pending:
                        break
                    if pipe.ghost_issues_in_flight():
                        # every free slot is ghost-quarantined: the
                        # pending ghost acks (or a watchdog restart)
                        # will free one — drain instead of raising on
                        # a ring that is merely small
                        pipe.drain_one_ack()
                        continue
                    # only unreleased LEASES can still be holding the
                    # ring: those the consumer must release
                    raise RuntimeError(
                        f"decode-ahead ring stalled: all "
                        f"{pipe.slots} slots are held by unreleased "
                        f"leases with no batch in flight — release "
                        f"leases promptly or raise DPTPU_RING_DEPTH"
                    )
                self._issue_ahead_sum += len(pending)
                self._issue_ahead_n += 1
                slot, n_valid = pending.popleft()
                out_size = self.batch_size if self.pad_final else n_valid
                # the parent-blocked-on-spans moment (the ring's own
                # io_wait_s counter measures the same wait cumulatively;
                # the span places each wait on the step timeline)
                with obs.get_tracer().span("collect"):
                    imgs, labels, lease = pipe.collect(
                        slot, out_size, leased=self.leased
                    )
                batch = self._assemble(imgs, labels, n_valid,
                                       valid=chunks[b][1])
                if lease is not None:
                    batch["_lease"] = lease
                try:
                    yield batch
                finally:
                    if lease is not None:
                        # backstop: the consumer moved on (or abandoned
                        # the epoch — GeneratorExit lands here too)
                        # without releasing; no-op when DevicePrefetcher
                        # already did
                        lease.release()
        except WorkerPoolBroken as e:
            self._degrade_to_thread(str(e))
            # batch b was never yielded; re-decode from it on threads
            # (pre-issued batches beyond b die with the pool — the
            # thread path re-earns them)
            yield from self._epoch_thread(chunks[b:], epoch, ahead)

    def _retire_pipeline(self, forgive_leases: bool = False):
        """Close the pipeline, folding its supervision counters into the
        loader's base first — feed_stats' survive-rebuilds invariant has
        exactly one implementation.

        ``forgive_leases``: a loader-initiated retirement (ring-depth
        rebuild between epochs, degrade-to-thread) REVOKES any lease
        carried over from an abandoned epoch — the consumer's late
        ``release()`` voids against the closed pipeline — instead of
        reporting it as a protocol leak; only ``close()`` (the consumer
        said it was done) treats an unreleased lease as a bug for the
        conftest leak guard to fail on."""
        if self._pipeline is not None:
            if forgive_leases:
                self._pipeline._leased.clear()
            for k, v in self._pipeline.supervision_stats().items():
                self._supervision[k] += v
            for k, v in self._pipeline.copy_stats().items():
                self._copy_totals[k] += v
            for k, v in self._pipeline.ring_stats().items():
                if k in self._ring_totals:
                    self._ring_totals[k] += v
            self._pipeline.close()
            self._pipeline = None

    def _degrade_to_thread(self, reason: str):
        """Graceful degradation: give up on worker processes for the rest
        of this run, loudly, instead of dying mid-job."""
        import sys

        print(
            f"WARNING: dptpu process-mode data pipeline is degrading to "
            f"thread mode (slower, but alive): {reason}",
            file=sys.stderr,
        )
        self._retire_pipeline(forgive_leases=True)
        self.workers_mode = "thread"
        self._degraded = True
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self.num_workers, thread_name_prefix="dptpu-data"
            )

    def kill_one_worker(self):
        """Fault-injection/debug hook (``DPTPU_FAULT=worker_kill@step=N``):
        SIGKILL one live decode worker; no-op in thread mode."""
        if self._pipeline is not None:
            return self._pipeline.kill_worker()
        return None

    # -- straggler-control seam (dptpu/resilience/elastic.py) ---------------
    # All three no-op safely in thread mode / before the lazy pipeline
    # exists, so the controller may always be armed.

    def worker_latency_observations(self):
        """Span issue→ack latencies ``[(worker_id, seconds), ...]``
        accumulated since the last call (process mode only)."""
        if self._pipeline is not None:
            return self._pipeline.drain_latency_observations()
        return []

    def resplit_worker(self, worker_id: int) -> int:
        """Re-issue a slow worker's pending span tail to healthy workers
        and route future affinity away from it; returns spans re-issued."""
        if self._pipeline is not None:
            return self._pipeline.resplit_worker(worker_id)
        return 0

    def restore_worker(self, worker_id: int):
        """Let a recovered worker rejoin the affinity router."""
        if self._pipeline is not None:
            self._pipeline.restore_worker(worker_id)

    def evict_worker(self, worker_id: int):
        """Escalate to the supervisor's eviction policy: kill the worker
        (the watchdog restart re-enqueues its work); returns the pid."""
        if self._pipeline is not None:
            return self._pipeline.evict_worker(worker_id)
        return None

    def _ensure_pipeline(self, slots: int):
        from dptpu.data.shm import ShmBatchPipeline

        if self._pipeline is not None and self._pipeline.slots != slots:
            # ring depth changed between epochs — GREW (deeper prefetch/
            # decode-ahead: the old ring cannot hold the window) or
            # SHRANK (a smaller window would silently pin the surplus
            # slots' memory forever): rebuild either way. Leased slots
            # carried over from the old ring are safe: retire closes the
            # pipeline, so a consumer's late release() voids against the
            # closed/generation check instead of touching the new ring,
            # and close_segment unlinks the segment NAME even while the
            # stale views keep their mapping alive.
            self._retire_pipeline(forgive_leases=True)
        if self._pipeline is None:
            self._pipeline = ShmBatchPipeline(
                self.dataset, self.batch_size, self._item_shape,
                num_workers=self.num_workers, seed=self.seed, slots=slots,
                mp_start=self.mp_start, span_affinity=self.span_affinity,
                speculate=self.speculate,
                speculate_after_s=self.speculate_after_s,
                readahead=self.readahead,
            )
            # fresh workers count from zero: re-baseline the interval
            # hit-rate bookkeeping in feed_stats
            self._prev_cache_counts = (0, 0)
        return self._pipeline

    def io_wait_total_s(self) -> float:
        """Cumulative parent-blocked-on-spans seconds (process mode;
        0.0 in thread mode), read WITHOUT consuming the ``feed_stats``
        interval baseline — the tune controller's decode-ahead actuator
        computes its own intervals, and the obs per-epoch interval must
        stay exactly what it was."""
        total = float(self._ring_totals["io_wait_s"])
        if self._pipeline is not None:
            total += float(self._pipeline.ring_stats()["io_wait_s"])
        return total

    def grow_decode_ahead(self, max_ahead: int = 16):
        """Bounded decode-ahead step (the tune controller's actuator
        seam, ISSUE 19): deepen the issue window by ONE batch. Takes
        effect at the next epoch's pipeline build — ``_epoch_process``
        derives the slot count there and ``_ensure_pipeline`` rebuilds
        the ring when it grew, so no mid-epoch slot protocol is ever
        resized under in-flight leases. Returns the new window, or None
        at the bound / in thread mode (the actuator reads None as "no
        headroom" and disarms cleanly)."""
        if self.workers_mode != "process":
            return None
        # default window is max(legacy prefetch, 4) — start the bounded
        # climb from the deepened floor, never below it
        cur = self.decode_ahead if self.decode_ahead is not None else 4
        if self.ring_depth is not None:
            # an explicit ring depth caps the usable window: the pump
            # can never hold more pending batches than free slots
            cap = self.ring_depth - 1 \
                - (self.lease_depth if self.leased else 0)
            max_ahead = min(max_ahead, cap)
        if cur >= max_ahead:
            return None
        self.decode_ahead = cur + 1
        return self.decode_ahead

    def feed_stats(self) -> dict:
        """Pipeline telemetry for the train loop: worker configuration +
        decode-cache counters (pool-aggregated in process mode).

        ``cache_hits``/``cache_misses`` are cumulative since loader
        creation; ``cache_hit_rate`` covers the INTERVAL since the
        previous ``feed_stats()`` call (→ per-epoch when called once per
        epoch, as the train loop does), so a warm epoch reads ~1.0
        instead of being diluted by epoch-0 fill misses."""
        stats = {
            "workers_mode": self.workers_mode,
            "num_workers": self.num_workers,
        }
        # supervision counters survive pool rebuilds and degradation:
        # the loader folds closed pipelines' totals into its own base
        restarts = dict(self._supervision)
        if self._pipeline is not None:
            for k, v in self._pipeline.supervision_stats().items():
                restarts[k] += v
        if any(restarts.values()) or self._degraded:
            stats.update(restarts)
        if self._degraded:
            stats["degraded"] = True
        if self.workers_mode == "process":
            stats["leased"] = self.leased
            stats["span_affinity"] = self.span_affinity
            # which affinity key routes spans to workers (the shared
            # shm.routing_of derivation, so the lazy-pipeline fallback
            # can never diverge from what the pipeline actually does)
            from dptpu.data.shm import routing_of

            stats["span_routing"] = (
                self._pipeline.routing if self._pipeline is not None
                else routing_of(self.dataset, self.span_affinity)
            )
            copied = dict(self._copy_totals)
            ring = dict(self._ring_totals)
            if self._pipeline is not None:
                stats.update(self._pipeline.cache_stats())
                for k, v in self._pipeline.copy_stats().items():
                    copied[k] += v
                pipe_ring = self._pipeline.ring_stats()
                for k in ring:
                    ring[k] += pipe_ring[k]
                stats["ring_depth"] = pipe_ring["ring_depth"]
            # the zero-copy contract, measured: parent-side copy-out
            # bytes per collected batch (0 when every collect was leased)
            stats["bytes_copied_per_batch"] = (
                copied["bytes_copied"] / copied["collects"]
                if copied["collects"] else 0.0
            )
            # decode-ahead telemetry: mean in-flight slots at collect
            # time, mean pre-issued batches, speculative re-issues, and
            # the INTERVAL parent-blocked-on-spans time (per-epoch when
            # feed_stats is called once per epoch, like the train loop)
            stats["ring_occupancy"] = (
                ring["occupancy_sum"] / ring["occupancy_samples"]
                if ring["occupancy_samples"] else 0.0
            )
            stats["issue_ahead_depth"] = (
                self._issue_ahead_sum / self._issue_ahead_n
                if self._issue_ahead_n else 0.0
            )
            stats["straggler_reissues"] = ring["straggler_reissues"]
            stats["io_wait_s"] = ring["io_wait_s"] - self._prev_io_wait
            self._prev_io_wait = ring["io_wait_s"]
        else:
            cache = getattr(self.dataset, "decode_cache", None)
            if cache is not None:
                stats.update(cache.stats())
        # shard-streaming telemetry (dptpu/data/stream.py): byte-ring /
        # store-fetch counters, plus the I/O-ownership invariant. The
        # fadvise readahead and the shard engine must NEVER both be
        # armed — WILLNEED would repopulate the page cache the O_DIRECT
        # ring exists to bypass — so feed_stats ASSERTS the exclusion
        # rather than just reporting it.
        io_fn = getattr(self.dataset, "io_stats", None)
        shard_owns_io = self._prefetch_extents is not None
        fadvise_active = (
            self.readahead and not shard_owns_io
            and self.workers_mode == "process"
            and getattr(self.dataset, "samples", None) is not None
        )
        if shard_owns_io and self.readahead \
                and getattr(self.dataset, "samples", None) is not None:
            raise RuntimeError(
                "feed invariant violated: the dataset exposes BOTH "
                "prefetch_extents (shard engine owns the I/O) and a "
                "samples path list (the fadvise readahead target) — "
                "the two byte-prefetch paths must be mutually exclusive"
            )
        stats["readahead_active"] = fadvise_active
        if io_fn is not None:
            stats.update(io_fn())
            assert not (stats["readahead_active"]
                        and stats.get("odirect_active")), (
                "fadvise readahead and the O_DIRECT shard ring are both "
                "active — mutually exclusive by contract"
            )
        if "cache_hits" in stats:
            dh = stats["cache_hits"] - self._prev_cache_counts[0]
            dm = stats["cache_misses"] - self._prev_cache_counts[1]
            self._prev_cache_counts = (
                stats["cache_hits"], stats["cache_misses"]
            )
            stats["cache_hit_rate"] = dh / (dh + dm) if dh + dm else 0.0
        return stats

    def close(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)
        self._retire_pipeline()


class DevicePrefetcher:
    """Keep one batch resident on device ahead of the consumer.

    ``put`` is either ``jax.device_put`` (single host) or
    ``dptpu.parallel.shard_host_batch`` partially applied with the mesh.
    JAX dispatches the transfer asynchronously, so the copy of batch N+1
    overlaps the compiled step running on batch N — the DataPrefetcher's
    double-buffering (imagenet_ddp_apex.py:304-351) with zero custom
    stream code.

    LEASED batches (a ``"_lease"`` token from the process-mode loader's
    zero-copy path) are the prefetcher's responsibility to release —
    only then may the shared-memory ring recycle the slot:

    * on an accelerator backend, ``put`` DMAs the host views to device
      memory; the prefetcher blocks until that transfer completes, then
      releases — the blocking overlaps the PREVIOUS step's device
      compute, and no host-side byte is ever copied;
    * on the CPU backend, ``jax.device_put`` may ZERO-COPY ALIAS the
      host buffer (measured on this toolchain: a mutated source mutates
      the "device" array), so recycling after a mere block would corrupt
      the batch mid-step. The prefetcher therefore copies the views once
      before ``put`` and releases immediately — the same cost as the
      legacy copy-out, paid only where physics offers no transfer.
      ``copy_before_put`` overrides the backend auto-detection (tests
      use it to drive the raw lease protocol with a custom ``put``).
    """

    def __init__(self, batches: Iterator[dict], put=jax.device_put,
                 copy_before_put: Optional[bool] = None):
        self._it = iter(batches)
        self._put = put
        self._copy = copy_before_put
        self._next = self._advance()

    def _advance(self):
        tracer = obs.get_tracer()
        try:
            batch = next(self._it)
        except StopIteration:
            return None
        lease = batch.pop("_lease", None)
        if lease is None:
            with tracer.span("h2d"):
                return self._put(batch)
        if self._copy is None:
            # CPU PJRT zero-copies suitably-shaped numpy buffers — the
            # device array then aliases the ring slot for its lifetime
            self._copy = jax.default_backend() == "cpu"
        if self._copy:
            with tracer.span("h2d"):
                batch = {k: np.array(v) for k, v in batch.items()}  # dptpu: allow-host-sync(the documented CPU-backend defense: device_put zero-copy-aliases host buffers there, so recycling the slot would corrupt the in-flight batch — copy once, host to host)
                out = self._put(batch)
            lease.release()
            return out
        with tracer.span("h2d"):
            out = self._put(batch)
            # the H2D read must finish before the slot may be
            # overwritten; this wait overlaps the previous step's device
            # compute
            jax.block_until_ready(out)  # dptpu: allow-host-sync(H2D completion gate before the leased slot may be recycled; the wait overlaps the PREVIOUS step's device compute)
        lease.release()
        return out

    def __iter__(self):
        return self

    def __next__(self):
        if self._next is None:
            raise StopIteration
        current, self._next = self._next, self._advance()
        return current
