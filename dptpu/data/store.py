"""Byte-store abstraction: local files and HTTP range fetch, one API.

Production datasets (and checkpoints) live in object stores, not on the
training host's disk. ``Store`` is the minimal byte-addressed interface
the data plane needs — whole objects, byte ranges, durable puts, listing
— with two backends:

* :class:`LocalStore` — a directory. ``put_bytes`` is the checkpoint
  writer's exact durability discipline (tmp + flush + fsync + atomic
  rename + best-effort directory fsync), hoisted here so checkpoint
  writes "through the store" stay bit-for-bit what they were.
* :class:`HTTPStore` — an HTTP(S) prefix. ``get_range`` issues RFC 7233
  ``Range:`` requests (the object-store read primitive); ``put_bytes``/
  ``delete`` map to PUT/DELETE, ``list`` to a JSON directory GET (the
  bundled dev server speaks all four; S3/GCS adapters are a follow-on —
  the interface is the contract).

RETRY/BACKOFF is the store's job, not the caller's: every remote op runs
under ``_io`` — up to ``DPTPU_STORE_RETRIES`` retries with exponential
backoff from ``DPTPU_STORE_BACKOFF_S`` — because a transient fetch error
mid-epoch must cost milliseconds, not the run. The ``DPTPU_FAULT
io_error:p=F`` chaos spec injects ``OSError`` into store ops through the
same hook the decode workers use (:meth:`FaultPlan.on_store_io`), so
FAULTBENCH can prove a fault-injected range fetch retries to a
bit-identical run. Non-retryable outcomes (404 → ``FileNotFoundError``)
fail immediately. Counters (``retries``, ``wait_s``, ``bytes_fetched``)
feed the loader's ``feed_stats`` → ``Feed/store_*`` metrics.

This module is imported inside spawned decode workers: stdlib + numpy
only, never JAX. Stores pickle by spec (root/URL + knobs), never by
handle — each process re-opens its own connections.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
import urllib.error
import urllib.request
from typing import List, Optional, Tuple

import numpy as np

from dptpu.envknob import env_float, env_int, env_str
from dptpu.utils.sync import OrderedLock

_SCHEMES = ("http://", "https://", "file://")


def is_store_url(path: str) -> bool:
    """True when ``path`` is a store URL rather than a plain filesystem
    path (``http://``/``https://``/``file://``)."""
    return isinstance(path, str) and path.startswith(_SCHEMES)


def open_store(location: str) -> "Store":
    """A :class:`Store` rooted at ``location``: HTTP(S) URLs get an
    :class:`HTTPStore`, ``file://`` and plain paths a :class:`LocalStore`."""
    if location.startswith(("http://", "https://")):
        return HTTPStore(location)
    if location.startswith("file://"):
        return LocalStore(location[len("file://"):])
    return LocalStore(location)


def split_store_url(url: str) -> Tuple[str, str]:
    """Split a store URL naming one OBJECT into ``(base, name)`` — the
    store root and the object's name inside it."""
    base, _, name = url.rstrip("/").rpartition("/")
    return base, name


class StoreError(OSError):
    """A store operation failed after exhausting its retry budget."""


# ONE fault plan (and thus ONE advancing injection rng) per process,
# shared by every Store instance: checkpoint paths build a fresh Store
# per operation, and a per-instance plan would re-seed the rng each
# time — every op would replay the identical draw sequence, turning
# "transient with probability p" into deterministic all-or-nothing
# (a p=0.6 spec would kill EVERY save despite retries). Keyed by the
# (spec, seed) env pair so chaos benches that re-scope DPTPU_FAULT
# between runs get a fresh plan.
_FAULT_CACHE = {"key": None, "plan": None}


def _shared_fault_plan():
    key = (env_str("DPTPU_FAULT", ""), env_str("DPTPU_FAULT_SEED", ""))
    if _FAULT_CACHE["key"] != key:
        from dptpu.resilience.faults import FaultPlan

        try:
            plan = FaultPlan.from_env()
        except ValueError:
            plan = None  # the trainer raises the parse error loudly
        _FAULT_CACHE["key"] = key
        _FAULT_CACHE["plan"] = plan
    return _FAULT_CACHE["plan"]


class Store:
    """Byte-store interface + the shared retry/backoff/fault-injection
    engine. Subclasses implement the raw ``_get_range``/``_get_bytes``/
    ``_size``/``_put_bytes``/``_copy``/``_delete``/``_list`` primitives;
    every public op runs them under :meth:`_io`."""

    scheme = "abstract"

    def __init__(self, retries: Optional[int] = None,
                 backoff_s: Optional[float] = None):
        self.retries = (
            retries if retries is not None
            else env_int("DPTPU_STORE_RETRIES", 3)
        )
        self.backoff_s = (
            backoff_s if backoff_s is not None
            else env_float("DPTPU_STORE_BACKOFF_S", 0.05)
        )
        if self.retries < 0:
            raise ValueError(
                f"DPTPU_STORE_RETRIES={self.retries} must be >= 0 retries"
            )
        if self.backoff_s < 0:
            raise ValueError(
                f"DPTPU_STORE_BACKOFF_S={self.backoff_s} must be >= 0 "
                f"seconds"
            )
        # telemetry (per-process; the loader aggregates into feed_stats)
        # — fetched from the parent's prefetcher thread AND the
        # consumer's decode path concurrently
        self.retry_count = 0  # guarded-by: _lock
        self.wait_s = 0.0  # guarded-by: _lock
        self.bytes_fetched = 0  # guarded-by: _lock
        self._lock = OrderedLock("data.store")

    # -- retry engine -------------------------------------------------------

    def _plan(self):
        """The process-shared DPTPU_FAULT plan (workers re-parse the
        inherited env — same discipline as dptpu/data/shm.py's decode
        workers; shared across Store instances so the injection rng
        ADVANCES, see _shared_fault_plan)."""
        return _shared_fault_plan()

    def _io(self, desc: str, fn):
        """Run one store primitive under retry/backoff + fault injection.
        ``FileNotFoundError`` is never retried (absence is an answer, not
        a fault); any other ``OSError`` — including the injected ones —
        burns one attempt and backs off exponentially."""
        t0 = time.monotonic()
        delay = self.backoff_s
        try:
            for attempt in range(self.retries + 1):
                try:
                    plan = self._plan()
                    if plan is not None:
                        plan.on_store_io(desc)
                    return fn()
                except FileNotFoundError:
                    raise
                except (OSError, urllib.error.URLError) as e:
                    if attempt >= self.retries:
                        raise StoreError(
                            f"store op {desc!r} failed after "
                            f"{attempt + 1} attempt(s): {e}"
                        ) from e
                    with self._lock:
                        self.retry_count += 1
                    time.sleep(delay)
                    delay *= 2
        finally:
            with self._lock:
                self.wait_s += time.monotonic() - t0

    # -- public API ---------------------------------------------------------

    def get_bytes(self, name: str) -> bytes:
        data = self._io(f"get {name}", lambda: self._get_bytes(name))
        with self._lock:
            self.bytes_fetched += len(data)
        return data

    def get_range(self, name: str, offset: int, length: int) -> bytes:
        data = self._io(
            f"get_range {name}[{offset}:{offset + length}]",
            lambda: self._get_range(name, offset, length),
        )
        with self._lock:
            self.bytes_fetched += len(data)
        return data

    def size(self, name: str) -> int:
        return self._io(f"size {name}", lambda: self._size(name))

    def put_bytes(self, name: str, data: bytes) -> None:
        self._io(f"put {name}", lambda: self._put_bytes(name, data))

    def copy(self, src: str, dst: str) -> None:
        self._io(f"copy {src} -> {dst}", lambda: self._copy(src, dst))

    def delete(self, name: str) -> None:
        self._io(f"delete {name}", lambda: self._delete(name))

    def list(self) -> List[Tuple[str, float]]:
        """``[(name, mtime), ...]`` of the objects under the root."""
        return self._io("list", self._list)

    def stats(self) -> dict:
        with self._lock:
            return {
                "store_scheme": self.scheme,
                "store_retries": self.retry_count,
                "store_wait_s": self.wait_s,
                "store_bytes_fetched": self.bytes_fetched,
            }

    def path_for(self, name: str) -> str:
        raise NotImplementedError


class LocalStore(Store):
    """A directory as a store. Reads are plain (p)reads; ``put_bytes``
    is the atomic+durable checkpoint write discipline."""

    scheme = "file"

    def __init__(self, root: str, retries: Optional[int] = None,
                 backoff_s: Optional[float] = None):
        super().__init__(retries=retries, backoff_s=backoff_s)
        self.root = root

    def __reduce__(self):
        return (LocalStore, (self.root, self.retries, self.backoff_s))

    def path_for(self, name: str) -> str:
        return os.path.join(self.root, name)

    def _get_bytes(self, name: str) -> bytes:
        with open(self.path_for(name), "rb") as f:
            return f.read()

    def _get_range(self, name: str, offset: int, length: int) -> bytes:
        with open(self.path_for(name), "rb") as f:
            return os.pread(f.fileno(), length, offset)

    def _size(self, name: str) -> int:
        return os.path.getsize(self.path_for(name))

    def _put_bytes(self, name: str, data: bytes) -> None:
        # the checkpoint writer's durability discipline, verbatim
        # (dptpu/train/checkpoint.py): tmp + flush + fsync + atomic
        # rename + best-effort dirent fsync — a power loss can yield the
        # old object or the new one, never a torn mix
        os.makedirs(self.root, exist_ok=True)
        path = self.path_for(name)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        try:
            dirfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
            try:
                os.fsync(dirfd)
            finally:
                os.close(dirfd)
        except OSError:
            pass  # filesystems/platforms that refuse directory fds

    def _copy(self, src: str, dst: str) -> None:
        shutil.copyfile(self.path_for(src), self.path_for(dst))

    def _delete(self, name: str) -> None:
        os.remove(self.path_for(name))

    def _list(self) -> List[Tuple[str, float]]:
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for n in names:
            try:
                out.append((n, os.path.getmtime(self.path_for(n))))
            except OSError:
                continue
        return out


class HTTPStore(Store):
    """An HTTP(S) prefix as a store: ``Range:`` GETs for extents, PUT /
    DELETE for checkpoint writes, a JSON directory GET for listing. 404
    maps to ``FileNotFoundError`` (never retried); connection errors and
    5xx retry under the shared backoff."""

    scheme = "http"

    def __init__(self, base_url: str, retries: Optional[int] = None,
                 backoff_s: Optional[float] = None,
                 timeout_s: float = 30.0):
        super().__init__(retries=retries, backoff_s=backoff_s)
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self._range_unsupported = False  # guarded-by: _lock

    def __reduce__(self):
        return (HTTPStore,
                (self.base_url, self.retries, self.backoff_s,
                 self.timeout_s))

    def path_for(self, name: str) -> str:
        return f"{self.base_url}/{name}"

    def _request(self, name: str, method: str = "GET", headers=None,
                 data: Optional[bytes] = None) -> bytes:
        req = urllib.request.Request(
            self.path_for(name), method=method, data=data,
            headers=headers or {},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return r.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise FileNotFoundError(
                    f"{self.path_for(name)}: HTTP 404"
                ) from e
            raise OSError(
                f"{self.path_for(name)}: HTTP {e.code} {e.reason}"
            ) from e

    def _get_bytes(self, name: str) -> bytes:
        return self._request(name)

    def _get_range(self, name: str, offset: int, length: int) -> bytes:
        data = self._request(
            name, headers={"Range": f"bytes={offset}-{offset + length - 1}"}
        )
        if len(data) > length:  # server ignored Range: slice locally
            # account the WASTE (the public wrapper adds only the slice
            # length) and warn once — a rangeless server turns every
            # extent read into a whole-object download, and telemetry
            # must show that, not hide it
            with self._lock:
                self.bytes_fetched += len(data) - length
                if not self._range_unsupported:
                    self._range_unsupported = True
                    import sys

                    print(
                        f"WARNING: dptpu store {self.base_url} ignored a "
                        f"Range request ({len(data)} bytes returned for a "
                        f"{length}-byte extent) — every extent read now "
                        f"downloads the whole object; prefer "
                        f"DPTPU_STORE_FETCH=shard or a range-capable "
                        f"store",
                        file=sys.stderr,
                    )
            data = data[offset:offset + length]
        return data

    def _size(self, name: str) -> int:
        req = urllib.request.Request(self.path_for(name), method="HEAD")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return int(r.headers.get("Content-Length", 0))
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise FileNotFoundError(
                    f"{self.path_for(name)}: HTTP 404"
                ) from e
            raise OSError(
                f"{self.path_for(name)}: HTTP {e.code} {e.reason}"
            ) from e

    def _put_bytes(self, name: str, data: bytes) -> None:
        self._request(name, method="PUT", data=data,
                      headers={"Content-Length": str(len(data))})

    def _copy(self, src: str, dst: str) -> None:
        self._put_bytes(dst, self._get_bytes(src))

    def _delete(self, name: str) -> None:
        self._request(name, method="DELETE")

    def stats(self) -> dict:
        s = super().stats()
        with self._lock:
            range_unsupported = self._range_unsupported
        if range_unsupported:
            s["store_range_unsupported"] = True
        return s

    def _list(self) -> List[Tuple[str, float]]:
        raw = self._request("")
        try:
            entries = json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            raise OSError(
                f"{self.base_url}/: listing is not the JSON index this "
                f"store expects (a generic object store needs a list "
                f"adapter): {e}"
            ) from e
        return [(e["name"], float(e.get("mtime", 0.0))) for e in entries]


# ---- pooled shard byte cache ----------------------------------------------


class ShardByteCache:
    """The pooled /dev/shm slab (dptpu/data/shm_cache.py) reused as a
    SHARD BYTE cache: raw JPEG/PNG extents, fetched once by the parent's
    prefetcher (O_DIRECT ring or store range fetch), hit by every decode
    worker. Segments are named ``dptpu_shard_*`` so the conftest
    /dev/shm leak guard can police them separately from the decoded-
    pixel slabs.

    The slab stores uint8 HWC arrays; byte payloads ride as
    ``(ceil(n/3), 1, 3)`` views with the real length carried by the
    caller (the shard index knows every extent's exact size). Same
    budget/eviction/crash-recovery semantics as the decode cache —
    including surviving worker pool restarts warm.
    """

    def __init__(self, budget_bytes: int):
        from dptpu.data.shm_cache import ShmDecodeCache

        self._cache = ShmDecodeCache(
            budget_bytes, segment_prefix="dptpu_shard"
        )

    def contains(self, key) -> bool:
        """Staged-already check without copying the payload out."""
        return self._cache.contains(key)

    def get(self, key, length: int) -> Optional[bytes]:
        arr = self._cache.get(key)
        if arr is None:
            return None
        flat = arr.reshape(-1)
        if flat.size < length:
            return None  # torn/foreign entry: treat as a miss
        return flat[:length].tobytes()

    def put(self, key, data: bytes) -> bool:
        n = len(data)
        pad = (-n) % 3
        arr = np.frombuffer(data + b"\x00" * pad, np.uint8)
        return self._cache.put(key, arr.reshape(-1, 1, 3))

    def stats(self) -> dict:
        # slab-level keys are namespaced shard_slab_* so they can never
        # clobber the ENGINE-level shard_cache_hits/misses (sample-level
        # staging effectiveness) in io_stats
        s = self._cache.stats()
        return {
            "shard_slab_hits": s["cache_hits"],
            "shard_slab_misses": s["cache_misses"],
            "shard_slab_bytes_in_use": s["cache_bytes_in_use"],
            "shard_slab_budget_bytes": s["cache_budget_bytes"],
        }

    @property
    def closed(self) -> bool:
        return self._cache.closed

    def close(self):
        self._cache.close()


# ---- dev range server (tests + DATABENCH) ---------------------------------


def dev_store_server(root: str, latency_s: float = 0.0,
                     fail_first: int = 0):
    """A threaded HTTP store server over ``root`` for tests and the
    DATABENCH remote arms: GET (with ``Range:``), HEAD, PUT, DELETE, and
    a JSON directory listing. ``latency_s`` sleeps before every response
    (the latency-injection curve); ``fail_first`` 500s the first N GETs
    (the network-flake retry path). Returns ``(server, base_url)`` —
    call ``server.shutdown()`` when done."""
    import http.server
    import socketserver

    state = {"fails_left": int(fail_first)}

    class Handler(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):  # quiet
            pass

        def _path(self):
            rel = self.path.lstrip("/")
            p = os.path.normpath(os.path.join(root, rel))
            if not p.startswith(os.path.normpath(root)):
                return None
            return p

        def _maybe_flake(self) -> bool:
            if state["fails_left"] > 0:
                state["fails_left"] -= 1
                self.send_response(503)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return True
            return False

        def do_GET(self):
            if latency_s:
                time.sleep(latency_s)
            if self._maybe_flake():
                return
            p = self._path()
            if p is None or not os.path.exists(p):
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            if os.path.isdir(p):
                entries = []
                for n in sorted(os.listdir(p)):
                    fp = os.path.join(p, n)
                    if os.path.isfile(fp):
                        entries.append({
                            "name": n, "mtime": os.path.getmtime(fp),
                            "size": os.path.getsize(fp),
                        })
                body = json.dumps(entries).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            size = os.path.getsize(p)
            rng = self.headers.get("Range")
            start, end = 0, size - 1
            status = 200
            if rng and rng.startswith("bytes="):
                spec = rng[len("bytes="):].split("-")
                start = int(spec[0]) if spec[0] else 0
                if spec[1]:
                    end = min(int(spec[1]), size - 1)
                status = 206
            length = max(end - start + 1, 0)
            with open(p, "rb") as f:
                body = os.pread(f.fileno(), length, start)
            self.send_response(status)
            if status == 206:
                self.send_header(
                    "Content-Range", f"bytes {start}-{end}/{size}"
                )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_HEAD(self):
            p = self._path()
            if p is None or not os.path.isfile(p):
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Length", str(os.path.getsize(p)))
            self.end_headers()

        def do_PUT(self):
            p = self._path()
            n = int(self.headers.get("Content-Length", 0))
            data = self.rfile.read(n)
            os.makedirs(os.path.dirname(p), exist_ok=True)
            tmp = p + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, p)
            self.send_response(201)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def do_DELETE(self):
            p = self._path()
            if p is None or not os.path.isfile(p):
                self.send_response(404)
            else:
                os.remove(p)
                self.send_response(204)
            self.send_header("Content-Length", "0")
            self.end_headers()

    class Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
        daemon_threads = True
        allow_reuse_address = True

    server = Server(("127.0.0.1", 0), Handler)
    thread = threading.Thread(
        target=server.serve_forever, daemon=True, name="dptpu-dev-store"
    )
    thread.start()
    host, port = server.server_address
    return server, f"http://{host}:{port}"
