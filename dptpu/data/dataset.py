"""Datasets: ImageFolder-layout reader + synthetic stand-in.

``ImageFolderDataset`` reproduces ``datasets.ImageFolder`` semantics
(reference imagenet_ddp.py:166-173): one subdirectory per class under the
root, class index = position in the *sorted* subdirectory list, every image
file inside belongs to that class. Decoding is PIL (RGB), matching
torchvision's default loader.

``SyntheticDataset`` generates deterministic random uint8 images — the
fixture for integration tests and throughput benchmarks (it removes host
decode from the measurement, isolating the device-side number).
"""

from __future__ import annotations

import os
from typing import Callable, Optional, Tuple

import numpy as np

_IMG_EXTENSIONS = (
    ".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif", ".tiff", ".webp",
)


def _copy_checked(out: np.ndarray, img, index: int):
    """Copy a decoded sample into a preallocated batch row, surfacing the
    loader's fixed-shape contract instead of numpy's broadcast error."""
    img = np.asarray(img)
    if img.shape != out.shape:
        raise ValueError(
            f"sample {index} decoded to shape {img.shape}, but the batch "
            f"was preallocated for shape {out.shape} (probed from the "
            f"first sample). DataLoader requires every sample to share "
            f"one shape — use a sizing transform (train_transform/"
            f"val_transform) or pre-resize the dataset."
        )
    np.copyto(out, img)


def native_decode_sample(read_bytes, is_jpeg, transform, rng,
                         decode_cache=None, cache_key=None, out=None):
    """The fused libjpeg decode-crop-resize path from ENCODED BYTES —
    shared by :class:`ImageFolderDataset` (bytes = the file) and the
    packed-shard streaming dataset (bytes = a shard extent), so the two
    sources produce bit-identical pixels by construction. ``read_bytes``
    is a thunk: with a decode cache attached, a cache hit never fetches
    the encoded bytes at all. Returns the decoded array, or None when
    this sample/environment can't take the native path (caller falls
    back to PIL). Transforms may veto via ``native_ok = False``
    (ValTransform does — see its docstring)."""
    if transform is None or not hasattr(transform, "sample") \
            or not getattr(transform, "native_ok", True) or not is_jpeg:
        return None
    from dptpu.data import native_image

    if not native_image.available():
        return None
    if decode_cache is not None:
        rng_state = rng.bit_generator.state

        def _resample(full):
            # identical for a hit (cached view, in place — zero-copy
            # even out of the pooled /dev/shm slab) and a miss (the
            # freshly decoded buffer): same pixels, same rng draw.
            # IDEMPOTENT by contract: the pooled cache's lock-free
            # hit path may run this on a torn view and then retry or
            # fall back to the miss path, so the rng state consumed
            # by sample() is restored on every entry — the crop that
            # finally lands is always the (seed, epoch, index) one.
            rng.bit_generator.state = rng_state
            h, w = full.shape[:2]
            box, flip = transform.sample(w, h, rng)
            return native_image.crop_resize(
                full, box, transform.size, flip, out=out
            )

        hit, res = decode_cache.with_entry(cache_key, _resample)
        if hit:
            return res
        data = read_bytes()
        dims = native_image.jpeg_dims(data)
        if dims is None:
            return None
        full = np.empty((dims[1], dims[0], 3), np.uint8)
        if not native_image.decode_into_cache(data, full):
            return None
        decode_cache.put(cache_key, full)
        return _resample(full)
    data = read_bytes()
    dims = native_image.jpeg_dims(data)
    if dims is None:
        return None
    box, flip = transform.sample(dims[0], dims[1], rng)
    return native_image.decode_crop_resize(
        data, box, transform.size, flip, out=out
    )


def pil_decode_sample(read_bytes, transform, rng, decode_cache=None,
                      cache_key=None):
    """The PIL fallback path from encoded bytes (same sharing story as
    :func:`native_decode_sample`; PIL decodes a BytesIO of the file's
    bytes to the identical pixels it decodes from the file itself)."""
    import io

    from PIL import Image

    if decode_cache is not None:
        arr = decode_cache.get(cache_key)
        if arr is None:
            with Image.open(io.BytesIO(read_bytes())) as img:
                arr = np.asarray(img.convert("RGB"))
            decode_cache.put(cache_key, arr)
        if transform is None:
            # callers own (and may mutate) what get() returns — hand
            # out a copy, never the shared cached buffer
            return arr.copy()
        # re-applying the transform to the cached full decode is
        # bit-identical to the uncached PIL path (same source pixels)
        return transform(Image.fromarray(arr), rng)
    with Image.open(io.BytesIO(read_bytes())) as img:
        img = img.convert("RGB")
        if transform is None:
            return np.asarray(img)
        return transform(img, rng)


class ImageFolderDataset:
    """root/<class_name>/<image> layout, torchvision class-index semantics.

    ``cache_bytes > 0`` attaches a decoded-pixel cache: full-resolution
    pixels are kept (byte-budgeted, oldest-evicted) and epoch 1+
    re-applies only the per-epoch crop/resize/flip — a cache hit skips
    JPEG Huffman decode entirely. ``cache_scope`` picks the
    implementation:

    * ``"sharded"`` (default) — in-process
      :class:`dptpu.data.cache.DecodeCache`; a process-mode worker pool
      splits the budget N ways and each worker warms its own shard;
    * ``"pooled"`` — cross-process
      :class:`dptpu.data.shm_cache.ShmDecodeCache`: ONE /dev/shm slab of
      the full budget shared by every worker (and surviving pool
      restarts warm).

    Hits and misses produce identical pixels for identical augmentation
    RNG (both resample the same decoded buffer) under EITHER scope, so
    cache warmth never changes what a seeded run sees. Note the cached
    native path decodes at FULL resolution on a miss (the buffer must
    serve every future crop), whereas the uncached path may use
    libjpeg's crop-dependent scaled decode — pixels between cache-on and
    cache-off therefore match bit-for-bit only when the scale picker
    stays at 8/8 (always true when no crop axis reaches
    ``out_size*8/7``); for larger images the cached path resamples from
    strictly higher-resolution source pixels.
    """

    def __init__(self, root: str, transform: Optional[Callable] = None,
                 cache_bytes: int = 0, cache_scope: str = "sharded"):
        self.root = root
        self.transform = transform
        if cache_scope not in ("sharded", "pooled"):
            raise ValueError(
                f"cache_scope={cache_scope!r} must be 'sharded' or "
                f"'pooled'"
            )
        if cache_bytes and cache_scope == "pooled":
            from dptpu.data.shm_cache import ShmDecodeCache

            self.decode_cache = ShmDecodeCache(cache_bytes)
        elif cache_bytes:
            from dptpu.data.cache import DecodeCache

            self.decode_cache = DecodeCache(cache_bytes)
        else:
            self.decode_cache = None
        classes = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d))
        )
        if not classes:
            raise FileNotFoundError(f"no class directories under {root!r}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples: list[Tuple[str, int]] = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, filenames in sorted(os.walk(cdir)):
                for fn in sorted(filenames):
                    if fn.lower().endswith(_IMG_EXTENSIONS):
                        self.samples.append(
                            (os.path.join(dirpath, fn), self.class_to_idx[c])
                        )
        if not self.samples:
            raise FileNotFoundError(f"no images under {root!r}")

    def __len__(self) -> int:
        return len(self.samples)

    @staticmethod
    def _read_file(path: str):
        def read_bytes():
            with open(path, "rb") as f:
                return f.read()
        return read_bytes

    def _native_decode(self, path: str, rng, out=None):
        """Fused libjpeg decode-crop-resize into ``out`` (or a fresh
        array); None when this sample/environment can't take the path
        (see :func:`native_decode_sample` — the bytes-level
        implementation shared with the packed-shard dataset)."""
        return native_decode_sample(
            self._read_file(path),
            path.lower().endswith((".jpg", ".jpeg")),
            self.transform, rng,
            decode_cache=self.decode_cache, cache_key=("native", path),
            out=out,
        )

    def _pil_decode(self, path: str, rng):
        return pil_decode_sample(
            self._read_file(path), self.transform, rng,
            decode_cache=self.decode_cache, cache_key=("pil", path),
        )

    def get(self, index: int, rng: Optional[np.random.Generator] = None):
        """Load + transform one sample; ``rng`` drives any augmentation
        randomness (per-item, loader-provided — see DataLoader).

        JPEGs with a box-sampling transform take the native fast path
        (libjpeg scaled decode + fused crop-resize, dptpu/native) when the
        in-tree C library is buildable; everything else decodes via PIL.
        Both paths consume the same sampled crop box, so the choice of
        decoder never changes which pixels a seeded run selects.
        """
        path, label = self.samples[index]
        if rng is None:
            rng = np.random.default_rng(index)
        out = self._native_decode(path, rng)
        if out is None:
            out = self._pil_decode(path, rng)
        return out, label

    def get_into(self, index: int, rng, out: np.ndarray) -> int:
        """Decode + transform sample ``index`` DIRECTLY into ``out``
        (uint8 HWC — typically one row of the loader's preallocated
        batch) and return the label. The native path writes the pixels
        in place with zero intermediates; fallbacks copy once."""
        path, label = self.samples[index]
        nat = self._native_decode(path, rng, out=out)
        if nat is None:
            _copy_checked(out, self._pil_decode(path, rng), index)
        elif nat is not out:  # non-contiguous out fell back to a fresh array
            np.copyto(out, nat)
        return label

    def __getitem__(self, index: int):
        return self.get(index)


class SyntheticDataset:
    """Deterministic random uint8 HWC images; index-stable across epochs."""

    def __init__(self, num_samples: int = 1024, image_size: int = 224,
                 num_classes: int = 1000, transform: Optional[Callable] = None):
        self.num_samples = num_samples
        self.image_size = image_size
        self.num_classes = num_classes
        self.transform = transform

    def __len__(self) -> int:
        return self.num_samples

    def get(self, index: int, rng: Optional[np.random.Generator] = None):
        data_rng = np.random.RandomState(index % self.num_samples)
        img = data_rng.randint(
            0, 256, (self.image_size, self.image_size, 3), dtype=np.uint8
        )
        label = int(data_rng.randint(0, self.num_classes))
        if self.transform:
            from PIL import Image

            img = self.transform(
                Image.fromarray(img),
                rng if rng is not None else np.random.default_rng(index),
            )
        return img, label

    def get_into(self, index: int, rng, out: np.ndarray) -> int:
        """Loader fast-path API parity with ImageFolderDataset (one copy
        into the preallocated batch row; generation dominates anyway)."""
        img, label = self.get(index, rng)
        _copy_checked(out, img, index)
        return label

    def __getitem__(self, index: int):
        return self.get(index)
