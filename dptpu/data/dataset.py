"""Datasets: ImageFolder-layout reader + synthetic stand-in.

``ImageFolderDataset`` reproduces ``datasets.ImageFolder`` semantics
(reference imagenet_ddp.py:166-173): one subdirectory per class under the
root, class index = position in the *sorted* subdirectory list, every image
file inside belongs to that class. Decoding is PIL (RGB), matching
torchvision's default loader.

``SyntheticDataset`` generates deterministic random uint8 images — the
fixture for integration tests and throughput benchmarks (it removes host
decode from the measurement, isolating the device-side number).
"""

from __future__ import annotations

import os
from typing import Callable, Optional, Tuple

import numpy as np

_IMG_EXTENSIONS = (
    ".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif", ".tiff", ".webp",
)


class ImageFolderDataset:
    """root/<class_name>/<image> layout, torchvision class-index semantics."""

    def __init__(self, root: str, transform: Optional[Callable] = None):
        self.root = root
        self.transform = transform
        classes = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d))
        )
        if not classes:
            raise FileNotFoundError(f"no class directories under {root!r}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples: list[Tuple[str, int]] = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, filenames in sorted(os.walk(cdir)):
                for fn in sorted(filenames):
                    if fn.lower().endswith(_IMG_EXTENSIONS):
                        self.samples.append(
                            (os.path.join(dirpath, fn), self.class_to_idx[c])
                        )
        if not self.samples:
            raise FileNotFoundError(f"no images under {root!r}")

    def __len__(self) -> int:
        return len(self.samples)

    def get(self, index: int, rng: Optional[np.random.Generator] = None):
        """Load + transform one sample; ``rng`` drives any augmentation
        randomness (per-item, loader-provided — see DataLoader).

        JPEGs with a box-sampling transform take the native fast path
        (libjpeg scaled decode + fused crop-resize, dptpu/native) when the
        in-tree C library is buildable; everything else decodes via PIL.
        Both paths consume the same sampled crop box, so the choice of
        decoder never changes which pixels a seeded run selects.
        """
        path, label = self.samples[index]
        if rng is None:
            rng = np.random.default_rng(index)
        if self.transform is not None and hasattr(self.transform, "sample") \
                and path.lower().endswith((".jpg", ".jpeg")):
            from dptpu.data import native_image

            if native_image.available():
                with open(path, "rb") as f:
                    data = f.read()
                dims = native_image.jpeg_dims(data)
                if dims is not None:
                    box, flip = self.transform.sample(dims[0], dims[1], rng)
                    out = native_image.decode_crop_resize(
                        data, box, self.transform.size, flip
                    )
                    if out is not None:
                        return out, label
        from PIL import Image

        with Image.open(path) as img:
            img = img.convert("RGB")
            if self.transform is None:
                out = np.asarray(img)
            else:
                out = self.transform(img, rng)
        return out, label

    def __getitem__(self, index: int):
        return self.get(index)


class SyntheticDataset:
    """Deterministic random uint8 HWC images; index-stable across epochs."""

    def __init__(self, num_samples: int = 1024, image_size: int = 224,
                 num_classes: int = 1000, transform: Optional[Callable] = None):
        self.num_samples = num_samples
        self.image_size = image_size
        self.num_classes = num_classes
        self.transform = transform

    def __len__(self) -> int:
        return self.num_samples

    def get(self, index: int, rng: Optional[np.random.Generator] = None):
        data_rng = np.random.RandomState(index % self.num_samples)
        img = data_rng.randint(
            0, 256, (self.image_size, self.image_size, 3), dtype=np.uint8
        )
        label = int(data_rng.randint(0, self.num_classes))
        if self.transform:
            from PIL import Image

            img = self.transform(
                Image.fromarray(img),
                rng if rng is not None else np.random.default_rng(index),
            )
        return img, label

    def __getitem__(self, index: int):
        return self.get(index)
