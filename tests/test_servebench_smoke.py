"""Tier-1 smoke of scripts/run_servebench.py (the pattern of
test_obsbench_smoke.py): the serving stack's latency/throughput curves,
bucket accounting, padded-parity gate, tail gate and the ISSUE 17
robustness arms (overload shedding, multi-model, canary auto-rollback,
dead-request hygiene, serve faults) are continuously checked — one
subprocess, smallest preset, same gate logic as the committed
SERVEBENCH.json."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_servebench_smoke_gates(tmp_path):
    out = str(tmp_path / "SERVEBENCH.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # real single-CPU topology, like the obsbench smoke: the fake
    # 8-device pod the conftest forces is a training-suite fixture; the
    # serving gates being smoked are topology-independent
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "run_servebench.py"),
         "--smoke", "--out", out],
        capture_output=True, text=True, timeout=480, env=env,
        cwd=str(tmp_path),
    )
    assert proc.returncode == 0, (
        f"servebench gate failed\nstdout:\n{proc.stdout[-4000:]}\n"
        f"stderr:\n{proc.stderr[-4000:]}"
    )
    with open(out) as f:
        bench = json.load(f)
    # the acceptance contract: padded-bucket serving is logit-identical
    # to the single-request path, EXACTLY
    assert bench["parity_max_abs_dlogit"] == 0.0
    assert bench["gates"]["parity_ok"] and bench["gates"]["tail_ok"]
    # both load models produced complete points
    for point in list(bench["closed_loop"].values()) \
            + list(bench["open_loop"].values()):
        assert point["requests"] > 0
        assert point["p50_ms"] <= point["p99_ms"] <= point["max_ms"]
        # every dispatched batch is accounted to a configured bucket
        assert all(int(b) in bench["buckets"]
                   for b in point["bucket_counts"])
        assert 0.0 <= point["padding_waste"] < 1.0
    # open-loop points record what was offered (the load model's knob)
    assert all("offered_qps" in p for p in bench["open_loop"].values())
    assert bench["saturation_qps"] > 0
    # the tail gate is evaluated at the SLO-typical 0.5x-saturation point
    assert bench["tail_gate"]["at_offered_frac"] == 0.5
    assert bench["tail_gate"]["p99_ms"] <= bench["tail_gate"]["budget_ms"]
    # robustness arms (ISSUE 17), all gated
    g = bench["gates"]
    assert g["shed_ok"] and g["multi_model_ok"] and g["canary_ok"]
    assert g["hygiene_ok"] and g["faults_ok"]
    rb = bench["robustness"]
    # overload: 2x saturation through admission actually shed, admitted
    # p99 stayed bounded, and every shed decision beat a service time
    shed = rb["overload_shedding"]
    assert shed["shed"] > 0 and shed["admitted"] > 0
    assert shed["admitted_p99_ms"] <= shed["admitted_p99_budget_ms"]
    assert shed["shed_decision_p99_ms"] < shed["admitted_p50_ms"]
    # multi-model: two co-resident engines both completed under
    # concurrent load, per-model p99s on record
    mm = rb["multi_model"]["models"]
    assert set(mm) == {"a", "b"}
    assert all(m["p99_ms"] > 0 and m["requests"] > 0 for m in mm.values())
    # canary: the injected drift triggered EXACTLY one loud rollback and
    # no response ever mixed generations
    can = rb["canary_rollback"]
    assert can["state"] == "rolled_back" and can["rollbacks"] == 1
    assert can["mixed_generation_responses"] == 0
    assert can["post_rollback_serves_base"]
    assert "ROLLED BACK" in proc.stderr
    # hygiene: 4 cancelled of 6 claimed -> dispatched at the LIVE
    # count's bucket; padding-waste accounting proves zero dead rows
    hyg = rb["dead_request_hygiene"]
    assert hyg["dead_rows"] == 4
    assert hyg["dispatched_bucket"] < hyg["claimed_bucket"]
    # every serve fault scenario green
    flt = rb["serve_faults"]
    assert flt["serve_exception"]["ok"]
    assert flt["preprocess_crash"]["ok"]
    assert flt["slow_model"]["ok"]
    # quantized arm (ISSUE 18): the int8 rollout PROMOTED through the
    # canary's artifact-armed gate (never assumed), measured drift sits
    # inside the artifact's own bounds, and the acceptance lever held —
    # on this CPU host that is the >= 40% resident-bytes cut (compute
    # speedup is a TPU claim, gated statically by the serve-quant HLO
    # budget row)
    assert g["quant_ok"]
    quant = bench["quantized"]
    assert quant["rollout"]["state"] == "promoted"
    assert quant["rollout"]["rollbacks"] == 0
    cal = quant["calibration"]
    assert cal["max_abs_dlogit"] <= cal["bounds"]["max_abs_dlogit"]
    assert cal["top1_agreement"] >= cal["bounds"]["min_top1_agreement"]
    rb_bytes = quant["resident_bytes"]
    assert rb_bytes["int8"] < rb_bytes["bf16"] < rb_bytes["fp32"]
    assert quant["residency_cut"] >= 0.40 or quant["speedup"] >= 1.3
    # the co-resident interference point ran with BOTH generations
    # serving (the deterministic 0.5-fraction pick guarantees both)
    co = quant["coresident"]
    assert co["requests"] > 0 and co["qps"] > 0
    assert set(co["by_generation"]) == {"fp32", "int8"}
    # fleet arm (ISSUE 18): hard-killing one of two member hosts
    # mid-load lost ZERO requests — the router failed over in-flight
    # forwards and the staleness verdict auto-drained the corpse
    assert g["fleet_ok"]
    fleet = bench["fleet"]
    assert fleet["failed_requests"] == 0 and not fleet["client_errors"]
    assert fleet["requests"] > fleet["killed_at_request"]
    assert fleet["failovers"] >= 1 and fleet["drains"] >= 1
    assert fleet["survivors"] == ["host-b"]
    assert fleet["ready_after_drain"]
    # the drain curve recorded the member count dropping to 1
    assert any(p["members"] == 1 for p in fleet["drain_curve"])
    assert "DRAINED member host-a" in proc.stderr
