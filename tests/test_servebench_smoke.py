"""Tier-1 smoke of scripts/run_servebench.py (the pattern of
test_obsbench_smoke.py): the serving stack's latency/throughput curves,
bucket accounting, padded-parity gate and tail gate are continuously
checked — one subprocess, smallest preset, same gate logic as the
committed SERVEBENCH.json."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_servebench_smoke_gates(tmp_path):
    out = str(tmp_path / "SERVEBENCH.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # real single-CPU topology, like the obsbench smoke: the fake
    # 8-device pod the conftest forces is a training-suite fixture; the
    # serving gates being smoked are topology-independent
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "run_servebench.py"),
         "--smoke", "--out", out],
        capture_output=True, text=True, timeout=480, env=env,
        cwd=str(tmp_path),
    )
    assert proc.returncode == 0, (
        f"servebench gate failed\nstdout:\n{proc.stdout[-4000:]}\n"
        f"stderr:\n{proc.stderr[-4000:]}"
    )
    with open(out) as f:
        bench = json.load(f)
    # the acceptance contract: padded-bucket serving is logit-identical
    # to the single-request path, EXACTLY
    assert bench["parity_max_abs_dlogit"] == 0.0
    assert bench["gates"]["parity_ok"] and bench["gates"]["tail_ok"]
    # both load models produced complete points
    for point in list(bench["closed_loop"].values()) \
            + list(bench["open_loop"].values()):
        assert point["requests"] > 0
        assert point["p50_ms"] <= point["p99_ms"] <= point["max_ms"]
        # every dispatched batch is accounted to a configured bucket
        assert all(int(b) in bench["buckets"]
                   for b in point["bucket_counts"])
        assert 0.0 <= point["padding_waste"] < 1.0
    # open-loop points record what was offered (the load model's knob)
    assert all("offered_qps" in p for p in bench["open_loop"].values())
    assert bench["saturation_qps"] > 0
    # the tail gate is evaluated at the SLO-typical 0.5x-saturation point
    assert bench["tail_gate"]["at_offered_frac"] == 0.5
    assert bench["tail_gate"]["p99_ms"] <= bench["tail_gate"]["budget_ms"]
