"""Model zoo structural parity: parameter counts must equal torchvision's
(the reference's model source, imagenet_ddp.py:108-114), output shapes must
be [batch, num_classes], and BN state must exist exactly where expected."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dptpu.models import create_model, model_names

# Exact torchvision parameter counts (weights + biases + BN affine;
# excluding BN running stats, which live in a separate collection here
# just as they are non-Parameter buffers in torch).
TORCHVISION_PARAM_COUNTS = {
    "resnet18": 11_689_512,
    "resnet34": 21_797_672,
    "resnet50": 25_557_032,
    "resnet101": 44_549_160,
    "resnet152": 60_192_808,
    "alexnet": 61_100_840,
    "vgg11": 132_863_336,
    "vgg11_bn": 132_868_840,
    "vgg13": 133_047_848,
    "vgg13_bn": 133_053_736,
    "vgg16": 138_357_544,
    "vgg16_bn": 138_365_992,
    "vgg19": 143_667_240,
    "vgg19_bn": 143_678_248,
    "densenet121": 7_978_856,
    "densenet161": 28_681_000,
    "densenet169": 14_149_480,
    "densenet201": 20_013_928,
    "squeezenet1_0": 1_248_424,
    "squeezenet1_1": 1_235_496,
    "wide_resnet50_2": 68_883_240,
    "wide_resnet101_2": 126_886_696,
    "resnext50_32x4d": 25_028_904,
    "resnext101_32x8d": 88_791_336,
    "mobilenet_v2": 3_504_872,
    "shufflenet_v2_x0_5": 1_366_792,
    "shufflenet_v2_x1_0": 2_278_604,
    "mnasnet0_5": 2_218_512,
    "mnasnet1_0": 4_383_312,
    "shufflenet_v2_x1_5": 3_503_624,
    "shufflenet_v2_x2_0": 7_393_996,
    "mnasnet0_75": 3_170_208,
    "mnasnet1_3": 6_282_256,
    "mobilenet_v3_large": 5_483_032,
    "mobilenet_v3_small": 2_542_856,
    "googlenet": 6_624_904,
    "efficientnet_b0": 5_288_548,
    "efficientnet_b1": 7_794_184,
    "efficientnet_b2": 9_109_994,
    "efficientnet_b3": 12_233_232,
    "efficientnet_b4": 19_341_616,
    "efficientnet_b5": 30_389_784,
    "efficientnet_b6": 43_040_704,
    "efficientnet_b7": 66_347_960,
    "efficientnet_v2_s": 21_458_488,
    "efficientnet_v2_m": 54_139_356,
    "efficientnet_v2_l": 118_515_272,
    "regnet_x_400mf": 5_495_976,
    "regnet_x_800mf": 7_259_656,
    "regnet_x_1_6gf": 9_190_136,
    "regnet_x_3_2gf": 15_296_552,
    "regnet_x_8gf": 39_572_648,
    "regnet_x_16gf": 54_278_536,
    "regnet_x_32gf": 107_811_560,
    "regnet_y_400mf": 4_344_144,
    "regnet_y_800mf": 6_432_512,
    "regnet_y_1_6gf": 11_202_430,
    "regnet_y_3_2gf": 19_436_338,
    "regnet_y_8gf": 39_381_472,
    "regnet_y_16gf": 83_590_140,
    "regnet_y_32gf": 145_046_770,
    "regnet_y_128gf": 644_812_894,
    "maxvit_t": 30_919_624,  # image-size independent, needs 224-style grid
    "swin_t": 28_288_354,
    "swin_s": 49_606_258,
    "swin_b": 87_768_224,
    "swin_v2_t": 28_351_570,
    "swin_v2_s": 49_737_442,
    "swin_v2_b": 87_930_848,
    "convnext_tiny": 28_589_128,
    "convnext_small": 50_223_688,
    "convnext_base": 88_591_464,
    "convnext_large": 197_767_336,
    # ViT counts are image-size dependent (pos embedding); locked at 224
    "vit_b_16": 86_567_656,
    "vit_b_32": 88_224_232,
    "vit_l_16": 304_326_632,
    "vit_l_32": 306_535_400,
    "vit_h_14": 632_045_800,
}


def _init(name, image=64):
    model = create_model(name)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, image, image, 3), jnp.float32)
    )
    return model, variables


def _count(tree):
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(tree))


def _param_count(name, image=64):
    """Parameter count via jax.eval_shape — exact (counts need shapes
    only) and ~100x faster than materializing a 100M-param init on CPU."""
    model = create_model(name)
    shapes = jax.eval_shape(
        model.init, jax.random.PRNGKey(0),
        jnp.zeros((1, image, image, 3), jnp.float32),
    )
    return _count(shapes["params"])


@pytest.mark.parametrize("name", sorted(TORCHVISION_PARAM_COUNTS))
def test_param_counts_match_torchvision(name):
    image = (224 if name.startswith(("alexnet", "vgg", "squeezenet", "vit",
                                     "maxvit"))
             else 64)
    assert _param_count(name, image) == TORCHVISION_PARAM_COUNTS[name]


@pytest.mark.parametrize("name,image", [
    ("vgg11_bn", 224), ("mnasnet0_5", 64), ("resnext50_32x4d", 64),
    ("wide_resnet50_2", 64), ("alexnet", 224), ("mobilenet_v3_small", 64),
    ("efficientnet_b0", 64), ("efficientnet_v2_s", 64),
    ("regnet_y_400mf", 64), ("regnet_x_400mf", 64), ("vit_b_32", 64),
    ("convnext_tiny", 64), ("swin_t", 64), ("swin_v2_t", 64),
])
def test_family_concrete_init_and_forward(name, image):
    """One CONCRETE init+forward per family not covered elsewhere:
    eval_shape-based count tests never execute initializers, so a
    value-level init bug (NaN std, concrete-only dtype path) needs this."""
    m = create_model(name, num_classes=5)
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, image, image, 3)))
    out = m.apply(v, jnp.zeros((2, image, image, 3)), train=False)
    assert out.shape == (2, 5)
    assert np.isfinite(np.asarray(out)).all()


def test_maxvit_rejects_bad_grid():
    m = create_model("maxvit_t", num_classes=3)
    with pytest.raises(ValueError, match="divisible"):
        jax.eval_shape(
            m.init, jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3))
        )


def test_maxvit_forward():
    m = create_model("maxvit_t", num_classes=3)
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)))
    out = m.apply(v, jnp.ones((1, 224, 224, 3)), train=False)
    assert out.shape == (1, 3)
    assert np.isfinite(np.asarray(out)).all()


def test_swin_static_helpers():
    from dptpu.models.swin import (
        _coords_table,
        _relative_position_index,
        _shift_mask,
    )

    idx = _relative_position_index(7)
    assert idx.shape == (49, 49) and idx.min() == 0 and idx.max() == 168
    # every self-pair maps to the center of the (2w-1)^2 table
    assert (np.diag(idx) == 6 * 13 + 6).all()
    m = _shift_mask(21, 21, 7, 3, 3)
    assert m.shape == (9, 49, 49)
    assert (m[0] == 0).all()  # interior window: no masking
    assert (m == np.transpose(m, (0, 2, 1))).all()  # pair symmetry
    assert (m[-1] != 0).any()  # corner window crosses regions
    t = _coords_table(8)
    # torchvision normalizes to sign(x)*log2(|8x|+1)/3: max = log2(9)/3
    assert t.shape == (225, 2)
    np.testing.assert_allclose(np.abs(t).max(), np.log2(9.0) / 3, rtol=1e-6)


def test_shufflenet_forward_and_channel_shuffle():
    from dptpu.models.shufflenet import channel_shuffle

    x = jnp.arange(8.0).reshape(1, 1, 1, 8)
    # groups=2: [0..3 | 4..7] interleaves to [0,4,1,5,2,6,3,7]
    np.testing.assert_array_equal(
        np.asarray(channel_shuffle(x)).ravel(), [0, 4, 1, 5, 2, 6, 3, 7]
    )
    m = create_model("shufflenet_v2_x0_5", num_classes=6)
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
    out = m.apply(v, jnp.zeros((2, 64, 64, 3)), train=False)
    assert out.shape == (2, 6)


def test_mobilenet_v2_param_count_and_forward():
    m = create_model("mobilenet_v2", num_classes=9)
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
    out = m.apply(v, jnp.zeros((2, 64, 64, 3)), train=False)
    assert out.shape == (2, 9)


def test_squeezenet_ceil_mode_pool_shapes():
    """torchvision squeezenet1_0 feature map is 13x13 at 224 input; the
    ceil-mode pools are what make the 54 -> 27 -> 13 chain work."""
    m = create_model("squeezenet1_0", num_classes=10)
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)))
    out = m.apply(v, jnp.zeros((2, 224, 224, 3)), train=False)
    assert out.shape == (2, 10)


def test_densenet_forward_and_bn_state():
    m = create_model("densenet121", num_classes=5)
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
    assert "batch_stats" in v  # DenseNet is BN-heavy
    out, mutated = m.apply(
        v, jnp.ones((2, 64, 64, 3)), train=True, mutable=["batch_stats"]
    )
    assert out.shape == (2, 5)
    assert np.isfinite(np.asarray(out)).all()


def test_googlenet_inception_aux_param_counts():
    """torchvision's documented inception_v3 count (27,161,264) includes
    the aux head (its default constructor carries it); googlenet's
    documented 6,624,904 excludes aux. Lock both aux trees."""
    import jax as _jax

    def count(name, **kw):
        m = create_model(name, **kw)
        image = 299 if name == "inception_v3" else 64
        shapes = _jax.eval_shape(
            lambda r, x: m.init(r, x), jax.random.PRNGKey(0),
            jnp.zeros((1, image, image, 3)),
        )
        return _count(shapes["params"])

    assert count("inception_v3", aux_logits=True) == 27_161_264
    assert count("inception_v3") == 23_834_568  # minus the aux head
    assert count("googlenet", aux_logits=True) == 13_004_888


def test_googlenet_inception_forward():
    for name, image in (("googlenet", 64), ("inception_v3", 299)):
        m = create_model(name, num_classes=4)
        v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, image, image, 3)))
        out = m.apply(v, jnp.zeros((2, image, image, 3)), train=False)
        assert out.shape == (2, 4)
        assert np.isfinite(np.asarray(out)).all()


def test_registry_surface():
    names = model_names()
    assert names == sorted(names)
    for required in ("resnet18", "resnet50", "resnet152", "alexnet", "vgg16",
                     "densenet121", "densenet201", "squeezenet1_0",
                     "squeezenet1_1"):
        assert required in names


def test_pretrained_without_weights_fails_fast(monkeypatch, tmp_path):
    # no converted weights anywhere -> actionable error naming the converter
    monkeypatch.setenv("DPTPU_PRETRAINED_DIR", str(tmp_path))
    with pytest.raises(FileNotFoundError, match="convert_torchvision"):
        create_model("resnet50", pretrained=True)


def test_resnet_forward_shapes_and_finite():
    model, variables = _init("resnet18")
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 64, 3))
    logits = model.apply(variables, x)
    assert logits.shape == (2, 1000)
    assert bool(jnp.isfinite(logits).all())


def test_resnet_train_mode_updates_batch_stats():
    model, variables = _init("resnet18")
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 64, 64, 3)) + 3.0
    _, mutated = model.apply(variables, x, train=True, mutable=["batch_stats"])
    before = jax.tree_util.tree_leaves(variables["batch_stats"])
    after = jax.tree_util.tree_leaves(mutated["batch_stats"])
    assert any(not np.allclose(b, a) for b, a in zip(before, after))


def test_num_classes_override():
    model = create_model("resnet18", num_classes=10)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
    logits = model.apply(variables, jnp.zeros((2, 64, 64, 3)))
    assert logits.shape == (2, 10)


def test_bf16_compute_dtype_keeps_fp32_params():
    model = create_model("resnet18", dtype=jnp.bfloat16)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
    kernels = jax.tree_util.tree_leaves(variables["params"])
    assert all(k.dtype == jnp.float32 for k in kernels)
    logits = model.apply(variables, jnp.zeros((2, 64, 64, 3), jnp.bfloat16))
    assert logits.dtype == jnp.bfloat16


def test_space_to_depth_stem_matches_standard():
    """s2d stem is a pure re-layout: same params, allclose outputs (ADVICE
    round 1; VERDICT round 1 item 2). Checked through the full resnet18."""
    std = create_model("resnet18")
    s2d = create_model("resnet18", stem_space_to_depth=True)
    variables = std.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 64, 64, 3))
    out_std = std.apply(variables, x)
    out_s2d = s2d.apply(variables, x)  # same variables: params interchange
    np.testing.assert_allclose(
        np.asarray(out_std), np.asarray(out_s2d), atol=2e-5, rtol=2e-5
    )


def test_space_to_depth_stem_at_224():
    """The s2d padding math must hold at the real 224 input — the shipping
    config (imagenet_ddp.py:169)."""
    std = create_model("resnet18", num_classes=8)
    s2d = create_model("resnet18", num_classes=8, stem_space_to_depth=True)
    variables = std.init(jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)))
    x = jax.random.normal(jax.random.PRNGKey(5), (1, 224, 224, 3))
    np.testing.assert_allclose(
        np.asarray(std.apply(variables, x)),
        np.asarray(s2d.apply(variables, x)),
        atol=2e-5,
        rtol=2e-5,
    )


def test_space_to_depth_stem_rejects_odd_input():
    s2d = create_model("resnet18", num_classes=8, stem_space_to_depth=True)
    std = create_model("resnet18", num_classes=8)
    variables = std.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
    with pytest.raises(ValueError, match="even input"):
        s2d.apply(variables, jnp.zeros((1, 65, 65, 3)))


def test_dropout_models_need_rng_in_train():
    model, variables = _init("alexnet", image=224)
    x = jnp.zeros((2, 224, 224, 3))
    out = model.apply(
        variables, x, train=True, rngs={"dropout": jax.random.PRNGKey(3)}
    )
    assert out.shape == (2, 1000)
