"""Native C++ image ops: build, decode correctness vs PIL, fallback paths."""

import io

import numpy as np
import pytest
from PIL import Image

from dptpu.data import native_image
from dptpu.data.dataset import ImageFolderDataset
from dptpu.data.transforms import TrainTransform, ValTransform

pytestmark = pytest.mark.skipif(
    not native_image.available(), reason="native toolchain/libjpeg unavailable"
)


def _jpeg_bytes(arr, quality=95):
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG", quality=quality)
    return buf.getvalue()


def _smooth_image(w, h):
    # smooth gradient → JPEG-compresses nearly losslessly, so decoder
    # differences dominate the comparison, not compression artifacts
    x = np.linspace(0, 255, w, dtype=np.float32)
    y = np.linspace(0, 255, h, dtype=np.float32)[:, None]
    r = np.broadcast_to(x, (h, w))
    g = np.broadcast_to(y, (h, w))
    b = (r + g) / 2
    return np.stack([r, g, b], axis=-1).astype(np.uint8)


def test_jpeg_dims():
    data = _jpeg_bytes(_smooth_image(320, 200))
    assert native_image.jpeg_dims(data) == (320, 200)
    assert native_image.jpeg_dims(b"not a jpeg") is None


def test_decode_matches_pil_closely():
    arr = _smooth_image(400, 300)
    data = _jpeg_bytes(arr)
    box = (40, 30, 300, 240)
    native = native_image.decode_crop_resize(data, box, 224, flip=False)
    assert native is not None and native.shape == (224, 224, 3)

    with Image.open(io.BytesIO(data)) as img:
        pil = np.asarray(
            img.convert("RGB").resize(
                (224, 224), 2, box=(40, 30, 340, 270)
            ),
            dtype=np.uint8,
        )
    diff = np.abs(native.astype(int) - pil.astype(int))
    # same pixels selected; small resampler differences allowed
    assert np.mean(diff) < 3.0, np.mean(diff)
    assert np.percentile(diff, 99) <= 12


def test_decode_flip():
    arr = _smooth_image(256, 256)
    data = _jpeg_bytes(arr)
    box = (0, 0, 256, 256)
    plain = native_image.decode_crop_resize(data, box, 64, flip=False)
    flipped = native_image.decode_crop_resize(data, box, 64, flip=True)
    np.testing.assert_array_equal(plain[:, ::-1], flipped)


def test_scaled_decode_still_accurate():
    # large source, small crop target → libjpeg scale path engages
    arr = _smooth_image(1600, 1200)
    data = _jpeg_bytes(arr)
    box = ValTransform(224, 256).sample(1600, 1200)[0]
    native = native_image.decode_crop_resize(data, box, 224, flip=False)
    with Image.open(io.BytesIO(data)) as img:
        left, top, cw, ch = box
        pil = np.asarray(
            img.convert("RGB").resize(
                (224, 224), 2, box=(left, top, left + cw, top + ch)
            ),
            dtype=np.uint8,
        )
    assert np.mean(np.abs(native.astype(int) - pil.astype(int))) < 4.0


def test_dataset_native_path_and_png_fallback(tmp_path):
    arr = _smooth_image(300, 300)
    d = tmp_path / "train" / "c0"
    d.mkdir(parents=True)
    Image.fromarray(arr).save(d / "a.jpg", quality=95)
    Image.fromarray(arr).save(d / "b.png")
    ds = ImageFolderDataset(str(tmp_path / "train"), TrainTransform(64))
    rng_a = np.random.default_rng(7)
    rng_b = np.random.default_rng(7)
    img_jpg, _ = ds.get(0, rng_a)  # native path
    img_png, _ = ds.get(1, rng_b)  # PIL fallback, same rng stream → same box
    assert img_jpg.shape == img_png.shape == (64, 64, 3)
    # same sampled crop on (nearly) identical sources → near-identical output
    assert np.mean(np.abs(img_jpg.astype(int) - img_png.astype(int))) < 4.0


def test_val_pipeline_routes_to_exact_pil_path(tmp_path):
    """The PRODUCTION val path (ImageFolderDataset + ValTransform on a
    JPEG) must be bit-identical to torchvision's two-step pipeline —
    i.e. the approximate native fast path (scaled decode + IFAST +
    2-tap lerp) must NOT engage for validation, only for train
    augmentation (native_ok veto)."""
    arr = _smooth_image(500, 400)
    d = tmp_path / "val" / "c0"
    d.mkdir(parents=True)
    Image.fromarray(arr).save(d / "a.jpg", quality=85)
    ds = ImageFolderDataset(str(tmp_path / "val"), ValTransform(224, 256))
    got = ds.get(0)[0].astype(np.int16)
    with Image.open(d / "a.jpg") as img:
        img = img.convert("RGB")
        w, h = img.size
        if w <= h:
            nw, nh = 256, int(256 * h / w)
        else:
            nh, nw = 256, int(256 * w / h)
        resized = img.resize((nw, nh), Image.BILINEAR)
        left, top = (nw - 224) // 2, (nh - 224) // 2
        want = np.asarray(
            resized.crop((left, top, left + 224, top + 224)), np.int16
        )
    d2 = np.abs(got - want)
    assert d2.max() <= 1 and (d2 > 0).mean() < 0.02, (
        d2.max(), (d2 > 0).mean()
    )
    # the in-place loader path routes identically
    out = np.empty((224, 224, 3), np.uint8)
    ds.get_into(0, np.random.default_rng(0), out)
    np.testing.assert_array_equal(out, got.astype(np.uint8))


# ---------------------------------------------------- serve-ingest (ISSUE 18)


def _serve_lib():
    from dptpu.native.build import load_library

    lib = load_library()
    if lib is None or not hasattr(lib, "dptpu_serve_ingest"):
        pytest.skip("native lib without dptpu_serve_ingest")
    return lib


def test_serve_ingest_bit_identical_to_pil_matrix():
    """The fused serve-ingest kernel byte-matches the PIL val path —
    BIT-identity, not closeness — across geometries that exercise every
    resample branch: odd dims, portrait/landscape, square, enlarge
    (source smaller than the resize edge), progressive scan, 4:4:4."""
    from dptpu.serve.preprocess import _pil_val_pixels, val_resize_for

    lib = _serve_lib()
    rng = np.random.RandomState(0)
    cases = []
    for (w, h), kw in [
        ((337, 251), {"quality": 85}),
        ((251, 337), {"quality": 85}),
        ((224, 224), {"quality": 92}),
        ((96, 80), {"quality": 90}),        # box-ENLARGE path
        ((230, 310), {"quality": 85, "progressive": True}),
        ((301, 200), {"quality": 95, "subsampling": 0}),
    ]:
        buf = io.BytesIO()
        Image.fromarray(
            rng.randint(0, 256, (h, w, 3), np.uint8)
        ).save(buf, "JPEG", **kw)
        cases.append(buf.getvalue())
    for size in (224, 32):
        resize = val_resize_for(size)
        for data in cases:
            ref = _pil_val_pixels(data, size, resize)
            out = np.empty((size, size, 3), np.uint8)
            rc = lib.dptpu_serve_ingest(data, len(data), size, resize,
                                        out.ctypes.data)
            assert rc == 0
            np.testing.assert_array_equal(out, ref)


def test_serve_ingest_grayscale_matches_pil_convert():
    from dptpu.serve.preprocess import _pil_val_pixels

    lib = _serve_lib()
    rng = np.random.RandomState(1)
    buf = io.BytesIO()
    Image.fromarray(rng.randint(0, 256, (200, 300), np.uint8), "L").save(
        buf, "JPEG", quality=88
    )
    data = buf.getvalue()
    out = np.empty((224, 224, 3), np.uint8)
    rc = lib.dptpu_serve_ingest(data, len(data), 224, 256, out.ctypes.data)
    assert rc == 0
    np.testing.assert_array_equal(out, _pil_val_pixels(data, 224, 256))


def test_serve_ingest_bails_negative_on_cmyk_and_garbage():
    """Per-image bails return negative (caller falls to PIL) instead of
    writing wrong pixels."""
    lib = _serve_lib()
    out = np.empty((224, 224, 3), np.uint8)
    buf = io.BytesIO()
    Image.fromarray(
        np.random.RandomState(2).randint(0, 256, (60, 80, 4), np.uint8),
        "CMYK",
    ).save(buf, "JPEG")
    data = buf.getvalue()
    assert lib.dptpu_serve_ingest(data, len(data), 224, 256,
                                  out.ctypes.data) < 0
    bad = b"\xff\xd8\xff" + b"garbage" * 16
    assert lib.dptpu_serve_ingest(bad, len(bad), 224, 256,
                                  out.ctypes.data) < 0


def test_preprocess_bytes_uses_native_only_after_probe(monkeypatch):
    """The probe gate: when the probe says the kernel is not
    bit-identical on this host, preprocess_bytes stays on PIL — same
    pixels, loudly."""
    from dptpu.serve import preprocess as pp

    _serve_lib()
    rng = np.random.RandomState(3)
    buf = io.BytesIO()
    Image.fromarray(rng.randint(0, 256, (180, 260, 3), np.uint8)).save(
        buf, "JPEG", quality=85
    )
    data = buf.getvalue()
    ref = pp._pil_val_pixels(data, 224, 256)

    monkeypatch.setattr(pp, "_NATIVE_INGEST_OK", False)
    np.testing.assert_array_equal(pp.preprocess_bytes(data), ref)

    monkeypatch.setattr(pp, "_NATIVE_INGEST_OK", None)  # force re-probe
    np.testing.assert_array_equal(pp.preprocess_bytes(data), ref)
    assert pp._NATIVE_INGEST_OK is True  # probe ran and passed here
