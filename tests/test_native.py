"""Native C++ image ops: build, decode correctness vs PIL, fallback paths."""

import io

import numpy as np
import pytest
from PIL import Image

from dptpu.data import native_image
from dptpu.data.dataset import ImageFolderDataset
from dptpu.data.transforms import TrainTransform, ValTransform

pytestmark = pytest.mark.skipif(
    not native_image.available(), reason="native toolchain/libjpeg unavailable"
)


def _jpeg_bytes(arr, quality=95):
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG", quality=quality)
    return buf.getvalue()


def _smooth_image(w, h):
    # smooth gradient → JPEG-compresses nearly losslessly, so decoder
    # differences dominate the comparison, not compression artifacts
    x = np.linspace(0, 255, w, dtype=np.float32)
    y = np.linspace(0, 255, h, dtype=np.float32)[:, None]
    r = np.broadcast_to(x, (h, w))
    g = np.broadcast_to(y, (h, w))
    b = (r + g) / 2
    return np.stack([r, g, b], axis=-1).astype(np.uint8)


def test_jpeg_dims():
    data = _jpeg_bytes(_smooth_image(320, 200))
    assert native_image.jpeg_dims(data) == (320, 200)
    assert native_image.jpeg_dims(b"not a jpeg") is None


def test_decode_matches_pil_closely():
    arr = _smooth_image(400, 300)
    data = _jpeg_bytes(arr)
    box = (40, 30, 300, 240)
    native = native_image.decode_crop_resize(data, box, 224, flip=False)
    assert native is not None and native.shape == (224, 224, 3)

    with Image.open(io.BytesIO(data)) as img:
        pil = np.asarray(
            img.convert("RGB").resize(
                (224, 224), 2, box=(40, 30, 340, 270)
            ),
            dtype=np.uint8,
        )
    diff = np.abs(native.astype(int) - pil.astype(int))
    # same pixels selected; small resampler differences allowed
    assert np.mean(diff) < 3.0, np.mean(diff)
    assert np.percentile(diff, 99) <= 12


def test_decode_flip():
    arr = _smooth_image(256, 256)
    data = _jpeg_bytes(arr)
    box = (0, 0, 256, 256)
    plain = native_image.decode_crop_resize(data, box, 64, flip=False)
    flipped = native_image.decode_crop_resize(data, box, 64, flip=True)
    np.testing.assert_array_equal(plain[:, ::-1], flipped)


def test_scaled_decode_still_accurate():
    # large source, small crop target → libjpeg scale path engages
    arr = _smooth_image(1600, 1200)
    data = _jpeg_bytes(arr)
    box = ValTransform(224, 256).sample(1600, 1200)[0]
    native = native_image.decode_crop_resize(data, box, 224, flip=False)
    with Image.open(io.BytesIO(data)) as img:
        left, top, cw, ch = box
        pil = np.asarray(
            img.convert("RGB").resize(
                (224, 224), 2, box=(left, top, left + cw, top + ch)
            ),
            dtype=np.uint8,
        )
    assert np.mean(np.abs(native.astype(int) - pil.astype(int))) < 4.0


def test_dataset_native_path_and_png_fallback(tmp_path):
    arr = _smooth_image(300, 300)
    d = tmp_path / "train" / "c0"
    d.mkdir(parents=True)
    Image.fromarray(arr).save(d / "a.jpg", quality=95)
    Image.fromarray(arr).save(d / "b.png")
    ds = ImageFolderDataset(str(tmp_path / "train"), TrainTransform(64))
    rng_a = np.random.default_rng(7)
    rng_b = np.random.default_rng(7)
    img_jpg, _ = ds.get(0, rng_a)  # native path
    img_png, _ = ds.get(1, rng_b)  # PIL fallback, same rng stream → same box
    assert img_jpg.shape == img_png.shape == (64, 64, 3)
    # same sampled crop on (nearly) identical sources → near-identical output
    assert np.mean(np.abs(img_jpg.astype(int) - img_png.astype(int))) < 4.0
