"""Top-k accuracy vs reference semantics (imagenet_ddp.py:381-395)."""

import numpy as np
import pytest

from dptpu.ops.loss import cross_entropy_loss
from dptpu.ops.metrics import accuracy, topk_correct_fraction


def test_topk_exact_small_case():
    logits = np.array(
        [
            [9.0, 1.0, 0.0, 0.0],  # pred 0, label 0 → top1 hit
            [1.0, 9.0, 8.0, 0.0],  # pred 1, label 2 → top1 miss, top2 hit
            [5.0, 4.0, 3.0, 2.0],  # pred 0, label 3 → miss all top3
        ],
        dtype=np.float32,
    )
    labels = np.array([0, 2, 3])
    acc1, acc5 = accuracy(logits, labels, topk=(1, 2))
    assert float(acc1) == pytest.approx(100.0 * 1 / 3, rel=1e-6)
    assert float(acc5) == pytest.approx(100.0 * 2 / 3, rel=1e-6)


def test_topk_matches_torch_reference_impl():
    torch = __import__("torch")
    rng = np.random.RandomState(0)
    logits = rng.randn(64, 1000).astype(np.float32)
    labels = rng.randint(0, 1000, size=64)

    # reference implementation (imagenet_ddp.py:381-395), verbatim semantics
    t_out, t_tgt = torch.from_numpy(logits), torch.from_numpy(labels)
    _, pred = t_out.topk(5, 1, True, True)
    pred = pred.t()
    correct = pred.eq(t_tgt.view(1, -1).expand_as(pred))
    ref = [
        float(correct[:k].reshape(-1).float().sum(0) * (100.0 / 64)) for k in (1, 5)
    ]

    ours = [float(a) for a in accuracy(logits, labels, topk=(1, 5))]
    np.testing.assert_allclose(ours, ref, rtol=1e-6)


def test_fraction_bounds():
    rng = np.random.RandomState(1)
    logits = rng.randn(32, 10).astype(np.float32)
    labels = rng.randint(0, 10, size=32)
    f1, f5 = topk_correct_fraction(logits, labels, (1, 5))
    assert 0.0 <= float(f1) <= float(f5) <= 1.0


def test_cross_entropy_matches_torch():
    torch = __import__("torch")
    rng = np.random.RandomState(2)
    logits = rng.randn(16, 10).astype(np.float32)
    labels = rng.randint(0, 10, size=16)
    ref = float(
        torch.nn.functional.cross_entropy(
            torch.from_numpy(logits), torch.from_numpy(labels)
        )
    )
    ours = float(cross_entropy_loss(logits, labels))
    np.testing.assert_allclose(ours, ref, rtol=1e-5)
