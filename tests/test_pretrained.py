"""--pretrained: torchvision-state-dict conversion + runtime loading.

The reference builds ``models.__dict__[arch](pretrained=True)``
(imagenet_ddp.py:109-111); dptpu splits that into an offline converter and
a torch-free runtime loader (dptpu/models/pretrained.py). These tests
round-trip synthetic torch-keyed weights through the full pipeline.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dptpu.models import create_model
from dptpu.models.pretrained import (
    _to_torch,
    convert_state_dict,
    find_weights,
    load_npz,
    load_pretrained_variables,
    npz_meta,
    save_npz,
    torch_key_map,
)


def _init_vars(arch, num_classes=10, image=None):
    if image is None:
        # vgg/alexnet/squeezenet need full-size inputs (fixed-grid pools)
        image = (32 if arch.startswith(("resnet", "densenet", "mobilenet",
                                         "wide_resnet", "resnext",
                                         "shufflenet", "mnasnet",
                                         "efficientnet", "regnet",
                                         "convnext", "swin"))
                 else 224)
    model = create_model(arch, num_classes=num_classes)
    # key maps / fake state dicts / conversion templates only need SHAPES:
    # eval_shape skips materializing 100M-param inits on CPU
    v = jax.eval_shape(
        lambda rng, x: model.init(rng, x, train=False),
        jax.random.PRNGKey(0), jnp.zeros((1, image, image, 3)),
    )
    return model, {"params": v["params"],
                   "batch_stats": v.get("batch_stats", {})}


def _fake_torch_sd(arch, variables, rng):
    """Synthetic torch-keyed state dict with the right (torch) layouts."""
    sd = {}
    flat = {
        (c, tuple(p.key for p in path)): leaf
        for c in ("params", "batch_stats")
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            variables.get(c, {}))[0]
    }
    for key, (collection, names, kind) in torch_key_map(arch, variables).items():
        shape = flat[(collection, names)].shape
        if key.endswith("running_var"):
            arr = (rng.rand(*shape) + 0.5).astype(np.float32)  # positive
        else:
            # small values so eval through 18+ layers stays finite
            arr = (rng.randn(*shape) * 0.05).astype(np.float32)
        sd[key] = _to_torch(arr, kind)
    return sd


@pytest.mark.parametrize("arch", ["resnet18", "alexnet", "densenet121",
                                  "squeezenet1_0", "vgg11_bn",
                                  "resnext50_32x4d", "wide_resnet50_2",
                                  "mobilenet_v2", "shufflenet_v2_x1_0",
                                  "mnasnet1_0", "mobilenet_v3_large",
                                  "mobilenet_v3_small", "googlenet",
                                  "efficientnet_b0", "efficientnet_v2_s",
                                  "regnet_y_400mf", "regnet_x_800mf",
                                  "vit_b_32", "convnext_tiny",
                                  "swin_t", "swin_v2_t", "maxvit_t"])
def test_key_map_unique_and_torch_shaped(arch):
    _, v = _init_vars(arch)
    kmap = torch_key_map(arch, v)
    n_leaves = sum(
        len(jax.tree_util.tree_leaves(v[c])) for c in ("params", "batch_stats")
    )
    assert len(kmap) == n_leaves  # every leaf mapped, no collisions


def test_key_map_matches_known_torchvision_names():
    _, v = _init_vars("resnet50")
    keys = torch_key_map("resnet50", v)
    for k in ("conv1.weight", "bn1.running_mean", "layer1.0.downsample.0.weight",
              "layer1.0.downsample.1.weight", "layer4.2.conv3.weight",
              "fc.weight", "fc.bias"):
        assert k in keys, k
    _, v = _init_vars("densenet121")
    keys = torch_key_map("densenet121", v)
    for k in ("features.conv0.weight", "features.norm5.bias",
              "features.denseblock1.denselayer1.norm1.weight",
              "features.denseblock4.denselayer16.conv2.weight",
              "features.transition1.conv.weight", "classifier.weight"):
        assert k in keys, k
    _, v = _init_vars("squeezenet1_0", image=224)
    keys = torch_key_map("squeezenet1_0", v)
    for k in ("features.0.weight", "features.3.squeeze.weight",
              "features.12.expand3x3.bias", "classifier.1.weight"):
        assert k in keys, k
    _, v = _init_vars("alexnet", image=224)
    keys = torch_key_map("alexnet", v)
    assert "features.0.weight" in keys and "classifier.6.bias" in keys
    _, v = _init_vars("efficientnet_b0", image=32)
    keys = torch_key_map("efficientnet_b0", v)
    for k in ("features.0.0.weight",  # stem conv
              # stage 0 (no expand): dw at block.0, SE block.1, proj block.2
              "features.1.0.block.0.0.weight",
              "features.1.0.block.1.fc1.bias",
              "features.1.0.block.2.1.running_mean",
              # stage 1 (expand 6): expand block.0, dw block.1, SE block.2,
              # project block.3
              "features.2.0.block.0.0.weight",
              "features.2.1.block.3.0.weight",
              "features.8.1.weight",  # head bn
              "classifier.1.weight"):
        assert k in keys, k
    _, v = _init_vars("efficientnet_v2_s", image=32)
    keys = torch_key_map("efficientnet_v2_s", v)
    for k in ("features.1.0.block.0.0.weight",   # fused, expand 1: one conv
              "features.2.0.block.1.0.weight",   # fused, expand 4: project
              "features.4.0.block.1.1.running_var",  # MBConv dw bn
              "classifier.1.bias"):
        assert k in keys, k
    _, v = _init_vars("regnet_y_400mf", image=32)
    keys = torch_key_map("regnet_y_400mf", v)
    for k in ("stem.0.weight", "stem.1.running_mean",
              "trunk_output.block1.block1-0.proj.0.weight",
              "trunk_output.block1.block1-0.f.a.0.weight",
              "trunk_output.block1.block1-0.f.se.fc1.bias",
              "trunk_output.block4.block4-5.f.c.1.weight",
              "fc.weight"):
        assert k in keys, k
    _, v = _init_vars("vit_b_32", image=64)
    keys = torch_key_map("vit_b_32", v)
    for k in ("class_token", "conv_proj.weight", "encoder.pos_embedding",
              "encoder.layers.encoder_layer_0.ln_1.weight",
              "encoder.layers.encoder_layer_0.self_attention.in_proj_weight",
              "encoder.layers.encoder_layer_0.self_attention.in_proj_bias",
              "encoder.layers.encoder_layer_0.self_attention.out_proj.weight",
              "encoder.layers.encoder_layer_11.mlp.0.weight",
              "encoder.layers.encoder_layer_11.mlp.3.bias",
              "encoder.ln.weight", "heads.head.weight"):
        assert k in keys, k
    # the fused in_proj is a raw Parameter: no ".weight"-suffixed variant
    assert "encoder.layers.encoder_layer_0.self_attention.in_proj.weight" \
        not in keys
    _, v = _init_vars("convnext_tiny", image=32)
    keys = torch_key_map("convnext_tiny", v)
    for k in ("features.0.0.weight", "features.0.1.bias",
              "features.1.0.block.0.weight",   # dw conv
              "features.1.0.block.2.weight",   # LN
              "features.1.0.block.3.weight",   # mlp Linear 1
              "features.1.0.block.5.bias",     # mlp Linear 2
              "features.1.0.layer_scale",      # raw Parameter
              "features.2.0.weight",           # downsample LN
              "features.2.1.weight",           # downsample conv
              "features.7.2.layer_scale",
              "classifier.0.weight", "classifier.2.weight"):
        assert k in keys, k
    assert keys["features.1.0.layer_scale"][2] == "layer_scale"
    _, v = _init_vars("swin_t", image=32)
    keys = torch_key_map("swin_t", v)
    for k in ("features.0.0.weight", "features.0.2.weight",
              "features.1.0.attn.qkv.weight",
              "features.1.0.attn.relative_position_bias_table",
              "features.1.1.norm2.bias", "features.1.1.mlp.0.weight",
              "features.2.norm.weight", "features.2.reduction.weight",
              "features.7.1.attn.proj.bias", "norm.weight", "head.weight"):
        assert k in keys, k
    _, v = _init_vars("swin_v2_t", image=32)
    keys = torch_key_map("swin_v2_t", v)
    for k in ("features.1.0.attn.logit_scale",
              "features.1.0.attn.cpb_mlp.0.weight",
              "features.1.0.attn.cpb_mlp.2.weight"):
        assert k in keys, k
    # v2 swaps the table for the cpb MLP
    assert "features.1.0.attn.relative_position_bias_table" not in keys
    _, v = _init_vars("maxvit_t", image=224)
    keys = torch_key_map("maxvit_t", v)
    for k in ("stem.0.0.weight", "stem.1.0.bias",
              "blocks.0.layers.0.layers.MBconv.proj.1.weight",
              "blocks.0.layers.0.layers.MBconv.layers.pre_norm.running_var",
              "blocks.0.layers.0.layers.MBconv.layers.conv_b.0.weight",
              "blocks.0.layers.0.layers.MBconv.layers.squeeze_excitation.fc1.weight",
              "blocks.0.layers.0.layers.window_attention.attn_layer.1.relative_position_bias_table",
              "blocks.0.layers.0.layers.grid_attention.attn_layer.1.to_qkv.weight",
              "blocks.3.layers.1.layers.grid_attention.mlp_layer.3.bias",
              "classifier.2.weight", "classifier.3.bias",
              "classifier.5.weight"):
        assert k in keys, k
    assert "classifier.5.bias" not in keys  # final head has no bias


def test_convert_round_trip_resnet18():
    """torch layouts (OIHW / OI) convert back to exactly the dptpu tree."""
    rng = np.random.RandomState(0)
    model, template = _init_vars("resnet18")
    sd = _fake_torch_sd("resnet18", template, rng)
    converted = convert_state_dict("resnet18", sd, template)
    # structure identical
    assert (jax.tree_util.tree_structure(converted)
            == jax.tree_util.tree_structure(template))
    # conv kernels really were transposed, not just reshaped
    k = converted["params"]["conv1"]["kernel"]
    np.testing.assert_array_equal(
        np.transpose(sd["conv1.weight"], (2, 3, 1, 0)), k
    )
    # and the model runs with them
    out = model.apply(converted, jnp.zeros((2, 32, 32, 3)), train=False)
    assert out.shape == (2, 10) and np.isfinite(np.asarray(out)).all()


def test_convert_rejects_missing_and_mismatched():
    rng = np.random.RandomState(1)
    _, template = _init_vars("resnet18")
    sd = _fake_torch_sd("resnet18", template, rng)
    bad = dict(sd)
    bad.pop("fc.bias")
    with pytest.raises(KeyError, match="missing"):
        convert_state_dict("resnet18", bad, template)
    bad = dict(sd)
    bad["fc.weight"] = bad["fc.weight"][:, :3]
    with pytest.raises(ValueError, match="shape"):
        convert_state_dict("resnet18", bad, template)


def test_npz_round_trip_and_runtime_load(tmp_path, monkeypatch):
    rng = np.random.RandomState(2)
    model, template = _init_vars("resnet18")
    sd = _fake_torch_sd("resnet18", template, rng)
    converted = convert_state_dict("resnet18", sd, template)
    save_npz(str(tmp_path / "resnet18.npz"), converted)
    monkeypatch.setenv("DPTPU_PRETRAINED_DIR", str(tmp_path))
    assert find_weights("resnet18") == str(tmp_path / "resnet18.npz")

    loaded = load_pretrained_variables(
        "resnet18", model, input_shape=(1, 32, 32, 3)
    )
    for a, b in zip(jax.tree_util.tree_leaves(loaded),
                    jax.tree_util.tree_leaves(converted)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # wrong num_classes -> loud shape error
    model5 = create_model("resnet18", num_classes=5)
    with pytest.raises(ValueError, match="num_classes|shape"):
        load_pretrained_variables("resnet18", model5, input_shape=(1, 32, 32, 3))


def test_vit_npz_layout_marker_and_legacy_migration(tmp_path, monkeypatch):
    """Converted npz files are stamped with the head-major qkv layout;
    an UNSTAMPED ViT file (pre-round-4 conversion, [q|k|v]-major) is
    permuted on load so old conversions keep working bit-for-bit."""
    rng = np.random.RandomState(3)
    model, template = _init_vars("vit_b_32", image=64)
    sd = _fake_torch_sd("vit_b_32", template, rng)
    converted = convert_state_dict("vit_b_32", sd, template)
    new_path = str(tmp_path / "vit_b_32.npz")
    save_npz(new_path, converted)
    from dptpu.models.pretrained import QKV_LAYOUT, qkv_needs_migration

    assert npz_meta(new_path)["qkv_layout"] == QKV_LAYOUT
    assert not qkv_needs_migration("vit_b_32", QKV_LAYOUT)
    # the early-round-4 "head_major" marker covered ViT only: a swin
    # artifact carrying it is still [q|k|v]-major and MUST migrate,
    # while a vit artifact carrying it must NOT be re-permuted
    assert qkv_needs_migration("swin_t", "head_major")
    assert not qkv_needs_migration("vit_b_32", "head_major")
    assert qkv_needs_migration("swin_v2_t", None)
    assert not qkv_needs_migration("resnet50", None)

    # forge a legacy file: same values but with in_proj in [q|k|v]-major
    # order and NO marker — exactly what a round-3 converter wrote
    from dptpu.models.pretrained import _qkv_to_head_major

    heads = 12

    def to_legacy(path, leaf):
        names = tuple(p.key for p in path)
        if len(names) >= 2 and names[-2] == "in_proj":
            if names[-1] == "kernel":
                h = leaf.shape[0]
                return leaf.reshape(h, heads, 3, h // heads).transpose(
                    0, 2, 1, 3).reshape(h, 3 * h)
            h = leaf.shape[0] // 3
            return leaf.reshape(heads, 3, h // heads).transpose(
                1, 0, 2).reshape(3 * h)
        return leaf

    legacy = jax.tree_util.tree_map_with_path(to_legacy, converted)
    # round-trip sanity: migrating the forged legacy tree restores it
    migrated = _qkv_to_head_major("vit_b_32", legacy)
    np.testing.assert_array_equal(
        migrated["params"]["encoder"]["encoder_layer_0"]["self_attention"]
        ["in_proj"]["kernel"],
        converted["params"]["encoder"]["encoder_layer_0"]["self_attention"]
        ["in_proj"]["kernel"],
    )
    legacy_dir = tmp_path / "legacy"
    legacy_dir.mkdir()
    flat = {}
    for collection in ("params", "batch_stats"):
        for p, leaf in jax.tree_util.tree_flatten_with_path(
                legacy.get(collection, {}))[0]:
            flat[collection + "/" + "/".join(k.key for k in p)] = \
                np.asarray(leaf)
    np.savez(str(legacy_dir / "vit_b_32.npz"), **flat)  # no __meta__ key

    monkeypatch.setenv("DPTPU_PRETRAINED_DIR", str(legacy_dir))
    loaded = load_pretrained_variables(
        "vit_b_32", model, input_shape=(1, 64, 64, 3)
    )
    for a, b in zip(jax.tree_util.tree_leaves(loaded),
                    jax.tree_util.tree_leaves(converted)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_create_model_pretrained_gate(tmp_path, monkeypatch):
    monkeypatch.setenv("DPTPU_PRETRAINED_DIR", str(tmp_path / "nope"))
    with pytest.raises(FileNotFoundError, match="convert_torchvision"):
        create_model("resnet18", pretrained=True)
    # with the file present, construction succeeds
    rng = np.random.RandomState(3)
    model, template = _init_vars("resnet18")
    sd = _fake_torch_sd("resnet18", template, rng)
    converted = convert_state_dict("resnet18", sd, template)
    d = tmp_path / "weights"
    d.mkdir()
    save_npz(str(d / "resnet18.npz"), converted)
    monkeypatch.setenv("DPTPU_PRETRAINED_DIR", str(d))
    assert create_model("resnet18", pretrained=True) is not None


def test_converter_cli_npz_input(tmp_path, monkeypatch):
    """The CLI converter accepts a torch-keyed .npz (no torch needed)."""
    from dptpu.tools.convert_torchvision import main

    rng = np.random.RandomState(4)
    model = create_model("resnet18")  # default 1000 classes, 224 input
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)),
                   train=False)
    template = {"params": v["params"], "batch_stats": v["batch_stats"]}
    sd = _fake_torch_sd("resnet18", template, rng)
    np.savez(tmp_path / "raw.npz", **sd)
    out_dir = tmp_path / "out"
    assert main([str(tmp_path / "raw.npz"), "-a", "resnet18",
                 "-o", str(out_dir)]) == 0
    loaded = load_npz(str(out_dir / "resnet18.npz"))
    assert "conv1" in loaded["params"]


def test_aux_head_key_maps():
    """aux_logits=True trees map every aux key to torchvision's names."""
    for arch, kw, need in [
        ("googlenet", {"aux_logits": True},
         ("aux1.conv.conv.weight", "aux1.conv.bn.running_var",
          "aux1.fc1.weight", "aux2.fc2.bias")),
        ("inception_v3", {"aux_logits": True},
         ("AuxLogits.conv0.conv.weight", "AuxLogits.conv1.bn.running_mean",
          "AuxLogits.fc.weight", "Mixed_7c.branch_pool.conv.weight")),
    ]:
        model = create_model(arch, num_classes=10, **kw)
        image = 299 if arch == "inception_v3" else 64
        v = jax.eval_shape(
            lambda rng, x: model.init(rng, x, train=False),
            jax.random.PRNGKey(0), jnp.zeros((1, image, image, 3)),
        )
        variables = {"params": v["params"],
                     "batch_stats": v.get("batch_stats", {})}
        kmap = torch_key_map(arch, variables)
        n_leaves = sum(len(jax.tree_util.tree_leaves(variables[c]))
                       for c in ("params", "batch_stats"))
        assert len(kmap) == n_leaves
        for k in need:
            assert k in kmap, k


def test_dense_after_flatten_reorders_chw():
    """Linears that consume flattened conv maps: torch flattens CHW, flax
    flattens HWC — conversion must permute, not just transpose (shapes
    alone match silently). Checked functionally: torch-side matmul on the
    CHW flatten equals flax-side matmul on the HWC flatten."""
    from dptpu.models.pretrained import _from_torch

    rng = np.random.RandomState(0)
    c, h, w, o = 128, 4, 4, 3  # googlenet aux fc1 geometry
    w_torch = rng.randn(o, c * h * w).astype(np.float32)
    k_flax = _from_torch(w_torch, ("dense_chw", (c, h, w)))
    x = rng.randn(1, h, w, c).astype(np.float32)  # NHWC feature map
    y_flax = x.reshape(1, -1) @ k_flax
    x_chw = np.transpose(x, (0, 3, 1, 2)).reshape(1, -1)  # torch flatten
    y_torch = x_chw @ w_torch.T
    np.testing.assert_allclose(y_flax, y_torch, rtol=1e-5)
    # and it really is a different matrix than the naive transpose
    assert not np.allclose(k_flax, w_torch.T)


def test_alexnet_vgg_classifier_use_chw_kind():
    for arch in ("alexnet", "vgg11", "vgg16_bn"):
        _, v = _init_vars(arch)
        kmap = torch_key_map(arch, v)
        key = "classifier.1.weight" if arch == "alexnet" else "classifier.0.weight"
        kind = kmap[key][2]
        assert isinstance(kind, tuple) and kind[0] == "dense_chw", (arch, kind)
