"""Store abstraction (dptpu/data/store.py): local + HTTP range fetch,
retry/backoff, fault injection, and checkpoint-through-store round trips
(the --ckpt-dir satellite's contract: CRC footer + fallback scan,
bit-for-bit, whichever backend holds the bytes)."""

import os

import numpy as np
import pytest

from dptpu.data.store import (
    HTTPStore,
    LocalStore,
    ShardByteCache,
    StoreError,
    dev_store_server,
    is_store_url,
    open_store,
    split_store_url,
)


@pytest.fixture()
def served(tmp_path):
    root = tmp_path / "objs"
    root.mkdir()
    server, url = dev_store_server(str(root))
    yield str(root), url
    server.shutdown()


def test_open_store_dispatch(tmp_path):
    assert isinstance(open_store(str(tmp_path)), LocalStore)
    assert isinstance(open_store(f"file://{tmp_path}"), LocalStore)
    assert isinstance(open_store("http://h:1/x"), HTTPStore)
    assert is_store_url("https://h/x") and not is_store_url(str(tmp_path))
    assert split_store_url("http://h:1/a/b/c.bin") == ("http://h:1/a/b",
                                                      "c.bin")


def test_local_store_roundtrip(tmp_path):
    s = LocalStore(str(tmp_path / "sub"))
    s.put_bytes("a.bin", b"hello world")
    assert s.get_bytes("a.bin") == b"hello world"
    assert s.get_range("a.bin", 6, 5) == b"world"
    assert s.size("a.bin") == 11
    s.copy("a.bin", "b.bin")
    names = {n for n, _ in s.list()}
    assert names == {"a.bin", "b.bin"}
    s.delete("b.bin")
    assert {n for n, _ in s.list()} == {"a.bin"}
    # put is atomic-overwrite: no .tmp litter
    s.put_bytes("a.bin", b"v2")
    assert s.get_bytes("a.bin") == b"v2"
    assert not any(n.endswith(".tmp") for n, _ in s.list())
    with pytest.raises(FileNotFoundError):
        s.get_bytes("missing.bin")


def test_http_store_roundtrip_and_ranges(served):
    root, url = served
    s = HTTPStore(url)
    s.put_bytes("x/data.bin", bytes(range(200)))
    assert s.get_bytes("x/data.bin") == bytes(range(200))
    assert s.get_range("x/data.bin", 10, 5) == bytes(range(10, 15))
    assert s.size("x/data.bin") == 200
    sub = HTTPStore(f"{url}/x")
    assert {n for n, _ in sub.list()} == {"data.bin"}
    sub.delete("data.bin")
    with pytest.raises(FileNotFoundError):
        sub.get_bytes("data.bin")
    assert s.retry_count == 0  # 404/absence is an answer, never retried


def test_http_store_retries_transient_5xx(tmp_path):
    root = tmp_path / "objs"
    root.mkdir()
    (root / "a.bin").write_bytes(b"payload")
    server, url = dev_store_server(str(root), fail_first=2)
    try:
        s = HTTPStore(url, retries=4, backoff_s=0.01)
        assert s.get_bytes("a.bin") == b"payload"
        assert s.retry_count == 2  # burned exactly the two injected 503s
        assert s.wait_s > 0.0
    finally:
        server.shutdown()


def test_http_store_exhausted_retries_raise(tmp_path):
    root = tmp_path / "objs"
    root.mkdir()
    (root / "a.bin").write_bytes(b"payload")
    server, url = dev_store_server(str(root), fail_first=50)
    try:
        s = HTTPStore(url, retries=2, backoff_s=0.0)
        with pytest.raises(StoreError, match="after 3 attempt"):
            s.get_bytes("a.bin")
    finally:
        server.shutdown()


def test_fault_injected_io_error_is_retried(tmp_path, monkeypatch):
    """DPTPU_FAULT=io_error:p=F injects OSError into store ops through
    FaultPlan.on_store_io; the retry engine absorbs them — the chaos
    contract FAULTBENCH's shard scenario runs at fit() scale."""
    monkeypatch.setenv("DPTPU_FAULT", "io_error:p=0.5")
    monkeypatch.setenv("DPTPU_FAULT_SEED", "3")
    s = LocalStore(str(tmp_path), retries=50, backoff_s=0.0)
    s.put_bytes("a.bin", b"x" * 64)
    total_retries = 0
    for _ in range(20):
        assert s.get_bytes("a.bin") == b"x" * 64
    total_retries = s.retry_count
    assert total_retries > 0, "p=0.5 over 20+ ops must inject at least once"


def test_store_knob_validation(monkeypatch):
    monkeypatch.setenv("DPTPU_STORE_RETRIES", "-1")
    with pytest.raises(ValueError, match="DPTPU_STORE_RETRIES"):
        LocalStore(".")
    monkeypatch.setenv("DPTPU_STORE_RETRIES", "junk")
    with pytest.raises(ValueError, match="not an integer"):
        LocalStore(".")
    monkeypatch.delenv("DPTPU_STORE_RETRIES")
    monkeypatch.setenv("DPTPU_STORE_BACKOFF_S", "-0.5")
    with pytest.raises(ValueError, match="DPTPU_STORE_BACKOFF_S"):
        LocalStore(".")


def test_shard_byte_cache_roundtrip_odd_lengths():
    cache = ShardByteCache(1 << 20)
    try:
        for n in (1, 2, 3, 7, 1024, 12345):
            payload = bytes((i * 31) % 256 for i in range(n))
            assert cache.put(("k", n), payload)
            assert cache.get(("k", n), n) == payload
        assert cache.get(("absent", 0), 16) is None
        stats = cache.stats()
        assert stats["shard_slab_hits"] >= 6
        assert stats["shard_slab_budget_bytes"] == 1 << 20
    finally:
        cache.close()


# ---- checkpoints through the store ----------------------------------------


def _tiny_state():
    import jax
    import optax
    from flax import linen as nn

    from dptpu.train.state import create_train_state

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(4)(x.reshape((x.shape[0], -1)))

    return create_train_state(
        jax.random.PRNGKey(0), Tiny(), optax.sgd(0.1),
        input_shape=(1, 4, 4, 3),
    )


def test_checkpoint_roundtrip_via_http_store(served):
    import jax
    import numpy as np

    from dptpu.resilience import find_resumable
    from dptpu.train.checkpoint import load_checkpoint, save_checkpoint

    root, url = served
    state = _tiny_state()
    ckpt_url = f"{url}/run"
    path = save_checkpoint(
        state, epoch=3, arch="tiny", best_acc1=1.0, is_best=True,
        directory=ckpt_url, step_in_epoch=5, data_position=40,
    )
    assert path == f"{ckpt_url}/checkpoint.pth.tar"
    # the bytes on the far side carry the CRC footer: the store changed,
    # the seal did not
    raw = open(os.path.join(root, "run", "checkpoint.pth.tar"), "rb").read()
    from dptpu.train.checkpoint import CRC_MAGIC, split_payload

    _, verified = split_payload(raw)
    assert verified and CRC_MAGIC in raw[-12:]
    # is_best copied model_best alongside
    assert os.path.exists(os.path.join(root, "run", "model_best.pth.tar"))

    resolved = find_resumable(ckpt_url, verbose=False)
    assert resolved == path
    restored, meta = load_checkpoint(resolved, _tiny_state())
    assert meta["epoch"] == 3 and meta["step_in_epoch"] == 5
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(state.params)),
                    jax.tree_util.tree_leaves(
                        jax.device_get(restored.params))):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_store_resume_falls_back_past_corrupt(served):
    """The find_resumable fallback-scan contract over a store URL: the
    newest object is torn (truncated behind the server), the scan skips
    it and lands on the older verifiable save."""
    import time

    from dptpu.resilience import find_resumable, step_checkpoint_name
    from dptpu.train.checkpoint import save_checkpoint

    root, url = served
    state = _tiny_state()
    ckpt_url = f"{url}/run"
    save_checkpoint(state, epoch=0, arch="tiny", best_acc1=0.0,
                    is_best=False, directory=ckpt_url,
                    filename=step_checkpoint_name(0, 2), step_in_epoch=2)
    time.sleep(0.05)  # distinct mtimes: the scan orders by save time
    save_checkpoint(state, epoch=0, arch="tiny", best_acc1=0.0,
                    is_best=False, directory=ckpt_url,
                    filename=step_checkpoint_name(0, 4), step_in_epoch=4)
    newest = os.path.join(root, "run", step_checkpoint_name(0, 4))
    with open(newest, "r+b") as f:
        f.truncate(os.path.getsize(newest) // 2)
    resolved = find_resumable(ckpt_url, verbose=False)
    assert resolved == f"{ckpt_url}/{step_checkpoint_name(0, 2)}"
    # a direct file URL that verifies resolves to itself
    assert find_resumable(resolved, verbose=False) == resolved


def test_checkpoint_manager_rotation_over_store(served):
    from dptpu.resilience import CheckpointManager, step_checkpoint_name

    root, url = served
    state = _tiny_state()
    mgr = CheckpointManager(directory=f"{url}/run", keep=2, arch="tiny")
    for step in (1, 2, 3):
        mgr.save_step(state, epoch=0, step_in_epoch=step, sync=True)
    names = sorted(os.listdir(os.path.join(root, "run")))
    assert step_checkpoint_name(0, 1) not in names  # rotated away
    assert step_checkpoint_name(0, 2) in names
    assert step_checkpoint_name(0, 3) in names
