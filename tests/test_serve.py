"""dptpu/serve acceptance locks (ISSUE 7).

* padded-bucket LOGIT IDENTITY — a request answered via bucket 16 with
  3 real rows equals the bucket-1 answer bit-for-bit (max|Δlogit| = 0),
  across a CNN (resnet18, BatchNorm trunk) and a ViT family (vit_b_32,
  LayerNorm/attention) — the engine's batch-invariant-numerics design
  (execution floor + single-thread-Eigen compile, dptpu/serve/engine.py);
* hot-swap DRAINING — swapping weights never drops an in-flight
  request, no batch is served with mixed-generation weights, and a
  superseded generation's buffers are dropped once its last batch lands;
* ``preprocess_bytes`` BIT-IDENTITY — request preprocessing equals the
  training/eval val pipeline's pixels for the same file;
* the continuous batcher's coalescing / backpressure / bad-request
  behavior and the staging ring's lease hygiene.
"""

import io
import os
import time

import numpy as np
import pytest

import jax

from dptpu.serve import DynamicBatcher, ServeEngine, preprocess_bytes
from dptpu.serve import staging as serve_staging


def _rand_images(n, size, seed=0):
    return np.random.RandomState(seed).randint(
        0, 256, (n, size, size, 3), np.uint8
    )


def _fresh_variables(engine, seed):
    init = engine.model.init(
        jax.random.PRNGKey(seed),
        np.zeros((1, engine.image_size, engine.image_size, 3), np.float32),
        train=False,
    )
    return {"params": init["params"],
            "batch_stats": init.get("batch_stats", {})}


@pytest.fixture(scope="module")
def cnn_engine():
    # buckets 1 and 16: the ISSUE's exact parity scenario; exec sizes
    # dedup to {2, 16}
    return ServeEngine("resnet18", buckets=(1, 4, 16), num_classes=8,
                       image_size=32)


@pytest.fixture(scope="module")
def vit_engine():
    # vit_b_32 at 64px (5 tokens) — the cheap ViT; auto placement takes
    # TP on the fake 8-device pod (tp_rule vit_tp_specs)
    return ServeEngine("vit_b_32", buckets=(1, 16), num_classes=8,
                       image_size=64)


# ---------------------------------------------------------------- parity ----


@pytest.mark.parametrize("fixture", ["cnn_engine", "vit_engine"])
def test_padded_bucket_logit_identity(fixture, request):
    """Bucket 16 with 3 real rows ≡ bucket-1 answers, max|Δlogit| = 0."""
    engine = request.getfixturevalue(fixture)
    x = _rand_images(3, engine.image_size)
    solo = np.concatenate(
        [engine.infer(x[i:i + 1]) for i in range(3)]
    )  # three bucket-1 answers
    via16 = engine.infer(x)  # coalesced: bucket 16, 13 pad rows
    assert engine.bucket_for(3) in (4, 16)
    np.testing.assert_array_equal(via16, solo)  # max|Δlogit| = 0, exactly


def test_pad_content_cannot_perturb_real_rows(cnn_engine):
    """Row independence: the same 3 real rows padded with DIFFERENT
    garbage give identical logits (the padded-execution contract is not
    'pads happen to be row-0')."""
    x = _rand_images(3, 32, seed=1)
    nexec = cnn_engine.exec_batch(16)
    a = np.concatenate([x, np.zeros((nexec - 3, 32, 32, 3), np.uint8)])
    b = np.concatenate([x, _rand_images(nexec - 3, 32, seed=9)])
    np.testing.assert_array_equal(
        cnn_engine.run_bucket(16, a, 3), cnn_engine.run_bucket(16, b, 3)
    )


def test_tp_placement_matches_replicated(vit_engine):
    if vit_engine.placement != "tp":
        pytest.skip("needs the multi-device fake pod")
    rep = ServeEngine(
        "vit_b_32", buckets=(1,), num_classes=8, image_size=64,
        placement="replicated",
        variables=jax.device_get(vit_engine._weights[
            vit_engine.current_generation]),
    )
    x = _rand_images(1, 64, seed=3)
    np.testing.assert_array_equal(vit_engine.infer(x), rep.infer(x))


def test_tp_per_shard_loading_matches_gathered(vit_engine):
    """ISSUE 18 satellite lock: TP weights load per-shard from the
    rules projection (``jax.make_array_from_callback``, no full-array
    gather) and the result is value- AND layout-identical to the old
    gather-then-reshard path, with max|Δlogit| = 0 through the compiled
    forward."""
    if vit_engine.placement != "tp":
        pytest.skip("needs the multi-device fake pod")
    gen = vit_engine.current_generation
    host = jax.device_get(vit_engine._weights[gen])
    per_shard = vit_engine._place(host)
    gathered = vit_engine._place_gathered(host)
    for a, b in zip(jax.tree_util.tree_leaves(per_shard),
                    jax.tree_util.tree_leaves(gathered)):
        assert a.sharding.is_equivalent_to(b.sharding, a.ndim)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    nexec = vit_engine.exec_batch(1)
    x = np.repeat(_rand_images(1, 64, seed=7), nexec, axis=0)
    out_a = np.asarray(vit_engine._compiled[("fp32", nexec)](per_shard, x))
    out_b = np.asarray(vit_engine._compiled[("fp32", nexec)](gathered, x))
    np.testing.assert_array_equal(out_a, out_b)  # max|Δlogit| = 0


def test_bucket_ladder_aot_and_bounds(cnn_engine):
    # the ladder is compiled up front: every bucket's exec size has an
    # executable before any request arrives
    assert set(cnn_engine._compiled) == {
        ("fp32", 2), ("fp32", 4), ("fp32", 16)
    }
    assert cnn_engine.bucket_for(1) == 1
    assert cnn_engine.bucket_for(5) == 16
    with pytest.raises(ValueError, match="largest bucket"):
        cnn_engine.bucket_for(17)


# -------------------------------------------------------------- batching ----


def test_batcher_parity_and_coalescing(cnn_engine):
    x = _rand_images(8, 32, seed=2)
    solo = np.concatenate(
        [cnn_engine.infer(x[i:i + 1]) for i in range(8)]
    )
    b = DynamicBatcher(cnn_engine, max_delay_ms=5.0, slots=3)
    try:
        futs = [b.submit_array(x[i % 8]) for i in range(32)]
        for i, f in enumerate(futs):
            np.testing.assert_array_equal(
                f.result(timeout=60), solo[i % 8]
            )
        st = b.stats()
        assert st["completed"] == 32 and st["failed"] == 0
        # coalescing happened: fewer batches than requests, and some
        # batch used a multi-row bucket
        assert st["batches"] < 32
        assert any(k > 1 for k in st["bucket_counts"])
        assert 0.0 <= st["padding_waste"] < 1.0
    finally:
        b.close()


def test_batcher_zero_delay_serves_immediately(cnn_engine):
    b = DynamicBatcher(cnn_engine, max_delay_ms=0.0, slots=2)
    try:
        x = _rand_images(1, 32, seed=4)
        f = b.submit_array(x[0])
        out = f.result(timeout=60)
        np.testing.assert_array_equal(out, cnn_engine.infer(x)[0])
        assert f.timings["bucket"] == 1
    finally:
        b.close()


def test_bad_request_fails_alone_not_the_batch(cnn_engine):
    b = DynamicBatcher(cnn_engine, max_delay_ms=20.0, slots=2)
    try:
        x = _rand_images(2, 32, seed=5)
        good1 = b.submit_array(x[0])
        bad = b.submit_bytes(b"not an image")
        good2 = b.submit_array(x[1])
        with pytest.raises(ValueError, match="undecodable"):
            bad.result(timeout=60)
        solo = np.concatenate(
            [cnn_engine.infer(x[i:i + 1]) for i in range(2)]
        )
        np.testing.assert_array_equal(good1.result(timeout=60), solo[0])
        np.testing.assert_array_equal(good2.result(timeout=60), solo[1])
    finally:
        b.close()


# -------------------------------------------------------------- hot swap ----


def test_hot_swap_drains_without_mixing(cnn_engine):
    """Generation contract: a batch dispatched on gen G is served by G
    even if a swap lands mid-flight; every batch sees exactly one
    generation; the superseded generation drops once drained."""
    engine = ServeEngine("resnet18", buckets=(4,), num_classes=8,
                         image_size=32)
    x = _rand_images(4, 32, seed=6)
    g1 = engine.current_generation
    out_g1 = engine.infer(x)
    # pin g1 as an in-flight batch would, then swap under it
    pinned = engine.acquire_generation()
    assert pinned == g1
    g2 = engine.swap_weights(_fresh_variables(engine, seed=7))
    assert engine.generations() == (g1, g2)  # old gen still draining
    # the pinned batch still serves g1's weights, bit-identically
    np.testing.assert_array_equal(
        engine.run_bucket(4, x, 4, gen=pinned), out_g1
    )
    engine.release_generation(pinned)
    assert engine.generations() == (g2,)  # drained -> dropped
    out_g2 = engine.infer(x)
    assert not np.array_equal(out_g1, out_g2)  # weights really changed


def test_batcher_swap_under_load_single_generation_per_batch():
    engine = ServeEngine("resnet18", buckets=(1, 4), num_classes=8,
                         image_size=32)
    b = DynamicBatcher(engine, max_delay_ms=2.0, slots=3)
    try:
        x = _rand_images(4, 32, seed=8)
        futs = [b.submit_array(x[i % 4]) for i in range(12)]
        engine.swap_weights(_fresh_variables(engine, seed=9))
        futs += [b.submit_array(x[i % 4]) for i in range(12)]
        by_batch = {}
        for f in futs:
            f.result(timeout=60)
            by_batch.setdefault(
                f.timings["batch_index"], set()
            ).add(f.generation)
        # NO batch was served with mixed-generation weights
        assert all(len(gens) == 1 for gens in by_batch.values()), by_batch
        # both generations actually served traffic across the swap
        assert {g for gens in by_batch.values() for g in gens} == {1, 2}
        # old generation fully drained away
        assert engine.generations() == (2,)
    finally:
        b.close()


# ------------------------------------------------- request preprocessing ----


def test_preprocess_bytes_bit_identical_to_val_pipeline(tmp_path):
    """The serving preprocessing path IS the eval pipeline: same file,
    same pixels, byte for byte."""
    from PIL import Image

    from dptpu.data.dataset import ImageFolderDataset
    from dptpu.data.transforms import ValTransform

    cls = tmp_path / "cat"
    cls.mkdir()
    rng = np.random.RandomState(0)
    for i, (w, h) in enumerate([(320, 240), (240, 320), (300, 300)]):
        Image.fromarray(
            rng.randint(0, 256, (h, w, 3), np.uint8)
        ).save(cls / f"{i}.jpg", quality=90)
    ds = ImageFolderDataset(str(tmp_path), transform=ValTransform(224))
    for i in range(len(ds)):
        want, _ = ds.get(i)
        with open(ds.samples[i][0], "rb") as f:
            got = preprocess_bytes(f.read(), size=224)
        np.testing.assert_array_equal(got, want)
    # the in-place staging-row write path produces the same bytes
    out = np.empty((224, 224, 3), np.uint8)
    with open(ds.samples[0][0], "rb") as f:
        data = f.read()
    assert preprocess_bytes(data, out=out) is out
    np.testing.assert_array_equal(out, ds.get(0)[0])


def test_preprocess_matches_val_pipeline_at_non_224_sizes(tmp_path):
    """The resize edge must SCALE with the crop (fit.py's
    int(size*256/224) formula): a 64px engine crops the same fraction
    of the image the val loader would, not a 64/256 center zoom."""
    from PIL import Image

    from dptpu.data.dataset import ImageFolderDataset
    from dptpu.data.transforms import ValTransform
    from dptpu.serve.preprocess import val_resize_for

    assert val_resize_for(224) == 256  # the reference pair, unchanged
    cls = tmp_path / "dog"
    cls.mkdir()
    rng = np.random.RandomState(3)
    Image.fromarray(rng.randint(0, 256, (300, 260, 3), np.uint8)).save(
        cls / "0.jpg", quality=90
    )
    for size in (64, 160):
        ds = ImageFolderDataset(
            str(tmp_path),
            transform=ValTransform(size, int(size * 256 / 224)),
        )
        want, _ = ds.get(0)
        with open(ds.samples[0][0], "rb") as f:
            got = preprocess_bytes(f.read(), size=size)
        np.testing.assert_array_equal(got, want)


def test_bucket1_only_ladder_serves_concurrent_requests():
    """A 1-only ladder still executes at the >= 2 floor, but admission
    caps at the BUCKET (the floor rows are pad-only): two concurrent
    submits must both resolve via bucket 1, never a dead dispatcher."""
    engine = ServeEngine("resnet18", buckets=(1,), num_classes=8,
                         image_size=32)
    b = DynamicBatcher(engine, max_delay_ms=20.0, slots=3)
    try:
        x = _rand_images(2, 32, seed=11)
        futs = [b.submit_array(x[0]), b.submit_array(x[1])]
        for i, f in enumerate(futs):
            np.testing.assert_array_equal(
                f.result(timeout=60), engine.infer(x[i:i + 1])[0]
            )
            assert f.timings["bucket"] == 1
        st = b.stats()
        assert st["completed"] == 2 and st["failed"] == 0
    finally:
        b.close()


def test_preprocess_rejects_garbage():
    with pytest.raises(ValueError, match="undecodable"):
        preprocess_bytes(b"\x00\x01\x02")


# ------------------------------------------------------- staging hygiene ----


def test_staging_ring_lease_lifecycle():
    ring = serve_staging.StagingRing(2, 4, (8, 8, 3))
    try:
        s0 = ring.acquire()
        s1 = ring.acquire()
        assert ring.acquire() is None  # backpressure: ring exhausted
        lease = ring.lease(s0)
        assert ring.leased_count() == 1
        lease.release()
        lease.release()  # double release is a no-op (SlotLease contract)
        assert ring.leased_count() == 0 and ring.free_count() == 1
        ring.abandon(s1)
        assert ring.free_count() == 2
    finally:
        ring.close()


def test_staging_close_with_lease_counts_as_leak():
    before = serve_staging.leaked_lease_count()
    ring = serve_staging.StagingRing(2, 4, (8, 8, 3))
    slot = ring.acquire()
    lease = ring.lease(slot)
    name = ring._shm.name.lstrip("/")
    assert name in serve_staging.live_segment_names()
    ring.close()
    assert serve_staging.leaked_lease_count() == before + 1
    assert name not in serve_staging.live_segment_names()
    lease.release()  # late release against a closed ring: no-op
    # restore the module counter so the conftest session guard (which
    # polices REAL leaks) stays meaningful
    serve_staging._LEASE_LEAKS = before
    if os.path.isdir("/dev/shm"):
        assert not os.path.exists(f"/dev/shm/{name}")  # unlinked


# ------------------------------------------------- request lifecycle ----
# ISSUE 17: deadlines, cancellation, and dead-request hygiene — a
# cancelled/expired request must free its admission slot, stop anchoring
# the coalescing timer, and occupy ZERO bucket rows at execution.


def test_cancel_pre_dispatch_frees_rows(cnn_engine):
    from dptpu.serve import ServeCancelled

    b = DynamicBatcher(cnn_engine, max_delay_ms=400.0, slots=2)
    try:
        imgs = _rand_images(6, 32, seed=11)
        futs = [b.submit_array(imgs[i]) for i in range(6)]
        # withdraw 4 of 6 while the batch is still coalescing
        for f in futs[1:5]:
            assert f.cancel()
        for f in futs[1:5]:
            with pytest.raises(ServeCancelled):
                f.result(timeout=5)
            assert not f.cancel()  # already done: cancel() is False
        r0 = futs[0].result(timeout=30)
        r5 = futs[5].result(timeout=30)
        # dead-request hygiene: 2 live rows execute at bucket 4 (claimed
        # count 6 would have needed bucket 16)
        assert futs[0].timings["bucket"] == 4
        assert futs[5].timings["bucket"] == 4
        # the two live requests still get THEIR pixels' logits: parity
        # against a fresh batcher proves compaction moved the right rows
        b2 = DynamicBatcher(cnn_engine, max_delay_ms=0.0, slots=2)
        try:
            want0 = b2.submit_array(imgs[0]).result(timeout=30)
            want5 = b2.submit_array(imgs[5]).result(timeout=30)
        finally:
            b2.close()
        np.testing.assert_array_equal(r0, want0)
        np.testing.assert_array_equal(r5, want5)
        s = b.stats()
        assert s["cancelled"] == 4
        assert s["dead_rows"] == 4
        assert s["completed"] == 2
    finally:
        b.close()


def test_cancel_whole_batch_abandons_slot(cnn_engine):
    from dptpu.serve import ServeCancelled

    b = DynamicBatcher(cnn_engine, max_delay_ms=400.0, slots=2)
    try:
        futs = [b.submit_array(_rand_images(1, 32, seed=i)[0])
                for i in range(3)]
        for f in futs:
            f.cancel()
        for f in futs:
            with pytest.raises(ServeCancelled):
                f.result(timeout=5)
        deadline = time.perf_counter() + 10
        while time.perf_counter() < deadline:
            if b.stats(reset_window=False)["dead_rows"] == 3:
                break
            time.sleep(0.02)
        s = b.stats()
        assert s["dead_rows"] == 3 and s["batches"] == 0
        # the slot was abandoned, not leaked: a new request still serves
        out = b.submit_array(_rand_images(1, 32, seed=9)[0])
        assert out.result(timeout=30).shape == (8,)
    finally:
        b.close()


def test_deadline_evicted_while_coalescing(cnn_engine):
    from dptpu.serve import DeadlineExceeded

    b = DynamicBatcher(cnn_engine, max_delay_ms=5000.0, slots=2)
    try:
        img = _rand_images(1, 32, seed=3)[0]
        fut = b.submit_array(img,
                             deadline=time.perf_counter() + 0.05)
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=10)
        s = b.stats()
        assert s["expired"] == 1
        assert s["completed"] == 0
    finally:
        b.close(drain=False)


def test_cancel_after_dispatch_returns_false(cnn_engine):
    b = DynamicBatcher(cnn_engine, max_delay_ms=0.0, slots=2)
    try:
        fut = b.submit_array(_rand_images(1, 32, seed=4)[0])
        fut.result(timeout=30)
        assert not fut.cancel()  # device work cannot be unclaimed
    finally:
        b.close()


def test_timer_reanchors_to_oldest_live_request(cnn_engine):
    """Cancelling the OLDEST request must re-anchor the max_delay_ms
    coalescing timer onto the next-oldest LIVE request — the batch must
    NOT dispatch at the dead request's (earlier) budget expiry."""
    from dptpu.serve import ServeCancelled

    delay_ms = 700.0
    b = DynamicBatcher(cnn_engine, max_delay_ms=delay_ms, slots=2)
    try:
        old = b.submit_array(_rand_images(1, 32, seed=5)[0])
        time.sleep(0.35)  # half the budget later...
        young = b.submit_array(_rand_images(1, 32, seed=6)[0])
        t_young = time.perf_counter()
        old.cancel()
        with pytest.raises(ServeCancelled):
            old.result(timeout=5)
        young.result(timeout=30)
        served_after = time.perf_counter() - t_young
        # anchored to the dead request, the batch would have gone out
        # ~0.35 s after `young` arrived; re-anchored it waits the full
        # budget from young's t_ready
        assert served_after >= delay_ms / 1e3 - 0.05, (
            f"dispatched {served_after:.3f}s after the live request — "
            f"timer still anchored to the cancelled one"
        )
        s = b.stats()
        assert s["cancelled"] == 1 and s["completed"] == 1
    finally:
        b.close()
