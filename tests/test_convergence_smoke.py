"""Compressed-schedule convergence smoke (SURVEY.md §4 gap-fill).

The reference's acceptance test is convergence itself: 90 epochs of
step-decay (x0.1 at 30/60) to ``--desired-acc`` (imagenet_ddp.py:224-236,
README --desired-acc 0.75). A full ImageNet run is out of scope for CI, so
this compresses the *schedule* rather than replacing it: a separable
3-class fixture trained through 65 real epochs (tiny ones — 2 steps each)
descends the exact reference LR trajectory through two decay steps, and
must actually converge (train top-1 >= 95%, loss < 0.2) while the logged
LR matches lr0 * 0.1^(epoch // 30) at every epoch.
"""

import numpy as np
import pytest
from PIL import Image

from dptpu.config import Config
from dptpu.train import fit


@pytest.fixture(scope="module")
def separable_imagenet(tmp_path_factory):
    root = tmp_path_factory.mktemp("sepimg")
    rng = np.random.RandomState(0)
    for split, per_class in [("train", 16), ("val", 8)]:
        for cls in range(3):
            d = root / split / f"class{cls}"
            d.mkdir(parents=True)
            for i in range(per_class):
                base = np.full((40, 40, 3), 50 + 80 * cls, np.uint8)
                noise = rng.randint(0, 40, base.shape, dtype=np.uint8)
                Image.fromarray(base + noise).save(d / f"{i}.png")
    return str(root)


def test_step_decay_schedule_descends_and_converges(separable_imagenet,
                                                    tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    lr0 = 0.05
    cfg = Config(
        data=separable_imagenet,
        arch="resnet18",
        epochs=65,
        batch_size=48,  # one step per epoch: the schedule, not the steps, is under test
        lr=lr0,
        workers=2,
        print_freq=100,
        seed=3,
        gpu=0,  # single-device: the schedule smoke needs epochs, not a mesh
    )
    result = fit(cfg, image_size=32, verbose=False)
    hist = result["history"]
    assert len(hist) == 65

    # the exact reference trajectory: lr = lr0 * 0.1^(epoch//30)
    # (imagenet_ddp.py:374-378), read back from the logged metrics
    for h in hist:
        want = lr0 * (0.1 ** (h["epoch"] // 30))
        assert h["train_lr"] == pytest.approx(want, rel=1e-5), h["epoch"]

    # convergence through the decays: by the last stage the model must
    # have actually learned the separable data
    tail = hist[-5:]
    assert max(h["train_top1"] for h in tail) >= 95.0
    assert min(h["train_loss"] for h in tail) < 0.2
    # and the post-decay stage must not be *worse* than the first stage
    assert np.mean([h["train_loss"] for h in tail]) < np.mean(
        [h["train_loss"] for h in hist[:5]]
    )
