"""Meter parity tests (reference imagenet_ddp.py:333-371; nd_imagenet.py:361-421)."""

from dptpu.utils.meters import AverageMeter, ProgressMeter, Summary


def test_average_meter_running_stats():
    m = AverageMeter("Loss", ":.4e")
    m.update(2.0)
    m.update(4.0, n=3)
    assert m.val == 4.0
    assert m.sum == 2.0 + 12.0
    assert m.count == 4
    assert m.avg == 14.0 / 4


def test_average_meter_str_format():
    m = AverageMeter("Acc@1", ":6.2f")
    m.update(12.5)
    assert str(m) == "Acc@1  12.50 ( 12.50)"


def test_average_meter_reset():
    m = AverageMeter("Time", ":6.3f")
    m.update(1.0)
    m.reset()
    assert (m.val, m.avg, m.sum, m.count) == (0, 0, 0, 0)


def test_summary_variants():
    m = AverageMeter("Acc@5", ":6.2f", summary_type=Summary.AVERAGE)
    m.update(50.0)
    m.update(100.0)
    assert m.summary() == "Acc@5 75.000"
    m.summary_type = Summary.SUM
    assert m.summary() == "Acc@5 150.000"
    m.summary_type = Summary.COUNT
    assert m.summary() == "Acc@5 2.000"
    m.summary_type = Summary.NONE
    assert m.summary() == ""


def test_progress_meter_display(capsys):
    m = AverageMeter("Loss", ":.4e")
    m.update(0.5)
    p = ProgressMeter(100, [m], prefix="Epoch: [3]")
    p.display(7)
    out = capsys.readouterr().out
    # Reference format: "Epoch: [3][  7/100]\tLoss 5.0000e-01 (5.0000e-01)"
    assert out == "Epoch: [3][  7/100]\tLoss 5.0000e-01 (5.0000e-01)\n"


def test_progress_meter_display_summary(capsys):
    m = AverageMeter("Acc@1", ":6.2f")
    m.update(10.0)
    p = ProgressMeter(10, [m], prefix="Test: ")
    p.display_summary()
    out = capsys.readouterr().out
    assert out == " * Acc@1 10.000\n"
