"""HTTP front-end locks (ISSUE 17): liveness vs readiness, the predict
status surface (400/404/429/503/504), and the client-disconnect
hygiene fix — a peer that hangs up mid-request must get its request
CANCELLED so the staging row is compacted away and the admission ticket
releases (the conftest lease-leak guard polices the session for the
leak this test would otherwise plant).
"""

import io
import json
import socket
import threading
import time
from http.client import HTTPConnection
from http.server import ThreadingHTTPServer

import numpy as np
import pytest

from dptpu.serve import staging as serve_staging
from dptpu.serve.http import make_handler
from dptpu.serve.knobs import ServeKnobs
from dptpu.serve.router import ModelRouter, build_served_model


def _png_bytes(size=48, seed=0):
    from PIL import Image

    arr = np.random.RandomState(seed).randint(
        0, 256, (size, size, 3), np.uint8
    )
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, "PNG")
    return buf.getvalue()


def _knobs(**over):
    base = dict(
        buckets=(1, 4), max_delay_ms=0.0, placement="auto", slots=2,
        queue_depth=8, priorities=(1.0, 0.85, 0.6), deadline_ms=0.0,
        canary_fraction=0.5, canary_drift=50.0, canary_lat_factor=5.0,
    )
    base.update(over)
    return ServeKnobs(**base)


@pytest.fixture(scope="module")
def server():
    # "main" answers immediately; "slow" coalesces for seconds — long
    # enough for a disconnect to land while the request is still pending
    router = ModelRouter([
        build_served_model("main", "resnet18", _knobs(),
                           num_classes=8, image_size=32),
        build_served_model("slow", "resnet18",
                           _knobs(max_delay_ms=4000.0, queue_depth=4),
                           num_classes=8, image_size=32),
    ])
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(router))
    t = threading.Thread(target=httpd.serve_forever,
                         name="dptpu-test-httpd", daemon=True)
    t.start()
    try:
        yield httpd.server_address[1], router
    finally:
        httpd.shutdown()
        httpd.server_close()
        t.join(10)
        router.close(drain=False)


def _request(port, method, path, body=None, headers=None):
    conn = HTTPConnection("127.0.0.1", port, timeout=60)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        payload = json.loads(resp.read().decode())
        return resp.status, dict(resp.getheaders()), payload
    finally:
        conn.close()


def test_healthz_is_liveness_only(server):
    port, _ = server
    status, _, payload = _request(port, "GET", "/healthz")
    assert status == 200 and payload["ok"]
    assert set(payload["models"]) == {"main", "slow"}
    m = payload["models"]["main"]
    assert m["arch"] == "resnet18" and m["buckets"] == [1, 4]
    assert m["generation"] >= 1


def test_readyz_reflects_shedding(server):
    port, router = server
    status, _, payload = _request(port, "GET", "/readyz")
    assert status == 200 and payload["ready"]
    adm = router.models["slow"].admission
    held = [adm.try_admit("high") for _ in range(adm.thresholds["normal"])]
    try:
        status, _, payload = _request(port, "GET", "/readyz")
        assert status == 503 and not payload["ready"]
        assert payload["reasons"] == ["slow: shedding"]
        # liveness is UNAFFECTED: the process is still up
        status, _, _ = _request(port, "GET", "/healthz")
        assert status == 200
    finally:
        for t in held:
            adm.release(t)
    status, _, _ = _request(port, "GET", "/readyz")
    assert status == 200


def test_predict_default_and_named_routes(server):
    port, router = server
    body = _png_bytes(seed=1)
    status, _, payload = _request(port, "POST", "/predict", body=body)
    assert status == 200
    assert payload["model"] == "main"
    assert len(payload["top5"]) == 5
    assert payload["generation"] >= 1
    assert payload["timings"]["bucket"] in (1, 4)
    status, _, payload = _request(port, "POST", "/predict/main", body=body)
    assert status == 200 and payload["model"] == "main"
    status, _, payload = _request(port, "POST", "/predict/nope", body=body)
    assert status == 404 and "no model" in payload["error"]
    status, _, payload = _request(port, "POST", "/nope", body=body)
    assert status == 404
    status, _, payload = _request(port, "GET", "/nope")
    assert status == 404


def test_predict_rejects_bad_inputs(server):
    port, _ = server
    status, _, payload = _request(port, "POST", "/predict",
                                  body=b"not an image")
    assert status == 400
    status, _, payload = _request(port, "POST", "/predict", body=b"")
    assert status == 400 and "body" in payload["error"]
    status, _, payload = _request(
        port, "POST", "/predict", body=_png_bytes(),
        headers={"X-DPTPU-Priority": "urgent"},
    )
    assert status == 400 and "not one of" in payload["error"]
    status, _, payload = _request(
        port, "POST", "/predict", body=_png_bytes(),
        headers={"X-DPTPU-Deadline-Ms": "banana"},
    )
    assert status == 400 and "millisecond budget" in payload["error"]
    status, _, payload = _request(
        port, "POST", "/predict", body=_png_bytes(),
        headers={"X-DPTPU-Deadline-Ms": "-5"},
    )
    assert status == 400


def test_predict_sheds_with_429_and_503(server):
    port, router = server
    # 1 ms against the 50 ms service hint: infeasible, no Retry-After
    status, headers, payload = _request(
        port, "POST", "/predict", body=_png_bytes(),
        headers={"X-DPTPU-Deadline-Ms": "1"},
    )
    assert status == 429
    assert "Retry-After" not in headers
    assert "infeasible" in payload["error"]
    # saturate main's normal water mark: 503 + Retry-After
    adm = router.models["main"].admission
    held = [adm.try_admit("high") for _ in range(adm.thresholds["normal"])]
    try:
        status, headers, payload = _request(
            port, "POST", "/predict", body=_png_bytes(),
        )
        assert status == 503
        assert float(headers["Retry-After"]) >= 0.05
        assert "water mark" in payload["error"]
    finally:
        for t in held:
            adm.release(t)


def test_client_disconnect_cancels_and_releases(server):
    """The satellite-2 lock: hang up mid-request and prove the request
    is withdrawn — cancelled counter bumps, the admission ticket comes
    back, and no staging lease leaks (session guard backstops)."""
    port, router = server
    m = router.models["slow"]
    leaks_before = serve_staging.leaked_lease_count()
    cancelled_before = m.batcher.stats(reset_window=False)["cancelled"]
    body = _png_bytes(seed=2)
    raw = (
        f"POST /predict/slow HTTP/1.1\r\n"
        f"Host: 127.0.0.1:{port}\r\n"
        f"Content-Length: {len(body)}\r\n"
        f"Connection: keep-alive\r\n\r\n"
    ).encode() + body
    s = socket.create_connection(("127.0.0.1", port), timeout=10)
    try:
        s.sendall(raw)
        # let the handler read the body and submit into the batcher,
        # where the 4 s coalescing window holds the request pending
        deadline = time.perf_counter() + 10
        while m.admission.stats()["occupancy"] == 0:
            assert time.perf_counter() < deadline, "request never admitted"
            time.sleep(0.01)
    finally:
        s.close()  # the client vanishes mid-wait
    deadline = time.perf_counter() + 15
    while (m.batcher.stats(reset_window=False)["cancelled"]
           == cancelled_before):
        assert time.perf_counter() < deadline, \
            "disconnect did not cancel the pending request"
        time.sleep(0.05)
    # the done-callback returned the admission ticket...
    deadline = time.perf_counter() + 10
    while m.admission.stats()["occupancy"]:
        assert time.perf_counter() < deadline, "occupancy never released"
        time.sleep(0.01)
    # ...and the slot was abandoned, not leased-and-lost
    deadline = time.perf_counter() + 10
    while m.batcher.stats(reset_window=False)["dead_rows"] == 0:
        assert time.perf_counter() < deadline, "row never compacted away"
        time.sleep(0.05)
    assert m.batcher._ring.leased_count() == 0
    assert serve_staging.leaked_lease_count() == leaks_before
    # the server is still healthy for the NEXT client
    status, _, _ = _request(port, "POST", "/predict", body=_png_bytes())
    assert status == 200
