"""Supervised data-worker pool: crash restart, hang watchdog, transient
I/O retries, graceful degradation, atexit segment cleanup.

The contract under test (dptpu/data/shm.py + loader.py): a process-mode
loader must deliver the SAME bit-identical batches as thread mode even
while its workers are being killed, hung, or fed injected I/O errors —
failure costs restarts/retries (counted in ``feed_stats``), never wrong
pixels and never a wedged job. When the pool exhausts its restart budget
it degrades to thread mode instead of raising out of a multi-hour run.

Worker-side faults come from the ``DPTPU_FAULT`` env (inherited across
spawn), so nothing fault-related needs to cross the dataset pickle.
"""

import os

import numpy as np
import pytest

from dptpu.data import DataLoader, SyntheticDataset


def _batches_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x["images"], y["images"])
        np.testing.assert_array_equal(x["labels"], y["labels"])


class CrashAtFive:
    """Deterministic decode-error fixture — module level so spawn can
    pickle it (same pattern as tests/test_shm_loader.py)."""

    def __len__(self):
        return 12

    def get(self, index, rng=None):
        if index == 5:
            raise ValueError("decode exploded on sample 5")
        return np.full((8, 8, 3), index, np.uint8), index

    def get_into(self, index, rng, out):
        img, lab = self.get(index, rng)
        np.copyto(out, img)
        return lab

    def __getitem__(self, index):
        return self.get(index)


@pytest.fixture()
def reference_batches():
    ds = SyntheticDataset(32, 8, 10)
    th = DataLoader(ds, 4, num_workers=2, seed=3)
    try:
        yield ds, list(th.epoch(0))
    finally:
        th.close()


def test_worker_crash_restarts_and_batches_stay_bit_identical(
        reference_batches):
    ds, ref = reference_batches
    pr = DataLoader(ds, 4, num_workers=2, seed=3, workers_mode="process")
    try:
        it = pr.epoch(0)
        got = [next(it)]
        assert pr.kill_one_worker() is not None  # SIGKILL, mid-epoch
        got += list(it)
        _batches_equal(ref, got)
        fs = pr.feed_stats()
        assert fs["pool_restarts"] >= 1
        assert "degraded" not in fs  # recovered, did NOT give up
        assert pr.workers_mode == "process"
    finally:
        pr.close()


def test_worker_hang_exhausts_restarts_then_degrades_to_thread(
        reference_batches, monkeypatch, capsys):
    ds, ref = reference_batches
    # index 3 hangs DETERMINISTICALLY (every restart hangs again), so the
    # watchdog burns its whole restart budget and must then degrade
    monkeypatch.setenv("DPTPU_FAULT", "worker_hang@index=3")
    monkeypatch.setenv("DPTPU_WORKER_TIMEOUT_S", "1")
    monkeypatch.setenv("DPTPU_POOL_RESTARTS", "1")
    pr = DataLoader(ds, 4, num_workers=2, seed=3, workers_mode="process")
    try:
        got = list(pr.epoch(0))
        _batches_equal(ref, got)  # thread fallback re-decoded everything
        assert pr.workers_mode == "thread"
        fs = pr.feed_stats()
        assert fs["degraded"] is True
        assert fs["pool_restarts"] >= 1
        err = capsys.readouterr().err
        assert "degrading to thread mode" in err
    finally:
        pr.close()


def test_transient_io_errors_are_retried_not_fatal(reference_batches,
                                                   monkeypatch):
    ds, ref = reference_batches
    monkeypatch.setenv("DPTPU_FAULT", "io_error:p=0.3")
    monkeypatch.setenv("DPTPU_FAULT_SEED", "1")
    monkeypatch.setenv("DPTPU_SPAN_RETRIES", "25")
    pr = DataLoader(ds, 4, num_workers=2, seed=3, workers_mode="process")
    try:
        got = list(pr.epoch(0))
        _batches_equal(ref, got)
        fs = pr.feed_stats()
        assert fs["span_retries"] >= 1  # p=0.3 over 32 decodes must trip
        assert pr.workers_mode == "process"
    finally:
        pr.close()


def test_deterministic_decode_error_still_raises_with_traceback(
        monkeypatch):
    """A REAL application error (same sample fails every attempt) must
    surface with the worker traceback once retries are spent — retries
    cover transience, they must not bury bugs."""
    monkeypatch.setenv("DPTPU_SPAN_RETRIES", "1")
    loader = DataLoader(CrashAtFive(), 4, num_workers=2, seed=0,
                        workers_mode="process")
    try:
        with pytest.raises(RuntimeError,
                           match="decode exploded on sample 5"):
            list(loader.epoch(0))
    finally:
        loader.close()


def test_atexit_cleanup_unlinks_abandoned_segments():
    import dptpu.data.shm as shm

    ds = SyntheticDataset(16, 8, 10)
    pr = DataLoader(ds, 4, num_workers=1, seed=0, workers_mode="process")
    it = pr.epoch(0)
    next(it)  # forces pipeline + segment creation
    pipe = pr._pipeline
    seg_paths = [
        "/dev/shm/" + pipe._shm_imgs.name.lstrip("/"),
        "/dev/shm/" + pipe._shm_labels.name.lstrip("/"),
    ]
    if not all(os.path.exists(p) for p in seg_paths):
        pytest.skip("/dev/shm not exposed as a filesystem here")
    # parent "forgets" to close(); the registered atexit hook must unlink
    shm._atexit_close_all()
    assert not any(os.path.exists(p) for p in seg_paths)
    pr.close()  # double-close stays a no-op
