"""Tier-1-adjacent smoke of scripts/run_databench.py: the streaming
data plane's bit-identity gate (and the never-silently-skipped O_DIRECT
arm) are continuously checked, not just on the bench host. One
subprocess, smallest preset, same gate logic (the obsbench pattern)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_databench_smoke_gates(tmp_path):
    out = str(tmp_path / "DATABENCH.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "run_databench.py"),
         "--smoke", "--out", out],
        capture_output=True, text=True, timeout=420, env=env,
        cwd=str(tmp_path),
    )
    assert proc.returncode == 0, (
        f"databench gate failed\nstdout:\n{proc.stdout[-4000:]}\n"
        f"stderr:\n{proc.stderr[-4000:]}"
    )
    with open(out) as f:
        bench = json.load(f)
    # THE gate: streaming vs ImageFolder, max byte delta == 0
    assert bench["gates"]["bit_identity_ok"]
    assert bench["gates"]["bit_identity_max_delta"] == 0
    arms = bench["arms"]
    # every arm ran and produced a throughput number
    for arm in ("imagefolder", "shards_read", "shards_odirect",
                "shards_staged", "bounded_ram"):
        assert arms[arm]["img_per_s"] > 0, arm
    # the O_DIRECT arm is never silently skipped: either it ran with
    # O_DIRECT active, or the fallback ran AND recorded the limitation
    od = arms["shards_odirect"]
    assert od["odirect_active"] or od.get("limitation"), od
    # the remote curve covered the injected latencies
    assert len(arms["remote_latency"]) >= 2
    for point in arms["remote_latency"]:
        assert point["img_per_s"] > 0
    # host provenance is stamped (the machine-readable 2-core caveat)
    host = bench["host"]
    assert host["cpu_count"] and host["platform"] and host["jax"]
