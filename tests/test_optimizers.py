"""Large-batch recipe math (dptpu/ops/optimizers.py + the accumulated
step): LARS/LAMB trust ratios against hand-computed small cases, the
paper skip list, the zero-norm guard, label smoothing, the warmup+cosine
schedule, and gradient-accumulation identity locks.

Fast-tier by design: everything here is either pure optax math or a
TinyNet-sized jit (the test_fault_resume precedent) — the recipe's
correctness must hold in tier 1, not only in the compile-heavy tier.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from dptpu.ops.loss import cross_entropy_loss
from dptpu.ops.optimizers import (
    lamb,
    lars,
    scale_by_trust_ratio,
    trust_mask,
    trust_ratio_stats,
)
from dptpu.ops.schedules import make_warmup_cosine_schedule
from dptpu.train import create_train_state, make_optimizer, make_train_step

TC = 0.001  # LARS trust coefficient
WD = 1e-4
M = 0.9


def _params():
    # one trusted matrix, one skip-list bias — the smallest tree that
    # exercises both branches of the mask
    return {
        "w": jnp.asarray([[3.0, 0.0], [0.0, 4.0]], jnp.float32),  # ||w||=5
        "b": jnp.asarray([1.0, -2.0], jnp.float32),
    }


def _grads():
    return {
        "w": jnp.asarray([[0.6, 0.0], [0.8, 0.0]], jnp.float32),  # ||g||=1
        "b": jnp.asarray([0.5, 0.5], jnp.float32),
    }


def test_trust_mask_is_ndim_based():
    mask = trust_mask(_params())
    assert mask == {"w": True, "b": False}


def test_lars_first_step_hand_computed():
    """First LARS direction vs the paper formula computed by hand:
    d = g + wd*w; r = tc*||w||/||d||; buf = r*d (zero momentum buffer).
    The bias takes plain momentum SGD with NO decay and ratio 1."""
    params, grads = _params(), _grads()
    tx = lars(momentum=M, weight_decay=WD, trust_coefficient=TC)
    state = tx.init(params)
    direction, state = tx.update(grads, state, params)

    d = np.asarray(grads["w"]) + WD * np.asarray(params["w"])
    r = TC * 5.0 / np.linalg.norm(d)
    np.testing.assert_allclose(
        np.asarray(direction["w"]), r * d, rtol=1e-6
    )
    # skip list: bias gets NO weight decay and NO trust scaling
    np.testing.assert_allclose(
        np.asarray(direction["b"]), np.asarray(grads["b"]), rtol=1e-6
    )
    stats = trust_ratio_stats(state)
    assert stats is not None
    # one trusted layer: min == mean == max == r
    for v in stats.values():
        assert float(v) == pytest.approx(r, rel=1e-6)


def test_lars_second_step_momentum_accumulates():
    """buf2 = m*buf1 + r2*d2 — the trust ratio rescales the CURRENT
    gradient before the momentum fold (paper eq. 6 ordering), not the
    accumulated buffer."""
    params, g1 = _params(), _grads()
    g2 = {"w": jnp.asarray([[0.0, 1.0], [0.0, 0.0]], jnp.float32),
          "b": jnp.asarray([0.1, 0.1], jnp.float32)}
    tx = lars(momentum=M, weight_decay=WD, trust_coefficient=TC)
    state = tx.init(params)
    dir1, state = tx.update(g1, state, params)
    dir2, state = tx.update(g2, state, params)  # params held fixed

    d2 = np.asarray(g2["w"]) + WD * np.asarray(params["w"])
    r2 = TC * 5.0 / np.linalg.norm(d2)
    want = M * np.asarray(dir1["w"]) + r2 * d2
    np.testing.assert_allclose(np.asarray(dir2["w"]), want, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(dir2["b"]),
        M * np.asarray(g1["b"]) + np.asarray(g2["b"]),
        rtol=1e-6,
    )


def test_lamb_first_step_hand_computed():
    """First LAMB direction: bias-corrected Adam gives g/(|g|+eps)
    elementwise on step 1; decoupled decay adds wd*w (trusted only);
    the unit trust ratio rescales to ||w||/||u||."""
    params, grads = _params(), _grads()
    b1, b2, eps = 0.9, 0.999, 1e-6
    tx = lamb(b1=b1, b2=b2, eps=eps, weight_decay=WD)
    state = tx.init(params)
    direction, state = tx.update(grads, state, params)

    g = np.asarray(grads["w"])
    adam = g / (np.abs(g) + eps)  # mu_hat=g, sqrt(nu_hat)=|g| on step 1
    u = adam + WD * np.asarray(params["w"])
    r = 5.0 / np.linalg.norm(u)
    np.testing.assert_allclose(
        np.asarray(direction["w"]), r * u, rtol=1e-5
    )
    gb = np.asarray(grads["b"])
    np.testing.assert_allclose(
        np.asarray(direction["b"]), gb / (np.abs(gb) + eps), rtol=1e-5
    )
    stats = trust_ratio_stats(state)
    assert float(stats["trust_mean"]) == pytest.approx(r, rel=1e-5)


def test_trust_ratio_zero_norm_guard():
    """Fresh zero init (||w||=0) and dead gradient (||u||=0) both fall
    back to ratio exactly 1 — the update passes through unscaled instead
    of dividing by zero."""
    tx = scale_by_trust_ratio(trust_coefficient=TC)
    zero_w = {"w": jnp.zeros((2, 2), jnp.float32)}
    u = {"w": jnp.ones((2, 2), jnp.float32)}
    direction, _ = tx.update(u, tx.init(zero_w), zero_w)
    np.testing.assert_array_equal(np.asarray(direction["w"]), np.asarray(u["w"]))

    params = {"w": jnp.ones((2, 2), jnp.float32)}
    dead = {"w": jnp.zeros((2, 2), jnp.float32)}
    direction, state = tx.update(dead, tx.init(params), params)
    np.testing.assert_array_equal(
        np.asarray(direction["w"]), np.zeros((2, 2), np.float32)
    )
    assert float(trust_ratio_stats(state)["trust_mean"]) == 1.0


def test_sgd_decays_bias_but_lars_does_not():
    """The reference's torch SGD decays EVERY param (make_optimizer
    docstring); the large-batch recipes follow their papers' skip list."""
    params = _params()
    zero_g = jax.tree_util.tree_map(jnp.zeros_like, params)
    sgd = make_optimizer(M, WD, name="sgd")
    d_sgd, _ = sgd.update(zero_g, sgd.init(params), params)
    assert float(np.abs(np.asarray(d_sgd["b"])).max()) > 0  # wd*b
    tx = make_optimizer(M, WD, name="lars")
    d_lars, _ = tx.update(zero_g, tx.init(params), params)
    np.testing.assert_array_equal(
        np.asarray(d_lars["b"]), np.zeros((2,), np.float32)
    )


def test_trust_ratio_stats_absent_for_sgd():
    params = _params()
    sgd = make_optimizer(M, WD, name="sgd")
    assert trust_ratio_stats(sgd.init(params)) is None


def test_sumsq_reduce_hook_receives_local_pairs():
    """The weight-update-sharding seam: the injected reducer sees a
    params-structured tree of [sum(w^2), sum(u^2)] f32 pairs and its
    output REPLACES the local sums in the ratio — doubling every pair
    must scale each ratio by 1/sqrt(2)·sqrt(2) = 1 for w and u alike,
    so scale only u to observe the effect."""
    params, grads = _params(), _grads()
    seen = {}

    def reducer(pairs):
        seen["pairs"] = pairs
        # pretend the global ||u||^2 is 4x the local one (e.g. 4 shards
        # holding identical slices): ratio must halve
        return jax.tree_util.tree_map(
            lambda p: jnp.stack([p[0], 4.0 * p[1]]), pairs
        )

    base = scale_by_trust_ratio(trust_coefficient=TC)
    hooked = scale_by_trust_ratio(trust_coefficient=TC, sumsq_reduce=reducer)
    d0, _ = base.update(grads, base.init(params), params)
    d1, _ = hooked.update(grads, hooked.init(params), params)
    assert set(seen["pairs"].keys()) == {"w", "b"}
    assert seen["pairs"]["w"].shape == (2,)
    w2 = float(seen["pairs"]["w"][0])
    assert w2 == pytest.approx(25.0, rel=1e-6)  # sum(w^2) over the leaf
    np.testing.assert_allclose(
        np.asarray(d1["w"]), 0.5 * np.asarray(d0["w"]), rtol=1e-6
    )
    # skip-list leaves never scale, whatever the reducer reports
    np.testing.assert_array_equal(np.asarray(d1["b"]), np.asarray(d0["b"]))


def test_label_smoothing_matches_hand_math():
    logits = jnp.asarray([[2.0, 0.5, -1.0], [0.0, 1.0, 0.0]], jnp.float32)
    labels = jnp.asarray([0, 1], jnp.int32)
    s = 0.1
    logp = np.asarray(jax.nn.log_softmax(logits))
    k = logits.shape[-1]
    want = 0.0
    for i, lab in enumerate(np.asarray(labels)):
        t = np.full((k,), s / k)
        t[lab] += 1.0 - s
        want += -(t * logp[i]).sum()
    want /= len(labels)
    got = float(cross_entropy_loss(logits, labels, s))
    assert got == pytest.approx(want, rel=1e-6)
    # s=0 is the exact reference hard-target path
    assert float(cross_entropy_loss(logits, labels, 0.0)) == pytest.approx(
        float(cross_entropy_loss(logits, labels)), rel=1e-7
    )


def test_warmup_cosine_schedule_shape():
    spe, epochs, warm = 10, 10, 2
    sched = make_warmup_cosine_schedule(0.8, spe, epochs, warm)
    ws = warm * spe
    # 1-based linear warmup: first step already nonzero, peak at the
    # warmup boundary
    assert float(sched(0)) == pytest.approx(0.8 / ws)
    assert float(sched(ws - 1)) == pytest.approx(0.8)
    assert float(sched(ws)) == pytest.approx(0.8)
    # half-cosine midpoint and floor
    mid = ws + (epochs * spe - ws) // 2
    assert float(sched(mid)) == pytest.approx(0.4, rel=1e-6)
    assert float(sched(epochs * spe)) == pytest.approx(0.0, abs=1e-9)
    # monotone non-increasing after the peak
    vals = [float(sched(c)) for c in range(ws, epochs * spe + 1)]
    assert all(a >= b - 1e-9 for a, b in zip(vals, vals[1:]))


# --- gradient-accumulation identity locks (TinyNet-sized jits) ---


class _NoBN(nn.Module):
    """BN-free tiny net: with no batch statistics the accumulated step's
    microbatch forward is IDENTICAL math to the big-batch forward, so
    the lock against the single big-batch step is ulp-tight."""

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(8, (3, 3), use_bias=False)(x)
        x = nn.relu(x)
        x = x.mean(axis=(1, 2))
        return nn.Dense(10)(x)


def _nobn_state(name="sgd"):
    tx = make_optimizer(M, WD, name=name)
    return create_train_state(
        jax.random.PRNGKey(0), _NoBN(), tx, input_shape=(1, 8, 8, 3)
    )


def _batch(n=32, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "images": rng.randint(0, 256, (n, 8, 8, 3)).astype(np.uint8),
        "labels": rng.randint(0, 10, (n,)).astype(np.int32),
    }


def test_accum_one_is_bit_identical_to_default():
    """accum=1 takes the exact unaccumulated code path — bitwise equal
    params and metrics after several steps, not just allclose."""
    s_def, s_a1 = _nobn_state(), _nobn_state()
    step_def = make_train_step()
    step_a1 = make_train_step(accum_steps=1)
    for i in range(3):
        b = _batch(seed=i)
        s_def, m_def = step_def(s_def, b)
        s_a1, m_a1 = step_a1(s_a1, b)
    assert float(m_def["loss"]) == float(m_a1["loss"])
    for a, b in zip(jax.tree_util.tree_leaves(s_def.params),
                    jax.tree_util.tree_leaves(s_a1.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("opt", ["sgd", "lars"])
def test_accum_matches_big_batch_fp32(opt):
    """The fp32 accumulation lock: accum=k on a batch of k*b must match
    the single unaccumulated step on the same batch to fp32-ulp
    reordering (the only difference is partial-mean summation order;
    measured <= 6e-8 per weight after 5 steps on CPU). Runs for SGD and
    for LARS — the trust-ratio norms see the same accumulated gradient."""
    s_acc, s_big = _nobn_state(opt), _nobn_state(opt)
    step_acc = make_train_step(accum_steps=4)
    step_big = make_train_step()
    for i in range(5):
        b = _batch(32, seed=i)
        s_acc, m_acc = step_acc(s_acc, b)
        s_big, m_big = step_big(s_big, b)
    assert float(m_acc["loss"]) == pytest.approx(
        float(m_big["loss"]), rel=1e-6
    )
    for a, b in zip(jax.tree_util.tree_leaves(s_acc.params),
                    jax.tree_util.tree_leaves(s_big.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_accum_must_divide_batch():
    state = _nobn_state()
    step = make_train_step(accum_steps=5)
    with pytest.raises(ValueError, match="accum_steps=5 does not divide"):
        step(state, _batch(32))


@pytest.mark.parametrize("opt", ["lars", "lamb"])
def test_trust_optimizer_checkpoint_roundtrip(opt, tmp_path):
    """LARS/LAMB optimizer state (momentum trace / Adam moments /
    trust-ratio summary) survives the checkpoint: the restored state's
    next step is bit-identical to the uninterrupted run's."""
    from dptpu.train import load_checkpoint, save_checkpoint

    state = _nobn_state(opt)
    step = make_train_step()
    b = _batch(8)
    for _ in range(3):
        state, _ = step(state, b)
    path = save_checkpoint(
        state, epoch=1, arch="nobn", best_acc1=1.0, is_best=False,
        directory=str(tmp_path),
    )
    fresh = create_train_state(
        jax.random.PRNGKey(1), _NoBN(), make_optimizer(M, WD, name=opt),
        input_shape=(1, 8, 8, 3),
    )
    restored, _ = load_checkpoint(path, fresh)
    for a, c in zip(
        jax.tree_util.tree_leaves(jax.device_get(state.opt_state)),
        jax.tree_util.tree_leaves(restored.opt_state),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
    cont, m_cont = step(state, b)
    resumed, m_res = step(restored, b)
    assert float(m_cont["loss"]) == float(m_res["loss"])
    for a, c in zip(jax.tree_util.tree_leaves(cont.params),
                    jax.tree_util.tree_leaves(resumed.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


def test_accum_metrics_average_microbatches():
    """Reported loss under accumulation is the mean over microbatches —
    the same definition as the unaccumulated batch mean."""
    state = _nobn_state()
    _, m = make_train_step(accum_steps=4)(state, _batch(32))
    state2 = _nobn_state()
    _, m2 = make_train_step()(state2, _batch(32))
    assert float(m["loss"]) == pytest.approx(float(m2["loss"]), rel=1e-6)
    assert float(m["top1"]) == pytest.approx(float(m2["top1"]), abs=1e-4)
