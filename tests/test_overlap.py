"""Bucketed backward-overlapped gradient comms
(dptpu/parallel/overlap.py) on the fake 8-device pod.

Locks, per ISSUE 13:

* bucket partitioner units — size bound, reverse flatten order,
  tiny-leaf coalescing, single-oversized-leaf buckets, dtype grouping,
  and the 1-bucket degeneracy;
* knob fail-fast contract for DPTPU_OVERLAP / DPTPU_BUCKET_MB;
* the parity ladder — DPTPU_OVERLAP=1 is params-Δ=0 against the
  unbucketed step at ANY bucket count (the regrouping contract), for
  DDP, ZeRO-1, --accum-steps and the --slices hierarchical mesh (fp32
  AND bf16-DCN), with multi-bucket ≡ single-bucket at Δ=0;
* HLO structure — the bucketed program's total collective bytes equal
  the unbucketed program's (pure regrouping), donation aliasing stays
  intact, and the compiled schedule interleaves >= 2 per-bucket
  reductions with backward compute (overlap_evidence — the same
  numbers `dptpu check` gates);
* overlap_evidence parser units on synthetic scheduled HLO, including
  the async start/done form this CPU backend never emits;
* distributed evaluation (DPTPU_DIST_EVAL): the sharded val pass's
  psum'd correct/count sums aggregate to the single-stream pass's
  numbers bit-identically.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from flax import linen as nn

from dptpu.parallel import (
    gather_state,
    make_hierarchical_mesh,
    make_mesh,
    make_zero1_train_step,
    replicated_sharding,
    shard_host_batch,
    shard_zero1_state,
)
from dptpu.parallel.hlo_accounting import (
    collective_bytes_per_chip,
    donated_alias_count,
    overlap_evidence,
)
from dptpu.parallel.overlap import (
    DEFAULT_BUCKET_MB,
    bucket_sizes_bytes,
    overlap_knobs,
    partition_buckets,
)
from dptpu.train import create_train_state, make_optimizer, make_train_step
from dptpu.train.step import make_eval_step


class TinyDense(nn.Module):
    """The test_hierarchy probe: channel dims divide 2/4/8 so leaves
    scatter at every geometry; BN exercises the replicated pmean."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(16, (3, 3), use_bias=False)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
        x = nn.relu(x)
        x = x.mean(axis=(1, 2))
        x = nn.Dense(32)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes)(x)


def _state():
    tx = make_optimizer(momentum=0.9, weight_decay=1e-4)
    return create_train_state(
        jax.random.PRNGKey(0), TinyDense(), tx, input_shape=(1, 8, 8, 3)
    )


def _batch(n=16, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "images": rng.randint(0, 256, (n, 8, 8, 3)).astype(np.uint8),
        "labels": rng.randint(0, 10, (n,)).astype(np.int32),
    }


def _replicate(state, mesh):
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, replicated_sharding(mesh)), state
    )


def _run(mesh, steps=5, zero1=False, **kw):
    st = _state()
    if zero1:
        step = make_zero1_train_step(mesh, st, **kw)
        st = shard_zero1_state(st, mesh)
    else:
        step = make_train_step(mesh, **kw)
        st = _replicate(st, mesh)
    for i in range(steps):
        st, m = step(st, shard_host_batch(_batch(16, seed=i), mesh))
    if zero1:
        st = gather_state(st, mesh)
    return jax.device_get(st.params), m


def _max_delta(a, b):
    return max(
        float(np.abs(np.asarray(x) - np.asarray(y)).max())
        for x, y in zip(jax.tree_util.tree_leaves(a),
                        jax.tree_util.tree_leaves(b))
    )


def _flat_mesh(n=4):
    return make_mesh(jax.devices()[:n], {"data": n})


def _hier_mesh(s=2, i=2):
    return make_hierarchical_mesh(s, jax.devices()[:s * i])


# ----------------------------------------------------------- partitioner


def test_partition_respects_size_bound():
    tree = {"a": np.zeros(100, np.float32), "b": np.zeros(100, np.float32),
            "c": np.zeros(100, np.float32)}
    buckets = partition_buckets(tree, 400)  # 2 leaves of 400B fit, 3 don't
    sizes = bucket_sizes_bytes(tree, buckets)
    assert all(s <= 400 for s in sizes)
    assert len(buckets) == 3  # 400B leaves: one each


def test_partition_reverse_flatten_order():
    tree = {"a": np.zeros(4, np.float32), "b": np.zeros(4, np.float32),
            "c": np.zeros(4, np.float32)}
    [bucket] = partition_buckets(tree, 10**9)
    # one bucket holding every leaf, walked in REVERSE flatten order
    assert bucket == [2, 1, 0]


def test_partition_tiny_leaves_coalesce():
    tree = [np.zeros(2, np.float32) for _ in range(10)]  # 8 B each
    buckets = partition_buckets(tree, 64)
    assert len(buckets) == 2  # 10 x 8B pack 8-per-64B bucket
    assert [len(b) for b in buckets] == [8, 2]


def test_partition_oversized_leaf_gets_own_bucket():
    tree = [np.zeros(2, np.float32), np.zeros(1000, np.float32),
            np.zeros(2, np.float32)]
    buckets = partition_buckets(tree, 64)
    assert [sorted(b) for b in buckets] == [[2], [1], [0]]


def test_partition_never_mixes_dtypes():
    tree = [np.zeros(4, np.float32), np.zeros(4, np.int32),
            np.zeros(4, np.float32)]
    buckets = partition_buckets(tree, 10**9)
    leaves = tree
    for b in buckets:
        assert len({leaves[i].dtype for i in b}) == 1
    assert len(buckets) == 3  # f32 / s32 / f32 in reverse order


def test_partition_single_bucket_degeneracy():
    params = _state().params
    buckets = partition_buckets(params, 10**9)
    n = len(jax.tree_util.tree_leaves(params))
    assert len(buckets) == 1 and sorted(buckets[0]) == list(range(n))


def test_partition_is_deterministic():
    params = _state().params
    assert partition_buckets(params, 2048) == partition_buckets(
        params, 2048
    )


def test_partition_invalid_bound_raises():
    with pytest.raises(ValueError, match="bucket_bytes"):
        partition_buckets([np.zeros(4, np.float32)], 0)


# ----------------------------------------------------------------- knobs


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for k in ("DPTPU_OVERLAP", "DPTPU_BUCKET_MB"):
        monkeypatch.delenv(k, raising=False)


def test_knob_defaults():
    assert overlap_knobs() == (False, int(DEFAULT_BUCKET_MB * 1e6), False)


def test_knob_reads(monkeypatch):
    monkeypatch.setenv("DPTPU_OVERLAP", "1")
    monkeypatch.setenv("DPTPU_BUCKET_MB", "0.5")
    assert overlap_knobs() == (True, 500000, True)


@pytest.mark.parametrize("bad", ["0", "-3", "junk"])
def test_bucket_mb_invalid_raises(monkeypatch, bad):
    monkeypatch.setenv("DPTPU_BUCKET_MB", bad)
    with pytest.raises(ValueError, match="DPTPU_BUCKET_MB"):
        overlap_knobs()


def test_overlap_junk_raises(monkeypatch):
    monkeypatch.setenv("DPTPU_OVERLAP", "flase")
    with pytest.raises(ValueError, match="DPTPU_OVERLAP"):
        overlap_knobs()


# ---------------------------------------------------------- parity ladder


def test_ddp_overlap_single_bucket_bit_identical():
    mesh = _flat_mesh()
    base, _ = _run(mesh)
    over, _ = _run(mesh, overlap=True, bucket_bytes=10**9)
    assert _max_delta(base, over) == 0.0


def test_ddp_overlap_multi_bucket_bit_identical():
    mesh = _flat_mesh()
    base, _ = _run(mesh)
    multi, _ = _run(mesh, overlap=True, bucket_bytes=2048)
    assert _max_delta(base, multi) == 0.0


def test_overlap_accum_bit_identical():
    mesh = _flat_mesh()
    base, _ = _run(mesh, accum_steps=2)
    over, _ = _run(mesh, accum_steps=2, overlap=True, bucket_bytes=2048)
    assert _max_delta(base, over) == 0.0


def test_zero1_overlap_bit_identical():
    mesh = _flat_mesh()
    base, _ = _run(mesh, zero1=True)
    over, _ = _run(mesh, zero1=True, overlap=True, bucket_bytes=2048)
    assert _max_delta(base, over) == 0.0


def test_zero1_overlap_accum_bit_identical():
    mesh = _flat_mesh()
    base, _ = _run(mesh, zero1=True, accum_steps=2)
    over, _ = _run(mesh, zero1=True, accum_steps=2, overlap=True,
                   bucket_bytes=2048)
    assert _max_delta(base, over) == 0.0


def test_hier_overlap_bit_identical():
    mesh = _hier_mesh()
    base, _ = _run(mesh)
    over, _ = _run(mesh, overlap=True, bucket_bytes=2048)
    assert _max_delta(base, over) == 0.0


def test_hier_overlap_bf16_bit_identical():
    mesh = _hier_mesh()
    base, _ = _run(mesh, dcn_dtype="bf16")
    over, _ = _run(mesh, dcn_dtype="bf16", overlap=True,
                   bucket_bytes=2048)
    assert _max_delta(base, over) == 0.0


def test_hier_zero1_overlap_bit_identical():
    mesh = _hier_mesh()
    base, _ = _run(mesh, zero1=True)
    over, _ = _run(mesh, zero1=True, overlap=True, bucket_bytes=2048)
    assert _max_delta(base, over) == 0.0


def test_overlap_metrics_match_unbucketed():
    mesh = _flat_mesh()
    _, m_base = _run(mesh, steps=1)
    _, m_over = _run(mesh, steps=1, overlap=True, bucket_bytes=2048)
    for k in ("loss", "top1", "top5"):
        np.testing.assert_array_equal(
            np.asarray(m_base[k]), np.asarray(m_over[k])
        )


# ------------------------------------------------------- HLO structure


def _compiled_text(mesh, **kw):
    st = _replicate(_state(), mesh)
    step = make_train_step(mesh, **kw)
    return step.lower(st, shard_host_batch(_batch(), mesh)).compile(
    ).as_text()


def test_overlap_total_bytes_and_donation_unchanged():
    mesh = _flat_mesh()
    base = _compiled_text(mesh)
    over = _compiled_text(mesh, overlap=True, bucket_bytes=2048)
    b = collective_bytes_per_chip(base, 4)
    o = collective_bytes_per_chip(over, 4)
    # pure regrouping: identical total reduction bytes, fewer or equal
    # instructions (leaves fuse into buckets)
    assert o["total"] == b["total"]
    assert o["instructions"] <= b["instructions"]
    assert donated_alias_count(over) == donated_alias_count(base)


def test_overlap_schedule_shows_interleaved_buckets():
    mesh = _flat_mesh()
    ev = overlap_evidence(
        _compiled_text(mesh, overlap=True, bucket_bytes=2048)
    )
    assert ev["reductions"] >= 2
    assert ev["interleaved_gaps"] >= 1
    assert not ev["contiguous_tail_block"]


# ------------------------------------------------- evidence parser units


_SYNTH_SYNC = """\
HloModule m, is_scheduled=true

ENTRY %main (p0: f32[64]) -> f32[64] {
  %p0 = f32[64]{0} parameter(0)
  %f1 = f32[256]{0} fusion(f32[64]{0} %p0), kind=kLoop, calls=%fc.1
  %ar1 = f32[256]{0} all-reduce(f32[256]{0} %f1), replica_groups={{0,1}}, to_apply=%add
  %f2 = f32[256]{0} fusion(f32[256]{0} %ar1), kind=kLoop, calls=%fc.2
  %ar2 = f32[256]{0} all-reduce(f32[256]{0} %f2), replica_groups={{0,1}}, to_apply=%add
  %tiny = f32[] all-reduce(f32[] %p0), replica_groups={{0,1}}, to_apply=%add
  ROOT %out = f32[64]{0} fusion(f32[256]{0} %ar2), kind=kLoop, calls=%fc.3
}
"""

_SYNTH_TAIL = """\
HloModule m, is_scheduled=true

ENTRY %main (p0: f32[64]) -> f32[64] {
  %p0 = f32[64]{0} parameter(0)
  %f1 = f32[256]{0} fusion(f32[64]{0} %p0), kind=kLoop, calls=%fc.1
  %ar1 = f32[256]{0} all-reduce(f32[256]{0} %f1), replica_groups={{0,1}}, to_apply=%add
  %ar2 = f32[256]{0} all-reduce(f32[256]{0} %f1), replica_groups={{0,1}}, to_apply=%add
  ROOT %out = f32[64]{0} fusion(f32[256]{0} %ar2), kind=kLoop, calls=%fc.3
}
"""

_SYNTH_ASYNC = """\
HloModule m, is_scheduled=true

ENTRY %main (p0: f32[64]) -> f32[64] {
  %p0 = f32[64]{0} parameter(0)
  %f1 = f32[256]{0} fusion(f32[64]{0} %p0), kind=kLoop, calls=%fc.1
  %ars = (f32[256]{0}, f32[256]{0}) all-reduce-start(f32[256]{0} %f1), replica_groups={{0,1}}, to_apply=%add
  %f2 = f32[128]{0} fusion(f32[64]{0} %p0), kind=kLoop, calls=%fc.2
  %f3 = f32[128]{0} fusion(f32[128]{0} %f2), kind=kLoop, calls=%fc.3
  %ard = f32[256]{0} all-reduce-done((f32[256]{0}, f32[256]{0}) %ars)
  ROOT %out = f32[64]{0} fusion(f32[256]{0} %ard), kind=kLoop, calls=%fc.4
}
"""


def test_evidence_sync_interleaved():
    ev = overlap_evidence(_SYNTH_SYNC)
    assert ev["reductions"] == 2  # the f32[] psum falls below min_bytes
    assert ev["interleaved_gaps"] == 1
    assert ev["compute_between"] == 1
    assert not ev["contiguous_tail_block"]


def test_evidence_contiguous_tail_detected():
    ev = overlap_evidence(_SYNTH_TAIL)
    assert ev["reductions"] == 2
    assert ev["interleaved_gaps"] == 0
    assert ev["contiguous_tail_block"]


def test_evidence_async_pairs():
    ev = overlap_evidence(_SYNTH_ASYNC)
    assert ev["reductions"] == 1  # the -start counts once
    assert ev["async_pairs"] == 1
    # two fusions scheduled inside the start..done window
    assert ev["async_compute_between"] == 2


def test_evidence_min_bytes_filter():
    ev = overlap_evidence(_SYNTH_SYNC, min_bytes=10**6)
    assert ev["reductions"] == 0


# ------------------------------------------------- distributed evaluation


def test_dist_eval_sharded_sums_bit_identical():
    """The DPTPU_DIST_EVAL contract: splitting the val set into host
    shards and summing the per-shard psum'd correct/count sums equals
    the single-stream pass EXACTLY — the eval step emits integer-valued
    f32 sums, so the aggregation is associative bit-for-bit."""
    from dptpu.data import ShardedSampler

    st = _state()
    eval_step = make_eval_step(None)
    images = np.random.RandomState(0).randint(
        0, 256, (48, 8, 8, 3)).astype(np.uint8)
    labels = np.random.RandomState(1).randint(0, 10, (48,)).astype(
        np.int32)

    def sums(idxs):
        out = {"loss_sum": 0.0, "correct1": 0.0, "correct5": 0.0,
               "count": 0.0}
        for lo in range(0, len(idxs), 16):
            sel = idxs[lo:lo + 16]
            s = jax.device_get(eval_step(st, {
                "images": images[sel], "labels": labels[sel]
            }))
            for k in out:
                out[k] += float(s[k])
        return out

    full = sums(np.arange(48))
    shards = [
        ShardedSampler(48, num_shards=2, shard_index=i,
                       shuffle=False).indices(0)
        for i in range(2)
    ]
    # the two shards partition the full set (no wrap padding at 48/2)
    assert sorted(np.concatenate(shards).tolist()) == list(range(48))
    merged = {k: 0.0 for k in full}
    for sh in shards:
        part = sums(sh)
        for k in merged:
            merged[k] += part[k]
    assert merged["correct1"] == full["correct1"]
    assert merged["correct5"] == full["correct5"]
    assert merged["count"] == full["count"]
    assert abs(merged["loss_sum"] - full["loss_sum"]) <= 1e-4 * max(
        abs(full["loss_sum"]), 1.0
    )
