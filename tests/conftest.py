"""Test harness: fake TPU pod on CPU.

Multi-chip hardware is not available in CI, so every test runs on a virtual
8-device CPU mesh — the standard JAX fake-cluster trick (SURVEY.md §4): the
CPU platform is forced and split into 8 devices BEFORE jax initializes.
This stands in for a single-host TPU slice; the driver separately dry-runs
the multi-chip path via __graft_entry__.dryrun_multichip.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected 8 fake devices, got {len(devices)}"
    return devices[:8]
