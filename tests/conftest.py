"""Test harness: fake TPU pod on CPU.

Multi-chip hardware is not available in CI, so every test runs on a virtual
8-device CPU mesh — the standard JAX fake-cluster trick (SURVEY.md §4). The
CPU platform must be forced via ``jax.config.update``, not env vars: this
image's sitecustomize imports jax at interpreter startup (to register the
axon TPU plugin), so ``JAX_PLATFORMS`` is already latched by the time test
code runs. ``XLA_FLAGS`` is still honored because the CPU PJRT client is
created lazily, at the first backend use — which is after this conftest.
The driver separately dry-runs the real multi-chip path via
``__graft_entry__.dryrun_multichip``.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Arm the lock-order sanitizer for the WHOLE suite (ISSUE 14): every
# OrderedLock built during tests records per-thread acquisition stacks
# and asserts the declared LOCK_RANKS order, so tier-1 exercises the
# real lock orders under load — an inverted acquisition fails the test
# that performed it, with both stacks in the message. Must be set
# BEFORE any dptpu module constructs a lock (the knob is read at lock
# construction, which is what keeps the disabled mode zero-cost).
os.environ.setdefault("DPTPU_SYNC_CHECK", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# Two-tier suite: `-m fast` is the quick all-unit check (~1-2 min on one
# CPU, at most one tiny-model compile); everything else is the
# compile-heavy `slow` tier. Modules are the marking unit — a whole file
# is fast only if none of its tests build/compile a zoo model or run
# fit(). Deliberate exception: test_fault_resume (ONE resnet18@32 compile,
# reused by every run in the module) — the resilience acceptance bar
# "SIGTERM'd run resumes bit-identically" must hold in tier 1, and it can
# only be asserted through fit().
_FAST_MODULES = {
    "test_bench_logic", "test_config", "test_schedules", "test_metrics",
    "test_meters", "test_data", "test_tensorboard", "test_native",
    "test_cache", "test_shm_loader", "test_feed_knobs", "test_tv_template",
    "test_resilience", "test_shm_supervision", "test_fault_resume",
    # observability tier (PR 5): obs unit tests are pure-fast; the
    # obsbench smoke is the second deliberate fit()-driven exception —
    # the overhead/coverage/trigger gates must hold in tier 1, and they
    # can only be asserted through fit() (one subprocess, tiny preset)
    "test_obs", "test_obs_knobs", "test_profiling", "test_obsbench_smoke",
    # large-batch engine (PR 6): knob validation is pure; the recipe-math
    # module is pure optax math plus TinyNet-sized jits (the
    # test_fault_resume precedent) — the accumulation/trust-ratio locks
    # must hold in tier 1
    "test_opt_knobs", "test_optimizers",
    # serving (PR 7): knob validation is pure; test_serve compiles only
    # tiny-model bucket ladders (resnet18@32 / vit_b_32@64 — the
    # test_fault_resume precedent) and holds the ISSUE acceptance bar —
    # padded-bucket logit identity and hot-swap draining MUST hold in
    # tier 1; the servebench smoke is the third fit-shaped exception
    # (one subprocess, --smoke preset, same gates as SERVEBENCH.json)
    "test_serve", "test_serve_knobs", "test_servebench_smoke",
    # streaming data plane (PR 8): store/shard units are pure-fast;
    # test_shards holds the bit-identity + resume-on-shards acceptance
    # bars (ONE resnet18@48 compile, the test_fault_resume precedent);
    # the databench smoke is the fourth fit-shaped exception (one
    # subprocess, --smoke preset, same gates as DATABENCH.json)
    "test_shards", "test_store", "test_databench_smoke",
    # hierarchical comms (PR 10): knob/parser units are pure; the
    # parity + HLO locks compile only TinyDense-sized shard_map steps
    # (the test_optimizers precedent) and hold the ISSUE acceptance
    # bars — pure-hop Δ=0 parity and per-axis byte counts MUST hold in
    # tier 1; the commbench smoke is the fifth fit-shaped exception
    # (one subprocess, --smoke preset, same gates as COMMBENCH.json)
    "test_hierarchy", "test_commbench_smoke",
    # elastic pod lifecycle (PR 11): remap/quorum/straggler units are
    # pure-fast (one pre-compile fail-fast fit); the faultbench smoke
    # is the sixth fit-shaped exception — the shrink-resume, quorum and
    # straggler chaos gates MUST hold in tier 1 (one subprocess,
    # --smoke preset, same gates as FAULTBENCH.json)
    "test_elastic", "test_faultbench_smoke",
    # static analysis (PR 12): the lint units are pure stdlib; the
    # repo gate compiles only the four TinyDense-sized budget configs
    # (the test_hierarchy precedent, cached module-wide) — the
    # zero-findings + HLO-budget acceptance bars MUST hold in tier 1
    "test_analysis", "test_analysis_repo",
    # concurrency analyzer (ISSUE 14): the three lint rules are pure
    # stdlib; the runtime OrderedLock/StopToken/heartbeat units are
    # sub-second thread exercises — the ABBA and unguarded-shared-write
    # acceptance bars MUST hold in tier 1
    "test_concurrency",
    # unified partition rules (ISSUE 16): the matcher/projection units
    # and the dptpu-check partition-rules gate are eval_shape-only (no
    # weights allocated, no step compiles) — the one-table-many-views
    # equivalence locks MUST hold in tier 1
    "test_rules",
    # overlapped gradient comms (ISSUE 13): partitioner/evidence units
    # are pure; the parity ladder compiles TinyDense-sized shard_map
    # steps (the test_hierarchy precedent) and holds the acceptance
    # bars — overlap Δ=0 for DDP/ZeRO-1/slices MUST hold in tier 1;
    # the racebench smoke is the seventh fit-shaped exception (one
    # subprocess, --smoke preset, same gates as RACEBENCH.json)
    "test_overlap", "test_racebench_smoke",
    # robust serving tier (ISSUE 17): admission/canary/router units and
    # the HTTP surface reuse the tiny resnet18@32 ladder (the test_serve
    # precedent) — the shed/rollback/disconnect-hygiene acceptance bars
    # MUST hold in tier 1
    "test_serve_admission", "test_serve_http",
    # quantized serving + fleet (ISSUE 18): quant/calibration units and
    # the canary top-1 gate reuse the tiny resnet18@32 ladder (the
    # test_serve precedent; the CLI end-to-end is opted out per-test);
    # the fleet tier is pure stdlib threads + loopback HTTP — the
    # zero-failed-failover acceptance bar MUST hold in tier 1
    "test_serve_quant", "test_fleet",
    # self-tuning control plane (ISSUE 19): artifact/controller/search
    # units are pure; the precedence locks ride tiny resnet18@32 fits
    # (the test_fault_resume precedent) and the cost-model extraction
    # lock is analytic — explicit-knobs-win and bounded-actuation bars
    # MUST hold in tier 1; the tunebench smoke is the eighth fit-shaped
    # exception (one subprocess, --smoke preset, same gates as
    # TUNEBENCH.json)
    "test_tune", "test_tune_costmodel", "test_tunebench_smoke",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        name = item.module.__name__.rsplit(".", 1)[-1] if item.module else ""
        item.add_marker(
            pytest.mark.fast if name in _FAST_MODULES else pytest.mark.slow
        )


@pytest.fixture(scope="session", autouse=True)
def dptpu_shm_leak_guard():
    """CI gate on /dev/shm hygiene: every dptpu segment (batch-slot ring
    ``dptpu_ring_*``, pooled decode-cache slab ``dptpu_cache_*``) that
    appears during the suite must be gone — or still owned by a live,
    registered object whose atexit hook will unlink it — by session end.
    A segment that is neither was abandoned without ``close()`` and
    would leak host RAM until reboot in production.

    Also policed: LEASES — the feed ring's AND the serve staging ring's
    (``dptpu_serve_*``, dptpu/serve/staging.py — same SlotLease
    protocol). A slot still leased when its pipeline/ring closed was
    neither released by the consumer nor revoked by an epoch reset /
    loader-initiated rebuild — a zero-copy protocol bug that would pin
    (and, worse, silently recycle under) live batch views in
    production. The ``leaked_lease_count()``s only advance on
    close-with-lease-outstanding, so abandoned epochs whose leases the
    generator backstop or a reset reclaimed stay clean.

    And the chief collector's merged-timeline temp files
    (dptpu/obs/report.py ``merge_pod_timeline``): every merge must
    either finish its atomic rename or unlink its temp — a temp still
    tracked at session end was abandoned mid-write."""
    import glob

    from dptpu.data import shm as _shm
    from dptpu.data import stream as _stream
    from dptpu.obs import report as _obs_report
    from dptpu.serve import staging as _serve_staging

    def lease_leaks():
        return (_shm.leaked_lease_count()
                + _serve_staging.leaked_lease_count())

    leases_before = lease_leaks()
    merge_tmps_before = _obs_report.live_merge_tmp_count()
    # shard-file descriptors (the O_DIRECT/pread byte ring,
    # dptpu/data/stream.py): every reader a test opens must be closed
    # (dataset.close() or GC) by session end, or the suite fails
    fds_before = _stream.open_fd_count()
    if not os.path.isdir("/dev/shm"):
        yield  # platform without a tmpfs view; segments can't be policed
        import gc

        gc.collect()
        assert lease_leaks() == leases_before, (
            "slots were still leased when their pipeline/ring closed "
            "(consumer never released, no reset revoked) — a zero-copy "
            "lease leak"
        )
        assert _stream.open_fd_count() <= fds_before, (
            "shard-file descriptors leaked past dataset close()"
        )
        return
    # segment names embed their CREATOR pid (dptpu_{kind}_{pid}_{hex});
    # only this process creates segments for this suite (workers merely
    # attach), so scoping to our pid keeps concurrent dptpu runs on the
    # same host from tripping the guard
    mine = (f"/dev/shm/dptpu_ring_{os.getpid()}_*",
            f"/dev/shm/dptpu_cache_{os.getpid()}_*",
            f"/dev/shm/dptpu_serve_{os.getpid()}_*",
            f"/dev/shm/dptpu_shard_{os.getpid()}_*")
    snapshot = lambda: {p for pat in mine for p in glob.glob(pat)}  # noqa: E731
    before = snapshot()
    yield
    import gc

    gc.collect()  # run __del__ for dropped loaders/datasets first
    from dptpu.data import shm_cache as _shm_cache

    live = {
        "/dev/shm/" + n.lstrip("/")
        for n in (_shm.live_segment_names()
                  | _shm_cache.live_segment_names()
                  | _serve_staging.live_segment_names())
    }
    leaked = snapshot() - before - live
    assert not leaked, (
        f"leaked /dev/shm segments (created during the suite, not "
        f"closed, not owned by any live pipeline/cache/staging ring): "
        f"{sorted(leaked)}"
    )
    assert lease_leaks() == leases_before, (
        "slots were still leased when their pipeline/ring closed "
        "(consumer never released, no reset revoked) — a zero-copy "
        "lease leak"
    )
    assert _stream.open_fd_count() <= fds_before, (
        "shard-file descriptors leaked: a ShardFileReader opened during "
        "the suite was never closed (dataset.close() missing?)"
    )
    assert _obs_report.live_merge_tmp_count() == merge_tmps_before, (
        "pod-timeline merge temp files leaked: a merge_pod_timeline "
        "call neither completed its atomic rename nor unlinked its temp"
    )


@pytest.fixture(scope="session", autouse=True)
def dptpu_thread_census():
    """CI gate on thread hygiene (the shm-segment/fd/lease censuses'
    sibling, ISSUE 14): every ``dptpu``-named thread started during the
    suite must be stopped by session end. A leaked NON-daemon thread
    blocks interpreter exit in production; a leaked daemon thread —
    and all of dptpu's service threads are daemon by design — keeps
    touching shared state (posting heartbeats for a dead host,
    dispatching against a closed ring) long after its owner died, so
    daemons are policed too, with a short join grace for pools mid-
    ``shutdown(wait=False)``. The census names the thread and its
    target so the leak is attributable; the static half (``dptpu
    check``'s thread-hygiene rule) enforces the dptpu- name prefix it
    keys on."""
    import threading

    def census():
        return [
            t for t in threading.enumerate()
            if t is not threading.main_thread() and t.is_alive()
            and t.name.startswith("dptpu")
        ]

    before = {id(t) for t in census()}
    yield
    import gc

    gc.collect()  # run __del__ teardown for dropped owners first
    leaked = []
    for t in census():
        if id(t) in before:
            continue
        t.join(timeout=2.0)  # grace for executor shutdown(wait=False)
        if t.is_alive():
            leaked.append(t)
    assert not leaked, (
        "leaked dptpu threads alive at session end (started during "
        "the suite, never stopped/joined): "
        + ", ".join(
            f"{t.name}"
            f" ({'daemon' if t.daemon else 'NON-DAEMON'},"
            f" target={getattr(getattr(t, '_target', None), '__qualname__', getattr(t, '_target', None))!r})"
            for t in leaked
        )
    )


@pytest.fixture(scope="session")
def eight_devices():
    devices = jax.devices()
    assert len(devices) >= 8, f"expected 8 fake devices, got {len(devices)}"
    return devices[:8]


@pytest.fixture(scope="session")
def tiny_imagenet(tmp_path_factory):
    """ImageFolder-shaped 3-class dataset with class-separable means —
    shared by the fit()-level integration tests."""
    import numpy as np
    from PIL import Image

    root = tmp_path_factory.mktemp("tinyimg")
    rng = np.random.RandomState(0)
    for split, per_class in [("train", 24), ("val", 8)]:
        for cls in range(3):
            d = root / split / f"class{cls}"
            d.mkdir(parents=True)
            for i in range(per_class):
                # class-dependent mean so the model can actually learn
                base = np.full((40, 40, 3), 60 + 70 * cls, np.uint8)
                noise = rng.randint(0, 40, base.shape, dtype=np.uint8)
                Image.fromarray(base + noise).save(d / f"{i}.png")
    return str(root)
