"""Test harness: fake TPU pod on CPU.

Multi-chip hardware is not available in CI, so every test runs on a virtual
8-device CPU mesh — the standard JAX fake-cluster trick (SURVEY.md §4). The
CPU platform must be forced via ``jax.config.update``, not env vars: this
image's sitecustomize imports jax at interpreter startup (to register the
axon TPU plugin), so ``JAX_PLATFORMS`` is already latched by the time test
code runs. ``XLA_FLAGS`` is still honored because the CPU PJRT client is
created lazily, at the first backend use — which is after this conftest.
The driver separately dry-runs the real multi-chip path via
``__graft_entry__.dryrun_multichip``.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    devices = jax.devices()
    assert len(devices) >= 8, f"expected 8 fake devices, got {len(devices)}"
    return devices[:8]
