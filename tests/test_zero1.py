"""ZeRO-1 weight-update sharding (dptpu/parallel/zero.py) on the fake
8-device pod: the sharded-optimizer step must produce the SAME update as
the single-device big-batch step (the DDP invariant), while params and
momentum actually live sharded (1/N per device)."""

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from dptpu.parallel import (
    gather_state,
    make_mesh,
    make_zero1_train_step,
    shard_host_batch,
    shard_zero1_state,
    zero1_state_specs,
)
from dptpu.train import create_train_state, make_optimizer, make_train_step


class TinyDense(nn.Module):
    """Dense-heavy so dim-0 leaves (16, 32, ...) actually shard 8 ways;
    includes BN so replicated batch_stats are exercised."""

    num_classes: int = 10
    bn_axis_name: str = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(16, (3, 3), use_bias=False)(x)
        x = nn.BatchNorm(
            use_running_average=not train, momentum=0.9,
            axis_name=self.bn_axis_name,
        )(x)
        x = nn.relu(x)
        x = x.mean(axis=(1, 2))
        x = nn.Dense(32)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes)(x)


def _state(bn_axis_name=None):
    tx = make_optimizer(momentum=0.9, weight_decay=1e-4)
    return create_train_state(
        jax.random.PRNGKey(0), TinyDense(bn_axis_name=bn_axis_name), tx,
        input_shape=(1, 8, 8, 3),
    )


def _batch(n=16, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "images": rng.randint(0, 256, (n, 8, 8, 3)).astype(np.uint8),
        "labels": rng.randint(0, 10, (n,)).astype(np.int32),
    }


def test_specs_shard_largest_divisible_dim(eight_devices):
    state = _state()
    mesh = make_mesh(eight_devices, {"data": 8})
    specs = zero1_state_specs(state, mesh)
    # conv kernel (3,3,3,16) is HWIO — only the out-channel dim divides 8
    assert specs.params["Conv_0"]["kernel"] == P(None, None, None, "data")
    # Dense_0 (16,32): both dims divide; the larger (32) wins
    assert specs.params["Dense_0"]["kernel"] == P(None, "data")
    assert specs.params["BatchNorm_0"]["scale"] == P("data")
    # momentum mirrors params
    flat = jax.tree_util.tree_leaves(
        specs.opt_state, is_leaf=lambda x: isinstance(x, P)
    )
    assert P(None, "data") in flat


def test_sharded_fraction_covers_cnn_and_vit_zoo(eight_devices):
    """The headline memory claim, asserted AT the documented bound
    (PARALLELISM.md / zero.py: ">=99% of bytes shard 1/N"): for BOTH a
    conv net (HWIO kernels — dim 0 is kernel height, which a dim-0-only
    rule misses almost entirely) and a ViT. Measured 100.0% for both on
    an 8-wide axis; the bound is 0.99 so the docs can never silently
    drift above what the suite enforces. Shapes come from
    jax.eval_shape: no weights are allocated."""
    import optax

    from dptpu.models import create_model
    from dptpu.parallel import zero1_sharded_fraction

    mesh = make_mesh(eight_devices, {"data": 8})
    for name, image_size in (("resnet50", 224), ("vit_b_16", 224)):
        model = create_model(name)
        tx = make_optimizer(momentum=0.9, weight_decay=1e-4)
        shapes = jax.eval_shape(
            lambda m=model, t=tx: create_train_state(
                jax.random.PRNGKey(0), m, t,
                input_shape=(1, image_size, image_size, 3),
            )
        )
        frac = zero1_sharded_fraction(shapes, mesh)
        assert frac >= 0.99, f"{name}: only {frac:.1%} of bytes shard"


def test_zero1_state_is_physically_sharded(eight_devices):
    state = _state()
    mesh = make_mesh(eight_devices, {"data": 8})
    z = shard_zero1_state(state, mesh)
    k = z.params["Dense_0"]["kernel"]  # (16, 32) -> split on dim 1
    assert k.sharding.spec == P(None, "data")
    assert k.addressable_shards[0].data.shape == (16, 4)  # 32/8 per device
    # values untouched
    np.testing.assert_array_equal(
        np.asarray(k), np.asarray(state.params["Dense_0"]["kernel"])
    )


def test_zero1_step_matches_single_device(eight_devices):
    """30 steps of ZeRO-1 == 30 steps of the single-device big-batch step
    (bitwise-close): all-gather + psum_scatter + local SGD is the same
    math as all-reduce + replicated SGD."""
    mesh = make_mesh(eight_devices, {"data": 8})
    # one state instance: shard_zero1_state copies (device_put), and the
    # spec tree's static metadata (apply_fn/tx) must match the stepped
    # state's, so template and runtime state share the same objects
    # SyncBN in the sharded path so BN sees the same global-batch
    # statistics as the single-device reference (per-replica BN would
    # legitimately diverge — same setup as the DDP parity test)
    state0 = _state(bn_axis_name="data")
    z_state = shard_zero1_state(state0, mesh)
    z_step = make_zero1_train_step(mesh, state0)
    ref_state = _state()  # same init values (same PRNGKey), no axis name
    ref_step = make_train_step()
    for i in range(30):
        batch = _batch(seed=i)
        ref_state, ref_m = ref_step(ref_state, batch)
        z_state, z_m = z_step(z_state, shard_host_batch(batch, mesh))
        np.testing.assert_allclose(
            float(z_m["loss"]), float(ref_m["loss"]), rtol=1e-5, atol=1e-6
        )
    for zp, rp in zip(
        jax.tree_util.tree_leaves(z_state.params),
        jax.tree_util.tree_leaves(ref_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(zp), np.asarray(rp), rtol=1e-4, atol=1e-5
        )
    # momentum buffers agree too (optimizer state parity, not just params)
    for zt, rt in zip(
        jax.tree_util.tree_leaves(z_state.opt_state),
        jax.tree_util.tree_leaves(ref_state.opt_state),
    ):
        np.testing.assert_allclose(
            np.asarray(zt), np.asarray(rt), rtol=1e-4, atol=1e-5
        )


def test_zero1_physical_per_device_bytes_resnet50(eight_devices):
    """The memory claim measured PHYSICALLY, not just by specs: after
    shard_zero1_state, the bytes device 0 actually holds for
    params+opt_state must be ~1/8 of the replicated total (a conv net —
    exactly the family the old dim-0 rule left ~92% replicated)."""
    from dptpu.models import create_model

    mesh = make_mesh(eight_devices, {"data": 8})
    model = create_model("resnet50", num_classes=10)
    tx = make_optimizer(momentum=0.9, weight_decay=1e-4)
    state = create_train_state(
        jax.random.PRNGKey(0), model, tx, input_shape=(1, 32, 32, 3)
    )
    leaves = jax.tree_util.tree_leaves((state.params, state.opt_state))
    total = sum(
        leaf.size * leaf.dtype.itemsize for leaf in leaves
        if hasattr(leaf, "size")
    )
    z = shard_zero1_state(state, mesh)
    dev0 = eight_devices[0]
    per_dev = 0
    for leaf in jax.tree_util.tree_leaves((z.params, z.opt_state)):
        if not hasattr(leaf, "addressable_shards"):
            continue
        for shard in leaf.addressable_shards:
            if shard.device == dev0:
                per_dev += shard.data.size * shard.data.dtype.itemsize
    # resnet50: >99% of bytes shard (largest-divisible-dim rule), so
    # device 0 holds barely more than total/8 — and the lower bound
    # keeps the test from passing vacuously if shard accounting breaks
    assert total / 8 * 0.95 <= per_dev <= total / 8 * 1.05, (
        f"device 0 holds {per_dev / 2**20:.1f} MiB of "
        f"{total / 2**20:.1f} MiB total — not ~1/8"
    )


def test_gather_state_rereplicates(eight_devices):
    mesh = make_mesh(eight_devices, {"data": 8})
    z = shard_zero1_state(_state(), mesh)
    g = gather_state(z, mesh)
    k = g.params["Dense_0"]["kernel"]
    assert k.sharding.spec == P()
    assert k.addressable_shards[0].data.shape == (16, 32)


# --- sharded weight update (arXiv:2004.13336): the ENTIRE optimizer
# math — LARS/LAMB trust ratios included — runs on the local shard,
# completed by one small psum. Parity vs the replicated update. ---


def _trust_state(name, bn_axis_name=None):
    tx = make_optimizer(momentum=0.9, weight_decay=1e-4, name=name)
    return create_train_state(
        jax.random.PRNGKey(0), TinyDense(bn_axis_name=bn_axis_name), tx,
        input_shape=(1, 8, 8, 3),
    )


def _tx_factory(name):
    from functools import partial

    return partial(make_optimizer, 0.9, 1e-4, name)


def test_zero1_sharded_lars_matches_replicated_8dev(eight_devices):
    """20 steps of the sharded LARS update (trust-ratio norms completed
    from shard-local partials with one [L,2] psum) == 20 steps of the
    replicated single-device LARS step, within the NUMERICS tolerance.
    Locks optimizer-math-on-1/N against the full-math baseline."""
    mesh = make_mesh(eight_devices, {"data": 8})
    state0 = _trust_state("lars", bn_axis_name="data")
    z_state = shard_zero1_state(state0, mesh)
    z_step = make_zero1_train_step(
        mesh, state0, tx_factory=_tx_factory("lars")
    )
    ref_state = _trust_state("lars")
    ref_step = make_train_step()
    for i in range(20):
        batch = _batch(seed=i)
        ref_state, ref_m = ref_step(ref_state, batch)
        z_state, z_m = z_step(z_state, shard_host_batch(batch, mesh))
        np.testing.assert_allclose(
            float(z_m["loss"]), float(ref_m["loss"]), rtol=1e-5, atol=1e-6
        )
        # the trust-ratio telemetry from the sharded norms equals the
        # replicated optimizer's (same completed sums)
        np.testing.assert_allclose(
            float(z_m["trust_mean"]), float(ref_m["trust_mean"]),
            rtol=1e-5, atol=1e-7,
        )
    for part in ("params", "opt_state"):
        for zp, rp in zip(
            jax.tree_util.tree_leaves(getattr(z_state, part)),
            jax.tree_util.tree_leaves(getattr(ref_state, part)),
        ):
            np.testing.assert_allclose(
                np.asarray(zp), np.asarray(rp), rtol=1e-4, atol=1e-5
            )


def test_zero1_sharded_lamb_matches_replicated_2dev(eight_devices):
    """Same lock for LAMB on the minimal 2-device mesh (the smallest
    geometry where sharded != replicated): Adam moments live 1/N and the
    unit trust ratio completes from partial sums."""
    mesh = make_mesh(eight_devices[:2], {"data": 2})
    state0 = _trust_state("lamb", bn_axis_name="data")
    z_state = shard_zero1_state(state0, mesh)
    z_step = make_zero1_train_step(
        mesh, state0, tx_factory=_tx_factory("lamb")
    )
    ref_state = _trust_state("lamb")
    ref_step = make_train_step()
    for i in range(10):
        batch = _batch(seed=i)
        ref_state, ref_m = ref_step(ref_state, batch)
        z_state, z_m = z_step(z_state, shard_host_batch(batch, mesh))
    np.testing.assert_allclose(
        float(z_m["loss"]), float(ref_m["loss"]), rtol=1e-5, atol=1e-6
    )
    for zp, rp in zip(
        jax.tree_util.tree_leaves(z_state.params),
        jax.tree_util.tree_leaves(ref_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(zp), np.asarray(rp), rtol=1e-4, atol=1e-5
        )


def test_zero1_accum_composes_with_sharding(eight_devices):
    """accum=2 under ZeRO-1 == accum=2 under DDP (same virtual-replica
    math; the fp32 accumulator is shard-sized but the completed update
    is identical)."""
    mesh = make_mesh(eight_devices, {"data": 8})
    state0 = _trust_state("lars", bn_axis_name="data")
    z_state = shard_zero1_state(state0, mesh)
    z_step = make_zero1_train_step(
        mesh, state0, accum_steps=2, tx_factory=_tx_factory("lars")
    )
    d_state = _trust_state("lars", bn_axis_name="data")
    d_step = make_train_step(mesh=mesh, accum_steps=2)
    for i in range(5):
        batch = _batch(n=32, seed=i)
        sharded = shard_host_batch(batch, mesh)
        z_state, z_m = z_step(z_state, sharded)
        d_state, d_m = d_step(d_state, sharded)
    np.testing.assert_allclose(
        float(z_m["loss"]), float(d_m["loss"]), rtol=1e-5, atol=1e-6
    )
    for zp, dp in zip(
        jax.tree_util.tree_leaves(z_state.params),
        jax.tree_util.tree_leaves(jax.device_get(d_state.params)),
    ):
        np.testing.assert_allclose(
            np.asarray(zp), np.asarray(dp), rtol=1e-4, atol=1e-5
        )


def test_zero1_sumsq_reduce_completes_only_sharded_leaves(eight_devices):
    """The one-small-psum completer: sharded leaves' [sum(w²), sum(u²)]
    partials sum across the axis; replicated leaves pass through (a psum
    would count each copy N times)."""
    import jax.numpy as jnp

    from dptpu.parallel.zero import zero1_sumsq_reduce
    from dptpu.train.step import shard_map_nocheck

    mesh = make_mesh(eight_devices, {"data": 8})
    param_specs = {"b": P(), "w": P(None, "data")}
    reduce = zero1_sumsq_reduce(param_specs)

    def body():
        pairs = {"b": jnp.asarray([3.0, 5.0]), "w": jnp.asarray([1.0, 2.0])}
        return reduce(pairs)

    out = jax.jit(shard_map_nocheck(
        body, mesh=mesh, in_specs=(), out_specs={"b": P(), "w": P()}
    ))()
    np.testing.assert_allclose(np.asarray(out["w"]), [8.0, 16.0])  # psum'd
    np.testing.assert_allclose(np.asarray(out["b"]), [3.0, 5.0])  # untouched

    # structure mismatch (optimizer built against another param tree)
    # fails loudly, not with a silently wrong stack alignment
    import pytest

    with pytest.raises(ValueError, match="different param tree"):
        reduce({"w": jnp.zeros(2)})


def test_zero1_update_shard_bytes_scales_inverse_n(eight_devices):
    """The Opt/update_shard_bytes gauge: per-update optimizer bytes on
    one chip are ~1/N of the replicated total (replicated remainder is a
    rounding error for TinyDense: only the 10-wide head bias resists 8)."""
    from dptpu.parallel import zero1_update_shard_bytes

    state = _state()
    mesh8 = make_mesh(eight_devices, {"data": 8})
    mesh2 = make_mesh(eight_devices[:2], {"data": 2})
    total = sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(
            (state.params, state.opt_state)
        )
        if hasattr(leaf, "size")
    )
    b8 = zero1_update_shard_bytes(state, mesh8)
    b2 = zero1_update_shard_bytes(state, mesh2)
    assert total / 8 <= b8 <= total / 8 * 1.15
    assert total / 2 <= b2 <= total / 2 * 1.05
    assert b8 < b2 < total


# --- ZeRO-3/FSDP (ISSUE 16): placement comes from the registry rules
# table (zero3_param_specs), the gather/scatter boundary is the explicit
# custom VJP, and parity vs DDP is EXACT in flat fp32 — all-gather at
# use + psum_scatter in backward + shard-local SGD is the same math as
# all-reduce + replicated SGD, and on the flat mesh it is the same
# floating-point program (Δ=0 locked below). ---


def test_zero3_specs_match_zero1_for_generic_family(eight_devices):
    """For a generic-family arch the rules table is ((".*", AUTO_FSDP),)
    — the ZeRO-3 param placement must be BIT-IDENTICAL to the legacy
    ``_leaf_spec`` layout ZeRO-1 uses (the fallback resolves through the
    same largest-divisible-dim rule)."""
    from dptpu.parallel import zero3_param_specs

    state = _state()
    mesh = make_mesh(eight_devices, {"data": 8})
    z3 = zero3_param_specs("resnet18", state.params, mesh)
    z1 = zero1_state_specs(state, mesh).params
    assert jax.tree_util.tree_all(
        jax.tree_util.tree_map(lambda a, b: a == b, z3, z1,
                               is_leaf=lambda x: isinstance(x, P))
    )


def test_zero3_step_bitwise_matches_ddp_8dev(eight_devices):
    """THE acceptance bar: 5 fp32 steps of the rules-driven ZeRO-3 step
    == 5 steps of the shard_map DDP step with Δ=0 — params, momentum and
    loss bitwise equal on the fake 8-device pod."""
    from dptpu.parallel import (
        make_zero3_train_step,
        shard_zero3_state,
        zero3_param_specs,
    )

    mesh = make_mesh(eight_devices, {"data": 8})
    state0 = _state(bn_axis_name="data")
    z3p = zero3_param_specs("resnet18", state0.params, mesh)
    z_state = shard_zero3_state(state0, mesh, z3p)
    z_step = make_zero3_train_step(mesh, state0, z3p)
    d_state = jax.tree_util.tree_map(jnp.array, _state(bn_axis_name="data"))
    d_step = make_train_step(mesh=mesh)
    for i in range(5):
        batch = shard_host_batch(_batch(seed=i), mesh)
        z_state, z_m = z_step(z_state, batch)
        d_state, d_m = d_step(d_state, batch)
        assert float(z_m["loss"]) == float(d_m["loss"])
    for part in ("params", "opt_state", "batch_stats"):
        for zp, dp in zip(
            jax.tree_util.tree_leaves(getattr(z_state, part)),
            jax.tree_util.tree_leaves(getattr(d_state, part)),
        ):
            np.testing.assert_array_equal(np.asarray(zp), np.asarray(dp))


def test_zero3_accum_composes_with_sharding(eight_devices):
    """accum=2 under ZeRO-3 == accum=2 under DDP: the fp32 accumulator
    is SHARD-sized (the scatter runs per microbatch inside the boundary
    VJP) but the completed update is the same virtual-replica math."""
    from dptpu.parallel import (
        make_zero3_train_step,
        shard_zero3_state,
        zero3_param_specs,
    )

    mesh = make_mesh(eight_devices, {"data": 8})
    state0 = _state(bn_axis_name="data")
    z3p = zero3_param_specs("resnet18", state0.params, mesh)
    z_state = shard_zero3_state(state0, mesh, z3p)
    z_step = make_zero3_train_step(mesh, state0, z3p, accum_steps=2)
    d_state = jax.tree_util.tree_map(jnp.array, _state(bn_axis_name="data"))
    d_step = make_train_step(mesh=mesh, accum_steps=2)
    for i in range(5):
        batch = shard_host_batch(_batch(n=32, seed=i), mesh)
        z_state, z_m = z_step(z_state, batch)
        d_state, d_m = d_step(d_state, batch)
    np.testing.assert_allclose(
        float(z_m["loss"]), float(d_m["loss"]), rtol=1e-6, atol=1e-7
    )
    for zp, dp in zip(
        jax.tree_util.tree_leaves(z_state.params),
        jax.tree_util.tree_leaves(d_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(zp), np.asarray(dp), rtol=1e-5, atol=1e-6
        )


def test_zero3_slices_and_overlap_compose(eight_devices):
    """{slice: 2, data: 4} + overlap buckets: the hierarchical ZeRO-3
    step (RS over ICI, shard-sized fp32 DCN hop, bucketed in-backward
    reduction) matches the flat 8-wide DDP step to reduction-grouping
    tolerance (measured 3e-8 after 5 steps). BN syncs over BOTH axes —
    fit() wires squeeze_axes(data_axis_names(mesh)), mirrored here."""
    from dptpu.parallel import (
        make_zero3_train_step,
        shard_zero3_state,
        zero3_param_specs,
    )
    from dptpu.parallel.mesh import data_axis_names, squeeze_axes

    hmesh = make_mesh(eight_devices, {"slice": 2, "data": 4})
    fmesh = make_mesh(eight_devices, {"data": 8})
    hbn = squeeze_axes(data_axis_names(hmesh))
    state0 = _state(bn_axis_name=hbn)
    z3p = zero3_param_specs("resnet18", state0.params, hmesh)
    z_state = shard_zero3_state(state0, hmesh, z3p)
    z_step = make_zero3_train_step(
        hmesh, state0, z3p, overlap=True, bucket_bytes=2048
    )
    d_state = jax.tree_util.tree_map(jnp.array, _state(bn_axis_name="data"))
    d_step = make_train_step(mesh=fmesh)
    for i in range(5):
        batch = _batch(seed=i)
        z_state, z_m = z_step(z_state, shard_host_batch(batch, hmesh))
        d_state, d_m = d_step(d_state, shard_host_batch(batch, fmesh))
    np.testing.assert_allclose(
        float(z_m["loss"]), float(d_m["loss"]), rtol=1e-6, atol=1e-7
    )
    for zp, dp in zip(
        jax.tree_util.tree_leaves(z_state.params),
        jax.tree_util.tree_leaves(d_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(zp), np.asarray(dp), rtol=1e-5, atol=1e-6
        )


def test_zero3_opt_state_is_shard_sized_resnet18(eight_devices):
    """The memory gate: per-chip params+opt bytes under the ZeRO-3 spec
    tree are EXACTLY 1/8 of the replicated total on resnet18 (every
    leaf's largest dim divides 8 with the default 1000-class head — no
    replicated remainder), and the physically placed state matches the
    accounting."""
    from dptpu.models import create_model
    from dptpu.parallel import (
        shard_zero3_state,
        state_shard_bytes,
        zero3_param_specs,
        zero3_state_specs,
    )

    mesh = make_mesh(eight_devices, {"data": 8})
    model = create_model("resnet18")
    tx = make_optimizer(momentum=0.9, weight_decay=1e-4)
    state = create_train_state(
        jax.random.PRNGKey(0), model, tx, input_shape=(1, 32, 32, 3)
    )
    total = sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(
            (state.params, state.opt_state)
        )
        if hasattr(leaf, "size")
    )
    z3p = zero3_param_specs("resnet18", state.params, mesh)
    shard = state_shard_bytes(
        state, mesh, zero3_state_specs(state, mesh, z3p)
    )
    assert shard * 8 == total, (
        f"per-chip {shard} B x 8 != replicated {total} B"
    )
    # the accounting is honest: device 0 physically holds exactly that
    z = shard_zero3_state(state, mesh, z3p)
    dev0 = eight_devices[0]
    per_dev = 0
    for leaf in jax.tree_util.tree_leaves((z.params, z.opt_state)):
        for s in getattr(leaf, "addressable_shards", ()):
            if s.device == dev0:
                per_dev += s.data.size * s.data.dtype.itemsize
    assert per_dev == shard
