"""Input-pipeline tests: ImageFolder semantics, sharding, collation, prefetch."""

import numpy as np
import pytest
from PIL import Image

from dptpu.data import (
    DataLoader,
    DevicePrefetcher,
    ImageFolderDataset,
    ShardedSampler,
    SyntheticDataset,
    center_crop,
    random_resized_crop,
    resize_shorter,
    train_transform,
    val_transform,
)
@pytest.fixture
def image_folder(tmp_path):
    # 3 classes × 5 images, deliberately created out of sorted order
    rng = np.random.RandomState(0)
    for cls in ["n02", "n01", "n03"]:
        d = tmp_path / "train" / cls
        d.mkdir(parents=True)
        for i in range(5):
            arr = rng.randint(0, 256, (40, 52, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"img_{i}.png")
    return str(tmp_path / "train")


def test_image_folder_semantics(image_folder):
    ds = ImageFolderDataset(image_folder)
    # sorted class names → indices (torchvision contract)
    assert ds.classes == ["n01", "n02", "n03"]
    assert ds.class_to_idx["n01"] == 0
    assert len(ds) == 15
    img, label = ds[0]
    assert img.shape == (40, 52, 3) and img.dtype == np.uint8
    assert label == 0


def test_sampler_disjoint_cover_and_reshuffle():
    s = [ShardedSampler(103, num_shards=4, shard_index=i, seed=7)
         for i in range(4)]
    all_idx = np.concatenate([x.indices(epoch=0) for x in s])
    # ceil(103/4)=26 per shard; padded total 104 covers every example
    assert all(len(x) == 26 for x in s)
    assert set(all_idx.tolist()) == set(range(103))
    # disjoint before padding: only one duplicated example (104-103)
    vals, counts = np.unique(all_idx, return_counts=True)
    assert (counts > 1).sum() == 1
    # set_epoch analog: different permutation, same cover
    e1 = np.concatenate([x.indices(epoch=1) for x in s])
    assert not np.array_equal(all_idx, e1)
    assert set(e1.tolist()) == set(range(103))


def test_sampler_validity_flags_wrap_around_padding():
    """Exact-val prerequisite (imagenet_ddp_apex.py:457-460): the union of
    valid positions across shards covers every example exactly once, and
    padded duplicates are flagged invalid."""
    n, shards = 103, 4
    samplers = [ShardedSampler(n, num_shards=shards, shard_index=i, seed=7)
                for i in range(shards)]
    seen = []
    for s in samplers:
        idx, valid = s.indices_and_validity(epoch=0)
        assert idx.shape == valid.shape
        seen.extend(idx[valid].tolist())
    assert sorted(seen) == list(range(n))  # each real sample exactly once
    # evenly divisible: nothing flagged
    s = ShardedSampler(12, num_shards=4, shard_index=1)
    _, valid = s.indices_and_validity(0)
    assert valid.all()


def test_loader_masks_wrap_around_duplicates(image_folder):
    """A val shard whose padding wraps around gets mask zeros on the
    duplicated samples so psum aggregation stays exact."""
    ds = ImageFolderDataset(image_folder)  # 15 examples
    # 4 shards -> ceil(15/4)=4 per shard, 1 wrap duplicate somewhere
    total_valid = 0
    for shard in range(4):
        loader = DataLoader(
            ds, batch_size=4,
            sampler=ShardedSampler(len(ds), num_shards=4, shard_index=shard,
                                   shuffle=False),
            num_workers=1,
        )
        for b in loader.epoch(0):
            mask = b.get("mask")
            total_valid += int(mask.sum()) if mask is not None else len(b["labels"])
        loader.close()
    assert total_valid == len(ds)  # duplicates excluded exactly


def test_sampler_no_shuffle_drop_last():
    s = ShardedSampler(10, num_shards=3, shard_index=2, shuffle=False,
                       drop_last=True)
    assert len(s) == 3
    np.testing.assert_array_equal(s.indices(0), [2, 5, 8])


def test_loader_batches_and_padded_tail(image_folder):
    ds = ImageFolderDataset(image_folder)
    loader = DataLoader(ds, batch_size=4, num_workers=2)
    batches = list(loader.epoch(0))
    assert len(batches) == 4  # ceil(15/4)
    for b in batches[:-1]:
        assert b["images"].shape == (4, 40, 52, 3)
        assert b["images"].dtype == np.uint8
        assert b["labels"].dtype == np.int32
        assert "mask" not in b
    tail = batches[-1]
    assert tail["mask"].tolist() == [1.0, 1.0, 1.0, 0.0]
    # padding repeats sample 0 of the batch
    np.testing.assert_array_equal(tail["images"][3], tail["images"][0])
    loader.close()


def test_loader_short_tail_without_padding(image_folder):
    ds = ImageFolderDataset(image_folder)
    loader = DataLoader(ds, batch_size=4, pad_final=False)
    batches = list(loader.epoch(0))
    assert len(batches) == 4
    assert batches[-1]["images"].shape[0] == 3  # 15 % 4, unpadded
    assert "mask" not in batches[-1]
    loader.close()


def test_loader_augmentation_deterministic_across_runs(image_folder):
    # per-(seed, epoch, index) RNG: identical batches regardless of thread
    # scheduling; different epoch → different augmentation
    from dptpu.data import train_transform

    ds = ImageFolderDataset(image_folder, train_transform(32))
    a = DataLoader(ds, batch_size=4, num_workers=4, seed=5)
    b = DataLoader(ds, batch_size=4, num_workers=1, seed=5)
    ba = list(a.epoch(0))
    bb = list(b.epoch(0))
    for x, y in zip(ba, bb):
        np.testing.assert_array_equal(x["images"], y["images"])
    e1 = list(a.epoch(1))
    assert not all(
        np.array_equal(x["images"], y["images"]) for x, y in zip(ba, e1)
    )
    a.close()
    b.close()


def test_loader_drop_last(image_folder):
    ds = ImageFolderDataset(image_folder)
    loader = DataLoader(ds, batch_size=4, drop_last=True)
    batches = list(loader.epoch(0))
    assert len(batches) == 3
    assert all("mask" not in b for b in batches)
    loader.close()


def test_transforms_shapes_and_determinism():
    img = Image.fromarray(
        np.random.RandomState(1).randint(0, 256, (300, 400, 3), dtype=np.uint8)
    )
    # val path: deterministic, torchvision-exact geometry
    assert resize_shorter(img, 256).size == (341, 256)  # w>h keeps aspect
    assert center_crop(resize_shorter(img, 256), 224).size == (224, 224)
    out = val_transform()(img)
    assert out.shape == (224, 224, 3) and out.dtype == np.uint8
    # train path: correct size; same seed → same crop
    t1 = random_resized_crop(img, np.random.default_rng(3), 224)
    t2 = random_resized_crop(img, np.random.default_rng(3), 224)
    assert t1.size == (224, 224)
    np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))
    tt = train_transform(96)
    assert tt(img, np.random.default_rng(0)).shape == (96, 96, 3)


def test_synthetic_dataset_deterministic():
    ds = SyntheticDataset(num_samples=8, image_size=32, num_classes=10)
    img_a, lab_a = ds[3]
    img_b, lab_b = ds[3]
    np.testing.assert_array_equal(img_a, img_b)
    assert lab_a == lab_b and 0 <= lab_a < 10
    assert img_a.shape == (32, 32, 3)


def test_device_prefetcher_preserves_order():
    ds = SyntheticDataset(num_samples=12, image_size=8, num_classes=4)
    loader = DataLoader(ds, batch_size=4, num_workers=2)
    direct = [b["labels"].copy() for b in loader.epoch(0)]
    fetched = [
        np.asarray(b["labels"])
        for b in DevicePrefetcher(loader.epoch(0))
    ]
    assert len(fetched) == len(direct) == 3
    for a, b in zip(direct, fetched):
        np.testing.assert_array_equal(a, b)
    loader.close()


def test_loader_fixed_shape_contract_raises_clearly(tmp_path):
    """Without a sizing transform, mixed image sizes violate the
    fixed-shape contract: the loader must name the offending sample and
    the contract, not die with a numpy broadcast error."""
    d = tmp_path / "train" / "c0"
    d.mkdir(parents=True)
    rng = np.random.RandomState(0)
    Image.fromarray(rng.randint(0, 256, (40, 52, 3), dtype=np.uint8)).save(
        d / "a_first.png"
    )
    Image.fromarray(rng.randint(0, 256, (30, 20, 3), dtype=np.uint8)).save(
        d / "b_second.png"
    )
    ds = ImageFolderDataset(str(tmp_path / "train"))  # transform=None
    loader = DataLoader(ds, batch_size=2, num_workers=1)
    with pytest.raises(ValueError, match="decoded to shape"):
        list(loader.epoch(0))
    loader.close()


def test_loader_probe_decode_reused_for_first_row():
    """The shape probe's decode is reused for its sample's batch row
    (ADVICE r5): sample 0 must be loaded exactly once per first epoch,
    and the reuse must not change the yielded pixels."""

    class Counting(SyntheticDataset):
        def __init__(self, *a, **k):
            super().__init__(*a, **k)
            self.loads = {}

        def get(self, index, rng=None):
            self.loads[index] = self.loads.get(index, 0) + 1
            return super().get(index, rng)

        # force the get()-based path so every decode is counted
        get_into = None

    ds = Counting(num_samples=8, image_size=8, num_classes=4)
    loader = DataLoader(ds, batch_size=4, num_workers=2, seed=3)
    batches = list(loader.epoch(0))
    assert ds.loads[0] == 1  # probed once, reused — not decoded twice
    ref = SyntheticDataset(num_samples=8, image_size=8, num_classes=4)
    np.testing.assert_array_equal(batches[0]["images"][0], ref.get(0)[0])
    loader.close()


def test_val_transform_matches_torchvision_two_step_exactly():
    """The fused one-box val resample must be PIXEL-EXACT (±1 LSB of
    uint8 rounding) to torchvision's two-step Resize(256)→CenterCrop(224)
    across awkward geometries — including non-integer long-edge scales,
    where the pre-round-5 integer box drifted by a sub-pixel phase
    (mean |Δpx| up to ~10, scripts/check_tv_parity.py)."""
    import numpy as np
    from PIL import Image

    from dptpu.data.transforms import ValTransform

    fused = ValTransform(224, 256)
    rng = np.random.RandomState(3)
    for (w, h) in [(500, 400), (640, 480), (1024, 768), (300, 224),
                   (231, 256), (257, 511)]:
        low = rng.randint(0, 255, (max(h // 8, 2), max(w // 8, 2), 3),
                          np.uint8)
        img = Image.fromarray(low).resize((w, h), Image.BILINEAR)
        a = fused(img).astype(np.int16)
        if w <= h:
            nw, nh = 256, int(256 * h / w)
        else:
            nh, nw = 256, int(256 * w / h)
        resized = img.resize((nw, nh), Image.BILINEAR)
        left, top = (nw - 224) // 2, (nh - 224) // 2
        b = np.asarray(
            resized.crop((left, top, left + 224, top + 224)), np.int16
        )
        d = np.abs(a - b)
        assert d.max() <= 1, (w, h, d.max())
        assert (d > 0).mean() < 0.02, (w, h, (d > 0).mean())
