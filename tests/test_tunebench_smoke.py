"""Tier-1-adjacent smoke of scripts/run_tunebench.py: the autotuner's
never-worse-than-default promise is continuously checked — a fresh
artifact is tuned, loaded through the real DPTPU_TUNE_ARTIFACT path,
and gated against default on the cost model AND a measured fit() arm.
One subprocess, smallest preset, same gate logic."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_tunebench_smoke_gates(tmp_path):
    out = str(tmp_path / "TUNEBENCH.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # real single-CPU topology: the fake 8-device pod the test harness
    # forces would route the subprocess into the shard_map DDP step
    # (the obsbench smoke's rationale); the never-worse gate being
    # smoked is topology-independent
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    env.pop("DPTPU_TUNE_ARTIFACT", None)
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "run_tunebench.py"),
         "--smoke", "--images", "128", "--epochs", "2", "--reps", "2",
         "--out", out],
        capture_output=True, text=True, timeout=540, env=env,
        cwd=str(tmp_path),
    )
    assert proc.returncode == 0, (
        f"tunebench gate failed\nstdout:\n{proc.stdout[-4000:]}\n"
        f"stderr:\n{proc.stderr[-4000:]}"
    )
    with open(out) as f:
        bench = json.load(f)
    assert all(bench["gates"].values()), bench["gates"]
    # the artifact really flowed through fit(): the tuned arm recorded
    # the applied/overridden banner from the run itself
    assert bench["measured"]["applied"]["artifact"]
    assert bench["artifact_crc32"] == \
        bench["measured"]["applied"]["crc32"]
    # the gate is honest about host noise: never tighter than the
    # requested bound, widened to the measured spreads
    m = bench["measured"]
    assert m["effective_gate_pct"] >= m["gate_pct"]
    assert m["effective_gate_pct"] >= m["paired_spread_pct"]
    assert len(m["paired_deltas_pct"]) == m["reps"]
    # the analytic arms are deterministic: tuned never worse
    cm = bench["cost_model"]
    assert cm["tuned_overlapped_ms"] <= cm["default_overlapped_ms"]
    sl = bench["serve_ladder"]
    assert sl["tuned_waste"] <= sl["default_waste"]
    # provenance stamp (the committed-artifact discipline)
    assert bench["host"]["cpu_count"]
