"""The hand-rolled event writer must be readable by stock TensorBoard."""

import glob
import os

from dptpu.utils.tensorboard import SummaryWriter, _crc32c


def test_crc32c_known_vectors():
    # public CRC-32C (Castagnoli) test vectors
    assert _crc32c(b"") == 0x0
    assert _crc32c(b"123456789") == 0xE3069283
    assert _crc32c(b"a") == 0xC1D04330


def test_tensorboard_reads_our_events(tmp_path):
    w = SummaryWriter(log_dir=str(tmp_path / "run1"))
    scalars = {
        "Loss/train": [(1, 6.9), (2, 5.5)],
        "Top1/val": [(1, 12.5), (2, 31.25)],
        "Lr": [(1, 0.1)],
    }
    for tag, points in scalars.items():
        for step, val in points:
            w.add_scalar(tag, val, step)
    w.close()

    from tensorboard.backend.event_processing import event_accumulator

    acc = event_accumulator.EventAccumulator(str(tmp_path / "run1"))
    acc.Reload()
    assert set(acc.Tags()["scalars"]) == set(scalars)
    for tag, points in scalars.items():
        got = [(e.step, round(e.value, 5)) for e in acc.Scalars(tag)]
        assert got == [(s, round(v, 5)) for s, v in points]


def test_run_dir_naming_comment():
    w = SummaryWriter(log_dir=None, comment="_resnet50_gpux4_b224_cpu4_optO2")
    try:
        assert "runs" in w.log_dir
        assert w.log_dir.endswith("_resnet50_gpux4_b224_cpu4_optO2")
        assert glob.glob(os.path.join(w.log_dir, "events.out.tfevents.*"))
    finally:
        w.close()
        # clean the cwd-relative runs dir created by this test
        import shutil

        shutil.rmtree("runs", ignore_errors=True)
