"""The hand-rolled event writer must be readable by stock TensorBoard."""

import glob
import os
import signal
import subprocess
import sys

from dptpu.utils.tensorboard import SummaryWriter, _crc32c


def test_crc32c_known_vectors():
    # public CRC-32C (Castagnoli) test vectors
    assert _crc32c(b"") == 0x0
    assert _crc32c(b"123456789") == 0xE3069283
    assert _crc32c(b"a") == 0xC1D04330


def test_tensorboard_reads_our_events(tmp_path):
    w = SummaryWriter(log_dir=str(tmp_path / "run1"))
    scalars = {
        "Loss/train": [(1, 6.9), (2, 5.5)],
        "Top1/val": [(1, 12.5), (2, 31.25)],
        "Lr": [(1, 0.1)],
    }
    for tag, points in scalars.items():
        for step, val in points:
            w.add_scalar(tag, val, step)
    w.close()

    from tensorboard.backend.event_processing import event_accumulator

    acc = event_accumulator.EventAccumulator(str(tmp_path / "run1"))
    acc.Reload()
    assert set(acc.Tags()["scalars"]) == set(scalars)
    for tag, points in scalars.items():
        got = [(e.step, round(e.value, 5)) for e in acc.Scalars(tag)]
        assert got == [(s, round(v, 5)) for s, v in points]


def test_killed_writer_leaves_parseable_file(tmp_path):
    """Preemption durability (dptpu/resilience): every add_scalar is
    flushed to the OS, so a writer killed with SIGKILL — no atexit, no
    close(), no SIGTERM grace — still leaves an event file stock
    TensorBoard parses, containing every scalar written before death."""
    logdir = str(tmp_path / "killed")
    child = (
        "import os, signal\n"
        "from dptpu.utils.tensorboard import SummaryWriter\n"
        f"w = SummaryWriter(log_dir={logdir!r})\n"
        "for step in (1, 2, 3):\n"
        "    w.add_scalar('Loss/train', 7.0 - step, step)\n"
        "os.kill(os.getpid(), signal.SIGKILL)\n"
    )
    proc = subprocess.run([sys.executable, "-c", child],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == -signal.SIGKILL, proc.stderr

    from tensorboard.backend.event_processing import event_accumulator

    acc = event_accumulator.EventAccumulator(logdir)
    acc.Reload()
    got = [(e.step, e.value) for e in acc.Scalars("Loss/train")]
    assert got == [(1, 6.0), (2, 5.0), (3, 4.0)]


def test_close_is_idempotent_and_atexit_safe(tmp_path):
    # double close must not raise (the atexit hook runs after an
    # explicit close on every normal path)
    w = SummaryWriter(log_dir=str(tmp_path / "run2"))
    w.add_scalar("Lr", 0.1, 1)
    w.close()
    w.close()


def test_run_dir_naming_comment():
    w = SummaryWriter(log_dir=None, comment="_resnet50_gpux4_b224_cpu4_optO2")
    try:
        assert "runs" in w.log_dir
        assert w.log_dir.endswith("_resnet50_gpux4_b224_cpu4_optO2")
        assert glob.glob(os.path.join(w.log_dir, "events.out.tfevents.*"))
    finally:
        w.close()
        # clean the cwd-relative runs dir created by this test
        import shutil

        shutil.rmtree("runs", ignore_errors=True)


def test_parse_perfetto_trace_sums_device_ops():
    """Device-time parser: host tracks excluded, per-core duplicate tracks
    collapsed by max, durations normalized per iteration."""
    from dptpu.utils.profiling import parse_perfetto_trace

    trace = {"traceEvents": [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 2, "name": "process_name",
         "args": {"name": "Host threads"}},
        # two duplicate device tracks (tids) reporting the same ops
        {"ph": "X", "pid": 1, "tid": 10, "name": "fusion.1", "dur": 4000},
        {"ph": "X", "pid": 1, "tid": 11, "name": "fusion.1", "dur": 4000},
        {"ph": "X", "pid": 1, "tid": 10, "name": "copy.2", "dur": 1000},
        # host event must not count
        {"ph": "X", "pid": 2, "tid": 20, "name": "dispatch", "dur": 9999},
    ]}
    total, per_op = parse_perfetto_trace(trace, iters=2)
    assert per_op == {"fusion.1": 2.0, "copy.2": 0.5}  # us->ms, /iters
    assert total == 2.5
    # with module-level jit_ spans present, their SUM is the total and
    # they are filtered from the per-op table (children would otherwise
    # double-count against the total)
    trace["traceEvents"].append(
        {"ph": "X", "pid": 1, "tid": 10, "name": "jit_step(123)", "dur": 5200}
    )
    trace["traceEvents"].append(
        {"ph": "X", "pid": 1, "tid": 10, "name": "jit_aux(9)", "dur": 800}
    )
    total, per_op = parse_perfetto_trace(trace, iters=2)
    assert total == 3.0  # 2.6 + 0.4
    assert not any(k.startswith("jit_") for k in per_op)
