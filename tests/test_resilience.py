"""Resilience primitives: fault specs, checkpoint integrity, rotation,
corrupt-file fallback, preemption guard.

The contracts under test (dptpu/resilience + train/checkpoint.py):

* checkpoints carry a CRC content footer; a flipped byte or a truncated
  tail is DETECTED, never silently loaded;
* an empty checkpoint file raises a FileNotFoundError-derived error
  (warn-and-continue resume treats it like absence);
* rotated step checkpoints keep exactly ``keep`` files and resume
  falls back PAST corrupt files to the newest verifiable one;
* ``DPTPU_FAULT`` specs parse strictly (typos fail before training);
* the preemption guard converts the first SIGTERM into a flag, not a
  crash.
"""

import os
import signal

import jax.numpy as jnp
import numpy as np
import pytest

from dptpu.resilience import (
    CheckpointManager,
    FaultPlan,
    PreemptionGuard,
    find_resumable,
    step_checkpoint_name,
    verify_checkpoint,
)
from dptpu.train.checkpoint import (
    CorruptCheckpointError,
    EmptyCheckpointError,
    load_checkpoint,
    save_checkpoint,
)
from dptpu.train.state import TrainState, make_optimizer


def tiny_state(value: float = 1.0) -> TrainState:
    """A real TrainState over a toy param tree — no model, no compile."""
    params = {"dense": {"kernel": np.full((4, 3), value, np.float32),
                        "bias": np.zeros((3,), np.float32)}}
    tx = make_optimizer()
    return TrainState(
        step=jnp.asarray(0, jnp.int32),
        params=params,
        batch_stats={},
        opt_state=tx.init(params),
        apply_fn=lambda *a, **k: None,
        tx=tx,
    )


# -- fault spec parsing ------------------------------------------------------

def test_fault_spec_parses_all_kinds():
    p = FaultPlan("sigterm@step=12,worker_kill@step=7,ckpt_truncate@save=2,"
                  "io_error:p=0.1,worker_hang@index=4")
    kinds = [f.kind for f in p.faults]
    assert kinds == ["sigterm", "worker_kill", "ckpt_truncate", "io_error",
                     "worker_hang"]
    assert p.faults[0].step == 12
    assert p.faults[2].save == 2
    assert p.faults[3].p == pytest.approx(0.1)
    assert p.faults[4].index == 4


@pytest.mark.parametrize("bad", [
    "explode@step=1",       # unknown kind
    "io_error:p=nope",      # non-numeric probability
    "io_error:p=1.5",       # probability out of range
    "sigterm",              # missing required @step
    "worker_hang",          # missing required @index
    "sigterm@tick=3",       # unknown modifier key
    "worker_hang@index=2@s=0",     # straggler sleep must be > 0
    "worker_hang@index=2@s=soon",  # non-numeric sleep
    "serve_exception",      # missing required @request
    "preprocess_crash",     # missing required @request
    "serve_exception@request=0",   # request index is 1-based
    "serve_exception@request=abc", # non-numeric request index
    "slow_model",           # missing required :factor
    "slow_model:factor=1",  # factor must be > 1
])
def test_fault_spec_rejects_typos(bad):
    with pytest.raises(ValueError):
        FaultPlan(bad)


def test_serve_fault_kinds_parse_and_fire():
    p = FaultPlan("serve_exception@request=3,preprocess_crash@request=5,"
                  "slow_model:factor=4,canary_drift")
    assert [f.kind for f in p.faults] == [
        "serve_exception", "preprocess_crash", "slow_model",
        "canary_drift",
    ]
    # submit hook: fires ONCE at the matching 1-based index
    p.on_serve_submit(1)
    p.on_serve_submit(2)
    with pytest.raises(RuntimeError, match="serve_exception on request 3"):
        p.on_serve_submit(3)
    p.on_serve_submit(3)  # fired flag: one-shot
    # preprocess hook
    p.on_serve_preprocess(4)
    with pytest.raises(RuntimeError,
                       match="preprocess_crash on request 5"):
        p.on_serve_preprocess(5)
    p.on_serve_preprocess(5)
    # model delay: base x factor, summed over armed slow_model faults
    assert p.serve_model_delay_s() == pytest.approx(0.02 * 4)
    assert p.canary_drift_armed()
    assert not FaultPlan("slow_model:factor=2").canary_drift_armed()
    assert FaultPlan("canary_drift").serve_model_delay_s() == 0.0


def test_worker_hang_straggler_modifiers(monkeypatch):
    """``s=``/``worker=`` turn the forever-hang into a bounded straggler
    restricted to one worker id — the decode-ahead speculation A/B's
    injection vehicle."""
    import time as _time

    p = FaultPlan("worker_hang@index=4@s=0.05@worker=1")
    f = p.faults[0]
    assert (f.index, f.seconds, f.worker) == (4, pytest.approx(0.05), 1)
    t0 = _time.monotonic()
    p.worker_decode_hook(worker_id=0, index=4)  # wrong worker: no hang
    p.worker_decode_hook(worker_id=1, index=3)  # wrong index: no hang
    assert _time.monotonic() - t0 < 0.04
    t0 = _time.monotonic()
    p.worker_decode_hook(worker_id=1, index=4)  # the straggler
    assert _time.monotonic() - t0 >= 0.05


def test_fault_plan_from_env(monkeypatch):
    monkeypatch.delenv("DPTPU_FAULT", raising=False)
    assert FaultPlan.from_env() is None
    monkeypatch.setenv("DPTPU_FAULT", "sigterm@step=5")
    plan = FaultPlan.from_env()
    assert plan is not None and plan.faults[0].step == 5


def test_ckpt_truncate_fault_fires_on_armed_save(tmp_path):
    plan = FaultPlan("ckpt_truncate@save=2")
    manager = CheckpointManager(directory=str(tmp_path), keep=5,
                                arch="toy", fault_plan=plan)
    p1 = manager.save_step(tiny_state(), epoch=0, step_in_epoch=1)
    p2 = manager.save_step(tiny_state(), epoch=0, step_in_epoch=2)
    ok1, _ = verify_checkpoint(p1)
    ok2, reason2 = verify_checkpoint(p2)
    assert ok1
    assert not ok2, reason2  # the armed (2nd) save was torn in place


# -- checkpoint integrity ----------------------------------------------------

def test_checkpoint_roundtrip_carries_resume_coordinates(tmp_path):
    state = tiny_state(2.5)
    path = save_checkpoint(
        state, epoch=3, arch="toy", best_acc1=12.5, is_best=False,
        directory=str(tmp_path), step_in_epoch=17, data_position=17 * 24,
    )
    ok, reason = verify_checkpoint(path)
    assert ok, reason
    new, meta = load_checkpoint(path, tiny_state(0.0))
    assert meta["epoch"] == 3
    assert meta["step_in_epoch"] == 17
    assert meta["data_position"] == 17 * 24
    np.testing.assert_array_equal(
        new.params["dense"]["kernel"], state.params["dense"]["kernel"]
    )


def test_bitflip_fails_checksum(tmp_path):
    path = save_checkpoint(tiny_state(), epoch=1, arch="toy", best_acc1=0.0,
                           is_best=False, directory=str(tmp_path))
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    ok, reason = verify_checkpoint(path)
    assert not ok and "checksum" in reason
    with pytest.raises(CorruptCheckpointError, match="checksum"):
        load_checkpoint(path, tiny_state())


def test_truncation_detected_even_without_footer(tmp_path):
    """Truncation removes the CRC footer too — the scanner must not
    mistake the stump for a healthy legacy (footerless) file."""
    path = save_checkpoint(tiny_state(), epoch=1, arch="toy", best_acc1=0.0,
                           is_best=False, directory=str(tmp_path))
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size // 2)
    ok, reason = verify_checkpoint(path)
    assert not ok


def test_empty_checkpoint_raises_filenotfound_subclass(tmp_path):
    path = str(tmp_path / "checkpoint.pth.tar")
    open(path, "wb").close()
    with pytest.raises(FileNotFoundError, match="empty"):
        load_checkpoint(path, tiny_state())
    with pytest.raises(EmptyCheckpointError):
        load_checkpoint(path, tiny_state())
    ok, reason = verify_checkpoint(path)
    assert not ok and "empty" in reason


def test_legacy_footerless_checkpoint_still_loads(tmp_path):
    """A pre-resilience file (no CRC footer, no resume coordinates) loads
    with defaulted coordinates — old runs keep resuming."""
    import jax
    from flax import serialization

    state = tiny_state(1.5)
    legacy_payload = {
        "epoch": 2,
        "arch": "toy",
        "best_acc1": 5.0,
        "step": jax.device_get(state.step),
        "params": jax.device_get(state.params),
        "batch_stats": {},
        "opt_state": jax.device_get(state.opt_state),
        "training_time": -1.0,
        "qkv_layout": "",
    }
    path = str(tmp_path / "checkpoint.pth.tar")
    open(path, "wb").write(serialization.to_bytes(legacy_payload))
    ok, reason = verify_checkpoint(path)
    assert ok and "legacy" in reason
    new, meta = load_checkpoint(path, tiny_state())
    assert meta["epoch"] == 2
    assert meta["step_in_epoch"] == 0  # defaulted: boundary semantics
    np.testing.assert_array_equal(
        new.params["dense"]["kernel"], state.params["dense"]["kernel"]
    )


# -- rotation + fallback -----------------------------------------------------

def test_rotation_keeps_last_k(tmp_path):
    manager = CheckpointManager(directory=str(tmp_path), keep=2, arch="toy")
    for step in range(1, 5):
        manager.save_step(tiny_state(float(step)), epoch=0,
                          step_in_epoch=step)
    names = sorted(f for f in os.listdir(tmp_path) if "checkpoint-e" in f)
    assert names == [step_checkpoint_name(0, 3), step_checkpoint_name(0, 4)]


def test_find_resumable_falls_back_past_corrupt(tmp_path):
    manager = CheckpointManager(directory=str(tmp_path), keep=3, arch="toy")
    paths = [
        manager.save_step(tiny_state(float(s)), epoch=0, step_in_epoch=s)
        for s in (1, 2, 3)
    ]
    assert find_resumable(str(tmp_path)) == paths[-1]
    with open(paths[-1], "r+b") as f:  # tear the newest
        f.truncate(os.path.getsize(paths[-1]) // 2)
    assert find_resumable(str(tmp_path)) == paths[-2]
    # an explicitly-named corrupt FILE also falls back to its siblings
    assert find_resumable(paths[-1]) == paths[-2]
    # resume coordinates of the survivor point at step 2
    _, meta = load_checkpoint(find_resumable(str(tmp_path)), tiny_state())
    assert meta["step_in_epoch"] == 2


def test_find_resumable_missing_paths(tmp_path):
    assert find_resumable(str(tmp_path / "nope.pth.tar")) is None
    assert find_resumable(str(tmp_path)) is None  # empty dir


# -- preemption guard --------------------------------------------------------

def test_preemption_guard_catches_sigterm_and_restores_handler():
    before = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard() as guard:
        assert not guard.requested
        os.kill(os.getpid(), signal.SIGTERM)
        for _ in range(10000):  # let the Python-level handler run
            if guard.requested:
                break
        assert guard.requested
        assert guard.signal_name == "SIGTERM"
    assert signal.getsignal(signal.SIGTERM) is before


def test_preemption_guard_second_signal_aborts():
    with PreemptionGuard() as guard:
        guard._handler(signal.SIGTERM, None)
        assert guard.requested
        with pytest.raises(KeyboardInterrupt):
            guard._handler(signal.SIGTERM, None)


# -- async checkpoint writer -------------------------------------------------

def test_async_saves_land_identical_in_order_and_rotate(tmp_path):
    """Cadence saves through the writer thread must produce the same
    verifiable files a synchronous manager writes, in submission order,
    with rotation applied."""
    from dptpu.train.checkpoint import AsyncCheckpointWriter

    w = AsyncCheckpointWriter()
    manager = CheckpointManager(directory=str(tmp_path), keep=2,
                                batch_size=4, async_writer=w)
    paths = [
        manager.save_step(tiny_state(float(s)), epoch=0, step_in_epoch=s)
        for s in (1, 2, 3)
    ]
    w.flush()
    # rotation kept the newest two; every survivor verifies and carries
    # its exact resume coordinates
    assert not os.path.exists(paths[0])
    for s, p in zip((2, 3), paths[1:]):
        ok, reason = verify_checkpoint(p)
        assert ok, reason
        restored, meta = load_checkpoint(p, tiny_state())
        assert meta["step_in_epoch"] == s
        assert meta["data_position"] == s * 4
        np.testing.assert_array_equal(
            restored.params["dense"]["kernel"],
            tiny_state(float(s)).params["dense"]["kernel"],
        )
    w.close()


def test_sync_save_drains_queue_first_so_newest_wins(tmp_path):
    """A preemption/emergency save (sync=True) must flush queued async
    saves before writing, so the newest-mtime file — what find_resumable
    trusts — is the true latest position."""
    from dptpu.train.checkpoint import AsyncCheckpointWriter

    w = AsyncCheckpointWriter()
    manager = CheckpointManager(directory=str(tmp_path), keep=3,
                                async_writer=w)
    manager.save_step(tiny_state(1.0), epoch=0, step_in_epoch=1)
    final = manager.save_step(tiny_state(2.0), epoch=0, step_in_epoch=2,
                              sync=True)
    assert os.path.exists(final)  # durable the moment the call returns
    assert find_resumable(str(tmp_path), verbose=False) == final
    w.close()


def test_async_write_error_surfaces_on_next_call(tmp_path):
    """A failed background write must fail the run loudly on the next
    checkpoint call — never vanish."""
    from dptpu.train.checkpoint import AsyncCheckpointWriter

    w = AsyncCheckpointWriter()
    # a write closure that raises — the manager enqueues through the
    # identical submit path
    w.submit(lambda: (_ for _ in ()).throw(OSError("disk on fire")))
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        w.flush()
    # the writer recovers: later saves work again
    ok_dir = tmp_path / "ok"
    manager2 = CheckpointManager(directory=str(ok_dir), keep=2,
                                 async_writer=w)
    p = manager2.save_step(tiny_state(), epoch=0, step_in_epoch=1)
    w.flush()
    assert os.path.exists(p)
    w.close()


def test_ckpt_truncate_fault_counts_async_writes_in_order(tmp_path):
    """The ckpt_truncate@save=N fault hook rides the writer thread, so
    'the N-th checkpoint written' keeps meaning write order under async
    saves."""
    from dptpu.train.checkpoint import AsyncCheckpointWriter

    plan = FaultPlan("ckpt_truncate@save=2")
    w = AsyncCheckpointWriter()
    manager = CheckpointManager(directory=str(tmp_path), keep=3,
                                fault_plan=plan, async_writer=w)
    p1 = manager.save_step(tiny_state(1.0), epoch=0, step_in_epoch=1)
    p2 = manager.save_step(tiny_state(2.0), epoch=0, step_in_epoch=2)
    w.flush()
    ok1, _ = verify_checkpoint(p1)
    ok2, reason2 = verify_checkpoint(p2)
    assert ok1
    assert not ok2, "save #2 should have been torn by the fault"
    w.close()
