"""Unified partition-rules engine units (dptpu/parallel/rules.py +
dptpu/analysis/partition.py): the ordered regex → PartitionSpec matcher,
its consumer-side projections (pure TP, ZeRO-3/FSDP), the table
fingerprints the checkpoint sharding stamp carries, and the ``dptpu
check`` partition-rules gate.

The TP-equivalence locks here deliberately RE-STATE the expected specs
by hand: ``vit_tp_specs`` et al. are now projections of the same tables,
so comparing them against ``match_partition_rules`` would be circular —
the hand-written expectations are the independent truth (same style as
tests/test_gspmd.py's vit locks, extended to swin v2 and convnext)."""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from dptpu.models import create_model
from dptpu.models.registry import (
    CONVNEXT_RULES,
    FAMILY_RULES,
    GENERIC_RULES,
    SWIN_RULES,
    VIT_RULES,
    partition_family,
    partition_rules_for_arch,
)
from dptpu.parallel.rules import (
    AUTO_FSDP,
    clamp_spec,
    fsdp_auto_spec,
    match_partition_rules,
    project_spec,
    rule_match_counts,
    rules_fingerprint,
    validate_rules,
)


def _shaped_params(arch, px=64):
    """Shape-only param tree (nothing allocated) — matching and
    projection need paths and shapes, not values."""
    model = create_model(arch, num_classes=8)
    shaped = jax.eval_shape(
        lambda r, x: model.init(r, x, train=False),
        jax.random.PRNGKey(0), jnp.zeros((1, px, px, 3), jnp.float32),
    )
    return shaped["params"]


# ---------------------------------------------------------------- matcher


def test_validate_rules_rejects_malformed_tables():
    with pytest.raises(ValueError, match="empty"):
        validate_rules(())
    with pytest.raises(ValueError, match="fallback"):
        validate_rules(((r"kernel$", P("model")),))  # no trailing .*
    with pytest.raises(ValueError, match="does not compile"):
        validate_rules(((r"(unclosed", P()), (r".*", AUTO_FSDP)))
    with pytest.raises(ValueError, match="PartitionSpec or AUTO_FSDP"):
        validate_rules(((r".*", "data"),))


def test_first_match_wins_in_declaration_order():
    params = {"block": {"kernel": jnp.zeros((4, 4))}}
    rules = (
        (r"kernel$", P("model")),
        (r"block/kernel$", P("data")),  # also matches, but comes later
        (r".*", AUTO_FSDP),
    )
    specs = match_partition_rules(rules, params)
    assert specs["block"]["kernel"] == P("model")
    # and the census sees the same claim order
    assert rule_match_counts(rules, params) == [1, 0, 0]


def test_anchored_segments_do_not_claim_suffix_modules():
    # the (^|/) anchor: a rule for `proj` must not claim `out_proj`
    params = {
        "proj": {"kernel": jnp.zeros((4, 4))},
        "out_proj": {"kernel": jnp.zeros((4, 4))},
    }
    rules = ((r"(^|/)proj/kernel$", P("model", None)), (r".*", AUTO_FSDP))
    specs = match_partition_rules(rules, params)
    assert specs["proj"]["kernel"] == P("model", None)
    assert specs["out_proj"]["kernel"] != P("model", None)


def test_strict_dead_raises_and_census_counts():
    params = {"mlp": {"kernel": jnp.zeros((8, 8))}}
    rules = (
        (r"(^|/)nonexistent/kernel$", P("data", "model")),
        (r".*", AUTO_FSDP),
    )
    assert rule_match_counts(rules, params) == [0, 1]
    with pytest.raises(ValueError, match="dead partition rule"):
        match_partition_rules(rules, params, strict_dead=True)
    # the .* fallback itself is exempt from strictness
    match_partition_rules(GENERIC_RULES, params, strict_dead=True)


# ------------------------------------------------------------ projections


def test_tp_projection_grammar_truth_table():
    """The grammar's pure-TP projections (keep only ``model``) — the
    exact equivalences the registry tables rely on."""
    keep = ("model",)
    assert project_spec(P("data", "model"), keep) == P(None, "model")
    assert project_spec(P(("data", "model")), keep) == P("model")
    assert project_spec(P("model", "data"), keep) == P("model", None)
    assert project_spec(P("data"), keep) == P()
    assert project_spec(P(), keep) == P()


def test_fsdp_projection_grammar_truth_table():
    keep = ("data",)
    assert project_spec(P("data", "model"), keep) == P("data", None)
    assert project_spec(P(("data", "model")), keep) == P("data")
    assert project_spec(P("model", "data"), keep) == P(None, "data")
    assert project_spec(P("data"), keep) == P("data")


def test_clamp_degrades_undivisible_dims_to_replicated():
    # 6 % 4 != 0: the data entry drops; 8 % 4 == 0: it stays
    assert clamp_spec(P("data", None), (6, 16), {"data": 4}) == P()
    assert clamp_spec(P("data", None), (8, 16), {"data": 4}) \
        == P("data", None)
    # compound entries drop members from the END until the product fits
    assert clamp_spec(P(("data", "model")), (8,), {"data": 4, "model": 4}) \
        == P("data")
    assert clamp_spec(P(("data", "model")), (16,), {"data": 4, "model": 4}) \
        == P(("data", "model"))


def test_auto_fsdp_resolution():
    # largest evenly-divisible dim takes the data axis...
    assert fsdp_auto_spec((3, 64, 64, 128), 8) == P(None, None, None, "data")
    # ...ties/none-dividing degrade to replicated
    assert fsdp_auto_spec((3, 3), 8) == P()
    # and under a pure-TP projection AUTO_FSDP resolves to replicated
    params = {"conv": {"kernel": jnp.zeros((64, 128))}}
    specs = match_partition_rules(GENERIC_RULES, params,
                                  keep_axes=("model",))
    assert specs["conv"]["kernel"] == P()
    # with the data axis kept + clamped, it IS the ZeRO shard layout
    specs = match_partition_rules(GENERIC_RULES, params,
                                  keep_axes=("data",), clamp={"data": 8})
    assert specs["conv"]["kernel"] == P(None, "data")


# ------------------------------------- family tables: serve-TP equivalence


def test_vit_rules_project_to_locked_tp_specs():
    params = _shaped_params("vit_b_32")
    specs = match_partition_rules(VIT_RULES, params, keep_axes=("model",))
    layer = specs["encoder"]["encoder_layer_0"]
    assert layer["mlp_1"]["kernel"] == P(None, "model")
    assert layer["mlp_1"]["bias"] == P("model")
    assert layer["mlp_2"]["kernel"] == P("model", None)
    assert layer["mlp_2"]["bias"] == P()
    attn = layer["self_attention"]
    assert attn["in_proj"]["kernel"] == P(None, "model")
    assert attn["in_proj"]["bias"] == P("model")
    assert attn["out_proj"]["kernel"] == P("model", None)
    assert attn["out_proj"]["bias"] == P()
    assert specs["conv_proj"]["kernel"] == P()


def test_swin_v2_rules_project_to_locked_tp_specs():
    params = _shaped_params("swin_v2_t", px=64)
    specs = match_partition_rules(SWIN_RULES, params, keep_axes=("model",))
    flat = jax.tree_util.tree_flatten_with_path(specs)[0]
    by_path = {
        "/".join(str(k.key) for k in path): spec for path, spec in flat
    }
    qkv_k = [p for p in by_path if p.endswith("qkv/kernel")]
    proj_k = [p for p in by_path if p.endswith("proj/kernel")
              and "cpb" not in p]
    scale = [p for p in by_path if p.endswith("logit_scale")]
    cpb2 = [p for p in by_path if p.endswith("cpb_mlp_2/kernel")]
    assert qkv_k and proj_k and scale and cpb2  # v2 carries all four
    for p in qkv_k:
        assert by_path[p] == P(None, "model")
    for p in proj_k:
        assert by_path[p] == P("model", None)
    for p in scale:
        assert by_path[p] == P("model")
    for p in cpb2:
        assert by_path[p] == P(None, "model")


def test_convnext_rules_project_to_locked_tp_specs():
    params = _shaped_params("convnext_tiny")
    specs = match_partition_rules(CONVNEXT_RULES, params,
                                  keep_axes=("model",))
    flat = jax.tree_util.tree_flatten_with_path(specs)[0]
    by_path = {
        "/".join(str(k.key) for k in path): spec for path, spec in flat
    }
    m1 = [p for p in by_path if p.endswith("mlp_1/kernel")]
    m2 = [p for p in by_path if p.endswith("mlp_2/kernel")]
    assert m1 and m2
    for p in m1:
        assert by_path[p] == P(None, "model")
    for p in m2:
        assert by_path[p] == P("model", None)
    # dwconv / norms / stem stay replicated under pure TP
    for p, spec in by_path.items():
        if "mlp_" not in p:
            assert spec == P(), f"{p} unexpectedly sharded: {spec}"


def test_one_table_yields_tp_and_fsdp_views():
    """THE tentpole property: the same VIT declaration projects to the
    pure-TP placement AND the ZeRO-3/FSDP layout — placements cannot
    drift because both are views of one table."""
    params = _shaped_params("vit_b_32")
    tp = match_partition_rules(VIT_RULES, params, keep_axes=("model",))
    fsdp = match_partition_rules(VIT_RULES, params, keep_axes=("data",),
                                 clamp={"data": 8})
    layer_tp = tp["encoder"]["encoder_layer_0"]
    layer_fs = fsdp["encoder"]["encoder_layer_0"]
    assert layer_tp["mlp_1"]["kernel"] == P(None, "model")
    assert layer_fs["mlp_1"]["kernel"] == P("data", None)
    assert layer_tp["mlp_1"]["bias"] == P("model")
    assert layer_fs["mlp_1"]["bias"] == P("data")
    # and the generic fallback resolves per-view too (AUTO_FSDP)
    assert tp["conv_proj"]["kernel"] == P()
    assert layer_fs["mlp_2"]["bias"] == P("data")


# ------------------------------------------------- fingerprints + registry


def test_rules_fingerprint_stable_and_sensitive():
    fp = rules_fingerprint(VIT_RULES)
    assert fp == rules_fingerprint(VIT_RULES)
    assert len(fp) == 12 and fp != rules_fingerprint(SWIN_RULES)
    edited = ((r"(^|/)in_proj/kernel$", P("model", "data")),) + VIT_RULES[1:]
    assert rules_fingerprint(edited) != fp


def test_partition_family_env_override(monkeypatch):
    assert partition_family("resnet18") == "generic"
    assert partition_family("vit_b_32") == "vit"
    monkeypatch.setenv("DPTPU_RULES", "vit")
    assert partition_family("resnet18") == "vit"
    assert partition_rules_for_arch("resnet18") is VIT_RULES
    monkeypatch.setenv("DPTPU_RULES", "bogus")
    with pytest.raises(ValueError, match="DPTPU_RULES"):
        partition_family("resnet18")


def test_every_family_table_is_well_formed():
    for family, rules in FAMILY_RULES.items():
        validate_rules(rules)
        assert rules[-1][0] == ".*", family


# ------------------------------------------- dptpu check: partition-rules


def test_partition_check_clean_on_repo_tables():
    from dptpu.analysis.partition import (
        check_partition_rules,
        partition_summary,
    )

    violations = check_partition_rules()
    assert violations == []
    summary = partition_summary(violations)
    assert summary["ok"] is True
    assert summary["fingerprints"]["generic"] \
        == rules_fingerprint(GENERIC_RULES)


def test_partition_check_flags_dead_rule_and_fallback_only(monkeypatch):
    from dptpu.analysis import partition as partition_mod
    from dptpu.models import registry as registry_mod

    dead_table = (
        (r"(^|/)no_such_module/kernel$", P("data", "model")),
        (r".*", AUTO_FSDP),
    )
    monkeypatch.setattr(registry_mod, "FAMILY_RULES",
                        {"generic": dead_table})
    monkeypatch.setattr(partition_mod, "FAMILY_REPRESENTATIVES",
                        {"generic": ("resnet18",)})
    violations = partition_mod.check_partition_rules()
    msgs = [v.format() for v in violations]
    assert any("dead rule" in m and "no_such_module" in m for m in msgs)
    assert any("fallback-only" in m for m in msgs)


def test_partition_check_flags_non_mesh_axis(monkeypatch):
    from dptpu.analysis import partition as partition_mod
    from dptpu.models import registry as registry_mod

    typo_table = (
        (r"(^|/)conv1/kernel$", P("modle")),  # typo'd axis
        (r".*", AUTO_FSDP),
    )
    monkeypatch.setattr(registry_mod, "FAMILY_RULES",
                        {"generic": typo_table})
    monkeypatch.setattr(partition_mod, "FAMILY_REPRESENTATIVES",
                        {"generic": ("resnet18",)})
    violations = partition_mod.check_partition_rules()
    assert any("non-mesh axes" in v.format() and "modle" in v.format()
               for v in violations)
