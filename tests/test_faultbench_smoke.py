"""Tier-1 smoke of scripts/run_faultbench.py --smoke (the obsbench /
commbench pattern): the elastic pod-lifecycle chaos gates — shrink-
resume remainder exactness, quorum pod-consistency, straggler re-split
engagement — run continuously, not just on the bench host, so they can
never silently rot. One subprocess, smallest preset, same gate logic as
the committed FAULTBENCH.json."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_faultbench_smoke_gates(tmp_path):
    out = str(tmp_path / "FAULTBENCH.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # run on the REAL single-CPU topology (the obsbench-smoke
    # precedent): the chaos contract under test — determinism across
    # preemption/remap — is topology-independent, and the fake 8-device
    # pod the conftest forces would only multiply compile time
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "run_faultbench.py"),
         "--smoke", "--out", out],
        capture_output=True, text=True, timeout=540, env=env,
        cwd=str(tmp_path),
    )
    assert proc.returncode == 0, (
        f"faultbench gate failed\nstdout:\n{proc.stdout[-4000:]}\n"
        f"stderr:\n{proc.stderr[-4000:]}"
    )
    with open(out) as f:
        bench = json.load(f)
    assert bench["smoke"] is True
    by_name = {s["name"]: s for s in bench["scenarios"]}
    assert sorted(by_name) == ["lost_host", "shrink_resume",
                               "sigterm_one_host", "slow_host"]
    assert bench["all_ok"], by_name

    # shrink-resume: the untrained remainder replays EXACTLY — the
    # visited-index set difference is empty and the elastic replay is
    # bit-identical to its same-geometry replay reference
    sr = by_name["shrink_resume"]
    assert sr["index_set_delta"] == 0
    assert sr["replay_params_max_delta"] == 0.0
    assert sr["replay_max_abs_dloss"] == 0.0
    assert sr["elastic"]["consumed"] == \
        sr["elastic"]["resume_step"] * sr["elastic"]["new_geometry"][1]

    # lost-host: the gone-for-good verdict saved at the exact position
    # and the elastic resume engaged with the same exactness
    lh = by_name["lost_host"]
    assert lh["host_lost"] and lh["preempted"]
    assert lh["index_set_delta"] == 0

    # quorum one-host save: the protocol record proves pod-consistency
    # (agreed step == the step the checkpoint names, not degraded) and
    # the same-geometry resume is bit-identical to the baseline
    q = by_name["sigterm_one_host"]
    assert q["quorum"]["agreed_step"] is not None
    assert not q["quorum"]["degraded"]
    assert f"s{q['quorum']['agreed_step']:06d}" in q["resumed_from"]
    assert q["bit_identical"]

    # slow-host: re-split ENGAGED (resplit + reissue counters moved)
    # and the straggler never cost bit-identity
    sh = by_name["slow_host"]
    assert sh["resplits"] > 0
    assert sh["straggler_reissues"] > 0
    assert sh["bit_identical"]
