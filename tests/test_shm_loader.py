"""Process-mode (shared-memory ring) loader: parity, cache, errors.

The contract under test (dptpu/data/shm.py + loader.py): for the same
``(seed, epoch, index)`` RNG, ``workers_mode="process"`` must yield
BATCHES BIT-IDENTICAL to thread mode — same pixels, labels, pad/mask
semantics — because workers run the exact same span-decode path, only
into shared memory instead of a same-process array. A worker decode
error must surface as a parent-side exception carrying the worker's
traceback, never a hang.

JPEG fixtures are 52×44 (< 48·8/7): the native scale picker then stays
at full resolution, which also makes cache-on/off comparisons bit-exact
(see ImageFolderDataset docstring).
"""

import numpy as np
import pytest
from PIL import Image

from dptpu.data import (
    DataLoader,
    ImageFolderDataset,
    train_transform,
)


@pytest.fixture(scope="module")
def jpeg_folder(tmp_path_factory):
    root = tmp_path_factory.mktemp("shmjpeg")
    rng = np.random.RandomState(0)
    for cls in ["c0", "c1"]:
        d = root / cls
        d.mkdir()
        for i in range(9):
            low = rng.randint(0, 255, (8, 7, 3), np.uint8)
            img = Image.fromarray(low).resize((52, 44), Image.BILINEAR)
            img.save(str(d / f"{i}.jpg"), quality=85)
    return str(root)


class CrashAtFive:
    """Decode-error fixture — module level so spawn can pickle it."""

    def __len__(self):
        return 12

    def get(self, index, rng=None):
        if index == 5:
            raise ValueError("decode exploded on sample 5")
        return np.full((8, 8, 3), index, np.uint8), index

    def get_into(self, index, rng, out):
        img, lab = self.get(index, rng)
        np.copyto(out, img)
        return lab

    def __getitem__(self, index):
        return self.get(index)


def _assert_batches_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x["images"], y["images"])
        np.testing.assert_array_equal(x["labels"], y["labels"])
        assert ("mask" in x) == ("mask" in y)
        if "mask" in x:
            np.testing.assert_array_equal(x["mask"], y["mask"])


def test_process_loader_bit_identical_to_thread(jpeg_folder):
    ds = ImageFolderDataset(jpeg_folder, train_transform(48))  # 18 samples
    th = DataLoader(ds, 4, num_workers=2, seed=5)
    pr = DataLoader(ds, 4, num_workers=2, seed=5, workers_mode="process")
    try:
        for epoch in (0, 1):
            a, b = list(th.epoch(epoch)), list(pr.epoch(epoch))
            assert len(a) == 5  # ceil(18/4): padded+masked tail included
            _assert_batches_equal(a, b)
        # abandoning an epoch mid-flight must not wedge the slot ring
        it = pr.epoch(2)
        next(it)
        del it
        _assert_batches_equal(list(th.epoch(3)), list(pr.epoch(3)))
    finally:
        th.close()
        pr.close()


def test_process_loader_cache_parity_and_stats(jpeg_folder):
    """Per-worker decode caches change nothing about the pixels (hit and
    miss resample the same decoded buffer) and aggregate into
    ``feed_stats`` through the done-message piggyback."""
    ds_th = ImageFolderDataset(jpeg_folder, train_transform(48),
                               cache_bytes=32 << 20)
    ds_pr = ImageFolderDataset(jpeg_folder, train_transform(48),
                               cache_bytes=32 << 20)
    th = DataLoader(ds_th, 4, num_workers=2, seed=5)
    pr = DataLoader(ds_pr, 4, num_workers=2, seed=5,
                    workers_mode="process")
    try:
        for epoch in (0, 1):
            _assert_batches_equal(list(th.epoch(epoch)),
                                  list(pr.epoch(epoch)))
        fs = pr.feed_stats()
        assert fs["workers_mode"] == "process"
        assert fs["cache_hits"] > 0
        assert 0.0 < fs["cache_hit_rate"] <= 1.0
    finally:
        th.close()
        pr.close()


def test_worker_decode_error_propagates_with_traceback():
    loader = DataLoader(CrashAtFive(), 4, num_workers=2, seed=0,
                        workers_mode="process")
    try:
        with pytest.raises(RuntimeError, match="decode exploded on sample 5"):
            list(loader.epoch(0))
    finally:
        loader.close()


def test_invalid_workers_mode_rejected():
    with pytest.raises(ValueError, match="workers_mode"):
        DataLoader(CrashAtFive(), 4, workers_mode="greenlet")
