"""Process-mode (shared-memory ring) loader: parity, cache, errors.

The contract under test (dptpu/data/shm.py + loader.py): for the same
``(seed, epoch, index)`` RNG, ``workers_mode="process"`` must yield
BATCHES BIT-IDENTICAL to thread mode — same pixels, labels, pad/mask
semantics — because workers run the exact same span-decode path, only
into shared memory instead of a same-process array. A worker decode
error must surface as a parent-side exception carrying the worker's
traceback, never a hang.

JPEG fixtures are 52×44 (< 48·8/7): the native scale picker then stays
at full resolution, which also makes cache-on/off comparisons bit-exact
(see ImageFolderDataset docstring).
"""

import numpy as np
import pytest
from PIL import Image

from dptpu.data import (
    DataLoader,
    ImageFolderDataset,
    train_transform,
)


@pytest.fixture(scope="module")
def jpeg_folder(tmp_path_factory):
    root = tmp_path_factory.mktemp("shmjpeg")
    rng = np.random.RandomState(0)
    for cls in ["c0", "c1"]:
        d = root / cls
        d.mkdir()
        for i in range(9):
            low = rng.randint(0, 255, (8, 7, 3), np.uint8)
            img = Image.fromarray(low).resize((52, 44), Image.BILINEAR)
            img.save(str(d / f"{i}.jpg"), quality=85)
    return str(root)


class CrashAtFive:
    """Decode-error fixture — module level so spawn can pickle it."""

    def __len__(self):
        return 12

    def get(self, index, rng=None):
        if index == 5:
            raise ValueError("decode exploded on sample 5")
        return np.full((8, 8, 3), index, np.uint8), index

    def get_into(self, index, rng, out):
        img, lab = self.get(index, rng)
        np.copyto(out, img)
        return lab

    def __getitem__(self, index):
        return self.get(index)


def _assert_batches_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x["images"], y["images"])
        np.testing.assert_array_equal(x["labels"], y["labels"])
        assert ("mask" in x) == ("mask" in y)
        if "mask" in x:
            np.testing.assert_array_equal(x["mask"], y["mask"])


def test_process_loader_bit_identical_to_thread(jpeg_folder):
    ds = ImageFolderDataset(jpeg_folder, train_transform(48))  # 18 samples
    th = DataLoader(ds, 4, num_workers=2, seed=5)
    pr = DataLoader(ds, 4, num_workers=2, seed=5, workers_mode="process")
    try:
        for epoch in (0, 1):
            a, b = list(th.epoch(epoch)), list(pr.epoch(epoch))
            assert len(a) == 5  # ceil(18/4): padded+masked tail included
            _assert_batches_equal(a, b)
        # abandoning an epoch mid-flight must not wedge the slot ring
        it = pr.epoch(2)
        next(it)
        del it
        _assert_batches_equal(list(th.epoch(3)), list(pr.epoch(3)))
    finally:
        th.close()
        pr.close()


def test_process_loader_cache_parity_and_stats(jpeg_folder):
    """Per-worker decode caches change nothing about the pixels (hit and
    miss resample the same decoded buffer) and aggregate into
    ``feed_stats`` through the done-message piggyback."""
    ds_th = ImageFolderDataset(jpeg_folder, train_transform(48),
                               cache_bytes=32 << 20)
    ds_pr = ImageFolderDataset(jpeg_folder, train_transform(48),
                               cache_bytes=32 << 20)
    th = DataLoader(ds_th, 4, num_workers=2, seed=5)
    pr = DataLoader(ds_pr, 4, num_workers=2, seed=5,
                    workers_mode="process")
    try:
        for epoch in (0, 1):
            _assert_batches_equal(list(th.epoch(epoch)),
                                  list(pr.epoch(epoch)))
        fs = pr.feed_stats()
        assert fs["workers_mode"] == "process"
        assert fs["cache_hits"] > 0
        assert 0.0 < fs["cache_hit_rate"] <= 1.0
    finally:
        th.close()
        pr.close()


def test_worker_decode_error_propagates_with_traceback():
    loader = DataLoader(CrashAtFive(), 4, num_workers=2, seed=0,
                        workers_mode="process")
    try:
        with pytest.raises(RuntimeError, match="decode exploded on sample 5"):
            list(loader.epoch(0))
    finally:
        loader.close()


def test_invalid_workers_mode_rejected():
    with pytest.raises(ValueError, match="workers_mode"):
        DataLoader(CrashAtFive(), 4, workers_mode="greenlet")


# -- consumer-leased zero-copy slots ---------------------------------------

def test_leased_slot_not_recycled_while_put_in_flight(jpeg_folder):
    """The lease-lifetime contract: with a SLOW ``put`` (simulating the
    device transfer) the ring must not recycle the leased slot — the
    batch bytes read after the sleep must equal thread mode's, bit for
    bit, and the parent must have copied nothing."""
    import time

    from dptpu.data import DevicePrefetcher

    ds = ImageFolderDataset(jpeg_folder, train_transform(48))
    th = DataLoader(ds, 4, num_workers=2, seed=5)
    pr = DataLoader(ds, 4, num_workers=2, seed=5, workers_mode="process",
                    leased=True)
    try:
        ref = list(th.epoch(0))

        def slow_put(batch):
            # while we sleep, the loader keeps submitting ahead — only
            # the lease protocol stops a worker from overwriting these
            # exact rows before we read them
            time.sleep(0.1)
            return {k: np.array(v) for k, v in batch.items()}

        got = list(DevicePrefetcher(pr.epoch(0), put=slow_put,
                                    copy_before_put=False))
        _assert_batches_equal(ref, got)
        fs = pr.feed_stats()
        assert fs["leased"] is True
        assert fs["bytes_copied_per_batch"] == 0.0
        # epoch 2: the ring and its leases recycle cleanly
        _assert_batches_equal(
            list(th.epoch(1)),
            list(DevicePrefetcher(pr.epoch(1), put=slow_put,
                                  copy_before_put=False)),
        )
    finally:
        th.close()
        pr.close()


def test_leased_through_real_jax_put_bit_identical(jpeg_folder):
    """End-to-end through jax.device_put: on the CPU test backend the
    prefetcher must detect host-buffer aliasing and defend (copy before
    put); batches on 'device' must match thread mode after the ring has
    long recycled the slots."""
    import jax

    from dptpu.data import DevicePrefetcher

    ds = ImageFolderDataset(jpeg_folder, train_transform(48))
    th = DataLoader(ds, 4, num_workers=2, seed=9)
    pr = DataLoader(ds, 4, num_workers=2, seed=9, workers_mode="process",
                    leased=True)
    try:
        ref = list(th.epoch(0))
        dev = list(DevicePrefetcher(pr.epoch(0), put=jax.device_put))
        assert len(ref) == len(dev)
        for a, b in zip(ref, dev):
            np.testing.assert_array_equal(a["images"],
                                          np.asarray(b["images"]))
            np.testing.assert_array_equal(a["labels"],
                                          np.asarray(b["labels"]))
            assert "_lease" not in b  # the prefetcher consumed the token
    finally:
        th.close()
        pr.close()


def test_lease_release_is_idempotent_and_generation_checked():
    from dptpu.data import SyntheticDataset

    ds = SyntheticDataset(24, 8, 10)
    pr = DataLoader(ds, 8, num_workers=2, seed=0, workers_mode="process",
                    leased=True)
    try:
        it = pr.epoch(0)
        b0 = next(it)
        lease = b0["_lease"]
        lease.release()
        lease.release()  # double release: no-op
        rest = list(it)  # backstop releases ride the generator
        assert len(rest) == 2
        lease.release()  # stale (slot long recycled): generation no-op
        # the ring is fully free again: a fresh epoch works
        assert len(list(pr.epoch(1))) == 3
    finally:
        pr.close()


def test_affinity_spans_cover_batch_and_balance():
    from dptpu.data.shm import _affinity_of, _affinity_spans

    idxs = list(range(1000, 1064))
    spans = _affinity_spans(idxs, 4)
    seen = {}
    for wid, offsets, span_idxs in spans:
        assert len(offsets) == len(span_idxs)
        assert len(offsets) <= -(-64 // 4)  # rebalanced to cap
        for o, i in zip(offsets, span_idxs):
            assert o not in seen
            seen[o] = (wid, i)
    assert sorted(seen) == list(range(64))  # every row exactly once
    assert sorted(i for _, i in seen.values()) == idxs
    # determinism: the same index routes to the same worker every time
    assert _affinity_spans(idxs, 4) == spans
    for i in idxs:
        assert _affinity_of(i, 4) == _affinity_of(i, 4)


def test_shard_affinity_routes_whole_shard_to_one_worker():
    """Shard-level decode-cache affinity (ISSUE 10 satellite): with an
    ``affinity_key`` (a packed-shard dataset's ``shard_of``), every
    sample of one shard hashes to the SAME worker — stable in the
    SHARD id, so the routing survives any sampler reshuffle — up to
    the ceil(B/N) rebalance cap (utilization still beats affinity for
    overflow)."""
    from dptpu.data.shm import _affinity_of, _affinity_spans

    shard_of = lambda i: i // 16  # noqa: E731 — 16-sample shards
    # pick 4 shards that hash to 4 DISTINCT workers (no collision, so
    # no rebalance overflow): each worker gets exactly ceil(B/N) and
    # every shard must stay whole
    shards, targets = [], set()
    for s in range(64):
        w = _affinity_of(s, 4)
        if w not in targets:
            targets.add(w)
            shards.append(s)
        if len(shards) == 4:
            break
    idxs = [s * 16 + j for j in range(8) for s in shards]  # interleaved
    spans = _affinity_spans(idxs, 4, shard_of)
    worker_of = {}
    for wid, offsets, span_idxs in spans:
        assert len(offsets) <= -(-len(idxs) // 4)  # rebalance cap holds
        for i in span_idxs:
            worker_of[i] = wid
    assert sorted(worker_of) == sorted(idxs)
    for s in shards:
        workers = {worker_of[s * 16 + j] for j in range(8)}
        assert workers == {_affinity_of(s, 4)}  # whole shard, one worker
    # with hash collisions the ceil(B/N) rebalance may split ONLY the
    # overflow (utilization beats affinity there): cap still holds and
    # non-overflowing shards stay whole
    mixed = [s * 16 + j for j in range(8) for s in range(8)]
    mixed_spans = _affinity_spans(mixed, 4, shard_of)
    loads = {}
    for s in range(8):
        loads.setdefault(_affinity_of(s, 4), []).append(s)
    whole = {i: w for w, offs, sidx in mixed_spans for i, w in
             zip(sidx, [w] * len(sidx))}
    for w, ss in loads.items():
        if len(ss) * 8 <= -(-64 // 4):  # this worker never overflowed
            for s in ss:
                assert {whole[s * 16 + j] for j in range(8)} == {w}
    # and the grouping is BY SHARD, not by index: two samples of one
    # shard with very different indices share a worker pre-rebalance
    for s in range(8):
        assert _affinity_of(shard_of(s * 16), 4) == \
            _affinity_of(shard_of(s * 16 + 7), 4)


def test_feed_stats_records_span_routing(tmp_path):
    """The routing mode is observable: ``span_routing`` reads "shard"
    for a dataset exposing shard_of, "index" otherwise, "contiguous"
    with affinity off — before AND after the lazy pipeline exists."""
    from dptpu.data.loader import DataLoader
    from dptpu.data.sampler import ShardedSampler

    class _FakeShardDS:
        """Minimal dataset surface; never decoded (no epochs run)."""

        def __len__(self):
            return 32

        def shard_of(self, i):
            return i // 8

    class _FakeDS:
        def __len__(self):
            return 32

    for ds, affinity, expect in (
        (_FakeShardDS(), True, "shard"),
        (_FakeDS(), True, "index"),
        (_FakeShardDS(), False, "contiguous"),
    ):
        dl = DataLoader(
            ds, 8, sampler=ShardedSampler(32, shuffle=False),
            num_workers=2, workers_mode="process",
            span_affinity=affinity,
        )
        try:
            assert dl.feed_stats()["span_routing"] == expect
        finally:
            dl.close()


def test_degrade_to_thread_with_leases_held(monkeypatch):
    """A pool that hangs past its restart budget must degrade to thread
    mode even mid-leased-epoch: the retiring pipeline tolerates the
    consumer's outstanding views (BufferError-safe close, generation-
    checked lease release) and the thread path re-decodes the unyielded
    tail — batches stay bit-identical across the hand-off."""
    from dptpu.data import DevicePrefetcher, SyntheticDataset

    monkeypatch.setenv("DPTPU_FAULT", "worker_hang@index=3")
    monkeypatch.setenv("DPTPU_WORKER_TIMEOUT_S", "1")
    monkeypatch.setenv("DPTPU_POOL_RESTARTS", "1")
    ds = SyntheticDataset(32, 8, 10)
    th = DataLoader(ds, 4, num_workers=2, seed=3)
    pr = DataLoader(ds, 4, num_workers=2, seed=3, workers_mode="process",
                    leased=True)
    try:
        ref = list(th.epoch(0))

        def put(batch):
            return {k: np.array(v) for k, v in batch.items()}

        got = list(DevicePrefetcher(pr.epoch(0), put=put,
                                    copy_before_put=False))
        assert len(got) == len(ref)
        for a, b in zip(ref, got):
            np.testing.assert_array_equal(a["images"], b["images"])
            np.testing.assert_array_equal(a["labels"], b["labels"])
        assert pr.workers_mode == "thread"
        assert pr.feed_stats()["degraded"] is True
    finally:
        th.close()
        pr.close()


# -- decode-ahead pipelined feed -------------------------------------------

def test_decode_ahead_bit_identical_across_depths(jpeg_folder):
    """The tentpole contract: deep multi-batch span pre-issue (out-of-
    order completion, workers rolling across batch boundaries) changes
    NOTHING about the bytes — decode_ahead=1 (batch-serial baseline),
    a deep ring, and thread mode all agree bit for bit, epoch after
    epoch."""
    ds = ImageFolderDataset(jpeg_folder, train_transform(48))
    th = DataLoader(ds, 4, num_workers=2, seed=5)
    serial = DataLoader(ds, 4, num_workers=2, seed=5,
                        workers_mode="process", decode_ahead=1)
    deep = DataLoader(ds, 4, num_workers=2, seed=5,
                      workers_mode="process", decode_ahead=5, ring_depth=8)
    try:
        for epoch in (0, 1):
            ref = list(th.epoch(epoch))
            _assert_batches_equal(ref, list(serial.epoch(epoch)))
            _assert_batches_equal(ref, list(deep.epoch(epoch)))
        fs = deep.feed_stats()
        assert fs["ring_depth"] == 8
        # the pump actually ran ahead (5 batches of lookahead over the
        # 5-batch epoch: every non-tail collect saw > 1 pre-issued)
        assert fs["issue_ahead_depth"] > 1.0
        assert serial.feed_stats()["issue_ahead_depth"] == 1.0
    finally:
        th.close()
        serial.close()
        deep.close()


def test_straggler_speculation_keeps_bit_identity(monkeypatch):
    """A worker stalled mid-span (worker_hang straggler mode: only
    worker 0, bounded sleep) must not gate the epoch: speculation
    re-issues its spans to a healthy worker, first-writer-wins, and the
    late twin's ghost ack is absorbed without corrupting any later
    batch — everything stays bit-identical, including the NEXT epoch
    (whose slots must not be recycled under a still-writing ghost)."""
    from dptpu.data import SyntheticDataset
    from dptpu.data.shm import _affinity_of

    ds = SyntheticDataset(48, 8, 10)
    th = DataLoader(ds, 8, num_workers=2, seed=3)
    stall = next(i for i in range(48) if _affinity_of(i, 2) == 0)
    monkeypatch.setenv("DPTPU_FAULT",
                       f"worker_hang@index={stall}@s=1@worker=0")
    monkeypatch.setenv("DPTPU_WORKER_TIMEOUT_S", "30")
    pr = DataLoader(ds, 8, num_workers=2, seed=3, workers_mode="process",
                    decode_ahead=4, ring_depth=8, speculate_after_s=0.1)
    try:
        ref0, ref1 = list(th.epoch(0)), list(th.epoch(1))
        _assert_batches_equal(ref0, list(pr.epoch(0)))
        fs = pr.feed_stats()
        assert fs["straggler_reissues"] >= 1
        # epoch 1 re-stalls on the same index; the ring keeps flowing
        # and the bytes keep matching (ghost quarantine did its job)
        _assert_batches_equal(ref1, list(pr.epoch(1)))
        assert pr.workers_mode == "process"  # no restart exhaustion
    finally:
        th.close()
        pr.close()


def test_duplicate_span_completion_is_ghosted():
    """Unit-level dup-ack safety: a second 'done' for an already-
    completed span (the speculative twin finishing late) must not drive
    the slot's completion counter negative or double-free the slot."""
    from dptpu.data import SyntheticDataset

    ds = SyntheticDataset(16, 8, 10)
    pr = DataLoader(ds, 8, num_workers=2, seed=0, workers_mode="process",
                    decode_ahead=1)
    try:
        batches = list(pr.epoch(0))
        assert len(batches) == 2
        pipe = pr._pipeline
        free_before = pipe.free_slot_count()
        # forge the late twin's acks: done AND error flavors of a span
        # that was already completed and whose slot was recycled
        pipe._extra_issues[0] = 2
        pipe._handle(("done", 0, 0, 0, 0, 0), mode="normal")
        pipe._handle(("error", 1, 0, 0, "late twin traceback"),
                     mode="normal")
        assert pipe._outstanding[0] == 0  # never went negative
        assert pipe._extra_issues[0] == 0  # both ghosts absorbed
        assert pipe.free_slot_count() == free_before  # no double-free
        # the ring still works end to end after the ghosts
        assert len(list(pr.epoch(1))) == 2
    finally:
        pr.close()


def test_pool_restart_with_preissued_spans_in_flight():
    """Supervisor restart under deep lookahead: killing a worker while
    spans for several future batches sit in its queue must re-enqueue
    ALL of them (the _pending map spans every pre-issued slot) and the
    epoch must complete bit-identically."""
    from dptpu.data import SyntheticDataset

    ds = SyntheticDataset(48, 8, 10)
    th = DataLoader(ds, 8, num_workers=2, seed=3)
    pr = DataLoader(ds, 8, num_workers=2, seed=3, workers_mode="process",
                    decode_ahead=5, ring_depth=8)
    try:
        ref = list(th.epoch(0))
        it = pr.epoch(0)
        got = [next(it)]  # the pump has now pre-issued deep lookahead
        assert pr.kill_one_worker() is not None
        got += list(it)
        _assert_batches_equal(ref, got)
        fs = pr.feed_stats()
        assert fs["pool_restarts"] >= 1
        assert "degraded" not in fs
    finally:
        th.close()
        pr.close()


def test_ring_rebuild_handles_shrink_and_lease_carryover():
    """The epoch ring-rebuild fix: a depth change between epochs
    rebuilds the ring in BOTH directions (growth AND shrink — the old
    code only grew), and a lease carried over from an abandoned epoch
    is revoked by the loader-initiated rebuild, not reported as a
    leak; its late release voids against the closed pipeline."""
    import dptpu.data.shm as shm
    from dptpu.data import SyntheticDataset

    leaks_before = shm.leaked_lease_count()
    ds = SyntheticDataset(48, 8, 10)
    th = DataLoader(ds, 8, num_workers=2, seed=3)
    pr = DataLoader(ds, 8, num_workers=2, seed=3, workers_mode="process",
                    leased=True)
    try:
        ref = list(th.epoch(1))
        it0 = pr.epoch(0, prefetch_batches=8)  # window 9 → deep ring
        b0 = next(it0)
        big = pr._pipeline.slots
        lease = b0["_lease"]  # deliberately NOT released; epoch abandoned
        # shrink: the next epoch wants a much smaller window. Leased
        # batches are views — copy before advancing (the lease contract)
        got = [
            {"images": np.array(b["images"]), "labels": np.array(b["labels"])}
            for b in pr.epoch(1, prefetch_batches=0)
        ]
        small = pr._pipeline.slots
        assert small < big  # the ring actually rebuilt downward
        for a, b in zip(ref, got):
            np.testing.assert_array_equal(a["images"], b["images"])
            np.testing.assert_array_equal(a["labels"], b["labels"])
        lease.release()  # stale: voids against the closed old pipeline
        assert shm.leaked_lease_count() == leaks_before  # forgiven
    finally:
        del it0
        th.close()
        pr.close()
    assert shm.leaked_lease_count() == leaks_before


def test_close_with_unreleased_lease_counts_as_leak():
    """The conftest lease-leak guard's hook: closing the loader while a
    consumer still holds an unreleased lease (no reset/rebuild ever
    revoked it) must advance the module leak counter."""
    import dptpu.data.shm as shm
    from dptpu.data import SyntheticDataset

    before = shm.leaked_lease_count()
    ds = SyntheticDataset(24, 8, 10)
    pr = DataLoader(ds, 8, num_workers=2, seed=0, workers_mode="process",
                    leased=True)
    it = pr.epoch(0)
    batch = next(it)  # generator suspended: the backstop has NOT run
    pr.close()
    assert shm.leaked_lease_count() == before + 1
    # this leak was deliberate — restore the counter so the session
    # fixture keeps policing the REST of the suite
    shm._LEASE_LEAKS = before
    del batch, it


def test_affinity_off_still_bit_identical(jpeg_folder):
    ds = ImageFolderDataset(jpeg_folder, train_transform(48))
    th = DataLoader(ds, 4, num_workers=2, seed=3)
    pr = DataLoader(ds, 4, num_workers=2, seed=3, workers_mode="process",
                    span_affinity=False)
    try:
        for epoch in (0, 1):
            _assert_batches_equal(list(th.epoch(epoch)),
                                  list(pr.epoch(epoch)))
    finally:
        th.close()
        pr.close()
