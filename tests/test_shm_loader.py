"""Process-mode (shared-memory ring) loader: parity, cache, errors.

The contract under test (dptpu/data/shm.py + loader.py): for the same
``(seed, epoch, index)`` RNG, ``workers_mode="process"`` must yield
BATCHES BIT-IDENTICAL to thread mode — same pixels, labels, pad/mask
semantics — because workers run the exact same span-decode path, only
into shared memory instead of a same-process array. A worker decode
error must surface as a parent-side exception carrying the worker's
traceback, never a hang.

JPEG fixtures are 52×44 (< 48·8/7): the native scale picker then stays
at full resolution, which also makes cache-on/off comparisons bit-exact
(see ImageFolderDataset docstring).
"""

import numpy as np
import pytest
from PIL import Image

from dptpu.data import (
    DataLoader,
    ImageFolderDataset,
    train_transform,
)


@pytest.fixture(scope="module")
def jpeg_folder(tmp_path_factory):
    root = tmp_path_factory.mktemp("shmjpeg")
    rng = np.random.RandomState(0)
    for cls in ["c0", "c1"]:
        d = root / cls
        d.mkdir()
        for i in range(9):
            low = rng.randint(0, 255, (8, 7, 3), np.uint8)
            img = Image.fromarray(low).resize((52, 44), Image.BILINEAR)
            img.save(str(d / f"{i}.jpg"), quality=85)
    return str(root)


class CrashAtFive:
    """Decode-error fixture — module level so spawn can pickle it."""

    def __len__(self):
        return 12

    def get(self, index, rng=None):
        if index == 5:
            raise ValueError("decode exploded on sample 5")
        return np.full((8, 8, 3), index, np.uint8), index

    def get_into(self, index, rng, out):
        img, lab = self.get(index, rng)
        np.copyto(out, img)
        return lab

    def __getitem__(self, index):
        return self.get(index)


def _assert_batches_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x["images"], y["images"])
        np.testing.assert_array_equal(x["labels"], y["labels"])
        assert ("mask" in x) == ("mask" in y)
        if "mask" in x:
            np.testing.assert_array_equal(x["mask"], y["mask"])


def test_process_loader_bit_identical_to_thread(jpeg_folder):
    ds = ImageFolderDataset(jpeg_folder, train_transform(48))  # 18 samples
    th = DataLoader(ds, 4, num_workers=2, seed=5)
    pr = DataLoader(ds, 4, num_workers=2, seed=5, workers_mode="process")
    try:
        for epoch in (0, 1):
            a, b = list(th.epoch(epoch)), list(pr.epoch(epoch))
            assert len(a) == 5  # ceil(18/4): padded+masked tail included
            _assert_batches_equal(a, b)
        # abandoning an epoch mid-flight must not wedge the slot ring
        it = pr.epoch(2)
        next(it)
        del it
        _assert_batches_equal(list(th.epoch(3)), list(pr.epoch(3)))
    finally:
        th.close()
        pr.close()


def test_process_loader_cache_parity_and_stats(jpeg_folder):
    """Per-worker decode caches change nothing about the pixels (hit and
    miss resample the same decoded buffer) and aggregate into
    ``feed_stats`` through the done-message piggyback."""
    ds_th = ImageFolderDataset(jpeg_folder, train_transform(48),
                               cache_bytes=32 << 20)
    ds_pr = ImageFolderDataset(jpeg_folder, train_transform(48),
                               cache_bytes=32 << 20)
    th = DataLoader(ds_th, 4, num_workers=2, seed=5)
    pr = DataLoader(ds_pr, 4, num_workers=2, seed=5,
                    workers_mode="process")
    try:
        for epoch in (0, 1):
            _assert_batches_equal(list(th.epoch(epoch)),
                                  list(pr.epoch(epoch)))
        fs = pr.feed_stats()
        assert fs["workers_mode"] == "process"
        assert fs["cache_hits"] > 0
        assert 0.0 < fs["cache_hit_rate"] <= 1.0
    finally:
        th.close()
        pr.close()


def test_worker_decode_error_propagates_with_traceback():
    loader = DataLoader(CrashAtFive(), 4, num_workers=2, seed=0,
                        workers_mode="process")
    try:
        with pytest.raises(RuntimeError, match="decode exploded on sample 5"):
            list(loader.epoch(0))
    finally:
        loader.close()


def test_invalid_workers_mode_rejected():
    with pytest.raises(ValueError, match="workers_mode"):
        DataLoader(CrashAtFive(), 4, workers_mode="greenlet")


# -- consumer-leased zero-copy slots ---------------------------------------

def test_leased_slot_not_recycled_while_put_in_flight(jpeg_folder):
    """The lease-lifetime contract: with a SLOW ``put`` (simulating the
    device transfer) the ring must not recycle the leased slot — the
    batch bytes read after the sleep must equal thread mode's, bit for
    bit, and the parent must have copied nothing."""
    import time

    from dptpu.data import DevicePrefetcher

    ds = ImageFolderDataset(jpeg_folder, train_transform(48))
    th = DataLoader(ds, 4, num_workers=2, seed=5)
    pr = DataLoader(ds, 4, num_workers=2, seed=5, workers_mode="process",
                    leased=True)
    try:
        ref = list(th.epoch(0))

        def slow_put(batch):
            # while we sleep, the loader keeps submitting ahead — only
            # the lease protocol stops a worker from overwriting these
            # exact rows before we read them
            time.sleep(0.1)
            return {k: np.array(v) for k, v in batch.items()}

        got = list(DevicePrefetcher(pr.epoch(0), put=slow_put,
                                    copy_before_put=False))
        _assert_batches_equal(ref, got)
        fs = pr.feed_stats()
        assert fs["leased"] is True
        assert fs["bytes_copied_per_batch"] == 0.0
        # epoch 2: the ring and its leases recycle cleanly
        _assert_batches_equal(
            list(th.epoch(1)),
            list(DevicePrefetcher(pr.epoch(1), put=slow_put,
                                  copy_before_put=False)),
        )
    finally:
        th.close()
        pr.close()


def test_leased_through_real_jax_put_bit_identical(jpeg_folder):
    """End-to-end through jax.device_put: on the CPU test backend the
    prefetcher must detect host-buffer aliasing and defend (copy before
    put); batches on 'device' must match thread mode after the ring has
    long recycled the slots."""
    import jax

    from dptpu.data import DevicePrefetcher

    ds = ImageFolderDataset(jpeg_folder, train_transform(48))
    th = DataLoader(ds, 4, num_workers=2, seed=9)
    pr = DataLoader(ds, 4, num_workers=2, seed=9, workers_mode="process",
                    leased=True)
    try:
        ref = list(th.epoch(0))
        dev = list(DevicePrefetcher(pr.epoch(0), put=jax.device_put))
        assert len(ref) == len(dev)
        for a, b in zip(ref, dev):
            np.testing.assert_array_equal(a["images"],
                                          np.asarray(b["images"]))
            np.testing.assert_array_equal(a["labels"],
                                          np.asarray(b["labels"]))
            assert "_lease" not in b  # the prefetcher consumed the token
    finally:
        th.close()
        pr.close()


def test_lease_release_is_idempotent_and_generation_checked():
    from dptpu.data import SyntheticDataset

    ds = SyntheticDataset(24, 8, 10)
    pr = DataLoader(ds, 8, num_workers=2, seed=0, workers_mode="process",
                    leased=True)
    try:
        it = pr.epoch(0)
        b0 = next(it)
        lease = b0["_lease"]
        lease.release()
        lease.release()  # double release: no-op
        rest = list(it)  # backstop releases ride the generator
        assert len(rest) == 2
        lease.release()  # stale (slot long recycled): generation no-op
        # the ring is fully free again: a fresh epoch works
        assert len(list(pr.epoch(1))) == 3
    finally:
        pr.close()


def test_affinity_spans_cover_batch_and_balance():
    from dptpu.data.shm import _affinity_of, _affinity_spans

    idxs = list(range(1000, 1064))
    spans = _affinity_spans(idxs, 4)
    seen = {}
    for wid, offsets, span_idxs in spans:
        assert len(offsets) == len(span_idxs)
        assert len(offsets) <= -(-64 // 4)  # rebalanced to cap
        for o, i in zip(offsets, span_idxs):
            assert o not in seen
            seen[o] = (wid, i)
    assert sorted(seen) == list(range(64))  # every row exactly once
    assert sorted(i for _, i in seen.values()) == idxs
    # determinism: the same index routes to the same worker every time
    assert _affinity_spans(idxs, 4) == spans
    for i in idxs:
        assert _affinity_of(i, 4) == _affinity_of(i, 4)


def test_degrade_to_thread_with_leases_held(monkeypatch):
    """A pool that hangs past its restart budget must degrade to thread
    mode even mid-leased-epoch: the retiring pipeline tolerates the
    consumer's outstanding views (BufferError-safe close, generation-
    checked lease release) and the thread path re-decodes the unyielded
    tail — batches stay bit-identical across the hand-off."""
    from dptpu.data import DevicePrefetcher, SyntheticDataset

    monkeypatch.setenv("DPTPU_FAULT", "worker_hang@index=3")
    monkeypatch.setenv("DPTPU_WORKER_TIMEOUT_S", "1")
    monkeypatch.setenv("DPTPU_POOL_RESTARTS", "1")
    ds = SyntheticDataset(32, 8, 10)
    th = DataLoader(ds, 4, num_workers=2, seed=3)
    pr = DataLoader(ds, 4, num_workers=2, seed=3, workers_mode="process",
                    leased=True)
    try:
        ref = list(th.epoch(0))

        def put(batch):
            return {k: np.array(v) for k, v in batch.items()}

        got = list(DevicePrefetcher(pr.epoch(0), put=put,
                                    copy_before_put=False))
        assert len(got) == len(ref)
        for a, b in zip(ref, got):
            np.testing.assert_array_equal(a["images"], b["images"])
            np.testing.assert_array_equal(a["labels"], b["labels"])
        assert pr.workers_mode == "thread"
        assert pr.feed_stats()["degraded"] is True
    finally:
        th.close()
        pr.close()


def test_affinity_off_still_bit_identical(jpeg_folder):
    ds = ImageFolderDataset(jpeg_folder, train_transform(48))
    th = DataLoader(ds, 4, num_workers=2, seed=3)
    pr = DataLoader(ds, 4, num_workers=2, seed=3, workers_mode="process",
                    span_affinity=False)
    try:
        for epoch in (0, 1):
            _assert_batches_equal(list(th.epoch(epoch)),
                                  list(pr.epoch(epoch)))
    finally:
        th.close()
        pr.close()
