"""dptpu.obs: span tracer ring, metrics registry fan-out, epoch
attribution, and the on-demand in-flight profiling trigger."""

import json
import os
import signal
import threading
import time

import pytest

from dptpu import obs


# ------------------------------------------------------------- tracer -------


def test_tracer_span_and_record():
    t = obs.Tracer(capacity=16)
    with t.span("data_wait", step=3):
        time.sleep(0.01)
    t.record("h2d", time.perf_counter(), 0.5, step=3)
    spans = t.snapshot()
    assert [s["name"] for s in spans] == ["data_wait", "h2d"]
    assert spans[0]["step"] == 3 and spans[0]["dur_s"] >= 0.01
    assert spans[1]["dur_s"] == 0.5
    # snapshot does not clear; drain does
    assert len(t.snapshot()) == 2
    assert len(t.drain()) == 2
    assert t.drain() == []


def test_tracer_ring_overwrites_oldest_and_counts_dropped():
    t = obs.Tracer(capacity=4)
    for i in range(10):
        t.record(f"s{i}", float(i), 0.1)
    assert t.dropped == 6
    names = [s["name"] for s in t.drain()]
    assert names == ["s6", "s7", "s8", "s9"]  # oldest→newest, tail kept


def test_tracer_capacity_validated():
    with pytest.raises(ValueError, match="capacity"):
        obs.Tracer(capacity=1)


def test_tracer_thread_safety():
    t = obs.Tracer(capacity=10000)

    def worker(k):
        for i in range(1000):
            t.record(f"w{k}", time.perf_counter(), 1e-6)

    threads = [threading.Thread(target=worker, args=(k,)) for k in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert len(t.drain()) + t.dropped == 4000


def test_null_tracer_is_inert():
    t = obs.NullTracer()
    with t.span("x"):
        pass
    t.record("x", 0.0, 1.0)
    assert t.snapshot() == [] and t.drain() == []


def test_global_tracer_accessors():
    assert isinstance(obs.get_tracer(), obs.NullTracer)
    real = obs.set_tracer(obs.Tracer(capacity=64))
    try:
        assert obs.get_tracer() is real
    finally:
        obs.reset()
    assert isinstance(obs.get_tracer(), obs.NullTracer)


def test_chrome_export_is_host_only_for_device_parser():
    """The exported host timeline must NEVER be mistaken for a device
    track by the XLA trace parser — merged files stay unambiguous."""
    from dptpu.utils.profiling import parse_perfetto_trace

    t = obs.Tracer(capacity=16)
    with t.span("step", step=0):
        pass
    events = obs.spans_to_chrome_events(t.drain())
    assert events[0]["ph"] == "M"  # process_name metadata first
    assert "Host" in events[0]["args"]["name"]
    assert events[1]["ph"] == "X" and events[1]["args"]["step"] == 0
    with pytest.raises(RuntimeError, match="no device tracks"):
        parse_perfetto_trace({"traceEvents": events})


def test_trace_sink_writes_jsonl_and_chrome(tmp_path):
    t = obs.Tracer(capacity=16)
    with t.span("data_wait", step=1):
        pass
    sink = obs.TraceSink(str(tmp_path))
    sink.add_spans(t.drain())
    sink.log_event("metrics", {"step": 1})
    sink.close()
    lines = [json.loads(line)
             for line in open(sink.jsonl_path).read().splitlines()]
    assert [rec["kind"] for rec in lines] == ["span", "metrics"]
    trace = json.load(open(sink.chrome_path))
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 1 and xs[0]["name"] == "data_wait"


# ----------------------------------------------------------- registry -------


def test_registry_instruments_and_type_guard():
    r = obs.Registry()
    r.counter("n").inc()
    r.counter("n").inc(2)
    r.gauge("g").set(1.5)
    for v in (1.0, 2.0, 3.0, 10.0):
        r.histogram("h").observe(v)
    s = r.scalars()
    assert s["n"] == 3.0 and s["g"] == 1.5
    assert s["h/count"] == 4.0 and s["h/max"] == 10.0
    assert s["h/p50"] in (2.0, 3.0)
    with pytest.raises(TypeError, match="already registered"):
        r.gauge("n")


def test_registry_flush_fans_out_and_resets_histograms():
    r = obs.Registry()

    class FakeSink:
        def __init__(self):
            self.emitted = []
            self.ended = []

        def emit(self, tag, value, step):
            self.emitted.append((tag, value, step))

        def flush_end(self, step):
            self.ended.append(step)

    a, b = FakeSink(), FakeSink()
    r.add_sink(a)
    r.add_sink(b)
    r.set_scalars({"Feed/x": 1.0, "Obs/y": 2.0})
    r.histogram("h").observe(5.0)
    r.flush(7)
    assert a.emitted == b.emitted
    assert ("Feed/x", 1.0, 7) in a.emitted and ("Obs/y", 2.0, 7) in a.emitted
    assert a.ended == [7]
    # histogram window reset on flush: next flush reports empty
    r.flush(8)
    assert ("h/count", 0.0, 8) in a.emitted


def test_registry_tb_bridge_roundtrip(tmp_path):
    """Satellite: every registered Feed/*, Obs/* and Cache/* scalar must
    round-trip through the TB sink with correct step indices."""
    from dptpu.utils.tensorboard import SummaryWriter

    w = SummaryWriter(log_dir=str(tmp_path / "run"))
    r = obs.Registry()
    r.add_sink(obs.TensorBoardSink(w))
    series = {
        "Feed/ring_occupancy": [(1, 3.5), (2, 4.0), (3, 2.25)],
        "Feed/io_wait_s": [(1, 0.5), (2, 0.25), (3, 0.125)],
        "Obs/data_wait_s": [(1, 1.5), (2, 1.25), (3, 1.0)],
        "Obs/coverage": [(1, 0.96875), (2, 0.984375), (3, 0.9921875)],
        "Cache/hit_rate": [(1, 0.0), (2, 0.5), (3, 1.0)],
    }
    for step in (1, 2, 3):
        r.set_scalars({tag: dict(pts)[step] for tag, pts in series.items()})
        r.flush(step)
    w.close()

    from tensorboard.backend.event_processing import event_accumulator

    acc = event_accumulator.EventAccumulator(str(tmp_path / "run"))
    acc.Reload()
    assert set(series) <= set(acc.Tags()["scalars"])
    for tag, pts in series.items():
        got = [(e.step, e.value) for e in acc.Scalars(tag)]
        assert got == pts, tag


def test_jsonl_sink_one_line_per_flush(tmp_path):
    path = str(tmp_path / "m.jsonl")
    r = obs.Registry()
    r.add_sink(obs.JsonlSink(path))
    r.set_scalars({"Obs/x": 1.0})
    r.flush(1)
    r.set_scalars({"Obs/x": 2.0})
    r.flush(2)
    lines = [json.loads(line) for line in open(path).read().splitlines()]
    assert [(rec["step"], rec["scalars"]["Obs/x"]) for rec in lines] == \
        [(1, 1.0), (2, 2.0)]


def test_console_sink_filters_prefix(capsys):
    r = obs.Registry()
    r.add_sink(obs.ConsoleSink(prefixes=("Obs/",)))
    r.set_scalars({"Obs/coverage": 0.99, "Loss/train": 5.0})
    r.flush(3)
    out = capsys.readouterr().out
    assert "Obs[3]:" in out and "coverage=0.99" in out
    assert "Loss" not in out


# ----------------------------------------------------------- reporting ------


def _span(name, t0, dur, step=-1, tid=1):
    return {"name": name, "ts": t0, "t0": t0, "dur_s": dur, "step": step,
            "tid": tid}


def test_exclusive_durations_nesting():
    spans = [
        _span("data_wait", 0.0, 1.0),   # contains h2d [0.2, 0.5]
        _span("h2d", 0.2, 0.3),
        _span("step", 1.0, 0.4),
        _span("other_thread", 0.0, 5.0, tid=2),
    ]
    excl = {(s["name"], s["tid"]): e
            for s, e in obs.exclusive_durations(spans)}
    assert excl[("data_wait", 1)] == pytest.approx(0.7)
    assert excl[("h2d", 1)] == pytest.approx(0.3)
    assert excl[("step", 1)] == pytest.approx(0.4)
    assert excl[("other_thread", 2)] == pytest.approx(5.0)


def test_attribute_epoch_categories_coverage_and_anomalies():
    spans = []
    t = 0.0
    for i in range(20):
        dur = 1.0 if i != 7 else 5.0  # step 7 is the anomaly
        spans.append(_span("data_wait", t, 0.2, step=i))
        spans.append(_span("h2d", t + 0.05, 0.1, step=i))  # nested
        spans.append(_span("step", t + 0.2, dur - 0.2, step=i))
        spans.append(_span("iter", t, dur, step=i))
        t += dur
    rep = obs.attribute_epoch(spans, wall_s=t + 1.0, anomaly_x=3.0)
    # data_wait is exclusive of the nested h2d span
    assert rep["data_wait_s"] == pytest.approx(20 * 0.1, abs=1e-6)
    assert rep["h2d_s"] == pytest.approx(20 * 0.1, abs=1e-6)
    assert rep["device_s"] == pytest.approx(t - 20 * 0.2, abs=1e-6)
    assert rep["other_s"] == pytest.approx(1.0, abs=1e-6)
    assert rep["coverage"] == pytest.approx(t / (t + 1.0), abs=1e-3)
    assert rep["steps"] == 20 and rep["step_p50_s"] == pytest.approx(1.0)
    assert rep["step_max_s"] == pytest.approx(5.0)
    anomalies = rep["anomalous_steps"]
    assert len(anomalies) == 1 and anomalies[0]["step"] == 7
    assert anomalies[0]["phases"]["device"] == pytest.approx(4.8)
    # async ckpt spans are reported separately, never in the budget
    spans.append(_span("ckpt_write", 0.0, 3.0, tid=9))
    rep2 = obs.attribute_epoch(spans, wall_s=t + 1.0)
    assert rep2["ckpt_s"] == 0.0
    assert rep2["ckpt_async_s"] == pytest.approx(3.0)
    assert rep2["coverage"] == pytest.approx(rep["coverage"], abs=1e-6)


def test_format_report_mentions_anomalies():
    spans = [_span("iter", float(i), 1.0 if i else 10.0, step=i)
             for i in range(10)]
    rep = obs.attribute_epoch(spans, wall_s=19.0)
    text = obs.format_report(rep, epoch=4)
    assert "obs epoch 4" in text and "anomalous step 0" in text


# ------------------------------------------------------------- trigger ------


def test_trigger_sentinel_and_signal_capture(tmp_path):
    """The full in-flight loop on a live-ish step sequence: sentinel
    arms → device trace for N steps → merged attribution written."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((64, 64))

    tracer = obs.Tracer(capacity=256)
    sentinel = str(tmp_path / "armme")
    trig = obs.ProfileTrigger(
        str(tmp_path), trace_steps=2, tracer=tracer, sentinel=sentinel,
        verbose=False,
    ).install()
    try:
        # SIGUSR2 only sets the armed flag (async-signal-safe handler)
        os.kill(os.getpid(), signal.SIGUSR2)
        assert trig._armed
        trig._armed = False  # exercise the sentinel path instead
        open(sentinel, "w").close()
        for step in range(4):
            with tracer.span("iter", step=step):
                with tracer.span("step", step=step):
                    float(f(x))
            trig.tick(step)
        assert not os.path.exists(sentinel)  # consumed: one touch, one trace
        assert trig.last_report is not None
        rep = trig.last_report
        assert rep["steps"] == 2
        assert "host_phases_s" in rep
        # host spans of the window landed in the merged report
        assert rep["host_phases_s"]["device"] > 0
        path = os.path.join(rep["trace_dir"], "attribution.json")
        assert os.path.exists(path)
        # formatting never raises, with or without a device table
        assert "on-demand profile" in trig.format_report(rep)
    finally:
        trig.uninstall()


def test_trigger_trace_steps_validated(tmp_path):
    with pytest.raises(ValueError, match="trace_steps"):
        obs.ProfileTrigger(str(tmp_path), trace_steps=0)


def test_trigger_window_survives_a_drain(tmp_path):
    """A window straddling fit's epoch-boundary drain must keep its
    early spans: the drainer hands them back via absorb()."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: (x * x).sum())
    x = jnp.ones((8, 8))
    tracer = obs.Tracer(capacity=256)
    trig = obs.ProfileTrigger(
        str(tmp_path), trace_steps=2, tracer=tracer, verbose=False,
    )
    trig.arm()
    with tracer.span("step", step=0):
        float(f(x))
    trig.tick(0)  # window opens
    with tracer.span("step", step=1):
        float(f(x))
    # the epoch boundary: fit drains the ring for its report and hands
    # the spans to the trigger
    trig.absorb(tracer.drain())
    trig.tick(1)
    with tracer.span("step", step=2):
        float(f(x))
    trig.tick(2)  # window closes
    rep = trig.last_report
    assert rep is not None
    # both window steps' device time is attributed — including step 1,
    # whose span was drained out of the ring mid-window
    assert rep["host_phases_s"]["device"] > 0
    assert trig._window_spans == []  # buffer released after the report


def test_anomaly_phases_are_exclusive():
    """A nested collect inside its data_wait must not double-bill the
    anomalous step's printed breakdown (phases <= step time)."""
    spans = []
    for i in range(8):
        t = float(i)
        dur = 1.0 if i != 3 else 0.31
        if i == 3:
            spans.append(_span("data_wait", t, 0.24, step=i))
            spans.append(_span("collect", t + 0.005, 0.23, step=i))
            spans.append(_span("step", t + 0.24, 0.07, step=i))
        else:
            spans.append(_span("step", t, 0.05, step=i))
        spans.append(_span("iter", t, dur if i != 3 else 3.31, step=i))
    rep = obs.attribute_epoch(spans, wall_s=12.0, anomaly_x=3.0)
    a = {x["step"]: x for x in rep["anomalous_steps"]}[3]
    # exclusive: 0.24 total data_wait-category time, NOT 0.24 + 0.23
    assert a["phases"]["data_wait"] == pytest.approx(0.24, abs=1e-6)
    assert sum(a["phases"].values()) == pytest.approx(0.31, abs=1e-6)
