"""Worker process for the 2-host distributed integration test.

Each instance is one "host" (JAX process) with 2 fake CPU chips; together
they form a 4-chip pod. Exercises the real multi-host stack: gRPC rendezvous
through ``initialize_distributed`` (the init_process_group analog), a global
mesh, per-host disjoint batches assembled with
``make_array_from_process_local_data``, pmean'd DDP steps, and the
single-writer checkpoint guard.

Usage: python _multihost_worker.py <port> <rank> <outdir>
"""

import os
import sys


def main():
    port, rank, outdir = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from dptpu.config import Config, derive
    from dptpu.parallel import initialize_distributed, make_mesh, shard_host_batch
    from dptpu.train import (
        create_train_state,
        make_optimizer,
        make_train_step,
        save_checkpoint,
    )
    from flax import linen as nn

    cfg = Config(
        data="unused",
        dist_url=f"tcp://127.0.0.1:{port}",
        world_size=2,
        rank=rank,
    )
    assert initialize_distributed(cfg)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4, jax.device_count()

    derived = derive(
        cfg,
        local_device_count=jax.local_device_count(),
        num_processes=jax.process_count(),
        process_index=jax.process_index(),
    )

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = nn.Conv(8, (3, 3), use_bias=False)(x)
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
            x = nn.relu(x).mean(axis=(1, 2))
            return nn.Dense(4)(x)

    mesh = make_mesh()
    # hierarchical (DCN, ICI) layout auto-engages with >1 process: each
    # host's chips must form a contiguous block along the data axis
    arr = mesh.devices.reshape(-1)
    procs = [d.process_index for d in arr]
    assert procs == sorted(procs), f"mesh not host-major: {procs}"
    tx = make_optimizer(0.9, 1e-4)
    state = create_train_state(
        jax.random.PRNGKey(0), Tiny(), tx, input_shape=(1, 8, 8, 3)
    )
    step = make_train_step(mesh, lr_schedule=lambda c: 0.1)

    # per-host disjoint data (what the ShardedSampler would produce)
    rng = np.random.RandomState(100 + rank)
    losses = []
    for i in range(3):
        host_batch = {
            "images": rng.randint(0, 256, (8, 8, 8, 3)).astype(np.uint8),
            "labels": rng.randint(0, 4, (8,)).astype(np.int32),
        }
        state, metrics = step(state, shard_host_batch(host_batch, mesh))
        losses.append(float(metrics["loss"]))

    save_checkpoint(
        state,
        epoch=1,
        arch="tiny",
        best_acc1=0.0,
        is_best=False,
        directory=outdir,
        is_chief=derived.is_chief,
        filename=f"ckpt_rank{rank}.pth.tar",
    )
    print(f"RANK{rank} LOSSES {' '.join(f'{l:.6f}' for l in losses)}", flush=True)


if __name__ == "__main__":
    main()
