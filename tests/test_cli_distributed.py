"""CLI-surface tests for the distributed flags and val-mode split.

The reference CLIs accept CUDA-era distributed flags (``--dist-backend
nccl``, ``--multiprocessing-distributed``, ``--dist-url``); BASELINE.json
requires them to run unchanged. README documents the mapping: the backend
string is accepted and recorded, rendezvous/collectives always go through
jax.distributed + XLA collectives, and the mp.spawn ladder collapses into
one process per host. These tests drive the real argparse schemas
(dptpu.config.parse_config — the same object the root shims call) into
fit() on the fake pod.
"""

import numpy as np
import pytest

from dptpu.config import parse_config
from dptpu.train import fit


def test_ddp_cli_distributed_flags_parse_and_map():
    cfg = parse_config(
        ["synthetic:48", "-a", "resnet18", "--dist-backend", "nccl",
         "--dist-url", "tcp://224.66.41.62:23456", "--world-size", "1",
         "--rank", "0", "-b", "16", "--epochs", "1"],
        variant="ddp",
    )
    # accepted + recorded, exactly as typed (imagenet_ddp.py:61-65)
    assert cfg.dist_backend == "nccl"
    assert cfg.dist_url == "tcp://224.66.41.62:23456"
    assert cfg.world_size == 1 and cfg.rank == 0


def test_nd_cli_multiprocessing_distributed_parses():
    cfg = parse_config(
        ["synthetic:48", "-a", "resnet18", "--multiprocessing-distributed",
         "-b", "16", "--epochs", "1"],
        variant="nd",
    )
    assert cfg.multiprocessing_distributed is True


@pytest.mark.parametrize("variant,extra", [
    ("ddp", ["--dist-backend", "nccl"]),
    ("nd", ["--multiprocessing-distributed"]),
])
def test_distributed_flags_train_end_to_end(variant, extra, tmp_path,
                                            monkeypatch):
    """The documented behavior: CUDA-specific flags never crash; training
    proceeds through the mesh/jit path (SURVEY.md §7 hard part (e))."""
    monkeypatch.chdir(tmp_path)
    cfg = parse_config(
        ["synthetic:48", "-a", "resnet18", "-b", "16", "--epochs", "1",
         "-j", "2", "--lr", "0.01", *extra],
        variant=variant,
    )
    result = fit(cfg, image_size=32, verbose=False)
    assert result["epochs_run"] == 1
    assert np.isfinite(result["history"][0]["train_loss"])


def test_multiprocessing_distributed_prints_notice(tmp_path, monkeypatch,
                                                   capsys):
    """--multiprocessing-distributed is a deliberate no-op (one process
    per host drives every chip) but must SAY so, like DPTPU_ZERO1 /
    DPTPU_S2D do — no silent flag swallowing (VERDICT r3 #8)."""
    monkeypatch.chdir(tmp_path)
    cfg = parse_config(
        ["synthetic:48", "-a", "resnet18", "-b", "16", "--epochs", "1",
         "-j", "2", "--lr", "0.01", "--multiprocessing-distributed"],
        variant="nd",
    )
    result = fit(cfg, image_size=32, verbose=True)
    assert result["epochs_run"] == 1
    out = capsys.readouterr().out
    assert "--multiprocessing-distributed noted" in out
    assert "no worker processes are spawned" in out


def test_full_val_mode_counts_once_per_dataset(tmp_path, monkeypatch):
    """ddp/nd report count == len(val) in full-val mode (single host), the
    imagenet_ddp.py:186-194 behavior; apex's sharded val reports the same
    by exact psum aggregation."""
    monkeypatch.chdir(tmp_path)
    counts = {}
    for variant in ("ddp", "apex"):
        cfg = parse_config(
            ["synthetic:48", "-a", "resnet18", "-b", "16", "--epochs", "1",
             "--lr", "0.01"],
            variant=variant,
        )
        if variant == "apex":
            cfg = cfg.replace(dist_url="env://")
        result = fit(cfg, image_size=32, verbose=False)
        counts[variant] = result["history"][0]["val_count"]
    # synthetic val set = 48 // 10 = 4 samples; both modes count each once
    assert counts["ddp"] == counts["apex"]


def test_dropout_arch_trains_on_mesh(tmp_path, monkeypatch):
    """Dropout models (alexnet/vgg heads, squeezenet) need the train step
    to supply a dropout rng — regression for the per-step
    fold_in(PRNGKey(seed), step) + per-shard axis fold plumbing."""
    monkeypatch.chdir(tmp_path)
    cfg = parse_config(
        ["synthetic:48", "-a", "squeezenet1_1", "-b", "16", "--epochs", "1",
         "--lr", "0.001", "--seed", "7"],
        variant="nd",
    )
    result = fit(cfg, image_size=64, verbose=False)
    assert result["epochs_run"] == 1
    assert np.isfinite(result["history"][0]["train_loss"])


def test_apex_rejects_inception_v3_like_reference():
    """Reference parity: the Apex script refuses inception_v3 by name
    (imagenet_ddp_apex.py:209-210) — same message, before any data work."""
    cfg = parse_config(
        ["synthetic:16", "-a", "inception_v3", "-b", "8", "--epochs", "1"],
        variant="apex",
    ).replace(dist_url="env://")
    with pytest.raises(RuntimeError, match="inception_v3 is not supported"):
        fit(cfg, image_size=64, verbose=False)


def test_initialize_distributed_idempotent_and_conflict(monkeypatch):
    """Rendezvous hardening (VERDICT r4 weak #6): a second fit() in one
    process must not crash — same-job re-entry is a no-op, a DIFFERENT
    rendezvous raises actionably, and only ONE jax.distributed.initialize
    ever happens."""
    import dptpu.parallel.dist as dist_mod
    from dptpu.config import Config

    calls = []
    monkeypatch.setattr(dist_mod, "_initialized", None)
    monkeypatch.setattr(
        dist_mod.jax.distributed, "initialize",
        lambda **kw: calls.append(kw),
    )
    cfg = Config(data="synthetic:8", world_size=2, rank=0,
                 dist_url="tcp://127.0.0.1:29400")
    assert dist_mod.initialize_distributed(cfg) is True
    assert len(calls) == 1
    # idempotent re-entry (the second fit() in one process)
    assert dist_mod.initialize_distributed(cfg) is True
    assert len(calls) == 1  # no second initialize
    # a conflicting rendezvous refuses loudly
    with pytest.raises(RuntimeError, match="already joined"):
        dist_mod.initialize_distributed(cfg.replace(rank=1))


def test_initialize_distributed_timeout_maps_and_errors(monkeypatch):
    """DPTPU_RENDEZVOUS_TIMEOUT reaches jax.distributed.initialize, and
    a rendezvous failure surfaces as an actionable error naming the
    coordinator, not a bare backend trace."""
    import dptpu.parallel.dist as dist_mod
    from dptpu.config import Config

    seen = {}

    def fake_init(**kw):
        seen.update(kw)
        raise TimeoutError("deadline exceeded")

    monkeypatch.setattr(dist_mod, "_initialized", None)
    monkeypatch.setattr(dist_mod.jax.distributed, "initialize", fake_init)
    monkeypatch.setenv("DPTPU_RENDEZVOUS_TIMEOUT", "17")
    cfg = Config(data="synthetic:8", world_size=4, rank=2,
                 dist_url="tcp://10.0.0.1:29400")
    with pytest.raises(RuntimeError) as exc:
        dist_mod.initialize_distributed(cfg)
    assert seen["initialization_timeout"] == 17
    msg = str(exc.value)
    assert "10.0.0.1:29400" in msg and "rank 2/4" in msg
    assert "process_cleanup.sh" in msg


def test_apex_local_rank_prints_notice(tmp_path, monkeypatch, capsys):
    """apex --local_rank is accepted-and-mapped with a notice (the last
    silently-absorbed distributed flag, VERDICT r4 weak #6)."""
    monkeypatch.chdir(tmp_path)
    cfg = parse_config(
        ["synthetic:48", "-a", "resnet18", "-b", "16", "--epochs", "1",
         "-j", "2", "--lr", "0.01", "--local_rank", "3"],
        variant="apex",
    )
    result = fit(cfg, image_size=32, verbose=True)
    assert result["epochs_run"] == 1
    out = capsys.readouterr().out
    assert "--local_rank 3 noted" in out
