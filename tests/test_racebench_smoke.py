"""Tier-1 smoke of scripts/run_racebench.py (the obsbench pattern):
the overlap engine's race-harness gates — params Δ=0 parity against
the unbucketed step, the simulated-pod overlap win (overlapped step <
serial step at the modeled DCN bandwidth, on BOTH compute anchors),
the bucketing-vs-per-leaf latency-amortization win, and the HLO
schedule evidence — are continuously checked, not just on the bench
host. One subprocess, --smoke preset, same gate logic as the committed
RACEBENCH.json."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_racebench_smoke_gates(tmp_path):
    out = str(tmp_path / "RACEBENCH.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "run_racebench.py"),
         "--smoke", "--out", out],
        capture_output=True, text=True, timeout=560, env=env,
        cwd=str(tmp_path),
    )
    assert proc.returncode == 0, (
        f"racebench gate failed\nstdout:\n{proc.stdout[-4000:]}\n"
        f"stderr:\n{proc.stderr[-4000:]}"
    )
    with open(out) as f:
        bench = json.load(f)
    # artifact schema: every consumer-facing section present
    for key in ("simulated_pod", "hlo_evidence", "parity", "gates",
                "measured_step_s", "model_assumptions", "local_caveat",
                "grad_bytes", "host"):
        assert key in bench, key
    gates = bench["gates"]
    assert gates["parity_ok"], bench["parity"]
    assert gates["overlap_win_ok"]
    assert gates["bucketing_win_ok"]
    assert gates["evidence_ok"], bench["hlo_evidence"]
    # the Δ=0 claim specifically, per overlap arm
    deltas = [v for k, v in bench["parity"].items()
              if k.endswith("_max_delta")]
    assert deltas and all(d == 0.0 for d in deltas)
    # the model rows cover both compute anchors, and the chip-equivalent
    # headline actually shows a speedup > 1
    anchors = {r["compute_anchor"] for r in bench["simulated_pod"]}
    assert anchors == {"measured_host", "chip_equivalent"}
    head = next(r for r in bench["simulated_pod"]
                if r["compute_anchor"] == "chip_equivalent")
    assert head["overlapped_ms"] < head["serial_ms"]
    assert head["speedup"] > 1.0
    # evidence: >= 2 interleaved per-bucket reductions in every arm
    for ev in bench["hlo_evidence"].values():
        assert ev["reductions"] >= 2
        assert ev["interleaved_gaps"] >= 1
