"""``dptpu check`` over the repo itself — the tier-1 CI gate (ISSUE 12).

Locks, per the acceptance criteria:

* the repo lints CLEAN: zero unsuppressed findings, every suppression
  carries a reason, and the committed ANALYSIS.json baseline agrees;
* the HLO budget gates hold: the four representative configs compile
  to exactly the committed HLO_BUDGETS.json and reproduce the analytic
  r06/COMMBENCH collective byte formulas;
* seeded regressions FAIL the check with the locked actionable
  message — a knob-contract violation (raw environ read) and a
  collective-budget change (tampered table) each produce a finding
  naming the rule/config, the location, and the remediation;
* the exit-code contract: 0 clean / 1 findings, via the real
  ``python -m dptpu.analysis`` entry.

The four compiles are TinyDense-sized (the tests/test_hierarchy.py
precedent) and cached module-wide — tier-1 pays them once.
"""

import copy
import json
import os
import subprocess
import sys

import pytest

from dptpu.analysis.lint import lint_repo

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def repo_lint():
    findings, suppressions, n_files = lint_repo(ROOT)
    return findings, suppressions, n_files


@pytest.fixture(scope="module")
def computed_budgets(eight_devices):
    from dptpu.analysis.hlo_budget import compute_budgets

    return compute_budgets()


# ------------------------------------------------------- the clean gate


def test_repo_lints_clean(repo_lint):
    findings, _, n_files = repo_lint
    assert n_files > 100  # the whole dptpu/ + scripts/ tree, not a stub
    assert findings == [], "unsuppressed findings:\n" + "\n".join(
        f.format() for f in findings
    )


def test_every_suppression_carries_a_reason(repo_lint):
    _, suppressions, _ = repo_lint
    assert suppressions, "the repo documents its waivers via pragmas"
    for s in suppressions:
        assert s.reason.strip(), f"reasonless suppression at " \
                                 f"{s.path}:{s.line}"


def test_committed_analysis_baseline_agrees(repo_lint):
    with open(os.path.join(ROOT, "ANALYSIS.json"), encoding="utf-8") as f:
        baseline = json.load(f)
    assert baseline["ok"] is True
    assert baseline["lint"]["findings"] == []
    # the committed suppression census matches the live tree
    findings, suppressions, _ = repo_lint
    live = {(s.path, s.rule) for s in suppressions}
    committed = {(s["path"], s["rule"])
                 for s in baseline["lint"]["suppressions"]}
    assert live == committed, (
        "suppressions changed — regenerate the baseline with "
        "`dptpu check --json ANALYSIS.json`"
    )
    assert baseline["hlo"]["ok"] is True
    assert "provenance" in baseline  # host-stamped like every artifact


def test_hlo_budget_gate_holds(computed_budgets):
    from dptpu.analysis.hlo_budget import check_hlo_budgets

    violations, computed = check_hlo_budgets(
        ROOT, computed=computed_budgets
    )
    assert violations == [], "\n".join(v.format() for v in violations)
    # the committed table IS the compiled truth, byte for byte
    with open(os.path.join(ROOT, "HLO_BUDGETS.json"),
              encoding="utf-8") as f:
        committed = json.load(f)
    assert committed["configs"] == computed["configs"]


def test_budget_table_reproduces_analytic_formulas(computed_budgets):
    """The committed numbers re-derive from the r06/COMMBENCH formulas
    (tests/test_hierarchy.py's locks, restated against the table)."""
    g = computed_budgets["model"]["grad_bytes"]
    p = computed_budgets["model"]["pmean_bytes"]
    n = computed_budgets["geometry"]["devices"]
    s = computed_budgets["geometry"]["slices"]
    inner = computed_budgets["geometry"]["inner"]
    cfg = computed_budgets["configs"]
    ddp = cfg["ddp"]["per_chip"]
    assert ddp["reduce-scatter"] == 0 and ddp["all-gather"] == 0
    want = 2 * (n - 1) / n * (g + p)
    assert abs(ddp["all-reduce"] - want) / want < 0.02
    assert cfg["accum"]["per_chip"] == ddp  # ONE reduction per update
    z = cfg["zero1"]["per_chip"]["total"]
    assert abs(z - ddp["total"]) / ddp["total"] < 0.001
    link = cfg["slices"]["by_link"]
    assert link["ici"]["all-reduce"] == 0
    assert link["dcn"]["reduce-scatter"] == 0
    assert link["dcn"]["all-gather"] == 0
    want_ici = 2 * (inner - 1) / inner * g
    want_dcn = 2 * (s - 1) / s * g / inner + 2 * (n - 1) / n * p
    assert abs(link["ici"]["total"] - want_ici) / want_ici < 0.02
    assert abs(link["dcn"]["total"] - want_dcn) / want_dcn < 0.02
    for name, row in cfg.items():
        assert row["f64_shapes"] == 0
        if name == "serve_quant":
            # an inference forward donates nothing; its row gates the
            # REQUESTED matmul dtypes instead — every dot bf16, s8
            # parameters present, no silent fp32 fallback
            assert row["s8_params"] >= 1
            assert row["dots"].get("bf16", 0) >= 1
            assert not row["dots"].get("f32", 0) \
                and not row["dots"].get("f64", 0)
            continue
        assert row["alias_entries"] >= \
            computed_budgets["model"]["param_leaves"]


# --------------------------------------------------- seeded regressions


def test_seeded_knob_violation_fails_actionably(tmp_path):
    """A raw environ read of a DPTPU knob must fail the check with the
    locked message: rule name, file:line, pragma syntax."""
    pkg = tmp_path / "dptpu"
    pkg.mkdir()
    bad = pkg / "newmod.py"
    bad.write_text(
        'import os\nv = os.environ.get("DPTPU_ACCUM", "1")\n'
    )
    findings, _, _ = lint_repo(str(tmp_path))
    assert len(findings) == 1
    msg = findings[0].format()
    assert "knob-contract" in msg
    assert "dptpu/newmod.py:2" in msg
    assert "# dptpu: allow-knob-contract(" in msg
    assert "envknob" in msg


def test_seeded_budget_change_fails_actionably(computed_budgets):
    """A collective-budget drift (here: one DCN byte) must fail the
    gate naming the config, both values, and the re-commit path."""
    from dptpu.analysis.hlo_budget import check_hlo_budgets

    tampered = copy.deepcopy(computed_budgets)
    row = tampered["configs"]["slices"]["by_link"]["dcn"]
    row["all-reduce"] += 1
    row["total"] += 1
    violations, _ = check_hlo_budgets(
        ROOT, budgets=tampered, computed=computed_budgets
    )
    assert len(violations) == 1
    msg = violations[0].format()
    assert "slices" in msg and "by_link" in msg
    assert "--update-hlo-budgets" in msg
    # ...and an instruction-count change trips the same gate
    tampered = copy.deepcopy(computed_budgets)
    tampered["configs"]["ddp"]["collective_instructions"][
        "all-reduce"] -= 1
    violations, _ = check_hlo_budgets(
        ROOT, budgets=tampered, computed=computed_budgets
    )
    assert any("collective_instructions" in v.format()
               for v in violations)


# ---------------------------------------------------- exit-code contract


def _run_check(*args):
    return subprocess.run(
        [sys.executable, "-m", "dptpu.analysis", *args],
        cwd=ROOT, capture_output=True, text=True, timeout=120,
    )


def test_exit_code_contract_clean_repo():
    proc = _run_check("--no-hlo", "--root", ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_exit_code_contract_findings(tmp_path):
    pkg = tmp_path / "dptpu"
    pkg.mkdir()
    (pkg / "newmod.py").write_text(
        'import os\nv = os.environ.get("DPTPU_ACCUM", "1")\n'
    )
    proc = _run_check("--no-hlo", "--root", str(tmp_path))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "knob-contract" in proc.stdout
    assert "NOT CLEAN" in proc.stdout


def test_update_budgets_with_no_hlo_is_refused():
    """Committing a table the gates never validated must be a usage
    error (argparse exit 2), never a silent 'clean'."""
    proc = _run_check("--update-hlo-budgets", "--no-hlo", "--root", ROOT)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "drop --no-hlo" in proc.stderr


def test_exit_code_contract_wrong_root_is_usage_error(tmp_path):
    """A mis-set --root (no dptpu/ or scripts/ underneath) must exit 2,
    never report a zero-file scan as 'clean'."""
    proc = _run_check("--no-hlo", "--root", str(tmp_path))
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "wrong directory" in proc.stderr


def test_no_hlo_run_never_imports_jax():
    """The lint half's worker-safe contract, enforced for real: a
    --no-hlo run (including its provenance stamp) must finish with jax
    absent from sys.modules."""
    proc = subprocess.run(
        [sys.executable, "-c",
         # this image's sitecustomize preloads jax at startup; pop it so
         # any import ATTEMPT during the lint re-registers it visibly
         "import sys\n"
         "sys.modules.pop('jax', None)\n"
         "from dptpu.analysis.cli import main_check\n"
         "rc = main_check(['--no-hlo', '--quiet'])\n"
         "assert 'jax' not in sys.modules, 'lint run imported jax'\n"
         "sys.exit(rc)"],
        cwd=ROOT, capture_output=True, text=True, timeout=120,
        env={k: v for k, v in os.environ.items()},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
