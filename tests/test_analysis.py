"""Unit tests for the dptpu.analysis lint engine and every rule —
positive (a violating snippet is found), negative (idiomatic code is
not), and pragma-suppressed (a reasoned pragma silences exactly that
line and lands in the suppression census) — plus the LOCKED
actionable-message contract: every finding names its rule, its
file:line, and the pragma syntax that would suppress it.

Pure stdlib (the lint engine imports no jax/numpy) — tier-1 fast.
"""

import textwrap

import pytest

from dptpu.analysis import KNOB_REGISTRY, lint_source
from dptpu.analysis.lint import RepoContext, iter_rules
from dptpu.envknob import env_str


def _lint(path, src, readme=None, only=None):
    repo = RepoContext(root=None, readme_text=readme, knobs=KNOB_REGISTRY)
    return lint_source(path, textwrap.dedent(src), repo, only_rules=only)


def _rules_of(findings):
    return [f.rule for f in findings]


# ------------------------------------------------------- message contract


def test_finding_message_contract_is_locked():
    """Rule name, file:line, and the pragma syntax in EVERY finding."""
    findings, _ = _lint(
        "dptpu/train/fit.py",
        'import os\nv = os.environ.get("DPTPU_ACCUM", "1")\n',
    )
    assert findings, "seeded violation must be found"
    for f in findings:
        msg = f.format()
        assert f.rule in msg
        assert f"{f.path}:{f.line}" in msg
        assert f"# dptpu: allow-{f.rule}(" in msg


def test_unsuppressible_findings_do_not_advertise_a_pragma():
    """The 'pragma' meta-rule cannot be pragma'd away — its messages
    must not tell the user to try (following a bogus hint would just
    mint an unknown-rule finding)."""
    findings, _ = _lint(
        "dptpu/train/step.py",
        "x = 1  # dptpu: allow-host-sync no parens\n",
    )
    assert _rules_of(findings) == ["pragma"]
    msg = findings[0].format()
    assert "not suppressible" in msg
    assert "# dptpu: allow-pragma(" not in msg


def test_every_rule_has_a_doc():
    rules = iter_rules()
    assert {r.name for r in rules} >= {
        "knob-contract", "determinism", "host-sync", "shm-hygiene",
        "shard-map",
    }
    assert all(r.doc for r in rules)


# --------------------------------------------------------- knob-contract


def test_knob_raw_environ_get_flagged():
    findings, _ = _lint(
        "dptpu/serve/engine.py",
        'import os\nx = os.environ.get("DPTPU_SERVE_SLOTS", "4")\n',
        only=["knob-contract"],
    )
    assert _rules_of(findings) == ["knob-contract"]
    assert "envknob" in findings[0].message


def test_knob_os_getenv_and_setdefault_flagged():
    findings, _ = _lint(
        "dptpu/train/fit.py",
        'import os\n'
        'a = os.getenv("DPTPU_ACCUM", "1")\n'
        'b = os.environ.setdefault("DPTPU_ACCUM", "1")\n'
        'c = os.environ.setdefault("JAX_PLATFORMS", "cpu")\n',
        only=["knob-contract"],
    )
    assert [f.line for f in findings] == [2, 3]


def test_knob_raw_subscript_read_flagged_but_write_allowed():
    findings, _ = _lint(
        "scripts/run_x.py",
        'import os\n'
        'os.environ["DPTPU_FAULT"] = "spec"\n'   # write: a bench arming
        'v = os.environ["DPTPU_FAULT"]\n',       # load: a raw read
        only=["knob-contract"],
    )
    assert len(findings) == 1
    assert findings[0].line == 3


def test_knob_undeclared_literal_flagged_and_declared_ok():
    findings, _ = _lint(
        "dptpu/train/fit.py",
        'K = "DPTPU_TOTALLY_NEW_KNOB"\nG = "DPTPU_ACCUM"\n',
        only=["knob-contract"],
    )
    assert len(findings) == 1
    assert "DPTPU_TOTALLY_NEW_KNOB" in findings[0].message


def test_knob_prefix_literal_matches_registry():
    findings, _ = _lint(
        "dptpu/train/fit.py",
        'P = "DPTPU_OBS_"\nQ = "DPTPU_NOPE_"\n',
        only=["knob-contract"],
    )
    assert len(findings) == 1
    assert "DPTPU_NOPE_" in findings[0].message


def test_knob_envknob_helpers_are_clean():
    findings, _ = _lint(
        "dptpu/train/fit.py",
        'from dptpu.envknob import env_int\n'
        'v = env_int("DPTPU_ACCUM", 1)\n',
        only=["knob-contract"],
    )
    assert findings == []


def test_knob_registry_readme_cross_check():
    src = open("dptpu/analysis/knobs.py", encoding="utf-8").read()
    # a README documenting everything -> clean
    full_readme = "\n".join(KNOB_REGISTRY)
    findings, _ = _lint("dptpu/analysis/knobs.py", src,
                        readme=full_readme, only=["knob-contract"])
    assert findings == []
    # drop one non-internal knob from the docs -> exactly that finding
    partial = "\n".join(k for k in KNOB_REGISTRY if k != "DPTPU_ACCUM")
    findings, _ = _lint("dptpu/analysis/knobs.py", src,
                        readme=partial, only=["knob-contract"])
    assert len(findings) == 1
    assert "DPTPU_ACCUM" in findings[0].message
    # internal sentinels never require README docs
    partial = "\n".join(
        k for k in KNOB_REGISTRY if k != "DPTPU_NUMERICS_CHILD"
    )
    findings, _ = _lint("dptpu/analysis/knobs.py", src,
                        readme=partial, only=["knob-contract"])
    assert findings == []
    # boundary match: DPTPU_SP_MODE being documented must NOT count as
    # documentation for its prefix DPTPU_SP
    partial = "\n".join(k for k in KNOB_REGISTRY if k != "DPTPU_SP")
    assert "DPTPU_SP_MODE" in partial
    findings, _ = _lint("dptpu/analysis/knobs.py", src,
                        readme=partial, only=["knob-contract"])
    assert len(findings) == 1
    assert "DPTPU_SP " in findings[0].message + " "


# ---------------------------------------------------------- determinism


@pytest.mark.parametrize("snippet,needle", [
    ("import time\nts = time.time()\n", "wall-clock"),
    ("import os\nb = os.urandom(8)\n", "urandom"),
    ("import random\nx = random.random()\n", "process-global"),
    ("import random\nr = random.Random()\n", "without a seed"),
    ("import numpy as np\nx = np.random.randint(0, 4)\n", "global RNG"),
    ("import numpy as np\nr = np.random.RandomState()\n",
     "without a seed"),
    ("for x in {1, 2}:\n    pass\n", "set"),
    ("out = [x for x in set(range(3))]\n", "set"),
])
def test_determinism_positive(snippet, needle):
    findings, _ = _lint("dptpu/data/sampler.py", snippet,
                        only=["determinism"])
    assert _rules_of(findings) == ["determinism"], snippet
    assert needle in findings[0].message


def test_determinism_seeded_and_monotonic_are_clean():
    findings, _ = _lint(
        "dptpu/resilience/faults.py",
        "import random\nimport time\nimport numpy as np\n"
        "r = random.Random(7)\n"
        "g = np.random.RandomState(0)\n"
        "d = np.random.default_rng(3)\n"
        "t = time.monotonic()\n"
        "for x in sorted({1, 2}):\n    pass\n",
        only=["determinism"],
    )
    assert findings == []


def test_determinism_scoped_to_bit_identity_surfaces():
    findings, _ = _lint(
        "dptpu/serve/engine.py", "import time\nts = time.time()\n",
        only=["determinism"],
    )
    assert findings == []


# ------------------------------------------------------------ host-sync


@pytest.mark.parametrize("snippet,needle", [
    ("import jax\nv = jax.device_get(x)\n", "device_get"),
    ("v = arr.item()\n", ".item()"),
    ("arr.block_until_ready()\n", "dispatch queue"),
    ("import numpy as np\nv = np.asarray(arr)\n", "host"),
    ("v = float(arr)\n", "sync"),
])
def test_host_sync_positive_in_step(snippet, needle):
    findings, _ = _lint("dptpu/train/step.py", snippet,
                        only=["host-sync"])
    assert _rules_of(findings) == ["host-sync"], snippet
    assert needle in findings[0].message


def test_host_sync_scoped_to_hot_files_and_prefetcher():
    # not a hot file -> clean
    findings, _ = _lint("dptpu/obs/report.py",
                        "v = arr.item()\n", only=["host-sync"])
    assert findings == []
    # loader.py outside DevicePrefetcher -> clean; inside -> finding
    src = """\
    import jax

    def worker():
        return jax.device_get(x)

    class DevicePrefetcher:
        def go(self):
            return jax.device_get(x)
    """
    findings, _ = _lint("dptpu/data/loader.py", src, only=["host-sync"])
    assert len(findings) == 1
    assert findings[0].line == 8


def test_host_sync_float_not_flagged_in_loop():
    # loop.py converts ALREADY-FETCHED host scalars with float(); the
    # device_get sites are the policed sync points there
    findings, _ = _lint("dptpu/train/loop.py",
                        'v = float(m["loss"])\n', only=["host-sync"])
    assert findings == []


# ---------------------------------------------------------- shm-hygiene


def test_shm_direct_creation_flagged():
    findings, _ = _lint(
        "dptpu/data/newring.py",
        "from multiprocessing import shared_memory\n"
        "s = shared_memory.SharedMemory(name='x', create=True, size=4)\n",
        only=["shm-hygiene"],
    )
    assert _rules_of(findings) == ["shm-hygiene"]
    assert "create_named_segment" in findings[0].message


def test_shm_census_prefix_enforced():
    findings, _ = _lint(
        "dptpu/data/newring.py",
        "from dptpu.data.shm_cache import create_named_segment\n"
        "a = create_named_segment('dptpu_ring', 64)\n"
        "b = create_named_segment('dptpu_rogue', 64)\n",
        only=["shm-hygiene"],
    )
    assert len(findings) == 1
    assert findings[0].line == 3
    assert "census" in findings[0].message


def test_shm_module_const_prefix_resolves():
    findings, _ = _lint(
        "dptpu/serve/newstage.py",
        "from dptpu.data.shm_cache import create_named_segment\n"
        "SEGMENT_PREFIX = 'dptpu_serve'\n"
        "s = create_named_segment(SEGMENT_PREFIX, 64)\n",
        only=["shm-hygiene"],
    )
    assert findings == []


# ------------------------------------------------------------ shard-map


def test_shard_map_raw_call_flagged_nocheck_wrapper_clean():
    src = """\
    from jax import shard_map

    def shard_map_nocheck(f, mesh, in_specs, out_specs):
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)

    def make_step(mesh):
        return shard_map(lambda s: s, mesh=mesh, in_specs=(),
                         out_specs=())
    """
    findings, _ = _lint("dptpu/parallel/newstep.py", src,
                        only=["shard-map"])
    assert len(findings) == 1
    assert findings[0].line == 8
    assert "check_rep=False" in findings[0].message


def test_shard_map_axis_names_threading():
    src = """\
    from dptpu.train.step import train_step_body

    def good(state, batch):
        return train_step_body(state, batch, axis_names=("data",))

    def bad(state, batch):
        return train_step_body(state, batch)
    """
    findings, _ = _lint("dptpu/parallel/newstep.py", src,
                        only=["shard-map"])
    assert len(findings) == 1
    assert findings[0].line == 7
    assert "axis_names" in findings[0].message


# ----------------------------------------------------- pragma mechanics


def test_pragma_suppresses_and_is_censused():
    findings, sups = _lint(
        "dptpu/train/step.py",
        "v = arr.item()  "
        "# dptpu: allow-host-sync(measured harness needs the sync)\n",
    )
    assert findings == []
    assert len(sups) == 1
    assert sups[0].rule == "host-sync"
    assert sups[0].reason == "measured harness needs the sync"


def test_pragma_reason_is_mandatory():
    findings, sups = _lint(
        "dptpu/train/step.py",
        "v = arr.item()  # dptpu: allow-host-sync()\n",
    )
    rules = _rules_of(findings)
    # the empty-reason pragma suppresses nothing AND is itself flagged
    assert "pragma" in rules and "host-sync" in rules
    assert sups == []


def test_pragma_unknown_rule_and_unused_are_findings():
    findings, _ = _lint(
        "dptpu/train/step.py",
        "x = 1  # dptpu: allow-no-such-rule(because)\n"
        "y = 2  # dptpu: allow-host-sync(nothing here syncs)\n",
    )
    msgs = [f.message for f in findings]
    assert any("unknown rule" in m for m in msgs)
    assert any("unused pragma" in m for m in msgs)


def test_pragma_malformed_flagged_but_syntax_docs_are_not():
    findings, _ = _lint(
        "dptpu/train/step.py",
        "x = 1  # dptpu: allow-host-sync no parens\n"
        '"""the syntax is # dptpu: allow-<rule>(<reason>)"""\n',
    )
    assert _rules_of(findings) == ["pragma"]
    assert "malformed" in findings[0].message


def test_pragma_only_suppresses_its_own_rule_and_line():
    findings, _ = _lint(
        "dptpu/train/step.py",
        "v = arr.item()  # dptpu: allow-determinism(wrong rule)\n",
    )
    rules = _rules_of(findings)
    assert "host-sync" in rules          # still found
    assert "pragma" in rules             # and the pragma is unused


# ------------------------------------------------------------- env_str


def test_env_str_contract():
    assert env_str("DPTPU_X", None, environ={}) is None
    assert env_str("DPTPU_X", "d", environ={"DPTPU_X": ""}) == "d"
    assert env_str("DPTPU_X", "d", environ={"DPTPU_X": "  v  "}) == "v"
