"""Acceptance: a preempted run resumes BIT-IDENTICALLY (tier-1, synthetic).

The resilience tentpole's end-to-end claim (ISSUE 2): kill a training run
mid-epoch with SIGTERM (injected via ``DPTPU_FAULT=sigterm@step=N``), and
the ``--resume`` run — replaying the deterministic ``(seed, epoch,
index)`` sampler to the checkpoint's exact ``(epoch, step_in_epoch)`` —
produces the SAME final parameters and the SAME loss trajectory as the
run that was never interrupted. Not approximately: bit for bit (XLA CPU
is run-to-run deterministic for identical programs and inputs).

Also locked here: ``--ckpt-steps`` rotation through the real trainer, and
resume falling back past a truncated newest checkpoint to an older
verifiable one — which, under the replay contract, STILL converges to the
bit-identical trajectory (it just re-earns a few steps).

Synthetic data + resnet18@32px on the single-device path keeps this in
the tier-1 budget (one model compile, reused by every run in-process).
"""

import os

import jax
import numpy as np
import pytest

from dptpu.config import Config
from dptpu.resilience import find_resumable, step_checkpoint_name
from dptpu.train import fit


def _cfg(**kw):
    base = dict(
        data="synthetic:96",
        arch="resnet18",
        epochs=2,
        batch_size=24,
        lr=0.02,
        workers=2,
        print_freq=100,
        seed=1,
        gpu=0,  # single-device jit path; 96/24 = 4 steps per epoch
    )
    base.update(kw)
    return Config(**base)


def _params_max_delta(state_a, state_b) -> float:
    la = jax.tree_util.tree_leaves(jax.device_get(state_a.params))
    lb = jax.tree_util.tree_leaves(jax.device_get(state_b.params))
    assert len(la) == len(lb)
    return max(
        float(np.max(np.abs(np.asarray(x) - np.asarray(y))))
        for x, y in zip(la, lb)
    )


@pytest.fixture(scope="module")
def baseline(tmp_path_factory):
    """The uninterrupted 2-epoch run every chaos run must reproduce."""
    d = tmp_path_factory.mktemp("baseline")
    cwd = os.getcwd()
    os.chdir(d)
    try:
        result = fit(_cfg(), image_size=32, verbose=False)
    finally:
        os.chdir(cwd)
    assert result["epochs_run"] == 2
    return result


def test_sigterm_midepoch_resume_is_bit_identical(baseline, tmp_path,
                                                  monkeypatch):
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("DPTPU_FAULT", "sigterm@step=2")
    r1 = fit(_cfg(), image_size=32, verbose=False)
    assert r1["preempted"] is True
    assert r1["epochs_run"] == 0  # died inside epoch 0
    # the preemption save landed at the exact position: epoch 0, 2 steps
    assert os.path.exists(step_checkpoint_name(0, 2))

    monkeypatch.delenv("DPTPU_FAULT")
    # a changed batch geometry voids the replay contract — fail fast,
    # and the message names BOTH the saved and the current (world_size,
    # global_batch, accum) tuples (the coordinates an elastic-resume
    # remapper needs, ROADMAP item 3b) — locked here so a reworded
    # error cannot degrade back to a bare mismatch
    with pytest.raises(ValueError, match="batch geometry changed") as ei:
        fit(_cfg(resume=".", batch_size=12), image_size=32, verbose=False)
    msg = str(ei.value)
    # (derive() counts the 8 fake local devices even on the gpu-pinned
    # path; what matters is that save and resume agree on the frame)
    assert "(8, 24, 1)" in msg  # the SAVED (world, global_batch, accum)
    assert "(8, 8, 1)" in msg   # the CURRENT tuple (12//8 -> 1/chip)
    assert "world_size" in msg and "global_batch" in msg
    # a changed accumulation depth alone is ALSO a geometry change:
    # the virtual-replica microbatch streams differ, so the replay
    # would diverge silently (accum=3 divides the 3/chip batch, so the
    # geometry check is the FIRST error hit)
    monkeypatch.setenv("DPTPU_ACCUM", "3")
    with pytest.raises(ValueError, match="batch geometry changed") as ei:
        fit(_cfg(resume="."), image_size=32, verbose=False)
    assert "(8, 24, 3)" in str(ei.value)
    monkeypatch.delenv("DPTPU_ACCUM")
    # LEGACY (pre-geometry) checkpoints — world_size absent — still get
    # the data_position cross-check: the tuple check stands down and
    # the fallback fires on a position that disagrees with
    # step x THIS run's host batch (a batch-18 run's 2x18=36 samples
    # resumed at batch 24 expects 2x24=48)
    from dptpu.train.checkpoint import save_checkpoint

    # in a SIBLING dir so the newest-mtime scan of "." below still
    # resolves the real preemption save, not this synthetic file
    legacy = os.path.join("legacy", step_checkpoint_name(0, 2))
    save_checkpoint(
        baseline["state"], epoch=0, arch="resnet18", best_acc1=0.0,
        is_best=False, directory="legacy",
        filename=step_checkpoint_name(0, 2), step_in_epoch=2,
        data_position=36, geometry=None,
    )
    with pytest.raises(ValueError,
                       match="samples consumed per host") as ei:
        fit(_cfg(resume=legacy), image_size=32, verbose=False)
    assert "batch geometry changed" in str(ei.value)
    r2 = fit(_cfg(resume="."), image_size=32, verbose=False)
    assert r2["preempted"] is False
    assert r2["epochs_run"] == 2  # epoch 0 (resumed mid-way) + epoch 1

    # THE claim: bit-identical to the run that was never killed
    assert _params_max_delta(baseline["state"], r2["state"]) == 0.0
    for hb, hr in zip(baseline["history"], r2["history"]):
        assert hb["epoch"] == hr["epoch"]
        # end-of-epoch state matches exactly, so validation matches
        # exactly — including the resumed epoch itself
        assert hb["val_loss"] == hr["val_loss"]
        assert hb["val_top1"] == hr["val_top1"]
    # epochs after the interruption also train identically step for step
    assert baseline["history"][1]["train_loss"] == \
        r2["history"][1]["train_loss"]


def test_midepoch_resume_under_decode_ahead(baseline, tmp_path,
                                            monkeypatch):
    """The lookahead-resume contract (ISSUE 4): with the process-mode
    decode-ahead ring pre-issuing spans for several future batches —
    plus speculation armed — a SIGTERM mid-epoch must still save the
    exact consumed position (pre-issued-but-unconsumed batches do NOT
    count), and ``--resume`` must replay to it bit-identically against
    the thread-mode, no-lookahead baseline."""
    monkeypatch.chdir(tmp_path)
    for k, v in (("DPTPU_WORKERS_MODE", "process"),
                 ("DPTPU_DECODE_AHEAD", "4"),
                 ("DPTPU_RING_DEPTH", "8"),
                 ("DPTPU_SPECULATE", "1"),
                 ("DPTPU_FAULT", "sigterm@step=2")):
        monkeypatch.setenv(k, v)
    r1 = fit(_cfg(), image_size=32, verbose=False)
    assert r1["preempted"] is True
    assert os.path.exists(step_checkpoint_name(0, 2))

    monkeypatch.delenv("DPTPU_FAULT")
    r2 = fit(_cfg(resume="."), image_size=32, verbose=False)
    assert r2["epochs_run"] == 2
    assert _params_max_delta(baseline["state"], r2["state"]) == 0.0
    for hb, hr in zip(baseline["history"], r2["history"]):
        assert hb["val_loss"] == hr["val_loss"]


def test_ckpt_steps_rotation_and_corrupt_fallback(baseline, tmp_path,
                                                  monkeypatch):
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("DPTPU_FAULT", "sigterm@step=3")
    r1 = fit(_cfg(ckpt_steps=1, ckpt_keep=2), image_size=32, verbose=False)
    assert r1["preempted"] is True
    # --ckpt-steps 1 saved after steps 1..3; --ckpt-keep 2 pruned step 1
    # (the preemption save coincides with the step-3 rotation member)
    names = sorted(f for f in os.listdir(".") if f.startswith("checkpoint-e"))
    assert names == [step_checkpoint_name(0, 2), step_checkpoint_name(0, 3)]

    # tear the NEWEST checkpoint: resume must fall back to step 2 and,
    # because replay is deterministic, still land bit-identically
    newest = step_checkpoint_name(0, 3)
    with open(newest, "r+b") as f:
        f.truncate(os.path.getsize(newest) // 2)
    assert find_resumable(".", verbose=False).endswith(
        step_checkpoint_name(0, 2)
    )
    monkeypatch.delenv("DPTPU_FAULT")
    r2 = fit(_cfg(resume="."), image_size=32, verbose=False)
    assert r2["epochs_run"] == 2
    assert _params_max_delta(baseline["state"], r2["state"]) == 0.0
    assert baseline["history"][1]["val_loss"] == \
        r2["history"][1]["val_loss"]


def test_elastic_shrink_resume_replays_exact_remainder(baseline, tmp_path,
                                                      monkeypatch):
    """The elastic tentpole at fit() level (ROADMAP item 3a): a run
    preempted at step 2 of an (8, 24, 1) geometry resumes on the SHRUNK
    (8, 16, 1) geometry under DPTPU_ELASTIC=1 — the remapped position
    (48 consumed / 16 = step 3 of 6) replays exactly the untrained
    remainder (index-set Δ = ∅ against the pure sampler oracle), the
    replay is deterministic (a second elastic resume from a pristine
    copy of the checkpoint is bit-identical in params AND losses), and
    the remap details land in result["elastic"]. Without the opt-in the
    geometry mismatch still fails fast, now naming DPTPU_ELASTIC."""
    import shutil

    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("DPTPU_FAULT", "sigterm@step=2")
    r1 = fit(_cfg(), image_size=32, verbose=False)
    assert r1["preempted"] is True
    assert os.path.exists(step_checkpoint_name(0, 2))
    monkeypatch.delenv("DPTPU_FAULT")

    # the fail-fast without the opt-in now names the elastic knob
    with pytest.raises(ValueError, match="DPTPU_ELASTIC"):
        fit(_cfg(resume=".", batch_size=16), image_size=32, verbose=False)

    # a pristine copy is the same-geometry replay reference's source
    os.makedirs("ref")
    for f in os.listdir("."):
        if f.startswith("checkpoint"):
            shutil.copy(f, os.path.join("ref", f))

    monkeypatch.setenv("DPTPU_ELASTIC", "1")
    # an indivisible consumed prefix still fails fast, naming a fix:
    # 48 consumed does not split into whole batches of 36
    with pytest.raises(ValueError, match="Pick a global batch"):
        fit(_cfg(resume=".", batch_size=36), image_size=32, verbose=False)

    r2 = fit(_cfg(resume=".", batch_size=16), image_size=32, verbose=False)
    assert r2["epochs_run"] == 2
    el = r2["elastic"]
    assert el["saved_geometry"] == [8, 24, 1]
    assert el["new_geometry"] == [8, 16, 1]
    assert el["consumed"] == 48
    assert el["resume_step"] == 3
    # the resumed epoch trained exactly the 3-step remainder (96 - 48
    # = 48 samples at the new global batch of 16)
    assert r2["history"][0]["train_num_batches"] == 3
    assert r2["history"][0]["train_steps_done"] == 6

    # Δ = ∅: trained prefix ∪ elastic remainder == the epoch-0 visit
    # set, straight from the pure (seed, epoch) sampler math the
    # loaders run
    from dptpu.data.sampler import ShardedSampler
    from dptpu.resilience.elastic import remainder_indices

    order = ShardedSampler(96, shuffle=True, seed=1).indices(0)
    rem = remainder_indices(96, seed=1, epoch=0, consumed=48,
                            global_batch=16)
    assert set(int(i) for i in order[:48]).union(
        int(i) for i in rem) == set(range(96))
    assert np.array_equal(np.sort(np.asarray(order[48:])), rem)

    # the same-geometry replay reference: a second elastic resume from
    # the pristine checkpoint copy must be bit-identical
    monkeypatch.chdir(tmp_path / "ref")
    r3 = fit(_cfg(resume=".", batch_size=16), image_size=32, verbose=False)
    assert _params_max_delta(r2["state"], r3["state"]) == 0.0
    for h2, h3 in zip(r2["history"], r3["history"]):
        assert h2["val_loss"] == h3["val_loss"]
        assert h2["train_loss"] == h3["train_loss"]
    monkeypatch.delenv("DPTPU_ELASTIC")


def test_emergency_checkpoint_on_unexpected_crash(tmp_path, monkeypatch):
    """An exception mid-epoch (not a signal — a bug, an OOM, a loader
    blow-up) still leaves a resumable checkpoint at the last completed
    step: the try/finally satellite."""

    class Boom(RuntimeError):
        pass

    from dptpu.train import loop as loop_mod

    real = loop_mod.jax.device_get
    calls = {"n": 0}

    def exploding_device_get(x):
        calls["n"] += 1
        if calls["n"] == 2:  # first display sync survives; next dies
            raise Boom("injected mid-epoch crash")
        return real(x)

    monkeypatch.chdir(tmp_path)
    monkeypatch.setattr(loop_mod.jax, "device_get", exploding_device_get)
    with pytest.raises(Boom):
        fit(_cfg(print_freq=1), image_size=32, verbose=False)
    monkeypatch.setattr(loop_mod.jax, "device_get", real)
    saved = [f for f in os.listdir(".") if f.startswith("checkpoint-e")]
    assert saved, "emergency save did not run"
    resolved = find_resumable(".", verbose=False)
    assert resolved is not None


def test_overlap_midepoch_resume_is_bit_identical(tmp_path_factory,
                                                  monkeypatch):
    """ISSUE 13: DPTPU_OVERLAP=1 (bucketed in-backward reductions on
    the 8-device mesh) + mid-epoch SIGTERM + --resume reproduces the
    uninterrupted overlap-on run bit for bit — the overlap engine
    changes WHERE the collectives run, never what the replay contract
    sees."""
    monkeypatch.setenv("DPTPU_OVERLAP", "1")
    monkeypatch.setenv("DPTPU_BUCKET_MB", "1")
    cfg_kw = dict(gpu=None, batch_size=24, epochs=2)  # the full fake pod
    da = tmp_path_factory.mktemp("overlap_base")
    cwd = os.getcwd()
    os.chdir(da)
    try:
        ra = fit(_cfg(**cfg_kw), image_size=32, verbose=False)
    finally:
        os.chdir(cwd)
    assert ra["epochs_run"] == 2

    db = tmp_path_factory.mktemp("overlap_chaos")
    monkeypatch.chdir(db)
    monkeypatch.setenv("DPTPU_FAULT", "sigterm@step=2")
    r1 = fit(_cfg(**cfg_kw), image_size=32, verbose=False)
    assert r1["preempted"] is True
    monkeypatch.delenv("DPTPU_FAULT")
    r2 = fit(_cfg(resume=str(db), **cfg_kw), image_size=32,
             verbose=False)
    assert r2["epochs_run"] == 2
    assert _params_max_delta(ra["state"], r2["state"]) == 0.0


def test_batch_ramp_resume_in_ramped_phase_is_bit_identical(
        tmp_path_factory, monkeypatch):
    """ISSUE 13 satellite: the batch ramp stamps the PHASE geometry
    into every checkpoint, so a SIGTERM inside the RAMPED phase (the
    batch just doubled, the loader/step were rebuilt, the LR rescaled)
    resumes bit-identically — and the resumed run reconstructs the
    phase schedule from the ramp table alone."""
    monkeypatch.setenv("DPTPU_BATCH_RAMP", "2:2")
    cfg_kw = dict(gpu=None, batch_size=24, epochs=3, warmup_epochs=1)
    da = tmp_path_factory.mktemp("ramp_base")
    cwd = os.getcwd()
    os.chdir(da)
    try:
        ra = fit(_cfg(**cfg_kw), image_size=32, verbose=False)
    finally:
        os.chdir(cwd)
    assert ra["epochs_run"] == 3
    assert [p["mult"] for p in ra["batch_ramp"]] == [1, 2]

    db = tmp_path_factory.mktemp("ramp_chaos")
    monkeypatch.chdir(db)
    # phase 0: 96/24 = 4 steps x 2 epochs; phase 1 (epoch 2): batch 48,
    # 2 steps. Step 9 = one step INTO the ramped phase.
    monkeypatch.setenv("DPTPU_FAULT", "sigterm@step=9")
    r1 = fit(_cfg(**cfg_kw), image_size=32, verbose=False)
    assert r1["preempted"] is True
    monkeypatch.delenv("DPTPU_FAULT")
    r2 = fit(_cfg(resume=str(db), **cfg_kw), image_size=32,
             verbose=False)
    assert r2["epochs_run"] >= 1
    assert _params_max_delta(ra["state"], r2["state"]) == 0.0


def test_batch_ramp_resume_wrong_ramp_fails_actionably(
        tmp_path_factory, monkeypatch):
    """A checkpoint saved inside a ramped phase must refuse a resume
    whose ramp spec puts that epoch at a DIFFERENT geometry — naming
    the spec, not silently replaying the wrong batch."""
    monkeypatch.setenv("DPTPU_BATCH_RAMP", "1:2")
    cfg_kw = dict(gpu=None, batch_size=24, epochs=3, warmup_epochs=1)
    d = tmp_path_factory.mktemp("ramp_wrong")
    monkeypatch.chdir(d)
    # stop INSIDE the ramped phase (epoch 1, batch 48: 4 + 1 steps)
    monkeypatch.setenv("DPTPU_FAULT", "sigterm@step=5")
    r1 = fit(_cfg(**cfg_kw), image_size=32, verbose=False)
    assert r1["preempted"] is True
    monkeypatch.delenv("DPTPU_FAULT")
    # resume under a DIFFERENT ramp (epoch 1 now x4): geometry mismatch
    monkeypatch.setenv("DPTPU_BATCH_RAMP", "1:4")
    with pytest.raises(ValueError, match="DPTPU_BATCH_RAMP"):
        fit(_cfg(resume=str(d), **cfg_kw), image_size=32, verbose=False)


def test_sharding_fingerprint_mismatch_fails_then_elastic_reshards(
        tmp_path_factory, monkeypatch):
    """ISSUE 16: checkpoints are stamped with the sharding fingerprint
    (rules-table hash + placement). A MID-EPOCH resume whose run places
    differently must fail fast naming BOTH stamps — the replay contract
    cannot promise bit-identity across a placement change — and
    ``DPTPU_ELASTIC=1`` opts into the explicit re-shard (checkpoints
    hold gathered full leaves, so the load itself is placement-free).
    Pod-path run (one extra resnet18@32 ZeRO-3 compile — the module's
    second deliberate compile, carrying the ISSUE acceptance bar)."""
    d = tmp_path_factory.mktemp("shard_fp")
    monkeypatch.chdir(d)
    monkeypatch.setenv("DPTPU_ZERO", "3")
    monkeypatch.setenv("DPTPU_FAULT", "sigterm@step=2")
    r1 = fit(_cfg(gpu=None, workers=0), image_size=32, verbose=False)
    assert r1["preempted"] is True
    monkeypatch.delenv("DPTPU_FAULT")
    monkeypatch.delenv("DPTPU_ZERO")
    # resume as plain DDP: mid-epoch + changed placement -> fail-fast
    # naming both the saved and the current sharding tag
    with pytest.raises(ValueError) as exc:
        fit(_cfg(gpu=None, workers=0, resume=str(d)), image_size=32,
            verbose=False)
    msg = str(exc.value)
    assert "zero3" in msg and "replicated" in msg
    # the waiver: elastic re-shard resumes and completes
    monkeypatch.setenv("DPTPU_ELASTIC", "1")
    r2 = fit(_cfg(gpu=None, workers=0, resume=str(d)), image_size=32,
             verbose=False)
    assert r2["epochs_run"] == 2


def test_sharding_fingerprint_same_placement_resumes_unwaivered(
        tmp_path_factory, monkeypatch):
    """Control for the fingerprint gate: resuming under the SAME
    sharding needs no DPTPU_ELASTIC waiver (reuses the ZeRO-3 pod
    compile from the mismatch test, in-process jit cache)."""
    d = tmp_path_factory.mktemp("shard_fp_same")
    monkeypatch.chdir(d)
    monkeypatch.setenv("DPTPU_ZERO", "3")
    monkeypatch.setenv("DPTPU_FAULT", "sigterm@step=2")
    r1 = fit(_cfg(gpu=None, workers=0), image_size=32, verbose=False)
    assert r1["preempted"] is True
    monkeypatch.delenv("DPTPU_FAULT")
    r2 = fit(_cfg(gpu=None, workers=0, resume=str(d)), image_size=32,
             verbose=False)
    assert r2["epochs_run"] == 2
