"""Template construction for the torchvision parity harness (ADVICE r5).

``tree_map(np.zeros_like, jax.eval_shape(...))`` yields 0-d OBJECT
arrays (numpy treats a ShapeDtypeStruct as a scalar), which made the
published-weights parity section crash wherever torch actually exists.
``make_zeros_template`` must produce real zero arrays with the model's
leaf shapes/dtypes — locked here so the fix can't regress unnoticed.
"""

import os
import sys

import numpy as np

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ),
)
import check_tv_parity  # noqa: E402


def test_make_zeros_template_builds_real_arrays():
    import jax

    from dptpu.models import create_model

    model = create_model("resnet18", num_classes=10)
    template = check_tv_parity.make_zeros_template(model, 32)

    assert set(template) == {"params", "batch_stats"}
    leaves = jax.tree_util.tree_leaves(template)
    assert leaves
    for leaf in leaves:
        assert isinstance(leaf, np.ndarray)
        assert leaf.dtype != np.dtype(object)  # the regression mode
        assert leaf.ndim >= 1  # 0-d scalars were the crash

    # shapes/dtypes agree leaf-for-leaf with an abstract init
    want = jax.eval_shape(
        lambda: model.init(
            jax.random.PRNGKey(0),
            np.zeros((1, 32, 32, 3), np.float32),
            train=False,
        )
    )
    want = {k: want[k] for k in ("params", "batch_stats") if k in want}
    got_shapes = jax.tree_util.tree_map(lambda a: (a.shape, a.dtype), template)
    want_shapes = jax.tree_util.tree_map(
        lambda s: (s.shape, s.dtype), want
    )
    assert got_shapes == want_shapes
