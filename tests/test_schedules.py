"""LR schedule math vs the reference formulas (imagenet_ddp.py:374-378;
imagenet_ddp_apex.py:161-162,527-543)."""

import pytest

from dptpu.ops.schedules import (
    scale_lr_linear,
    step_decay_lr,
    warmup_step_decay_lr,
)


@pytest.mark.parametrize(
    "epoch,expected_factor",
    [(0, 1.0), (29, 1.0), (30, 0.1), (59, 0.1), (60, 0.01), (89, 0.01), (90, 0.001)],
)
def test_step_decay(epoch, expected_factor):
    assert step_decay_lr(0.1, epoch) == pytest.approx(0.1 * expected_factor)


def test_apex_decay_extra_factor_at_80():
    # epoch 80: factor = 80//30 + 1 = 3 → lr = 0.1 * 1e-3
    assert warmup_step_decay_lr(0.1, 80, 1, 100) == pytest.approx(0.1 * 1e-3)
    # epoch 79: factor = 2
    assert warmup_step_decay_lr(0.1, 79, 1, 100) == pytest.approx(0.1 * 1e-2)


def test_apex_warmup_linear_in_global_step():
    base, len_epoch = 0.4, 100
    # reference: lr * (1 + step + epoch*len_epoch) / (5*len_epoch)
    for epoch in range(5):
        for step in (1, 50, 100):
            got = warmup_step_decay_lr(base, epoch, step, len_epoch)
            want = base * float(1 + step + epoch * len_epoch) / (5.0 * len_epoch)
            assert got == pytest.approx(want)
    # warmup reaches ~base at end of epoch 4 and is exact beyond
    assert warmup_step_decay_lr(base, 5, 1, len_epoch) == pytest.approx(base)


def test_warmup_is_monotonic_until_epoch5():
    prev = 0.0
    for epoch in range(5):
        for step in range(1, 101):
            lr = warmup_step_decay_lr(0.4, epoch, step, 100)
            assert lr > prev
            prev = lr


def test_linear_scaling_rule():
    # imagenet_ddp_apex.py:162 — lr * batch*world/256
    assert scale_lr_linear(0.1, 224 * 16) == pytest.approx(0.1 * 224 * 16 / 256.0)
    assert scale_lr_linear(0.1, 256) == pytest.approx(0.1)
