"""The cost-model extraction lock (ISSUE 19 satellite): the simulated-
pod model moved from scripts/run_racebench.py into
dptpu/tune/costmodel.py so the autotuner can score candidates against
it — these tests prove the move behavior-preserving by RECOMPUTING the
committed RACEBENCH.json ``chip_equivalent`` rows from the extracted
functions. The chip anchor is exactly reconstructible
(``per_chip_batch / chip_img_per_s``); the ``measured_host`` rows carry
a host-measured step time, so they are checked for internal
consistency rather than bit-equality."""

import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHIP_IMG_PER_S = 2734.0  # BENCH_r04 anchor (run_racebench default)


@pytest.fixture(scope="module")
def racebench():
    with open(os.path.join(REPO, "RACEBENCH.json")) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def perleaf_sizes(racebench):
    """Per-leaf gradient bytes in issue order, rebuilt from the
    artifact's recorded arch via shapes only (eval_shape: no init)."""
    from dptpu.tune.search import model_leaf_sizes

    return model_leaf_sizes(
        racebench["arch"], image_size=racebench["image"], num_classes=16
    )


def test_leaf_profile_matches_artifact(racebench, perleaf_sizes):
    assert sum(perleaf_sizes) == racebench["grad_bytes"]
    assert len(perleaf_sizes) == racebench["param_leaves"]


def test_chip_equivalent_rows_locked(racebench, perleaf_sizes):
    """Every committed chip_equivalent row recomputes EXACTLY from the
    extracted model — rounding included. A drift here means the
    extraction changed the model the committed bench numbers came
    from."""
    from dptpu.tune.costmodel import greedy_bucket_sizes, model_row

    latency_s = racebench["model_assumptions"]["dcn_latency_us"] * 1e-6
    t_chip = racebench["per_chip_batch"] / CHIP_IMG_PER_S
    rows = [r for r in racebench["simulated_pod"]
            if r["compute_anchor"] == "chip_equivalent"]
    assert rows, "RACEBENCH.json lost its chip_equivalent rows"
    for committed in rows:
        sizes = greedy_bucket_sizes(
            perleaf_sizes, int(committed["bucket_mb"] * 1e6)
        )
        got = model_row(
            "chip_equivalent", t_chip, committed["bucket_mb"], sizes,
            perleaf_sizes, committed["dcn_gbps"], latency_s,
            racebench["slices"], racebench["chips_per_slice"],
        )
        assert got == committed, (
            f"extracted model drifted at bucket "
            f"{committed['bucket_mb']} MB / {committed['dcn_gbps']} "
            f"GB/s:\n got {got}\n want {committed}"
        )


def test_headline_speedup_locked(racebench):
    """The headline simulated-pod claim: 1.604x chip-equivalent speedup
    at 12.5 GB/s DCN with 1 MB buckets, >= 92% of the communication
    hidden under backward."""
    head = next(
        r for r in racebench["simulated_pod"]
        if r["compute_anchor"] == "chip_equivalent"
        and r["bucket_mb"] == 1.0 and r["dcn_gbps"] == 12.5
    )
    assert head["speedup"] == 1.604
    assert head["hidden_comm_fraction"] >= 0.92
    assert head["buckets"] == 15


def test_measured_host_rows_internally_consistent(racebench):
    """The measured_host anchor carries a 3-dp-rounded step time, so
    bit-recomputation is not meaningful — but every committed row must
    still satisfy the model's own identities."""
    for r in racebench["simulated_pod"]:
        if r["compute_anchor"] != "measured_host":
            continue
        assert r["overlapped_ms"] <= r["serial_ms"]
        assert r["overlapped_ms"] >= r["compute_ms"]
        assert r["serial_ms"] <= r["perleaf_serial_ms"]
        assert r["speedup"] == pytest.approx(
            r["serial_ms"] / r["overlapped_ms"], abs=2e-3
        )
        assert r["exposed_comm_ms"] == pytest.approx(
            r["overlapped_ms"] - r["compute_ms"], abs=2e-3
        )


def test_greedy_matches_engine_partition(racebench, perleaf_sizes):
    """greedy_bucket_sizes (the tuner's jax-free sweep) reproduces the
    real engine partition (partition_buckets + bucket_sizes_bytes) for
    every candidate bucket size — same close-before-exceed rule, same
    reverse-flatten walk."""
    import jax
    import jax.numpy as jnp

    from dptpu.models import create_model
    from dptpu.parallel.overlap import bucket_sizes_bytes, partition_buckets
    from dptpu.tune.costmodel import greedy_bucket_sizes

    model = create_model(racebench["arch"], num_classes=16)
    variables = jax.eval_shape(
        lambda rng: model.init(
            rng,
            jnp.zeros((1, racebench["image"], racebench["image"], 3),
                      jnp.float32),
            train=False,
        ),
        jax.random.PRNGKey(0),
    )
    params = variables["params"]
    for mb in (0.25, 1.0, 8.0, 25.0, 1000.0):
        want = bucket_sizes_bytes(
            params, partition_buckets(params, int(mb * 1e6))
        )
        got = greedy_bucket_sizes(perleaf_sizes, int(mb * 1e6))
        assert got == want, f"partition drift at {mb} MB"


def test_simulate_pod_identities():
    """Model invariants the tuner's sweep relies on, independent of any
    committed artifact."""
    from dptpu.tune.costmodel import simulate_pod

    sizes = [4_000_000, 3_000_000, 2_000_000, 1_000_000]
    sim = simulate_pod(sizes, 0.01, 25.0, 15e-6, 2, 2)
    assert sim["overlapped_s"] <= sim["serial_s"]
    assert sim["overlapped_s"] >= 0.01  # never beats pure compute
    assert len(sim["events"]) == len(sizes)
    # the FIFO channel never reorders or overlaps with itself
    for a, b in zip(sim["events"], sim["events"][1:]):
        assert b["comm_start_s"] >= a["comm_end_s"]
        assert a["comm_start_s"] >= a["grads_ready_s"]
    # one giant bucket: no pipelining, everything exposed after compute
    one = simulate_pod([sum(sizes)], 0.01, 25.0, 15e-6, 2, 2)
    assert one["overlapped_s"] == pytest.approx(one["serial_s"])
