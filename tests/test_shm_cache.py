"""Pooled cross-process decode cache (dptpu/data/shm_cache.py).

The contract under test: one /dev/shm slab pools the whole
``DPTPU_CACHE_BYTES`` budget across every worker process — any worker
hits any cached image, hit ≡ miss bit-identical, byte budget respected
with oldest-first eviction, oversized entries rejected, and the slab
SURVIVES a supervisor pool restart warm (it belongs to the parent's
dataset, not to the workers). Pooled, sharded and cache-off loaders must
all yield the same bytes for the same ``(seed, epoch, index)`` RNG.

JPEG fixtures are 52×44 (< 48·8/7): the native scale picker then stays
at full resolution, which makes cache-on/off comparisons bit-exact (see
ImageFolderDataset docstring) — the same fixture discipline as
tests/test_shm_loader.py.
"""

import numpy as np
import pytest
from PIL import Image

from dptpu.data import (
    DataLoader,
    ImageFolderDataset,
    ShmDecodeCache,
    train_transform,
)


@pytest.fixture(scope="module")
def jpeg_folder(tmp_path_factory):
    root = tmp_path_factory.mktemp("shmcachejpeg")
    rng = np.random.RandomState(7)
    for cls in ["c0", "c1"]:
        d = root / cls
        d.mkdir()
        for i in range(9):
            low = rng.randint(0, 255, (8, 7, 3), np.uint8)
            img = Image.fromarray(low).resize((52, 44), Image.BILINEAR)
            img.save(str(d / f"{i}.jpg"), quality=85)
    return str(root)


def _assert_batches_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x["images"], y["images"])
        np.testing.assert_array_equal(x["labels"], y["labels"])
        assert ("mask" in x) == ("mask" in y)
        if "mask" in x:
            np.testing.assert_array_equal(x["mask"], y["mask"])


# -- unit: slab semantics ---------------------------------------------------

def test_roundtrip_and_budget_contract():
    c = ShmDecodeCache(1 << 20)
    try:
        rng = np.random.RandomState(0)
        arrs = {i: rng.randint(0, 256, (32, 40, 3), np.uint8)
                for i in range(6)}
        for i, a in arrs.items():
            assert c.put(("k", i), a)
        for i, a in arrs.items():
            got = c.get(("k", i))
            np.testing.assert_array_equal(got, a)
        assert c.hits == 6 and len(c) == 6
        assert c.bytes_in_use <= c.budget_bytes
        # unknown key is a miss
        assert c.get(("k", 99)) is None
        assert c.misses == 1
    finally:
        c.close()


def test_eviction_is_oldest_first_and_budget_holds():
    c = ShmDecodeCache(512 << 10)
    try:
        rng = np.random.RandomState(1)
        arrs = {i: rng.randint(0, 256, (64, 100, 3), np.uint8)
                for i in range(40)}  # ~19 KB each, way past 512 KB total
        for i, a in arrs.items():
            assert c.put(("e", i), a)
            assert c.bytes_in_use <= c.budget_bytes
        assert c.evictions > 0
        # the newest insert always survives; the oldest is gone
        np.testing.assert_array_equal(c.get(("e", 39)), arrs[39])
        assert c.get(("e", 0)) is None
    finally:
        c.close()


def test_oversized_entry_rejected_not_cached():
    c = ShmDecodeCache(256 << 10)
    try:
        big = np.zeros((300, 300, 3), np.uint8)  # 270 KB > 256 KB budget
        assert not c.put("big", big)
        assert len(c) == 0 and c.bytes_in_use == 0
    finally:
        c.close()


def test_wraparound_preserves_survivor_bytes():
    """Ring-arena stress: random-size inserts far past the budget; every
    surviving entry must read back bit-exact (no torn regions across the
    wrap seam)."""
    c = ShmDecodeCache(1 << 20)
    try:
        rng = np.random.RandomState(2)
        kept = {}
        for i in range(300):
            a = rng.randint(
                0, 256,
                (int(rng.randint(8, 90)), int(rng.randint(8, 90)), 3),
                np.uint8,
            )
            if c.put(("w", i), a):
                kept[i] = a
        survivors = 0
        for i, a in kept.items():
            got = c.get(("w", i))
            if got is None:
                continue
            np.testing.assert_array_equal(got, a)
            survivors += 1
        assert survivors > 0
    finally:
        c.close()


def test_scale_budget_is_a_pooled_noop():
    c = ShmDecodeCache(1 << 20)
    try:
        c.scale_budget(8)  # the worker-pool split call: must not shrink
        assert c.budget_bytes == 1 << 20
        with pytest.raises(ValueError):
            c.scale_budget(0)
    finally:
        c.close()


def test_close_unlinks_segment():
    import os

    c = ShmDecodeCache(1 << 20)
    seg = "/dev/shm/" + c.segment_name.lstrip("/")
    if not os.path.exists(seg):
        c.close()
        pytest.skip("/dev/shm not exposed as a filesystem here")
    c.close()
    assert not os.path.exists(seg)
    c.close()  # double-close stays a no-op
    assert c.get("anything") is None and not c.put("x", np.zeros(
        (2, 2, 3), np.uint8))


def test_stats_shape_matches_decode_cache():
    c = ShmDecodeCache(1 << 20)
    try:
        s = c.stats()
        for k in ("cache_hits", "cache_misses", "cache_evictions",
                  "cache_entries", "cache_bytes_in_use",
                  "cache_budget_bytes", "cache_hit_rate"):
            assert k in s
        assert s["cache_scope"] == "pooled"
    finally:
        c.close()


# -- integration: pooled vs sharded vs thread, bit for bit ------------------

def test_pooled_cache_bit_identical_across_modes_and_reshuffles(jpeg_folder):
    """The acceptance bar: pooled-slab process loader ≡ sharded process
    loader ≡ thread loader, across epochs (each epoch is a fresh
    reshuffle), with the pooled cache actually getting hits."""
    mk = lambda scope: ImageFolderDataset(  # noqa: E731
        jpeg_folder, train_transform(48), cache_bytes=32 << 20,
        cache_scope=scope,
    )
    th = DataLoader(mk("sharded"), 4, num_workers=2, seed=5)
    sh = DataLoader(mk("sharded"), 4, num_workers=2, seed=5,
                    workers_mode="process")
    po = DataLoader(mk("pooled"), 4, num_workers=2, seed=5,
                    workers_mode="process")
    try:
        for epoch in (0, 1, 2):
            a = list(th.epoch(epoch))
            _assert_batches_equal(a, list(sh.epoch(epoch)))
            _assert_batches_equal(a, list(po.epoch(epoch)))
        fs = po.feed_stats()
        assert fs["cache_scope"] == "pooled"
        assert fs["cache_hits"] > 0
        assert 0.0 < fs["cache_hit_rate"] <= 1.0
    finally:
        th.close()
        sh.close()
        po.close()


def test_pooled_slab_survives_pool_restart_warm(jpeg_folder):
    """Kill a worker mid-epoch: the supervisor restarts the pool, the
    slab (owned by the parent's dataset) keeps its entries, and the
    epoch completes bit-identical to thread mode."""
    ds = ImageFolderDataset(jpeg_folder, train_transform(48),
                            cache_bytes=32 << 20, cache_scope="pooled")
    th = DataLoader(
        ImageFolderDataset(jpeg_folder, train_transform(48)),
        4, num_workers=2, seed=5,
    )
    pr = DataLoader(ds, 4, num_workers=2, seed=5, workers_mode="process")
    try:
        warm_entries_before = None
        ref = list(th.epoch(0))
        _ = list(pr.epoch(0))  # epoch 0 fills the slab
        pr.feed_stats()  # set the interval baseline at the epoch edge
        warm_entries_before = len(ds.decode_cache)
        assert warm_entries_before > 0
        it = pr.epoch(1)
        got = [next(it)]
        assert pr.kill_one_worker() is not None
        got += list(it)
        _assert_batches_equal(list(th.epoch(1)), got)
        fs = pr.feed_stats()
        assert fs["pool_restarts"] >= 1
        # the restart did NOT cold-start the cache: the slab still holds
        # (at least) the pre-kill working set
        assert len(ds.decode_cache) >= warm_entries_before
        # ... and the respawned pool's counter reset didn't corrupt the
        # interval hit rate (counters fold into a monotonic base): the
        # post-kill epoch ran warm off the surviving slab
        assert fs["cache_hit_rate"] > 0.5
        assert pr.workers_mode == "process"
    finally:
        th.close()
        pr.close()


def test_invalid_cache_scope_rejected(jpeg_folder):
    with pytest.raises(ValueError, match="cache_scope"):
        ImageFolderDataset(jpeg_folder, train_transform(48),
                           cache_bytes=1 << 20, cache_scope="global")
