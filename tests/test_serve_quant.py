"""Quantized serving locks (ISSUE 18).

* ops-level per-channel int8 round trip and the quantized-leaf marker
  contract (``dptpu/ops/quant.py``);
* the calibration artifact: ``dptpu quantize`` end to end, CRC seal,
  and the loader's fail-fast chain — every refusal NAMES the
  recalibration command (the satellite lock);
* the engine's precision axis: int8/bf16 generations on the bucket
  ladder, drift vs fp32 bounded, ≥40% resident-bytes reduction for the
  int8 generation (the acceptance lever);
* the canary top-1 agreement gate: a disagreeing rollout rolls back
  naming the agreement deficit; a quantized rollout under the
  artifact's bounds promotes.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dptpu.ops.quant import (
    cast_tree,
    channel_scales,
    dequantize_leaf,
    dequantize_tree,
    is_quantized_leaf,
    quantize_leaf,
    quantize_tree,
    scales_tree,
    tree_nbytes,
)
from dptpu.serve import ServeEngine
from dptpu.serve.batcher import DynamicBatcher
from dptpu.serve.canary import CanaryController
from dptpu.serve.quant import (
    CalibrationError,
    load_calibration,
    measure_drift,
    quantize_variables,
    save_calibration,
    weights_fingerprint,
)

ARCH = "resnet18"


def _rand_images(n, size, seed=0):
    return np.random.RandomState(seed).randint(
        0, 256, (n, size, size, 3), np.uint8
    )


def _fresh_variables(engine, seed):
    init = engine.model.init(
        jax.random.PRNGKey(seed),
        np.zeros((1, engine.image_size, engine.image_size, 3), np.float32),
        train=False,
    )
    return {"params": init["params"],
            "batch_stats": init.get("batch_stats", {})}


@pytest.fixture(scope="module")
def engine():
    return ServeEngine(ARCH, buckets=(1, 4), num_classes=8,
                       image_size=32, placement="replicated")


def _host_params(engine):
    return jax.tree_util.tree_map(
        np.asarray, engine._host_variables["params"]
    )


def _artifact(engine, tmp_path, name="calib.dptpu", **over):
    params = _host_params(engine)
    kw = dict(
        arch=ARCH, params=params,
        stats={"top1_agreement": 0.95, "max_abs_dlogit": 0.03},
        bounds={"min_top1_agreement": 0.5, "max_abs_dlogit": 10.0},
        num_classes=8, image_size=32, sample_n=8,
    )
    kw.update(over)
    path = str(tmp_path / name)
    save_calibration(path, **kw)
    return path


# ------------------------------------------------------------- ops ----


def test_quantize_leaf_roundtrip_bound():
    rng = np.random.RandomState(0)
    w = rng.randn(16, 32).astype(np.float32) * 0.1
    q, scale = quantize_leaf(w)
    assert q.dtype == jnp.int8 and scale.shape == (32,)
    np.testing.assert_array_equal(scale, channel_scales(w))
    back = np.asarray(dequantize_leaf(q, scale, jnp.float32))
    # symmetric absmax: error per element <= scale/2 = absmax/254
    err = np.abs(back - w)
    assert np.all(err <= np.asarray(scale) / 2 + 1e-7)


def test_quantize_tree_marker_and_passthrough():
    rng = np.random.RandomState(1)
    tree = {
        "dense": {"kernel": rng.randn(8, 4).astype(np.float32),
                  "bias": rng.randn(4).astype(np.float32)},
        "norm": {"scale": np.ones(4, np.float32)},
    }
    qt = quantize_tree(tree)
    assert is_quantized_leaf(qt["dense"]["kernel"])
    # bias and norm params stay fp32, untouched
    np.testing.assert_array_equal(qt["dense"]["bias"],
                                  tree["dense"]["bias"])
    assert not is_quantized_leaf(qt["norm"])
    back = dequantize_tree(qt, jnp.float32)
    assert np.abs(
        np.asarray(back["dense"]["kernel"]) - tree["dense"]["kernel"]
    ).max() < 0.05
    # size ordering: int8 < bf16 < fp32 residency
    n_fp32 = tree_nbytes(tree)
    n_bf16 = tree_nbytes(cast_tree(tree, jnp.bfloat16))
    n_int8 = tree_nbytes(qt)
    assert n_int8 < n_bf16 < n_fp32


def test_scales_tree_placeholders_recomputed():
    rng = np.random.RandomState(2)
    tree = {"k": rng.randn(6, 3).astype(np.float32),
            "b": rng.randn(3).astype(np.float32)}
    st = scales_tree(tree)
    assert st["k"].shape == (3,)
    assert st["b"].size == 0  # non-quantizable placeholder
    # quantize_tree must treat the placeholder as "recompute", not as a
    # literal zero-length scale
    qt = quantize_tree(tree, st)
    assert is_quantized_leaf(qt["k"])
    np.testing.assert_array_equal(qt["b"], tree["b"])


def test_measure_drift_shapes():
    a = np.zeros((4, 8), np.float32)
    b = a.copy()
    b[0, 0] = 0.5
    agree, drift = measure_drift(a, b)
    assert drift == pytest.approx(0.5)
    with pytest.raises(ValueError, match="shape mismatch"):
        measure_drift(a, np.zeros((4, 9), np.float32))


# -------------------------------------------------- artifact loader ----


def test_calibration_roundtrip(engine, tmp_path):
    path = _artifact(engine, tmp_path)
    payload = load_calibration(path, arch=ARCH,
                               params=_host_params(engine))
    meta = payload["meta"]
    assert meta["arch"] == ARCH
    assert meta["scheme"].startswith("absmax-int8")
    assert meta["bounds"]["max_abs_dlogit"] == 10.0
    assert "host" in meta  # provenance stamp
    assert meta["weights_fingerprint"] == weights_fingerprint(
        _host_params(engine)
    )
    assert "scales" in payload


def test_calibration_loader_fail_fast_names_recalibration(engine,
                                                          tmp_path):
    """The satellite lock: EVERY load failure is a CalibrationError
    whose message names the ``dptpu quantize`` command."""
    params = _host_params(engine)

    # missing file
    with pytest.raises(CalibrationError, match="dptpu quantize"):
        load_calibration(str(tmp_path / "nope.dptpu"), arch=ARCH)

    # empty file (crashed write)
    empty = tmp_path / "empty.dptpu"
    empty.write_bytes(b"")
    with pytest.raises(CalibrationError, match="dptpu quantize"):
        load_calibration(str(empty), arch=ARCH)

    # garbage without a CRC footer is NOT an artifact
    garbage = tmp_path / "garbage.dptpu"
    garbage.write_bytes(b"not an artifact at all")
    with pytest.raises(CalibrationError, match="dptpu quantize"):
        load_calibration(str(garbage), arch=ARCH)

    # bit rot under the seal: flip one payload byte
    path = _artifact(engine, tmp_path, name="rot.dptpu")
    raw = bytearray(open(path, "rb").read())
    raw[10] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    with pytest.raises(CalibrationError, match="dptpu quantize"):
        load_calibration(path, arch=ARCH)

    # arch mismatch names BOTH the wrong arch and the command
    path = _artifact(engine, tmp_path, name="arch.dptpu")
    with pytest.raises(CalibrationError) as ei:
        load_calibration(path, arch="vit_b_32")
    assert "calibrated for arch" in str(ei.value)
    assert "dptpu quantize --arch vit_b_32" in str(ei.value)

    # weights-generation mismatch (stale scales = the silent-drift path)
    path = _artifact(engine, tmp_path, name="gen.dptpu")
    other = jax.tree_util.tree_map(lambda a: a + 1.0, params)
    with pytest.raises(CalibrationError) as ei:
        load_calibration(path, arch=ARCH, params=other)
    assert "stale scales drift silently" in str(ei.value)
    assert "dptpu quantize" in str(ei.value)


# ------------------------------------------------- engine precision ----


def test_engine_precision_axis_int8(engine, tmp_path):
    path = _artifact(engine, tmp_path, name="engine.dptpu")
    base = engine.infer(_rand_images(4, 32, seed=3))
    gen, meta = engine.stage_quantized(path, precision="int8")
    try:
        assert engine.generation_precision(gen) == "int8"
        assert meta["arch"] == ARCH
        # the ladder compiled an int8 arm for every dedup'd exec size
        for nexec in {engine.exec_batch(b) for b in engine.buckets}:
            assert ("int8", nexec) in engine._compiled
        # ≥40% resident-bytes reduction vs the fp32 generation: the
        # acceptance lever this host CAN honestly show (2-core CPU)
        rb = engine.resident_bytes()
        assert rb[gen] < 0.6 * rb[engine.current_generation]
        # bounded drift, computed through the real bucket path
        nexec = engine.exec_batch(4)
        x = _rand_images(4, 32, seed=3)
        pad = np.concatenate(
            [x, np.repeat(x[:1], nexec - 4, axis=0)]
        ) if nexec > 4 else x
        q = engine.run_bucket(4, pad, 4, gen=gen)
        agree, drift = measure_drift(base, q)
        assert drift < 1.0
        assert q.dtype == np.float32
    finally:
        engine.discard_staged(gen)


def test_engine_bf16_generation(engine):
    variables = quantize_variables(engine._host_variables, "bf16")
    gen = engine.stage_weights(variables, precision="bf16")
    try:
        assert engine.generation_precision(gen) == "bf16"
        base = engine.infer(_rand_images(2, 32, seed=4))
        nexec = engine.exec_batch(1)
        x = np.repeat(_rand_images(1, 32, seed=4), nexec, axis=0)
        q = engine.run_bucket(1, x, 1, gen=gen)
        _, drift = measure_drift(base[:1], q)
        assert drift < 0.5
    finally:
        engine.discard_staged(gen)


def test_engine_rejects_quantized_tp(engine, monkeypatch):
    monkeypatch.setattr(engine, "placement", "tp")
    with pytest.raises(ValueError, match="tp"):
        engine.stage_weights(
            quantize_variables(engine._host_variables, "int8"),
            precision="int8",
        )


def test_stage_quantized_refuses_wrong_arch_artifact(engine, tmp_path):
    path = _artifact(engine, tmp_path, name="wrong.dptpu",
                     arch="vit_b_32")
    with pytest.raises(CalibrationError, match="calibrated for arch"):
        engine.stage_quantized(path)


# ------------------------------------------------------ canary gate ----


def test_canary_top1_agreement_gate_rolls_back(engine):
    """A rollout whose predictions DISAGREE with the baseline rolls
    back on the cumulative top-1 agreement gate even when the drift
    gate is disarmed — the quantized deployment's never-silent lock."""
    canary = CanaryController(engine, fraction=0.5, drift_limit=1e9,
                              min_batches=2, min_top1_agreement=0.99)
    b = DynamicBatcher(engine, max_delay_ms=0.0, slots=2, canary=canary)
    try:
        base = engine.current_generation
        gen = canary.start(_fresh_variables(engine, seed=99))
        for img in _rand_images(10, 32, seed=5):
            b.submit_array(img).result(timeout=30)
        canary.drain_evals()
        st = canary.status()
        assert st["state"] == "rolled_back"
        assert "top-1 agreement" in st["rollback_reason"]
        assert st["top1_floor"] == 0.99
        assert engine.current_generation == base
        assert gen != base
    finally:
        b.close()
        canary.close()


def test_canary_quantized_rollout_promotes_under_bounds(engine,
                                                        tmp_path):
    path = _artifact(engine, tmp_path, name="promote.dptpu")
    canary = CanaryController(engine, fraction=0.5, min_batches=2)
    b = DynamicBatcher(engine, max_delay_ms=0.0, slots=2, canary=canary)
    try:
        # operator overrides win over artifact bounds (generous, so the
        # promotion is deterministic on random-init weights)
        gen = canary.start_quantized(path, drift_limit=10.0,
                                     top1_min=0.01)
        assert engine.generation_precision(gen) == "int8"
        st = canary.status()
        assert st["drift_limit"] == 10.0
        assert st["top1_floor"] == 0.01
        for i in range(30):
            b.submit_array(
                _rand_images(1, 32, seed=20 + i)[0]
            ).result(timeout=30)
            canary.drain_evals()
            if canary.status()["state"] == "promoted":
                break
        st = canary.status()
        assert st["state"] == "promoted"
        assert st["top1_agreement"] is not None
        assert engine.current_generation == gen
        assert engine.generation_precision() == "int8"
    finally:
        b.close()
        canary.close()


def test_canary_quantized_artifact_bounds_are_default(engine, tmp_path):
    path = _artifact(engine, tmp_path, name="bounds.dptpu",
                     bounds={"min_top1_agreement": 0.125,
                             "max_abs_dlogit": 7.5})
    canary = CanaryController(engine, fraction=0.5, min_batches=2)
    try:
        gen = canary.start_quantized(path)
        st = canary.status()
        assert st["drift_limit"] == 7.5
        assert st["top1_floor"] == 0.125
        engine.discard_staged(gen)
    finally:
        canary.close()


# -------------------------------------------------------- quantize CLI ----


@pytest.mark.slow
def test_quantize_cli_end_to_end(tmp_path):
    from dptpu.cli import main_quantize

    out = str(tmp_path / "cli.dptpu")
    meta = main_quantize([
        "--arch", ARCH, "--out", out, "--num-classes", "8",
        "--image-size", "32", "--sample", "8",
    ])
    assert os.path.exists(out)
    assert meta["arch"] == ARCH
    assert meta["stats"]["top1_agreement"] >= 0.0
    assert meta["bounds"]["max_abs_dlogit"] > 0.0
    payload = load_calibration(out, arch=ARCH)
    assert payload["meta"]["sample_n"] == 8
