"""Tier-1 smoke of scripts/run_commbench.py (the obsbench pattern):
the hierarchical-comms gates — per-chip DCN bytes <= 1.1x the ideal
1/chips_per_slice of the flat all-reduce, the bf16-DCN halving, and
the hierarchical-vs-flat fp32 parity gate (params Δ=0 after 5 steps on
the pure-hop geometries) — are continuously checked, not just on the
bench host. One subprocess, --smoke preset, same gate logic as the
committed COMMBENCH.json."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_commbench_smoke_gates(tmp_path):
    out = str(tmp_path / "COMMBENCH.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the bench needs >= slices x chips_per_slice virtual devices; the
    # harness's 8-device XLA_FLAGS (conftest) covers the 2x2 preset,
    # and the script re-execs itself if the pool is too small
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "run_commbench.py"),
         "--smoke", "--slices", "2", "--chips-per-slice", "2",
         "--per-chip-batch", "8", "--steps", "5", "--out", out],
        capture_output=True, text=True, timeout=560, env=env,
        cwd=str(tmp_path),
    )
    assert proc.returncode == 0, (
        f"commbench gate failed\nstdout:\n{proc.stdout[-4000:]}\n"
        f"stderr:\n{proc.stderr[-4000:]}"
    )
    with open(out) as f:
        bench = json.load(f)
    # artifact schema: every consumer-facing section present
    for key in ("flat_allreduce_per_chip", "hier_fp32_by_link",
                "hier_bf16_by_link_preopt", "bf16_limitation",
                "dcn_vs_ideal_ratio", "bf16_dcn_vs_fp32_dcn_ratio",
                "parity", "gates", "host"):
        assert key in bench, key
    gates = bench["gates"]
    assert gates["dcn_bytes_ok"], bench["dcn_vs_ideal_ratio"]
    assert gates["bf16_halving_ok"], bench["bf16_dcn_vs_fp32_dcn_ratio"]
    assert gates["parity_ok"], bench["parity"]
    # the Δ=0 claims specifically (not just the rolled-up gate)
    assert bench["parity"]["fp32_pure_ici_max_delta"] == 0.0
    assert bench["parity"]["fp32_pure_dcn_max_delta"] == 0.0
    assert bench["parity"]["steps"] >= 5
    # per-link accounting is structurally sane: the hierarchical DCN
    # hop is all-reduce-only and strictly smaller than the flat total
    hier = bench["hier_fp32_by_link"]
    assert hier["dcn"]["reduce-scatter"] == 0
    assert hier["dcn"]["total"] < bench["flat_allreduce_per_chip"]["total"]
    # the overlap arm (ISSUE 13): Δ=0 vs the unbucketed ladder, DCN
    # bytes within the padding tolerance, schedule evidence present
    assert gates["overlap_ok"]
    assert bench["parity"]["overlap_vs_hier_max_delta"] == 0.0
    assert abs(bench["overlap_dcn_vs_hier_ratio"] - 1.0) <= 0.02
    assert bench["overlap_evidence"]["reductions"] >= 2
    assert bench["overlap_evidence"]["interleaved_gaps"] >= 1
    # the GSPMD-path arms (ISSUE 16): the same rules table drives the
    # compiler-placed hierarchy, and the annotation-only overlap claim
    # is byte-exact
    for key in ("gspmd_flat_per_chip", "gspmd_hier_by_link",
                "gspmd_overlap_per_chip", "gspmd_overlap_evidence"):
        assert key in bench, key
    assert gates["gspmd_hier_ok"], bench["gspmd_hier_by_link"]
    assert gates["gspmd_overlap_ok"], bench["gspmd_overlap_evidence"]
    # hierarchy: GSPMD emits AG+AR mixes rather than the shard_map
    # RS/AR/AG ladder, so the gate is DCN-byte reduction, not shape
    gh = bench["gspmd_hier_by_link"]
    assert gh["dcn"]["total"] * 2 < bench["gspmd_flat_per_chip"]["total"]
    assert gh["ici"]["total"] > gh["dcn"]["total"]
    # overlap: bucketing annotations change the schedule, never a byte
    assert bench["parity"]["gspmd_overlap_vs_flat_max_delta"] == 0.0
    assert bench["gspmd_overlap_per_chip"] == bench["gspmd_flat_per_chip"]
    assert bench["gspmd_overlap_evidence"]["reductions"] >= 2
    assert bench["gspmd_overlap_evidence"]["interleaved_gaps"] >= 1
