"""The locked fail-fast env-knob contract, large-batch-engine edition
(mirrors tests/test_feed_knobs.py): every explicitly-set-but-invalid
value of DPTPU_OPT / DPTPU_ACCUM / DPTPU_WARMUP_EPOCHS /
DPTPU_LABEL_SMOOTH raises pre-compile with an actionable message, the
env twin overrides the CLI/config field, and config values passed
programmatically get the identical validation as env values.
"""

import pytest

from dptpu.config import Config
from dptpu.train.fit import _opt_knobs

_KNOBS = ("DPTPU_OPT", "DPTPU_ACCUM", "DPTPU_WARMUP_EPOCHS",
          "DPTPU_LABEL_SMOOTH")


def _cfg(**kw):
    return Config(data="synthetic:16", **kw)


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for k in _KNOBS:
        monkeypatch.delenv(k, raising=False)


def test_defaults_reproduce_reference(monkeypatch):
    # unset env + default config = the reference recipe exactly
    assert _opt_knobs(_cfg()) == ("sgd", 1, 0, 0.0)


def test_env_overrides_config(monkeypatch):
    cfg = _cfg(optimizer="sgd", accum_steps=1, warmup_epochs=0,
                 label_smoothing=0.0)
    monkeypatch.setenv("DPTPU_OPT", "lars")
    monkeypatch.setenv("DPTPU_ACCUM", "4")
    monkeypatch.setenv("DPTPU_WARMUP_EPOCHS", "5")
    monkeypatch.setenv("DPTPU_LABEL_SMOOTH", "0.1")
    assert _opt_knobs(cfg) == ("lars", 4, 5, 0.1)


def test_config_values_pass_through():
    cfg = _cfg(optimizer="lamb", accum_steps=2, warmup_epochs=3,
                 label_smoothing=0.2)
    assert _opt_knobs(cfg) == ("lamb", 2, 3, 0.2)


def test_opt_choice_validated_env_and_config(monkeypatch):
    monkeypatch.setenv("DPTPU_OPT", "adam")
    with pytest.raises(ValueError, match="DPTPU_OPT"):
        _opt_knobs(_cfg())
    monkeypatch.delenv("DPTPU_OPT")
    with pytest.raises(ValueError, match="--optimizer"):
        _opt_knobs(_cfg(optimizer="adam"))


def test_accum_zero_negative_garbage_raise(monkeypatch):
    for bad in ("0", "-2"):
        monkeypatch.setenv("DPTPU_ACCUM", bad)
        with pytest.raises(ValueError, match="DPTPU_ACCUM"):
            _opt_knobs(_cfg())
    monkeypatch.setenv("DPTPU_ACCUM", "many")
    with pytest.raises(ValueError, match="not an integer"):
        _opt_knobs(_cfg())
    monkeypatch.delenv("DPTPU_ACCUM")
    # config field hits the same validation as the env twin
    for bad in (0, -1):
        with pytest.raises(ValueError, match="accum-steps"):
            _opt_knobs(_cfg(accum_steps=bad))
    # =1 is the documented off value, never an error
    assert _opt_knobs(_cfg(accum_steps=1))[1] == 1


def test_warmup_negative_and_garbage_raise(monkeypatch):
    monkeypatch.setenv("DPTPU_WARMUP_EPOCHS", "-1")
    with pytest.raises(ValueError, match="DPTPU_WARMUP_EPOCHS"):
        _opt_knobs(_cfg())
    monkeypatch.setenv("DPTPU_WARMUP_EPOCHS", "soon")
    with pytest.raises(ValueError, match="not an integer"):
        _opt_knobs(_cfg())
    monkeypatch.delenv("DPTPU_WARMUP_EPOCHS")
    with pytest.raises(ValueError, match="warmup-epochs"):
        _opt_knobs(_cfg(warmup_epochs=-3))
    # explicit 0 keeps the reference schedule — valid
    assert _opt_knobs(_cfg(warmup_epochs=0))[2] == 0


def test_warmup_swallowing_the_whole_run_raises(monkeypatch):
    """warmup >= epochs would clamp the cosine phase away and the run
    would never reach peak LR — silently-worse training, so it fails
    fast like every other invalid knob (env twin and config field)."""
    with pytest.raises(ValueError, match="mid-warmup"):
        _opt_knobs(_cfg(epochs=10, warmup_epochs=10))
    with pytest.raises(ValueError, match="mid-warmup"):
        _opt_knobs(_cfg(epochs=10, warmup_epochs=25))
    monkeypatch.setenv("DPTPU_WARMUP_EPOCHS", "90")
    with pytest.raises(ValueError, match="mid-warmup"):
        _opt_knobs(_cfg(epochs=90))
    # the last warmup-compatible value is valid
    monkeypatch.delenv("DPTPU_WARMUP_EPOCHS")
    assert _opt_knobs(_cfg(epochs=10, warmup_epochs=9))[2] == 9


def test_label_smooth_range_and_garbage_raise(monkeypatch):
    for bad in ("1.0", "-0.1", "2"):
        monkeypatch.setenv("DPTPU_LABEL_SMOOTH", bad)
        with pytest.raises(ValueError, match="DPTPU_LABEL_SMOOTH"):
            _opt_knobs(_cfg())
    monkeypatch.setenv("DPTPU_LABEL_SMOOTH", "a little")
    with pytest.raises(ValueError, match="not a number"):
        _opt_knobs(_cfg())
    monkeypatch.delenv("DPTPU_LABEL_SMOOTH")
    with pytest.raises(ValueError, match="label-smoothing"):
        _opt_knobs(_cfg(label_smoothing=1.0))
    # boundary: 0 valid (off), 0.999... valid
    assert _opt_knobs(_cfg(label_smoothing=0.0))[3] == 0.0
    assert _opt_knobs(_cfg(label_smoothing=0.9))[3] == 0.9


def test_fit_rejects_accum_not_dividing_per_device_batch(monkeypatch):
    """fit() fails fast (pre-mesh, pre-compile) when accum does not
    divide the per-device batch — the microbatch must be integral."""
    from dptpu.train.fit import fit

    # 8 fake devices (conftest): batch 8 -> per-device 1; accum 3 can't
    # divide it
    cfg = Config(data="synthetic:16", arch="resnet18", batch_size=8,
                 epochs=1, accum_steps=3)
    with pytest.raises(ValueError, match="does not divide"):
        fit(cfg, image_size=32, verbose=False)


def test_sp_accum_error_names_knob_and_alternative(monkeypatch):
    """The sequence-parallel step's accumulation fail-fast (ROADMAP
    PR-6 follow-on) must name the offending knob AND the supported
    alternatives, not just refuse — locked here so a reworded message
    cannot silently lose the actionable half."""
    from dptpu.train.fit import fit

    monkeypatch.setenv("DPTPU_SP", "2")
    # batch 16 on the 8-device fake pod -> per-device 2, accum 2
    # divides it, so the SP x accum conflict is the FIRST error hit
    cfg = Config(data="synthetic:16", arch="vit_b_32", batch_size=16,
                 epochs=1, accum_steps=2)
    with pytest.raises(ValueError) as ei:
        fit(cfg, image_size=32, verbose=False)
    msg = str(ei.value)
    assert "DPTPU_ACCUM=2" in msg  # the offending knob, with its value
    assert "DPTPU_SP=2" in msg  # the conflicting axis knob
    # both supported alternatives are spelled out
    assert "DPTPU_ACCUM=1" in msg
    assert "unset DPTPU_SP" in msg


def test_cli_flags_parse_into_config():
    from dptpu.config import parse_config

    cfg = parse_config([
        "--optimizer", "lamb", "--accum-steps", "4",
        "--warmup-epochs", "5", "--label-smoothing", "0.1", "data",
    ], variant="ddp")
    assert (cfg.optimizer, cfg.accum_steps, cfg.warmup_epochs,
            cfg.label_smoothing) == ("lamb", 4, 5, 0.1)
    # the parser rejects an unknown optimizer at the CLI boundary too
    with pytest.raises(SystemExit):
        parse_config(["--optimizer", "adam", "data"], variant="ddp")


# --------------------------------------------------- ISSUE 13: recipe knobs
# DPTPU_BATCH_RAMP / DPTPU_WARMUP_POLY (parse in dptpu/ops/schedules.py,
# wiring + composition fail-fasts in fit) under the same locked contract.


@pytest.fixture()
def _clean_recipe_env(monkeypatch):
    for k in ("DPTPU_BATCH_RAMP", "DPTPU_WARMUP_POLY", "DPTPU_OVERLAP",
              "DPTPU_BUCKET_MB", "DPTPU_DIST_EVAL",
              "DPTPU_STRAGGLER_FACTOR"):
        monkeypatch.delenv(k, raising=False)
    return monkeypatch


def test_parse_batch_ramp_happy_path():
    from dptpu.ops.schedules import parse_batch_ramp, ramp_multiplier

    ramp = parse_batch_ramp("4:2,8:4")
    assert ramp == [(0, 1), (4, 2), (8, 4)]  # implied epoch-0 phase
    assert [ramp_multiplier(ramp, e) for e in (0, 3, 4, 7, 8, 99)] == \
        [1, 1, 2, 2, 4, 4]


def test_parse_batch_ramp_explicit_epoch0():
    from dptpu.ops.schedules import parse_batch_ramp

    assert parse_batch_ramp("0:2,5:4") == [(0, 2), (5, 4)]


@pytest.mark.parametrize("bad", ["junk", "4", "4:", ":2", "4:0", "-1:2",
                                 "4:2,4:3", "8:2,4:4", "", " , "])
def test_parse_batch_ramp_malformed_raises(bad):
    from dptpu.ops.schedules import parse_batch_ramp

    with pytest.raises(ValueError, match="DPTPU_BATCH_RAMP"):
        parse_batch_ramp(bad)


def test_fit_warmup_poly_invalid_raises(_clean_recipe_env):
    from dptpu.train.fit import fit

    _clean_recipe_env.setenv("DPTPU_WARMUP_POLY", "0")
    cfg = Config(data="synthetic:16", arch="resnet18", batch_size=8,
                 epochs=1, warmup_epochs=0)
    with pytest.raises(ValueError, match="DPTPU_WARMUP_POLY"):
        fit(cfg, image_size=32, verbose=False)


def test_fit_warmup_poly_needs_warmup(_clean_recipe_env):
    from dptpu.train.fit import fit

    _clean_recipe_env.setenv("DPTPU_WARMUP_POLY", "2")
    cfg = Config(data="synthetic:16", arch="resnet18", batch_size=8,
                 epochs=1, warmup_epochs=0)
    with pytest.raises(ValueError, match="--warmup-epochs"):
        fit(cfg, image_size=32, verbose=False)


def test_fit_batch_ramp_needs_warmup(_clean_recipe_env):
    from dptpu.train.fit import fit

    _clean_recipe_env.setenv("DPTPU_BATCH_RAMP", "1:2")
    cfg = Config(data="synthetic:16", arch="resnet18", batch_size=8,
                 epochs=2, warmup_epochs=0)
    with pytest.raises(ValueError, match="DPTPU_BATCH_RAMP"):
        fit(cfg, image_size=32, verbose=False)


def test_fit_batch_ramp_beyond_epochs_raises(_clean_recipe_env):
    from dptpu.train.fit import fit

    _clean_recipe_env.setenv("DPTPU_BATCH_RAMP", "5:2")
    cfg = Config(data="synthetic:16", arch="resnet18", batch_size=8,
                 epochs=3, warmup_epochs=1)
    with pytest.raises(ValueError, match="--epochs"):
        fit(cfg, image_size=32, verbose=False)


def test_fit_batch_ramp_straggler_composition_allowed(_clean_recipe_env):
    """The ramp x straggler refusal is gone: StragglerController
    survives the DPTPU_BATCH_RAMP pool rebuild via rebind() (semantics
    locked in tests/test_tune.py), so fit must accept the pair and run
    the ramp to completion."""
    from dptpu.train.fit import fit

    _clean_recipe_env.setenv("DPTPU_BATCH_RAMP", "1:2")
    _clean_recipe_env.setenv("DPTPU_STRAGGLER_FACTOR", "2.0")
    cfg = Config(data="synthetic:64", arch="resnet18", batch_size=16,
                 epochs=3, warmup_epochs=1)
    result = fit(cfg, image_size=32, verbose=False)
    assert len(result["history"]) == 3
    # the ramp actually fired: epoch 1+ trains the doubled batch
    assert result["batch_ramp"][-1]["global_batch"] == 32


def test_fit_batch_ramp_tp_composition_names_alternatives(
        _clean_recipe_env):
    from dptpu.train.fit import fit

    _clean_recipe_env.setenv("DPTPU_BATCH_RAMP", "1:2")
    _clean_recipe_env.setenv("DPTPU_TP", "2")
    cfg = Config(data="synthetic:64", arch="vit_b_32", batch_size=16,
                 epochs=3, warmup_epochs=1)
    with pytest.raises(ValueError) as ei:
        fit(cfg, image_size=32, verbose=False)
    msg = str(ei.value)
    assert "DPTPU_BATCH_RAMP" in msg and "DPTPU_TP" in msg
    assert "unset" in msg  # both alternatives spelled out


def test_poly_power_one_is_linear_warmup():
    """DPTPU_WARMUP_POLY=1 must be bit-identical to the linear ramp —
    the power path is never traced at p=1 (dptpu/ops/schedules.py)."""
    import numpy as np

    from dptpu.ops.schedules import make_warmup_cosine_schedule

    lin = make_warmup_cosine_schedule(2.0, 10, 4, 1)
    p1 = make_warmup_cosine_schedule(2.0, 10, 4, 1, power=1.0)
    for step in range(40):
        np.testing.assert_array_equal(np.asarray(lin(step)),
                                      np.asarray(p1(step)))


def test_poly_power_two_bends_warmup():
    import numpy as np

    from dptpu.ops.schedules import make_warmup_cosine_schedule

    lin = make_warmup_cosine_schedule(2.0, 10, 4, 2)
    p2 = make_warmup_cosine_schedule(2.0, 10, 4, 2, power=2.0)
    # polynomial warmup sits strictly below linear mid-ramp ...
    assert float(p2(5)) < float(lin(5))
    # ... and both land on the same peak / cosine tail
    np.testing.assert_allclose(float(p2(30)), float(lin(30)), rtol=1e-6)


def test_ramp_phase_schedule_is_continuous_at_boundary():
    """The phase schedule chains in fractional epochs: the epoch the
    ramp fires, the NEW phase's schedule evaluated at the boundary step
    equals the old phase's trajectory at the same epoch, scaled x mult
    (the linear-scaling jump is the ONLY discontinuity)."""
    from dptpu.ops.schedules import make_ramp_phase_schedule

    spe0, spe1 = 8, 4  # phase 1 has half the steps (double batch)
    s0 = make_ramp_phase_schedule(1.0, spe0, 10, 2, epoch0=0, step0=0)
    s1 = make_ramp_phase_schedule(2.0, spe1, 10, 2, epoch0=4,
                                  step0=4 * spe0)
    boundary = 4 * spe0
    lr_old = float(s0(boundary))       # what phase 0 would have taken
    lr_new = float(s1(boundary))       # what phase 1 actually takes
    assert abs(lr_new - 2.0 * lr_old) < 2.0 * 0.02  # x mult, same shape
