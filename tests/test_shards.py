"""Packed-shard data plane (dptpu/data/{shards,stream}.py): pack
determinism, streaming-vs-ImageFolder bit-identity, mid-epoch resume on
shards, corrupt-shard CRC detection, O_DIRECT fallback, the fadvise/
byte-ring mutual-exclusion invariant, and the new knobs' fail-fast
contract. One resnet18@48 compile backs the fit()-level resume lock
(the test_fault_resume precedent)."""

import os

import numpy as np
import pytest

from dptpu.data import (
    DataLoader,
    ImageFolderDataset,
    ShardLocalitySampler,
    ShardSet,
    ShardStreamDataset,
    ShardedSampler,
    train_transform,
    verify_shard,
    write_shards,
)
from dptpu.data.shards import (
    MANIFEST_NAME,
    ShardCorruptError,
    ShardFormatError,
    shard_name,
)
from dptpu.data.stream import ShardFileReader, open_fd_count


@pytest.fixture(scope="module")
def jpeg_tree(tmp_path_factory):
    """ImageFolder split of tiny 52x44 JPEGs (< 48*8/7, so the native
    scale picker stays at 8/8 — the fixture discipline that keeps every
    decode path bit-exact) plus one PNG per class (the PIL path + the
    jpeg flag)."""
    from PIL import Image

    root = tmp_path_factory.mktemp("jpegtree")
    rng = np.random.RandomState(0)
    for c in range(2):
        d = root / f"class{c}"
        d.mkdir()
        for i in range(8):
            low = rng.randint(0, 255, (8, 7, 3), np.uint8)
            img = Image.fromarray(low).resize((52, 44), Image.BILINEAR)
            img.save(str(d / f"{i}.jpg"), quality=85)
        Image.fromarray(
            rng.randint(0, 255, (44, 52, 3), np.uint8)
        ).save(str(d / "p.png"))
    return str(root)


@pytest.fixture(scope="module")
def packed(jpeg_tree, tmp_path_factory):
    dest = str(tmp_path_factory.mktemp("packed"))
    manifest = write_shards(jpeg_tree, dest, 3)
    return dest, manifest


def test_pack_is_deterministic(jpeg_tree, packed, tmp_path):
    """Same tree -> byte-identical shards AND manifest (no timestamps,
    no hostnames: shards are content-addressable)."""
    dest, manifest = packed
    again = str(tmp_path / "again")
    write_shards(jpeg_tree, again, 3)
    for s in manifest["shards"]:
        a = open(os.path.join(dest, s["name"]), "rb").read()
        b = open(os.path.join(again, s["name"]), "rb").read()
        assert a == b, f"{s['name']} not byte-identical across packs"
    assert open(os.path.join(dest, MANIFEST_NAME)).read() == \
        open(os.path.join(again, MANIFEST_NAME)).read()


def test_pack_verifies_deep(packed):
    dest, manifest = packed
    assert manifest["num_samples"] == 18 and manifest["num_shards"] == 3
    for s in manifest["shards"]:
        ok, reason = verify_shard(os.path.join(dest, s["name"]), deep=True)
        assert ok, reason


def test_shard_set_extent_map(packed):
    dest, manifest = packed
    ss = ShardSet(dest)
    assert len(ss) == 18 and ss.classes == ["class0", "class1"]
    # contiguous split: 6/6/6
    assert ss.shard_counts.tolist() == [6, 6, 6]
    ext = ss.extent(7)
    assert ext["shard"] == shard_name(1) and ext["pos"] == 1
    assert ext["length"] > 0 and ext["offset"] >= 4096
    with pytest.raises(IndexError):
        ss.locate(18)


def test_streaming_vs_imagefolder_bit_identity(jpeg_tree, packed):
    """THE gate (DATABENCH's bit-identity arm at unit scale): the same
    (seed, epoch, index) yields byte-identical batches whether the
    bytes come from the ImageFolder tree or the packed shards."""
    dest, _ = packed
    imf = ImageFolderDataset(jpeg_tree, train_transform(48))
    sds = ShardStreamDataset(dest, train_transform(48),
                             byte_cache_bytes=4 << 20)
    try:
        for seed in (0, 7):
            la = DataLoader(imf, 5, num_workers=2, seed=seed,
                            sampler=ShardedSampler(len(imf), shuffle=True,
                                                   seed=seed))
            lb = DataLoader(sds, 5, num_workers=2, seed=seed,
                            sampler=ShardedSampler(len(sds), shuffle=True,
                                                   seed=seed))
            for ba, bb in zip(la.epoch(1), lb.epoch(1)):
                assert np.array_equal(ba["images"], bb["images"])
                assert np.array_equal(ba["labels"], bb["labels"])
            la.close()
            lb.close()
    finally:
        sds.close()


def test_midepoch_resume_on_shards_replays_exactly(packed):
    """epoch(e, start_batch=k) over shards == the tail of the full
    epoch — the (seed, epoch, index) replay contract on the streaming
    path, including with the shard-locality sampler."""
    dest, _ = packed
    sds = ShardStreamDataset(dest, train_transform(48),
                             byte_cache_bytes=4 << 20)
    try:
        for sampler in (
            ShardedSampler(len(sds), shuffle=True, seed=3),
            ShardLocalitySampler(sds.shard_set, shuffle=True, seed=3),
        ):
            loader = DataLoader(sds, 4, num_workers=2, seed=3,
                                sampler=sampler)
            full = list(loader.epoch(2))
            tail = list(loader.epoch(2, start_batch=2))
            assert len(tail) == len(full) - 2
            for bf, bt in zip(full[2:], tail):
                assert np.array_equal(bf["images"], bt["images"])
                assert np.array_equal(bf["labels"], bt["labels"])
            loader.close()
    finally:
        sds.close()


def test_shard_locality_sampler_contract(packed):
    """Pure in (seed, epoch); a full permutation; and shard-local:
    each shard's samples form ONE contiguous run of the visit order
    (the streaming reader drains a shard before touching the next)."""
    dest, _ = packed
    ss = ShardSet(dest)
    s1 = ShardLocalitySampler(ss, shuffle=True, seed=5)
    s2 = ShardLocalitySampler(ss, shuffle=True, seed=5)
    o1, o2 = s1._epoch_order(4), s2._epoch_order(4)
    assert np.array_equal(o1, o2), "not pure in (seed, epoch)"
    assert not np.array_equal(o1, s1._epoch_order(5))
    assert sorted(o1.tolist()) == list(range(18)), "not a permutation"
    shard_of = np.searchsorted(ss.shard_starts, o1, side="right") - 1
    # contiguous runs: the shard id changes exactly num_shards - 1 times
    changes = int(np.sum(shard_of[1:] != shard_of[:-1]))
    assert changes == ss.num_shards - 1, shard_of.tolist()


def test_corrupt_shard_data_detected(jpeg_tree, tmp_path):
    dest = str(tmp_path / "p")
    manifest = write_shards(jpeg_tree, dest, 2)
    path = os.path.join(dest, manifest["shards"][0]["name"])
    # flip one byte in the data region of sample 0
    ss = ShardSet(dest)
    ext = ss.extent(0)
    with open(path, "r+b") as f:
        f.seek(ext["offset"] + ext["length"] // 2)
        byte = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([byte[0] ^ 0xFF]))
    ok, reason = verify_shard(path, deep=True)
    assert not ok and "CRC mismatch" in reason
    sds = ShardStreamDataset(dest, train_transform(48), byte_cache_bytes=0)
    try:
        with pytest.raises(ShardCorruptError, match="sample 0 content CRC"):
            sds.get(0, np.random.default_rng([1, 0, 0]))
        # other samples are untouched and still readable
        sds.get(5, np.random.default_rng([1, 0, 5]))
    finally:
        sds.close()


def test_corrupt_shard_header_detected(jpeg_tree, tmp_path):
    dest = str(tmp_path / "p")
    manifest = write_shards(jpeg_tree, dest, 2)
    path = os.path.join(dest, manifest["shards"][1]["name"])
    with open(path, "r+b") as f:
        f.seek(20)  # inside the sealed header
        f.write(b"\xFF")
    ok, reason = verify_shard(path)
    assert not ok and "header CRC" in reason
    with pytest.raises(ShardFormatError):
        ShardSet(dest).shard_table(1)


def test_odirect_fallback_on_unsupported_fs(packed, tmp_path, monkeypatch):
    """tmpfs (and platforms without O_DIRECT) must fall back to plain
    reads with the reason RECORDED — identical bytes either way."""
    dest, manifest = packed
    name = manifest["shards"][0]["name"]
    path = os.path.join(dest, name)
    want = open(path, "rb").read()

    # force the open to refuse O_DIRECT (portable stand-in for tmpfs)
    real_open = os.open

    def refusing_open(p, flags, *a, **kw):
        if flags & getattr(os, "O_DIRECT", 0):
            raise OSError(22, "Invalid argument (simulated tmpfs)")
        return real_open(p, flags, *a, **kw)

    monkeypatch.setattr(os, "open", refusing_open)
    r = ShardFileReader(path, want_odirect=True)
    got = r.read_range(0, len(want))
    assert got == want
    assert r.odirect is False
    assert "O_DIRECT open refused" in r.odirect_why
    r.close()
    monkeypatch.undo()

    # and the dataset surfaces the state through io_stats
    sds = ShardStreamDataset(dest, train_transform(48),
                             byte_cache_bytes=0, odirect=False)
    try:
        sds.get(0, np.random.default_rng([1, 0, 0]))
        stats = sds.io_stats()
        assert stats["odirect_active"] is False
        assert "disabled" in stats["odirect_why"]
    finally:
        sds.close()


def test_odirect_and_plain_reads_agree(packed):
    """When the filesystem DOES grant O_DIRECT, the aligned-ring read
    returns the same bytes as a plain read (alignment slicing lock)."""
    dest, manifest = packed
    path = os.path.join(dest, manifest["shards"][0]["name"])
    want = open(path, "rb").read()
    r = ShardFileReader(path, want_odirect=True)
    try:
        # arbitrary unaligned extents, including the file tail
        for off, ln in ((0, 96), (5000, 777), (len(want) - 100, 100),
                        (1, len(want) - 2)):
            assert r.read_range(off, ln) == want[off:off + ln]
    finally:
        r.close()


def test_feed_stats_mutual_exclusion(packed):
    """feed_stats asserts the fadvise readahead and the shard engine
    never both own the byte-prefetch path; a dataset claiming both is
    rejected loudly."""
    dest, _ = packed
    sds = ShardStreamDataset(dest, train_transform(48),
                             byte_cache_bytes=4 << 20)
    try:
        loader = DataLoader(sds, 4, num_workers=1, seed=0)
        next(iter(loader.epoch(0)))
        stats = loader.feed_stats()
        assert stats["readahead_active"] is False  # shard engine owns I/O
        assert "odirect_active" in stats
        loader.close()

        # a hybrid claiming BOTH paths trips the invariant
        sds.samples = [("bogus", 0)]
        bad = DataLoader(sds, 4, num_workers=1, seed=0,
                         workers_mode="process")
        with pytest.raises(RuntimeError, match="mutually exclusive"):
            bad.feed_stats()
        del sds.samples
        bad.close()  # never started a pipeline; nothing else to release
    finally:
        sds.close()


def test_stream_knob_validation(monkeypatch, packed):
    dest, _ = packed
    monkeypatch.setenv("DPTPU_SHARD_CACHE_BYTES", "-5")
    with pytest.raises(ValueError, match="DPTPU_SHARD_CACHE_BYTES"):
        ShardStreamDataset(dest)
    monkeypatch.setenv("DPTPU_SHARD_CACHE_BYTES", "junk")
    with pytest.raises(ValueError, match="not an integer"):
        ShardStreamDataset(dest)
    monkeypatch.delenv("DPTPU_SHARD_CACHE_BYTES")
    monkeypatch.setenv("DPTPU_ODIRECT", "flase")
    with pytest.raises(ValueError, match="not a boolean"):
        ShardStreamDataset(dest)
    monkeypatch.delenv("DPTPU_ODIRECT")
    monkeypatch.setenv("DPTPU_STORE_FETCH", "chunky")
    with pytest.raises(ValueError, match="DPTPU_STORE_FETCH"):
        ShardStreamDataset(dest)
    monkeypatch.delenv("DPTPU_STORE_FETCH")
    with pytest.raises(ValueError, match="'extent' or 'shard'"):
        ShardStreamDataset(dest, fetch_mode="chunky")
    from dptpu.data.shards import shard_split

    with pytest.raises(ValueError, match="num_shards"):
        shard_split(10, 0)
    with pytest.raises(ValueError, match="at least one sample"):
        shard_split(3, 8)


def test_remote_store_streaming_with_fault_retries(jpeg_tree, tmp_path,
                                                   monkeypatch):
    """Range fetches over HTTP with DPTPU_FAULT io_error injected: the
    store's retry/backoff absorbs the chaos and pixels stay identical
    to the local ImageFolder read — the FAULTBENCH shard scenario at
    unit scale."""
    from dptpu.data.store import dev_store_server

    dest = str(tmp_path / "p")
    write_shards(jpeg_tree, dest, 2)
    server, url = dev_store_server(dest)
    try:
        monkeypatch.setenv("DPTPU_FAULT", "io_error:p=0.4")
        monkeypatch.setenv("DPTPU_FAULT_SEED", "2")
        monkeypatch.setenv("DPTPU_STORE_RETRIES", "50")
        monkeypatch.setenv("DPTPU_STORE_BACKOFF_S", "0.001")
        imf = ImageFolderDataset(jpeg_tree, train_transform(48))
        rds = ShardStreamDataset(url, train_transform(48),
                                 byte_cache_bytes=2 << 20)
        try:
            for i in (0, 4, 9, 17):
                r1 = np.random.default_rng([5, 0, i])
                r2 = np.random.default_rng([5, 0, i])
                a, la = imf.get(i, r1)
                b, lb = rds.get(i, r2)
                assert la == lb and np.array_equal(a, b)
            stats = rds.io_stats()
            assert stats["store_retries"] > 0, \
                "p=0.4 over this many fetches must have injected"
            assert stats["odirect_active"] is False
        finally:
            rds.close()
    finally:
        server.shutdown()


def test_no_leaked_shard_fds(packed):
    """Datasets close their readers; the conftest session guard backs
    this with a suite-wide census."""
    dest, _ = packed
    sds = ShardStreamDataset(dest, train_transform(48), byte_cache_bytes=0)
    sds.get(0, np.random.default_rng([1, 0, 0]))
    sds.close()
    import gc

    gc.collect()
    assert open_fd_count() == 0


# ---- fit()-level: mid-epoch resume on shards (one resnet18@48 compile) ----


def _cfg(data, **kw):
    from dptpu.config import Config

    base = dict(
        data=data, arch="resnet18", epochs=2, batch_size=8, lr=0.02,
        workers=2, print_freq=100, seed=1, gpu=0,
    )
    base.update(kw)
    return Config(**base)


@pytest.fixture(scope="module")
def packed_splits(tmp_path_factory):
    """train/ + val/ packed layout for fit(): 40 train JPEGs so the
    epoch holds 5 batches whether the host batch derives to 8 (the
    conftest's fake 8-device pod) or stays 8 on one device — the
    sigterm@step=2 injection is genuinely MID-epoch either way."""
    from PIL import Image

    rng = np.random.RandomState(1)
    src = tmp_path_factory.mktemp("fit_tree")
    for split, per_class in (("train", 20), ("val", 8)):
        for c in range(2):
            d = src / split / f"class{c}"
            d.mkdir(parents=True)
            for i in range(per_class):
                low = rng.randint(0, 255, (8, 7, 3), np.uint8)
                Image.fromarray(low).resize(
                    (52, 44), Image.BILINEAR
                ).save(str(d / f"{i}.jpg"), quality=85)
    dest = tmp_path_factory.mktemp("packed_fit")
    write_shards(str(src / "train"), str(dest / "train"), 2)
    write_shards(str(src / "val"), str(dest / "val"), 2)
    return str(dest)


def test_fit_midepoch_resume_on_shards_bit_identical(packed_splits,
                                                     tmp_path_factory,
                                                     monkeypatch):
    """The resilience layer's contract, unchanged on the streaming
    path: SIGTERM mid-epoch while training FROM PACKED SHARDS, then
    --resume replays to the exact position — bit-identical params and
    validation trajectory vs the uninterrupted shard run."""
    import jax

    from dptpu.train import fit

    base_dir = tmp_path_factory.mktemp("shard_base")
    monkeypatch.chdir(base_dir)
    baseline = fit(_cfg(packed_splits), image_size=48, verbose=False)
    assert baseline["epochs_run"] == 2

    run_dir = tmp_path_factory.mktemp("shard_resume")
    monkeypatch.chdir(run_dir)
    monkeypatch.setenv("DPTPU_FAULT", "sigterm@step=2")
    r1 = fit(_cfg(packed_splits), image_size=48, verbose=False)
    assert r1["preempted"] is True
    monkeypatch.delenv("DPTPU_FAULT")
    r2 = fit(_cfg(packed_splits, resume="."), image_size=48, verbose=False)
    assert r2["epochs_run"] == 2

    la = jax.tree_util.tree_leaves(jax.device_get(baseline["state"].params))
    lb = jax.tree_util.tree_leaves(jax.device_get(r2["state"].params))
    assert max(
        float(np.max(np.abs(np.asarray(x) - np.asarray(y))))
        for x, y in zip(la, lb)
    ) == 0.0
    for hb, hr in zip(baseline["history"], r2["history"]):
        assert hb["val_loss"] == hr["val_loss"]


def test_fit_shard_locality_knob(packed_splits, tmp_path_factory,
                                 monkeypatch):
    """DPTPU_SHARD_LOCALITY=1 routes fit() through the shard-level
    shuffle + in-shard shuffle sampler — reachable from the trainer,
    and still deterministic (two identical runs match bit for bit)."""
    import jax

    from dptpu.train import fit

    monkeypatch.setenv("DPTPU_SHARD_LOCALITY", "1")
    monkeypatch.chdir(tmp_path_factory.mktemp("loc1"))
    r1 = fit(_cfg(packed_splits, epochs=1), image_size=48, verbose=False)
    assert r1["epochs_run"] == 1
    monkeypatch.chdir(tmp_path_factory.mktemp("loc2"))
    r2 = fit(_cfg(packed_splits, epochs=1), image_size=48, verbose=False)
    assert r1["history"][0]["train_loss"] == r2["history"][0]["train_loss"]
    la = jax.tree_util.tree_leaves(jax.device_get(r1["state"].params))
    lb = jax.tree_util.tree_leaves(jax.device_get(r2["state"].params))
    assert max(
        float(np.max(np.abs(np.asarray(x) - np.asarray(y))))
        for x, y in zip(la, lb)
    ) == 0.0
