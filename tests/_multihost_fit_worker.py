"""Worker for the 2-host full-fit() integration test.

Each instance is one JAX process with 2 fake CPU chips; together a 4-chip
pod. Runs the COMPLETE fit() path — CLI-parsed config, rendezvous,
hierarchical mesh, per-host sharded train loader, full-val-on-every-host
validation with the count divisor, chief-only checkpointing — on
synthetic data, and prints per-epoch metrics for cross-rank comparison.

Usage: python _multihost_fit_worker.py <port> <rank> <outdir> [world_size]
"""

import os
import sys


def main():
    port, rank, outdir = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    world = int(sys.argv[4]) if len(sys.argv) > 4 else 2
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    os.chdir(outdir)
    rankdir = os.path.join(outdir, f"rank{rank}")
    os.makedirs(rankdir, exist_ok=True)
    os.chdir(rankdir)

    from dptpu.config import parse_config
    from dptpu.train import fit

    # capture the mesh fit() ACTUALLY builds so the host-major
    # hierarchical ordering is asserted end-to-end, not on a replica
    # (importlib: the package re-exports fit the FUNCTION under the
    # same dotted name, shadowing the module attribute)
    import importlib

    fit_mod = importlib.import_module("dptpu.train.fit")
    real_make_mesh = fit_mod.make_mesh
    captured = {}

    def capturing_make_mesh(*a, **k):
        captured["mesh"] = real_make_mesh(*a, **k)
        return captured["mesh"]

    fit_mod.make_mesh = capturing_make_mesh

    cfg = parse_config(
        [
            "synthetic:128", "-a", "resnet18", "-b", "16", "--epochs", "2",
            "--lr", "0.01", "-j", "2",
            "--dist-url", f"tcp://127.0.0.1:{port}",
            "--world-size", str(world), "--rank", str(rank),
        ],
        variant="ddp",
    )
    result = fit(cfg, image_size=32, verbose=False)
    mesh = captured.get("mesh")
    if mesh is not None:
        flat = list(mesh.devices.reshape(-1))
        procs = [d.process_index for d in flat]
        host_major = procs == sorted(procs) and len(set(procs)) == world
        print(f"RANK{rank} MESH host_major={host_major} procs={procs}",
              flush=True)
    for h in result["history"]:
        print(
            f"RANK{rank} EPOCH{h['epoch']} "
            f"loss={h['train_loss']:.6f} top1={h['train_top1']:.4f} "
            f"vloss={h['val_loss']:.6f} vcount={h['val_count']:.1f}",
            flush=True,
        )
    print(f"RANK{rank} CKPT {os.path.exists('checkpoint.pth.tar')}", flush=True)


if __name__ == "__main__":
    main()
