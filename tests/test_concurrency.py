"""Unit tests for the concurrency analyzer (ISSUE 14): the three rules
— ``guarded-by`` / ``lock-order`` / ``thread-hygiene`` — each with
positive / negative / pragma-suppressed cases under the locked
actionable-message contract (tests/test_analysis.py pattern), the
seeded ABBA-deadlock and unguarded-shared-write regressions that
``dptpu check`` must fail actionably, the ``--changed-only`` CLI mode,
and the runtime half: ``OrderedLock`` order violations raise naming
both locks and both acquisition stacks, disabled mode adds ZERO
wrapping, ``StopToken`` teardown is prompt, and the quorum heartbeat
thread beats off the host thread and stops immediately.

The lint parts are pure stdlib — tier-1 fast.
"""

import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from dptpu.analysis import KNOB_REGISTRY, lint_source
from dptpu.analysis.lint import RepoContext, lint_repo

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(path, src, only=None):
    repo = RepoContext(root=None, readme_text=None, knobs=KNOB_REGISTRY)
    return lint_source(path, textwrap.dedent(src), repo, only_rules=only)


def _rules_of(findings):
    return [f.rule for f in findings]


# ------------------------------------------------------------- guarded-by


def test_unannotated_shared_attribute_flagged():
    """A thread-spawning class mutating state from both sides with no
    annotation is the canonical silent-race shape."""
    findings, _ = _lint(
        "dptpu/serve/newmod.py",
        """
        import threading

        class Pump:
            def __init__(self):
                self._done = False
                self._t = threading.Thread(
                    target=self._run, daemon=True, name="dptpu-pump")
            def _run(self):
                self._done = True
            def poll(self):
                return self._done
            def reset(self):
                self._done = False
        """,
        only=["guarded-by"],
    )
    assert _rules_of(findings) == ["guarded-by"]
    msg = findings[0].format()
    assert "_done" in msg and "guarded-by:" in msg
    # locked actionable-message contract
    assert "dptpu/serve/newmod.py:" in msg
    assert "# dptpu: allow-guarded-by(" in msg


def test_guarded_attribute_unlocked_access_flagged():
    findings, _ = _lint(
        "dptpu/serve/newmod.py",
        """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # guarded-by: _lock
            def bump(self):
                with self._lock:
                    self._n += 1
            def peek(self):
                return self._n
        """,
        only=["guarded-by"],
    )
    assert len(findings) == 1
    assert "peek()" in findings[0].message
    assert "without the lock held" in findings[0].message


def test_condition_alias_counts_as_the_lock():
    """``with self._cond:`` holds the underlying lock (the batcher's
    exact shape) — no finding."""
    findings, _ = _lint(
        "dptpu/serve/newmod.py",
        """
        import threading

        class Batcher:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self._open = None  # guarded-by: _lock
            def submit(self):
                with self._cond:
                    self._open = 1
                    self._cond.notify_all()
            def stats(self):
                with self._lock:
                    return self._open
        """,
        only=["guarded-by"],
    )
    assert findings == []


def test_locked_suffix_is_held_by_contract_and_callsites_checked():
    findings, _ = _lint(
        "dptpu/serve/newmod.py",
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # guarded-by: _lock
            def _drop_locked(self):
                self._n = 0
            def good(self):
                with self._lock:
                    self._drop_locked()
            def bad(self):
                self._drop_locked()
        """,
        only=["guarded-by"],
    )
    assert len(findings) == 1
    assert "bad()" in findings[0].message
    assert "_locked" in findings[0].message


def test_stale_annotation_naming_nonexistent_lock_flagged():
    findings, _ = _lint(
        "dptpu/serve/newmod.py",
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # guarded-by: _lok
        """,
        only=["guarded-by"],
    )
    assert len(findings) == 1
    assert "_lok" in findings[0].message
    assert "stale" in findings[0].message


def test_owned_by_written_from_both_sides_flagged():
    findings, _ = _lint(
        "dptpu/serve/newmod.py",
        """
        import threading

        class C:
            def __init__(self):
                self._flag = False  # owned-by: worker
                self._t = threading.Thread(
                    target=self._run, daemon=True, name="dptpu-w")
            def _run(self):
                self._flag = True
            def reset(self):
                self._flag = False
            def poll(self):
                return self._flag
        """,
        only=["guarded-by"],
    )
    assert len(findings) == 1
    assert "single-writer" in findings[0].message


def test_owned_by_single_writer_and_init_are_clean():
    findings, _ = _lint(
        "dptpu/serve/newmod.py",
        """
        import threading

        class Guard:
            def __init__(self):
                self.requested = False  # owned-by: signal-handler
                import signal
                signal.signal(signal.SIGTERM, self._handler)
            def _handler(self, signum, frame):
                self.requested = True
            def poll(self):
                return self.requested
        """,
        only=["guarded-by"],
    )
    assert findings == []


def test_non_concurrent_class_is_exempt():
    findings, _ = _lint(
        "dptpu/serve/newmod.py",
        """
        class Plain:
            def __init__(self):
                self.x = 0
            def bump(self):
                self.x += 1
        """,
        only=["guarded-by"],
    )
    assert findings == []


def test_guarded_by_pragma_suppresses_and_is_censused():
    findings, sups = _lint(
        "dptpu/serve/newmod.py",
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.hits = 0  # dptpu: allow-guarded-by(racy telemetry counter undercounts only)
            def bump(self):
                self.hits += 1
        """,
        only=["guarded-by"],
    )
    assert findings == []
    assert len(sups) == 1
    assert sups[0].rule == "guarded-by"
    assert "telemetry" in sups[0].reason


# ------------------------------------------------------------- lock-order


_ABBA_SRC = """
import threading

class C:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
    def one(self):
        with self._a:
            with self._b:
                pass
    def two(self):
        with self._b:
            with self._a:
                pass
"""


def test_abba_cycle_flagged_with_both_sites():
    findings, _ = _lint("dptpu/serve/newmod.py", _ABBA_SRC,
                        only=["lock-order"])
    assert len(findings) == 1
    msg = findings[0].format()
    assert "ABBA" in msg
    assert "_a" in msg and "_b" in msg
    assert "LOCK_RANKS" in msg
    assert "# dptpu: allow-lock-order(" in msg


def test_self_deadlock_via_call_edge_flagged():
    findings, _ = _lint(
        "dptpu/serve/newmod.py",
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
            def stats(self):
                with self._lock:
                    return 1
            def report(self):
                with self._lock:
                    return self.stats()
        """,
        only=["lock-order"],
    )
    assert len(findings) == 1
    assert "self-deadlock" in findings[0].message


def test_rlock_reentry_not_flagged():
    findings, _ = _lint(
        "dptpu/serve/newmod.py",
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.RLock()
            def stats(self):
                with self._lock:
                    return 1
            def report(self):
                with self._lock:
                    return self.stats()
        """,
        only=["lock-order"],
    )
    assert findings == []


def test_undeclared_ordered_lock_name_flagged():
    findings, _ = _lint(
        "dptpu/serve/newmod.py",
        """
        from dptpu.utils.sync import OrderedLock

        class C:
            def __init__(self):
                self._lock = OrderedLock("serve.nonexistent")
        """,
        only=["lock-order"],
    )
    assert len(findings) == 1
    assert "serve.nonexistent" in findings[0].message
    assert "LOCK_RANKS" in findings[0].message


def test_rank_inversion_flagged_and_correct_nesting_clean():
    bad = """
    from dptpu.utils.sync import OrderedLock

    class C:
        def __init__(self):
            self._ring = OrderedLock("obs.trace_ring")
            self._batch = OrderedLock("serve.batcher")
        def go(self):
            with self._ring:
                with self._batch:
                    pass
    """
    findings, _ = _lint("dptpu/serve/newmod.py", bad, only=["lock-order"])
    assert len(findings) == 1
    assert "inverts" in findings[0].message
    good = """
    from dptpu.utils.sync import OrderedLock

    class C:
        def __init__(self):
            self._ring = OrderedLock("obs.trace_ring")
            self._batch = OrderedLock("serve.batcher")
        def go(self):
            with self._batch:
                with self._ring:
                    pass
    """
    findings, _ = _lint("dptpu/serve/newmod.py", good, only=["lock-order"])
    assert findings == []


def test_lock_order_pragma_suppresses():
    src = _ABBA_SRC.replace(
        "with self._b:\n                pass",
        "with self._b:  # dptpu: allow-lock-order(test seam: both paths "
        "are try-locked in production)\n                pass",
    )
    findings, sups = _lint("dptpu/serve/newmod.py", src,
                           only=["lock-order"])
    assert findings == []
    assert [s.rule for s in sups] == ["lock-order"]


# ---------------------------------------------------------- thread-hygiene


def test_non_daemon_thread_without_join_flagged():
    findings, _ = _lint(
        "dptpu/serve/newmod.py",
        """
        import threading

        class C:
            def __init__(self):
                self._t = threading.Thread(
                    target=self._run, name="dptpu-w")
                self._t.start()
            def _run(self):
                pass
        """,
        only=["thread-hygiene"],
    )
    assert len(findings) == 1
    assert "join()" in findings[0].message


def test_joined_non_daemon_and_daemon_threads_clean():
    findings, _ = _lint(
        "dptpu/serve/newmod.py",
        """
        import threading

        class C:
            def __init__(self):
                self._t = threading.Thread(
                    target=self._run, name="dptpu-w")
                self._d = threading.Thread(
                    target=self._run, daemon=True, name="dptpu-d")
            def _run(self):
                pass
            def close(self):
                self._t.join()
        """,
        only=["thread-hygiene"],
    )
    assert findings == []


def test_unnamed_dptpu_thread_flagged_for_census():
    findings, _ = _lint(
        "dptpu/serve/newmod.py",
        "import threading\n"
        "t = threading.Thread(target=print, daemon=True)\n",
        only=["thread-hygiene"],
    )
    assert len(findings) == 1
    assert "census" in findings[0].message
    # scripts are exempt from the name requirement (bench-local threads)
    findings, _ = _lint(
        "scripts/run_newbench.py",
        "import threading\n"
        "t = threading.Thread(target=print)\n"
        "t.start()\nt.join()\n",
        only=["thread-hygiene"],
    )
    assert findings == []


def test_condition_wait_needs_predicate_loop():
    findings, _ = _lint(
        "dptpu/serve/newmod.py",
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self._ready = False  # guarded-by: _lock
            def bad(self):
                with self._cond:
                    self._cond.wait(1.0)
            def good(self):
                with self._cond:
                    while not self._ready:
                        self._cond.wait(1.0)
        """,
        only=["thread-hygiene"],
    )
    assert len(findings) == 1
    assert "predicate" in findings[0].message
    assert "bad" in findings[0].message


def test_join_while_holding_lock_flagged():
    findings, _ = _lint(
        "dptpu/serve/newmod.py",
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._t = threading.Thread(
                    target=print, daemon=True, name="dptpu-w")
            def close(self):
                with self._lock:
                    self._t.join()
        """,
        only=["thread-hygiene"],
    )
    assert len(findings) == 1
    assert "holding" in findings[0].message
    assert "deadlock" in findings[0].message


def test_thread_hygiene_pragma_suppresses():
    findings, sups = _lint(
        "dptpu/serve/newmod.py",
        "import threading\n"
        "t = threading.Thread(target=print, daemon=True)"
        "  # dptpu: allow-thread-hygiene(repl helper thread, not census-"
        "tracked by design)\n",
        only=["thread-hygiene"],
    )
    assert findings == []
    assert [s.rule for s in sups] == ["thread-hygiene"]


# ------------------------------------------- seeded repo-level regressions


def test_seeded_abba_fails_dptpu_check_actionably(tmp_path):
    """The acceptance bar: a seeded lock-order cycle fails the real
    ``dptpu check`` entry with the locked actionable message."""
    pkg = tmp_path / "dptpu"
    pkg.mkdir()
    (pkg / "newmod.py").write_text(textwrap.dedent(_ABBA_SRC))
    proc = subprocess.run(
        [sys.executable, "-m", "dptpu.analysis", "--no-hlo",
         "--root", str(tmp_path)],
        cwd=ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "lock-order" in proc.stdout
    assert "ABBA" in proc.stdout
    assert "dptpu/newmod.py" in proc.stdout
    assert "# dptpu: allow-lock-order(" in proc.stdout


def test_seeded_unguarded_shared_write_fails_actionably(tmp_path):
    pkg = tmp_path / "dptpu"
    pkg.mkdir()
    (pkg / "newmod.py").write_text(textwrap.dedent("""
        import threading

        class Pump:
            def __init__(self):
                self._state = None
                self._t = threading.Thread(
                    target=self._run, daemon=True, name="dptpu-pump")
            def _run(self):
                self._state = "ran"
            def read(self):
                return self._state
            def reset(self):
                self._state = None
    """))
    findings, _, _ = lint_repo(str(tmp_path))
    assert len(findings) == 1
    msg = findings[0].format()
    assert "guarded-by" in msg
    assert "dptpu/newmod.py" in msg
    assert "_state" in msg
    assert "# dptpu: allow-guarded-by(" in msg


def test_repo_ships_check_clean_on_concurrency_rules():
    """The three new rules over the REAL tree: zero unsuppressed
    findings (the migrated modules are annotated; deliberate waivers
    are censused pragmas)."""
    findings, suppressions, _ = lint_repo(ROOT)
    conc = [f for f in findings
            if f.rule in ("guarded-by", "lock-order", "thread-hygiene")]
    assert conc == [], "\n".join(f.format() for f in conc)
    assert any(s.rule == "guarded-by" for s in suppressions), \
        "the deliberate lock-free counters are censused, not silent"


# ------------------------------------------------------- changed-only CLI


def _run_check(*args):
    return subprocess.run(
        [sys.executable, "-m", "dptpu.analysis", *args],
        cwd=ROOT, capture_output=True, text=True, timeout=120,
    )


def test_changed_only_with_explicit_files():
    proc = _run_check("--no-hlo", "--changed-only",
                      "--files", "dptpu/utils/sync.py", "--root", ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "1 changed file(s)" in proc.stdout
    assert "clean" in proc.stdout


def test_changed_only_missing_file_is_usage_error():
    proc = _run_check("--no-hlo", "--changed-only",
                      "--files", "dptpu/no_such_file.py", "--root", ROOT)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "missing" in proc.stderr


def test_changed_only_empty_files_list_is_usage_error():
    """An empty explicit list (a shell expansion that matched nothing)
    must never report 'clean over zero files'."""
    proc = _run_check("--no-hlo", "--changed-only", "--files",
                      "--root", ROOT)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "empty list" in proc.stderr


def test_changed_only_refuses_whole_repo_artifacts():
    proc = _run_check("--no-hlo", "--changed-only", "--json", "x.json",
                      "--root", ROOT)
    assert proc.returncode == 2
    proc = _run_check("--files", "dptpu/utils/sync.py", "--root", ROOT)
    assert proc.returncode == 2  # --files without --changed-only


def test_changed_only_against_git_diff_runs():
    """Against the real repo git state: must exit 0/1 (never crash),
    and report the changed-file count."""
    proc = _run_check("--no-hlo", "--changed-only", "--root", ROOT)
    assert proc.returncode in (0, 1), proc.stdout + proc.stderr
    assert "changed file(s)" in proc.stdout


# ------------------------------------------------------------ runtime half


class TestOrderedLockRuntime:
    def test_disabled_mode_adds_zero_wrapping(self, monkeypatch):
        monkeypatch.setenv("DPTPU_SYNC_CHECK", "0")
        from dptpu.utils.sync import OrderedLock, OrderedRLock

        lock = OrderedLock("serve.batcher")
        assert type(lock) is type(threading.Lock())
        rlock = OrderedRLock("serve.engine")
        assert type(rlock) is type(threading.RLock())

    def test_unknown_name_fails_fast_either_mode(self, monkeypatch):
        from dptpu.utils.sync import OrderedLock

        for v in ("0", "1"):
            monkeypatch.setenv("DPTPU_SYNC_CHECK", v)
            with pytest.raises(ValueError, match="LOCK_RANKS"):
                OrderedLock("serve.bogus")

    def test_violation_raises_naming_both_locks_and_stacks(
            self, monkeypatch):
        monkeypatch.setenv("DPTPU_SYNC_CHECK", "1")
        from dptpu.utils.sync import LockOrderError, OrderedLock

        inner = OrderedLock("obs.trace_ring")    # rank 80
        outer = OrderedLock("serve.batcher")     # rank 10
        with inner:
            with pytest.raises(LockOrderError) as ei:
                outer.acquire()
            msg = str(ei.value)
            assert "obs.trace_ring" in msg and "serve.batcher" in msg
            assert "rank 80" in msg and "rank 10" in msg
            # both acquisition stacks, with real frames from this file
            assert "acquired at" in msg and "acquisition at" in msg
            assert "test_concurrency.py" in msg
        # the violating acquire never took the lock: reusable
        with outer:
            with inner:
                pass

    def test_reacquire_nonreentrant_raises_and_rlock_reenters(
            self, monkeypatch):
        monkeypatch.setenv("DPTPU_SYNC_CHECK", "1")
        from dptpu.utils.sync import (
            LockOrderError,
            OrderedLock,
            OrderedRLock,
        )

        lock = OrderedLock("serve.engine")
        with lock:
            with pytest.raises(LockOrderError, match="self-deadlock"):
                lock.acquire()
        rlock = OrderedRLock("serve.engine")
        with rlock:
            with rlock:
                pass

    def test_bounded_acquire_is_exempt_and_condition_composes(
            self, monkeypatch):
        monkeypatch.setenv("DPTPU_SYNC_CHECK", "1")
        from dptpu.utils.sync import OrderedLock, held_locks

        inner = OrderedLock("obs.trace_ring")
        outer = OrderedLock("serve.batcher")
        with inner:
            # bounded try-acquire cannot deadlock: exempt by design
            assert outer.acquire(timeout=0.2)
            assert {n for n, _ in held_locks()} == {
                "obs.trace_ring", "serve.batcher"}
            outer.release()
        assert held_locks() == []
        # threading.Condition over a checked lock: wait releases and
        # reacquires through the wrapper's bookkeeping
        lock = OrderedLock("serve.batcher")
        cond = threading.Condition(lock)
        state = {"ready": False}

        def setter():
            with cond:
                state["ready"] = True
                cond.notify_all()

        t = threading.Thread(target=setter, daemon=True,
                             name="dptpu-test-cond")
        with cond:
            t.start()
            while not state["ready"]:
                assert cond.wait(5.0)
        t.join(5.0)
        assert held_locks() == []

    def test_ordered_mp_lock_bounded_protocol(self, monkeypatch):
        monkeypatch.setenv("DPTPU_SYNC_CHECK", "1")
        import multiprocessing as mp

        from dptpu.utils.sync import ordered_mp_lock

        lock = ordered_mp_lock("shm.stripe", mp.get_context("spawn"))
        assert lock.acquire(timeout=0.5)
        lock.release()
        with lock:
            pass


class TestStopToken:
    def test_wait_and_prompt_stop(self):
        from dptpu.utils.sync import StopToken

        tok = StopToken()
        assert not tok.stopped
        t0 = time.monotonic()
        assert tok.wait(0.02) is False
        woke = []

        def waiter():
            woke.append(tok.wait(30.0))

        t = threading.Thread(target=waiter, daemon=True,
                             name="dptpu-test-stop")
        t.start()
        tok.stop()
        t.join(5.0)
        assert woke == [True]
        assert tok.stopped
        assert time.monotonic() - t0 < 5.0  # nowhere near the 30s sleep


class TestQuorumHeartbeat:
    def test_beats_off_thread_and_stops_promptly(self, tmp_path):
        import json

        from dptpu.resilience.quorum import (
            FileKVStore,
            QuorumCoordinator,
            QuorumHeartbeat,
        )

        coord = QuorumCoordinator(
            FileKVStore(str(tmp_path)), host_id=0, num_hosts=1,
            deadline_s=5.0,
        )
        hb = QuorumHeartbeat(coord, lambda: 7, interval_s=0.05)
        deadline = time.monotonic() + 5.0
        beat = None
        while time.monotonic() < deadline:
            raw = coord.store.get("beat-0")
            if raw is not None:
                beat = json.loads(raw)
                break
            time.sleep(0.01)
        assert beat is not None, "heartbeat thread never posted"
        assert beat["step"] == 7
        assert hb.alive
        t0 = time.monotonic()
        hb.close()
        assert time.monotonic() - t0 < 1.0, "teardown must be prompt"
        assert not hb.alive

    def test_session_tick_defers_to_heartbeat_thread(self, tmp_path):
        from dptpu.resilience.quorum import (
            FileKVStore,
            QuorumCoordinator,
            QuorumSession,
        )

        coord = QuorumCoordinator(
            FileKVStore(str(tmp_path)), host_id=0, num_hosts=1,
            deadline_s=5.0,
        )
        qs = QuorumSession(coord, guard=None)
        hb = qs.start_heartbeat(interval_s=30.0)
        assert qs.start_heartbeat() is hb  # idempotent
        qs.tick()  # must not inline-beat while the thread owns liveness
        qs.close()
        assert not hb.alive
