"""Tier-1-adjacent smoke of scripts/run_obsbench.py: the tracer's
overhead/coverage/trigger gates are continuously checked, not just on
the bench host. One subprocess, smallest preset, same gate logic."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_obsbench_smoke_gates(tmp_path):
    out = str(tmp_path / "OBSBENCH.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # run the bench on the REAL single-CPU topology: the fake 8-device
    # pod the test harness forces (conftest XLA_FLAGS) would route the
    # subprocess into the shard_map DDP step, which fails its
    # replication check under this container's jax (pre-existing at the
    # seed — ROADMAP resilience follow-on (d)); the tracer gates being
    # smoked here are topology-independent
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    # the smallest honest run: 2 interleaved off/on pairs + trigger run.
    # One retry: with reps=2 the off arms can TIE exactly (rates round
    # to 0.1 img/s), collapsing the noise-widening to zero right when a
    # 1-CPU host drifts — seen once in-suite at 10% phantom overhead
    # with off-arm spread 0.0; two consecutive failing benches are a
    # real regression, one unlucky window is not
    for attempt in (0, 1):
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "run_obsbench.py"),
             "--smoke", "--images", "256", "--batch", "32", "--epochs",
             "2", "--reps", "2", "--out", out],
            capture_output=True, text=True, timeout=480, env=env,
            cwd=str(tmp_path),
        )
        if proc.returncode == 0:
            break
    assert proc.returncode == 0, (
        f"obsbench gate failed twice\nstdout:\n{proc.stdout[-4000:]}\n"
        f"stderr:\n{proc.stderr[-4000:]}"
    )
    with open(out) as f:
        bench = json.load(f)
    # coverage gate: attribution accounts for >= 95% of epoch wall time
    assert bench["attribution_coverage"] >= 0.95
    attr = bench["attribution"]
    accounted = (attr["data_wait_s"] + attr["h2d_s"] + attr["device_s"]
                 + attr["ckpt_s"])
    assert accounted + attr["other_s"] == \
        __import__("pytest").approx(attr["wall_s"], rel=0.02)
    # overhead gate: the drift-hardened form — overhead is the MEDIAN
    # of per-rep paired (off-on)/off deltas, pairs run in ABBA order
    # (adjacent pairs cancel between-pair drift; the alternating order
    # cancels monotonic drift, which a fixed order converts into a
    # phantom consistent overhead) and the gate widens to the measured
    # noise floor (off-arm spread AND paired-delta spread), so the
    # gate holds both in isolation and under full-suite load on a
    # drifting host
    assert bench["gates"]["overhead_ok"], bench
    assert len(bench["paired_deltas_pct"]) == bench["reps"]
    assert bench["effective_gate_pct"] >= bench["gate_pct"]
    assert bench["effective_gate_pct"] >= bench["paired_spread_pct"]
    # the live sentinel trigger captured an in-flight window and wrote
    # the merged attribution report — without restarting the run
    assert bench["ondemand_trigger"]["captured"], bench["ondemand_trigger"]
    rep = bench["ondemand_trigger"]["report"]
    assert rep["steps"] == 4 and "host_phases_s" in rep
    # device attribution when the backend exports device tracks, an
    # explained degradation otherwise — never a silent zero
    assert ("device_ms_per_step" in rep) or ("device_trace_error" in rep)
