"""GSPMD (pjit-style) tensor-parallel step on the fake 8-device pod:
single-program code + sharding annotations must reproduce the
single-device step while the MLP params physically live sharded over the
model axis (dptpu/parallel/gspmd.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from dptpu.models import create_model
from dptpu.parallel import make_mesh
from dptpu.parallel.gspmd import (
    dp_specs,
    make_gspmd_train_step,
    shard_gspmd_state,
    state_shardings,
    swin_tp_specs,
    vit_tp_specs,
)
from dptpu.train import create_train_state, make_optimizer, make_train_step


def _vit_state():
    # vit_b_32 at 64px: 4 patches + cls = 5 tokens, heads=12, h=768
    model = create_model("vit_b_32", num_classes=8)
    tx = make_optimizer(momentum=0.9, weight_decay=1e-4)
    return create_train_state(
        jax.random.PRNGKey(0), model, tx, input_shape=(1, 64, 64, 3)
    )


def _batch(n=16, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "images": rng.randint(0, 256, (n, 64, 64, 3)).astype(np.uint8),
        "labels": rng.randint(0, 8, (n,)).astype(np.int32),
    }


def test_vit_tp_specs_cover_mlp_and_attention():
    state = _vit_state()
    specs = vit_tp_specs(state.params)
    layer = specs["encoder"]["encoder_layer_0"]
    assert layer["mlp_1"]["kernel"] == P(None, "model")
    assert layer["mlp_1"]["bias"] == P("model")
    assert layer["mlp_2"]["kernel"] == P("model", None)
    assert layer["mlp_2"]["bias"] == P()
    # head-aligned attention TP: qkv column-parallel (head-major storage
    # layout makes the contiguous split head-aligned), out-proj row-parallel
    attn = layer["self_attention"]
    assert attn["in_proj"]["kernel"] == P(None, "model")
    assert attn["in_proj"]["bias"] == P("model")
    assert attn["out_proj"]["kernel"] == P("model", None)
    assert attn["out_proj"]["bias"] == P()
    assert specs["conv_proj"]["kernel"] == P()


def test_gspmd_forward_hlo_one_all_reduce_per_block(eight_devices):
    """The partitioned forward HLO must contain EXACTLY one all-reduce
    per MLP and one per attention block (2 x layers total): the
    head-aligned qkv split means no resharding collectives appear."""
    from jax.sharding import NamedSharding

    mesh = make_mesh(eight_devices, {"data": 2, "model": 4})
    state = _vit_state()
    specs = vit_tp_specs(state.params)
    pshard = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs
    )

    def forward(params, images):
        return state.apply_fn({"params": params}, images, train=False)

    # logits stay batch-sharded: a replicated output would add one
    # legitimate (non-TP) all-gather over the data axis and muddy the count
    images = jnp.zeros((8, 64, 64, 3), jnp.float32)
    compiled = (
        jax.jit(
            forward,
            in_shardings=(pshard, NamedSharding(mesh, P("data"))),
            out_shardings=NamedSharding(mesh, P("data")),
        )
        .lower(state.params, images)
        .compile()
    )
    hlo = compiled.as_text()
    n_layers = 12  # vit_b_32
    n_allreduce = hlo.count("all-reduce(")
    n_allreduce += hlo.count("all-reduce-start(")
    assert n_allreduce == 2 * n_layers, (
        f"expected {2 * n_layers} all-reduces, found {n_allreduce}"
    )
    # and no gather/all-to-all resharding sneaks in (sync or async forms)
    for bad in ("all-gather(", "all-gather-start(", "all-to-all(",
                "all-to-all-start(", "collective-permute(",
                "collective-permute-start("):
        assert hlo.count(bad) == 0, f"unexpected {bad} in partitioned HLO"


def test_gspmd_tp_dp_step_matches_single_device(eight_devices):
    """{data: 2, model: 4} mesh: 5 steps of the GSPMD TP+DP step must
    match the single-device step — XLA's inserted collectives (grad
    all-reduce over data, MLP all-reduce over model) are numerically the
    same program."""
    mesh = make_mesh(eight_devices, {"data": 2, "model": 4})
    state0 = _vit_state()
    specs = vit_tp_specs(state0.params)
    g_step = make_gspmd_train_step(mesh, state0, specs)
    g_state = shard_gspmd_state(state0, mesh, specs)
    ref_state = jax.tree_util.tree_map(jnp.array, state0)
    ref_step = make_train_step()
    for i in range(5):
        batch = _batch(seed=i)
        ref_state, ref_m = ref_step(ref_state, batch)
        g_state, g_m = g_step(g_state, batch)
        np.testing.assert_allclose(
            float(g_m["loss"]), float(ref_m["loss"]), rtol=2e-5, atol=1e-6
        )
    for gp, rp in zip(
        jax.tree_util.tree_leaves(g_state.params),
        jax.tree_util.tree_leaves(ref_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(gp), np.asarray(rp), rtol=2e-4, atol=2e-5
        )


def test_swin_tp_specs_cover_attention_and_side_tensors():
    model = create_model("swin_v2_t", num_classes=8)
    tx = make_optimizer(momentum=0.9, weight_decay=1e-4)
    state = jax.eval_shape(
        lambda: create_train_state(
            jax.random.PRNGKey(0), model, tx, input_shape=(1, 32, 32, 3)
        )
    )
    specs = swin_tp_specs(state.params)
    blk = specs["stage0_block0"]
    assert blk["attn"]["qkv"]["kernel"] == P(None, "model")
    assert blk["attn"]["qkv"]["bias"] == P("model")
    assert blk["attn"]["proj"]["kernel"] == P("model", None)
    assert blk["attn"]["proj"]["bias"] == P()
    assert blk["attn"]["logit_scale"] == P("model")
    assert blk["attn"]["cpb_mlp_2"]["kernel"] == P(None, "model")
    assert blk["attn"]["cpb_mlp_1"]["kernel"] == P()
    assert blk["mlp_1"]["kernel"] == P(None, "model")
    assert blk["mlp_2"]["kernel"] == P("model", None)
    assert specs["patch_conv"]["kernel"] == P()
    # v1 variant: the relative-position table shards on its heads dim
    model1 = create_model("swin_t", num_classes=8)
    state1 = jax.eval_shape(
        lambda: create_train_state(
            jax.random.PRNGKey(0), model1, tx, input_shape=(1, 32, 32, 3)
        )
    )
    specs1 = swin_tp_specs(state1.params)
    assert specs1["stage0_block0"]["attn"][
        "relative_position_bias_table"] == P(None, "model")


def test_gspmd_swin_tp_dp_step_matches_single_device(eight_devices):
    """{data: 2, model: 3} (3 divides every swin-t stage's head count:
    3/6/12/24): 3 steps of the GSPMD TP+DP step on swin_v2_t must track
    the single-device step — v2 exercises the head-major K-bias mask,
    per-head logit_scale, and the cpb head projection under sharding."""
    mesh = make_mesh(eight_devices[:6], {"data": 2, "model": 3})
    model = create_model("swin_v2_t", num_classes=8)
    tx = make_optimizer(momentum=0.9, weight_decay=1e-4)
    state0 = create_train_state(
        jax.random.PRNGKey(0), model, tx, input_shape=(1, 32, 32, 3)
    )
    specs = swin_tp_specs(state0.params)
    lr = lambda _: 0.01  # noqa: E731  (stable regime, see dp test)
    g_step = make_gspmd_train_step(mesh, state0, specs, lr_schedule=lr)
    g_state = shard_gspmd_state(state0, mesh, specs)
    ref_state = jax.tree_util.tree_map(jnp.array, state0)
    ref_step = make_train_step(lr_schedule=lr)
    for i in range(3):
        rng = np.random.RandomState(i)
        b = {
            "images": rng.randint(0, 256, (8, 32, 32, 3)).astype(np.uint8),
            "labels": rng.randint(0, 8, (8,)).astype(np.int32),
        }
        ref_state, ref_m = ref_step(ref_state, b)
        g_state, g_m = g_step(g_state, b)
        np.testing.assert_allclose(
            float(g_m["loss"]), float(ref_m["loss"]), rtol=1e-4, atol=1e-6
        )
    k = g_state.params["stage0_block0"]["attn"]["qkv"]["kernel"]
    assert k.sharding.spec == P(None, "model")  # physically TP-sharded


def test_gspmd_dp_any_arch_matches_single_device(eight_devices):
    """dp_specs runs a BN-bearing CNN through the GSPMD path: 5 steps on
    a {data: 8} mesh must equal the single-device big-batch step — under
    GSPMD, BN sees the GLOBAL batch (SyncBN semantics), which is exactly
    what the single-device step computes on the same batch."""
    mesh = make_mesh(eight_devices, {"data": 8})
    model = create_model("resnet18", num_classes=8)
    tx = make_optimizer(momentum=0.9, weight_decay=1e-4)
    state0 = create_train_state(
        jax.random.PRNGKey(0), model, tx, input_shape=(1, 32, 32, 3)
    )
    specs = dp_specs(state0.params)
    # lr 0.01: the default 0.1 on random data drives the loss into the
    # chaotic regime where float-associativity differences amplify past
    # any fixed tolerance within 5 steps (same phenomenon NUMERICS.json
    # documents across backends)
    lr = lambda _: 0.01  # noqa: E731
    g_step = make_gspmd_train_step(mesh, state0, specs, lr_schedule=lr)
    g_state = shard_gspmd_state(state0, mesh, specs)
    ref_state = jax.tree_util.tree_map(jnp.array, state0)
    ref_step = make_train_step(lr_schedule=lr)

    def batch(seed):
        rng = np.random.RandomState(seed)
        return {
            "images": rng.randint(0, 256, (16, 32, 32, 3)).astype(np.uint8),
            "labels": rng.randint(0, 8, (16,)).astype(np.int32),
        }

    # BN batch statistics are summed in partitioned order under GSPMD
    # (8 partial sums) vs one flat sum on the reference device. Measured
    # on this exact setup: step-0 loss agrees to 3e-7 (the semantics are
    # identical), then BN's 1/sigma^2 gradient terms amplify the
    # associativity residue ~10-30x per step (3e-7 -> 2.7e-4 -> 2.2e-3
    # -> 8.6e-3 -> 5.4e-2) — the same chaotic growth NUMERICS.json
    # documents across backends. So the gate is the pre-amplification
    # horizon; later steps are sanity-checked, not equality-checked.
    bounds = [1e-5, 1e-3]
    for i in range(5):
        b = batch(i)
        ref_state, ref_m = ref_step(ref_state, b)
        g_state, g_m = g_step(g_state, b)
        gl, rl = float(g_m["loss"]), float(ref_m["loss"])
        if i < len(bounds):
            np.testing.assert_allclose(gl, rl, rtol=bounds[i])
        else:
            assert np.isfinite(gl) and abs(gl - rl) / rl < 0.2, (i, gl, rl)
        if i == 0:
            # one update in: params and the pmean'd running stats must
            # still track. A wrong collective or mis-sharded stat shows
            # as an O(1) relative error here; BN-backward cancellation
            # makes per-element gradients order-sensitive at the ~1e-3
            # level, hence gross-error (not bitwise) tolerances.
            for gp, rp in zip(
                jax.tree_util.tree_leaves(
                    (g_state.params, g_state.batch_stats)),
                jax.tree_util.tree_leaves(
                    (ref_state.params, ref_state.batch_stats)),
            ):
                np.testing.assert_allclose(
                    np.asarray(gp), np.asarray(rp), rtol=1e-2, atol=1e-4
                )


def test_gspmd_state_physically_sharded(eight_devices):
    mesh = make_mesh(eight_devices, {"data": 2, "model": 4})
    state = _vit_state()
    specs = vit_tp_specs(state.params)
    g = shard_gspmd_state(state, mesh, specs)
    k = g.params["encoder"]["encoder_layer_0"]["mlp_1"]["kernel"]  # (768, 3072)
    assert k.sharding.spec == P(None, "model")
    assert k.addressable_shards[0].data.shape == (768, 3072 // 4)
    # the momentum mirror follows the same layout
    mom = None
    for leaf in jax.tree_util.tree_leaves(g.opt_state):
        if leaf.shape == (768, 3072):
            mom = leaf
            break
    assert mom is not None and mom.sharding.spec == P(None, "model")


def test_opt_shardings_are_structural_not_shape_keyed(eight_devices):
    """A replicated param whose SHAPE collides with a TP-sharded MLP leaf
    (head kernel when num_classes == mlp hidden) must keep a replicated
    momentum — the trace is matched by tree position, not by shape."""
    from dptpu.parallel.gspmd import state_shardings

    mesh = make_mesh(eight_devices, {"data": 2, "model": 4})
    model = create_model("vit_b_32", num_classes=3072)
    tx = make_optimizer(momentum=0.9, weight_decay=1e-4)
    state = create_train_state(
        jax.random.PRNGKey(0), model, tx, input_shape=(1, 64, 64, 3)
    )
    sh = state_shardings(state, mesh, vit_tp_specs(state.params))
    assert sh.params["head"]["kernel"].spec == P()
    # find the momentum sharding at the head kernel's tree position: it
    # must be replicated even though its shape equals mlp_1's kernel
    import optax

    for node in jax.tree_util.tree_leaves(
        sh.opt_state, is_leaf=lambda n: isinstance(n, optax.TraceState)
    ):
        if isinstance(node, optax.TraceState):
            assert node.trace["head"]["kernel"].spec == P()
            assert node.trace["encoder"]["encoder_layer_0"]["mlp_1"][
                "kernel"].spec == P(None, "model")
            break
    else:  # pragma: no cover
        raise AssertionError("no TraceState found in opt_state shardings")


def test_convnext_tp_specs_cover_mlp_only():
    from dptpu.parallel.gspmd import convnext_tp_specs, tp_rule_for_arch

    assert tp_rule_for_arch("convnext_tiny") == "convnext_tp_specs"
    model = create_model("convnext_tiny", num_classes=8)
    tx = make_optimizer(momentum=0.9, weight_decay=1e-4)
    state = jax.eval_shape(
        lambda: create_train_state(
            jax.random.PRNGKey(0), model, tx, input_shape=(1, 32, 32, 3)
        )
    )
    specs = convnext_tp_specs(state.params)
    blk = specs["stage0_block0"]
    assert blk["mlp_1"]["kernel"] == P(None, "model")
    assert blk["mlp_1"]["bias"] == P("model")
    assert blk["mlp_2"]["kernel"] == P("model", None)
    assert blk["mlp_2"]["bias"] == P()
    # depthwise conv, norms, layer_scale, stem, head all replicated
    assert blk["dw"]["kernel"] == P()
    assert blk["norm"]["scale"] == P()
    assert specs["stem_conv"]["kernel"] == P()
    assert specs["head"]["kernel"] == P()


def test_gspmd_convnext_tp_dp_step_matches_single_device(eight_devices):
    """{data: 2, model: 4}: 3 steps of the GSPMD TP+DP step on
    convnext_tiny must track the single-device step — the MLP pair is
    column/row-split (one all-reduce per block), dw/LN/layer_scale and
    the stochastic-depth rng ride along replicated."""
    from dptpu.parallel.gspmd import convnext_tp_specs

    mesh = make_mesh(eight_devices, {"data": 2, "model": 4})
    model = create_model("convnext_tiny", num_classes=8)
    tx = make_optimizer(momentum=0.9, weight_decay=1e-4)
    state0 = create_train_state(
        jax.random.PRNGKey(0), model, tx, input_shape=(1, 32, 32, 3)
    )
    specs = convnext_tp_specs(state0.params)
    lr = lambda _: 0.01  # noqa: E731  (stable regime, see dp test)
    g_step = make_gspmd_train_step(mesh, state0, specs, lr_schedule=lr)
    g_state = shard_gspmd_state(state0, mesh, specs)
    ref_state = jax.tree_util.tree_map(jnp.array, state0)
    ref_step = make_train_step(lr_schedule=lr)
    for i in range(3):
        rng = np.random.RandomState(i)
        b = {
            "images": rng.randint(0, 256, (8, 32, 32, 3)).astype(np.uint8),
            "labels": rng.randint(0, 8, (8,)).astype(np.int32),
        }
        ref_state, ref_m = ref_step(ref_state, b)
        g_state, g_m = g_step(g_state, b)
        np.testing.assert_allclose(
            float(g_m["loss"]), float(ref_m["loss"]), rtol=1e-4, atol=1e-6
        )
    k = g_state.params["stage0_block0"]["mlp_1"]["kernel"]
    assert k.sharding.spec == P(None, "model")  # physically TP-sharded


def test_gspmd_convnext_forward_hlo_one_all_reduce_per_block(eight_devices):
    """The partitioned ConvNeXt forward must contain EXACTLY one
    all-reduce per block (the row-parallel mlp_2) — the comm-volume
    claim in PARALLELISM.md, locked like ViT's two-per-layer."""
    from jax.sharding import NamedSharding

    from dptpu.parallel.gspmd import convnext_tp_specs

    mesh = make_mesh(eight_devices, {"data": 2, "model": 4})
    model = create_model("convnext_tiny", num_classes=8)
    tx = make_optimizer(momentum=0.9, weight_decay=1e-4)
    state = create_train_state(
        jax.random.PRNGKey(0), model, tx, input_shape=(1, 32, 32, 3)
    )
    specs = convnext_tp_specs(state.params)
    pshard = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs
    )

    def forward(params, images):
        return state.apply_fn({"params": params}, images, train=False)

    images = jnp.zeros((8, 32, 32, 3), jnp.float32)
    compiled = (
        jax.jit(
            forward,
            in_shardings=(pshard, NamedSharding(mesh, P("data"))),
            out_shardings=NamedSharding(mesh, P("data")),
        )
        .lower(state.params, images)
        .compile()
    )
    hlo = compiled.as_text()
    n_blocks = 3 + 3 + 9 + 3  # convnext_tiny stage depths
    n_allreduce = hlo.count("all-reduce(") + hlo.count("all-reduce-start(")
    assert n_allreduce == n_blocks, (
        f"expected {n_blocks} all-reduces, found {n_allreduce}"
    )
    for bad in ("all-gather(", "all-gather-start(", "all-to-all(",
                "all-to-all-start(", "collective-permute(",
                "collective-permute-start("):
        assert hlo.count(bad) == 0, f"unexpected {bad} in partitioned HLO"
