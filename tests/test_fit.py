"""End-to-end integration: fit() on a tiny ImageFolder over the fake pod.

The SURVEY.md §4 integration tier: synthetic ImageFolder-shaped data, a real
zoo model at small resolution, the full config→mesh→loaders→epochs→checkpoint
path, resume, and evaluate-only — exercised exactly as the CLIs drive it.
"""

import os

import numpy as np
import pytest

from dptpu.config import Config
from dptpu.train import fit

# the shared tiny_imagenet ImageFolder fixture lives in conftest.py


def test_fit_trains_checkpoints_and_early_stops(tiny_imagenet, tmp_path,
                                                monkeypatch):
    monkeypatch.chdir(tmp_path)  # checkpoints land in cwd like the reference
    cfg = Config(
        data=tiny_imagenet,
        arch="resnet18",
        epochs=4,
        batch_size=24,
        lr=0.02,
        workers=2,
        print_freq=1,
        seed=1,
        desired_acc=0.5,  # trivially separable → early stop expected
    )
    result = fit(cfg, image_size=32, verbose=False)
    assert result["epochs_run"] >= 1
    assert os.path.exists("checkpoint.pth.tar")
    hist = result["history"]
    assert hist[0]["train_loss"] > 0
    # feed-rate accounting: starvation fraction is present and sane
    assert 0.0 <= hist[0]["train_starvation"] <= 1.0
    if result["early_stopped"]:
        assert result["training_time"] > 0
        assert result["best_acc1"] >= 50.0

    # resume from the checkpoint and evaluate only
    cfg_eval = cfg.replace(resume="checkpoint.pth.tar", evaluate=True)
    eval_result = fit(cfg_eval, image_size=32, verbose=False)
    assert eval_result["val"]["count"] == 24  # full val set, once
    assert eval_result["val"]["top1"] == pytest.approx(
        result["history"][-1]["val_top1"], abs=1e-6
    )


def test_fit_zero1_matches_ddp(tiny_imagenet, tmp_path, monkeypatch):
    """DPTPU_ZERO1=1 through the full fit() path must reproduce the DDP
    run EPOCH FOR EPOCH (same seeded data order, same update math), while
    checkpointing a gathered state that round-trips into a non-ZeRO eval
    run."""
    monkeypatch.chdir(tmp_path)
    cfg = Config(
        data=tiny_imagenet,
        arch="resnet18",
        epochs=2,
        batch_size=24,
        lr=0.02,
        workers=2,
        print_freq=1,
        seed=1,
    )
    ddp = fit(cfg, image_size=32, verbose=False)
    monkeypatch.setenv("DPTPU_ZERO1", "1")
    zero = fit(cfg, image_size=32, verbose=False)
    assert os.path.exists("checkpoint.pth.tar")
    for hd, hz in zip(ddp["history"], zero["history"]):
        assert hz["train_loss"] == pytest.approx(hd["train_loss"], rel=1e-4)
        assert hz["val_top1"] == pytest.approx(hd["val_top1"], abs=1e-6)

    monkeypatch.delenv("DPTPU_ZERO1")
    cfg_eval = cfg.replace(resume="checkpoint.pth.tar", evaluate=True)
    eval_result = fit(cfg_eval, image_size=32, verbose=False)
    assert eval_result["val"]["top1"] == pytest.approx(
        zero["history"][-1]["val_top1"], abs=1e-6
    )


def test_fit_tp_matches_single_device(tiny_imagenet, tmp_path, monkeypatch):
    """DPTPU_TP=4 through the full fit() path: the {data: 2, model: 4}
    mesh trains a ViT with head-aligned Megatron TP (vit_tp_specs) and
    must track the single-device run loss-for-loss — the library parity
    of tests/test_gspmd.py, but THROUGH the trainer: config → mesh →
    spec selection → sharded state → epoch loop → gathered checkpoint."""
    from jax.sharding import PartitionSpec as P

    monkeypatch.chdir(tmp_path)
    cfg = Config(
        data=tiny_imagenet,
        arch="vit_b_32",
        epochs=2,
        batch_size=24,
        lr=0.02,
        workers=2,
        print_freq=1,
        seed=1,
    )
    single = fit(cfg.replace(gpu=0), image_size=32, verbose=False)
    monkeypatch.setenv("DPTPU_TP", "4")
    tp = fit(cfg, image_size=32, verbose=False)
    for hs, ht in zip(single["history"], tp["history"]):
        assert ht["train_loss"] == pytest.approx(hs["train_loss"], rel=1e-3)
        assert ht["val_loss"] == pytest.approx(hs["val_loss"], rel=1e-3)
    # the trainer's state is PHYSICALLY tensor-parallel: the head-major
    # fused qkv and both MLP kernels live sharded over the model axis
    layer = tp["state"].params["encoder"]["encoder_layer_0"]
    assert layer["self_attention"]["in_proj"]["kernel"].sharding.spec == P(
        None, "model"
    )
    assert layer["mlp_1"]["kernel"].sharding.spec == P(None, "model")
    assert layer["mlp_2"]["kernel"].sharding.spec == P("model", None)

    # the per-epoch checkpoint was written from the GATHERED view: it
    # round-trips into a plain (non-TP) evaluate-only run
    monkeypatch.delenv("DPTPU_TP")
    cfg_eval = cfg.replace(resume="checkpoint.pth.tar", evaluate=True)
    eval_result = fit(cfg_eval, image_size=32, verbose=False)
    assert eval_result["val"]["loss"] == pytest.approx(
        tp["history"][-1]["val_loss"], rel=1e-5
    )


def test_fit_tp_fallback_and_precedence_notices(tiny_imagenet, tmp_path,
                                                monkeypatch, capsys):
    """DPTPU_TP on a CNN arch is DEMOTED with a notice (no conv TP by
    design): the run keeps the flat full-width data mesh, and — unlike
    an active TP request — the inert request does not suppress
    DPTPU_ZERO1, which takes over as usual."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("DPTPU_TP", "2")
    cfg = Config(
        data=tiny_imagenet,
        arch="resnet18",
        epochs=1,
        batch_size=24,
        lr=0.02,
        workers=2,
        print_freq=1,
        seed=1,
    )
    result = fit(cfg, image_size=32, verbose=True)
    assert result["epochs_run"] == 1
    assert np.isfinite(result["history"][0]["train_loss"])
    out = capsys.readouterr().out
    assert "no tensor-parallel rule for 'resnet18'" in out
    # the fallback keeps the FULL device count on the data axis...
    assert "over all 8 devices" in out
    # ...and routes through the GSPMD dp step
    assert "GSPMD single-program data parallelism" in out

    # the demoted request must NOT suppress ZeRO-1 (it would on a real
    # TP run — that precedence is locked in the SP notices test)
    monkeypatch.setenv("DPTPU_ZERO1", "1")
    result = fit(cfg, image_size=32, verbose=True)
    assert result["epochs_run"] == 1
    out = capsys.readouterr().out
    assert "no tensor-parallel rule for 'resnet18'" in out
    assert "ZeRO-1 optimizer-state sharding" in out
    assert "DPTPU_ZERO1 ignored" not in out


@pytest.mark.parametrize("mode", ["ulysses", "ring"])
def test_fit_sp_matches_single_device(tiny_imagenet, tmp_path, monkeypatch,
                                      mode):
    """DPTPU_SP=4 through the full fit() path: the {data: 2, seq: 4}
    mesh trains a ViT sequence-parallel (5 tokens pad to 8, key-mask
    keeps padding out of every softmax, cls psum-recovered) and must
    track the single-device run loss-for-loss — no hand-written
    shard_map, no pos-embedding surgery."""
    monkeypatch.chdir(tmp_path)
    cfg = Config(
        data=tiny_imagenet,
        arch="vit_b_32",
        epochs=1,
        batch_size=24,
        lr=0.02,
        workers=2,
        print_freq=1,
        seed=1,
    )
    single = fit(cfg.replace(gpu=0), image_size=64, verbose=False)
    monkeypatch.setenv("DPTPU_SP", "4")
    monkeypatch.setenv("DPTPU_SP_MODE", mode)
    sp = fit(cfg, image_size=64, verbose=False)
    for hs, hp in zip(single["history"], sp["history"]):
        assert hp["train_loss"] == pytest.approx(hs["train_loss"], rel=1e-3)
        assert hp["val_loss"] == pytest.approx(hs["val_loss"], rel=1e-3)


def test_fit_sp_fallback_and_precedence_notices(tiny_imagenet, tmp_path,
                                                monkeypatch, capsys):
    """DPTPU_SP on a non-ViT arch falls back to plain data parallelism
    over the flat mesh with a notice, and DPTPU_TP takes precedence
    over DPTPU_SP with a notice."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("DPTPU_SP", "2")
    cfg = Config(
        data=tiny_imagenet,
        arch="resnet18",
        epochs=1,
        batch_size=24,
        lr=0.02,
        workers=2,
        print_freq=1,
        seed=1,
    )
    result = fit(cfg, image_size=32, verbose=True)
    assert result["epochs_run"] == 1
    assert np.isfinite(result["history"][0]["train_loss"])
    out = capsys.readouterr().out
    assert "no sequence-parallel path for 'resnet18'" in out
    assert "over all 8 devices" in out

    # TP > SP and TP > ZeRO-1 precedence (vit arch: TP is REAL here, so
    # unlike the CNN demotion above it suppresses both with notices)
    monkeypatch.setenv("DPTPU_TP", "2")
    monkeypatch.setenv("DPTPU_ZERO1", "1")
    cfg_vit = cfg.replace(arch="vit_b_32")
    result = fit(cfg_vit, image_size=32, verbose=True)
    assert result["epochs_run"] == 1
    out = capsys.readouterr().out
    assert "DPTPU_SP ignored: DPTPU_TP takes precedence" in out
    assert "DPTPU_ZERO1 ignored: DPTPU_TP drives the GSPMD" in out
    assert "tensor parallelism: vit_tp_specs" in out


def test_fit_gspmd_flag_trains_and_yields_to_zero1(tiny_imagenet, tmp_path,
                                                   monkeypatch, capsys):
    """DPTPU_GSPMD=1 routes fit() through the single-program pjit step
    (dp_specs): trains end-to-end with global-batch BN semantics, and
    DPTPU_ZERO1 takes precedence with a notice when both are set."""
    from dptpu.config import Config

    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("DPTPU_GSPMD", "1")
    cfg = Config(
        data=tiny_imagenet,
        arch="resnet18",
        epochs=1,
        batch_size=24,
        lr=0.02,
        workers=2,
        print_freq=1,
        seed=1,
    )
    result = fit(cfg, image_size=32, verbose=True)
    assert result["epochs_run"] == 1
    assert np.isfinite(result["history"][0]["train_loss"])
    out = capsys.readouterr().out
    assert "GSPMD single-program data parallelism" in out

    monkeypatch.setenv("DPTPU_ZERO1", "1")
    result = fit(cfg, image_size=32, verbose=True)
    assert result["epochs_run"] == 1
    out = capsys.readouterr().out
    assert "DPTPU_GSPMD ignored: DPTPU_ZERO1 takes precedence" in out
    assert "ZeRO-1 optimizer-state sharding" in out


def test_tp_sp_env_knob_error_contracts(tiny_imagenet, monkeypatch):
    """The DPTPU_TP/DPTPU_SP knobs fail FAST and actionably — before any
    model build or compile — on junk values, negatives, bad modes, and
    non-divisor axis sizes."""
    cfg = Config(data=tiny_imagenet, arch="vit_b_32", epochs=1,
                 batch_size=24, workers=1)
    monkeypatch.setenv("DPTPU_TP", "two")
    with pytest.raises(ValueError, match="not an integer"):
        fit(cfg, image_size=32, verbose=False)
    monkeypatch.setenv("DPTPU_TP", "-4")
    with pytest.raises(ValueError, match="positive"):
        fit(cfg, image_size=32, verbose=False)
    monkeypatch.setenv("DPTPU_TP", "3")  # 3 does not divide 8 devices
    with pytest.raises(ValueError, match="does not divide"):
        fit(cfg, image_size=32, verbose=False)
    monkeypatch.delenv("DPTPU_TP")
    monkeypatch.setenv("DPTPU_SP", "two")
    with pytest.raises(ValueError, match="not an integer"):
        fit(cfg, image_size=32, verbose=False)
    monkeypatch.setenv("DPTPU_SP", "-4")
    with pytest.raises(ValueError, match="positive"):
        fit(cfg, image_size=32, verbose=False)
    monkeypatch.setenv("DPTPU_SP", "2")
    monkeypatch.setenv("DPTPU_SP_MODE", "ringg")
    with pytest.raises(ValueError, match="ulysses.*ring|'ulysses' or 'ring'"):
        fit(cfg, image_size=32, verbose=False)
    monkeypatch.setenv("DPTPU_SP_MODE", "ring")
    monkeypatch.setenv("DPTPU_SP", "5")  # 5 does not divide 8
    with pytest.raises(ValueError, match="does not divide"):
        fit(cfg, image_size=32, verbose=False)
